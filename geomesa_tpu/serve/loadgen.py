"""Load generator for the serving layer (`gmtpu bench-serve`).

Two workload shapes, because they answer different questions:

- closed loop: N clients issue back-to-back queries (each waits for its
  response before sending the next). Measures sustainable throughput and
  the latency the system settles into under exactly-N outstanding
  requests. Throughput rises with N until the device saturates.
- open loop: arrivals at a fixed rate regardless of completions — the
  shape real traffic has. Latency here includes queue wait, so an
  offered rate above capacity shows UNBOUNDED latency growth... unless
  admission control sheds, which is precisely what the bounded queue +
  QueryRejected are for. The report separates served from shed.

Reports throughput plus p50/p95/p99/max latency (exact, from raw
samples — the serving histograms are bucket estimates; a bench should
not inherit their quantization), and the service's dispatch/coalesce
counters so a coalesced-vs-serial comparison is one subtraction.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import wait as futures_wait
from typing import Callable, Dict, List, Optional

import numpy as np

from geomesa_tpu.plan.planner import QueryTimeout
from geomesa_tpu.serve.scheduler import QueryRejected, ServeRequest
from geomesa_tpu.serve.service import QueryService


@dataclasses.dataclass
class LoadReport:
    mode: str
    duration_s: float
    sent: int
    ok: int
    rejected: int
    timeouts: int
    errors: int
    throughput_qps: float
    mean_ms: float
    p50_ms: float
    p95_ms: float
    p99_ms: float
    max_ms: float
    dispatches: int
    coalesced: int
    # sustained mode (docs/SERVING.md "Pipelined dispatch"): the
    # headline pts/s (store points scanned x served queries / wall) and
    # how deep the dispatch pipeline actually ran — the numbers the
    # 523M→700M sustained claim is reproduced from
    pts_per_s: float = 0.0
    windows_in_flight_max: int = 0
    pipelined_windows: int = 0
    fused_counts: int = 0
    # persistent serve loop (docs/SERVING.md "Persistent serve loop"):
    # how many windows rode a ring program, how many fell back typed,
    # and the per-window device-interaction count (`serve.device.ops`
    # delta / windows) — the number `bench-serve --mode sustained
    # --ring` compares against the pipelined baseline and the
    # `ring.dispatch.*` sentinel family gates
    ring_windows: int = 0
    ring_fallbacks: int = 0
    dispatches_per_window: float = 0.0
    # sharded serving (docs/SERVING.md "Sharded serving"): the mesh the
    # service dispatched on (0 = single-chip) and the headline pts/s
    # normalized per shard — the capacity-multiplier number the
    # ROADMAP item-1 claim is judged on
    mesh_devices: int = 0
    per_shard_pts_per_s: float = 0.0
    # subscribe mode (docs/SERVING.md "Standing queries"): N standing
    # subscriptions folded over M kafka batches — throughput is pushed
    # events/s, latency is the per-batch poll->eval->push cycle, and
    # `dispatches` is the evaluator's fused-kernel count (the
    # one-dispatch-per-poll invariant makes it == batches when warm)
    subscriptions: int = 0
    batches: int = 0
    events_total: int = 0
    events_per_s: float = 0.0
    # approx mode (docs/SERVING.md "Approximate answers"): tolerant vs
    # exact client split — the headline is approx_speedup_p50 (sketch
    # tier vs exact device scan at identical bound-respecting
    # accuracy) plus the serving-tier shares
    approx_ok: int = 0
    exact_ok: int = 0
    approx_p50_ms: float = 0.0
    approx_p99_ms: float = 0.0
    exact_p50_ms: float = 0.0
    exact_p99_ms: float = 0.0
    approx_speedup_p50: float = 0.0
    tier_sketch: int = 0
    tier_cached: int = 0
    tier_exact: int = 0
    bound_violations: int = 0
    # sentinel input (telemetry/sentinel.py): a bounded sample of the
    # raw end-to-end latencies, so `bench-serve --record-baseline` can
    # commit a DISTRIBUTION (median + overlap comparison) instead of
    # the point percentiles above. Evenly strided from the sorted
    # samples — order statistics, not a random subsample, so two runs
    # of the same workload produce comparable vectors.
    samples_ms: List[float] = dataclasses.field(default_factory=list)
    # approx mode: per-tier latency sample vectors for the sentinel's
    # approx.* reservoir families (a regressed sketch path fails CI)
    approx_samples_ms: List[float] = dataclasses.field(default_factory=list)
    exact_samples_ms: List[float] = dataclasses.field(default_factory=list)
    # wire mode (docs/SERVING.md "Columnar wire"): JSON-lines vs
    # columnar record-batch encode throughput over identical results,
    # plus the push fan-out (frames x sinks through the one-encode
    # PushMux) — the headline is wire_speedup (rows/s ratio) and the
    # one-encode invariant (push_encodes == frames published)
    wire_rows: int = 0
    wire_json_rows_s: float = 0.0
    wire_columnar_rows_s: float = 0.0
    wire_speedup: float = 0.0
    wire_json_bytes: int = 0
    wire_columnar_bytes: int = 0
    wire_json_p50_ms: float = 0.0
    wire_json_p99_ms: float = 0.0
    wire_columnar_p50_ms: float = 0.0
    wire_columnar_p99_ms: float = 0.0
    push_sinks: int = 0
    push_frames: int = 0
    push_encodes: int = 0
    push_events_per_s: float = 0.0
    wire_parity_ok: bool = True
    wire_json_samples_ms: List[float] = dataclasses.field(
        default_factory=list)
    wire_columnar_samples_ms: List[float] = dataclasses.field(
        default_factory=list)
    push_publish_samples_ms: List[float] = dataclasses.field(
        default_factory=list)

    def to_json(self) -> dict:
        doc = dataclasses.asdict(self)
        doc.pop("samples_ms", None)  # report lines stay readable
        doc.pop("approx_samples_ms", None)
        doc.pop("exact_samples_ms", None)
        doc.pop("wire_json_samples_ms", None)
        doc.pop("wire_columnar_samples_ms", None)
        doc.pop("push_publish_samples_ms", None)
        if self.mode != "approx":
            for k in ("approx_ok", "exact_ok", "approx_p50_ms",
                      "approx_p99_ms", "exact_p50_ms", "exact_p99_ms",
                      "approx_speedup_p50", "tier_sketch", "tier_cached",
                      "tier_exact", "bound_violations"):
                doc.pop(k, None)
        if self.mode != "wire":
            for k in ("wire_rows", "wire_json_rows_s",
                      "wire_columnar_rows_s", "wire_speedup",
                      "wire_json_bytes", "wire_columnar_bytes",
                      "wire_json_p50_ms", "wire_json_p99_ms",
                      "wire_columnar_p50_ms", "wire_columnar_p99_ms",
                      "push_sinks", "push_frames", "push_encodes",
                      "push_events_per_s", "wire_parity_ok"):
                doc.pop(k, None)
        return doc


def device_ops_count() -> float:
    """Process-lifetime `serve.device.ops` counter: one tick per
    serve-path device interaction (staged transfer, kernel/program
    dispatch, combined sync read — utils.metrics.note_device_op). The
    delta across a measured run over the window count is
    `dispatches_per_window`, the ring-vs-pipeline headline."""
    from geomesa_tpu.utils.metrics import metrics

    with metrics._lock:
        return float(metrics.counters.get("serve.device.ops", 0.0))


def mesh_dispatch_count() -> float:
    """Process-lifetime count of windows that actually ran a mesh
    route (whole-mesh programs + shard-affinity local windows). The
    delta across a measured run is the honest "did the mesh serve
    this?" signal the topology reporting keys on (bench-serve uses it
    too for closed/open modes)."""
    from geomesa_tpu.utils.metrics import metrics

    with metrics._lock:
        c = metrics.counters
        return float(c.get("knn.mesh.dispatches", 0.0)
                     + c.get("knn.mesh.local_dispatches", 0.0))


def _report(mode: str, duration: float, lat_s: List[float], sent: int,
            rejected: int, timeouts: int, errors: int,
            stats: Dict[str, int]) -> LoadReport:
    lat = np.asarray(lat_s, np.float64) * 1000.0
    ok = len(lat)

    def q(p):
        return float(np.percentile(lat, p)) if ok else 0.0

    sorted_lat = np.sort(lat)
    stride = max(1, ok // 512)
    return LoadReport(
        mode=mode,
        duration_s=duration,
        sent=sent,
        ok=ok,
        rejected=rejected,
        timeouts=timeouts,
        errors=errors,
        throughput_qps=ok / duration if duration > 0 else 0.0,
        mean_ms=float(lat.mean()) if ok else 0.0,
        p50_ms=q(50), p95_ms=q(95), p99_ms=q(99),
        max_ms=float(lat.max()) if ok else 0.0,
        dispatches=stats.get("dispatches", 0),
        coalesced=stats.get("coalesced", 0),
        samples_ms=[round(float(v), 4) for v in sorted_lat[::stride]],
    )


class _Tally:
    def __init__(self):
        self.lock = threading.Lock()
        self.lat_s: List[float] = []
        self.sent = 0
        self.rejected = 0
        self.timeouts = 0
        self.errors = 0

    def outcome(self, t0: float, fut) -> None:
        try:
            fut.result()
            dt = time.monotonic() - t0
            with self.lock:
                self.lat_s.append(dt)
        except QueryTimeout:
            with self.lock:
                self.timeouts += 1
        except QueryRejected:
            with self.lock:
                self.rejected += 1
        except Exception:
            with self.lock:
                self.errors += 1


def run_closed_loop(
    service: QueryService,
    make_request: Callable[[int], ServeRequest],
    concurrency: int = 8,
    duration_s: float = 5.0,
    requests_per_client: Optional[int] = None,
) -> LoadReport:
    """N clients, each submit→wait→repeat until the duration elapses (or
    a fixed per-client request count when given — deterministic mode for
    tests)."""
    tally = _Tally()
    base = service.stats()
    deadline = time.monotonic() + duration_s

    def client(cid: int):
        i = 0
        while True:
            if requests_per_client is not None:
                if i >= requests_per_client:
                    return
            elif time.monotonic() >= deadline:
                return
            with tally.lock:
                tally.sent += 1
            t0 = time.monotonic()
            try:
                fut = service.submit(make_request(cid * 1_000_003 + i))
            except QueryRejected:
                with tally.lock:
                    tally.rejected += 1
                i += 1
                continue
            tally.outcome(t0, fut)
            i += 1

    t_start = time.monotonic()
    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    stats = service.stats()
    delta = {k: stats.get(k, 0) - base.get(k, 0)
             for k in ("dispatches", "coalesced")}
    return _report("closed", wall, tally.lat_s, tally.sent,
                   tally.rejected, tally.timeouts, tally.errors, delta)


def run_open_loop(
    service: QueryService,
    make_request: Callable[[int], ServeRequest],
    rate_qps: float = 100.0,
    duration_s: float = 5.0,
) -> LoadReport:
    """Fixed-rate arrivals (uniform spacing), submissions never wait for
    completions. Latency = submit→resolve, queue wait included."""
    if rate_qps <= 0:
        raise ValueError("rate_qps must be > 0")
    tally = _Tally()
    base = service.stats()
    interval = 1.0 / rate_qps
    pending: List[tuple] = []
    t_start = time.monotonic()
    deadline = t_start + duration_s
    i = 0
    while True:
        due = t_start + i * interval
        now = time.monotonic()
        if due >= deadline:
            break
        if due > now:
            time.sleep(due - now)
        with tally.lock:
            tally.sent += 1
        t0 = time.monotonic()
        try:
            fut = service.submit(make_request(i))
            pending.append((t0, fut))
        except QueryRejected:
            with tally.lock:
                tally.rejected += 1
        i += 1
    for t0, fut in pending:
        tally.outcome(t0, fut)
    wall = time.monotonic() - t_start
    stats = service.stats()
    delta = {k: stats.get(k, 0) - base.get(k, 0)
             for k in ("dispatches", "coalesced")}
    return _report("open", wall, tally.lat_s, tally.sent,
                   tally.rejected, tally.timeouts, tally.errors, delta)


def run_sustained(
    service: QueryService,
    make_request: Callable[[int], ServeRequest],
    duration_s: float = 5.0,
    max_outstanding: int = 32,
    points_per_query: int = 0,
    requests: Optional[int] = None,
) -> LoadReport:
    """Sustained-throughput mode (`gmtpu bench-serve --mode sustained`):
    a fixed-duration closed loop that keeps exactly `max_outstanding`
    requests in flight — submissions are gated by a semaphore released
    from future callbacks, not by per-client turnarounds — and reports
    points/sec plus the pipeline's windows-in-flight, not just latency
    percentiles. This is the loop that reproduces the BENCH sustained
    pts/s headline from the CLI: `pts_per_s = points_per_query *
    served_qps` (each served query scans the whole resident store).
    `requests` caps total submissions for deterministic test runs."""
    tally = _Tally()
    base = service.stats()
    mesh_base = mesh_dispatch_count()
    ops_base = device_ops_count()
    pipe = getattr(service, "pipeline", None)
    if pipe is not None:
        # the in-flight high-water must be THIS run's, not the service
        # lifetime's (a warmup pass on the same service would otherwise
        # donate its peak)
        pipe.reset_max_inflight()
    gate = threading.Semaphore(max_outstanding)
    deadline = time.monotonic() + duration_s
    inflight = []
    t_start = time.monotonic()

    def on_done(t0):
        # latency stamps at RESOLUTION time (the callback runs on the
        # resolving thread), not when the harvest loop gets around to
        # the future — with K outstanding the two differ by up to the
        # whole run
        def cb(fut):
            dt = time.monotonic() - t0
            try:
                fut.result()
            except QueryTimeout:
                with tally.lock:
                    tally.timeouts += 1
            except QueryRejected:
                with tally.lock:
                    tally.rejected += 1
            except BaseException:  # noqa: BLE001 — tally, never raise
                with tally.lock:
                    tally.errors += 1
            else:
                with tally.lock:
                    tally.lat_s.append(dt)
            gate.release()

        return cb

    i = 0
    while time.monotonic() < deadline:
        if requests is not None and i >= requests:
            break
        if not gate.acquire(timeout=0.1):
            continue
        with tally.lock:
            tally.sent += 1
        t0 = time.monotonic()
        try:
            fut = service.submit(make_request(i))
        except QueryRejected:
            with tally.lock:
                tally.rejected += 1
            gate.release()
            i += 1
            continue
        i += 1
        inflight.append(fut)
        fut.add_done_callback(on_done(t0))
    # completion barrier only — outcomes were tallied in the callbacks
    # (wait() reports, never raises; a straggler past the bound is
    # abandoned rather than blocking the report)
    futures_wait(inflight, timeout=120)
    wall = time.monotonic() - t_start
    stats = service.stats()
    delta = {k: stats.get(k, 0) - base.get(k, 0)
             for k in ("dispatches", "coalesced")}
    rep = _report("sustained", wall, tally.lat_s, tally.sent,
                  tally.rejected, tally.timeouts, tally.errors, delta)
    rep.pts_per_s = rep.throughput_qps * points_per_query
    p = stats.get("pipeline") or {}
    pbase = base.get("pipeline") or {}
    rep.windows_in_flight_max = int(p.get("max_inflight", 0))
    rep.pipelined_windows = (
        stats.get("pipelined_windows", 0)
        - base.get("pipelined_windows", 0))
    # delta against the pre-run snapshot, like dispatches/coalesced —
    # lifetime totals would credit a warmup pass to the measured run
    rep.fused_counts = int(p.get("fused_counts", 0)
                           - pbase.get("fused_counts", 0))
    ring = p.get("ring") or {}
    ring_base = pbase.get("ring") or {}
    rep.ring_windows = int(ring.get("windows", 0)
                           - ring_base.get("windows", 0))
    rep.ring_fallbacks = (
        sum((ring.get("fallbacks") or {}).values())
        - sum((ring_base.get("fallbacks") or {}).values()))
    # per-window device interactions: the measured run's
    # serve.device.ops delta over its window count (pipelined windows
    # when the pipeline ran, dispatch count on the serial stack) — the
    # ring route's claim is this number strictly below the pipelined
    # baseline's on identical work
    windows = rep.pipelined_windows or rep.dispatches
    if windows > 0:
        rep.dispatches_per_window = round(
            (device_ops_count() - ops_base) / windows, 3)
    mesh = getattr(service, "mesh", None)
    if mesh is not None and mesh_dispatch_count() > mesh_base:
        # topology is reported from the LAUNCH route, not the resolved
        # config: a store the residency tier cannot shard (extended
        # geometry, cold/no device cache) serves single-chip even when
        # ServeConfig.mesh names a mesh, and claiming mesh_devices for
        # it would let bench-serve print a mesh_speedup computed from
        # two identical single-chip runs
        rep.mesh_devices = int(mesh.devices.size)
        rep.per_shard_pts_per_s = rep.pts_per_s / rep.mesh_devices
    return rep


def run_subscribe(
    store,
    type_name: str,
    make_batch: Callable[[int], object],
    subscriptions: int = 8,
    batches: int = 20,
    extent=(-60.0, 60.0),
    density_shape=(64, 32),
    seed: int = 0,
    manager=None,
) -> LoadReport:
    """Standing-query load mode (`gmtpu bench-serve --mode subscribe`):
    register N subscriptions (bbox geofences, dwithin geofences and a
    density window, cycling) over a live Kafka store, produce + poll M
    batches from `make_batch(i)`, and report pushed events/s plus the
    per-batch eval+push latency distribution (p99 is the line the
    ISSUE's standing-query workload is judged on). The evaluator's
    one-dispatch-per-poll invariant is visible in the report:
    `dispatches` ≈ `batches` once the fused kernel is warm."""
    from geomesa_tpu.subscribe import DensityWindow, SubscriptionManager

    mgr = manager if manager is not None else SubscriptionManager(store)
    rng = np.random.default_rng(seed)
    geom = store.get_schema(type_name).default_geometry.name
    lo, hi = extent
    registered = []
    for i in range(subscriptions):
        kind = i % 3
        if kind == 0:
            x0 = float(rng.uniform(lo, hi - 30))
            y0 = float(rng.uniform(lo / 2, hi / 2 - 20))
            registered.append(mgr.subscribe(
                type_name,
                f"BBOX({geom}, {x0}, {y0}, {x0 + 30}, {y0 + 20})"))
        elif kind == 1:
            px = float(rng.uniform(lo / 2, hi / 2))
            py = float(rng.uniform(lo / 4, hi / 4))
            registered.append(mgr.subscribe(
                type_name,
                f"DWITHIN({geom}, POINT({px} {py}), 1500000, meters)"))
        else:
            w, h = density_shape
            registered.append(mgr.subscribe(type_name, density=DensityWindow(
                (lo, lo / 2, hi, hi / 2), w, h)))
    # warm fold OUTSIDE the measured window: THIS manager's fused
    # kernel (the AOT key includes the evaluator nonce + version, so a
    # throwaway warm manager would compile a different entry and leave
    # batch 0 paying the trace+compile), plus the registration-time
    # `state` snapshot frames — the benchmark reports INCREMENTAL push
    # throughput, not baseline transfer or compile time
    store.write(type_name, make_batch(batches))
    mgr.poll_now()
    mgr.flush(lambda _f: None)
    frames: List[dict] = []
    lat_s: List[float] = []
    base = mgr.evaluator.stats()
    t_start = time.monotonic()
    for i in range(batches):
        store.write(type_name, make_batch(i))
        t0 = time.monotonic()
        mgr.poll_now()
        mgr.flush(frames.append)
        lat_s.append(time.monotonic() - t0)
    wall = time.monotonic() - t_start
    ev = mgr.evaluator.stats()
    # incremental events only: geofence transitions count per fid,
    # density folds per frame; lifecycle frames (state/lagged/...)
    # are bookkeeping, not workload output
    events = 0
    for f in frames:
        if f.get("event") in ("enter", "exit"):
            events += len(f.get("fids", ()))
        elif f.get("event") == "density":
            events += 1
    rep = _report("subscribe", wall, lat_s, batches, 0, 0, 0,
                  {"dispatches": ev.get("dispatches", 0)
                   - base.get("dispatches", 0), "coalesced": 0})
    rep.subscriptions = subscriptions
    rep.batches = batches
    rep.events_total = events
    rep.events_per_s = events / wall if wall > 0 else 0.0
    if manager is None:
        mgr.close()
    else:
        # caller-owned manager: cancel what THIS call registered, or
        # repeated runs accumulate 8 stale subs each — every
        # intervening poll pays fused evaluation for them until the
        # table bound rejects run ~32 with subscription_limit
        for s in registered:
            try:
                mgr.unsubscribe(s.sub_id)
            except KeyError:
                pass  # TTL-expired mid-run
    return rep


def run_subscribe_lanes(
    make_store,
    type_name: str,
    make_batch: Callable[[int], object],
    subscriptions: int = 1024,
    batches: int = 4,
    extent=(-60.0, 28.0, -30.0, 9.0),
    seed: int = 5,
    fused: bool = True,
    churn: bool = True,
) -> dict:
    """Lane-vs-fused-slot comparison (`gmtpu bench-serve --mode subscribe
    --lanes`, docs/SERVING.md "Standing queries"): register S same-class
    bbox geofences on a FRESH store per mode, then time the identical
    protocol under `SubscribeConfig(lanes=...)` both ways — first poll
    (where the fused path pays an S-proportional trace+compile and the
    lane path a single S-independent batched kernel), `batches` steady
    polls, and optionally one membership-churn event (register + cancel
    + poll: a full S-wide rebuild for fused slots, a parameter-row write
    for lanes). Events are identical across modes by construction, so
    `speedup` is the lane/fused events-per-second ratio over matching
    windows. Subscriptions register BEFORE the seed batch lands: the
    empty-store bootstrap is then a bookkeeping write, keeping the first
    measured poll about evaluation, not baseline transfer.

    `fused=False` skips the fused leg entirely — its compile cost grows
    super-linearly with S (measured ~1 s at S=64, ~11 s at S=256,
    ~120 s at S=1024 on CPU CI), so sweeps cap the fused mode and run
    lane-only beyond the cap rather than silently extrapolating."""
    from geomesa_tpu.subscribe import SubscribeConfig, SubscriptionManager

    x_lo, x_hi, y_lo, y_hi = extent

    def _mode(lanes: bool) -> dict:
        store = make_store()
        mgr = SubscriptionManager(store, SubscribeConfig(
            max_subscriptions=subscriptions + 8, lanes=lanes))
        geom = store.get_schema(type_name).default_geometry.name
        rng = np.random.default_rng(seed)
        registered = []
        for _ in range(subscriptions):
            x0 = float(rng.uniform(x_lo, x_hi))
            y0 = float(rng.uniform(y_lo, y_hi))
            registered.append(mgr.subscribe(
                type_name,
                f"BBOX({geom}, {x0}, {y0}, {x0 + 2}, {y0 + 2})"))
        store.write(type_name, make_batch(10_001))
        frames: List[dict] = []
        base = mgr.evaluator.stats()
        polls = 0
        t_start = time.monotonic()
        mgr.poll_now()
        mgr.flush(frames.append)
        first_poll_s = time.monotonic() - t_start
        polls += 1
        for i in range(batches):
            store.write(type_name, make_batch(i))
            mgr.poll_now()
            mgr.flush(frames.append)
            polls += 1
        churn_poll_s = None
        if churn:
            x0 = float(rng.uniform(x_lo, x_hi))
            y0 = float(rng.uniform(y_lo, y_hi))
            mgr.subscribe(
                type_name,
                f"BBOX({geom}, {x0}, {y0}, {x0 + 2}, {y0 + 2})")
            mgr.unsubscribe(registered[0].sub_id)
            store.write(type_name, make_batch(batches))
            t0 = time.monotonic()
            mgr.poll_now()
            mgr.flush(frames.append)
            churn_poll_s = time.monotonic() - t0
            polls += 1
        wall = time.monotonic() - t_start
        ev = mgr.evaluator.stats()
        # enter/exit transitions only, as run_subscribe counts them —
        # registration-time `state` frames are bookkeeping, and on the
        # register-before-seed protocol they are empty anyway
        events = 0
        for f in frames:
            if f.get("event") in ("enter", "exit"):
                events += len(f.get("fids", ()))
        dispatches = ev.get("dispatches", 0) - base.get("dispatches", 0)
        out = {
            "mode": "lanes" if lanes else "fused",
            "polls": polls,
            "wall_s": round(wall, 3),
            "events_total": events,
            "events_per_s": round(events / wall, 1) if wall > 0 else 0.0,
            "dispatches": dispatches,
            "dispatches_per_poll":
                round(dispatches / polls, 3) if polls else 0.0,
            "lane_dispatches": ev.get("lane_dispatches", 0)
            - base.get("lane_dispatches", 0),
            "first_poll_s": round(first_poll_s, 3),
        }
        if churn_poll_s is not None:
            out["churn_poll_s"] = round(churn_poll_s, 3)
        mgr.close()
        return out

    lanes_rep = _mode(True)
    out = {
        "run": "subscribe_lanes",
        "subscriptions": subscriptions,
        "batches": batches,
        "lanes": lanes_rep,
        "fused": None,
    }
    if fused:
        fused_rep = _mode(False)
        out["fused"] = fused_rep
        if fused_rep["events_per_s"] > 0:
            out["speedup"] = round(
                lanes_rep["events_per_s"] / fused_rep["events_per_s"], 1)
    else:
        out["note"] = ("fused leg skipped: S-proportional compile cost "
                       "exceeds the bench budget at this S")
    return out


def run_wire(
    store,
    type_name: str,
    rows: int = 100_000,
    iters_json: int = 3,
    iters_columnar: int = 10,
    push_sinks: int = 1000,
    push_frames: int = 50,
    push_fids: int = 64,
) -> LoadReport:
    """`bench-serve --mode wire` (docs/SERVING.md "Columnar wire"):
    encode ONE bulk `execute` result both ways — the JSON-lines path
    (per-row dict + json.dumps, exactly what the legacy wire ships)
    vs the columnar Arrow record-batch frame — over identical rows,
    and report rows/s, bytes and encode p50/p99 for each, plus a
    PushMux fan-out run (`push_frames` enter-frames to `push_sinks`
    in-process subscribers) whose one-encode-per-frame invariant is
    part of the verdict. Decoded columnar rows are parity-checked
    against the JSON rows before anything is timed "ok"."""
    import json as _json

    from geomesa_tpu.plan.query import Query
    from geomesa_tpu.serve import columnar as colwire
    from geomesa_tpu.serve.protocol import _payload

    if not colwire.have_pyarrow():
        # same stance as the wire itself: capability absence is typed,
        # never a mid-bench ModuleNotFoundError traceback
        raise RuntimeError(
            "bench-serve --mode wire needs pyarrow (this host's wire "
            "capability is json-only)")
    source = store.get_feature_source(type_name)
    result = source.planner.execute(
        Query(type_name, "INCLUDE", max_features=rows))
    n = len(result.features) if result.features is not None else 0

    def one_json() -> "tuple[bytes, float]":
        t0 = time.monotonic()
        doc = {"id": "b", "ok": True}
        doc.update(_payload("execute", result, rows))
        buf = (_json.dumps(doc) + "\n").encode()
        return buf, time.monotonic() - t0

    def one_columnar() -> "tuple[bytes, float]":
        t0 = time.monotonic()
        fields, payload = colwire.encode_execute_frame(
            result.features, rows)
        doc = {"id": "b", "ok": True, "kind": "features",
               "count": fields["rows"] if "rows" in fields else n}
        doc["frame"] = fields
        buf = colwire.frame_bytes(doc, payload)
        return buf, time.monotonic() - t0

    # parity BEFORE timing: a fast encoder that decodes wrong is not a
    # result (acceptance: decoded columnar == JSON rows, bit-identical)
    jbuf, _ = one_json()
    cbuf, _ = one_columnar()
    jrows = _json.loads(jbuf.decode())["features"]
    (cdoc, cpayload), = colwire.parse_stream(cbuf)
    crows = colwire.decode_execute_payload(cpayload)
    parity_ok = crows == jrows
    j_ms = []
    for _ in range(max(iters_json, 1)):
        jbuf, dt = one_json()
        j_ms.append(dt * 1000.0)
    c_ms = []
    for _ in range(max(iters_columnar, 1)):
        cbuf, dt = one_columnar()
        c_ms.append(dt * 1000.0)
    j_med = float(np.median(j_ms))
    c_med = float(np.median(c_ms))
    json_rows_s = n / (j_med / 1000.0) if j_med > 0 else 0.0
    col_rows_s = n / (c_med / 1000.0) if c_med > 0 else 0.0

    # push fan-out: one frame encoded once, fanned to every sink
    # (unthreaded in-process sinks — the encode counter is the claim
    # under test; threaded writer isolation is tests/test_wire.py's)
    mux = colwire.PushMux(queue_limit=push_frames + 8)
    sunk = [0]

    def sink_write(buf: bytes) -> None:
        sunk[0] += len(buf)

    sinks = [mux.register(sink_write, mode=colwire.WIRE_JSON,
                          threaded=False) for _ in range(push_sinks)]
    fids = [f"bench-f{i}" for i in range(push_fids)]
    p_ms = []
    t0 = time.monotonic()
    for i in range(push_frames):
        f0 = time.monotonic()
        mux.publish({"event": "enter", "subscription": "bench-sub",
                     "seq": i + 1, "fids": fids}, sinks)
        p_ms.append((time.monotonic() - f0) * 1000.0)
    push_wall = max(time.monotonic() - t0, 1e-9)
    mux_stats = mux.stats()
    mux.close()

    rep = _report("wire", sum(j_ms) / 1000.0 + sum(c_ms) / 1000.0,
                  [v / 1000.0 for v in c_ms],
                  iters_json + iters_columnar, 0, 0, 0, {})
    rep.wire_rows = n
    rep.wire_json_rows_s = json_rows_s
    rep.wire_columnar_rows_s = col_rows_s
    rep.wire_speedup = (col_rows_s / json_rows_s
                        if json_rows_s > 0 else 0.0)
    rep.wire_json_bytes = len(jbuf)
    rep.wire_columnar_bytes = len(cbuf)
    rep.wire_json_p50_ms = float(np.percentile(j_ms, 50))
    rep.wire_json_p99_ms = float(np.percentile(j_ms, 99))
    rep.wire_columnar_p50_ms = float(np.percentile(c_ms, 50))
    rep.wire_columnar_p99_ms = float(np.percentile(c_ms, 99))
    rep.push_sinks = push_sinks
    rep.push_frames = push_frames
    rep.push_encodes = mux_stats["encodes"]
    rep.push_events_per_s = push_frames * push_sinks / push_wall
    rep.wire_parity_ok = parity_ok
    rep.wire_json_samples_ms = sorted(j_ms)
    rep.wire_columnar_samples_ms = sorted(c_ms)
    rep.push_publish_samples_ms = sorted(p_ms)
    return rep


def run_approx(
    service: QueryService,
    type_name: str,
    cqls: List[str],
    duration_s: float = 5.0,
    clients: int = 8,
    tolerance: float = 0.1,
    requests_per_client: Optional[int] = None,
    exact_counts: Optional[Dict[str, int]] = None,
) -> LoadReport:
    """`bench-serve --mode approx`: a closed-loop workload mixing
    TOLERANT count clients (hints.tolerance — eligible for the sketch
    tier) and EXACT clients (the device-scan path) over a cycling CQL
    list, reporting per-tier p50/p99 and the sketch-vs-exact speedup at
    bound-respecting accuracy. `exact_counts` (cql -> exact answer,
    computed outside the measured window) arms per-answer bound
    verification: every approx answer whose interval does not contain
    the exact answer counts as a bound violation (must be zero)."""
    from geomesa_tpu.plan.hints import QueryHints
    from geomesa_tpu.plan.query import Query

    tally = _Tally()
    base = service.stats()
    deadline = time.monotonic() + duration_s
    approx_lat: List[float] = []
    exact_lat: List[float] = []
    violations = [0]
    lock = threading.Lock()

    def client(cid: int):
        tolerant = cid % 2 == 0
        i = 0
        while True:
            if requests_per_client is not None:
                if i >= requests_per_client:
                    return
            elif time.monotonic() >= deadline:
                return
            cql = cqls[(cid + i) % len(cqls)]
            hints = (QueryHints(tolerance=tolerance) if tolerant
                     else QueryHints())
            req = ServeRequest(kind="count",
                               query=Query(type_name, cql, hints=hints))
            with tally.lock:
                tally.sent += 1
            t0 = time.monotonic()
            try:
                fut = service.submit(req)
            except QueryRejected:
                with tally.lock:
                    tally.rejected += 1
                i += 1
                continue
            try:
                value = fut.result()
                dt = time.monotonic() - t0
                with tally.lock:
                    tally.lat_s.append(dt)
                served_approx = getattr(value, "approx", False)
                # classify by the TIER that answered, not the client's
                # intent: a tolerant request whose bound did not fit
                # paid the exact path and belongs in the exact leg —
                # the speedup headline is sketch-tier vs device-scan
                with lock:
                    (approx_lat if served_approx
                     else exact_lat).append(dt)
                if served_approx and exact_counts is not None:
                    exact = exact_counts.get(cql)
                    if exact is not None and \
                            abs(int(value) - exact) > value.bound:
                        with lock:
                            violations[0] += 1
            except QueryTimeout:
                with tally.lock:
                    tally.timeouts += 1
            except QueryRejected:
                with tally.lock:
                    tally.rejected += 1
            except Exception:
                with tally.lock:
                    tally.errors += 1
            i += 1

    t_start = time.monotonic()
    threads = [threading.Thread(target=client, args=(c,), daemon=True)
               for c in range(clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    wall = time.monotonic() - t_start
    stats = service.stats()
    delta = {k: stats.get(k, 0) - base.get(k, 0)
             for k in ("dispatches", "coalesced")}
    rep = _report("approx", wall, tally.lat_s, tally.sent,
                  tally.rejected, tally.timeouts, tally.errors, delta)

    def q(arr, p):
        return (float(np.percentile(np.asarray(arr) * 1000.0, p))
                if arr else 0.0)

    rep.approx_ok = len(approx_lat)
    rep.exact_ok = len(exact_lat)
    rep.approx_p50_ms = q(approx_lat, 50)
    rep.approx_p99_ms = q(approx_lat, 99)
    rep.exact_p50_ms = q(exact_lat, 50)
    rep.exact_p99_ms = q(exact_lat, 99)
    if rep.approx_p50_ms > 0:
        rep.approx_speedup_p50 = rep.exact_p50_ms / rep.approx_p50_ms
    tiers = (stats.get("approx") or {}).get("tiers", {})
    base_tiers = (base.get("approx") or {}).get("tiers", {})
    rep.tier_sketch = tiers.get("sketch", 0) - base_tiers.get("sketch", 0)
    rep.tier_cached = tiers.get("cached", 0) - base_tiers.get("cached", 0)
    rep.tier_exact = tiers.get("exact", 0) - base_tiers.get("exact", 0)
    rep.bound_violations = violations[0]
    for arr, dest in ((approx_lat, rep.approx_samples_ms),
                      (exact_lat, rep.exact_samples_ms)):
        s = np.sort(np.asarray(arr, np.float64) * 1000.0)
        stride = max(1, len(s) // 512)
        dest.extend(round(float(v), 4) for v in s[::stride])
    return rep


def run_fleet_bench(
    catalog: str,
    type_name: str,
    n_replicas: int = 2,
    duration_s: float = 5.0,
    clients: int = 8,
    k: int = 8,
    kill: bool = True,
    kill_window_s: float = 2.0,
    seed: int = 0,
    store_factory=None,
    subscribe_rider: bool = False,
) -> dict:
    """`gmtpu bench-serve --fleet N`: closed-loop clients over the
    ROUTER's wire (real sockets, real failover), one replica killed
    abruptly at half-time. The report separates overall latency from
    the p99 DURING the kill window — the number the fleet exists for —
    and asserts the accounting the chaos certification relies on:
    every request answered (zero dropped), zero un-typed errors.
    Thread-spawn replicas: same code path as deployment minus process
    spin-up, so the comparison measures routing + failover, not jax
    import time.

    `subscribe_rider` adds one standing query THROUGH the router for
    the bench's lifetime (needs a live-layer `store_factory` — the
    replicas must share a pollable store): the report gains a `rider`
    block with the frames seen, resyncs paid, and whether the stream
    survived the kill via the router's re-home — continuity measured
    under the same query storm the latency numbers come from."""
    import time as _time

    from geomesa_tpu.fleet import FleetConfig, FleetSupervisor
    from geomesa_tpu.fleet.wire import connect_json

    sup = FleetSupervisor(FleetConfig(
        n_replicas=n_replicas, catalog=catalog,
        store_factory=store_factory,
        probe_interval_s=0.25))
    lock = threading.Lock()
    lat: List[tuple] = []      # (t_done, latency_s, ok)
    counts = {"sent": 0, "ok": 0, "unavailable": 0, "rejected": 0,
              "timeout": 0, "untyped": 0, "answered": 0}
    kill_at = [None]
    try:
        port = sup.start()
        # warm EVERY replica before the measured window: kernel jits
        # are process-wide (thread spawn) but filter-compile and
        # residency caches are per-replica — an unwarmed replica would
        # charge its cold compiles to the measured p99 (and leave the
        # kill window empty of completions on slow CI hosts)
        for h in sup.membership.all():
            wconn = connect_json(h.host, h.port)
            try:
                for wid, wdoc in (
                    ("w1", {"op": "knn", "typeName": type_name,
                            "cql": "BBOX(geom, -180, -90, 180, 90)",
                            "x": [1.5], "y": [2.5], "k": k}),
                    ("w2", {"op": "count", "typeName": type_name,
                            "cql": "BBOX(geom, -180, -90, 180, 90)"}),
                ):
                    wconn.request({"id": wid, **wdoc}, timeout_s=300.0)
            finally:
                wconn.close()
        stop = threading.Event()

        rider_frames: List[dict] = []
        rider_sub = [None]
        rider_cli = None
        if subscribe_rider:
            from geomesa_tpu.fleet.router import FleetClient

            rider_cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
            got = rider_cli.request(
                {"op": "subscribe", "typeName": type_name,
                 "cql": "BBOX(geom, -60, -30, 60, 30)"},
                on_push=rider_frames.append)
            if got.get("ok"):
                rider_sub[0] = got["subscription"]

        def rider_loop():
            # the standing query rides the storm: periodic polls keep
            # the owner folding while the kill + re-home happen
            while not stop.wait(0.2):
                try:
                    rider_cli.request({"op": "poll"},
                                      on_push=rider_frames.append)
                except (OSError, TimeoutError):
                    return

        rider_thread = None
        if rider_sub[0] is not None:
            rider_thread = threading.Thread(target=rider_loop,
                                            daemon=True)
            rider_thread.start()

        def client(cid: int):
            rng = np.random.default_rng(seed * 9973 + cid)
            conn = connect_json("127.0.0.1", port)
            i = 0
            try:
                while not stop.is_set():
                    qx = float(rng.uniform(-60, 60))
                    qy = float(rng.uniform(-60, 60))
                    doc = {"id": f"c{cid}-{i}", "op": "knn",
                           "typeName": type_name,
                           "cql": "BBOX(geom, -180, -90, 180, 90)",
                           "x": [qx], "y": [qy], "k": k,
                           "timeoutMs": 30_000}
                    with lock:
                        counts["sent"] += 1
                    t0 = _time.monotonic()
                    try:
                        got = conn.request(doc, timeout_s=60.0)
                    except (OSError, TimeoutError):
                        with lock:
                            counts["untyped"] += 1
                        return
                    dt = _time.monotonic() - t0
                    with lock:
                        counts["answered"] += 1
                        if got.get("ok"):
                            counts["ok"] += 1
                            lat.append((_time.monotonic(), dt, True))
                        elif got.get("error") in ("unavailable",
                                                  "rejected", "timeout"):
                            counts[got["error"]] += 1
                            lat.append((_time.monotonic(), dt, False))
                        else:
                            counts["untyped"] += 1
                    i += 1
            finally:
                conn.close()

        threads = [threading.Thread(target=client, args=(c,),
                                    daemon=True)
                   for c in range(clients)]
        t_start = _time.monotonic()
        for t in threads:
            t.start()
        if kill and n_replicas > 1:
            _time.sleep(duration_s / 2.0)
            victim = next(h.replica_id for h in sup.membership.all()
                          if h.state in ("ready", "degraded"))
            kill_at[0] = _time.monotonic()
            sup.kill_replica(victim, graceful=False)
        deadline = t_start + duration_s
        while _time.monotonic() < deadline:
            _time.sleep(0.05)
        stop.set()
        for t in threads:
            t.join(timeout=90.0)
        if rider_thread is not None:
            rider_thread.join(timeout=30.0)
        if rider_cli is not None:
            try:
                rider_cli.close()
            except OSError:
                pass
        wall = _time.monotonic() - t_start
        router = sup.stats()["router"]
    finally:
        sup.close()

    ok_lat = np.asarray([d for _, d, ok in lat if ok], np.float64) * 1e3

    def q(arr, p):
        return round(float(np.percentile(arr, p)), 3) if len(arr) else 0.0

    doc = {
        "mode": "fleet",
        "replicas": n_replicas,
        "duration_s": round(wall, 3),
        "killed": kill and n_replicas > 1,
        **counts,
        "dropped": counts["sent"] - counts["answered"]
        - counts["untyped"],
        "throughput_qps": round(counts["ok"] / wall, 2) if wall else 0.0,
        "p50_ms": q(ok_lat, 50), "p99_ms": q(ok_lat, 99),
        "retried": router["retried"],
        "shed": router["shed"],
    }
    if kill_at[0] is not None:
        in_window = np.asarray(
            [d for t, d, ok in lat if ok
             and kill_at[0] <= t <= kill_at[0] + kill_window_s],
            np.float64) * 1e3
        doc["p99_during_kill_ms"] = q(in_window, 99)
        doc["served_during_kill"] = int(len(in_window))
    if subscribe_rider:
        evs = [f for f in rider_frames
               if f.get("subscription") == rider_sub[0]]
        seqs = [f.get("seq") for f in evs]
        doc["rider"] = {
            "subscribed": rider_sub[0] is not None,
            "frames": len(evs),
            # resyncs past the initial state frame = failovers paid
            "resyncs": sum(1 for f in evs[1:]
                           if f.get("event") == "state"),
            "seq_monotonic": seqs == sorted(seqs)
            and len(set(seqs)) == len(seqs),
            "rehomed": router.get("rehome_succeeded", 0),
        }
    return doc


# -- request factories -----------------------------------------------------


def knn_request_factory(type_name: str, cql: str, extent=(-60.0, 60.0),
                        k: int = 8, seed: int = 0,
                        **kw) -> Callable[[int], ServeRequest]:
    """Random single-point kNN requests sharing one (filter, k) — the
    maximally-coalescible serving workload. Points derive from the
    request index, so two runs offer identical work."""
    lo, hi = extent

    def make(i: int) -> ServeRequest:
        rng = np.random.default_rng(seed * 7_919 + i)
        from geomesa_tpu.plan.query import Query

        req = ServeRequest(kind="knn", query=Query(type_name, cql), **kw)
        req.qx = rng.uniform(lo, hi, 1)
        req.qy = rng.uniform(lo, hi, 1)
        req.k = k
        return req

    return make


def count_request_factory(type_name: str, cqls: List[str],
                          **kw) -> Callable[[int], ServeRequest]:
    """Counts cycling through a fixed CQL set: coalescing dedups the
    repeats, distinct filters dispatch apart."""
    from geomesa_tpu.plan.query import Query

    def make(i: int) -> ServeRequest:
        return ServeRequest(
            kind="count", query=Query(type_name, cqls[i % len(cqls)]), **kw)

    return make
