"""QueryService: the concurrent serving front end.

Wires the admission scheduler (bounded queue, priority classes, tenant
rate limits, typed shedding) to the request batcher (coalesced device
dispatches) over a DataStore. One dispatch thread drives the device —
the accelerator runs one program at a time, so more dispatch threads
would only interleave launches, not add throughput; concurrency buys
throughput here through COALESCING, not parallel dispatch.

Lifecycle:

    svc = QueryService(store)                 # starts the dispatcher
    fut = svc.knn("gdelt", CQL, qx, qy, k=8)  # -> Future
    dists, idx, batch = fut.result()
    svc.close(drain=True)                     # graceful: finish queue

Degradation ladder (opt-in per request via allow_degraded, master switch
ServeConfig.degrade): as queue occupancy crosses the watermarks the
service first downgrades hints (level 1: loose bbox — skip the exact
residual re-check of the spatial primary; level 2: + 1-in-4 sampling),
then sheds batch-class work, and the bounded queue rejects the rest.
Responses from downgraded queries carry request.degraded = True.

Observability: per-request ServeEvents into the store's audit writer,
queue-wait and end-to-end latency histograms (p50/p95/p99 via the
Prometheus export), dispatch/coalesce/shed counters — all through
`geomesa_tpu.utils.metrics` plus a per-instance `stats()` snapshot.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import Future
from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu.compilecache.stall import STALLS
from geomesa_tpu.plan.audit import ServeEvent
from geomesa_tpu.plan.planner import QueryTimeout
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve.batcher import (
    MIN_KNN_BATCH, compat_key, execute_batch, fail_expired, split_expired)
from geomesa_tpu.serve.scheduler import (
    PRIORITIES, AdmissionQueue, QueryRejected, RateLimiter, ServeRequest)
from geomesa_tpu.telemetry.recorder import RECORDER
from geomesa_tpu.telemetry.trace import TRACER
from geomesa_tpu.utils.padding import next_pow2 as _next_pow2


@dataclasses.dataclass
class ServeConfig:
    max_queue: int = 128        # admission bound (backpressure, not buffer)
    max_batch: int = 64         # coalescing cap per dispatch
    max_wait_ms: float = 2.0    # coalescing window: added latency ceiling
    default_timeout_ms: Optional[int] = None  # per-request deadline default
    tenant_rate: Optional[float] = None  # qps per tenant; None = unlimited
    tenant_burst: float = 8.0
    # poison-query quarantine (docs/ROBUSTNESS.md): a fingerprint that
    # crashes `quarantine_after` dispatches within the TTL is rejected
    # at admission with QueryRejected("quarantined") instead of
    # re-entering the dispatcher; 0 disables
    quarantine_after: int = 3
    quarantine_ttl_s: float = 600.0
    degrade: bool = False       # master switch for the degradation ladder
    degrade_watermark: float = 0.75  # queue occupancy -> hint downgrades
    shed_watermark: float = 0.90     # queue occupancy -> shed batch class
    drain_timeout_s: float = 30.0
    # cold-start management (docs/SERVING.md "Cold start"): a manifest
    # path replays BEFORE the dispatcher starts accepting traffic;
    # track_compiles installs a JitTracker over the engine jits so
    # recompiles are counted and ServeEvents carry kernel compile-stall
    # attribution (warmup()/record_warmup() install it on demand too)
    warmup_manifest: Optional[str] = None
    track_compiles: bool = False
    # telemetry (docs/OBSERVABILITY.md): trace=True enables the
    # PROCESS-WIDE span tracer at construction (TRACER is global — one
    # switch per process, like the stall meter); flight_dump sets the
    # flight recorder's crash-dump path for this process
    trace: bool = False
    flight_dump: Optional[str] = None
    # SLO engine (docs/OBSERVABILITY.md "SLOs"): a spec path (.toml/
    # .json), a spec dict, an SloSpec, or a pre-built SloEngine (tests
    # inject one with a fake clock). When set, every resolved request
    # feeds the engine's sliding windows, `slo.*` gauges export at
    # scrape time, /debug/slo renders the burn report, and — with
    # `degrade` on — the ladder takes the engine's burn-rate boost as a
    # second input alongside queue occupancy, so shedding engages on
    # budget exhaustion too
    slo: object = None
    # continuous profiler (telemetry/prof.py): fold every recorded
    # trace into the process-lifetime distributions (requires trace=
    # True to have traces to fold; the fold itself is budgeted and the
    # flag only flips the process-wide PROFILER switch)
    profile: bool = False
    # pipelined dispatch (docs/SERVING.md "Pipelined dispatch"): kNN
    # windows run prepare/transfer/launch on the dispatch thread and
    # defer the device sync to a completer thread, keeping up to
    # `pipeline_depth` windows in flight (transfer overlaps compute —
    # the ROADMAP item-2 host-gap work). pipeline=False restores the
    # fully serial dispatch (chaos determinism runs use it).
    # pipeline_donate: None = auto (donate staged query buffers via the
    # registry serve tier on backends that support donation; CPU does
    # not), True/False forces.
    pipeline: bool = True
    pipeline_depth: int = 2
    pipeline_donate: Optional[bool] = None
    # persistent serve loop (docs/SERVING.md "Persistent serve loop"):
    # eligible kNN window classes dispatch over ONE long-lived ring
    # program (frozen plan/mask/capacity, AOT handle, depth-`ring_depth`
    # ring of donated staging slots) — per window only a slot write +
    # one executable invocation + the completer's harvest read.
    # Ineligible or stale windows fall back typed to the pipeline
    # above; ring=False disables the tier entirely (serial-determinism
    # and chaos runs that already disable the pipeline get it for free)
    ring: bool = True
    ring_depth: int = 4
    # sharded serving (docs/SERVING.md "Sharded serving"): route live
    # traffic through the multi-chip engine. "auto" = single-chip on 1
    # device, sharded over every device when >1 (the `gmtpu serve`
    # default); N = first N devices; None/"off" = single-chip. When a
    # mesh resolves, the store's device cache re-tiers to mesh
    # residency (one NamedSharding upload per manifest snapshot, per-
    # chip tile ownership), coalesced kNN windows dispatch as ONE
    # pjit/shard_map program with psum/all_gather merge, and admission
    # tags each query's shard affinity so single-owner windows run on
    # the chip their tiles live on.
    mesh: object = None
    # standing queries (docs/SERVING.md "Standing queries"): bounds and
    # rate limits for the subscribe/unsubscribe wire verbs; the
    # SubscriptionManager shares this service's per-tenant token
    # buckets, so queries and subscriptions draw one admission budget.
    # subscribe_poll_ms drives the auto-poll pump while subscriptions
    # are active (None = polls happen only on the `poll` verb or when
    # queries fold the topic)
    subscribe_max: int = 256
    subscribe_outbox: int = 1024
    subscribe_rate: Optional[float] = None
    subscribe_poll_ms: Optional[float] = None
    # approximate-answer tier (docs/SERVING.md "Approximate answers"):
    # approx=True lets tolerant queries (hints.tolerance) serve from
    # sketches with typed bounds; while the SLO exactness budget is
    # spent the tolerance hint is STRIPPED at admission (budget
    # exhaustion moves traffic to the exact path, never to silent
    # accuracy loss). approx_degrade_tolerance is the degradation
    # ladder's first rung — BEFORE loose-bbox: an allow_degraded
    # count/density under overload gets a sketch answer with a bound
    # instead of a silently loosened exact scan.
    approx: bool = True
    approx_degrade_tolerance: float = 0.1
    # version-exact result cache: count/execute results keyed on
    # (typeName, canonical CQL, hints, manifest version) — repeated
    # dashboard queries cost a dict lookup, invalidation is exact by
    # construction (a write bumps the version). 0 disables.
    result_cache: int = 256


def _quarantine_key(req: ServeRequest):
    """Poison fingerprint: the coalescing key (canonical CQL + kind +
    kernel choice — exactly what would share the crashing dispatch), or
    a coarse (kind, type) key for requests that never coalesce."""
    return compat_key(req) or ("solo", req.kind, req.query.type_name)


class QueryService:
    """In-process serving API over a DataStore (or any store exposing
    get_feature_source). Thread-safe: submit from any thread."""

    def __init__(self, store, config: Optional[ServeConfig] = None,
                 autostart: bool = True):
        self.store = store
        self.config = config or ServeConfig()
        # sharded serving: resolve the mesh spec once and install it on
        # the store — existing sources re-tier their device cache, new
        # sources inherit it (docs/SERVING.md "Sharded serving").
        # None = inherit whatever the store already carries (a store
        # constructed with DataStore(mesh=...) serves sharded
        # regardless of the config spelling); "off" = force single-chip,
        # clearing a previously installed mesh.
        if self.config.mesh is not None:
            from geomesa_tpu.parallel.mesh import serve_mesh

            self.mesh = serve_mesh(self.config.mesh)
            if hasattr(store, "set_mesh"):
                store.set_mesh(self.mesh)
        else:
            self.mesh = getattr(store, "mesh", None)
        self.queue = AdmissionQueue(self.config.max_queue)
        self.limiter = RateLimiter(
            self.config.tenant_rate, self.config.tenant_burst)
        from geomesa_tpu.faults import QuarantineRegistry

        self.quarantine = QuarantineRegistry(
            strikes=max(self.config.quarantine_after, 1),
            ttl_s=self.config.quarantine_ttl_s)
        # version-exact result cache (geomesa_tpu.approx.cache):
        # admission peeks it before queueing, the dispatch loop
        # populates it, and a hit never enters a coalescing window
        self.result_cache = None
        if self.config.result_cache > 0:
            from geomesa_tpu.approx.cache import ResultCache

            self.result_cache = ResultCache(self.config.result_cache)
        self.audit = getattr(store, "audit", None)
        if self.config.trace:
            TRACER.enable()
        if self.config.flight_dump:
            RECORDER.auto_dump_path = self.config.flight_dump
        if self.config.profile:
            from geomesa_tpu.telemetry.prof import PROFILER

            PROFILER.enable()
        # SLO engine: accept a path, dict, SloSpec or a ready engine
        # (tests pass one with a fake clock)
        self.slo = None
        if self.config.slo is not None:
            from geomesa_tpu.telemetry.slo import SloEngine, SloSpec

            spec = self.config.slo
            if isinstance(spec, SloEngine):
                self.slo = spec
            else:
                if isinstance(spec, str):
                    spec = SloSpec.load(spec)
                elif isinstance(spec, dict):
                    spec = SloSpec.from_dict(spec)
                self.slo = SloEngine(spec)
        self._closed = False
        self._stop = threading.Event()
        self._inflight = 0
        self._state_lock = threading.Lock()
        self._counters: Dict[str, int] = {}
        self._worker: Optional[threading.Thread] = None
        # standing-query manager (geomesa_tpu.subscribe): attached by
        # the wire layer when the first subscribe verb arrives, so
        # stats()/debug endpoints surface subscription state
        self.subscriptions = None
        # columnar-wire push fan-out (docs/SERVING.md "Columnar wire"):
        # ONE PushMux per service, shared by every connection so a
        # subscription's frames can mirror onto attached connections —
        # built lazily by wire_mux() on the first push/attach
        self._push_mux = None
        # the bound /metrics port, when the owner started a
        # MetricsServer for this service (gmtpu serve --metrics-port,
        # fleet replicas). With port=0 the OS picks — N replicas on one
        # host must not collide on a fixed port — so the bound value is
        # reported here and in the startup line, not assumed
        self.metrics_port: Optional[int] = None
        # pipelined dispatch path (serve/pipeline.py): the default for
        # kNN windows; its completer thread starts lazily on the first
        # pipelined window
        self.pipeline = None
        if self.config.pipeline:
            from geomesa_tpu.serve.pipeline import DispatchPipeline

            self.pipeline = DispatchPipeline(
                self, depth=self.config.pipeline_depth,
                donate=self.config.pipeline_donate,
                ring=self.config.ring,
                ring_depth=self.config.ring_depth)
        # compilation management: compiled executables must survive
        # restarts (the cache is idempotent/never-failing to enable)
        try:
            from geomesa_tpu.compilecache.persist import (
                enable_persistent_cache)

            enable_persistent_cache()
        # gt: waive GT14
        # (deliberate degrade: the persistent compile cache is an
        # optimization that must never fail service construction —
        # compilecache/persist.py documents the never-raises contract)
        except Exception:
            pass
        self.tracker = None          # JitTracker over the engine jits
        self._tracker_acquired = False
        self._recorder = None        # WarmupRecorder, when recording
        try:
            if self.config.track_compiles:
                self._ensure_tracker()
            if self.config.warmup_manifest:
                # startup hook: replay before the dispatcher takes
                # traffic
                self.warmup(self.config.warmup_manifest)
        except BaseException:
            # a failed constructor (e.g. missing manifest) must not
            # leak the process-global engine wrappers: close() is
            # unreachable for a never-constructed service
            self._release_tracker()
            raise
        if autostart:
            self.start()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._worker is not None and self._worker.is_alive():
            return
        self._stop.clear()
        self._worker = threading.Thread(
            target=self._loop, name="gmtpu-serve-dispatch", daemon=True)
        self._worker.start()

    def close(self, drain: bool = True,
              timeout_s: Optional[float] = None) -> None:
        """Stop the service. drain=True (graceful): admissions stop with
        QueryRejected(shutting_down) while every already-admitted request
        still executes; drain=False: queued requests are rejected."""
        with self._state_lock:
            self._closed = True
        if not drain:
            for r in self.queue.drain_all():
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(
                        QueryRejected("shutting_down", "service closed"))
        deadline = time.monotonic() + (
            timeout_s if timeout_s is not None
            else self.config.drain_timeout_s)
        while time.monotonic() < deadline:
            with self._state_lock:
                idle = self._inflight == 0
            if idle and len(self.queue) == 0:
                break
            time.sleep(0.005)
        self._stop.set()
        if self._worker is not None:
            self._worker.join(timeout=5.0)
        if self.pipeline is not None:
            # windows already launched still sync (no torn responses);
            # runs after the dispatch thread stopped submitting
            self.pipeline.close()
        with self._state_lock:
            mux = self._push_mux
        if mux is not None:
            mux.close()  # joins the per-sink writer threads
        # restore the bare engine jits (owner only); the tracker object
        # (and its counters) stays readable after close
        self._release_tracker()

    # -- warmup / compile management ---------------------------------------

    def _ensure_tracker(self):
        """Attach the process-wide engine JitTracker to this service
        (the engine jits are module globals — services share ONE tracker
        via refcounted acquisition, and the wrappers come off when the
        LAST service releases; see
        analysis.runtime.acquire_engine_tracker). Counting recompiles is
        also what makes ServeEvent compile-stall attribution see kernel
        compiles."""
        if self.tracker is None:
            from geomesa_tpu.analysis.runtime import acquire_engine_tracker

            self.tracker, _ = acquire_engine_tracker(
                recorder=self._recorder)
            self._tracker_acquired = True
        return self.tracker

    def _release_tracker(self) -> None:
        if self._tracker_acquired and self.tracker is not None:
            from geomesa_tpu.analysis.runtime import release_engine_tracker

            release_engine_tracker(self.tracker)
            self._tracker_acquired = False

    def record_warmup(self):
        """Start recording a warmup manifest from live traffic: every
        compiling kernel signature (via JitTracker) and every dispatched
        query shape lands in the returned WarmupRecorder. Call
        `.manifest().save(path)` on it when the workload is
        representative."""
        from geomesa_tpu.compilecache.manifest import WarmupRecorder

        self._recorder = WarmupRecorder()
        tracker = self._ensure_tracker()
        tracker.recorder = self._recorder
        return self._recorder

    def warmup(self, manifest, check: bool = False):
        """Replay a warmup manifest (path, or a WarmupManifest) through
        the compilecache so every kernel/filter this service will need is
        compiled — and persisted — before traffic. With `check=True` the
        replay is followed by a second pass that must compile NOTHING
        (`report.residual_recompiles == 0`), the programmatic equivalent
        of `gmtpu warmup --check`. Returns the WarmupReport."""
        from geomesa_tpu.compilecache import warmup as _warmup
        from geomesa_tpu.compilecache.manifest import WarmupManifest
        from geomesa_tpu.utils.metrics import metrics

        if isinstance(manifest, str):
            manifest = WarmupManifest.load(manifest)
        self._ensure_tracker()
        t0 = time.monotonic()
        run = _warmup.check if check else _warmup.replay
        report = run(manifest, store=self.store)
        metrics.gauge("serve.warmup.seconds", time.monotonic() - t0)
        metrics.gauge("serve.warmup.ok", 1.0 if report.ok else 0.0)
        self._bump("warmups")
        return report

    # -- submission API ----------------------------------------------------

    def submit(self, req: ServeRequest) -> Future:
        """Admission control, then enqueue. Raises the typed
        QueryRejected (never queues unboundedly) on shed/limit/closed.
        With tracing on, opens the request's Trace (root span "query")
        and the "admit" child span; a rejected request finishes its
        trace here and still lands in the flight recorder — overload
        postmortems need the shed requests, not just the served ones."""
        trace = TRACER.start_trace(
            "query", kind=req.kind, type=req.query.type_name,
            tenant=req.tenant)
        if trace is None:
            try:
                self._admit(req)
                hit, value = self._cache_peek(req)
                if hit:
                    return self._resolve_cached(req, value)
                value = self._approx_peek(req)
                if value is not None:
                    return self._resolve_approx(req, value)
                return self._enqueue(req)
            except QueryRejected:
                self._observe_slo(req, "rejected", 0.0)
                raise
        req.trace = trace
        try:
            # the admit span must CLOSE before the request becomes
            # visible to the dispatcher (queue.put): the span's append
            # happens at __exit__, and a dispatcher racing ahead of it
            # could snapshot/finish the trace admit-less (or leak the
            # admit span into riders' adopted window slice)
            with TRACER.scope(trace):
                with TRACER.span("admit"):
                    self._admit(req)
            hit, value = self._cache_peek(req)
            if hit:
                return self._resolve_cached(req, value)
            with TRACER.scope(trace):
                value = self._approx_peek(req)
            if value is not None:
                return self._resolve_approx(req, value)
            return self._enqueue(req)
        except BaseException as e:
            if isinstance(e, QueryRejected):
                self._observe_slo(req, "rejected", 0.0)
            trace.finish(status="rejected", error=type(e).__name__)
            RECORDER.record(trace)
            raise

    def _admit(self, req: ServeRequest) -> None:
        """Admission checks up to — but excluding — the queue put."""
        self._bump("submitted")
        with self._state_lock:
            closed = self._closed
        if closed:
            self._bump("rejected")
            raise QueryRejected("shutting_down", "service closed")
        if self.config.quarantine_after and not self.quarantine.empty():
            detail = self.quarantine.blocked(_quarantine_key(req))
            if detail is not None:
                self._bump("rejected")
                self._bump("quarantined")
                raise QueryRejected("quarantined", detail)
        try:
            self.limiter.admit(req.tenant)
        except QueryRejected:
            self._bump("rejected")
            raise
        if req.deadline is None and self.config.default_timeout_ms:
            req.deadline = (time.monotonic()
                            + self.config.default_timeout_ms / 1000.0)
        level = self.degrade_level()
        if level >= 2 and req.priority >= PRIORITIES.index("batch"):
            self._bump("rejected")
            self._bump("shed")
            raise QueryRejected(
                "shed", "sustained overload: batch class shed")
        if level >= 1 and self.config.degrade and req.allow_degraded:
            self._degrade(req, level)
        # approximate-answer governor (docs/SERVING.md "Approximate
        # answers"): a spent exactness budget STRIPS the tolerance hint
        # — the request pays the exact path; approximation is a
        # budgeted contract, never silent degradation. Config-disabled
        # approx strips too but counts separately — "budget_exact"
        # must mean the GOVERNOR acted, or a disabled service reads as
        # perpetual budget exhaustion on dashboards.
        if req.query.hints.tolerance is not None and not self._approx_ok():
            req.query = dataclasses.replace(
                req.query, hints=dataclasses.replace(
                    req.query.hints, tolerance=None))
            if not self.config.approx:
                self._bump("approx_disabled")
            else:
                self._bump("approx_budget_exact")
                from geomesa_tpu.utils.metrics import metrics

                metrics.counter("approx.budget_exact")
        if req.kind in ("count", "execute") and self.result_cache is not None:
            # the batcher populates the cache with the version the
            # planner's plan actually pinned (exact-by-construction)
            req.cache = self.result_cache
        if self.mesh is not None:
            # shard-affinity admission (docs/SERVING.md "Sharded
            # serving"): tag the query with the chips owning its tiles
            # — metadata-only; the planner's dispatch seam recomputes
            # the authoritative value and routes single-owner windows
            # to their chip
            from geomesa_tpu.serve.scheduler import shard_affinity
            from geomesa_tpu.utils.metrics import metrics

            try:
                source = self.store.get_feature_source(
                    req.query.type_name)
            except Exception:
                return  # the dispatch path raises the typed error
            shards = shard_affinity(source, req)
            if shards:
                req.shards = ",".join(map(str, shards))
                metrics.counter("serve.affinity.admitted",
                                shards=req.shards)

    def _enqueue(self, req: ServeRequest) -> Future:
        try:
            self.queue.put(req)
        except QueryRejected:
            self._bump("rejected")
            raise
        from geomesa_tpu.utils.metrics import metrics

        metrics.gauge("serve.queue.depth", float(len(self.queue)))
        return req.future

    def query(self, type_name: str, cql: str = "INCLUDE",
              hints=None, **kw) -> Future:
        q = Query(type_name, cql, hints=hints) if hints is not None \
            else Query(type_name, cql)
        return self.submit(self._request("execute", q, **kw))

    def count(self, type_name: str, cql: str = "INCLUDE", **kw) -> Future:
        return self.submit(self._request("count", Query(type_name, cql), **kw))

    def knn(self, type_name: str, cql: str, qx, qy, k: int = 10,
            impl: str = "sparse", **kw) -> Future:
        req = self._request("knn", Query(type_name, cql), **kw)
        req.qx, req.qy, req.k, req.impl = qx, qy, k, impl
        return self.submit(req)

    def _request(self, kind: str, query: Query, tenant: str = "",
                 priority: "int | str" = "normal",
                 timeout_ms: Optional[int] = None,
                 allow_degraded: bool = False) -> ServeRequest:
        if isinstance(priority, str):
            priority = PRIORITIES.index(priority)
        deadline = (time.monotonic() + timeout_ms / 1000.0
                    if timeout_ms else None)
        return ServeRequest(kind=kind, query=query, tenant=tenant,
                            priority=priority, deadline=deadline,
                            allow_degraded=allow_degraded)

    # -- approximate tier + result cache -----------------------------------

    def _approx_ok(self) -> bool:
        """Sketch serving allowed right now? Config master switch AND
        the SLO exactness budget (spent budget routes exact)."""
        if not self.config.approx:
            return False
        if self.slo is None:
            return True
        return not self.slo.exactness_spent()

    def _sketch_rung_ok(self, req: ServeRequest) -> bool:
        """Can the sketch tier plausibly answer this request? The
        ladder's rung choice: an ELIGIBLE filter takes the sketch rung
        (typed bound), an ineligible one keeps the legacy loose-bbox/
        sampling rewrite — the ladder must not lose its shedding lever
        on filters the sketches cannot see. Memoized filter parse, no
        sketch builds, no I/O."""
        try:
            source = self.store.get_feature_source(req.query.type_name)
            eng = source.planner.approx_engine()
            if eng.store is None:
                return False
            eligible = eng._parse_filter(req.query)[0]
            return bool(eligible)
        # gt: waive GT14
        # (deliberate degrade: rung SELECTION is best-effort — any
        # failure here falls back to the legacy degrade rewrite)
        except Exception:
            return False

    def _cache_key(self, req: ServeRequest):
        """The request's result-cache key at the CURRENT committed
        manifest version, or None when uncacheable (knn, tolerant,
        unversioned storage). Recomputed fresh at every peek — a key
        minted before a concurrent write must never serve the old
        version's entry after the write committed."""
        if self.result_cache is None or req.kind == "knn":
            return None
        try:
            source = self.store.get_feature_source(req.query.type_name)
        except Exception:
            return None  # the dispatch path raises the typed error
        storage = getattr(source, "storage", None)
        mv = getattr(storage, "manifest_version", None)
        if not callable(mv):
            return None
        from geomesa_tpu.approx.cache import result_key

        return result_key(req.kind, req.query, mv())

    def _cache_peek(self, req: ServeRequest, count_miss: bool = True):
        """(hit, value) against the version-exact result cache."""
        if self.result_cache is None or req.kind == "knn":
            return False, None
        return self.result_cache.get(self._cache_key(req),
                                     count_miss=count_miss)

    def _approx_peek(self, req: ServeRequest):
        """Admission-time sketch resolution (docs/SERVING.md
        "Approximate answers"): a tolerant COUNT answers on the submit
        thread in microseconds — it never queues, never coalesces, and
        never waits behind an exact device scan. Returns the
        ApproxCount or None (every fallthrough pays the normal queued
        path, where the planner retries the sketch tier with full plan
        context)."""
        if req.kind != "count" or req.query.hints.tolerance is None:
            return None
        try:
            source = self.store.get_feature_source(req.query.type_name)
            planner = getattr(source, "planner", None)
            fn = getattr(planner, "approx_count_result", None)
            if fn is None:
                return None
            qr = fn(req.query)
        # gt: waive GT14
        # (deliberate degrade: the admission peek is an optimization —
        # any failure here falls through to the queued dispatch path,
        # which surfaces the typed error to the right future)
        except Exception:
            return None
        if qr is None:
            return None
        from geomesa_tpu.approx.engine import ApproxCount

        return ApproxCount(int(qr.count), int(qr.bound), qr.confidence)

    def _resolve_approx(self, req: ServeRequest, value) -> Future:
        """Resolve a sketch-served request at admission: full tier
        bookkeeping (metrics, SLO exactness spend, trace, audit), no
        queue, no dispatch."""
        from geomesa_tpu.utils.metrics import metrics

        req.approx = True
        if req.sketch_rung:
            # the ladder's speculative rung actually served: NOW the
            # request is a degraded answer (typed bound) and the
            # exactness budget spend is honest
            req.degraded = True
            self._bump("degraded")
            metrics.counter("serve.degraded")
        self._bump("approx_served")
        self._bump("completed")
        metrics.counter("serve.requests", kind=req.kind, status="ok")
        metrics.counter("serve.tier", tier="sketch")
        metrics.histogram("serve.latency").update(0.0)
        self._observe_slo(req, "ok", 0.0)
        if req.future.set_running_or_notify_cancel():
            req.future.set_result(value)
        if req.trace is not None:
            RECORDER.record(req.trace.finish(status="ok", approx=True))
        if self.audit is not None:
            self.audit.write(ServeEvent(
                trace_id=(req.trace.trace_id
                          if req.trace is not None else ""),
                type_name=req.query.type_name,
                kind=req.kind,
                tenant=req.tenant,
                priority=PRIORITIES[req.priority],
                queue_ms=0.0,
                exec_ms=0.0,
                batch_size=1,
                status="ok",
                degraded=req.degraded,
                approx=True,
            ))
        return req.future

    def _resolve_cached(self, req: ServeRequest, value,
                        queue_ms: float = 0.0) -> Future:
        """Resolve a request straight from the result cache: no queue,
        no coalescing window, no dispatch — full bookkeeping (metrics,
        SLO, trace, audit) still applies so tier shares stay honest."""
        from geomesa_tpu.utils.metrics import metrics

        req.cache_hit = True
        self._bump("cache_hits")
        self._bump("completed")
        metrics.counter("serve.requests", kind=req.kind, status="ok")
        metrics.counter("serve.tier", tier="cached")
        latency_s = queue_ms / 1000.0
        metrics.histogram("serve.latency").update(latency_s)
        self._observe_slo(req, "ok", latency_s)
        if req.future.set_running_or_notify_cancel():
            req.future.set_result(value)
        if req.trace is not None:
            RECORDER.record(req.trace.finish(status="ok", cache_hit=True))
        if self.audit is not None:
            self.audit.write(ServeEvent(
                trace_id=(req.trace.trace_id
                          if req.trace is not None else ""),
                type_name=req.query.type_name,
                kind=req.kind,
                tenant=req.tenant,
                priority=PRIORITIES[req.priority],
                queue_ms=queue_ms,
                exec_ms=0.0,
                batch_size=1,
                status="ok",
                degraded=req.degraded,
                cache_hit=True,
            ))
        return req.future

    # -- columnar wire -----------------------------------------------------

    def wire_mux(self):
        """The service-wide push fan-out (serve/columnar.py PushMux):
        one per service, lazily built — frames encode once and fan to
        every connection sink attached to their subscription."""
        with self._state_lock:
            if self._push_mux is None:
                from geomesa_tpu.serve.columnar import PushMux

                self._push_mux = PushMux(
                    queue_limit=self.config.subscribe_outbox)
            return self._push_mux

    # -- degradation ladder ------------------------------------------------

    def degrade_level(self) -> int:
        """0 = nominal; 1 = hint downgrades; 2 = + shed batch class.
        Two inputs, max wins: queue occupancy (a pure function, so the
        ladder releases the moment the backlog drains) and — when an
        SLO engine is attached — the burn-rate boost
        (docs/OBSERVABILITY.md "SLOs": a degrade-marked objective
        breaching the multi-window burn threshold engages the ladder
        even with an empty queue, because budget exhaustion means the
        served latency itself is the overload signal; the boost
        releases as the breach ages out of the fast window)."""
        if not self.config.degrade:
            return 0
        occ = len(self.queue) / self.config.max_queue
        level = 0
        if occ >= self.config.shed_watermark:
            level = 2
        elif occ >= self.config.degrade_watermark:
            level = 1
        if self.slo is not None and level < 2:
            level = max(level, self.slo.degrade_boost())
        return level

    def _degrade(self, req: ServeRequest, level: int) -> None:
        """Rewrite hints toward cheaper execution. The FIRST rung —
        before loose-bbox — is the sketch tier (docs/SERVING.md
        "Approximate answers"): an eligible count/density gets a
        tolerance hint and serves from sketches WITH a typed bound,
        which beats silently dropping the exact residual check; the
        planner's fallthrough keeps it safe when the bound does not
        fit. Aggregations with shapes a rewrite would corrupt
        (stats/bin/arrow) never degrade."""
        h = req.query.hints
        if h.is_stats or h.is_bin or h.is_arrow:
            return
        sketchable = (req.kind == "count"
                      or (req.kind == "execute" and h.is_density
                          and h.density_weight is None))
        if (sketchable and h.tolerance is None and self._approx_ok()
                and self._sketch_rung_ok(req)):
            # the rung is SPECULATIVE: it injects the tolerance hint
            # and records the level, but degraded/budget accounting
            # happens only where a sketch answer is actually served
            # (_resolve_approx / _finish_window) — a bound that does
            # not fit must not flag an EXACT answer degraded or spend
            # the exactness budget it never used
            if self.config.quarantine_after and req.quarantine_key is None:
                req.quarantine_key = _quarantine_key(req)
            req.query = dataclasses.replace(
                req.query, hints=dataclasses.replace(
                    h, tolerance=self.config.approx_degrade_tolerance))
            req.sketch_rung = level
            return
        if h.is_density:
            return  # loose-bbox/sampling would corrupt the grid
        # stash the PRE-degrade fingerprint: strikes must land on the
        # same key admission checks (see ServeRequest.quarantine_key)
        if self.config.quarantine_after and req.quarantine_key is None:
            req.quarantine_key = _quarantine_key(req)
        changes = {"loose_bbox": True}
        if level >= 2 and h.sampling is None:
            changes["sampling"] = 4
        req.query = dataclasses.replace(
            req.query, hints=dataclasses.replace(h, **changes))
        req.degraded = True
        self._bump("degraded")
        from geomesa_tpu.utils.metrics import metrics

        metrics.counter("serve.degraded")

    def _observe_slo(self, req: ServeRequest, status: str,
                     latency_s: float) -> None:
        """Feed one resolved request into the SLO engine's sliding
        windows (no-op without a spec; a tuple append with one)."""
        if self.slo is not None:
            # a sketch-served answer spends the exactness budget like a
            # ladder-degraded one: approximation is budgeted, and the
            # closed loop (exactness_spent -> tolerance stripped) is
            # what keeps it from becoming silent degradation
            self.slo.observe(req.kind, status, latency_s,
                             degraded=req.degraded or req.approx)

    # -- dispatch loop -----------------------------------------------------

    def _mark_inflight(self, _req: ServeRequest) -> None:
        # runs under the queue lock (pop's on_pop hook): removal and the
        # in-flight mark are one atomic step, so close(drain=True) can
        # never observe "queue empty, nothing in flight" while a popped
        # request is still on its way into _dispatch
        with self._state_lock:
            self._inflight += 1

    def _loop(self) -> None:
        import logging

        while True:
            req = self.queue.pop(timeout=0.05, on_pop=self._mark_inflight)
            if req is None:
                if self._stop.is_set():
                    return
                continue
            try:
                self._dispatch(req)
            except Exception as e:  # noqa: BLE001 — the dispatcher must live
                # _dispatch resolves member futures before anything that
                # can throw here (audit/metrics); log and keep serving.
                # An un-typed error escaping to here is exactly the
                # postmortem case the flight recorder exists for: dump
                # the last-N-queries window before continuing.
                logging.getLogger(__name__).exception(
                    "serve dispatch loop error")
                RECORDER.crash_dump("serve dispatch loop error", e)
            finally:
                with self._state_lock:
                    self._inflight -= 1

    def _gather(self, first: ServeRequest) -> List[ServeRequest]:
        """Coalescing window: collect queued requests compatible with
        `first` for up to max_wait_ms (bounded added latency), then go."""
        reqs = [first]
        key = compat_key(first)
        cap = self.config.max_batch
        if key is None or cap <= 1:
            return reqs
        deadline = time.monotonic() + self.config.max_wait_ms / 1000.0
        while len(reqs) < cap:
            got = self.queue.drain_compatible(
                key, compat_key, cap - len(reqs))
            reqs.extend(got)
            if len(reqs) >= cap:
                break
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            time.sleep(min(0.0005, remaining))
        return reqs

    def _run_window(self, live: List[ServeRequest]) -> None:
        """The device-facing part of one dispatch: source lookup +
        coalesced execution, futures resolved for every member."""
        try:
            # an unknown type name raises HERE, not in execute_batch's
            # guarded body — it must fail these futures, not the
            # dispatcher thread (one bad request would hang the service)
            source = self.store.get_feature_source(live[0].query.type_name)
        except BaseException as e:  # noqa: BLE001 — fan out like a dispatch
            for r in live:
                if r.future.set_running_or_notify_cancel():
                    r.future.set_exception(e)
        else:
            execute_batch(source, live)

    def _dispatch(self, first: ServeRequest) -> None:
        from geomesa_tpu.serve.batcher import fused_count_key
        from geomesa_tpu.utils.metrics import metrics

        g0_ns = time.perf_counter_ns()
        reqs = self._gather(first)
        g1_ns = time.perf_counter_ns()
        live, dead = split_expired(reqs)
        lead = live[0] if live else None
        pipelined = (self.pipeline is not None and lead is not None
                     and lead.kind == "knn")
        counts: List[ServeRequest] = []
        if pipelined:
            # cross-kind fusion: COUNT requests against the same
            # (type, CQL, hints) resolve from this window's filter-mask
            # reduction instead of their own dispatch RTT
            fkey = fused_count_key(lead)
            if fkey is not None:
                got = self.queue.drain_compatible(
                    fkey, compat_key, self.config.max_batch)
                counts, cdead = split_expired(got)
                dead = dead + cdead
        fail_expired(dead)
        for r in dead:
            self._bump("timeout")
            metrics.counter("serve.timeout")
            self._observe_slo(r, "timeout",
                              time.monotonic() - r.enqueued_at)
            if r.trace is not None:
                r.trace.record("queue.wait", r.enqueued_ns, g1_ns)
                RECORDER.record(r.trace.finish(status="timeout"))
        if not live:
            return
        if (lead.kind in ("count", "execute")
                and self.result_cache is not None
                and lead.query.hints.tolerance is None):
            # second-chance peek: a twin that dispatched while this
            # request queued may have populated the cache — resolve
            # the whole window (members share the coalescing key, so
            # one current-version key answers them all) without any
            # device work. The batcher therefore never coalesces a
            # cache-hit. Misses are unmetered here (admission already
            # counted them).
            hit, value = self._cache_peek(lead, count_miss=False)
            if hit:
                t_hit = time.monotonic()
                for r in live:
                    self._resolve_cached(
                        r, value,
                        queue_ms=(t_hit - r.enqueued_at) * 1000.0)
                return
        t0 = time.monotonic()
        now_ns = time.perf_counter_ns()
        for r in live + counts:
            metrics.histogram("serve.queue.wait").update(t0 - r.enqueued_at)
            if r.trace is not None:
                # cross-thread phase: opened (implicitly) at enqueue on
                # the submitting thread, closed here — recorded with
                # explicit stamps rather than a with-block
                r.trace.record("queue.wait", r.enqueued_ns, now_ns)
        # everything recorded into the LEAD trace from here on is the
        # shared dispatch window; riders adopt a copy at completion
        adopt_from = (lead.trace.span_count()
                      if lead.trace is not None else 0)
        if lead.trace is not None:
            lead.trace.record("coalesce", g0_ns, g1_ns,
                              gathered=len(reqs), fused=len(counts))
        if self._recorder is not None:
            self._record_queries(live, counts)
        from geomesa_tpu.faults import RECOVERY

        if pipelined:
            # pipelined route: the source lookup error fans out HERE
            # (the serial path does it inside _run_window)
            try:
                source = self.store.get_feature_source(
                    lead.query.type_name)
            except BaseException as e:  # noqa: BLE001 — fan out typed
                for r in live + counts:
                    if r.future.set_running_or_notify_cancel():
                        r.future.set_exception(e)
                self._finish_window(live, counts, lead, t0,
                                    time.monotonic(), adopt_from,
                                    None, 0, 0, [], [], pipelined=True)
                return
            # the window stays in flight past this method: it owns one
            # inflight token until _window_complete releases it, so
            # close(drain=True) waits for the completer too
            with self._state_lock:
                self._inflight += 1
            try:
                self.pipeline.submit(source, live, counts, lead, t0,
                                     g0_ns, adopt_from)
            except BaseException as e:
                # submit resolves all futures on its internal failure
                # paths; an exception HERE means the window never got a
                # slot (completer dead) — fail whatever is still
                # pending so no client hangs, then let _loop log it
                with self._state_lock:
                    self._inflight -= 1
                for r in live + counts:
                    if not r.future.done() and \
                            r.future.set_running_or_notify_cancel():
                        r.future.set_exception(e)
                raise
            return
        stall_token = STALLS.token()
        rec_token = RECOVERY.token()
        dispatch_span_id = None
        dispatch_start_ns = 0
        dispatch_end_ns = 0
        if lead.trace is not None:
            with TRACER.scope(lead.trace):
                with TRACER.span("dispatch", batch=len(live)) as dsp:
                    self._run_window(live)
                # read the handle RIGHT after the block closes (the
                # scope's shared handle holds the just-closed span);
                # None if tracing flipped off between admit and here
                dispatch_span_id = getattr(dsp, "span_id", None)
                dispatch_start_ns = getattr(dsp, "start_ns", 0)
                dispatch_end_ns = getattr(dsp, "end_ns", 0)
        else:
            self._run_window(live)
        t1 = time.monotonic()
        # per-dispatch compile-stall attribution: everything THIS THREAD
        # noted into the stall meter during the window (tracked kernel
        # compiles + filter compiles — the dispatch's own work runs
        # synchronously on the dispatch thread) is charged to the
        # requests that rode the dispatch; scoping by thread keeps the
        # window exact even when other services/planner callers share
        # the process-wide meter
        stalls = STALLS.since(stall_token,
                              thread_ident=threading.get_ident())
        # recovery attribution, same thread-scoped window discipline as
        # the compile stalls: retries/faults noted by this dispatch
        # thread are charged to the requests that rode the dispatch
        # (boundary work on helper threads — the streaming-count decode-
        # ahead — is metered globally but not attributed per-request)
        recovery = RECOVERY.since(rec_token,
                                  thread_ident=threading.get_ident())
        self._finish_window(live, [], lead, t0, t1, adopt_from,
                            dispatch_span_id, dispatch_start_ns,
                            dispatch_end_ns, stalls, recovery)

    def _window_complete(self, win, t1: float, end_ns: int) -> None:
        """Pipeline completion callback (completer thread): shared
        finish bookkeeping, then release the window's inflight token."""
        try:
            self._finish_window(
                win.live, win.counts, win.lead, win.t0, t1,
                win.adopt_from, win.wid, win.g0_ns, end_ns,
                win.stalls, win.recovery, pipelined=True)
        finally:
            with self._state_lock:
                self._inflight -= 1

    def _finish_window(self, live, counts, lead, t0, t1, adopt_from,
                       dispatch_span_id, dispatch_start_ns,
                       dispatch_end_ns, stalls, recovery,
                       pipelined: bool = False) -> None:
        """Everything that happens after a window's futures are
        resolved: stall/recovery attribution spans, counters, metrics,
        quarantine accounting, rider trace adoption, audit events.
        Shared verbatim by the serial path (dispatch thread) and the
        pipeline (completer thread)."""
        from geomesa_tpu.faults import BREAKERS, BreakerOpen, classify
        from geomesa_tpu.utils.metrics import metrics

        retries = sum(1 for kind, _ in recovery if kind == "retry")
        faults_seen = sum(1 for kind, _ in recovery if kind == "fault")
        breaker_state = ",".join(
            f"{name}={state}"
            for name, state in sorted(BREAKERS.states().items())
            if state != "closed")
        compile_ms = sum(s for _, s in stalls) * 1000.0
        labels = list(dict.fromkeys(lbl for lbl, _ in stalls))
        compiled = ",".join(labels[:5])
        if len(labels) > 5:
            compiled += f",+{len(labels) - 5}"
        if lead.trace is not None and dispatch_span_id is not None:
            # stall/recovery attribution as child spans of the dispatch
            # window (the meters only know durations, not start times:
            # stalls render right-aligned at the window end, marked
            # synthetic; retry/fault notes render as instants)
            for label, secs in stalls:
                dur_ns = int(secs * 1e9)
                lead.trace.record(
                    "compile.stall",
                    max(dispatch_end_ns - dur_ns, dispatch_start_ns),
                    dispatch_end_ns, parent_id=dispatch_span_id,
                    label=label, synthetic_ts=True)
            for kind, label in recovery:
                lead.trace.record(
                    kind, dispatch_end_ns, dispatch_end_ns,
                    parent_id=dispatch_span_id, label=label)
        if stalls:
            self._bump("compile_stalled_dispatches")
            metrics.counter("serve.compile.stalled")
        self._bump("dispatches")
        members = len(live) + len(counts)
        self._bump("coalesced", members - 1)
        metrics.counter("serve.dispatch")
        if pipelined:
            self._bump("pipelined_windows")
            metrics.counter("serve.pipeline.windows")
        if members > 1:
            metrics.counter("serve.coalesced", members - 1)
        metrics.gauge("serve.queue.depth", float(len(self.queue)))
        struck: set = set()
        adopted: Optional[list] = None
        for r in live + counts:
            if r.future.cancelled():
                # cancelled between queue pop and execute: .exception()
                # would raise CancelledError and kill the dispatcher
                if r.trace is not None:
                    RECORDER.record(r.trace.finish(status="cancelled"))
                continue
            metrics.histogram("serve.latency").update(t1 - r.enqueued_at)
            # labeled series: per-kind/status and per-tenant request
            # counts export as proper Prometheus labels (one
            # serve_requests family), so dashboards slice without
            # name-mangled metric explosions
            status = "ok"
            exc = r.future.exception()
            if exc is not None:
                status = ("timeout" if isinstance(exc, QueryTimeout)
                          else "error")
                self._bump("failed")
                # poison-query accounting: a crash (permanent/OOM after
                # every recovery layer gave up) strikes the request's
                # coalescing fingerprint; shed/timeout/transient and
                # breaker-open rejections say nothing about the QUERY
                # being poisonous — they are load/dependency signals.
                # The OSError family is exempt even when classified
                # permanent (FileNotFoundError from a compaction race,
                # PermissionError): infrastructure answers, not kernel
                # crashes — a healthy hot query must not get itself
                # quarantined by three raced reads.
                if (self.config.quarantine_after
                        and not isinstance(exc, (QueryRejected,
                                                 QueryTimeout,
                                                 BreakerOpen,
                                                 OSError))
                        and classify(exc) != "transient"):
                    # ONE strike per crashing dispatch, not one per
                    # coalesced rider: N riders share the fingerprint
                    # by construction, and striking each would let a
                    # single crash of a >=quarantine_after batch
                    # quarantine the query immediately. Degraded
                    # requests strike their PRE-degrade fingerprint —
                    # the one admission checks.
                    key = (r.quarantine_key
                           if r.quarantine_key is not None
                           else _quarantine_key(r))
                    if key not in struck:
                        struck.add(key)
                        self.quarantine.strike(key)
            else:
                self._bump("completed")
                if r.approx:
                    self._bump("approx_served")
                    if r.sketch_rung and not r.degraded:
                        # rung request sketch-served on the DISPATCH
                        # path (cold sketch built there): degraded
                        # accounting lands with the serve, same as
                        # the admission-resolved case
                        r.degraded = True
                        self._bump("degraded")
                        metrics.counter("serve.degraded")
                metrics.counter(
                    "serve.tier",
                    tier="sketch" if r.approx else "exact")
            # SLO accounting distinguishes rejection from failure even
            # where the wire status does not: a pipelined window failed
            # by shutdown/drain fans QueryRejected out to its members
            # (status "error" on the wire), but rejections must never
            # burn the availability budget (telemetry/slo.py
            # BAD_STATUSES — shedding protects the budget)
            self._observe_slo(
                r, "rejected" if isinstance(exc, QueryRejected)
                else status, t1 - r.enqueued_at)
            metrics.counter("serve.requests", kind=r.kind, status=status)
            if r.tenant:
                metrics.counter("serve.tenant.requests", tenant=r.tenant)
                metrics.histogram(
                    "serve.tenant.latency",
                    tenant=r.tenant).update(t1 - r.enqueued_at)
            if r.trace is not None:
                if r is not lead and lead.trace is not None:
                    # riders adopt a copy of the shared dispatch-window
                    # spans (coalesce + dispatch subtree). Span ids are
                    # preserved so the gap report can dedup the shared
                    # window; the lead's own respond span stays out —
                    # riders record their own via the protocol callback
                    if adopted is None:
                        adopted = [
                            s for s in
                            lead.trace.snapshot_spans()[adopt_from:]
                            if s.name != "respond"]
                    r.trace.adopt(
                        adopted, clamp_start_ns=r.trace.root.start_ns)
                RECORDER.record(r.trace.finish(
                    status=status, batch=members, degraded=r.degraded,
                    approx=r.approx))
            if self.audit is not None:
                self.audit.write(ServeEvent(
                    trace_id=(r.trace.trace_id
                              if r.trace is not None else ""),
                    type_name=r.query.type_name,
                    kind=r.kind,
                    tenant=r.tenant,
                    priority=PRIORITIES[r.priority],
                    queue_ms=(t0 - r.enqueued_at) * 1000.0,
                    exec_ms=(t1 - t0) * 1000.0,
                    batch_size=members,
                    pipelined=pipelined,
                    status=status,
                    degraded=r.degraded,
                    compile_ms=compile_ms,
                    compiled=compiled,
                    retries=retries,
                    fault_injected=faults_seen,
                    breaker_state=breaker_state,
                    # riders share the window's route; the lead carries
                    # the authoritative launch attribution (fused
                    # counts too — they resolved from the same program)
                    mesh_shape=r.mesh_shape or lead.mesh_shape,
                    shards=r.shards or lead.shards,
                    approx=r.approx,
                    cache_hit=r.cache_hit,
                ))

    def _record_queries(self, live: List[ServeRequest],
                        counts: List[ServeRequest] = ()) -> None:
        """Record this dispatch's query shape into the warmup recorder.
        Members share a compat key, so one entry per dispatch; the kNN
        bucket is the PADDED stacked-query axis the batcher will build,
        which is what the kernel actually compiles for. Fused count
        riders record their own count entry — the warmup replay runs
        counts through the real planner, and a count that happened to
        fuse onto a kNN window live must still pre-compile its serial
        program (the fusion is opportunistic, not guaranteed)."""
        lead = live[0]
        try:
            from geomesa_tpu.cql import ast

            cql = ast.to_cql(lead.query.filter_ast)
        except Exception:
            return
        from geomesa_tpu.plan.hints import QueryHints

        # replay runs with default hints: only default-hint queries are
        # recorded faithfully. This guards ALL kinds — a degraded kNN
        # (loose_bbox/sampling rewritten by the ladder) or a hinted
        # aggregation would replay as a DIFFERENT program, pre-compiling
        # something serving never runs while the real one still compiles
        # inline
        if lead.query.hints != QueryHints():
            return
        if lead.kind == "knn":
            total = sum(len(np.asarray(r.qx).ravel()) for r in live)
            padded = max(MIN_KNN_BATCH, _next_pow2(max(total, 1)))
            self._recorder.record_query(
                "knn", lead.query.type_name, cql,
                q=padded, k=lead.k, impl=lead.impl)
        else:
            self._recorder.record_query(
                lead.kind, lead.query.type_name, cql)
        if counts:
            # fused riders share the lead's (type, CQL, hints) by
            # construction, and the fusion key pins default-compatible
            # hints — record the count shape they would run serially
            self._recorder.record_query(
                "count", lead.query.type_name, cql)

    # -- introspection -----------------------------------------------------

    def _bump(self, name: str, n: int = 1) -> None:
        with self._state_lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def stats(self) -> Dict[str, int]:
        with self._state_lock:
            out = dict(self._counters)
        out.setdefault("dispatches", 0)
        out.setdefault("coalesced", 0)
        out["queue_depth"] = len(self.queue)
        out["degrade_level"] = self.degrade_level()
        out["quarantine"] = self.quarantine.stats()
        # serving-tier shares (docs/SERVING.md "Approximate answers"):
        # sketch / cached / exact out of everything completed — the
        # numbers /debug/approx, `gmtpu top` and the fleet router's
        # stats probe read
        sketch = out.get("approx_served", 0)
        cached = out.get("cache_hits", 0)
        completed = out.get("completed", 0)
        out["approx"] = {
            "enabled": self.config.approx,
            "allowed_now": self._approx_ok(),
            "budget_exact": out.get("approx_budget_exact", 0),
            "tiers": {"sketch": sketch, "cached": cached,
                      "exact": max(completed - sketch - cached, 0)},
        }
        if self.result_cache is not None:
            out["cache"] = self.result_cache.stats()
        if self.metrics_port is not None:
            out["metrics_port"] = self.metrics_port
        subs = self.subscriptions  # racing close() may null the attr
        if subs is not None:
            out["subscriptions"] = subs.stats()
        with self._state_lock:
            mux = self._push_mux
        if mux is not None:
            out["wire"] = mux.stats()
        if self.pipeline is not None:
            out["pipeline"] = self.pipeline.stats()
        if self.tracker is not None:
            out["recompiles"] = self.tracker.total_recompiles()
        if self.mesh is not None:
            # topology for /debug/stats consumers (`gmtpu top`): the
            # launch-route counters live in the metrics snapshot; the
            # shape belongs to the service
            out["mesh"] = {
                "shape": list(int(s) for s in self.mesh.devices.shape),
                "devices": int(self.mesh.devices.size),
            }
        if self.slo is not None:
            out["slo"] = self.slo.report()
        return out

    def export_gauges(self) -> None:
        """Push point-in-time gauges (queue depth, degrade level,
        in-flight count, quarantine size, breaker states) into the
        shared metrics registry. The `--metrics-port` endpoint calls
        this before every /metrics render so a scrape sees NOW, not the
        last time a request happened to update a gauge — an idle,
        fully-drained server must scrape as idle."""
        from geomesa_tpu.utils.metrics import metrics

        metrics.gauge("serve.queue.depth", float(len(self.queue)))
        for cls, depth in self.queue.depths().items():
            metrics.gauge("serve.queue.class_depth", float(depth),
                          priority=cls)
        metrics.gauge("serve.degrade.level", float(self.degrade_level()))
        with self._state_lock:
            inflight = self._inflight
        metrics.gauge("serve.inflight", float(inflight))
        if self.slo is not None:
            self.slo.export_gauges()
        if self.pipeline is not None:
            p = self.pipeline.stats()
            metrics.gauge("serve.pipeline.inflight", float(p["inflight"]))
            metrics.gauge("serve.pipeline.max_inflight",
                          float(p["max_inflight"]))
            ring = p.get("ring")
            if ring is not None:
                metrics.gauge("serve.ring.programs",
                              float(ring["programs"]))
        if self.result_cache is not None:
            c = self.result_cache.stats()
            metrics.gauge("serve.cache.entries", float(c["entries"]))
        metrics.gauge("serve.approx.allowed",
                      1.0 if self._approx_ok() else 0.0)
        q = self.quarantine.stats()
        metrics.gauge("fault.quarantine.active", float(q["quarantined"]))
        metrics.gauge("fault.quarantine.striking", float(q["striking"]))
        try:
            from geomesa_tpu.faults import BREAKERS
            from geomesa_tpu.faults.breaker import _STATE_NUM

            for name, state in BREAKERS.states().items():
                metrics.gauge(f"fault.breaker.{name}", _STATE_NUM[state])
        # gt: waive GT14
        # (deliberate degrade: gauge freshness is best-effort — a scrape
        # must render whatever IS fresh rather than 500 because one
        # breaker-registry read raced a reconfigure)
        except Exception:
            pass


def self_check(verbose: bool = True) -> int:
    """`gmtpu serve --self-check`: an end-to-end smoke against a
    throwaway store — coalescing happens (fewer dispatches than
    requests), coalesced kNN results match serial execution, the bounded
    queue sheds with a typed QueryRejected, and latency histograms
    export. Returns 0 on pass, 1 on failure; runs in-process in a few
    seconds on CPU (used by the non-slow test suite)."""
    import tempfile

    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore

    def say(msg):
        if verbose:
            print(f"serve self-check: {msg}")

    rng = np.random.default_rng(7)
    n = 512
    sft = SimpleFeatureType.from_spec(
        "selfcheck", "name:String,score:Double,dtg:Date,*geom:Point")
    batch = FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })
    cql = "BBOX(geom, -180, -90, 180, 90)"
    failures = 0
    with tempfile.TemporaryDirectory() as tmp:
        store = DataStore(tmp, use_device_cache=True)
        src = store.create_schema(sft)
        src.write(batch)

        qpts = rng.uniform(-60, 60, (8, 2))
        serial = [src.knn(cql, qpts[i:i + 1, 0], qpts[i:i + 1, 1], k=5)
                  for i in range(8)]

        svc = QueryService(store, ServeConfig(max_wait_ms=20.0),
                           autostart=False)
        futs = [svc.knn("selfcheck", cql, qpts[i:i + 1, 0],
                        qpts[i:i + 1, 1], k=5) for i in range(8)]
        cfuts = [svc.count("selfcheck", cql) for _ in range(3)]
        svc.start()
        results = [f.result(timeout=60) for f in futs]
        counts = [f.result(timeout=60) for f in cfuts]
        svc.close(drain=True)
        st = svc.stats()
        say(f"dispatches={st['dispatches']} for 11 requests "
            f"(coalesced {st['coalesced']})")
        if st["dispatches"] >= 11:
            say("FAIL: no coalescing happened")
            failures += 1
        for i, ((d, ix, _), (sd, six, _)) in enumerate(zip(results, serial)):
            if not (np.allclose(d, sd) and np.array_equal(ix, six)):
                say(f"FAIL: coalesced kNN result {i} != serial")
                failures += 1
        if len(set(counts)) != 1 or counts[0] != n:
            say(f"FAIL: coalesced counts wrong: {counts}")
            failures += 1

        svc2 = QueryService(store, ServeConfig(max_queue=2),
                            autostart=False)
        svc2.count("selfcheck", cql)
        svc2.count("selfcheck", "BBOX(geom, 0, 0, 10, 10)")
        try:
            svc2.count("selfcheck", "BBOX(geom, -10, -10, 0, 0)")
            say("FAIL: bounded queue did not shed")
            failures += 1
        except QueryRejected as e:
            say(f"bounded queue shed with reason={e.reason!r}")
            if e.reason != "queue_full":
                failures += 1
        svc2.start()
        svc2.close(drain=True)

        from geomesa_tpu.utils.metrics import metrics

        prom = metrics.to_prometheus()
        for needle in ("serve_latency_seconds_bucket",
                       "serve_latency_seconds_p99"):
            if needle not in prom:
                say(f"FAIL: {needle} missing from Prometheus export")
                failures += 1
    say("FAIL" if failures else "OK")
    return 1 if failures else 0
