"""Columnar wire framing + push fan-out (docs/SERVING.md "Columnar wire").

The JSON-lines protocol (serve/protocol.py) serializes bulk payloads —
`execute` feature results, density/topk grids, subscribe push frames —
one Python dict + `json.dumps` at a time. That per-row host work caps
throughput long before the chips do (BENCH r03-r05: the hot path is
host-bound). This module is the binary fast path:

- **Framing.** A columnar response/request is a normal JSON header line
  whose `"frame"` object announces `nbytes` of RAW payload following
  the newline. Control flow stays line-oriented (ids, errors, admin
  verbs are untouched); only bulk bytes leave JSON. A frame's payload
  is split into named `sections` so one buffer can carry several
  columns (kNN `x`/`y` query arrays, enter/exit fid columns).
- **Negotiation.** The `hello` response advertises `wire`
  capabilities; a request opts in with `"wire": "columnar"` (or the
  connection does, via `hello`). Anything that cannot go columnar —
  no pyarrow, no binary sink, a payload kind with no columnar encoding
  — falls back to plain JSON with a typed `wireFallback` marker, so
  every existing client keeps working unchanged.
- **Codecs.** `execute` feature results ride Arrow record-batch IPC
  (schema derived once per typeName and cached); density grids are ONE
  contiguous f64 buffer (no per-cell JSON); topk cells are a [k, 8]
  f64 table; push `enter`/`exit` frames carry their fid column as one
  utf8 buffer. Decoders rebuild payloads BIT-IDENTICAL to the JSON
  path (asserted in tests/test_wire.py).
- **PushMux.** The push fan-out: each frame is encoded ONCE per wire
  mode and the same immutable buffer fans to N subscriber sinks.
  Attached sinks get a dedicated writer thread + bounded queue each,
  so one slow subscriber never stalls the flusher or its peers; the
  subscription's OWNER connection keeps today's synchronous
  bounded-outbox contract (a failed write requeues frames).

pyarrow is OPTIONAL: without it the capability list drops "columnar"
and every columnar opt-in downgrades typed to JSON — asserted in
tests, never a crash.
"""

from __future__ import annotations

import json
import threading
from collections import deque
from time import perf_counter
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

__all__ = [
    "have_pyarrow", "wire_capabilities", "frame_bytes", "split_sections",
    "encode_execute_frame", "decode_execute_payload",
    "encode_density_frame", "decode_density_payload",
    "encode_topk_frame", "decode_topk_payload",
    "encode_push", "decode_push", "knn_sections", "decode_knn_sections",
    "PushMux", "MemoryWire", "parse_stream",
]

WIRE_JSON = "json"
WIRE_COLUMNAR = "columnar"

_PA = None
_PA_CHECKED = False
_PA_LOCK = threading.Lock()


def _pyarrow():
    """The pyarrow module, or None — checked once under a lock, never
    raising. The container may lack pyarrow entirely; the wire must
    then advertise json-only and downgrade typed, not crash at import
    time."""
    global _PA, _PA_CHECKED
    with _PA_LOCK:
        if not _PA_CHECKED:
            try:
                import pyarrow as pa

                _PA = pa
            # gt: waive GT14
            # (deliberate degrade: pyarrow absence IS the signal —
            # it becomes the typed json-only capability, not an error)
            except Exception:
                _PA = None
            _PA_CHECKED = True
        return _PA


def have_pyarrow() -> bool:
    return _pyarrow() is not None


def wire_capabilities() -> List[str]:
    """What the hello handshake advertises. JSON always; columnar only
    when pyarrow can encode/decode the Arrow execute payloads."""
    return [WIRE_JSON, WIRE_COLUMNAR] if have_pyarrow() else [WIRE_JSON]


# -- framing ---------------------------------------------------------------


def frame_header_bytes(doc: dict, payload: bytes) -> bytes:
    """The JSON header line of one wire frame, its `frame.nbytes`
    stamped from the actual payload. Callers that can write two parts
    under one lock (fleet sockets) send header + payload separately
    and skip the full-payload concat copy."""
    frame = dict(doc.get("frame") or {})
    frame["nbytes"] = len(payload)
    doc = dict(doc)
    doc["frame"] = frame
    return json.dumps(doc).encode() + b"\n"


def frame_bytes(doc: dict, payload: bytes) -> bytes:
    """One wire frame: header line + raw payload as ONE buffer, for
    sinks that take a single write call (the framing cannot tear)."""
    return frame_header_bytes(doc, payload) + payload


def sections_payload(
        sections: List[Tuple[str, bytes]]) -> Tuple[list, bytes]:
    """(frame `sections` descriptor, concatenated payload)."""
    desc = [[name, len(buf)] for name, buf in sections]
    return desc, b"".join(buf for _, buf in sections)


def split_sections(frame: dict, payload: bytes) -> Dict[str, memoryview]:
    """Named zero-copy views over a sectioned payload."""
    out: Dict[str, memoryview] = {}
    view = memoryview(payload)
    off = 0
    for name, nbytes in frame.get("sections") or ():
        out[str(name)] = view[off:off + int(nbytes)]
        off += int(nbytes)
    return out


# -- execute results: Arrow record batches ---------------------------------


class SchemaCache:
    """Per-typeName Arrow schema cache: the schema is derived from the
    SFT once and reused for every response of that type (the per-call
    derivation is pure overhead on a hot execute stream). Entries hold
    a strong reference to the SFT they were derived from and hits
    require IDENTITY with the caller's SFT — a replaced schema (remove
    + recreate, even one whose new object recycles the old address)
    misses and re-derives, so a stale schema can never serve."""

    def __init__(self):
        self._lock = threading.Lock()
        # (type name, include_fid) -> (sft object, derived schema)
        self._schemas: Dict[tuple, tuple] = {}

    def get(self, sft, include_fid: bool):
        from geomesa_tpu.core.arrow_io import arrow_schema

        key = (sft.name, bool(include_fid))
        with self._lock:
            entry = self._schemas.get(key)
        if entry is not None and entry[0] is sft:
            return entry[1]
        schema = arrow_schema(sft, include_fid=include_fid)
        with self._lock:
            # bound the cache: one entry per live (type, fid'ness);
            # entries of dropped types age out by eviction
            if len(self._schemas) > 256:
                self._schemas.clear()
            self._schemas[key] = (sft, schema)
        return schema

    def stats(self) -> dict:
        with self._lock:
            return {"schemas": len(self._schemas)}


SCHEMAS = SchemaCache()


def encode_execute_frame(batch, limit: int) -> Tuple[dict, bytes]:
    """One `execute` feature result as an Arrow IPC stream payload.
    Returns (frame descriptor, payload). `batch` is a FeatureBatch (or
    None/empty — encoded as a zero-row batch so decode still learns the
    schema)."""
    pa = _pyarrow()
    import io

    from geomesa_tpu.core.arrow_io import to_arrow

    t0 = perf_counter()
    n = 0 if batch is None else min(len(batch), limit)
    if batch is not None and n < len(batch):
        batch = batch.select(np.arange(n))
    schema = None
    if batch is not None:
        schema = SCHEMAS.get(batch.sft, include_fid=batch.fids is not None)
    rb = to_arrow(batch, schema=schema) if batch is not None else None
    sink = io.BytesIO()
    if rb is not None:
        with pa.ipc.new_stream(sink, rb.schema) as writer:
            writer.write_batch(rb)
    payload = sink.getvalue()
    _note_encode("execute", n, len(payload), perf_counter() - t0)
    return {"kind": "execute", "rows": n}, payload


def decode_execute_payload(payload: bytes) -> List[dict]:
    """Payload -> the exact row dicts the JSON path would have
    carried. Delegates to `protocol._rows_json` — ONE source of truth
    for row rendering (WKT points, dict decode, epoch-millis dates,
    non-finite floats as None), so a future change to the JSON path
    cannot silently fork the two wire modes' decoded shapes."""
    pa = _pyarrow()
    import io

    if not payload:
        return []
    from geomesa_tpu.core.arrow_io import from_arrow
    from geomesa_tpu.serve.protocol import _rows_json

    rows: List[dict] = []
    reader = pa.ipc.open_stream(io.BytesIO(payload))
    for rb in reader:
        fb = from_arrow(rb)
        rows.extend(_rows_json(fb, len(fb)))
    return rows


# -- density / topk grids: single raw buffers ------------------------------


def encode_density_frame(grid: np.ndarray) -> Tuple[dict, bytes]:
    """The whole density grid as ONE contiguous little-endian f64
    buffer — the JSON path only ships shape+total; columnar clients get
    the actual cells without any per-cell serialization."""
    t0 = perf_counter()
    arr = np.ascontiguousarray(np.asarray(grid, dtype="<f8"))
    payload = arr.tobytes()
    _note_encode("density", int(arr.size), len(payload),
                 perf_counter() - t0)
    return {"kind": "density", "shape": list(arr.shape),
            "dtype": "<f8"}, payload


def decode_density_payload(frame: dict, payload: bytes) -> np.ndarray:
    shape = tuple(int(s) for s in frame["shape"])
    return np.frombuffer(payload, dtype=frame.get("dtype", "<f8")
                         ).reshape(shape)


_TOPK_FIELDS = ("row", "col", "x0", "y0", "x1", "y1", "count", "bound")


def encode_topk_frame(cells: List[dict]) -> Tuple[dict, bytes]:
    """Top-k cells as a [k, 8] f64 table (row, col, bbox x0 y0 x1 y1,
    count, bound) — one buffer instead of k JSON objects."""
    t0 = perf_counter()
    k = len(cells)
    table = np.empty((k, len(_TOPK_FIELDS)), dtype="<f8")
    for i, c in enumerate(cells):
        table[i, 0] = c["row"]
        table[i, 1] = c["col"]
        table[i, 2:6] = c["bbox"]
        table[i, 6] = c["count"]
        table[i, 7] = c["bound"]
    payload = table.tobytes()
    _note_encode("topk", k, len(payload), perf_counter() - t0)
    return {"kind": "topk_cells", "k": k}, payload


def decode_topk_payload(frame: dict, payload: bytes) -> List[dict]:
    k = int(frame["k"])
    table = np.frombuffer(payload, dtype="<f8").reshape(
        k, len(_TOPK_FIELDS))
    return [{
        "row": int(t[0]), "col": int(t[1]),
        "bbox": [float(t[2]), float(t[3]), float(t[4]), float(t[5])],
        "count": int(t[6]), "bound": int(t[7]),
    } for t in table]


# -- push frames -----------------------------------------------------------

# push frame fields that move into payload sections in columnar mode
_PUSH_COLUMN = "fids"


def encode_push(frame: dict, mode: str) -> bytes:
    """ONE encode of a push frame for one wire mode — the buffer the
    PushMux fans to every sink of that mode. JSON mode: the frame as a
    JSON line (exactly what respond() used to produce per subscriber).
    Columnar mode: frames with a fid column (`enter`/`exit`/predicate
    `state`) ship it as Arrow-style offsets + one utf8 data buffer —
    length-prefixed, so a fid containing ANY byte sequence (newlines
    included: fids are user data off the ingest path) round-trips
    exactly. Everything else stays a JSON line (the scalar frames are
    already tiny)."""
    if mode == WIRE_COLUMNAR and frame.get(_PUSH_COLUMN):
        fids = frame[_PUSH_COLUMN]
        data = [f.encode() for f in fids]
        lengths = np.array([len(d) for d in data], dtype="<i4")
        offsets = np.zeros(len(data) + 1, dtype="<i4")
        np.cumsum(lengths, out=offsets[1:])
        obuf = offsets.tobytes()
        dbuf = b"".join(data)
        head = {k: v for k, v in frame.items() if k != _PUSH_COLUMN}
        head["frame"] = {"kind": "push.fids", "count": len(fids),
                         "sections": [["offsets", len(obuf)],
                                      ["fids", len(dbuf)]]}
        return frame_bytes(head, obuf + dbuf)
    return json.dumps(frame).encode() + b"\n"


def decode_push(doc: dict, payload: Optional[bytes]) -> dict:
    """Inverse of encode_push: rebuild the frame dict the JSON path
    would have delivered (bit-identical — parity-tested)."""
    frame = doc.get("frame")
    if not frame or payload is None:
        return doc
    out = {k: v for k, v in doc.items() if k != "frame"}
    if frame.get("kind") == "push.fids":
        secs = split_sections(frame, payload)
        offsets = np.frombuffer(secs["offsets"], dtype="<i4")
        data = bytes(secs["fids"])
        out[_PUSH_COLUMN] = [
            data[offsets[i]:offsets[i + 1]].decode()
            for i in range(len(offsets) - 1)]
    return out


# -- kNN query staging: request buffers as NumPy views ---------------------


def knn_sections(qx, qy) -> Tuple[list, bytes]:
    """Encode kNN query points as two f64 payload sections (client
    side). The server decodes them as zero-copy views that flow
    straight into batcher.stack_queries / the pipeline's prepare stage
    — no per-point JSON number parsing."""
    bx = np.ascontiguousarray(np.asarray(qx, dtype="<f8")).tobytes()
    by = np.ascontiguousarray(np.asarray(qy, dtype="<f8")).tobytes()
    return sections_payload([("x", bx), ("y", by)])


def decode_knn_sections(frame: dict, payload: bytes):
    """(qx, qy) as read-only f64 views over the wire buffer."""
    secs = split_sections(frame, payload)
    if "x" not in secs or "y" not in secs:
        raise ValueError("knn frame needs x and y sections")
    qx = np.frombuffer(secs["x"], dtype="<f8")
    qy = np.frombuffer(secs["y"], dtype="<f8")
    return qx, qy


# -- telemetry -------------------------------------------------------------


def _note_encode(kind: str, rows: int, nbytes: int, secs: float) -> None:
    """wire.* counters + encode-latency histograms (docs/SERVING.md
    metrics reference). Guarded: observability must never fail an
    encode that is already on the response path."""
    try:
        from geomesa_tpu.utils.metrics import metrics

        metrics.counter("wire.rows", rows, kind=kind)
        metrics.counter("wire.bytes", nbytes, kind=kind)
        metrics.histogram("wire.encode.latency", kind=kind).update(secs)
    # gt: waive GT14
    # (deliberate degrade: observability must never fail an encode
    # already on the response path)
    except Exception:
        pass


# -- push fan-out ----------------------------------------------------------


class _PushSink:
    """One subscriber endpoint. `threaded` sinks (socket connections)
    get a dedicated writer thread draining a bounded queue, so a slow
    peer backs up only its own queue; unthreaded sinks (the owner
    connection, in-process benches) write synchronously on the
    publisher's thread and keep the flush-requeue contract.

    Lock discipline: queue, counters and lifecycle flags live under
    ONE condition; the socket write itself always happens OUTSIDE it
    (a wedged peer must never hold the sink lock against the
    publisher)."""

    __slots__ = ("sink_id", "write", "mode", "limit", "threaded",
                 "_dead", "_sent", "_dropped", "_q", "_cond",
                 "_thread", "_stopping")

    def __init__(self, sink_id: str, write: Callable[[bytes], None],
                 mode: str, limit: int, threaded: bool):
        self.sink_id = sink_id
        self.write = write
        self.mode = mode
        self.limit = limit
        self.threaded = threaded
        self._dead = False
        self._stopping = False
        self._sent = 0
        self._dropped = 0
        self._q: "deque[bytes]" = deque()
        self._cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        if threaded:
            self._thread = threading.Thread(
                target=self._drain_loop, daemon=True,
                name=f"gmtpu-wire-push-{sink_id}")
            self._thread.start()

    @property
    def dead(self) -> bool:
        with self._cond:
            return self._dead

    def offer(self, buf: bytes) -> None:
        """Enqueue (threaded) or write now (unthreaded). The queue is
        BOUNDED: a sink past its limit drops the frame and counts it —
        attached sinks are best-effort mirrors; the subscription's own
        lag/resync contract lives in the owner's outbox."""
        if not self.threaded:
            # synchronous, write outside any lock: exceptions propagate
            # to the flusher, which requeues undelivered frames
            # (manager._flush_all)
            with self._cond:
                if self._dead:
                    return
            self.write(buf)
            with self._cond:
                self._sent += 1
            return
        with self._cond:
            if self._dead:
                return
            if len(self._q) >= self.limit:
                self._dropped += 1
                return
            self._q.append(buf)
            self._cond.notify()

    def _drain_loop(self) -> None:
        while True:
            with self._cond:
                while not self._q and not self._stopping:
                    # bounded wait (GT20 discipline): re-check the
                    # stop flag so close() can always join
                    self._cond.wait(timeout=0.25)
                if self._stopping and not self._q:
                    return
                buf = self._q.popleft() if self._q else None
            if buf is None:
                continue
            try:
                self.write(buf)
            # gt: waive GT14
            # (deliberate degrade: the peer vanished — the sink dies
            # typed [dead flag + reap in publish]; the subscription's
            # owner stream is unaffected)
            except Exception:
                with self._cond:
                    self._dead = True
                    self._stopping = True
                return
            with self._cond:
                self._sent += 1

    def snapshot(self) -> "tuple[int, int, bool]":
        with self._cond:
            return self._sent, self._dropped, self._dead

    def close(self) -> None:
        with self._cond:
            self._dead = True
            self._stopping = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=2.0)


class PushMux:
    """Cross-connection push fan-out: serialize each frame ONCE per
    wire mode, fan the same immutable buffer to every registered sink.

    Routing: every connection with standing queries registers one sink
    (its own outbox frames flow through it — the one-encode path holds
    even for a single JSON subscriber); `attach(sink, subscription)`
    mirrors one subscription's frames to additional connections, which
    is the >10^3-subscriber story: ONE registered predicate, ONE
    evaluation, ONE encode, N sockets (docs/SERVING.md "Columnar
    wire")."""

    def __init__(self, queue_limit: int = 1024):
        self.queue_limit = queue_limit
        self._lock = threading.Lock()
        self._sinks: Dict[str, _PushSink] = {}
        self._attached: Dict[str, set] = {}   # subscription -> sink ids
        self._ids = 0
        self.encodes = 0
        self.frames = 0
        self.fanout = 0

    # -- membership --------------------------------------------------------

    def register(self, write: Callable[[bytes], None],
                 mode: str = WIRE_JSON, threaded: bool = True,
                 sink_id: Optional[str] = None) -> str:
        with self._lock:
            if sink_id is None:
                self._ids += 1
                sink_id = f"sink-{self._ids}"
            sink = _PushSink(sink_id, write, mode, self.queue_limit,
                             threaded)
            self._sinks[sink_id] = sink
        return sink_id

    def unregister(self, sink_id: str) -> None:
        with self._lock:
            sink = self._sinks.pop(sink_id, None)
            for ids in self._attached.values():
                ids.discard(sink_id)
        if sink is not None:
            sink.close()

    def attach(self, sink_id: str, subscription_id: str) -> int:
        """Mirror `subscription_id`'s frames onto `sink_id`. Returns
        the subscription's sink count (owner excluded)."""
        with self._lock:
            if sink_id not in self._sinks:
                raise KeyError(f"unknown sink {sink_id!r}")
            ids = self._attached.setdefault(subscription_id, set())
            ids.add(sink_id)
            return len(ids)

    def detach(self, sink_id: str, subscription_id: str) -> None:
        with self._lock:
            ids = self._attached.get(subscription_id)
            if ids is not None:
                ids.discard(sink_id)

    # -- publishing --------------------------------------------------------

    def route(self, frame: dict, owner: Optional[str] = None) -> int:
        """Fan one frame to its owner sink + every sink attached to its
        subscription. Returns deliveries offered."""
        targets = set()
        if owner is not None:
            targets.add(owner)
        sub = frame.get("subscription")
        if sub is not None:
            with self._lock:
                targets |= self._attached.get(sub, set())
        return self.publish(frame, sorted(targets))

    def publish(self, frame: dict, sink_ids) -> int:
        """Encode once per wire mode present among `sink_ids`, offer
        the shared buffer to each sink. A synchronous (owner) sink's
        write error propagates so the flusher can requeue; threaded
        sinks fail independently and are reaped."""
        with self._lock:
            sinks = [self._sinks[s] for s in sink_ids
                     if s in self._sinks]
        # reap sinks whose writer thread died (peer vanished) so the
        # table does not accumulate corpses across publishes
        for s in [s for s in sinks if s.dead]:
            self.unregister(s.sink_id)
        sinks = [s for s in sinks if not s.dead]
        if not sinks:
            return 0
        bufs: Dict[str, bytes] = {}
        # encode-before-fan: every mode's buffer exists before any sink
        # write, so a raising owner write cannot skew the encode count
        for sink in sinks:
            if sink.mode not in bufs:
                bufs[sink.mode] = encode_push(frame, sink.mode)
        with self._lock:
            self.frames += 1
            self.encodes += len(bufs)
        try:
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("wire.push.encodes", len(bufs))
        # gt: waive GT14
        # (deliberate degrade: metrics are best-effort — a failed
        # counter must not drop a push frame)
        except Exception:
            pass
        n = 0
        # threaded mirrors first: the owner's synchronous write may
        # raise (that is its flush-requeue contract) and must not
        # starve the mirrors of a frame that was already encoded
        for sink in sorted(sinks, key=lambda s: not s.threaded):
            sink.offer(bufs[sink.mode])
            n += 1
        with self._lock:
            self.fanout += n
        return n

    # -- introspection / lifecycle -----------------------------------------

    def stats(self) -> dict:
        with self._lock:
            sinks = list(self._sinks.values())
            attached = {k: len(v) for k, v in self._attached.items() if v}
            frames, encodes, fanout = self.frames, self.encodes, self.fanout
        snaps = [s.snapshot() for s in sinks]
        return {
            "sinks": len(sinks),
            "attached": attached,
            "frames": frames,
            "encodes": encodes,
            "fanout": fanout,
            "sent": sum(sent for sent, _, _ in snaps),
            "dropped": sum(d for _, d, _ in snaps),
            "dead": sum(1 for _, _, dead in snaps if dead),
        }

    def close(self) -> None:
        with self._lock:
            sinks = list(self._sinks.values())
            self._sinks.clear()
            self._attached.clear()
        for s in sinks:
            s.close()


# -- in-memory wire helpers (tests, smokes, benches) -----------------------


class MemoryWire:
    """A pre-encoded request byte stream read the way the socket layer
    reads it: header lines via `lines()`, frame payloads via
    `read_exact` — the in-process stand-in for JsonLineConn that the
    wire smoke and the parity tests drive serve_connection with."""

    def __init__(self, data: bytes = b""):
        self.data = bytearray(data)
        self.pos = 0

    def add(self, doc: dict, payload: Optional[bytes] = None) -> None:
        if payload is None:
            # gt: waive GT12
            # (reader-confined by contract: a MemoryWire belongs to
            # exactly one driving thread — it is the in-process
            # stand-in for JsonLineConn's single-reader buffer)
            self.data += json.dumps(doc).encode() + b"\n"
        else:
            # gt: waive GT12
            # (reader-confined, see above)
            self.data += frame_bytes(doc, payload)

    def lines(self):
        while True:
            nl = self.data.find(b"\n", self.pos)
            if nl < 0:
                return
            line = self.data[self.pos:nl]
            # gt: waive GT12
            # (reader-confined, see add())
            self.pos = nl + 1
            yield line.decode()

    def read_exact(self, n: int) -> bytes:
        out = bytes(self.data[self.pos:self.pos + n])
        if len(out) < n:
            raise OSError("stream ended mid-frame")
        # gt: waive GT12
        # (reader-confined, see add())
        self.pos += n
        return out


def parse_stream(data: bytes) -> List[Tuple[dict, Optional[bytes]]]:
    """Parse a response byte stream into (doc, payload) pairs — the
    client-side decode loop, shared by tests/smokes/benches."""
    out: List[Tuple[dict, Optional[bytes]]] = []
    pos = 0
    n = len(data)
    while pos < n:
        nl = data.find(b"\n", pos)
        if nl < 0:
            break
        line = data[pos:nl].strip()
        pos = nl + 1
        if not line:
            continue
        doc = json.loads(line)
        payload = None
        frame = doc.get("frame")
        if frame and frame.get("nbytes"):
            nb = int(frame["nbytes"])
            payload = bytes(data[pos:pos + nb])
            if len(payload) < nb:
                raise ValueError("response stream ended mid-frame")
            pos += nb
        out.append((doc, payload))
    return out
