"""Persistent on-device serve loop: ring-fed, donated-buffer dispatch.

BENCH r03–r05 proved the serve hot path is dispatch-bound, not
FLOP-bound: per-window dispatch RTT (0.101 s) exceeds kernel time
(0.066 s), capping sustained throughput at ~513–523M pts/s against the
≥700M ROADMAP target. The PR-7 pipeline overlaps the per-window host
work with the previous window's kernel, but every window still PAYS
that host work — plan, residency ensure, filter mask, kernel binding —
before its dispatch. This module amortizes all of it to a one-time
setup cost.

One **ring program** per (type, canonical CQL, hints, k, impl,
Q-bucket[, mesh_shape]) window class (planner.ring_arm): the plan, the
resident superbatch, the f64-exact filter mask, the calibrated sparse
capacity, the fused-count scalar and the AOT executable are all frozen
at arm time under the ExecutableRegistry's ring tier
(`<kernel>@ring{depth}[+donate]` — depth and the donation contract key
the entry). Query inputs live in a fixed ring of `depth` staging slots
(engine.device.QueryStager generalized to depth R); with donation on
(non-CPU backends) each slot's buffer is consumed by its window's
program and XLA reuses that HBM across the rotation, so the device
drains slot after slot without a host round trip between windows. On
CPU CI the structural form is the same slot-reuse executable: per
window the Python work is ONLY

    slot write     QueryStager.stage into the next ring slot
    dispatch       ONE pre-compiled executable invocation
    harvest        the completer thread's combined sync read

— no plan, no residency walk, no mask recompute, no tracing, no new
executions compiled (zero recompiles asserted via JitTracker in
tests/test_ringloop.py). `dispatches_per_window` (bench-serve
`--mode sustained --ring`, sentinel family `ring.dispatch.*`) meters
exactly this: the ring route is strictly below the pipelined baseline
on CPU CI, and on real TPU it is the structure BENCH r06 needs to hit
sustained ≥700M pts/s.

Correctness contract:

- **bit-identity** on every route: the ring runs the same kernels over
  the same frozen mask with the same staged f64→f32 cast, and sync is
  the serial route's sync — same overflow ladder, same
  `_canonical_dists` f64 host recompute (asserted ring-vs-serial-vs-
  pipelined in tests/test_ringloop.py).
- **typed fallback**: anything the frozen contract cannot hold —
  interceptors, un-versioned storage, no resident superbatch,
  shard-affinity mesh windows, a stale version — raises/returns typed
  and the window takes the PR-7 pipelined route unchanged (the OOM
  ladder re-stages from host copies exactly as today: a feed failure
  fans out through the pipeline's `_fail`, whose `_oom_fallback` holds
  the host query copies).
- **staleness**: `RingProgram.fresh()` per window is a lock-peek
  (superbatch identity) plus an int compare (storage commit version);
  a write sends the next window down the pipelined route, whose
  plan/ensure rebuilds residency, and the ring re-arms against the new
  version.

GT23 (docs/ANALYSIS.md) lint-enforces the feed discipline: no blocking
host sync (`block_until_ready` / future `.result()` / `device_get`)
and no naked per-window `device_put` inside the feed/slot scope of
this module — the slot write goes through the stager's designated
path, and blocking belongs to the completer's harvest.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from geomesa_tpu.telemetry.trace import TRACER

__all__ = ["RingLoop"]


class RingLoop:
    """The ring-program table + feed seam behind DispatchPipeline.

    Owned by one pipeline; `try_feed` runs on the service's dispatch
    thread (the pipeline calls it in place of transfer+launch), the
    harvest stays on the pipeline's completer thread. Armed programs
    are bounded (`MAX_PROGRAMS`, least-recently-fed eviction) and
    ineligibility is negative-cached per key until the storage version
    moves, so a permanently ineligible window class costs one dict
    probe per window, not one failed arm."""

    MAX_PROGRAMS = 32

    def __init__(self, service, depth: int = 4,
                 donate: Optional[bool] = None):
        from geomesa_tpu.engine.device import QueryStager

        self.service = service
        self.depth = max(2, int(depth))
        self._donate = donate
        # ring slots: a QueryStager at depth R — the slot handed to
        # window N re-offers only after R windows, which the pipeline's
        # depth bound keeps safely past N's sync
        self._stager = QueryStager(depth=self.depth)
        self._lock = threading.Lock()
        self._programs: Dict[tuple, object] = {}   # key -> RingProgram
        self._refused: Dict[tuple, tuple] = {}     # key -> (mv, reason)
        self._windows = 0
        self._armed = 0
        self._fallbacks: Dict[str, int] = {}

    @property
    def donate(self) -> bool:
        if self._donate is None:
            import jax

            # donation is unimplemented on CPU (JAX warns and ignores);
            # resolved lazily like the pipeline's flag
            self._donate = jax.default_backend() != "cpu"
        return self._donate

    # -- feed seam (dispatch thread) ---------------------------------------

    def try_feed(self, win) -> bool:
        """Dispatch one prepared window over its ring program. Returns
        True with `win.launch` armed (the completer harvests it exactly
        like a pipelined launch), or False — the caller runs the
        pipelined transfer+launch path. Raises only what a pipelined
        launch could raise (fault-injected slot transfers included):
        the caller's failure ladder applies unchanged."""
        from geomesa_tpu.serve.batcher import ring_key

        lead = win.lead
        key = ring_key(lead, len(win.qx))
        if key is None:
            return False
        prog = self._current_program(key, win)
        if prog is None:
            return False
        from geomesa_tpu.serve.batcher import (
            batch_timeout_ms, note_launch_route)

        timeout_ms = batch_timeout_ms(win.running + win.running_counts)
        with TRACER.scope(lead.trace, parent_id=win.wid):
            with TRACER.span("ring.slot", q=int(len(win.qx)),
                             depth=self.depth):
                win.staged = self._stager.stage(
                    key, win.qx, win.qy, device=prog.placement)
            win.launch = prog.launch(
                win.staged, win.qx, win.qy, timeout_ms=timeout_ms,
                want_mask_count=bool(win.running_counts))
        note_launch_route(win.running + win.running_counts, win.launch)
        with self._lock:
            self._windows += 1
        return True

    def _current_program(self, key, win):
        """The fresh armed program for `key`, arming on first use —
        or None (typed fallback to the pipeline), with the reason
        metered and negative-cached against the current storage
        version."""
        with self._lock:
            prog = self._programs.pop(key, None)
            if prog is not None:
                self._programs[key] = prog  # re-insert = LRU touch
        if prog is not None:
            if prog.fresh():
                return prog
            # a version move stales EVERY armed program against that
            # storage generation — sweep them all now so idle keys do
            # not pin the previous superbatch's device arrays until LRU
            # eviction happens to reach them
            with self._lock:
                for k in [k for k, p in self._programs.items()
                          if not p.fresh()]:
                    del self._programs[k]
            self._note_fallback("stale")
            # deliberately NOT re-armed inline: the pipelined window
            # this falls back to runs plan/ensure, rebuilding residency
            # so the NEXT window's arm binds the new superbatch
            return None
        return self._arm(key, win)

    def _arm(self, key, win):
        """One-time arm for a window class (the ring's setup cost —
        comparable to a single pipelined window's plan+mask work plus
        one AOT compile, amortized over every window that follows)."""
        from geomesa_tpu.plan.planner import RingIneligible

        lead = win.lead
        planner = win.source.planner
        if not hasattr(planner, "ring_arm"):
            return None
        mv_fn = getattr(planner.storage, "manifest_version", None)
        mv = None
        if mv_fn is not None:
            try:
                mv = int(mv_fn())
            except Exception:
                mv = None
        with self._lock:
            refused = self._refused.get(key)
        if refused is not None and refused[0] == mv:
            # same meter as a fresh refusal: stats AND the exported
            # counter must agree on every fallback, cached or not
            self._note_fallback(refused[1])
            return None
        try:
            prog = planner.ring_arm(
                lead.query, q_padded=len(win.qx), k=lead.k,
                impl=lead.impl, donate=self.donate, depth=self.depth)
        except RingIneligible as e:
            with self._lock:
                self._refused[key] = (mv, e.reason)
                while len(self._refused) > 4 * self.MAX_PROGRAMS:
                    self._refused.pop(next(iter(self._refused)))
            self._note_fallback(e.reason)
            return None
        with self._lock:
            self._refused.pop(key, None)
            self._programs[key] = prog
            self._armed += 1
            while len(self._programs) > self.MAX_PROGRAMS:
                # least-recently-fed program goes first; its device
                # refs free once in-flight windows sync
                self._programs.pop(next(iter(self._programs)))
        return prog

    def _note_fallback(self, reason: str) -> None:
        from geomesa_tpu.utils.metrics import metrics

        with self._lock:
            self._fallbacks[reason] = self._fallbacks.get(reason, 0) + 1
        metrics.counter("serve.ring.fallbacks")

    # -- lifecycle / introspection -----------------------------------------

    def close(self) -> None:
        """Drop every armed program (their device refs free once
        in-flight windows sync — harvesting is the completer's job and
        each window syncs exactly once there)."""
        with self._lock:
            self._programs.clear()
            self._refused.clear()

    def stats(self) -> dict:
        with self._lock:
            return {
                "depth": self.depth,
                "programs": len(self._programs),
                "armed": self._armed,
                "windows": self._windows,
                "fallbacks": dict(sorted(self._fallbacks.items())),
                "stager": self._stager.stats(),
            }
