"""Pipelined dispatch: keep the device busy across coalesced windows.

BENCH r03 measured an 812M pts/s burst but only 523M sustained, with
dispatch RTT (0.101s) exceeding net kernel time (0.066s) — the serve
hot path is dispatch-bound, not FLOP-bound (ROADMAP item 2; PR 6's gap
report shows 32% host gap live even on CPU). The serial dispatch loop
serializes, per window: host stacking → host→device transfer → kernel →
device sync → respond. Every one of those host phases leaves the device
idle.

This module overlaps them. Each coalesced kNN window is split into
stages:

    prepare   host stacking/padding of member query points
              (batcher.stack_queries — byte-identical to serial)
    transfer  host→device staging of the stacked queries through
              engine.device.QueryStager's double-buffered slots
    launch    planner.knn_launch: plan → mask → kernel DISPATCH (JAX
              async dispatch returns before the kernel finishes)
    sync      planner.KnnLaunch.sync on the COMPLETER thread: the one
              combined device read, overflow fallback, result split,
              future resolution, audit

The dispatch thread runs prepare/transfer/launch for window N+1 while
window N's kernel is still executing; the sync is deferred to a
dedicated completer thread and happens exactly when the results are
consumed for the response. In-flight windows are bounded by `depth`
(default 2 — classic double buffering): the dispatch thread blocks on
the window slot semaphore when the pipeline is full, which is the
backpressure that keeps HBM footprint bounded.

Cross-kind fusion rides here too: COUNT requests whose (type, CQL,
hints) match the kNN window (batcher.fused_count_key) resolve from the
window's filter-mask reduction — one fused program instead of a second
dispatch RTT. The reduction runs over the f64-exact mask (band
corrections scattered in), so the planner currently accepts every
fusion request; `KnnLaunch.fused_ok` stays in the contract and riders
a future gate declines re-dispatch serially on the completer.

Failure semantics match the serial path exactly: device OOM runs the
batcher's halving → host-eval ladder (re-staging from the HOST query
copies each request still holds — staged device buffers are never
re-read, which is what makes the registry's serve donation tier safe);
any other error fans out typed to every member. A `device.transfer`
fault mid-pipeline fails ONLY its own window — windows already launched
drain cleanly through the completer (`gmtpu chaos` asserts this).

GT16 (docs/ANALYSIS.md) lint-enforces the stage discipline: no
`block_until_ready` / `future.result()` / `jax.device_get` inside the
prepare/transfer/launch stages — a blocking call there re-serializes
the exact host gap this module exists to remove.
"""

from __future__ import annotations

import threading
import time
from queue import SimpleQueue
from time import perf_counter_ns
from typing import List, Optional

from geomesa_tpu.serve.batcher import (
    _run_group, batch_timeout_ms, stack_queries, split_knn_results)
from geomesa_tpu.serve.scheduler import ServeRequest
from geomesa_tpu.telemetry.trace import TRACER, new_span_id

_STOP = object()


class PipelinedWindow:
    """One coalesced window moving through the pipeline stages."""

    __slots__ = ("source", "live", "counts", "lead", "t0", "g0_ns",
                 "adopt_from", "wid", "running", "running_counts",
                 "qx", "qy", "offsets", "staged", "launch",
                 "stalls", "recovery", "seq", "prep_start_ns")

    def __init__(self, source, live, counts, lead, t0, g0_ns,
                 adopt_from, seq):
        self.source = source
        self.live = live            # every popped member (incl. cancelled)
        self.counts = counts        # fused count riders
        self.lead = lead
        self.t0 = t0                # monotonic at dispatch start
        self.g0_ns = g0_ns          # perf_counter_ns at gather start
        self.adopt_from = adopt_from
        self.seq = seq
        self.wid: Optional[int] = None   # pre-allocated window span id
        self.running: List[ServeRequest] = []
        self.running_counts: List[ServeRequest] = []
        self.staged = None
        self.launch = None
        self.stalls: list = []
        self.recovery: list = []
        self.prep_start_ns = 0


class DispatchPipeline:
    """The pipelined execution path behind QueryService._dispatch.

    Owned by one QueryService; `submit` runs on the service's dispatch
    thread, the deferred syncs on this pipeline's completer thread.
    `depth` bounds windows in flight (submit blocks when full)."""

    def __init__(self, service, depth: int = 2,
                 donate: Optional[bool] = None, ring: bool = True,
                 ring_depth: int = 4):
        from geomesa_tpu.engine.device import QueryStager

        self.service = service
        self.depth = max(2, int(depth))
        self._donate = donate       # None = auto (backend supports it)
        self._stager = QueryStager(depth=self.depth)
        # persistent serve loop (serve/ringloop.py): eligible kNN
        # windows dispatch over a long-lived ring program instead of
        # the per-window transfer+launch below; ring-ineligible windows
        # fall back typed to this pipeline unchanged
        self.ring = None
        if ring:
            from geomesa_tpu.serve.ringloop import RingLoop

            self.ring = RingLoop(service,
                                 depth=max(int(ring_depth), self.depth),
                                 donate=donate)
        self._slots = threading.BoundedSemaphore(self.depth)
        self._completions: SimpleQueue = SimpleQueue()
        self._lock = threading.Lock()
        self._seq = 0
        self._inflight = 0
        self._max_inflight = 0
        self._windows = 0
        self._fused = 0
        self._fused_declined = 0
        self._closed = False
        self._worker: Optional[threading.Thread] = None

    # -- lifecycle ---------------------------------------------------------

    def _ensure_started(self) -> None:
        with self._lock:
            if self._worker is not None and self._worker.is_alive():
                return
            self._worker = threading.Thread(
                target=self._complete_loop, name="gmtpu-serve-sync",
                daemon=True)
            self._worker.start()

    def close(self, timeout_s: float = 10.0) -> None:
        """Drain remaining completions and stop the completer. Windows
        already launched still sync (no torn responses on shutdown)."""
        from queue import Empty

        with self._lock:
            self._closed = True
            worker = self._worker
        if self.ring is not None:
            self.ring.close()
        if worker is not None and worker.is_alive():
            self._completions.put(_STOP)
            worker.join(timeout=timeout_s)
        # a window enqueued AFTER the _STOP sentinel (the dispatch
        # thread raced shutdown past submit's closed-check) would sit in
        # a queue nobody reads: its member futures must fail typed
        # rather than hang a client forever
        while True:
            try:
                win = self._completions.get_nowait()
            except Empty:
                break
            if win is _STOP:
                continue
            from geomesa_tpu.serve.scheduler import QueryRejected

            exc = QueryRejected(
                "shutting_down",
                "service closed before the pipelined window synced")
            for r in win.running + win.running_counts:
                if not r.future.done():
                    r.future.set_exception(exc)
            self._window_done(win)

    @property
    def donate(self) -> bool:
        if self._donate is None:
            import jax

            # donation is unimplemented on CPU (JAX warns and ignores);
            # resolve once, lazily, so constructing a service does not
            # force backend init
            self._donate = jax.default_backend() != "cpu"
        return self._donate

    # -- dispatch-thread stages --------------------------------------------

    def submit(self, source, live: List[ServeRequest],
               counts: List[ServeRequest], lead, t0: float, g0_ns: int,
               adopt_from: int) -> None:
        """Run prepare/transfer/launch for one window and hand it to the
        completer. Blocks while `depth` windows are in flight. All
        failure modes resolve member futures and complete the window's
        bookkeeping before returning — the caller never needs to clean
        up."""
        from geomesa_tpu.compilecache.stall import STALLS
        from geomesa_tpu.faults import RECOVERY

        self._ensure_started()
        # bounded-wait acquire: the completer survives every window
        # error by construction, but if it is ever not running (process
        # teardown, BaseException) the dispatch thread must fail loudly
        # instead of wedging on a slot that can never free
        while not self._slots.acquire(timeout=1.0):
            with self._lock:
                worker = self._worker
            if worker is None or not worker.is_alive():
                raise RuntimeError(
                    "pipeline completer is not running; window slots "
                    "cannot free")
        with self._lock:
            self._seq += 1
            self._inflight += 1
            self._max_inflight = max(self._max_inflight, self._inflight)
            seq = self._seq
        win = PipelinedWindow(source, live, counts, lead, t0, g0_ns,
                              adopt_from, seq)
        trace = lead.trace
        if trace is not None:
            win.wid = new_span_id()
        stall_token = STALLS.token()
        rec_token = RECOVERY.token()
        ok = False
        try:
            self._prepare(win)
            if win.running:
                # ring route first (docs/SERVING.md "Persistent serve
                # loop"): slot write + one pre-compiled dispatch; a
                # typed refusal (ineligible/stale) keeps the pipelined
                # transfer+launch, and a feed ERROR lands in the same
                # failure ladder a launch error would
                if self.ring is None or not self.ring.try_feed(win):
                    self._transfer(win)
                    self._launch(win)
            ok = True
        except BaseException as e:  # noqa: BLE001 — serial-path parity
            self._note_meters(win, stall_token, rec_token)
            self._fail(win, e)
            self._window_done(win)
            return
        self._note_meters(win, stall_token, rec_token)
        if not win.running:
            # every kNN member was cancelled between pop and prepare:
            # any fused counts still deserve their (serial) dispatch
            if win.running_counts:
                _run_group(win.source, win.running_counts)
            self._window_done(win)
            return
        with self._lock:
            self._windows += 1
            closed = self._closed
        if closed:
            # shutdown raced this window past the launch: the completer
            # may already have consumed _STOP, so never enqueue — fail
            # typed here (close()'s drain sweep covers the narrower
            # race where the put itself beat the sentinel)
            from geomesa_tpu.serve.scheduler import QueryRejected

            self._fail(win, QueryRejected(
                "shutting_down",
                "service closed before the pipelined window synced"))
            self._window_done(win)
            return
        self._completions.put(win)

    def _note_meters(self, win, stall_token, rec_token) -> None:
        """Dispatch-thread attribution window: compile stalls + recovery
        events this thread noted during prepare/transfer/launch are this
        window's (same thread-scoped discipline as the serial path);
        the completer appends its own sync-side window later."""
        from geomesa_tpu.compilecache.stall import STALLS
        from geomesa_tpu.faults import RECOVERY

        ident = threading.get_ident()
        win.stalls.extend(STALLS.since(stall_token, thread_ident=ident))
        win.recovery.extend(RECOVERY.since(rec_token, thread_ident=ident))

    def _prepare(self, win: PipelinedWindow) -> None:
        """Host stacking/padding (batcher.stack_queries). Marks member
        futures running — a rider cancelled while queued drops out here
        exactly like the serial execute_batch."""
        win.prep_start_ns = perf_counter_ns()
        win.running = [r for r in win.live
                       if r.future.set_running_or_notify_cancel()]
        win.running_counts = [r for r in win.counts
                              if r.future.set_running_or_notify_cancel()]
        if not win.running:
            return
        win.qx, win.qy, win.offsets = stack_queries(win.running)
        trace = win.lead.trace
        if trace is not None and win.wid is not None:
            trace.record("prepare", win.prep_start_ns, perf_counter_ns(),
                         parent_id=win.wid, batch=len(win.running))

    def _transfer(self, win: PipelinedWindow) -> None:
        """Stage the stacked queries into the double-buffered device
        slots (QueryStager): the transfer overlaps the previous window's
        kernel instead of serializing in front of this one's. On a mesh
        service the slots are PER-SHARD-REPLICATED — one NamedSharding
        placement puts the window's queries on every chip (the sharded
        program reads them replicated; the shard-affinity route takes
        the owning chip's replica) — and the placement joins the slot
        key so mesh and single-chip slots never alias."""
        lead = win.lead
        # gate on the SOURCE's residency tier, not ServeConfig.mesh:
        # the planner only takes the mesh route when the superbatch is
        # mesh-resident, and a store the tier cannot shard (extended
        # geometry, --no-device-cache) must stage single-device
        # buffers for the single-chip kernel it will actually run
        cache = getattr(win.source.planner, "cache", None)
        mesh = cache.serving_mesh() if cache is not None else None
        placement = None
        key = (lead.query.type_name, lead.k, lead.impl, len(win.qx))
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            placement = NamedSharding(mesh, P())
            key = key + ("mesh", tuple(int(s) for s in mesh.devices.shape))
        with TRACER.scope(lead.trace, parent_id=win.wid):
            win.staged = self._stager.stage(key, win.qx, win.qy,
                                            device=placement)

    def _launch(self, win: PipelinedWindow) -> None:
        """planner.knn_launch: plan → mask → async kernel dispatch. The
        fused count reduction rides the same launch when requested."""
        lead = win.lead
        timeout_ms = batch_timeout_ms(win.running + win.running_counts)
        with TRACER.scope(lead.trace, parent_id=win.wid):
            win.launch = win.source.planner.knn_launch(
                lead.query, win.qx, win.qy, k=lead.k, impl=lead.impl,
                timeout_ms=timeout_ms, staged=win.staged,
                want_mask_count=bool(win.running_counts),
                donate=self.donate)
        from geomesa_tpu.serve.batcher import note_launch_route

        # routing attribution lands BEFORE the deferred sync, so the
        # completer's ServeEvents carry it even when the window fails
        note_launch_route(win.running + win.running_counts, win.launch)

    # -- completer thread --------------------------------------------------

    def _complete_loop(self) -> None:
        import logging

        from geomesa_tpu.telemetry.recorder import RECORDER

        log = logging.getLogger(__name__)
        while True:
            win = self._completions.get()
            if win is _STOP:
                return
            try:
                self._sync(win)
            except Exception as e:  # noqa: BLE001 — the completer must live
                log.exception("serve pipeline completer error")
                RECORDER.crash_dump("serve pipeline completer error", e)
            try:
                self._window_done(win)
            except Exception as e:  # noqa: BLE001 — ditto: the window's
                # slot/inflight releases ran in _window_done's finally,
                # so surviving a finish-bookkeeping error (audit I/O,
                # metrics) leaks nothing — it only costs that window's
                # audit record
                log.exception("serve pipeline finish error")
                RECORDER.crash_dump("serve pipeline finish error", e)

    def _sync(self, win: PipelinedWindow) -> None:
        """Deferred device sync: the one combined read, result split,
        fused-count resolution — and the serial path's full failure
        ladder when the window errors."""
        from geomesa_tpu.compilecache.stall import STALLS
        from geomesa_tpu.faults import RECOVERY

        stall_token = STALLS.token()
        rec_token = RECOVERY.token()
        lead = win.lead
        try:
            with TRACER.scope(lead.trace, parent_id=win.wid):
                dists, idx, batch = win.launch.sync()
                split_knn_results(win.running, win.offsets, dists, idx,
                                  batch)
            self._resolve_counts(win)
        except BaseException as e:  # noqa: BLE001 — fan out, serial parity
            self._fail(win, e)
        finally:
            self._note_meters(win, stall_token, rec_token)

    def _resolve_counts(self, win: PipelinedWindow) -> None:
        if not win.running_counts:
            return
        launch = win.launch
        if launch is not None and launch.fused_ok \
                and launch.mask_count is not None:
            with self._lock:
                self._fused += len(win.running_counts)
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("serve.fused.counts",
                            len(win.running_counts))
            n = launch.mask_count
            for r in win.running_counts:
                r.future.set_result(n)
        else:
            # defensive: the planner currently never declines
            # (fused_ok is always True when requested — the mask is
            # f64-exact), but the contract allows it, and a declined
            # rider gets its own serial dedup'd dispatch — slower,
            # never wrong
            with self._lock:
                self._fused_declined += len(win.running_counts)
            _run_group(win.source, win.running_counts)

    def _fail(self, win: PipelinedWindow, exc: BaseException) -> None:
        """Window failure = the serial path's ladder: OOM runs the
        batcher's halving → host-eval fallback (re-staging from the host
        query copies), everything else fans out typed. Fused counts
        always get a real (serial) count attempt — the count's failure
        story must not depend on the kNN it happened to ride with."""
        from geomesa_tpu.faults import classify
        from geomesa_tpu.serve.batcher import _oom_fallback
        from geomesa_tpu.telemetry.recorder import RECORDER

        # flight-recorder lifecycle event: a pipelined window dying
        # mid-flight is the multi-chip postmortem case — record WHICH
        # shards the window was routed to (note_launch_route stamped
        # the lead before the deferred sync) alongside the error, so a
        # crash dump distinguishes "one chip's windows keep failing"
        # from a fleet-wide fault
        RECORDER.note_event(
            "pipeline", action="window_failed", seq=win.seq,
            members=len(win.running) + len(win.running_counts),
            error=type(exc).__name__,
            shards=win.lead.shards or None,
            mesh_shape=win.lead.mesh_shape or None)
        # done-future guards throughout: a failure AFTER partial
        # resolution (e.g. the kNN split succeeded, then the fused-count
        # path threw) must only fail the still-pending members —
        # set_exception on a resolved future raises InvalidStateError
        pending = [r for r in win.running if not r.future.done()]
        if pending:
            if isinstance(exc, Exception) and classify(exc) == "oom":
                _oom_fallback(win.source, pending, exc)
            else:
                for r in pending:
                    r.future.set_exception(exc)
        pending_counts = [r for r in win.running_counts
                          if not r.future.done()]
        if pending_counts:
            try:
                _run_group(win.source, pending_counts)
            except BaseException as e:  # noqa: BLE001 — never drop a rider
                for r in pending_counts:
                    if not r.future.done():
                        r.future.set_exception(e)

    def _window_done(self, win: PipelinedWindow) -> None:
        """Completion bookkeeping: record the window span (its extent is
        only known now), hand the window to the service's shared finish
        path, free the slot. Called exactly once per submitted window —
        from submit's failure paths or from the completer — so the slot
        frees exactly once per acquire."""
        import logging

        t1 = time.monotonic()
        end_ns = perf_counter_ns()
        trace = win.lead.trace
        if trace is not None and win.wid is not None:
            trace.record(
                "dispatch", win.g0_ns, end_ns, span_id=win.wid,
                batch=len(win.live), pipelined=True, seq=win.seq,
                fused=len(win.counts))
        try:
            try:
                self.service._window_complete(win, t1, end_ns)
            except Exception as e:  # noqa: BLE001 — bookkeeping only:
                # futures are already resolved, and letting this
                # propagate out of submit's failure path would make the
                # service decrement its inflight token a SECOND time
                # (negative inflight wedges close(drain=True) for the
                # whole drain timeout)
                from geomesa_tpu.telemetry.recorder import RECORDER

                logging.getLogger(__name__).exception(
                    "serve pipeline finish error")
                RECORDER.crash_dump("serve pipeline finish error", e)
        finally:
            with self._lock:
                self._inflight -= 1
            self._slots.release()

    # -- introspection -----------------------------------------------------

    def reset_max_inflight(self) -> None:
        """Re-seed the windows-in-flight high-water mark at the current
        depth — measurement loops (loadgen.run_sustained) call this at
        run start so the reported peak is the RUN's, not the service
        lifetime's."""
        with self._lock:
            self._max_inflight = self._inflight

    def stats(self) -> dict:
        with self._lock:
            out = {
                "depth": self.depth,
                "windows": self._windows,
                "inflight": self._inflight,
                "max_inflight": self._max_inflight,
                "fused_counts": self._fused,
                "fused_declined": self._fused_declined,
                "stager": self._stager.stats(),
            }
        if self.ring is not None:
            out["ring"] = self.ring.stats()
        return out
