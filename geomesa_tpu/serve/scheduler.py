"""Admission control for the query-serving layer.

The serving shape this targets: many small BBOX/kNN/count queries from
concurrent clients against one device-resident store. The device executes
one program at a time, so the scheduler's job is to decide — BEFORE any
device work — which requests wait, which coalesce, and which are shed,
with explicit backpressure instead of unbounded buffering (the Clipper /
Orca admission-control stance; PAPERS.md serving citations).

Pieces:
- `ServeRequest`: one in-flight query (kind execute|count|knn) with a
  priority class, tenant label, absolute deadline, cancellation flag and
  a result future.
- `TokenBucket`: per-tenant rate limiting (rate r tokens/s, burst b).
- `AdmissionQueue`: bounded, priority-classed FIFO. `put` raises a typed
  `QueryRejected` when full (load shedding — the queue NEVER grows past
  its bound, so queue wait is bounded by design) and `drain_compatible`
  hands the batcher every queued request sharing a coalescing key.

Deadlines propagate into the planner's cooperative timeout checks via
`QueryPlanner.execute(timeout_ms=...)`; expiry surfaces as the typed
`plan.QueryTimeout`, distinct from `QueryRejected` (shed) and from real
errors — the three-way split a serving client needs for retry policy.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Callable, Deque, Dict, List, Optional

from geomesa_tpu.plan.query import Query

# priority classes, highest first; index = scheduling order
PRIORITIES = ("interactive", "normal", "batch")

_ids = itertools.count()


class QueryRejected(RuntimeError):
    """Typed load-shed signal: the request never reached the device.

    reason:
      queue_full    — admission queue at capacity (backpressure)
      rate_limited  — tenant token bucket empty
      shed          — degradation ladder dropping low-priority work
      shutting_down — service draining; no new admissions
      cancelled     — caller cancelled while queued
      quarantined   — poison-query quarantine: this fingerprint crashed
                      repeatedly and is blocked for the quarantine TTL
                      (faults/quarantine.py, docs/ROBUSTNESS.md)
    """

    def __init__(self, reason: str, detail: str = ""):
        super().__init__(
            f"query rejected ({reason})" + (f": {detail}" if detail else "")
        )
        self.reason = reason
        self.detail = detail


@dataclasses.dataclass
class ServeRequest:
    """One admitted (or to-be-admitted) query."""

    kind: str  # "execute" | "count" | "knn"
    query: Query
    # knn-only: host query coordinates + k + kernel choice
    qx: object = None
    qy: object = None
    k: int = 10
    impl: str = "sparse"
    tenant: str = ""
    priority: int = 1  # index into PRIORITIES
    deadline: Optional[float] = None  # absolute time.monotonic() seconds
    # degradation ladder opt-in: under sustained overload the service may
    # rewrite hints (loose bbox / sampling) for requests that allow it
    allow_degraded: bool = False
    id: int = dataclasses.field(default_factory=lambda: next(_ids))
    future: Future = dataclasses.field(default_factory=Future)
    enqueued_at: float = 0.0
    # telemetry (docs/OBSERVABILITY.md): the per-query Trace opened at
    # submit (None when tracing is off — every downstream telemetry
    # call no-ops on None), plus the perf_counter_ns enqueue stamp the
    # dispatch loop uses to record the cross-thread queue.wait span
    # (enqueued_at is time.monotonic seconds: a different clock)
    trace: object = None
    enqueued_ns: int = 0
    degraded: bool = False  # set by the service when the ladder rewrote hints
    # pre-degrade poison fingerprint, stashed by the service's ladder
    # BEFORE it rewrites hints: the coalescing key includes the hint
    # string, so striking the post-degrade key would never match the
    # key admission checks (quarantine would silently never trip for
    # degraded requests)
    quarantine_key: object = None
    # sharded serving (docs/SERVING.md "Sharded serving"): the shard-
    # affinity tag computed at admission (`shard_affinity`) — which
    # chips own the tiles this query's pruned partitions live on. The
    # planner's dispatch seam recomputes it authoritatively from the
    # plan and overrides; mesh_shape/shards end up on the ServeEvent.
    shards: str = ""
    mesh_shape: str = ""
    # approximate-answer tier + result cache (docs/SERVING.md
    # "Approximate answers"): the service attaches its ResultCache to
    # count/execute requests so the batcher can populate it with the
    # version the planner actually pinned; cache_hit marks a request
    # resolved without any dispatch, approx marks a sketch-served
    # answer (both ride the ServeEvent)
    cache: object = None
    cache_hit: bool = False
    approx: bool = False
    # degradation-ladder sketch rung (docs/SERVING.md "Degradation
    # ladder"): nonzero = the ladder injected the tolerance hint at
    # this level. The request is marked `degraded` — and spends the
    # SLO exactness budget — only if a sketch answer is actually
    # SERVED; a bound that does not fit runs exact, unmarked, with
    # the budget untouched.
    sketch_rung: int = 0
    # columnar wire (docs/SERVING.md "Columnar wire"): "columnar" when
    # the request opted into binary record-batch framing for its bulk
    # payload (execute features / density / topk grids). The protocol
    # layer sets it AND downgrades it typed when the capability is
    # absent; the dispatch path never reads it — encoding is a
    # response-time concern.
    wire: str = "json"

    def __post_init__(self):
        if self.kind not in ("execute", "count", "knn"):
            raise ValueError(f"unknown request kind {self.kind!r}")
        if not 0 <= self.priority < len(PRIORITIES):
            raise ValueError(
                f"priority must be in [0, {len(PRIORITIES)}), "
                f"got {self.priority}"
            )

    def cancel(self) -> bool:
        """Cancel a queued request; returns False once it started running."""
        return self.future.cancel()

    @property
    def remaining_ms(self) -> Optional[float]:
        if self.deadline is None:
            return None
        return (self.deadline - time.monotonic()) * 1000.0

    @property
    def expired(self) -> bool:
        r = self.remaining_ms
        return r is not None and r <= 0.0


def shard_affinity(source, req: ServeRequest) -> tuple:
    """Admission-time shard affinity: which mesh shards own the tiles
    `req`'s query will touch, so a query LANDS where its tiles live
    (docs/SERVING.md "Sharded serving").

    Metadata-only and best-effort: bbox/interval extraction + manifest
    partition pruning + the device cache's row-range ownership map — no
    planning, no device work, and no residency build (a cold cache
    answers () rather than paying an upload on the submit thread). The
    planner's mesh dispatch recomputes the authoritative value from the
    post-interceptor plan; this tag routes telemetry lanes and lets the
    dispatcher group same-affinity windows."""
    planner = getattr(source, "planner", None)
    cache = getattr(planner, "cache", None)
    if cache is None or getattr(cache, "mesh", None) is None:
        return ()
    try:
        from geomesa_tpu.cql.extract import (
            BBox, Interval, extract_bbox, extract_intervals)

        sft = source.storage.sft
        g = sft.default_geometry
        d = sft.default_dtg
        f = req.query.filter_ast
        bbox = extract_bbox(f, g.name) if g else BBox(-180, -90, 180, 90)
        interval = (extract_intervals(f, d.name) if d
                    else Interval(None, None))
        manifest = source.storage.manifest_snapshot()
        parts = source.storage.prune_partitions(
            bbox, interval, manifest=manifest)
        return cache.shards_for(parts)
    # gt: waive GT14
    # (deliberate degrade: affinity is a routing HINT — admission must
    # never fail a request because a metadata peek raced a write; the
    # planner recomputes the authoritative value at dispatch)
    except Exception:
        return ()


class TokenBucket:
    """Classic token bucket: capacity `burst`, refill `rate` tokens/s.
    Thread-safe; `try_acquire` never blocks (admission control sheds,
    it does not queue on rates)."""

    def __init__(self, rate: float, burst: float):
        if rate <= 0 or burst <= 0:
            raise ValueError("token bucket rate and burst must be > 0")
        self.rate = float(rate)
        self.burst = float(burst)
        self._tokens = float(burst)
        self._stamp = time.monotonic()
        self._lock = threading.Lock()

    def try_acquire(self, n: float = 1.0) -> bool:
        with self._lock:
            now = time.monotonic()
            self._tokens = min(
                self.burst, self._tokens + (now - self._stamp) * self.rate
            )
            self._stamp = now
            if self._tokens >= n:
                self._tokens -= n
                return True
            return False


class AdmissionQueue:
    """Bounded priority-classed FIFO. One deque per priority class;
    `pop` serves the highest class first, FIFO within a class, so a
    steady batch-class flood can never starve interactive queries of
    *ordering* (only of device time, which the bound caps)."""

    def __init__(self, max_depth: int):
        if max_depth < 1:
            raise ValueError("queue depth must be >= 1")
        self.max_depth = max_depth
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._classes: List[Deque[ServeRequest]] = [
            deque() for _ in PRIORITIES
        ]

    def __len__(self) -> int:
        with self._lock:
            return sum(len(d) for d in self._classes)

    def depths(self) -> Dict[str, int]:
        """Per-priority-class occupancy snapshot, keyed by class name
        (`serve.queue.class_depth{priority=...}` gauges — a batch-class
        backlog behind an empty interactive lane reads differently from
        a uniformly full queue on a dashboard)."""
        with self._lock:
            return {PRIORITIES[i]: len(d)
                    for i, d in enumerate(self._classes)}

    def put(self, req: ServeRequest) -> None:
        with self._lock:
            if sum(len(d) for d in self._classes) >= self.max_depth:
                raise QueryRejected(
                    "queue_full",
                    f"admission queue at capacity ({self.max_depth})",
                )
            req.enqueued_at = time.monotonic()
            req.enqueued_ns = time.perf_counter_ns()
            self._classes[req.priority].append(req)
            self._not_empty.notify()

    def pop(
        self,
        timeout: Optional[float] = None,
        on_pop: Optional[Callable[[ServeRequest], None]] = None,
    ) -> Optional[ServeRequest]:
        """Highest-priority oldest request, or None on timeout. Requests
        cancelled while queued are skipped (their futures are already
        resolved by Future.cancel). `on_pop` runs under the queue lock
        before the request is returned, so a caller can mark it in-flight
        atomically with its removal — a drain loop that checks
        "queue empty AND nothing in flight" must never observe the window
        between the two."""
        deadline = None if timeout is None else time.monotonic() + timeout
        with self._lock:
            while True:
                for d in self._classes:
                    while d:
                        req = d.popleft()
                        if req.future.cancelled():
                            continue
                        if on_pop is not None:
                            # gt: waive GT11
                            # (deliberate: the callback is the atomic
                            # pop+mark-inflight step, see docstring; its
                            # only consumer is _mark_inflight, which
                            # takes _state_lock, never this queue lock)
                            on_pop(req)
                        return req
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                    self._not_empty.wait(remaining)
                else:
                    self._not_empty.wait()

    def drain_compatible(
        self,
        key: object,
        key_fn: Callable[[ServeRequest], object],
        limit: int,
    ) -> List[ServeRequest]:
        """Remove and return up to `limit` queued requests whose
        coalescing key matches `key` (any priority class — a batch-class
        request identical to an interactive one rides its dispatch for
        free). Non-matching requests keep their positions."""
        out: List[ServeRequest] = []
        if key is None or limit <= 0:
            return out
        with self._lock:
            for d in self._classes:
                if len(out) >= limit:
                    break
                keep: Deque[ServeRequest] = deque()
                while d:
                    req = d.popleft()
                    if req.future.cancelled():
                        continue
                    if len(out) < limit and key_fn(req) == key:
                        out.append(req)
                    else:
                        keep.append(req)
                d.extend(keep)
        return out

    def drain_all(self) -> List[ServeRequest]:
        """Empty the queue (non-graceful shutdown path)."""
        with self._lock:
            out = [r for d in self._classes for r in d]
            for d in self._classes:
                d.clear()
        return out


class RateLimiter:
    """Per-tenant token buckets sharing one (rate, burst) config; tenants
    appear lazily. rate=None disables limiting entirely."""

    def __init__(self, rate: Optional[float], burst: float = 8.0):
        self.rate = rate
        self.burst = burst
        self._buckets: Dict[str, TokenBucket] = {}
        self._lock = threading.Lock()

    def admit(self, tenant: str) -> None:
        if self.rate is None:
            return
        with self._lock:
            bucket = self._buckets.get(tenant)
            if bucket is None:
                bucket = self._buckets[tenant] = TokenBucket(
                    self.rate, self.burst
                )
        if not bucket.try_acquire():
            raise QueryRejected(
                "rate_limited",
                f"tenant {tenant!r} over {self.rate:g} qps "
                f"(burst {self.burst:g})",
            )
