"""Concurrent query serving: admission control, request coalescing,
tail-latency observability (docs/SERVING.md).

No reference-module parity here — upstream GeoMesa delegates concurrency
to GeoServer/the client; a device-resident store needs its own serving
discipline because one accelerator runs one program at a time. The
design borrows from inference serving (Clipper-style adaptive batching
with latency knobs; Orca-style continuous batching — see PAPERS.md):
coalesce compatible requests into shared device dispatches, bound the
queue, shed explicitly — and pipeline the dispatches themselves
(pipeline.py): window N+1's host prep and transfer overlap window N's
kernel, with the device sync deferred to a completer thread
(docs/SERVING.md "Pipelined dispatch").
"""

from geomesa_tpu.serve.scheduler import (
    PRIORITIES, AdmissionQueue, QueryRejected, RateLimiter, ServeRequest,
    TokenBucket)
from geomesa_tpu.serve.batcher import (
    compat_key, execute_batch, fused_count_key)
from geomesa_tpu.serve.pipeline import DispatchPipeline
from geomesa_tpu.serve.service import QueryService, ServeConfig, self_check
from geomesa_tpu.serve.loadgen import (
    LoadReport, count_request_factory, knn_request_factory,
    run_closed_loop, run_open_loop, run_sustained, run_wire)
from geomesa_tpu.serve.columnar import PushMux, wire_capabilities

__all__ = [
    "PRIORITIES", "AdmissionQueue", "QueryRejected", "RateLimiter",
    "ServeRequest", "TokenBucket", "compat_key", "execute_batch",
    "fused_count_key", "DispatchPipeline",
    "QueryService", "ServeConfig", "self_check", "LoadReport",
    "knn_request_factory", "count_request_factory",
    "run_closed_loop", "run_open_loop", "run_sustained", "run_wire",
    "PushMux", "wire_capabilities",
]
