"""`gmtpu serve` wire protocol: JSON-lines request/response.

One JSON object per input line; one JSON response line per request,
written IN COMPLETION ORDER (a coalesced batch completes together; a
shed request answers immediately) — the id field is the correlation
key, exactly like a pipelined wire protocol:

    {"id": "r1", "op": "count", "typeName": "gdelt",
     "cql": "BBOX(geom,-10,-10,10,10)"}
    {"id": "r2", "op": "knn", "typeName": "gdelt", "cql": "INCLUDE",
     "x": [1.5], "y": [2.5], "k": 8}
    {"id": "r3", "op": "query", "typeName": "gdelt", "cql": "...",
     "maxFeatures": 100}

Optional request fields: tenant, priority (interactive|normal|batch),
timeoutMs, allowDegraded. Responses: {"id", "ok": true, ...} with
op-specific payload, or {"id", "ok": false, "error":
rejected|timeout|error, "reason", "message"}.

Standing queries (docs/SERVING.md "Standing queries") ride the same
stream against a live (Kafka) store:

    {"id": "s1", "op": "subscribe", "typeName": "vessels",
     "cql": "DWITHIN(geom, POINT(0 0), 50000, meters)", "ttlS": 600}
    {"id": "s2", "op": "subscribe", "typeName": "vessels",
     "density": {"bbox": [-180,-90,180,90], "width": 256, "height": 128}}
    {"id": "p1", "op": "poll"}
    {"id": "u1", "op": "unsubscribe", "subscription": "sub-1"}

The subscribe response carries the subscription id; from then on the
server interleaves PUSH FRAMES — JSON objects with an "event" field
instead of an "id" — into the response stream as Kafka batches fold
in: `enter`/`exit` (geofence transitions, fid lists), `density`
(window totals), `state` (full re-sync after lag), and the typed
`subscription_lagged` / `expired` / `quarantined` lifecycle frames.
`poll` folds pending Kafka messages synchronously and flushes
outboxes (deterministic clients; the `--live-poll-ms` pump does it on
a cadence otherwise).

Introspection: `{"id": "i1", "op": "stats"}` answers with the
service's live counters (queue depth, dispatch/coalesce totals,
quarantine, pipeline — and the SLO burn report when `--slo` loaded a
spec), so a wire client can watch its own error budget without a
separate metrics scrape.

Fleet verbs (docs/SERVING.md "Replica fleets"): `{"op": "hello",
"role": "router"}` is the replica-role handshake — the response
carries the replica's id + health state, and a `router`/`admin` role
marks the CONNECTION admin. `{"op": "drain"}` (admin-only; rejected
typed on plain client connections) drains gracefully: stop admitting,
finish every in-flight request, then close — so the fleet router and
`gmtpu fleet restart` never need process signals. A replica that is
not `ready` (warming until `gmtpu warmup --check` semantics pass, or
draining) refuses query traffic with a typed, retryable rejection
instead of serving cold or torn.

Columnar wire (docs/SERVING.md "Columnar wire"): the `hello` response
advertises `"wire": ["json", "columnar"]` when pyarrow is available; a
request (or the whole connection, via `{"op": "hello", "wire":
"columnar"}`) opts into binary record-batch framing for the bulk
payloads — `execute` feature results as Arrow IPC, density/topk grids
as single raw buffers, kNN query points and bulk `ingest` record
batches inbound, and push-frame fid columns. Everything else — and
every environment without pyarrow or a binary sink — stays plain
JSON lines, downgraded TYPED via `"wireFallback"` so a columnar
client knows it got the fallback rather than silently re-parsing.

Errors are per-request, never fatal to the stream: a malformed line
yields an ok=false response and the loop continues — one bad client
request must not drop everyone else's connection.
"""

from __future__ import annotations

import json
import math
import threading
from time import perf_counter_ns
from typing import Iterable, Optional

import numpy as np

from geomesa_tpu.plan.planner import QueryTimeout
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve import columnar as colwire
from geomesa_tpu.serve.scheduler import (
    PRIORITIES, QueryRejected, ServeRequest)
from geomesa_tpu.serve.service import QueryService, ServeConfig

MAX_FEATURE_ROWS = 10_000  # response-size guard for op=query


def _finite(v: float):
    return None if (isinstance(v, float) and not math.isfinite(v)) else v


def _rows_json(batch, limit: int):
    """Feature rows as JSON dicts (geometry as WKT), capped at `limit`."""
    from geomesa_tpu.core.columnar import DictColumn, GeometryColumn
    from geomesa_tpu.core.wkt import to_wkt

    if batch is None or len(batch) == 0:
        return []
    n = min(len(batch), limit)
    names = batch.sft.attribute_names
    cols = {}
    for name in names:
        col = batch.columns[name]
        if isinstance(col, GeometryColumn):
            cols[name] = col
        elif isinstance(col, DictColumn):
            cols[name] = col.decode()
        else:
            cols[name] = np.asarray(col)
    rows = []
    for i in range(n):
        row = {}
        for name in names:
            col = batch.columns[name]
            m = cols[name]
            if isinstance(col, GeometryColumn):
                row[name] = (f"POINT ({m.x[i]} {m.y[i]})" if m.is_point
                             else to_wkt(m.geometry(i)))
            elif isinstance(col, DictColumn):
                row[name] = m[i]
            else:
                v = m[i].item()
                row[name] = _finite(v) if isinstance(v, float) else v
        rows.append(row)
    return rows


def _payload(kind: str, result, limit: int) -> dict:
    if kind == "count":
        doc = {"count": int(result)}
        if getattr(result, "approx", False):
            # typed error bound on the wire (docs/SERVING.md
            # "Approximate answers"): the exact count is guaranteed in
            # [lo, hi] = [count - bound, count + bound] — shipped
            # pre-computed so clients need no arithmetic to act on it
            doc["approx"] = True
            doc["bound"] = result.bound
            doc["confidence"] = result.confidence
            doc["lo"] = max(0, int(result) - int(result.bound))
            doc["hi"] = int(result) + int(result.bound)
        return doc
    if kind == "knn":
        dists, idx, _batch = result
        return {
            "dists": [[_finite(float(d)) for d in row] for row in dists],
            "indices": [[int(j) for j in row] for row in idx],
        }
    out = {"kind": result.kind, "count": int(result.count)}
    if result.kind == "features":
        feats = result.features
        out["count"] = len(feats) if feats is not None else 0
        out["features"] = _rows_json(feats, limit)
    elif result.kind == "density" and result.grid is not None:
        out["shape"] = list(result.grid.shape)
        out["total"] = float(result.grid.sum())
    elif result.kind == "stats":
        out["stats"] = str(result.stats)
    elif result.kind == "topk_cells":
        out["cells"] = result.stats
    if getattr(result, "approx", False):
        out["approx"] = True
        out["bound"] = float(result.bound)
        out["confidence"] = float(result.confidence)
        out["lo"] = max(0, int(result.count) - int(result.bound))
        out["hi"] = int(result.count) + int(result.bound)
    return out


def _columnar_payload(kind: str, result, limit: int):
    """(response fields, frame payload) for a columnar-mode request —
    or (None, None) when this result kind has no columnar encoding
    (count/knn/stats answers are already tiny; they stay JSON with no
    fallback marker). The fields mirror the JSON `_payload` exactly,
    minus the bulk data that moved into the frame."""
    if kind in ("count", "knn"):
        return None, None
    out = {"kind": result.kind, "count": int(result.count)}
    if result.kind == "features":
        feats = result.features
        # same count semantics as the JSON path: the TOTAL match count,
        # even when the shipped rows are capped at `limit` (the frame's
        # own `rows` field carries the shipped count)
        out["count"] = len(feats) if feats is not None else 0
        desc, payload = colwire.encode_execute_frame(feats, limit)
    elif result.kind == "density" and result.grid is not None:
        # keep the JSON summary fields (shape/total) so a decoded
        # columnar response is a superset of the JSON one
        out["shape"] = list(result.grid.shape)
        out["total"] = float(result.grid.sum())
        desc, payload = colwire.encode_density_frame(result.grid)
    elif result.kind == "topk_cells":
        desc, payload = colwire.encode_topk_frame(result.stats)
    else:
        return None, None
    out["frame"] = desc
    if getattr(result, "approx", False):
        out["approx"] = True
        out["bound"] = float(result.bound)
        out["confidence"] = float(result.confidence)
        out["lo"] = max(0, int(result.count) - int(result.bound))
        out["hi"] = int(result.count) + int(result.bound)
    return out, payload


def parse_request(doc: dict,
                  payload: Optional[bytes] = None) -> ServeRequest:
    op = doc.get("op", "query")
    kind = {"query": "execute", "execute": "execute",
            "count": "count", "knn": "knn"}.get(op)
    if kind is None:
        raise ValueError(f"unknown op {op!r}")
    type_name = doc["typeName"]
    kw = {}
    if (doc.get("tolerance") is not None or doc.get("topkCells")
            or doc.get("density") or doc.get("distinct")):
        # aggregation + approximate-answer hints (docs/SERVING.md):
        # tolerance = the client's accuracy contract, topkCells = the
        # sketch-native top-k-cells aggregation, distinct = count the
        # DISTINCT values of one attribute (HLL-resolved at admission
        # when a tolerance allows it; exact otherwise), density = a
        # one-shot DensityScan window (same spec shape as the
        # subscribe verb's standing window) whose grid ships as ONE
        # columnar buffer on a columnar connection
        from geomesa_tpu.plan.hints import QueryHints

        hkw = {}
        d = doc.get("density")
        if d:
            hkw.update(
                density_bbox=tuple(float(v) for v in d["bbox"]),
                density_width=int(d["width"]),
                density_height=int(d["height"]),
                density_weight=d.get("weight"))
        kw["hints"] = QueryHints(
            tolerance=(float(doc["tolerance"])
                       if doc.get("tolerance") is not None else None),
            topk_cells=(int(doc["topkCells"])
                        if doc.get("topkCells") else None),
            distinct=doc.get("distinct"),
            **hkw)
    query = Query(type_name, doc.get("cql", "INCLUDE"),
                  max_features=doc.get("maxFeatures"), **kw)
    priority = doc.get("priority", "normal")
    if isinstance(priority, str):
        priority = PRIORITIES.index(priority)
    req = ServeRequest(
        kind=kind, query=query, tenant=doc.get("tenant", ""),
        priority=priority,
        allow_degraded=bool(doc.get("allowDegraded", False)),
    )
    timeout_ms = doc.get("timeoutMs")
    if timeout_ms:
        import time

        req.deadline = time.monotonic() + float(timeout_ms) / 1000.0
    if kind == "knn":
        if payload is not None and doc.get("frame"):
            # columnar request staging: the x/y sections decode as
            # zero-copy f64 views that flow straight into the
            # batcher's stack_queries / the pipeline's prepare stage —
            # no per-point JSON number parsing on the hot path
            req.qx, req.qy = colwire.decode_knn_sections(
                doc["frame"], payload)
        else:
            req.qx = np.asarray(doc["x"], np.float64)
            req.qy = np.asarray(doc["y"], np.float64)
        if req.qx.shape != req.qy.shape or req.qx.ndim != 1:
            raise ValueError("knn x/y must be equal-length 1-d arrays")
        req.k = int(doc.get("k", 10))
        req.impl = doc.get("impl", "sparse")
    return req


def _error_response(rid, exc) -> dict:
    from geomesa_tpu.faults import BreakerOpen

    if isinstance(exc, QueryRejected):
        return {"id": rid, "ok": False, "error": "rejected",
                "reason": exc.reason, "message": str(exc)}
    if isinstance(exc, BreakerOpen):
        # fail-fast dependency outage: tell the client WHEN to retry —
        # the three-way rejected/timeout/error split gains a fourth leg
        # for "not you, not your query: the backend is resting"
        return {"id": rid, "ok": False, "error": "unavailable",
                "reason": exc.reason,
                "retryAfterS": round(exc.retry_after_s, 3),
                "message": str(exc)}
    if isinstance(exc, QueryTimeout):
        return {"id": rid, "ok": False, "error": "timeout",
                "phase": exc.phase, "message": str(exc)}
    return {"id": rid, "ok": False, "error": "error", "message": str(exc)}


SUBSCRIBE_OPS = ("subscribe", "unsubscribe", "poll", "subscriptions",
                 "export_subscription", "pause", "resume")


def _parse_density(doc: dict):
    """The density window of a subscribe request, or None."""
    d = doc.get("density")
    if d is None:
        return None
    from geomesa_tpu.subscribe import DensityWindow

    return DensityWindow(
        bbox=tuple(float(v) for v in d["bbox"]),
        width=int(d["width"]), height=int(d["height"]),
        weight_attr=d.get("weight"), decay=d.get("decay"),
        tolerance=(float(d["tolerance"])
                   if d.get("tolerance") is not None else None))


class _SubscribeSession:
    """Per-connection standing-query state: lazily creates the
    SubscriptionManager on the first subscribe verb (sharing the
    QueryService's tenant buckets and quarantine tuning), runs the
    auto-poll pump when configured, and flushes outboxes into the
    response stream.

    `push` is the PUSH-FRAME sink (events without an `id`): it routes
    through the service's PushMux so each frame is encoded once and
    fanned to this connection plus any attached mirrors — even the
    single-subscriber JSON path takes the one-encode buffer
    (docs/SERVING.md "Columnar wire"). `respond` stays the direct
    request/response writer."""

    def __init__(self, store, svc: QueryService, respond, push=None):
        self.store = store
        self.svc = svc
        self.respond = respond
        self.push = push if push is not None else respond
        self.manager = None
        self._stop = threading.Event()
        self._pump = None

    def _ensure(self):
        if self.manager is not None:
            return self.manager
        if not hasattr(self.store, "poll"):
            raise ValueError(
                "standing queries need a live (Kafka) store; this "
                "catalog is durable-only")
        from geomesa_tpu.subscribe import (
            SubscribeConfig, SubscriptionManager)

        cfg = self.svc.config
        self.manager = SubscriptionManager(
            self.store,
            SubscribeConfig(
                max_subscriptions=cfg.subscribe_max,
                outbox_limit=cfg.subscribe_outbox,
                rate=cfg.subscribe_rate,
                quarantine_after=cfg.quarantine_after,
                quarantine_ttl_s=cfg.quarantine_ttl_s,
            ),
            limiter=self.svc.limiter)
        if self.svc.subscriptions is None:
            # stats surface: first manager wins; close() clears it —
            # a later connection must not shadow a live one, and a
            # closed one must not keep reporting a dead registry
            self.svc.subscriptions = self.manager
        if cfg.subscribe_poll_ms:
            self._pump = threading.Thread(
                target=self._pump_loop, name="gmtpu-subscribe-pump",
                daemon=True)
            self._pump.start()
        return self.manager

    def _pump_loop(self):
        interval = self.svc.config.subscribe_poll_ms / 1000.0
        while not self._stop.wait(interval):
            self.pump_once()

    def pump_once(self) -> int:
        """One poll + flush cycle. Typed broker errors surface as a
        `poll_error` frame — the stream stays alive, the client knows
        events are delayed, and the next cycle retries. The flush is
        guarded too: one raising write must not silently kill the pump
        thread and strand a live connection event-less."""
        if self.manager is None:
            return 0
        try:
            self.manager.poll_now()
        except Exception as e:  # noqa: BLE001 — typed surface, stream lives
            try:
                self.push({"event": "poll_error",
                           "error": type(e).__name__,
                           "message": str(e)})
            except Exception:
                return 0  # sink broken: frames stay queued, retry next tick
        try:
            return self.manager.flush(self.push)
        except Exception:  # noqa: BLE001 — pump thread must survive
            # a raising sink loses the frame in flight (the connection
            # is broken anyway); undrained frames stay in their bounded
            # outboxes and the next cycle retries instead of the pump
            # thread dying silently
            return 0

    def handle(self, rid, doc: dict) -> None:
        op = doc["op"]
        if self.manager is None and op != "subscribe":
            # only `subscribe` instantiates the manager (and its
            # auto-poll pump): a bare poll / introspection verb on a
            # subscription-less connection answers cheaply, and works
            # against durable-only catalogs too
            if op == "poll":
                self.respond({"id": rid, "ok": True, "applied": {},
                              "frames": 0})
            elif op == "subscriptions":
                self.respond({"id": rid, "ok": True, "subscriptions": 0})
            else:  # unsubscribe with nothing registered
                self.respond({"id": rid, "ok": False, "error": "error",
                              "message": "no such subscription"})
            return
        mgr = self._ensure()
        if op == "subscribe":
            # the manager runs `ack` under its flush lock, so the
            # response (which tells the client the subscription id) is
            # on the wire before any push frame referencing that id
            mgr.subscribe(
                doc["typeName"],
                cql=doc.get("cql", "INCLUDE"),
                density=_parse_density(doc),
                tenant=doc.get("tenant", ""),
                ttl_s=doc.get("ttlS"),
                rate=doc.get("rate"),
                outbox_limit=doc.get("outboxLimit"),
                initial_state=bool(doc.get("initialState", True)),
                handoff=doc.get("handoff"),
                paused=bool(doc.get("paused", False)),
                ack=lambda s: self.respond(
                    {"id": rid, "ok": True,
                     "subscription": s.sub_id, "mode": s.mode,
                     "status": s.status}))
            mgr.flush(self.push)  # deliver the initial state frame
        elif op == "unsubscribe":
            try:
                sub = mgr.unsubscribe(doc["subscription"])
            except KeyError:
                # same typed answer as the manager-less branch — an
                # unknown (or concurrently TTL-expired) id must not
                # leak a bare KeyError message
                self.respond({"id": rid, "ok": False, "error": "error",
                              "message": "no such subscription"})
                return
            mgr.flush(self.push)  # parting frames
            self.respond({"id": rid, "ok": True,
                          "subscription": sub.sub_id,
                          "status": sub.status})
        elif op in ("pause", "resume"):
            # lifecycle verbs for the fleet's re-home path (a paused
            # subscription must land paused on the survivor) and for
            # clients throttling their own streams
            try:
                sub = (mgr.pause if op == "pause"
                       else mgr.resume)(doc["subscription"])
            except KeyError:
                self.respond({"id": rid, "ok": False, "error": "error",
                              "message": "no such subscription"})
                return
            except ValueError as e:  # resume from non-paused, etc.
                self.respond({"id": rid, "ok": False, "error": "error",
                              "message": str(e)})
                return
            if op == "resume":
                mgr.flush(self.push)  # the resume's state resync frame
            self.respond({"id": rid, "ok": True,
                          "subscription": sub.sub_id,
                          "status": sub.status})
        elif op == "poll":
            applied = mgr.poll_now()
            frames = mgr.flush(self.push)
            self.respond({"id": rid, "ok": True, "applied": applied,
                          "frames": frames})
        elif op == "export_subscription":
            # failover handoff (docs/ROBUSTNESS.md): serialize one
            # predicate subscription's matched-set snapshot so the
            # client can re-subscribe against another replica with
            # `handoff` and continue its sequence numbers there
            sub = mgr.registry.maybe(doc.get("subscription"))
            if sub is None:
                self.respond({"id": rid, "ok": False, "error": "error",
                              "message": "no such subscription"})
                return
            try:
                snap = sub.handoff_snapshot()
            except ValueError as e:
                self.respond({"id": rid, "ok": False, "error": "error",
                              "message": str(e)})
                return
            self.respond({"id": rid, "ok": True,
                          "subscription": sub.sub_id, "handoff": snap})
        else:  # subscriptions: introspection
            self.respond({"id": rid, "ok": True, **mgr.stats()})

    def close(self) -> None:
        self._stop.set()
        if self._pump is not None:
            self._pump.join(timeout=5.0)
        if self.manager is not None:
            # final flush so cancelled/expired frames are not lost
            try:
                self.manager.flush(self.push)
            # gt: waive GT14
            # (deliberate degrade: the stream is closing — a broken
            # write sink must not mask the manager close that releases
            # subscriptions; frames at shutdown are best-effort)
            except Exception:
                pass
            self.manager.close()
            if self.svc.subscriptions is self.manager:
                self.svc.subscriptions = None


ADMIN_ROLES = ("router", "admin")

# ops a non-ready replica still answers (health probes, handshakes and
# lifecycle verbs must work WHILE warming/draining — that is the point)
CONTROL_OPS = ("hello", "drain", "stats")


class _WireState:
    """Per-connection columnar-wire state (docs/SERVING.md "Columnar
    wire"): the negotiated session mode, the byte writer shared with
    the line writer under one lock (frames and lines interleave on one
    stream — the framing must never tear), and this connection's
    PushMux sinks. The OWNER sink (its own subscriptions' frames) is
    synchronous so the manager's flush-requeue contract holds; the
    MIRROR sink (frames attached from other connections) is threaded —
    a slow mirror backs up only its own bounded queue."""

    def __init__(self, svc: QueryService, write, write_bytes, out_lock):
        self.svc = svc
        self.write = write
        self.write_bytes = write_bytes
        self.out_lock = out_lock
        self.mode = colwire.WIRE_JSON
        self.mux = None
        # sink registration is reached from TWO threads (the reader
        # thread's poll/subscribe flush and the --live-poll-ms pump):
        # lazy init needs its own guard or a race registers an orphan
        # sink that leaks in the service-wide mux
        self._sink_lock = threading.Lock()
        self.owner_sink: Optional[str] = None
        # one mirror sink per wire MODE: a second attach asking for a
        # different encoding gets its own sink, so the response's
        # wireMode always states the encoding actually delivered
        self.mirror_sinks: dict = {}

    def can_columnar(self) -> bool:
        return self.write_bytes is not None and colwire.have_pyarrow()

    def fallback_reason(self) -> str:
        return ("pyarrow_unavailable" if not colwire.have_pyarrow()
                else "no_binary_sink")

    def request_mode(self, doc: dict) -> str:
        """The wire mode one request resolved to (per-request opt-in
        overrides the session default)."""
        return str(doc.get("wire", self.mode))

    def write_buf(self, buf: bytes) -> None:
        """One encoded frame/line onto the stream, under the same lock
        as respond() — columnar JSON fallback sinks decode to the
        identical text line the legacy path wrote."""
        with self.out_lock:
            if self.write_bytes is not None:
                self.write_bytes(buf)
            else:
                self.write(buf.decode("utf-8"))

    def _mux(self):
        if self.mux is None:
            self.mux = self.svc.wire_mux()
        return self.mux

    def push(self, frame: dict) -> None:
        """Push-frame sink: route through the mux so the frame is
        encoded ONCE and fanned to this connection + attached mirrors
        (the one-encode path holds even for a lone JSON subscriber)."""
        mux = self._mux()
        with self._sink_lock:
            if self.owner_sink is None:
                mode = (self.mode if self.can_columnar()
                        else colwire.WIRE_JSON)
                self.owner_sink = mux.register(
                    self.write_buf, mode=mode, threaded=False)
            owner = self.owner_sink
        mux.route(frame, owner=owner)

    def ensure_mirror(self, mode: str) -> str:
        mux = self._mux()
        with self._sink_lock:
            sink = self.mirror_sinks.get(mode)
            if sink is None:
                sink = mux.register(
                    self.write_buf, mode=mode, threaded=True)
                self.mirror_sinks[mode] = sink
            return sink

    def mirror_detach(self, subscription_id: str) -> None:
        """Detach every mode's mirror sink from one subscription."""
        if self.mux is None:
            return
        with self._sink_lock:
            sinks = list(self.mirror_sinks.values())
        for sink in sinks:
            self.mux.detach(sink, subscription_id)

    def close(self) -> None:
        if self.mux is None:
            return
        with self._sink_lock:
            sinks = [self.owner_sink] + list(self.mirror_sinks.values())
        for sink in sinks:
            if sink is not None:
                self.mux.unregister(sink)


def _handle_ingest(store, rid, doc: dict, payload: Optional[bytes],
                   respond) -> None:
    """Columnar bulk ingest: `{"op": "ingest", "typeName": ...,
    "frame": {...}}` + an Arrow IPC stream payload. Record-batch
    column buffers flow into the store as NumPy views (DataStore.
    write_batch) — no per-feature Python dicts on the write path.
    Raises for the caller's per-request error isolation."""
    if payload is None:
        raise ValueError(
            "op=ingest needs a binary frame payload (an Arrow IPC "
            "stream; see docs/SERVING.md \"Columnar wire\")")
    if not colwire.have_pyarrow():
        respond({"id": rid, "ok": False, "error": "rejected",
                 "reason": "pyarrow_unavailable",
                 "message": "columnar ingest needs pyarrow on the "
                            "server; use the converter ingest path"})
        return
    type_name = doc["typeName"]
    wb = getattr(store, "write_batch", None)
    if wb is not None:
        rows, batches = wb(type_name, payload)
    else:
        # live (Kafka) and other non-DataStore stores have no
        # write_batch — decode here and write per record batch through
        # their own source.write path (the column buffers are still
        # NumPy views; only the dispatch differs)
        from geomesa_tpu.core.arrow_io import ipc_feature_batches

        src = store.get_feature_source(type_name)
        rows = batches = 0
        for fb in ipc_feature_batches(payload, src.sft):
            src.write(fb)
            rows += len(fb)
            batches += 1
    from geomesa_tpu.utils.metrics import metrics

    metrics.counter("wire.ingest.rows", rows)
    metrics.counter("wire.ingest.bytes", len(payload))
    respond({"id": rid, "ok": True, "rows": rows, "batches": batches})


def _handle_attach(svc: QueryService, wire: _WireState, rid, op: str,
                   doc: dict, respond) -> None:
    """`attach`/`detach`: mirror one subscription's push frames onto
    THIS connection (the cross-connection fan-out — the subscription
    itself lives on its owner connection's manager). One evaluation +
    one encode serve every mirror (PushMux)."""
    sub_id = doc.get("subscription")
    mgr = svc.subscriptions
    sub = mgr.registry.maybe(sub_id) if (mgr is not None
                                         and sub_id) else None
    if op == "detach":
        if sub_id:
            wire.mirror_detach(sub_id)
        respond({"id": rid, "ok": True, "subscription": sub_id})
        return
    if sub is None:
        respond({"id": rid, "ok": False, "error": "error",
                 "message": "no such subscription"})
        return
    mode = wire.request_mode(doc)
    out = {"id": rid, "ok": True, "subscription": sub_id}
    if mode == colwire.WIRE_COLUMNAR and not wire.can_columnar():
        mode = colwire.WIRE_JSON
        out["wireFallback"] = wire.fallback_reason()
    sink = wire.ensure_mirror(mode)
    out["sinks"] = svc.wire_mux().attach(sink, sub_id)
    out["wireMode"] = mode
    respond(out)


def serve_lines(
    store,
    lines: Iterable[str],
    write,
    config: Optional[ServeConfig] = None,
    service: Optional[QueryService] = None,
) -> int:
    """Run the JSON-lines loop: submit every request line to a
    QueryService over `store`, write one response line per request via
    `write(str)` as each completes, drain gracefully at end of input.
    Returns the number of requests processed. A caller that needs the
    service before the loop starts (the `--metrics-port` endpoint binds
    its stats provider to it) passes one in; ownership transfers — the
    loop drains and closes it either way.

    The stdin/file conversation is the process owner's, so it is
    admin: `{"op": "drain"}` here drains the service in place (new
    requests answer typed `shutting_down` while in-flight work
    finishes)."""
    svc = service if service is not None else QueryService(store, config)
    try:
        return serve_connection(store, svc, lines, write, admin=True)
    finally:
        svc.close(drain=True)


def serve_connection(
    store,
    svc: QueryService,
    lines: Iterable[str],
    write,
    admin: bool = False,
    control=None,
    write_bytes=None,
    read_bytes=None,
) -> int:
    """One JSON-lines conversation over a SHARED QueryService: the
    replica server runs one of these per accepted socket (the service
    outlives the connection — closing it is the caller's job; contrast
    `serve_lines`, which owns its service). `control` is the replica's
    lifecycle surface (fleet/replica.py): `describe()` feeds the hello
    handshake, `admitting()` gates query traffic on the health state
    machine, `drain()` implements the admin drain verb. `admin` seeds
    the connection's role; a hello with role router/admin upgrades it.

    `write_bytes`/`read_bytes` are the binary-frame transport (socket
    connections pass the JsonLineConn's raw read/write): without them
    the columnar wire downgrades typed to JSON and inbound binary
    frames are refused — a text transport keeps working unchanged."""
    out_lock = threading.Lock()
    processed = 0
    is_admin = admin

    def respond(doc: dict) -> None:
        with out_lock:
            write(json.dumps(doc) + "\n")

    wire = _WireState(svc, write, write_bytes, out_lock)

    def respond_frame(doc: dict, payload: bytes) -> None:
        # ONE buffer, one locked write: the header line and its raw
        # payload can never interleave with a concurrent response
        wire.write_buf(colwire.frame_bytes(doc, payload))

    subs = _SubscribeSession(store, svc, respond, push=wire.push)

    def on_done(rid, req):
        def cb(fut):
            # clock reads only when this request is traced: with
            # tracing off the response path stays stamp-free
            r0_ns = (perf_counter_ns()
                     if req.trace is not None else 0)
            try:
                exc = fut.exception() if not fut.cancelled() else None
                if fut.cancelled():
                    respond({"id": rid, "ok": False, "error": "rejected",
                             "reason": "cancelled", "message": "cancelled"})
                elif exc is not None:
                    respond(_error_response(rid, exc))
                else:
                    limit = req.query.max_features or MAX_FEATURE_ROWS
                    doc = {"id": rid, "ok": True}
                    payload = None
                    if req.wire == colwire.WIRE_COLUMNAR:
                        e0_ns = (perf_counter_ns()
                                 if req.trace is not None else 0)
                        fields, payload = _columnar_payload(
                            req.kind, fut.result(), limit)
                        if payload is not None:
                            doc.update(fields)
                            if req.trace is not None:
                                # the encode span feeds the profiler's
                                # phase.wire.encode sentinel family
                                req.trace.record(
                                    "wire.encode", e0_ns,
                                    perf_counter_ns(), kind=req.kind)
                    if payload is None:
                        doc.update(_payload(req.kind, fut.result(), limit))
                        fb = getattr(req, "wire_fallback", None)
                        if fb is not None:
                            doc["wireFallback"] = fb
                    if req.degraded:
                        doc["degraded"] = True
                    if req.cache_hit:
                        doc["cached"] = True
                    if payload is not None:
                        respond_frame(doc, payload)
                    else:
                        respond(doc)
            finally:
                if req.trace is not None:
                    # serialization + line write, per rider (callbacks
                    # run on the dispatch thread inside set_result, so
                    # this lands within the dispatch window)
                    req.trace.record("respond", r0_ns, perf_counter_ns())

        return cb

    try:
        for line in lines:
            line = line.strip()
            if not line:
                continue
            processed += 1
            rid = None
            try:
                doc = json.loads(line)
                rid = doc.get("id", processed)
                op = doc.get("op")
                payload = None
                fr = doc.get("frame")
                if fr and fr.get("nbytes"):
                    # inbound binary frame: the payload bytes follow
                    # this header line and MUST be consumed before the
                    # next line read, or the stream framing tears
                    if read_bytes is None:
                        raise ValueError(
                            "binary frames need a socket transport; "
                            "this stream is text-only")
                    payload = read_bytes(int(fr["nbytes"]))
                if op == "hello":
                    # replica-role handshake: the response names the
                    # replica + its health state; router/admin roles
                    # upgrade the connection to admin (drain rights).
                    # It also advertises + negotiates the wire: a
                    # columnar ask is honored when pyarrow and a
                    # binary sink exist, downgraded TYPED otherwise
                    role = str(doc.get("role", "client"))
                    if role in ADMIN_ROLES:
                        is_admin = True
                    out = {"id": rid, "ok": True, "role": role,
                           "admin": is_admin,
                           # capability flag: this server understands
                           # subscribe(handoff=) re-homing — a fleet
                           # router checks it before replaying a
                           # standing query here (back-compat: its
                           # absence means pre-upgrade)
                           "rehome": True,
                           "wire": colwire.wire_capabilities()}
                    if doc.get("wire") == colwire.WIRE_COLUMNAR:
                        if wire.can_columnar():
                            wire.mode = colwire.WIRE_COLUMNAR
                            out["wireMode"] = colwire.WIRE_COLUMNAR
                        else:
                            out["wireMode"] = colwire.WIRE_JSON
                            out["wireFallback"] = wire.fallback_reason()
                    if control is not None:
                        out.update(control.describe())
                    respond(out)
                    continue
                if op == "drain":
                    if not is_admin:
                        # lifecycle is the supervisor's, not a
                        # client's: reject typed, keep serving
                        respond({"id": rid, "ok": False,
                                 "error": "rejected",
                                 "reason": "admin_required",
                                 "message": "drain needs an admin "
                                            "connection (hello with "
                                            "role router/admin)"})
                        continue
                    if control is not None:
                        respond({"id": rid, "ok": True,
                                 **control.drain()})
                    else:
                        # standalone serve: drain the service in place
                        # — stop admitting, finish in-flight, close
                        svc.close(drain=True)
                        respond({"id": rid, "ok": True,
                                 "state": "drained"})
                    continue
                if control is not None and op not in CONTROL_OPS:
                    refusal = control.admitting()
                    if refusal is not None:
                        # a replica that is warming (gmtpu warmup
                        # --check not yet green) or draining refuses
                        # traffic TYPED and retryable — the router
                        # redistributes; nothing serves cold
                        respond({"id": rid, "ok": False,
                                 "error": "rejected",
                                 "reason": refusal,
                                 "retryable": True,
                                 "message": f"replica not ready "
                                            f"({refusal})"})
                        continue
                if op == "ingest":
                    _handle_ingest(store, rid, doc, payload, respond)
                    continue
                if op in ("attach", "detach"):
                    _handle_attach(svc, wire, rid, op, doc, respond)
                    continue
                if op in SUBSCRIBE_OPS:
                    subs.handle(rid, doc)
                    continue
                if op == "stats":
                    # introspection verb: the service's live counters
                    # (+ SLO burn report when a spec is loaded) without
                    # a scrape endpoint — wire clients watch their own
                    # error budget on the connection they already hold
                    stats = svc.stats()
                    if control is not None:
                        stats["replica"] = control.describe()
                    if subs.manager is not None:
                        # handoff-checkpoint piggyback (no new RPC):
                        # THIS connection's standing queries, scoped so
                        # a fleet router's stats probe checkpoints
                        # exactly the subscriptions it homed over this
                        # link; the seq-watermark cadence keeps an
                        # unchanged subscription at zero bytes
                        stats["subs_checkpoint"] = (
                            subs.manager.checkpoints())
                    respond({"id": rid, "ok": True, "stats": stats})
                    continue
                req = parse_request(doc, payload)
                if wire.request_mode(doc) == colwire.WIRE_COLUMNAR:
                    if wire.can_columnar():
                        req.wire = colwire.WIRE_COLUMNAR
                    else:
                        # typed downgrade: the JSON response will say
                        # WHY it is not a frame (tests assert this)
                        req.wire_fallback = wire.fallback_reason()
                fut = svc.submit(req)
                fut.add_done_callback(on_done(rid, req))
            except Exception as e:  # noqa: BLE001 — per-request isolation
                respond(_error_response(rid if rid is not None
                                        else processed, e))
    finally:
        subs.close()
        wire.close()
    return processed
