"""`gmtpu serve` wire protocol: JSON-lines request/response.

One JSON object per input line; one JSON response line per request,
written IN COMPLETION ORDER (a coalesced batch completes together; a
shed request answers immediately) — the id field is the correlation
key, exactly like a pipelined wire protocol:

    {"id": "r1", "op": "count", "typeName": "gdelt",
     "cql": "BBOX(geom,-10,-10,10,10)"}
    {"id": "r2", "op": "knn", "typeName": "gdelt", "cql": "INCLUDE",
     "x": [1.5], "y": [2.5], "k": 8}
    {"id": "r3", "op": "query", "typeName": "gdelt", "cql": "...",
     "maxFeatures": 100}

Optional request fields: tenant, priority (interactive|normal|batch),
timeoutMs, allowDegraded. Responses: {"id", "ok": true, ...} with
op-specific payload, or {"id", "ok": false, "error":
rejected|timeout|error, "reason", "message"}.

Standing queries (docs/SERVING.md "Standing queries") ride the same
stream against a live (Kafka) store:

    {"id": "s1", "op": "subscribe", "typeName": "vessels",
     "cql": "DWITHIN(geom, POINT(0 0), 50000, meters)", "ttlS": 600}
    {"id": "s2", "op": "subscribe", "typeName": "vessels",
     "density": {"bbox": [-180,-90,180,90], "width": 256, "height": 128}}
    {"id": "p1", "op": "poll"}
    {"id": "u1", "op": "unsubscribe", "subscription": "sub-1"}

The subscribe response carries the subscription id; from then on the
server interleaves PUSH FRAMES — JSON objects with an "event" field
instead of an "id" — into the response stream as Kafka batches fold
in: `enter`/`exit` (geofence transitions, fid lists), `density`
(window totals), `state` (full re-sync after lag), and the typed
`subscription_lagged` / `expired` / `quarantined` lifecycle frames.
`poll` folds pending Kafka messages synchronously and flushes
outboxes (deterministic clients; the `--live-poll-ms` pump does it on
a cadence otherwise).

Introspection: `{"id": "i1", "op": "stats"}` answers with the
service's live counters (queue depth, dispatch/coalesce totals,
quarantine, pipeline — and the SLO burn report when `--slo` loaded a
spec), so a wire client can watch its own error budget without a
separate metrics scrape.

Fleet verbs (docs/SERVING.md "Replica fleets"): `{"op": "hello",
"role": "router"}` is the replica-role handshake — the response
carries the replica's id + health state, and a `router`/`admin` role
marks the CONNECTION admin. `{"op": "drain"}` (admin-only; rejected
typed on plain client connections) drains gracefully: stop admitting,
finish every in-flight request, then close — so the fleet router and
`gmtpu fleet restart` never need process signals. A replica that is
not `ready` (warming until `gmtpu warmup --check` semantics pass, or
draining) refuses query traffic with a typed, retryable rejection
instead of serving cold or torn.

Errors are per-request, never fatal to the stream: a malformed line
yields an ok=false response and the loop continues — one bad client
request must not drop everyone else's connection.
"""

from __future__ import annotations

import json
import math
import threading
from time import perf_counter_ns
from typing import Iterable, Optional

import numpy as np

from geomesa_tpu.plan.planner import QueryTimeout
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve.scheduler import (
    PRIORITIES, QueryRejected, ServeRequest)
from geomesa_tpu.serve.service import QueryService, ServeConfig

MAX_FEATURE_ROWS = 10_000  # response-size guard for op=query


def _finite(v: float):
    return None if (isinstance(v, float) and not math.isfinite(v)) else v


def _rows_json(batch, limit: int):
    """Feature rows as JSON dicts (geometry as WKT), capped at `limit`."""
    from geomesa_tpu.core.columnar import DictColumn, GeometryColumn
    from geomesa_tpu.core.wkt import to_wkt

    if batch is None or len(batch) == 0:
        return []
    n = min(len(batch), limit)
    names = batch.sft.attribute_names
    cols = {}
    for name in names:
        col = batch.columns[name]
        if isinstance(col, GeometryColumn):
            cols[name] = col
        elif isinstance(col, DictColumn):
            cols[name] = col.decode()
        else:
            cols[name] = np.asarray(col)
    rows = []
    for i in range(n):
        row = {}
        for name in names:
            col = batch.columns[name]
            m = cols[name]
            if isinstance(col, GeometryColumn):
                row[name] = (f"POINT ({m.x[i]} {m.y[i]})" if m.is_point
                             else to_wkt(m.geometry(i)))
            elif isinstance(col, DictColumn):
                row[name] = m[i]
            else:
                v = m[i].item()
                row[name] = _finite(v) if isinstance(v, float) else v
        rows.append(row)
    return rows


def _payload(kind: str, result, limit: int) -> dict:
    if kind == "count":
        doc = {"count": int(result)}
        if getattr(result, "approx", False):
            # typed error bound on the wire (docs/SERVING.md
            # "Approximate answers"): the exact count is guaranteed in
            # [count - bound, count + bound]
            doc["approx"] = True
            doc["bound"] = result.bound
            doc["confidence"] = result.confidence
        return doc
    if kind == "knn":
        dists, idx, _batch = result
        return {
            "dists": [[_finite(float(d)) for d in row] for row in dists],
            "indices": [[int(j) for j in row] for row in idx],
        }
    out = {"kind": result.kind, "count": int(result.count)}
    if result.kind == "features":
        feats = result.features
        out["count"] = len(feats) if feats is not None else 0
        out["features"] = _rows_json(feats, limit)
    elif result.kind == "density" and result.grid is not None:
        out["shape"] = list(result.grid.shape)
        out["total"] = float(result.grid.sum())
    elif result.kind == "stats":
        out["stats"] = str(result.stats)
    elif result.kind == "topk_cells":
        out["cells"] = result.stats
    if getattr(result, "approx", False):
        out["approx"] = True
        out["bound"] = float(result.bound)
        out["confidence"] = float(result.confidence)
    return out


def parse_request(doc: dict) -> ServeRequest:
    op = doc.get("op", "query")
    kind = {"query": "execute", "execute": "execute",
            "count": "count", "knn": "knn"}.get(op)
    if kind is None:
        raise ValueError(f"unknown op {op!r}")
    type_name = doc["typeName"]
    kw = {}
    if doc.get("tolerance") is not None or doc.get("topkCells"):
        # approximate-answer tier hints (docs/SERVING.md "Approximate
        # answers"): tolerance = the client's accuracy contract,
        # topkCells = the sketch-native top-k-cells aggregation
        from geomesa_tpu.plan.hints import QueryHints

        kw["hints"] = QueryHints(
            tolerance=(float(doc["tolerance"])
                       if doc.get("tolerance") is not None else None),
            topk_cells=(int(doc["topkCells"])
                        if doc.get("topkCells") else None))
    query = Query(type_name, doc.get("cql", "INCLUDE"),
                  max_features=doc.get("maxFeatures"), **kw)
    priority = doc.get("priority", "normal")
    if isinstance(priority, str):
        priority = PRIORITIES.index(priority)
    req = ServeRequest(
        kind=kind, query=query, tenant=doc.get("tenant", ""),
        priority=priority,
        allow_degraded=bool(doc.get("allowDegraded", False)),
    )
    timeout_ms = doc.get("timeoutMs")
    if timeout_ms:
        import time

        req.deadline = time.monotonic() + float(timeout_ms) / 1000.0
    if kind == "knn":
        req.qx = np.asarray(doc["x"], np.float64)
        req.qy = np.asarray(doc["y"], np.float64)
        if req.qx.shape != req.qy.shape or req.qx.ndim != 1:
            raise ValueError("knn x/y must be equal-length 1-d arrays")
        req.k = int(doc.get("k", 10))
        req.impl = doc.get("impl", "sparse")
    return req


def _error_response(rid, exc) -> dict:
    from geomesa_tpu.faults import BreakerOpen

    if isinstance(exc, QueryRejected):
        return {"id": rid, "ok": False, "error": "rejected",
                "reason": exc.reason, "message": str(exc)}
    if isinstance(exc, BreakerOpen):
        # fail-fast dependency outage: tell the client WHEN to retry —
        # the three-way rejected/timeout/error split gains a fourth leg
        # for "not you, not your query: the backend is resting"
        return {"id": rid, "ok": False, "error": "unavailable",
                "reason": exc.reason,
                "retryAfterS": round(exc.retry_after_s, 3),
                "message": str(exc)}
    if isinstance(exc, QueryTimeout):
        return {"id": rid, "ok": False, "error": "timeout",
                "phase": exc.phase, "message": str(exc)}
    return {"id": rid, "ok": False, "error": "error", "message": str(exc)}


SUBSCRIBE_OPS = ("subscribe", "unsubscribe", "poll", "subscriptions")


def _parse_density(doc: dict):
    """The density window of a subscribe request, or None."""
    d = doc.get("density")
    if d is None:
        return None
    from geomesa_tpu.subscribe import DensityWindow

    return DensityWindow(
        bbox=tuple(float(v) for v in d["bbox"]),
        width=int(d["width"]), height=int(d["height"]),
        weight_attr=d.get("weight"), decay=d.get("decay"),
        tolerance=(float(d["tolerance"])
                   if d.get("tolerance") is not None else None))


class _SubscribeSession:
    """Per-connection standing-query state: lazily creates the
    SubscriptionManager on the first subscribe verb (sharing the
    QueryService's tenant buckets and quarantine tuning), runs the
    auto-poll pump when configured, and flushes outboxes into the
    response stream."""

    def __init__(self, store, svc: QueryService, respond):
        self.store = store
        self.svc = svc
        self.respond = respond
        self.manager = None
        self._stop = threading.Event()
        self._pump = None

    def _ensure(self):
        if self.manager is not None:
            return self.manager
        if not hasattr(self.store, "poll"):
            raise ValueError(
                "standing queries need a live (Kafka) store; this "
                "catalog is durable-only")
        from geomesa_tpu.subscribe import (
            SubscribeConfig, SubscriptionManager)

        cfg = self.svc.config
        self.manager = SubscriptionManager(
            self.store,
            SubscribeConfig(
                max_subscriptions=cfg.subscribe_max,
                outbox_limit=cfg.subscribe_outbox,
                rate=cfg.subscribe_rate,
                quarantine_after=cfg.quarantine_after,
                quarantine_ttl_s=cfg.quarantine_ttl_s,
            ),
            limiter=self.svc.limiter)
        if self.svc.subscriptions is None:
            # stats surface: first manager wins; close() clears it —
            # a later connection must not shadow a live one, and a
            # closed one must not keep reporting a dead registry
            self.svc.subscriptions = self.manager
        if cfg.subscribe_poll_ms:
            self._pump = threading.Thread(
                target=self._pump_loop, name="gmtpu-subscribe-pump",
                daemon=True)
            self._pump.start()
        return self.manager

    def _pump_loop(self):
        interval = self.svc.config.subscribe_poll_ms / 1000.0
        while not self._stop.wait(interval):
            self.pump_once()

    def pump_once(self) -> int:
        """One poll + flush cycle. Typed broker errors surface as a
        `poll_error` frame — the stream stays alive, the client knows
        events are delayed, and the next cycle retries. The flush is
        guarded too: one raising write must not silently kill the pump
        thread and strand a live connection event-less."""
        if self.manager is None:
            return 0
        try:
            self.manager.poll_now()
        except Exception as e:  # noqa: BLE001 — typed surface, stream lives
            try:
                self.respond({"event": "poll_error",
                              "error": type(e).__name__,
                              "message": str(e)})
            except Exception:
                return 0  # sink broken: frames stay queued, retry next tick
        try:
            return self.manager.flush(self.respond)
        except Exception:  # noqa: BLE001 — pump thread must survive
            # a raising sink loses the frame in flight (the connection
            # is broken anyway); undrained frames stay in their bounded
            # outboxes and the next cycle retries instead of the pump
            # thread dying silently
            return 0

    def handle(self, rid, doc: dict) -> None:
        op = doc["op"]
        if self.manager is None and op != "subscribe":
            # only `subscribe` instantiates the manager (and its
            # auto-poll pump): a bare poll / introspection verb on a
            # subscription-less connection answers cheaply, and works
            # against durable-only catalogs too
            if op == "poll":
                self.respond({"id": rid, "ok": True, "applied": {},
                              "frames": 0})
            elif op == "subscriptions":
                self.respond({"id": rid, "ok": True, "subscriptions": 0})
            else:  # unsubscribe with nothing registered
                self.respond({"id": rid, "ok": False, "error": "error",
                              "message": "no such subscription"})
            return
        mgr = self._ensure()
        if op == "subscribe":
            # the manager runs `ack` under its flush lock, so the
            # response (which tells the client the subscription id) is
            # on the wire before any push frame referencing that id
            mgr.subscribe(
                doc["typeName"],
                cql=doc.get("cql", "INCLUDE"),
                density=_parse_density(doc),
                tenant=doc.get("tenant", ""),
                ttl_s=doc.get("ttlS"),
                rate=doc.get("rate"),
                outbox_limit=doc.get("outboxLimit"),
                initial_state=bool(doc.get("initialState", True)),
                ack=lambda s: self.respond(
                    {"id": rid, "ok": True,
                     "subscription": s.sub_id, "mode": s.mode}))
            mgr.flush(self.respond)  # deliver the initial state frame
        elif op == "unsubscribe":
            try:
                sub = mgr.unsubscribe(doc["subscription"])
            except KeyError:
                # same typed answer as the manager-less branch — an
                # unknown (or concurrently TTL-expired) id must not
                # leak a bare KeyError message
                self.respond({"id": rid, "ok": False, "error": "error",
                              "message": "no such subscription"})
                return
            mgr.flush(self.respond)  # parting frames
            self.respond({"id": rid, "ok": True,
                          "subscription": sub.sub_id,
                          "status": sub.status})
        elif op == "poll":
            applied = mgr.poll_now()
            frames = mgr.flush(self.respond)
            self.respond({"id": rid, "ok": True, "applied": applied,
                          "frames": frames})
        else:  # subscriptions: introspection
            self.respond({"id": rid, "ok": True, **mgr.stats()})

    def close(self) -> None:
        self._stop.set()
        if self._pump is not None:
            self._pump.join(timeout=5.0)
        if self.manager is not None:
            # final flush so cancelled/expired frames are not lost
            try:
                self.manager.flush(self.respond)
            # gt: waive GT14
            # (deliberate degrade: the stream is closing — a broken
            # write sink must not mask the manager close that releases
            # subscriptions; frames at shutdown are best-effort)
            except Exception:
                pass
            self.manager.close()
            if self.svc.subscriptions is self.manager:
                self.svc.subscriptions = None


ADMIN_ROLES = ("router", "admin")

# ops a non-ready replica still answers (health probes, handshakes and
# lifecycle verbs must work WHILE warming/draining — that is the point)
CONTROL_OPS = ("hello", "drain", "stats")


def serve_lines(
    store,
    lines: Iterable[str],
    write,
    config: Optional[ServeConfig] = None,
    service: Optional[QueryService] = None,
) -> int:
    """Run the JSON-lines loop: submit every request line to a
    QueryService over `store`, write one response line per request via
    `write(str)` as each completes, drain gracefully at end of input.
    Returns the number of requests processed. A caller that needs the
    service before the loop starts (the `--metrics-port` endpoint binds
    its stats provider to it) passes one in; ownership transfers — the
    loop drains and closes it either way.

    The stdin/file conversation is the process owner's, so it is
    admin: `{"op": "drain"}` here drains the service in place (new
    requests answer typed `shutting_down` while in-flight work
    finishes)."""
    svc = service if service is not None else QueryService(store, config)
    try:
        return serve_connection(store, svc, lines, write, admin=True)
    finally:
        svc.close(drain=True)


def serve_connection(
    store,
    svc: QueryService,
    lines: Iterable[str],
    write,
    admin: bool = False,
    control=None,
) -> int:
    """One JSON-lines conversation over a SHARED QueryService: the
    replica server runs one of these per accepted socket (the service
    outlives the connection — closing it is the caller's job; contrast
    `serve_lines`, which owns its service). `control` is the replica's
    lifecycle surface (fleet/replica.py): `describe()` feeds the hello
    handshake, `admitting()` gates query traffic on the health state
    machine, `drain()` implements the admin drain verb. `admin` seeds
    the connection's role; a hello with role router/admin upgrades
    it."""
    out_lock = threading.Lock()
    processed = 0
    is_admin = admin

    def respond(doc: dict) -> None:
        with out_lock:
            write(json.dumps(doc) + "\n")

    subs = _SubscribeSession(store, svc, respond)

    def on_done(rid, req):
        def cb(fut):
            # clock reads only when this request is traced: with
            # tracing off the response path stays stamp-free
            r0_ns = (perf_counter_ns()
                     if req.trace is not None else 0)
            try:
                exc = fut.exception() if not fut.cancelled() else None
                if fut.cancelled():
                    respond({"id": rid, "ok": False, "error": "rejected",
                             "reason": "cancelled", "message": "cancelled"})
                elif exc is not None:
                    respond(_error_response(rid, exc))
                else:
                    limit = req.query.max_features or MAX_FEATURE_ROWS
                    doc = {"id": rid, "ok": True}
                    doc.update(_payload(req.kind, fut.result(), limit))
                    if req.degraded:
                        doc["degraded"] = True
                    if req.cache_hit:
                        doc["cached"] = True
                    respond(doc)
            finally:
                if req.trace is not None:
                    # serialization + line write, per rider (callbacks
                    # run on the dispatch thread inside set_result, so
                    # this lands within the dispatch window)
                    req.trace.record("respond", r0_ns, perf_counter_ns())

        return cb

    try:
        for line in lines:
            line = line.strip()
            if not line:
                continue
            processed += 1
            rid = None
            try:
                doc = json.loads(line)
                rid = doc.get("id", processed)
                op = doc.get("op")
                if op == "hello":
                    # replica-role handshake: the response names the
                    # replica + its health state; router/admin roles
                    # upgrade the connection to admin (drain rights)
                    role = str(doc.get("role", "client"))
                    if role in ADMIN_ROLES:
                        is_admin = True
                    out = {"id": rid, "ok": True, "role": role,
                           "admin": is_admin}
                    if control is not None:
                        out.update(control.describe())
                    respond(out)
                    continue
                if op == "drain":
                    if not is_admin:
                        # lifecycle is the supervisor's, not a
                        # client's: reject typed, keep serving
                        respond({"id": rid, "ok": False,
                                 "error": "rejected",
                                 "reason": "admin_required",
                                 "message": "drain needs an admin "
                                            "connection (hello with "
                                            "role router/admin)"})
                        continue
                    if control is not None:
                        respond({"id": rid, "ok": True,
                                 **control.drain()})
                    else:
                        # standalone serve: drain the service in place
                        # — stop admitting, finish in-flight, close
                        svc.close(drain=True)
                        respond({"id": rid, "ok": True,
                                 "state": "drained"})
                    continue
                if control is not None and op not in CONTROL_OPS:
                    refusal = control.admitting()
                    if refusal is not None:
                        # a replica that is warming (gmtpu warmup
                        # --check not yet green) or draining refuses
                        # traffic TYPED and retryable — the router
                        # redistributes; nothing serves cold
                        respond({"id": rid, "ok": False,
                                 "error": "rejected",
                                 "reason": refusal,
                                 "retryable": True,
                                 "message": f"replica not ready "
                                            f"({refusal})"})
                        continue
                if op in SUBSCRIBE_OPS:
                    subs.handle(rid, doc)
                    continue
                if op == "stats":
                    # introspection verb: the service's live counters
                    # (+ SLO burn report when a spec is loaded) without
                    # a scrape endpoint — wire clients watch their own
                    # error budget on the connection they already hold
                    stats = svc.stats()
                    if control is not None:
                        stats["replica"] = control.describe()
                    respond({"id": rid, "ok": True, "stats": stats})
                    continue
                req = parse_request(doc)
                fut = svc.submit(req)
                fut.add_done_callback(on_done(rid, req))
            except Exception as e:  # noqa: BLE001 — per-request isolation
                respond(_error_response(rid if rid is not None
                                        else processed, e))
    finally:
        subs.close()
    return processed
