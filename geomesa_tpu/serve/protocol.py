"""`gmtpu serve` wire protocol: JSON-lines request/response.

One JSON object per input line; one JSON response line per request,
written IN COMPLETION ORDER (a coalesced batch completes together; a
shed request answers immediately) — the id field is the correlation
key, exactly like a pipelined wire protocol:

    {"id": "r1", "op": "count", "typeName": "gdelt",
     "cql": "BBOX(geom,-10,-10,10,10)"}
    {"id": "r2", "op": "knn", "typeName": "gdelt", "cql": "INCLUDE",
     "x": [1.5], "y": [2.5], "k": 8}
    {"id": "r3", "op": "query", "typeName": "gdelt", "cql": "...",
     "maxFeatures": 100}

Optional request fields: tenant, priority (interactive|normal|batch),
timeoutMs, allowDegraded. Responses: {"id", "ok": true, ...} with
op-specific payload, or {"id", "ok": false, "error":
rejected|timeout|error, "reason", "message"}.

Errors are per-request, never fatal to the stream: a malformed line
yields an ok=false response and the loop continues — one bad client
request must not drop everyone else's connection.
"""

from __future__ import annotations

import json
import math
import threading
from time import perf_counter_ns
from typing import Iterable, Optional

import numpy as np

from geomesa_tpu.plan.planner import QueryTimeout
from geomesa_tpu.plan.query import Query
from geomesa_tpu.serve.scheduler import (
    PRIORITIES, QueryRejected, ServeRequest)
from geomesa_tpu.serve.service import QueryService, ServeConfig

MAX_FEATURE_ROWS = 10_000  # response-size guard for op=query


def _finite(v: float):
    return None if (isinstance(v, float) and not math.isfinite(v)) else v


def _rows_json(batch, limit: int):
    """Feature rows as JSON dicts (geometry as WKT), capped at `limit`."""
    from geomesa_tpu.core.columnar import DictColumn, GeometryColumn
    from geomesa_tpu.core.wkt import to_wkt

    if batch is None or len(batch) == 0:
        return []
    n = min(len(batch), limit)
    names = batch.sft.attribute_names
    cols = {}
    for name in names:
        col = batch.columns[name]
        if isinstance(col, GeometryColumn):
            cols[name] = col
        elif isinstance(col, DictColumn):
            cols[name] = col.decode()
        else:
            cols[name] = np.asarray(col)
    rows = []
    for i in range(n):
        row = {}
        for name in names:
            col = batch.columns[name]
            m = cols[name]
            if isinstance(col, GeometryColumn):
                row[name] = (f"POINT ({m.x[i]} {m.y[i]})" if m.is_point
                             else to_wkt(m.geometry(i)))
            elif isinstance(col, DictColumn):
                row[name] = m[i]
            else:
                v = m[i].item()
                row[name] = _finite(v) if isinstance(v, float) else v
        rows.append(row)
    return rows


def _payload(kind: str, result, limit: int) -> dict:
    if kind == "count":
        return {"count": int(result)}
    if kind == "knn":
        dists, idx, _batch = result
        return {
            "dists": [[_finite(float(d)) for d in row] for row in dists],
            "indices": [[int(j) for j in row] for row in idx],
        }
    out = {"kind": result.kind, "count": int(result.count)}
    if result.kind == "features":
        feats = result.features
        out["count"] = len(feats) if feats is not None else 0
        out["features"] = _rows_json(feats, limit)
    elif result.kind == "density" and result.grid is not None:
        out["shape"] = list(result.grid.shape)
        out["total"] = float(result.grid.sum())
    elif result.kind == "stats":
        out["stats"] = str(result.stats)
    return out


def parse_request(doc: dict) -> ServeRequest:
    op = doc.get("op", "query")
    kind = {"query": "execute", "execute": "execute",
            "count": "count", "knn": "knn"}.get(op)
    if kind is None:
        raise ValueError(f"unknown op {op!r}")
    type_name = doc["typeName"]
    query = Query(type_name, doc.get("cql", "INCLUDE"),
                  max_features=doc.get("maxFeatures"))
    priority = doc.get("priority", "normal")
    if isinstance(priority, str):
        priority = PRIORITIES.index(priority)
    req = ServeRequest(
        kind=kind, query=query, tenant=doc.get("tenant", ""),
        priority=priority,
        allow_degraded=bool(doc.get("allowDegraded", False)),
    )
    timeout_ms = doc.get("timeoutMs")
    if timeout_ms:
        import time

        req.deadline = time.monotonic() + float(timeout_ms) / 1000.0
    if kind == "knn":
        req.qx = np.asarray(doc["x"], np.float64)
        req.qy = np.asarray(doc["y"], np.float64)
        if req.qx.shape != req.qy.shape or req.qx.ndim != 1:
            raise ValueError("knn x/y must be equal-length 1-d arrays")
        req.k = int(doc.get("k", 10))
        req.impl = doc.get("impl", "sparse")
    return req


def _error_response(rid, exc) -> dict:
    from geomesa_tpu.faults import BreakerOpen

    if isinstance(exc, QueryRejected):
        return {"id": rid, "ok": False, "error": "rejected",
                "reason": exc.reason, "message": str(exc)}
    if isinstance(exc, BreakerOpen):
        # fail-fast dependency outage: tell the client WHEN to retry —
        # the three-way rejected/timeout/error split gains a fourth leg
        # for "not you, not your query: the backend is resting"
        return {"id": rid, "ok": False, "error": "unavailable",
                "reason": exc.reason,
                "retryAfterS": round(exc.retry_after_s, 3),
                "message": str(exc)}
    if isinstance(exc, QueryTimeout):
        return {"id": rid, "ok": False, "error": "timeout",
                "phase": exc.phase, "message": str(exc)}
    return {"id": rid, "ok": False, "error": "error", "message": str(exc)}


def serve_lines(
    store,
    lines: Iterable[str],
    write,
    config: Optional[ServeConfig] = None,
    service: Optional[QueryService] = None,
) -> int:
    """Run the JSON-lines loop: submit every request line to a
    QueryService over `store`, write one response line per request via
    `write(str)` as each completes, drain gracefully at end of input.
    Returns the number of requests processed. A caller that needs the
    service before the loop starts (the `--metrics-port` endpoint binds
    its stats provider to it) passes one in; ownership transfers — the
    loop drains and closes it either way."""
    svc = service if service is not None else QueryService(store, config)
    out_lock = threading.Lock()
    processed = 0

    def respond(doc: dict) -> None:
        with out_lock:
            write(json.dumps(doc) + "\n")

    def on_done(rid, req):
        def cb(fut):
            # clock reads only when this request is traced: with
            # tracing off the response path stays stamp-free
            r0_ns = (perf_counter_ns()
                     if req.trace is not None else 0)
            try:
                exc = fut.exception() if not fut.cancelled() else None
                if fut.cancelled():
                    respond({"id": rid, "ok": False, "error": "rejected",
                             "reason": "cancelled", "message": "cancelled"})
                elif exc is not None:
                    respond(_error_response(rid, exc))
                else:
                    limit = req.query.max_features or MAX_FEATURE_ROWS
                    doc = {"id": rid, "ok": True}
                    doc.update(_payload(req.kind, fut.result(), limit))
                    if req.degraded:
                        doc["degraded"] = True
                    respond(doc)
            finally:
                if req.trace is not None:
                    # serialization + line write, per rider (callbacks
                    # run on the dispatch thread inside set_result, so
                    # this lands within the dispatch window)
                    req.trace.record("respond", r0_ns, perf_counter_ns())

        return cb

    try:
        for line in lines:
            line = line.strip()
            if not line:
                continue
            processed += 1
            rid = None
            try:
                doc = json.loads(line)
                rid = doc.get("id", processed)
                req = parse_request(doc)
                fut = svc.submit(req)
                fut.add_done_callback(on_done(rid, req))
            except Exception as e:  # noqa: BLE001 — per-request isolation
                respond(_error_response(rid if rid is not None
                                        else processed, e))
    finally:
        svc.close(drain=True)
    return processed
