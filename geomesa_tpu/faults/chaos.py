"""`gmtpu chaos`: run a serve workload under a fault plan and prove the
recovery invariants hold.

The runner synthesizes (or opens) a store, starts a QueryService, and
drives a DETERMINISTIC sequential workload — FS counts/kNN/feature
fetches, FS writes, Kafka live-layer writes and polls, a compile-cache
enable — with the given FaultPlan installed. Sequential submission plus
coalescing-off config keeps every site's call sequence reproducible, so
the same plan+seed injects the same faults at the same calls; `--check`
replays the run and diffs the fire logs to prove it.

Invariants asserted (the acceptance contract, docs/ROBUSTNESS.md):

  1. zero un-typed escapes: every request resolves with a result or an
     error the taxonomy recognizes (QueryRejected / QueryTimeout /
     BreakerOpen / OSError-family / FaultInjected ...);
  2. zero torn manifests: after the run, metadata.json parses and every
     entry references an existing data file with a matching row count;
  3. injected coverage: every deterministic rule (nth_call / every) in
     the plan actually fired;
  4. breaker visibility: each dependency the plan names in
     `expect_breakers` shows open AND half-open transitions in metrics
     (the runner shrinks reset timeouts so the full closed -> open ->
     half-open -> closed cycle plays out in-process);
  5. graceful drain still completes and the dispatch thread survives;
  6. disabled-harness overhead: the no-op site check stays sub-µs-ish
     (bounded loosely so CI noise cannot flake it).
"""

from __future__ import annotations

import dataclasses
import json
import os
import sys
import tempfile
import time
from typing import Dict, List, Optional

import numpy as np

from geomesa_tpu.faults import errors as _errors
from geomesa_tpu.faults import harness as _harness
from geomesa_tpu.faults.breaker import BREAKERS
from geomesa_tpu.faults.plan import FaultPlan

# dependencies whose breakers the runner re-configures for fast
# in-process open -> half-open -> close cycles. reset_timeout_s=0 makes
# every open -> half-open transition happen on the NEXT gate instead of
# after a wall-clock wait: the full cycle still exercises all three
# states AND the fire sequence stays independent of run timing (run 1
# pays jit compiles, the replay doesn't — a real timeout would make the
# two runs' probe schedules diverge and break replay determinism)
_DEPS = ("storage", "kafka", "device")
_CHAOS_BREAKER = dict(failure_threshold=3, reset_timeout_s=0.0,
                      half_open_max=1)
_NOOP_CALLS = 200_000
_NOOP_BUDGET_US = 5.0  # per-call bound; a no-op attr check is ~0.1µs


@dataclasses.dataclass
class ChaosReport:
    requests: int = 0
    ok: int = 0
    typed_errors: Dict[str, int] = dataclasses.field(default_factory=dict)
    untyped_errors: List[str] = dataclasses.field(default_factory=list)
    writes_ok: int = 0
    writes_failed: int = 0
    fires: int = 0
    fired_sites: List[str] = dataclasses.field(default_factory=list)
    breaker_counters: Dict[str, float] = dataclasses.field(
        default_factory=dict)
    replay_match: Optional[bool] = None
    noop_us_per_call: float = 0.0
    invariant_failures: List[str] = dataclasses.field(default_factory=list)

    @property
    def ok_overall(self) -> bool:
        return not self.invariant_failures

    def to_json(self) -> dict:
        doc = dataclasses.asdict(self)
        # `ok` in the JSON is the invariant VERDICT (what --check exits
        # on); the per-request success count moves to `requests_ok` so
        # the two never shadow each other
        doc["requests_ok"] = doc.pop("ok")
        doc["ok"] = self.ok_overall
        return doc


def _synth_store(root: str, n: int = 384, seed: int = 5,
                 use_device_cache: bool = False):
    """A small FS store on the SCAN path (no device cache): every query
    re-reads partition files, so storage faults keep biting. The mesh
    phase flips `use_device_cache` on — mesh residency is a device-cache
    tier."""
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.plan.datastore import DataStore

    rng = np.random.default_rng(seed)
    sft = SimpleFeatureType.from_spec(
        "chaos", "name:String,score:Double,dtg:Date,*geom:Point")
    store = DataStore(root, use_device_cache=use_device_cache)
    src = store.create_schema(sft)
    src.write(_synth_batch(sft, rng, n))
    return store, sft


def _synth_batch(sft, rng, n):
    from geomesa_tpu.core.columnar import FeatureBatch

    # one-day dtg window -> one date partition (a handful of files, not
    # one per day: the workload's read sequence stays small and exact)
    return FeatureBatch.from_pydict(sft, {
        "name": rng.choice(["a", "b", "c"], n).tolist(),
        "score": rng.uniform(-10, 10, n),
        "dtg": rng.integers(1_590_000_000_000, 1_590_080_000_000, n),
        "geom": np.stack(
            [rng.uniform(-170, 170, n), rng.uniform(-80, 80, n)], 1),
    })


def _check_manifest(root: str, type_name: str, failures: List[str]) -> None:
    import pyarrow.parquet as pq

    meta_path = os.path.join(root, type_name, "metadata.json")
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except Exception as e:  # torn / unparseable manifest IS the failure
        failures.append(f"manifest unreadable: {e}")
        return
    for pname, entries in meta.get("manifest", {}).items():
        for entry in entries:
            path = os.path.join(root, type_name, pname, entry["file"])
            if not os.path.exists(path):
                failures.append(
                    f"manifest references missing file {path}")
                continue
            try:
                rows = pq.read_metadata(path).num_rows
            except Exception as e:
                failures.append(f"unreadable data file {path}: {e}")
                continue
            if rows != entry["count"]:
                failures.append(
                    f"manifest count {entry['count']} != file rows "
                    f"{rows} for {path}")


def _run_workload(plan: FaultPlan, root: str, requests: int,
                  report: ChaosReport, say) -> List[tuple]:
    """One seeded pass: build stores, serve the request mix under the
    installed harness, close, validate the manifest. Returns the fire
    log (the replay-determinism artifact)."""
    from geomesa_tpu.compilecache.persist import persistent_cache_dir
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.kafka.store import KafkaDataStore
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    store, sft = _synth_store(os.path.join(root, "cat"))
    live_sft = SimpleFeatureType.from_spec(
        "chaos_live", "name:String,*geom:Point")
    kstore = KafkaDataStore()
    ksrc = kstore.create_schema(live_sft)
    rng = np.random.default_rng(plan.seed + 17)
    qpts = rng.uniform(-60, 60, (requests, 2))
    cql = "BBOX(geom, -170, -80, 170, 80)"
    prior_cache = persistent_cache_dir()

    prior_breakers = {name: BREAKERS.current_config(name)
                      for name in _DEPS}
    svc = None

    def outcome(fn):
        report.requests += 1
        try:
            fn()
            report.ok += 1
        except Exception as e:  # noqa: BLE001 — the taxonomy decides
            if _errors.is_typed(e):
                key = type(e).__name__
                report.typed_errors[key] = (
                    report.typed_errors.get(key, 0) + 1)
            else:
                report.untyped_errors.append(f"{type(e).__name__}: {e}")

    # everything that mutates process-wide state (breaker tuning, the
    # service's dispatch thread, the harness) happens INSIDE this try:
    # a setup failure — e.g. another harness already installed — must
    # not leak chaos breakers or an orphaned dispatcher into the process
    try:
        for name in _DEPS:
            BREAKERS.configure(name, **_CHAOS_BREAKER)
        svc = QueryService(store, ServeConfig(
            max_wait_ms=0.0, max_batch=1, drain_timeout_s=30.0))
        log = _drive(plan, root, requests, report, svc, store, sft,
                     kstore, ksrc, qpts, cql, rng, outcome)
    finally:
        if svc is not None:
            try:
                svc.close(drain=False)
            except Exception:
                pass
        for name in _DEPS:
            # hand back whatever tuning the process had, not the
            # constructor defaults
            BREAKERS.restore_config(name, prior_breakers[name])
        # cache restore runs HERE — after _drive's harness context has
        # exited — so a plan injecting at compilecache.persist cannot
        # swallow the restore (enable degrades to None under injection
        # by contract). prior_cache came from persistent_cache_dir(),
        # which is ALREADY platform-suffixed: per_platform=False, or
        # the restore would point jax at <dir>/<backend>/<backend> and
        # orphan every previously persisted executable.
        from geomesa_tpu.compilecache.persist import (
            disable_persistent_cache, enable_persistent_cache)

        disable_persistent_cache()
        if prior_cache is not None:
            enable_persistent_cache(cache_dir=prior_cache,
                                    per_platform=False, force=True)
    _check_manifest(os.path.join(root, "cat"), "chaos",
                    report.invariant_failures)
    # pipeline-drain phase: a device.transfer fault fired MID-pipeline
    # (other windows in flight) must fail only its own window — typed —
    # while every other in-flight window drains cleanly. Runs in its
    # own harness scope (per-activation site counters keep it
    # deterministic regardless of the legacy phase's call counts); its
    # fires append to the returned log so the replay diff covers it.
    log += _pipeline_burst(plan, root, report, say)
    # standing-query phase: an injected kafka.poll outage must surface
    # TYPED from the poll, and the subscription event streams must show
    # zero missed / zero double-applied events across the outage — the
    # failed window's messages arrive exactly once when the broker
    # heals (offset-pinned fold + retained delta buffer). Own harness
    # scope; fires append to the replay-diffed log.
    log += _subscribe_phase(plan, report, say)
    # sharded-serving phase: a single-shard device.transfer outage
    # during a sharded window fails only that window — typed — while
    # the mesh keeps dispatching ONE-program windows (the breaker/
    # retry fabric is per-dependency, not a per-chip meltdown). Own
    # harness scope; fires append to the replay-diffed log.
    log += _mesh_phase(plan, root, report, say)
    say(f"workload: {report.ok}/{report.requests} ok, "
        f"typed={sum(report.typed_errors.values())}, "
        f"untyped={len(report.untyped_errors)}, "
        f"fires={len(log)}")
    return log


# burst shape: 6 single-request kNN windows through the pipeline
# (max_batch=1 keeps windows singleton => the stager's device.transfer
# fires land at deterministic call indices), with window 3's transfer
# failed through ALL retry attempts (the device RetryPolicy makes 3) —
# calls 5, 6, 7 at the site: windows 1-2 fire stage+scan-upload (2
# calls each), window 3's stage then retries twice more
_BURST_REQUESTS = 6
_BURST_FAULT_CALLS = (5, 6, 7)


def _pipeline_burst(plan: FaultPlan, root: str, report: ChaosReport,
                    say) -> List[tuple]:
    from geomesa_tpu.faults.plan import FaultRule
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    # same row count as the legacy phase's store: the padded batch hits
    # the SAME pow2 kernel bucket, so the burst re-uses warm compiles
    # instead of adding a shape to every seeded run's wall time
    store, sft = _synth_store(os.path.join(root, "burst"), n=384,
                              seed=plan.seed + 29)
    rng = np.random.default_rng(plan.seed + 31)
    qpts = rng.uniform(-60, 60, (_BURST_REQUESTS, 2))
    cql = "BBOX(geom, -170, -80, 170, 80)"
    svc = QueryService(store, ServeConfig(
        max_wait_ms=0.0, max_batch=1, drain_timeout_s=30.0))
    burst_plan = FaultPlan(
        seed=plan.seed + 37,
        rules=[FaultRule(site="device.transfer", error="unavailable",
                         nth_call=c) for c in _BURST_FAULT_CALLS])
    try:
        # warm OUTSIDE the harness: compiles and first-read I/O must not
        # consume injected calls (run 2's warm in-process caches would
        # otherwise shift the fire schedule and break replay)
        svc.knn("chaos", cql, qpts[0:1, 0], qpts[0:1, 1],
                k=5, timeout_ms=60_000).result(120)
        ok = typed = 0
        with _harness.active(burst_plan) as h:
            futs = [svc.knn("chaos", cql, qpts[i:i + 1, 0],
                            qpts[i:i + 1, 1], k=5, timeout_ms=60_000)
                    for i in range(_BURST_REQUESTS)]
            for f in futs:
                report.requests += 1
                try:
                    f.result(timeout=120)
                    ok += 1
                    report.ok += 1
                except Exception as e:  # noqa: BLE001 — taxonomy decides
                    if _errors.is_typed(e):
                        typed += 1
                        key = type(e).__name__
                        report.typed_errors[key] = (
                            report.typed_errors.get(key, 0) + 1)
                    else:
                        report.untyped_errors.append(
                            f"burst: {type(e).__name__}: {e}")
            svc.close(drain=True)
            blog = h.fire_log()
        pstats = (svc.stats().get("pipeline") or {})
        if len(blog) != len(_BURST_FAULT_CALLS):
            report.invariant_failures.append(
                f"pipeline burst: expected {len(_BURST_FAULT_CALLS)} "
                f"device.transfer fires, saw {len(blog)}")
        if typed != 1 or ok != _BURST_REQUESTS - 1:
            report.invariant_failures.append(
                f"pipeline burst: faulted window must fail alone and "
                f"typed (ok={ok}, typed={typed} of {_BURST_REQUESTS})")
        if pstats.get("inflight", 0) != 0:
            report.invariant_failures.append(
                "pipeline burst: windows still in flight after drain")
        if svc._worker is not None and svc._worker.is_alive():
            report.invariant_failures.append(
                "pipeline burst: dispatch thread alive after drain")
        say(f"pipeline burst: {ok} ok / {typed} typed, "
            f"max_inflight={pstats.get('max_inflight')}, "
            f"fires={len(blog)}")
        return blog
    finally:
        try:
            svc.close(drain=False)
        except Exception:
            pass


# standing-query phase shape: 2 subscriptions (a bbox geofence + a tiny
# density window) over a 6-feature moving fleet. The kafka retry policy
# makes 4 attempts, so every=1 + max_fires=4 exhausts the FIRST poll's
# retries (typed error, no fold) and leaves the second poll clean — it
# folds the outage window's messages exactly once.
_SUB_ROWS = 6
_SUB_FAULT_FIRES = 4


def _subscribe_phase(plan: FaultPlan, report: ChaosReport,
                     say) -> List[tuple]:
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.faults.plan import FaultRule
    from geomesa_tpu.kafka.store import KafkaDataStore
    from geomesa_tpu.subscribe import DensityWindow, SubscriptionManager

    sft = SimpleFeatureType.from_spec("chaos_sub", "name:String,*geom:Point")
    store = KafkaDataStore()
    store.create_schema(sft)
    mgr = SubscriptionManager(store)
    bbox = (-20.0, -20.0, 20.0, 20.0)

    def make_batch(i: int) -> FeatureBatch:
        rng = np.random.default_rng(plan.seed + 53 + i)
        return FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b"], _SUB_ROWS).tolist(),
            "geom": np.stack([rng.uniform(-50, 50, _SUB_ROWS),
                              rng.uniform(-30, 30, _SUB_ROWS)], 1),
        }, fids=[f"v{j}" for j in range(_SUB_ROWS)])

    rows: Dict[str, tuple] = {}  # fid -> (x, y): the host oracle

    def note_rows(batch):
        xs = batch.columns["geom"].x
        ys = batch.columns["geom"].y
        for j, fid in enumerate(batch.fids.decode()):
            rows[str(fid)] = (float(xs[j]), float(ys[j]))

    def oracle_matched():
        return {fid for fid, (x, y) in rows.items()
                if bbox[0] <= x <= bbox[2] and bbox[1] <= y <= bbox[3]}

    frames: List[dict] = []
    geo = mgr.subscribe("chaos_sub", f"BBOX(geom, {bbox[0]}, {bbox[1]}, "
                                     f"{bbox[2]}, {bbox[3]})",
                        initial_state=False)
    mgr.subscribe("chaos_sub",
                  density=DensityWindow((-60.0, -30.0, 60.0, 30.0), 8, 4),
                  initial_state=False)

    def replayed_matched() -> set:
        """Fold the pushed enter/exit stream in seq order — the event
        log must reconstruct the matched set exactly (zero missed /
        duplicate / phantom transitions)."""
        state: set = set()
        for f in sorted((f for f in frames
                         if f.get("subscription") == geo.sub_id
                         and f["event"] in ("enter", "exit")),
                        key=lambda f: f["seq"]):
            fids = set(f["fids"])
            if f["event"] == "enter":
                if fids & state:
                    report.invariant_failures.append(
                        f"subscribe phase: duplicate enter {fids & state}")
                state |= fids
            else:
                if fids - state:
                    report.invariant_failures.append(
                        f"subscribe phase: phantom exit {fids - state}")
                state -= fids
        return state

    # warm fold OUTSIDE the harness (fused-kernel compile must not
    # consume injected calls — replay determinism, as in the burst)
    b0 = make_batch(0)
    store.write("chaos_sub", b0)
    note_rows(b0)
    store.poll("chaos_sub")
    mgr.flush(frames.append)
    if replayed_matched() != oracle_matched():
        report.invariant_failures.append(
            "subscribe phase: warm fold diverged from the host oracle")
    sub_plan = FaultPlan(
        seed=plan.seed + 59,
        rules=[FaultRule(site="kafka.poll", error="unavailable",
                         every=1, max_fires=_SUB_FAULT_FIRES)])
    base_ev = mgr.evaluator.stats()
    # pin the kafka breaker to the chaos tuning for the injected
    # outage (same as the main workload — which RESTORED the
    # process's prior config before this phase runs): an ambient
    # threshold <= the 4 injected failures would open mid-retry,
    # yielding BreakerOpen instead of the expected typed poll error
    # and a fire-count short-fall
    prior_kafka = BREAKERS.current_config("kafka")
    BREAKERS.configure("kafka", **_CHAOS_BREAKER)
    try:
        with _harness.active(sub_plan) as h:
            b1 = make_batch(1)
            store.write("chaos_sub", b1)
            report.requests += 1
            try:
                store.poll("chaos_sub")  # all 4 retry attempts injected
                report.invariant_failures.append(
                    "subscribe phase: injected kafka.poll outage did not "
                    "surface from the poll")
            except Exception as e:  # noqa: BLE001 — the taxonomy decides
                # typed errors are recorded but NOT counted ok — same
                # accounting as outcome() and the pipeline burst
                if _errors.is_typed(e):
                    key = type(e).__name__
                    report.typed_errors[key] = (
                        report.typed_errors.get(key, 0) + 1)
                else:
                    report.untyped_errors.append(
                        f"subscribe poll: {type(e).__name__}: {e}")
            mgr.flush(frames.append)
            if replayed_matched() != oracle_matched():
                # the failed poll must not have half-applied the window
                report.invariant_failures.append(
                    "subscribe phase: failed poll leaked events")
            note_rows(b1)
            b2 = make_batch(2)
            store.write("chaos_sub", b2)
            note_rows(b2)
            store.poll("chaos_sub")  # heals: folds BOTH windows, once
            mgr.flush(frames.append)
            blog = h.fire_log()
    finally:
        BREAKERS.restore_config("kafka", prior_kafka)
        # the injected outage must not outlive the phase
        BREAKERS.reset("kafka")
    ev = mgr.evaluator.stats()
    if replayed_matched() != oracle_matched():
        report.invariant_failures.append(
            "subscribe phase: post-outage matched set diverged "
            "(missed or double-applied events)")
    # one committed fold with one dispatch per evaluation path: the
    # healed poll folds BOTH windows once and dispatches the bbox
    # geofence's lane plus the fused remainder carrying the density
    # window (docs/SERVING.md "Standing queries" lanes) — the faulted
    # poll never folded
    folds = ev["folds"] - base_ev["folds"]
    dispatches = ev["dispatches"] - base_ev["dispatches"]
    lane_disp = (ev.get("lane_dispatches", 0)
                 - base_ev.get("lane_dispatches", 0))
    if folds != 1 or dispatches != 2 or lane_disp != 1:
        report.invariant_failures.append(
            f"subscribe phase: expected 1 in-harness fold with one "
            f"lane + one fused dispatch (the healed poll), saw "
            f"folds={folds} dispatches={dispatches} "
            f"lane_dispatches={lane_disp}")
    if len(blog) != _SUB_FAULT_FIRES:
        report.invariant_failures.append(
            f"subscribe phase: expected {_SUB_FAULT_FIRES} kafka.poll "
            f"fires, saw {len(blog)}")
    mgr.close()
    say(f"subscribe phase: {len(frames)} frames, matched oracle ok, "
        f"fires={len(blog)}")
    return blog


# sharded-serving phase shape (docs/SERVING.md "Sharded serving"): 6
# singleton kNN windows through the pipelined MESH service (auto mesh
# over every local device, mesh residency on). Each window's only
# device.transfer call is its staged query upload, so window 3's
# transfer faulted through all 3 retry attempts = in-harness calls
# 3, 4, 5 at the site — modelling one shard's host->device tunnel
# dropping mid-window.
_MESH_REQUESTS = 6
_MESH_FAULT_CALLS = (3, 4, 5)


def _mesh_phase(plan: FaultPlan, root: str, report: ChaosReport,
                say) -> List[tuple]:
    """A single-shard device.transfer outage during a SHARDED window
    fails only that window — typed — and the mesh keeps serving: the
    breaker/retry fabric applies per-dependency, never as a per-chip
    meltdown (no degrade to single-chip, no dead dispatcher). Own
    harness scope; fires append to the replay-diffed log."""
    import jax

    from geomesa_tpu.faults.plan import FaultRule
    from geomesa_tpu.serve.loadgen import mesh_dispatch_count
    from geomesa_tpu.serve.service import QueryService, ServeConfig

    if len(jax.devices()) < 2:
        say("mesh phase: skipped (single device — no mesh to shard)")
        return []
    store, sft = _synth_store(os.path.join(root, "mesh"), n=384,
                              seed=plan.seed + 41, use_device_cache=True)
    rng = np.random.default_rng(plan.seed + 43)
    qpts = rng.uniform(-60, 60, (_MESH_REQUESTS, 2))
    cql = "BBOX(geom, -170, -80, 170, 80)"
    svc = QueryService(store, ServeConfig(
        max_wait_ms=0.0, max_batch=1, drain_timeout_s=30.0,
        mesh="auto"))
    mesh_d = int(svc.mesh.devices.size) if svc.mesh is not None else 0
    mesh_plan = FaultPlan(
        seed=plan.seed + 47,
        rules=[FaultRule(site="device.transfer", error="unavailable",
                         nth_call=c) for c in _MESH_FAULT_CALLS])

    try:
        # warm OUTSIDE the harness: the mesh program compile, the
        # sharded residency upload, and the stager's first slot must
        # not consume injected calls (replay determinism)
        svc.knn("chaos", cql, qpts[0:1, 0], qpts[0:1, 1],
                k=5, timeout_ms=60_000).result(120)
        base_mesh = mesh_dispatch_count()
        ok = typed = 0
        with _harness.active(mesh_plan) as h:
            futs = [svc.knn("chaos", cql, qpts[i:i + 1, 0],
                            qpts[i:i + 1, 1], k=5, timeout_ms=60_000)
                    for i in range(_MESH_REQUESTS)]
            for f in futs:
                report.requests += 1
                try:
                    f.result(timeout=120)
                    ok += 1
                    report.ok += 1
                except Exception as e:  # noqa: BLE001 — taxonomy decides
                    if _errors.is_typed(e):
                        typed += 1
                        key = type(e).__name__
                        report.typed_errors[key] = (
                            report.typed_errors.get(key, 0) + 1)
                    else:
                        report.untyped_errors.append(
                            f"mesh: {type(e).__name__}: {e}")
            svc.close(drain=True)
            blog = h.fire_log()
        if len(blog) != len(_MESH_FAULT_CALLS):
            report.invariant_failures.append(
                f"mesh phase: expected {len(_MESH_FAULT_CALLS)} "
                f"device.transfer fires, saw {len(blog)}")
        if typed != 1 or ok != _MESH_REQUESTS - 1:
            report.invariant_failures.append(
                f"mesh phase: the faulted sharded window must fail "
                f"alone and typed (ok={ok}, typed={typed} of "
                f"{_MESH_REQUESTS})")
        # no per-chip meltdown: every surviving window still ran the
        # ONE-program mesh route (the outage neither wedged the mesh
        # nor silently degraded the service to single-chip)
        # the shared route counter (whole-mesh + shard-affinity
        # local windows — loadgen reports topology off the same
        # signal, so the two can never disagree)
        survived = mesh_dispatch_count() - base_mesh
        if survived != _MESH_REQUESTS - 1:
            report.invariant_failures.append(
                f"mesh phase: expected {_MESH_REQUESTS - 1} sharded "
                f"dispatches around the outage, saw {survived:.0f}")
        if svc._worker is not None and svc._worker.is_alive():
            report.invariant_failures.append(
                "mesh phase: dispatch thread alive after drain")
        say(f"mesh phase: {ok} ok / {typed} typed over a {mesh_d}-chip "
            f"mesh, fires={len(blog)}")
        return blog
    finally:
        try:
            svc.close(drain=False)
        except Exception:
            pass


def _drive(plan, root, requests, report, svc, store, sft, kstore, ksrc,
           qpts, cql, rng, outcome) -> List[tuple]:
    """The harness-scoped middle of one chaos pass: enable the compile
    cache under injection, serve the request mix, interleave writers,
    drain; returns the fire log. Cache/breaker restoration is the
    CALLER's job, outside the harness scope."""
    from geomesa_tpu.compilecache.persist import enable_persistent_cache

    with _harness.active(plan) as h:
        try:
            # compile-cache boundary: an injected failure must DEGRADE
            # (enable returns None), never raise
            cache_dir = os.path.join(root, "jaxcache")
            try:
                enable_persistent_cache(cache_dir=cache_dir, force=True)
                enable_persistent_cache(cache_dir=cache_dir, force=True)
            except Exception as e:  # noqa: BLE001 — contract violation
                report.untyped_errors.append(
                    f"compile-cache enable raised: {type(e).__name__}")
            for i in range(requests):
                op = i % 4
                if op == 0:
                    outcome(lambda: svc.count(
                        "chaos", cql, timeout_ms=30_000).result(60))
                elif op == 1:
                    outcome(lambda i=i: svc.knn(
                        "chaos", cql, qpts[i:i + 1, 0], qpts[i:i + 1, 1],
                        k=5, timeout_ms=30_000).result(60))
                elif op == 2:
                    outcome(lambda: svc.query(
                        "chaos", cql, timeout_ms=30_000).result(60))
                else:
                    outcome(lambda: ksrc.get_count("INCLUDE"))
                if i % 5 == 4:
                    # interleaved writers: FS batch-atomic appends and
                    # Kafka produces, both under injection
                    try:
                        store.get_feature_source("chaos").write(
                            _synth_batch(sft, rng, 16))
                        report.writes_ok += 1
                    except Exception as e:  # noqa: BLE001
                        if _errors.is_typed(e):
                            report.writes_failed += 1
                        else:
                            report.untyped_errors.append(
                                f"write: {type(e).__name__}: {e}")
                    try:
                        kstore.write("chaos_live", _synth_batch(
                            ksrc.sft, rng, 4))
                        report.writes_ok += 1
                    except Exception as e:  # noqa: BLE001
                        if _errors.is_typed(e):
                            report.writes_failed += 1
                        else:
                            report.untyped_errors.append(
                                f"kafka write: {type(e).__name__}: {e}")
            svc.close(drain=True)
            if svc._worker is not None and svc._worker.is_alive():
                report.invariant_failures.append(
                    "dispatch thread still alive after drain")
            if len(svc.queue) != 0:
                report.invariant_failures.append(
                    "queue not empty after graceful drain")
        finally:
            try:
                svc.close(drain=False)
            except Exception:
                pass
        return h.fire_log()


def _counter_snapshot() -> Dict[str, float]:
    from geomesa_tpu.utils.metrics import metrics

    with metrics._lock:
        return dict(metrics.counters)


def run_chaos(plan: FaultPlan, requests: int = 32, replay: bool = True,
              out=None) -> ChaosReport:
    """Programmatic `gmtpu chaos`: returns a ChaosReport whose
    `ok_overall` reflects every invariant (the CLI exit code)."""
    out = out if out is not None else sys.stderr

    def say(msg):
        print(f"chaos: {msg}", file=out)

    report = ChaosReport()
    before = _counter_snapshot()
    with tempfile.TemporaryDirectory() as tmp:
        log = _run_workload(plan, os.path.join(tmp, "run1"),
                            requests, report, say)
        if replay:
            replay_report = ChaosReport()
            log2 = _run_workload(plan, os.path.join(tmp, "run2"),
                                 requests, replay_report, say)
            report.replay_match = log == log2
            if not report.replay_match:
                report.invariant_failures.append(
                    f"replay diverged: {len(log)} vs {len(log2)} fires "
                    f"(first diff at "
                    f"{next((i for i, (a, b) in enumerate(zip(log, log2)) if a != b), min(len(log), len(log2)))})")
            report.invariant_failures.extend(
                f"replay: {f}" for f in replay_report.invariant_failures)
            report.untyped_errors.extend(
                f"replay: {u}" for u in replay_report.untyped_errors)
    report.fires = len(log)
    report.fired_sites = sorted({s for s, _, _ in log})

    # invariant 1: zero un-typed escapes
    for u in report.untyped_errors:
        report.invariant_failures.append(f"un-typed escape: {u}")
    # invariant 3: every deterministic rule fired
    import fnmatch

    for rule in plan.rules:
        if rule.nth_call is None and rule.every is None:
            continue  # probabilistic rules may legitimately stay quiet
        hit = any(
            (site == rule.site or fnmatch.fnmatchcase(site, rule.site))
            and err == rule.error
            for site, _, err in log)
        if not hit:
            report.invariant_failures.append(
                f"rule for {rule.site!r} ({rule.error}) never fired — "
                f"the workload does not exercise that site")
    # invariant 4: breaker transitions visible in metrics
    after = _counter_snapshot()
    for name in plan.expect_breakers:
        for phase in ("open", "half_open"):
            key = f"fault.breaker.{name}.{phase}"
            delta = after.get(key, 0.0) - before.get(key, 0.0)
            report.breaker_counters[key] = delta
            if delta < 1:
                report.invariant_failures.append(
                    f"breaker {name!r} never transitioned to {phase} "
                    f"(metrics counter {key} unchanged)")
    # invariant 6: the disabled harness must cost ~nothing
    site = _harness.site("chaos.noop.probe")
    t0 = time.perf_counter()
    for _ in range(_NOOP_CALLS):
        site.fire()
    per_call_us = (time.perf_counter() - t0) / _NOOP_CALLS * 1e6
    report.noop_us_per_call = round(per_call_us, 4)
    if per_call_us > _NOOP_BUDGET_US:
        report.invariant_failures.append(
            f"no-op site check costs {per_call_us:.2f}µs/call "
            f"(budget {_NOOP_BUDGET_US}µs): the inactive fast path "
            "is doing work")
    say("OK" if report.ok_overall else
        f"FAIL: {'; '.join(report.invariant_failures)}")
    return report


# -- fleet chaos (docs/ROBUSTNESS.md "Replica fleets") ----------------------
#
# `gmtpu chaos --fleet`: the replica-kill certification. A 2-replica
# thread fleet (same process semantics as deployment: own stores, own
# queues, the real wire protocol over real sockets) serves five phases:
#
#   1. route   — sequential mixed traffic; every answer ok; both
#                replicas take traffic (rendezvous affinity spreads
#                deterministic keys deterministically);
#   2. faults  — the plan's deterministic rules fire under the harness
#                (sequential submission keeps the site call sequence
#                replayable) and the retry fabric absorbs them: every
#                answer still ok, fire log exact;
#   3. kill    — a burst pipelined on one client connection, replica
#                killed abruptly (abort(): the kill -9 stand-in) while
#                requests are in flight. EVERY request gets exactly one
#                answer: ok, or typed retryable
#                unavailable/rejected/timeout — zero un-typed errors,
#                zero silent drops, zero duplicate responses (the wire
#                has no write verbs and the router retries reads only,
#                so zero double-executed writes by construction);
#   4. warmup  — a FRESH replica with a manifest recorded from phase-1
#                traffic demonstrably refuses traffic (typed,
#                retryable `warming`) until `gmtpu warmup --check`
#                semantics pass, and the router never routes to it
#                before `ready`;
#   5. subscribe-kill — a geofence standing query subscribed THROUGH
#                the router over a shared Kafka live layer, owner
#                replica killed abruptly mid-stream. The router
#                re-homes the subscription onto the survivor from its
#                checkpoint; a host oracle replays the client's frame
#                stream and asserts ZERO missed / duplicate / phantom
#                enter-exit transitions modulo exactly ONE state
#                resync, seq strictly monotonic across the kill, and
#                zero client-side handoff choreography.
#
# The whole sequence runs twice with the same seed; the harness fire
# logs must match exactly (invariant 3's replay discipline).

_FLEET_ROUTE_REQUESTS = 12
_FLEET_FAULT_REQUESTS = 6
_FLEET_KILL_REQUESTS = 20


def default_fleet_plan(seed: int = 23) -> FaultPlan:
    """The built-in replica-kill plan: two deterministic storage
    faults the retry fabric must absorb (fires below the retry
    budget), asserted fired + replay-exact. The kill itself is
    scripted by the runner, not a harness rule — process death is not
    an injection site."""
    from geomesa_tpu.faults.plan import FaultRule

    # the fault phase makes 6 sequential scan-path counts -> one
    # fs.read_partition call each, +1 per injected fire's retry:
    # fires at calls 2 and 5 leave every request recovered (the retry
    # budget absorbs single faults) while both rules provably fire
    return FaultPlan(seed=seed, rules=[
        FaultRule(site="fs.read_partition", error="io", nth_call=2),
        FaultRule(site="fs.read_partition", error="io", nth_call=5),
    ])


def _fleet_request(i: int, qpts, cql: str,
                   rid: Optional[str] = None) -> dict:
    rid = rid if rid is not None else f"q{i}"
    if i % 2 == 0:
        return {"id": rid, "op": "count", "typeName": "chaos",
                "cql": cql, "timeoutMs": 60_000}
    return {"id": rid, "op": "knn", "typeName": "chaos",
            "cql": cql, "x": [float(qpts[i, 0])],
            "y": [float(qpts[i, 1])], "k": 5, "timeoutMs": 60_000}


def _fleet_answer(report: ChaosReport, doc: dict, where: str) -> None:
    report.requests += 1
    if doc.get("ok"):
        report.ok += 1
    elif doc.get("error") in ("unavailable", "rejected", "timeout"):
        key = doc.get("reason") or doc["error"]
        report.typed_errors[key] = report.typed_errors.get(key, 0) + 1
    else:
        report.untyped_errors.append(
            f"{where}: {doc.get('error')}: {doc.get('message')}")


def _run_fleet_pass(plan: FaultPlan, root: str, report: ChaosReport,
                    say) -> List[tuple]:
    import threading

    from geomesa_tpu.fleet import (
        FleetConfig, FleetSupervisor, ReplicaServer)
    from geomesa_tpu.fleet.wire import connect_json
    from geomesa_tpu.plan.datastore import DataStore
    from geomesa_tpu.serve.service import ServeConfig

    catalog = os.path.join(root, "cat")
    _synth_store(catalog, n=384, seed=plan.seed)
    rng = np.random.default_rng(plan.seed + 61)
    qpts = rng.uniform(-60, 60, (64, 2))
    cql = "BBOX(geom, -170, -80, 170, 80)"

    # scan-path stores so the plan's storage rules keep biting, and
    # coalescing-off so the fault phase's site sequence is replayable
    def store_factory():
        return DataStore(catalog, use_device_cache=False)

    sup = FleetSupervisor(FleetConfig(
        n_replicas=2, catalog=catalog, store_factory=store_factory,
        serve_config=ServeConfig(max_wait_ms=0.0, max_batch=1),
        probe_interval_s=0.2))
    extra = None
    try:
        port = sup.start()
        # phase-4 prep: record a warmup manifest from live traffic on
        # replica r0 (thread spawn exposes the service)
        recorder = sup.membership.get("r0").server.svc.record_warmup()

        cli = connect_json("127.0.0.1", port)
        # phase 1: route — sequential, every answer ok, both replicas
        # take traffic
        for i in range(_FLEET_ROUTE_REQUESTS):
            cli.send(_fleet_request(i, qpts, cql))
            got = next(cli.docs())
            _fleet_answer(report, got, "route")
            if not got.get("ok"):
                report.invariant_failures.append(
                    f"fleet route phase: request {i} failed "
                    f"{got.get('error')}/{got.get('reason')}")
        routed = {r["replica"]: r["routed"]
                  for r in sup.stats()["replicas"]}
        if sorted(v > 0 for v in routed.values()) != [True, True]:
            report.invariant_failures.append(
                f"fleet route phase: traffic did not spread over both "
                f"replicas ({routed})")

        # phase 2: deterministic faults under the harness, absorbed by
        # the retry fabric; sequential submission keeps the fire
        # schedule exact
        with _harness.active(plan) as h:
            for i in range(_FLEET_FAULT_REQUESTS):
                cli.send(_fleet_request(2 * i, qpts, cql))  # counts
                got = next(cli.docs())
                _fleet_answer(report, got, "fault")
                if not got.get("ok"):
                    report.invariant_failures.append(
                        f"fleet fault phase: retry fabric did not "
                        f"absorb an injected fault "
                        f"({got.get('error')}/{got.get('reason')})")
            log = list(h.fire_log())

        manifest_path = os.path.join(root, "fleet_warmup.json")
        recorder.manifest().save(manifest_path)

        # phase 3: replica kill mid-burst. Pipeline the burst on one
        # connection, kill r1 abruptly while requests are in flight.
        for i in range(_FLEET_KILL_REQUESTS):
            cli.send(_fleet_request(i % 16, qpts, cql, rid=f"k{i}"))
        sup.kill_replica("r1", graceful=False)
        answers: Dict[str, dict] = {}
        stop = threading.Event()
        timer = threading.Timer(120.0, stop.set)
        timer.start()
        for got in cli.docs(stop):
            rid = got.get("id")
            if rid in answers:
                report.invariant_failures.append(
                    f"fleet kill phase: duplicate response for {rid} "
                    f"(double-delivery)")
            answers[rid] = got
            if len(answers) >= _FLEET_KILL_REQUESTS:
                break
        timer.cancel()
        if len(answers) != _FLEET_KILL_REQUESTS:
            report.invariant_failures.append(
                f"fleet kill phase: {_FLEET_KILL_REQUESTS} requests, "
                f"{len(answers)} answers — requests were silently "
                f"dropped")
        for rid, got in answers.items():
            _fleet_answer(report, got, f"kill:{rid}")
        st = sup.stats()["router"]
        say(f"fleet kill phase: {len(answers)} answered, "
            f"retried={st['retried']}, unavailable={st['unavailable']}")

        # phase 4: a fresh replica refuses traffic until its warmup
        # check is green, and the router never routes to it before
        # ready
        hold = threading.Event()
        extra = ReplicaServer(
            store_factory, ServeConfig(max_wait_ms=0.0, max_batch=1),
            replica_id="r2", warmup_manifest=manifest_path,
            warmup_hold=hold)
        eport = extra.start()
        from geomesa_tpu.fleet.membership import ReplicaHandle

        handle = ReplicaHandle(replica_id="r2", host="127.0.0.1",
                               port=eport, spawn="thread", server=extra)
        sup.membership.add(handle)
        sup.router.attach(handle)
        probe = connect_json("127.0.0.1", eport)
        got = probe.request(
            {"id": "w1", "op": "count", "typeName": "chaos",
             "cql": cql}, timeout_s=30.0)
        if got.get("ok") or got.get("reason") != "warming" \
                or not got.get("retryable"):
            report.invariant_failures.append(
                f"fleet warmup phase: warming replica did not refuse "
                f"traffic typed+retryable (got {got})")
        if any(h2.replica_id == "r2"
               for h2 in sup.membership.routable()):
            report.invariant_failures.append(
                "fleet warmup phase: router considers a warming "
                "replica routable")
        hold.set()
        state = extra.wait_state("ready", timeout=120.0)
        if state != "ready" or (extra.warmup_report is not None
                                and not extra.warmup_report.ok):
            report.invariant_failures.append(
                f"fleet warmup phase: fresh replica came up {state} "
                f"({extra.error}) — warmup --check not green")
        else:
            got = probe.request(
                {"id": "w2", "op": "count", "typeName": "chaos",
                 "cql": cql}, timeout_s=60.0)
            report.requests += 1
            if got.get("ok"):
                report.ok += 1
            else:
                report.invariant_failures.append(
                    f"fleet warmup phase: ready replica refused "
                    f"traffic ({got})")
        probe.close()
        cli.close()

        # phase 5: subscribe-kill — fleet-native standing queries
        # survive an abrupt owner death with at most one resync
        _fleet_subscribe_kill_phase(plan, report, say)
        return log
    finally:
        if extra is not None:
            try:
                extra.abort()
            except Exception:
                pass
        sup.close()


_FLEET_SUB_BATCHES = 4          # geofence stream batches (kill after #2)
_FLEET_SUB_FIDS = 24


def _fleet_subscribe_kill_phase(plan: FaultPlan, report: ChaosReport,
                                say) -> None:
    """A geofence stream subscribed through the router across an
    abrupt owner kill. Host-oracle replay of the client's frames
    certifies the re-home contract: zero missed/dup/phantom
    transitions, exactly one state resync, seq monotonic — with the
    client doing nothing but reading its one connection."""
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.core.sft import SimpleFeatureType
    from geomesa_tpu.fleet import FleetConfig, FleetSupervisor
    from geomesa_tpu.fleet.router import FleetClient
    from geomesa_tpu.kafka.store import KafkaDataStore

    sft = SimpleFeatureType.from_spec(
        "geofence", "name:String,score:Double,dtg:Date,*geom:Point")
    fence = (-20.0, -15.0, 25.0, 20.0)
    cql = f"BBOX(geom, {fence[0]}, {fence[1]}, {fence[2]}, {fence[3]})"
    rng = np.random.default_rng(plan.seed + 97)
    fids = [f"v{i}" for i in range(_FLEET_SUB_FIDS)]

    def batch(k: int) -> FeatureBatch:
        # same fid population every batch: vessels MOVE, so the fence
        # sees enter AND exit transitions each fold
        return FeatureBatch.from_pydict(sft, {
            "name": rng.choice(["a", "b", "c"],
                               _FLEET_SUB_FIDS).tolist(),
            "score": rng.uniform(-5, 5, _FLEET_SUB_FIDS),
            "dtg": rng.integers(1_590_000_000_000, 1_600_000_000_000,
                                _FLEET_SUB_FIDS),
            "geom": np.stack([rng.uniform(-60, 60, _FLEET_SUB_FIDS),
                              rng.uniform(-30, 30, _FLEET_SUB_FIDS)],
                             1),
        }, fids=list(fids))

    def inside(b: FeatureBatch) -> set:
        g = b.columns[sft.default_geometry.name]
        x = np.asarray(g.x)
        y = np.asarray(g.y)
        keep = ((x >= fence[0]) & (x <= fence[2])
                & (y >= fence[1]) & (y <= fence[3]))
        return {f for f, k in zip(b.fids.decode(), keep) if k}

    store = KafkaDataStore()
    src = store.create_schema(sft)
    sup = FleetSupervisor(FleetConfig(
        n_replicas=2, store_factory=lambda: store,
        probe_interval_s=0.1))
    frames: List[dict] = []
    fail = report.invariant_failures.append
    try:
        port = sup.start()
        cli = FleetClient("127.0.0.1", port, timeout_s=30.0)
        got = cli.request({"op": "subscribe", "typeName": "geofence",
                           "cql": cql}, on_push=frames.append)
        if not got.get("ok"):
            fail(f"fleet subscribe phase: subscribe refused ({got})")
            return
        sid = got["subscription"]
        owner = got["replica"]
        oracle = None
        killed = False
        for k in range(_FLEET_SUB_BATCHES):
            b = batch(k)
            oracle = inside(b)
            src.write(b)
            if k == 2 and not killed:
                # let one checkpoint ride the stats probe, then kill
                # the owner abruptly mid-stream and wait for the
                # router's re-home to land on the survivor
                deadline = time.monotonic() + 5.0
                while time.monotonic() < deadline:
                    row = sup.membership.sub_owner(sid)
                    if row is not None and row.checkpoint is not None:
                        break
                    time.sleep(0.02)
                sup.kill_replica(owner, graceful=False)
                killed = True
                deadline = time.monotonic() + 10.0
                while time.monotonic() < deadline:
                    row = sup.membership.sub_owner(sid)
                    if row is not None and row.replica_id != owner:
                        break
                    time.sleep(0.02)
                row = sup.membership.sub_owner(sid)
                if row is None or row.replica_id == owner:
                    fail("fleet subscribe phase: subscription was not "
                         "re-homed after the owner kill")
                    return
            got = cli.request({"op": "poll"}, on_push=frames.append)
            report.requests += 1
            if got.get("ok"):
                report.ok += 1
            else:
                fail(f"fleet subscribe phase: poll {k} failed ({got})")
        cli.close()

        evs = [f for f in frames if f.get("subscription") == sid]
        seqs = [f.get("seq") for f in evs]
        if seqs != sorted(seqs) or len(set(seqs)) != len(seqs):
            fail(f"fleet subscribe phase: client seq not strictly "
                 f"monotonic across the kill ({seqs})")
        resyncs = sum(1 for f in evs[1:] if f.get("event") == "state")
        if resyncs != 1:
            fail(f"fleet subscribe phase: expected exactly one state "
                 f"resync from the kill, saw {resyncs}")
        state: set = set()
        for f in evs:
            ev = f.get("event")
            if ev == "state":
                state = set(f["fids"])
            elif ev == "enter":
                dup = set(f["fids"]) & state
                if dup:
                    fail(f"fleet subscribe phase: duplicate enter "
                         f"transitions for {sorted(dup)}")
                state |= set(f["fids"])
            elif ev == "exit":
                ghost = set(f["fids"]) - state
                if ghost:
                    fail(f"fleet subscribe phase: phantom exit "
                         f"transitions for {sorted(ghost)}")
                state -= set(f["fids"])
        if oracle is not None and state != oracle:
            fail(f"fleet subscribe phase: replayed matched set "
                 f"diverged from the host oracle (missed="
                 f"{sorted(oracle - state)}, extra="
                 f"{sorted(state - oracle)})")
        st = sup.stats()["router"]
        say(f"fleet subscribe phase: {len(evs)} frames, "
            f"1 resync, rehomed={st['rehome_succeeded']}")
    finally:
        sup.close()


def run_fleet_chaos(plan: Optional[FaultPlan] = None,
                    replay: bool = True, out=None) -> ChaosReport:
    """Programmatic `gmtpu chaos --fleet`. Returns a ChaosReport whose
    `ok_overall` is the certification verdict."""
    out = out if out is not None else sys.stderr

    def say(msg):
        print(f"chaos --fleet: {msg}", file=out)

    plan = plan if plan is not None else default_fleet_plan()
    report = ChaosReport()
    with tempfile.TemporaryDirectory() as tmp:
        log = _run_fleet_pass(plan, os.path.join(tmp, "run1"),
                              report, say)
        if replay:
            replay_report = ChaosReport()
            log2 = _run_fleet_pass(plan, os.path.join(tmp, "run2"),
                                   replay_report, say)
            report.replay_match = log == log2
            if not report.replay_match:
                report.invariant_failures.append(
                    f"fleet replay diverged: {len(log)} vs "
                    f"{len(log2)} fires")
            report.invariant_failures.extend(
                f"replay: {f}" for f in replay_report.invariant_failures)
            report.untyped_errors.extend(
                f"replay: {u}" for u in replay_report.untyped_errors)
    report.fires = len(log)
    report.fired_sites = sorted({s for s, _, _ in log})
    for u in report.untyped_errors:
        report.invariant_failures.append(f"un-typed escape: {u}")
    import fnmatch

    for rule in plan.rules:
        if rule.nth_call is None and rule.every is None:
            continue
        hit = any(
            (site == rule.site or fnmatch.fnmatchcase(site, rule.site))
            and err == rule.error
            for site, _, err in log)
        if not hit:
            report.invariant_failures.append(
                f"fleet rule for {rule.site!r} ({rule.error}) never "
                f"fired")
    say("OK" if report.ok_overall else
        f"FAIL: {'; '.join(report.invariant_failures)}")
    return report


def run_cli(args) -> int:
    if getattr(args, "fleet", False):
        plan = (FaultPlan.load(args.plan)
                if getattr(args, "plan", None) else None)
        if plan is not None and getattr(args, "seed", None) is not None:
            plan.seed = args.seed
        report = run_fleet_chaos(
            plan, replay=not getattr(args, "no_replay", False))
        print(json.dumps(report.to_json(), indent=1))
        if args.check:
            return 0 if report.ok_overall else 1
        return 0
    if getattr(args, "list_sites", False):
        # import the boundary modules so their sites register
        import geomesa_tpu.compilecache.manifest  # noqa: F401
        import geomesa_tpu.compilecache.persist  # noqa: F401
        import geomesa_tpu.engine.device  # noqa: F401
        import geomesa_tpu.index.kvstore  # noqa: F401
        import geomesa_tpu.kafka.store  # noqa: F401
        import geomesa_tpu.store.fs  # noqa: F401
        import geomesa_tpu.subscribe.evaluator  # noqa: F401

        for name, doc in sorted(_harness.SITES.items()):
            print(f"{name:<32} {doc}")
        return 0
    plan = FaultPlan.load(args.plan)
    if getattr(args, "seed", None) is not None:
        plan.seed = args.seed
    report = run_chaos(plan, requests=args.requests,
                       replay=not getattr(args, "no_replay", False))
    print(json.dumps(report.to_json(), indent=1))
    if args.check:
        return 0 if report.ok_overall else 1
    return 0
