"""geomesa_tpu.faults — fault injection + recovery fabric.

Two halves (docs/ROBUSTNESS.md):

1. **Injection harness** (`harness.py`, `plan.py`): named sites threaded
   through every dependency boundary (storage, Kafka, device transfer,
   kvstore, compile cache), driven by a declarative seeded `FaultPlan`
   so failures are a replayable INPUT. Zero-overhead no-op check when
   inactive.
2. **Recovery fabric** (`errors.py`, `retry.py`, `breaker.py`,
   `quarantine.py`, `context.py`): typed transient/permanent/OOM
   taxonomy, bounded deadline-aware retry with full-jitter backoff,
   per-dependency circuit breakers, poison-query quarantine, and the
   RecoveryMeter that attributes retries/faults to ServeEvents.

`chaos.py` (the `gmtpu chaos` CLI) runs a serve workload under a plan
and asserts the recovery invariants hold. `fallback.py` is the device-
OOM host-evaluation escape hatch; both import heavier subsystems and
are loaded lazily — this package root stays import-light so the engine
and storage layers can register sites without cycles.
"""

from geomesa_tpu.faults.breaker import BREAKERS, BreakerOpen, CircuitBreaker
from geomesa_tpu.faults.context import (
    RECOVERY, current_deadline, deadline_scope)
from geomesa_tpu.faults.errors import (
    DeviceOOM, FaultInjected, PermanentError, TransientError, classify,
    is_typed)
from geomesa_tpu.faults.harness import (
    SITES, FaultHarness, FaultSite, active, current, inject, install,
    site, uninstall)
from geomesa_tpu.faults.plan import FaultPlan, FaultRule
from geomesa_tpu.faults.quarantine import QuarantineRegistry
from geomesa_tpu.faults.retry import RetryPolicy, retry_call

__all__ = [
    "BREAKERS", "BreakerOpen", "CircuitBreaker",
    "RECOVERY", "current_deadline", "deadline_scope",
    "DeviceOOM", "FaultInjected", "PermanentError", "TransientError",
    "classify", "is_typed",
    "SITES", "FaultHarness", "FaultSite", "active", "current", "inject",
    "install", "site", "uninstall",
    "FaultPlan", "FaultRule", "QuarantineRegistry",
    "RetryPolicy", "retry_call",
]
