"""Declarative fault plans: site -> schedule -> error type.

A FaultPlan is a seeded, replayable description of WHICH injection
sites fail, WHEN, and HOW (in the lineage-driven fault injection spirit:
failure is an input, not an accident). JSON format:

    {
      "seed": 7,
      "rules": [
        {"site": "fs.read_partition", "error": "io",
         "every": 3, "max_fires": 8},
        {"site": "kafka.poll", "error": "unavailable", "nth_call": 2},
        {"site": "device.transfer", "error": "oom", "probability": 0.1},
        {"site": "fs.*", "error": "latency", "latency_ms": 5,
         "probability": 0.5}
      ]
    }

Schedules (first match wins per rule, rules evaluated in order):
  nth_call     fire exactly on the Nth call to the site (1-based)
  every        fire on every Nth call (count % every == 0)
  probability  fire with probability p per call (per-site seeded RNG —
               two runs with the same seed and call sequence replay the
               same fire decisions exactly)
  max_fires    stop a rule after N fires (lets a plan model recovery:
               the dependency "heals" and breakers can half-open/close)
  latency_ms   added latency when the rule fires; with error "latency"
               the call is delayed but succeeds.

Site names may be exact or fnmatch globs over the registered catalog
(faults.harness.SITES).
"""

from __future__ import annotations

import dataclasses
import json
from typing import List, Optional

from geomesa_tpu.faults.errors import ERROR_KINDS


@dataclasses.dataclass
class FaultRule:
    site: str
    error: str = "io"
    probability: float = 0.0
    nth_call: Optional[int] = None
    every: Optional[int] = None
    max_fires: Optional[int] = None
    latency_ms: float = 0.0

    def __post_init__(self):
        if self.error not in ERROR_KINDS:
            raise ValueError(
                f"unknown fault error kind {self.error!r} "
                f"(have {', '.join(sorted(ERROR_KINDS))})")
        if not 0.0 <= self.probability <= 1.0:
            raise ValueError(
                f"probability must be in [0, 1], got {self.probability}")
        if self.nth_call is not None and self.nth_call < 1:
            raise ValueError("nth_call is 1-based and must be >= 1")
        if self.every is not None and self.every < 1:
            raise ValueError("every must be >= 1")
        if (self.probability == 0.0 and self.nth_call is None
                and self.every is None):
            raise ValueError(
                f"rule for site {self.site!r} has no schedule "
                "(set probability, nth_call or every)")

    def to_json(self) -> dict:
        out = {"site": self.site, "error": self.error}
        if self.probability:
            out["probability"] = self.probability
        if self.nth_call is not None:
            out["nth_call"] = self.nth_call
        if self.every is not None:
            out["every"] = self.every
        if self.max_fires is not None:
            out["max_fires"] = self.max_fires
        if self.latency_ms:
            out["latency_ms"] = self.latency_ms
        return out


@dataclasses.dataclass
class FaultPlan:
    rules: List[FaultRule]
    seed: int = 0
    # dependencies whose breakers this plan is DESIGNED to cycle
    # (open + half-open): `gmtpu chaos --check` fails unless their
    # transitions appear in metrics during the run
    expect_breakers: List[str] = dataclasses.field(default_factory=list)

    def to_json(self) -> dict:
        out = {"seed": self.seed,
               "rules": [r.to_json() for r in self.rules]}
        if self.expect_breakers:
            out["expect_breakers"] = list(self.expect_breakers)
        return out

    def save(self, path: str) -> None:
        with open(path, "w", encoding="utf-8") as f:
            json.dump(self.to_json(), f, indent=1)
            f.write("\n")

    @classmethod
    def from_json(cls, doc: dict) -> "FaultPlan":
        rules = [FaultRule(**r) for r in doc.get("rules", [])]
        return cls(rules=rules, seed=int(doc.get("seed", 0)),
                   expect_breakers=list(doc.get("expect_breakers", ())))

    @classmethod
    def load(cls, path: str) -> "FaultPlan":
        with open(path, encoding="utf-8") as f:
            return cls.from_json(json.load(f))
