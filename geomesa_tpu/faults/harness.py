"""Deterministic, seedable fault-injection harness.

Dependency boundaries register named sites once at import time:

    _READ_SITE = faults.site("fs.read_partition", "partition file read")

and call ``_READ_SITE.fire()`` on the hot path. When no harness is
installed the fire is a single module-global ``is None`` check — the
zero-overhead no-op fast path the serving SLO depends on (asserted by
`gmtpu chaos --check`).

With a harness installed (``with faults.active(plan): ...``), each fire
consults the plan's rules for the site under a per-site lock: the site
call counter, the per-site seeded RNG stream and the per-rule fire
budget all advance deterministically, so two runs of the same workload
with the same seed inject the SAME faults at the SAME calls — the chaos
checker replays a run and diffs the fire logs to prove it. Per-site RNG
streams are seeded from (plan.seed, site-name CRC), not Python's salted
``hash``, so replay holds across processes.

Every fire is appended to a bounded log (site, call index, rule error)
and noted into the RecoveryMeter so ServeEvents can attribute
per-dispatch fault counts (`ServeEvent.fault_injected`).
"""

from __future__ import annotations

import fnmatch
import threading
import time
import zlib
from random import Random
from typing import Dict, List, Optional, Tuple

from geomesa_tpu.faults.errors import ERROR_KINDS
from geomesa_tpu.faults.plan import FaultPlan, FaultRule

# registered site catalog: name -> description (gmtpu chaos --list-sites)
SITES: Dict[str, str] = {}

_MAX_LOG = 65536


class FaultSite:
    """One named injection point. Cheap by construction: `fire` reads a
    single module global and returns immediately when inactive."""

    __slots__ = ("name",)

    def __init__(self, name: str):
        self.name = name

    def fire(self) -> None:
        h = _HARNESS
        if h is None:
            return
        h.check(self.name)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        return f"FaultSite({self.name!r})"


def site(name: str, doc: str = "") -> FaultSite:
    """Register (idempotently) and return a named injection site."""
    if doc or name not in SITES:
        SITES[name] = doc or SITES.get(name, "")
    return FaultSite(name)


def inject(name: str) -> None:
    """Ad-hoc fire for call sites without a prebound FaultSite."""
    h = _HARNESS
    if h is not None:
        h.check(name)


class _SiteState:
    __slots__ = ("lock", "count", "rng", "rules", "fires")

    def __init__(self, seed: int, name: str, rules: List[FaultRule]):
        self.lock = threading.Lock()
        self.count = 0
        # process-stable per-site stream: crc32, not salted str hash
        self.rng = Random((seed << 32) ^ zlib.crc32(name.encode()))
        self.rules = rules
        self.fires = [0] * len(rules)  # per-rule fire budget tracking


class FaultHarness:
    """Evaluates a FaultPlan at every site fire. Thread-safe; decisions
    are per-site-deterministic (see module docstring)."""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self._states: Dict[str, _SiteState] = {}
        self._states_lock = threading.Lock()
        self._log_lock = threading.Lock()
        self._log: List[Tuple[str, int, str]] = []

    def _state(self, name: str) -> _SiteState:
        # always under the lock: this path only runs while a harness is
        # ACTIVE (chaos runs), so the acquisition is off the serving
        # no-op fast path entirely
        with self._states_lock:
            st = self._states.get(name)
            if st is None:
                rules = [r for r in self.plan.rules
                         if r.site == name
                         or fnmatch.fnmatchcase(name, r.site)]
                st = self._states[name] = _SiteState(
                    self.plan.seed, name, rules)
            return st

    def check(self, name: str) -> None:
        st = self._state(name)
        if not st.rules:
            return
        fired: Optional[Tuple[FaultRule, int]] = None
        with st.lock:
            st.count += 1
            for i, rule in enumerate(st.rules):
                if rule.max_fires is not None and st.fires[i] >= rule.max_fires:
                    continue
                hit = False
                if rule.nth_call is not None:
                    hit = st.count == rule.nth_call
                elif rule.every is not None:
                    hit = st.count % rule.every == 0
                elif rule.probability > 0.0:
                    # the roll ALWAYS advances the stream for an armed
                    # probability rule, so replay determinism survives
                    # other rules firing first
                    hit = st.rng.random() < rule.probability
                if hit and fired is None:
                    st.fires[i] += 1
                    fired = (rule, st.count)
        if fired is None:
            return
        rule, count = fired
        with self._log_lock:
            if len(self._log) < _MAX_LOG:
                self._log.append((name, count, rule.error))
        try:
            from geomesa_tpu.faults.context import RECOVERY
            from geomesa_tpu.telemetry.recorder import RECORDER
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("fault.injected")
            metrics.counter(f"fault.injected.{name}")
            RECOVERY.note("fault", name)
            RECORDER.note_event("fault", site=name, call=count,
                                error=rule.error)
        except Exception:
            pass  # observability must never change injection behavior
        if rule.latency_ms:
            time.sleep(rule.latency_ms / 1000.0)
        exc_cls = ERROR_KINDS[rule.error]
        if exc_cls is not None:
            raise exc_cls(
                f"injected {rule.error} fault at {name} (call #{count})")

    def fire_log(self) -> List[Tuple[str, int, str]]:
        """(site, call index, error kind) per fire, in fire order."""
        with self._log_lock:
            return list(self._log)

    def fired_sites(self) -> List[str]:
        with self._log_lock:
            return sorted({s for s, _, _ in self._log})


_HARNESS: Optional[FaultHarness] = None
_INSTALL_LOCK = threading.Lock()


def install(plan: FaultPlan) -> FaultHarness:
    """Install a harness process-wide. Raises if one is already active
    (nested chaos runs would corrupt each other's determinism)."""
    global _HARNESS
    with _INSTALL_LOCK:
        if _HARNESS is not None:
            raise RuntimeError("a fault harness is already installed")
        h = FaultHarness(plan)
        _HARNESS = h
        return h


def uninstall() -> None:
    global _HARNESS
    with _INSTALL_LOCK:
        _HARNESS = None


def current() -> Optional[FaultHarness]:
    return _HARNESS


class active:
    """Context manager: ``with faults.active(plan) as harness: ...``"""

    def __init__(self, plan: FaultPlan):
        self.plan = plan
        self.harness: Optional[FaultHarness] = None

    def __enter__(self) -> FaultHarness:
        self.harness = install(self.plan)
        return self.harness

    def __exit__(self, *exc) -> bool:
        uninstall()
        return False
