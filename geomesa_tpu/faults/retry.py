"""Bounded retry with exponential backoff + full jitter, deadline-aware.

The one retry loop every dependency boundary shares (AWS builders'-
library full-jitter shape): attempt k sleeps uniform(0, min(cap,
base * 2^k)). Three hard bounds keep it from becoming the unbounded
while-True loop rule GT14 exists to flag:

  1. `max_attempts` caps total tries;
  2. only `classify(exc) == "transient"` errors retry — OOM and
     permanent errors surface immediately;
  3. the current deadline scope (faults.context) is never slept past:
     if the next backoff would cross the request's remaining budget the
     last error surfaces NOW, so a client sees the failure while its
     deadline can still act on it.

An optional circuit breaker gates every attempt (`allow` before,
`record_success`/`record_failure` after) so a dead dependency flips to
fail-fast instead of every request burning its full retry budget.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from random import Random
from typing import Callable, Optional

from geomesa_tpu.faults import context
from geomesa_tpu.faults.breaker import CircuitBreaker
from geomesa_tpu.faults.errors import classify as _classify


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 4
    base_ms: float = 10.0
    cap_ms: float = 2000.0

    def backoff_ms(self, attempt: int, rng: Random) -> float:
        """Full-jitter delay for `attempt` (0-based count of failures
        so far): uniform(0, min(cap, base * 2^attempt))."""
        return rng.uniform(
            0.0, min(self.cap_ms, self.base_ms * (2.0 ** attempt)))


# jitter quality does not need determinism in production; tests inject
# their own seeded Random
_RNG = Random()


def retry_call(
    fn: Callable,
    *args,
    policy: RetryPolicy,
    label: str,
    breaker: Optional[CircuitBreaker] = None,
    classify: Callable[[BaseException], str] = _classify,
    clock: Callable[[], float] = time.monotonic,
    sleep: Callable[[float], None] = time.sleep,
    rng: Optional[Random] = None,
    **kw,
):
    """Call `fn(*args, **kw)` under the retry/breaker fabric. Returns
    the call's result; raises the breaker's BreakerOpen, or the last
    error once retries are exhausted / ineligible."""
    rng = rng or _RNG
    attempt = 0
    while True:
        if breaker is not None:
            breaker.allow()
        try:
            out = fn(*args, **kw)
        except Exception as e:  # noqa: BLE001 — classification decides
            kind = classify(e)
            if breaker is not None and kind == "transient":
                # dependency-HEALTH signals only: a permanent error
                # (bad input) says nothing about the dependency, and
                # an OOM is a program-size signal with its own ladder
                # (halve the bucket, host-eval) — tripping the breaker
                # on OOM would fail-fast the very requests the ladder
                # exists to save
                breaker.record_failure()
            attempt += 1
            if kind != "transient" or attempt >= policy.max_attempts:
                raise
            delay_s = policy.backoff_ms(attempt - 1, rng) / 1000.0
            deadline = context.current_deadline()
            if deadline is not None and clock() + delay_s >= deadline:
                raise  # never retry past the request deadline
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.counter(f"fault.retry.{label}")
                context.RECOVERY.note("retry", label)
            except Exception:
                pass  # observability must never break the retry path
            sleep(delay_s)
            continue
        if breaker is not None:
            breaker.record_success()
        return out
