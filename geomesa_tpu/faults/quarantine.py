"""Poison-query quarantine.

A request that repeatedly crashes a kernel (permanent/OOM errors, not
shed/timeout/transient) must stop re-entering the dispatcher: each
crash costs a full dispatch, and a hot poison query can starve healthy
traffic while looking like "load". The registry keys strikes by the
request's coalescing fingerprint (serve.batcher.compat_key — same
canonical CQL + kind + kernel choice that would share a dispatch), and
after `strikes` crashes within `ttl_s` the service rejects the
fingerprint with a typed QueryRejected("quarantined", ...) at ADMISSION
— before it queues, before it dispatches.

Quarantine expires after `ttl_s` (a deploy may have fixed the kernel),
and the table is bounded so an adversarial stream of unique poison
queries cannot grow it without bound.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional, Tuple


class QuarantineRegistry:
    def __init__(self, strikes: int = 3, ttl_s: float = 600.0,
                 max_entries: int = 1024,
                 clock: Callable[[], float] = time.monotonic):
        if strikes < 1:
            raise ValueError("strikes must be >= 1")
        self.strikes = strikes
        self.ttl_s = ttl_s
        self.clock = clock
        self.max_entries = max_entries
        self._lock = threading.Lock()
        # key -> (strike_count, last_strike_at)
        self._strikes: Dict[object, Tuple[int, float]] = {}
        # key -> quarantined_at
        self._blocked: Dict[object, float] = {}

    def _expire(self, now: float) -> None:
        # callers hold self._lock
        dead = [k for k, at in self._blocked.items()
                if now - at >= self.ttl_s]
        for k in dead:
            del self._blocked[k]
        stale = [k for k, (_, at) in self._strikes.items()
                 if now - at >= self.ttl_s]
        for k in stale:
            del self._strikes[k]

    def empty(self) -> bool:
        """True when neither strikes nor quarantines exist — the
        admission hot path checks this BEFORE computing the fingerprint
        (a canonical-CQL serialization) so the steady state pays one
        lock acquisition, not an AST walk per request."""
        with self._lock:
            return not self._blocked and not self._strikes

    def blocked(self, key: object) -> Optional[str]:
        """A human-readable reason when `key` is quarantined, else
        None. Expiry is evaluated lazily here."""
        if key is None:
            return None
        now = self.clock()
        with self._lock:
            self._expire(now)
            at = self._blocked.get(key)
            if at is None:
                return None
            remaining = self.ttl_s - (now - at)
            return (f"query crashed {self.strikes}+ times; quarantined "
                    f"for another ~{remaining:.0f}s")

    def strike(self, key: object) -> bool:
        """Record one crash for `key`; returns True when this strike
        crossed the quarantine threshold."""
        if key is None:
            return False
        now = self.clock()
        with self._lock:
            self._expire(now)
            count, _ = self._strikes.get(key, (0, now))
            count += 1
            if count >= self.strikes and len(self._blocked) < self.max_entries:
                self._strikes.pop(key, None)
                self._blocked[key] = now
                tripped = True
            else:
                # below threshold — or the blocked table is full: keep
                # the strike history (clamped at the threshold) so the
                # key quarantines the moment capacity frees, instead of
                # resetting its own count and never quarantining while
                # falsely reporting tripped
                if key not in self._strikes and \
                        len(self._strikes) >= self.max_entries:
                    self._strikes.clear()  # bound adversarial streams
                self._strikes[key] = (min(count, self.strikes), now)
                tripped = False
            blocked_n = len(self._blocked)
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER
            from geomesa_tpu.utils.metrics import metrics

            if tripped:
                metrics.counter("fault.quarantined")
            metrics.gauge("fault.quarantine.active", blocked_n)
            RECORDER.note_event(
                "quarantine", action="trip" if tripped else "strike",
                key=repr(key), strikes=count)
        except Exception:
            pass
        return tripped

    def stats(self) -> dict:
        with self._lock:
            return {"quarantined": len(self._blocked),
                    "striking": len(self._strikes)}

    def clear(self) -> None:
        with self._lock:
            self._strikes.clear()
            self._blocked.clear()
