"""Device-OOM host-evaluation fallback.

When a dispatch exhausts device memory, the serve batcher first halves
the coalesced batch bucket (smaller stacked-query axis, smaller padded
program) and, for a request that still OOMs alone, evaluates it HERE:
full host scan, exact f64 filter evaluation via cql/hosteval.py, and a
NumPy haversine kNN — slow, but correct and device-free, so a memory-
squeezed server degrades to answers instead of errors.

Supported kinds: count, plain feature execute, knn. Aggregation hints
(density/stats/bin/arrow) have device-shaped outputs this path cannot
reproduce; those surface the original OOM as a typed error instead.
Results are equivalent to the device path on the same snapshot
(tests/test_faults.py asserts identity on a small workload: same
neighbor sets, same counts, distances to f32-noise tolerance).
"""

from __future__ import annotations

from typing import Tuple

import numpy as np

from geomesa_tpu.faults.errors import PermanentError


def _intercepted(source, query):
    """Run the planner's QueryInterceptor chain exactly like the device
    path does (plan() -> run_interceptors): a guard/rewrite configured
    on the type — e.g. a mandatory tenant-isolation filter — must bind
    on the host path too, or the fallback would return rows the device
    path excludes. run_interceptors marks the query, so the chain
    applies exactly once even for already-intercepted queries."""
    planner = getattr(source, "planner", None)
    interceptors = getattr(planner, "interceptors", None)
    if not interceptors:
        return query
    from geomesa_tpu.plan.interceptor import run_interceptors

    return run_interceptors(query, interceptors)


def _host_scan(source, query):
    """Materialize the source's rows on host (no device touch), with
    the same plan-time filter-column projection the device path uses
    left OFF — the host evaluator may need any referenced column."""
    from geomesa_tpu.core.columnar import FeatureBatch

    batches = list(source.storage.scan())
    if not batches:
        return None
    return FeatureBatch.concat(batches)


def _host_mask(source, query, batch) -> np.ndarray:
    from geomesa_tpu.cql.hosteval import eval_filter_host
    from geomesa_tpu.plan.runner import visibility_mask

    mask = eval_filter_host(query.filter_ast, batch)
    vm = visibility_mask(source.sft, batch, query.hints)
    if vm is not None:
        mask = mask & vm
    return mask


def host_count(source, query) -> int:
    query = _intercepted(source, query)
    batch = _host_scan(source, query)
    if batch is None:
        return 0
    n = int(_host_mask(source, query, batch).sum())
    if query.max_features is not None:
        n = min(n, query.max_features)
    return n


def host_execute(source, query):
    """Plain feature results (QueryResult kind="features")."""
    from geomesa_tpu.plan.planner import QueryResult
    from geomesa_tpu.plan.runner import finish_features

    query = _intercepted(source, query)
    h = query.hints
    if h.is_density or h.is_stats or h.is_bin or h.is_arrow:
        raise PermanentError(
            "host fallback cannot evaluate aggregation hints "
            "(density/stats/bin/arrow need the device)")
    if h.count_only:
        n = host_count(source, query)
        return QueryResult("count", count=n)
    batch = _host_scan(source, query)
    if batch is None:
        return QueryResult("features", features=None, count=0)
    sel = batch.select(_host_mask(source, query, batch))
    sel = finish_features(sel, query)
    return QueryResult("features", features=sel, count=len(sel))


def host_knn(source, query, qx, qy, k: int
             ) -> Tuple[np.ndarray, np.ndarray, object]:
    """Exact brute-force kNN on host: same (dists [Q,k] meters, idx
    [Q,k] into batch rows, batch) contract as planner.knn. Row order
    matches the device scan path (storage scan order), so indices are
    comparable on an identical snapshot."""
    from geomesa_tpu.core.columnar import FeatureBatch
    from geomesa_tpu.engine.geodesy import haversine_m_np
    from geomesa_tpu.plan.planner import _pad_to_k

    query = _intercepted(source, query)
    qx = np.asarray(qx, np.float64).ravel()
    qy = np.asarray(qy, np.float64).ravel()
    batch = _host_scan(source, query)
    if batch is None:
        sft = source.sft
        empty = FeatureBatch.from_pydict(
            sft, {a.name: [] for a in sft.attributes})
        return (np.full((len(qx), k), np.inf),
                np.zeros((len(qx), k), np.int32), empty)
    mask = _host_mask(source, query, batch)
    g = batch.sft.default_geometry
    col = batch.columns[g.name]
    cx = np.asarray(col.x, np.float64)
    cy = np.asarray(col.y, np.float64)
    kk = min(k, len(batch))
    dists = np.empty((len(qx), kk), np.float64)
    idx = np.empty((len(qx), kk), np.int64)
    for i in range(len(qx)):
        d = haversine_m_np(qx[i], qy[i], cx, cy)
        d = np.where(mask, d, np.inf)
        order = np.argsort(d, kind="stable")[:kk]
        idx[i] = order
        dists[i] = d[order]
    dists, idx = _pad_to_k(dists, idx.astype(np.int32), k)
    return dists, idx, batch


def host_fallback(source, req):
    """Resolve one ServeRequest on the host path; returns the value its
    future expects. `req` is a serve.scheduler.ServeRequest."""
    try:
        from geomesa_tpu.utils.metrics import metrics

        metrics.counter("fault.oom.hosteval")
    except Exception:
        pass
    if req.kind == "count":
        return host_count(source, req.query)
    if req.kind == "knn":
        return host_knn(source, req.query, req.qx, req.qy, req.k)
    return host_execute(source, req.query)
