"""Per-dependency circuit breakers (closed / open / half-open).

One breaker per dependency (storage, kafka, device, ...) shared by
every call site of that dependency in the process. Semantics:

  closed     — calls flow; consecutive transient failures count up.
  open       — after `failure_threshold` consecutive failures, calls
               are rejected immediately with the typed `BreakerOpen`
               (no queue time wasted on a dead dependency). After
               `reset_timeout_s` the next `allow()` transitions to
               half-open.
  half-open  — up to `half_open_max` probe calls pass; one success
               closes the breaker, one failure re-opens it (and
               restarts the reset clock).

Every transition is metrics-visible: gauge `fault.breaker.<name>`
(0=closed, 1=half-open, 2=open) plus counters
`fault.breaker.<name>.open|half_open|close` — the chaos checker asserts
open AND half-open transitions appeared under an outage plan.

The clock is injectable so the state machine is testable without
sleeps (tests/test_faults.py drives it with a fake clock).
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Optional

_STATE_NUM = {"closed": 0.0, "half_open": 1.0, "open": 2.0}


class BreakerOpen(RuntimeError):
    """Typed fail-fast rejection: the dependency's breaker is open.
    Carries `reason="breaker_open"` so protocol layers render it like
    the scheduler's QueryRejected family."""

    def __init__(self, dependency: str, retry_after_s: float):
        super().__init__(
            f"circuit breaker for {dependency!r} is open "
            f"(retry after ~{max(retry_after_s, 0.0):.2f}s)")
        self.dependency = dependency
        self.reason = "breaker_open"
        self.retry_after_s = max(retry_after_s, 0.0)


class CircuitBreaker:
    def __init__(self, name: str, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0, half_open_max: int = 1,
                 clock: Callable[[], float] = time.monotonic):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self.name = name
        self.failure_threshold = failure_threshold
        self.reset_timeout_s = reset_timeout_s
        self.half_open_max = half_open_max
        self.clock = clock
        self._lock = threading.Lock()
        self._state = "closed"
        self._failures = 0
        self._opened_at = 0.0
        self._probes = 0
        self._probe_at = 0.0  # when the last half-open probe was granted

    # -- state machine -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def _transition(self, state: str) -> None:
        # callers hold self._lock
        self._state = state
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER
            from geomesa_tpu.utils.metrics import metrics

            metrics.gauge(f"fault.breaker.{self.name}", _STATE_NUM[state])
            metrics.counter(
                f"fault.breaker.{self.name}."
                + ("close" if state == "closed" else state))
            # flight-recorder event: a breaker flip is exactly the kind
            # of context a last-N-queries postmortem needs alongside the
            # traces (a bounded deque append — not a blocking call)
            RECORDER.note_event("breaker", dependency=self.name,
                                state=state)
        except Exception:
            pass  # observability must never wedge the breaker

    def allow(self) -> None:
        """Gate a call: raises BreakerOpen when the dependency is open
        (or half-open with its probe budget spent)."""
        with self._lock:
            if self._state == "open":
                elapsed = self.clock() - self._opened_at
                if elapsed < self.reset_timeout_s:
                    raise BreakerOpen(
                        self.name, self.reset_timeout_s - elapsed)
                self._probes = 0
                self._transition("half_open")
            if self._state == "half_open":
                if self._probes >= self.half_open_max:
                    # a probe that never reported back (its failure was
                    # non-transient, so the retry fabric recorded
                    # neither success nor failure) must not wedge the
                    # breaker half-open forever: its slot goes stale
                    # after reset_timeout_s and a new probe round opens
                    since_probe = self.clock() - self._probe_at
                    if since_probe < self.reset_timeout_s:
                        raise BreakerOpen(
                            self.name,
                            self.reset_timeout_s - since_probe)
                    self._probes = 0
                self._probes += 1
                self._probe_at = self.clock()

    def record_success(self) -> None:
        with self._lock:
            self._failures = 0
            if self._state != "closed":
                self._transition("closed")

    def record_failure(self) -> None:
        with self._lock:
            self._failures += 1
            if self._state == "half_open" or (
                    self._state == "closed"
                    and self._failures >= self.failure_threshold):
                self._opened_at = self.clock()
                self._probes = 0
                if self._state != "open":
                    self._transition("open")
                else:  # pragma: no cover - defensive
                    self._opened_at = self.clock()

    def reset(self) -> None:
        with self._lock:
            self._failures = 0
            self._probes = 0
            if self._state != "closed":
                self._transition("closed")


class BreakerRegistry:
    """Lazy per-dependency breakers; `configure` (before first use or
    any time after) overrides thresholds — the chaos runner shrinks the
    reset timeout so open -> half-open -> closed plays out in-process."""

    def __init__(self):
        self._lock = threading.Lock()
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._config: Dict[str, dict] = {}

    def get(self, name: str) -> CircuitBreaker:
        with self._lock:
            b = self._breakers.get(name)
            if b is None:
                b = self._breakers[name] = CircuitBreaker(
                    name, **self._config.get(name, {}))
            return b

    def configure(self, name: str, **kw) -> CircuitBreaker:
        with self._lock:
            self._config[name] = kw
            b = self._breakers[name] = CircuitBreaker(name, **kw)
            return b

    def current_config(self, name: str) -> Optional[dict]:
        """The kwargs a prior `configure(name, ...)` installed, or None
        when the breaker runs on constructor defaults — pair with
        `restore_config` to scope a temporary override (the chaos
        runner must hand back whatever tuning the process had)."""
        with self._lock:
            cfg = self._config.get(name)
            return dict(cfg) if cfg is not None else None

    def restore_config(self, name: str, config: Optional[dict]) -> None:
        if config is not None:
            self.configure(name, **config)
            return
        with self._lock:
            self._config.pop(name, None)
            self._breakers[name] = CircuitBreaker(name)

    def states(self) -> Dict[str, str]:
        with self._lock:
            items = list(self._breakers.items())
        return {name: b.state for name, b in items}

    def reset(self, name: Optional[str] = None) -> None:
        with self._lock:
            items = ([self._breakers[name]] if name in self._breakers
                     else list(self._breakers.values())
                     if name is None else [])
        for b in items:
            b.reset()


BREAKERS = BreakerRegistry()
