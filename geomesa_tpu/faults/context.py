"""Per-request deadline propagation + per-dispatch recovery attribution.

Deadline scope: the planner's execute/knn entry points wrap their body
in ``deadline_scope(monotonic_deadline)`` so every retry loop at a
dependency boundary — however deep in the storage/Kafka/device stack —
can refuse to sleep past the request's remaining budget WITHOUT the
deadline being threaded through every call signature. Thread-local by
design: the serve dispatch thread runs one request group at a time.

RecoveryMeter: same token/since discipline as compilecache.stall.STALLS
— retry attempts and injected faults noted during one dispatch window
are charged to the requests that rode it (ServeEvent.retries /
ServeEvent.fault_injected).
"""

from __future__ import annotations

import contextlib
import threading
from typing import List, Optional, Tuple

_MAX_LOG = 8192

_tls = threading.local()


@contextlib.contextmanager
def deadline_scope(deadline: Optional[float]):
    """Set the current thread's absolute deadline (time.monotonic
    seconds) for the duration. None = no deadline. Nested scopes keep
    the TIGHTER deadline — an outer request budget must not be relaxed
    by an inner helper."""
    prev = getattr(_tls, "deadline", None)
    if deadline is None:
        eff = prev
    elif prev is None:
        eff = deadline
    else:
        eff = min(prev, deadline)
    _tls.deadline = eff
    try:
        yield eff
    finally:
        _tls.deadline = prev


def current_deadline() -> Optional[float]:
    """The calling thread's absolute deadline, or None."""
    return getattr(_tls, "deadline", None)


class RecoveryMeter:
    """Thread-safe bounded log of (seq, thread, kind, label) recovery
    events: kind "retry" (one backoff attempt at a boundary) or "fault"
    (one injected fault observed)."""

    def __init__(self, max_log: int = _MAX_LOG):
        self._lock = threading.Lock()
        self._seq = 0
        import collections

        self._log: "collections.deque" = collections.deque(maxlen=max_log)

    def note(self, kind: str, label: str) -> None:
        with self._lock:
            self._seq += 1
            self._log.append(
                (self._seq, threading.get_ident(), kind, label))

    def token(self) -> int:
        with self._lock:
            return self._seq

    def since(self, token: int,
              thread_ident: Optional[int] = None
              ) -> List[Tuple[str, str]]:
        """(kind, label) noted after `token`; with `thread_ident`, only
        events noted by that thread."""
        with self._lock:
            if self._seq == token:  # steady state: O(1) on the hot path
                return []
            return [(kind, label) for seq, tid, kind, label in self._log
                    if seq > token
                    and (thread_ident is None or tid == thread_ident)]


RECOVERY = RecoveryMeter()
