"""Typed transient/permanent error taxonomy for the recovery fabric.

Every dependency boundary (storage, Kafka, device transfer, kvstore,
compile cache) classifies failures into three kinds:

  transient  — worth retrying: I/O hiccups, connection resets, broker
               unavailability. Bounded retry with backoff applies.
  oom        — device memory exhaustion: NOT retried as-is (the same
               program would fail the same way); the serve layer halves
               the coalesced batch bucket and ultimately falls back to
               host evaluation (cql/hosteval.py).
  permanent  — bad input, schema drift, crashes: surfaced immediately,
               never retried, and counted toward poison-query quarantine.

The `FaultInjected` mixin marks exceptions raised by the injection
harness so the chaos checker can distinguish "an injected fault escaped
typed" (a bug) from organic failures. Injected classes subclass the
REAL exception families (OSError, ConnectionError, ...) so production
recovery code never special-cases injection — the fault path exercised
under test is byte-for-byte the path a real failure takes.
"""

from __future__ import annotations


class FaultInjected:
    """Marker mixin: this exception was raised by the fault harness."""


class TransientError(RuntimeError):
    """Explicitly-retryable dependency failure (base for wrappers)."""


class PermanentError(RuntimeError):
    """Explicitly non-retryable failure (bad input, unsupported path)."""


class DeviceOOM(MemoryError):
    """Device memory exhaustion (host->device transfer or kernel alloc).

    Real XLA OOMs surface as jaxlib XlaRuntimeError with a
    RESOURCE_EXHAUSTED status; `classify` maps those here by message so
    the recovery fabric needs no jaxlib import."""


class InjectedIOError(OSError, FaultInjected):
    """Injected storage/file I/O failure (transient)."""


class InjectedUnavailable(ConnectionError, FaultInjected):
    """Injected dependency-unavailable failure (transient)."""


class InjectedOOM(DeviceOOM, FaultInjected):
    """Injected device out-of-memory (oom)."""


class InjectedCrash(RuntimeError, FaultInjected):
    """Injected hard crash (permanent; feeds poison-query quarantine)."""


# FaultPlan `error` keys -> exception classes ("latency" injects delay
# only and maps to None)
ERROR_KINDS = {
    "io": InjectedIOError,
    "unavailable": InjectedUnavailable,
    "oom": InjectedOOM,
    "crash": InjectedCrash,
    "latency": None,
}

TYPED_ERRORS = (TransientError, PermanentError, DeviceOOM, OSError,
                ConnectionError)


def classify(exc: BaseException) -> str:
    """Map an exception to "transient" | "oom" | "permanent".

    Deadline expiry (plan.QueryTimeout subclasses TimeoutError and
    carries .phase) is permanent by definition — retrying past a blown
    deadline is the exact bug the fabric exists to prevent."""
    if isinstance(exc, DeviceOOM):
        return "oom"
    # real XLA OOM without importing jaxlib: status-name match
    name = type(exc).__name__
    if name == "XlaRuntimeError" and "RESOURCE_EXHAUSTED" in str(exc):
        return "oom"
    if isinstance(exc, PermanentError):
        return "permanent"
    if isinstance(exc, TimeoutError) and hasattr(exc, "phase"):
        return "permanent"  # QueryTimeout: the budget is gone
    if isinstance(exc, TransientError):
        return "transient"
    if isinstance(exc, (FileNotFoundError, PermissionError,
                        IsADirectoryError, NotADirectoryError)):
        # definitive filesystem answers, not flakiness: a missing file
        # (e.g. a compaction-raced read against an older manifest
        # snapshot) will be just as missing on attempt 4 — retrying
        # burns the backoff budget AND counts toward opening the
        # storage breaker on a perfectly healthy disk
        return "permanent"
    if isinstance(exc, (ConnectionError, TimeoutError)):
        return "transient"
    if isinstance(exc, OSError):
        return "transient"
    return "permanent"


def is_typed(exc: BaseException) -> bool:
    """True when the exception is part of the serving error contract:
    a client can act on it (retry, back off, fix the query). Used by
    the chaos checker to detect un-typed escapes."""
    if isinstance(exc, TYPED_ERRORS) or isinstance(exc, FaultInjected):
        return True
    # serve-layer typed signals, duck-typed to avoid import cycles
    if hasattr(exc, "reason"):  # QueryRejected / BreakerOpen
        return True
    if isinstance(exc, TimeoutError) and hasattr(exc, "phase"):
        return True  # QueryTimeout
    return False
