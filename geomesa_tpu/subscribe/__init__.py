"""geomesa_tpu.subscribe — standing queries over the Kafka live layer.

A client registers a long-lived predicate (CQL / BBOX / DWITHIN
geofence) or a density/heatmap window and receives incremental push
updates — enter/exit events, density folds — as Kafka batches fold in.
Every poll evaluates parametric geofences (bbox / dwithin / polygon)
as one [S]-batched lane dispatch per class and everything else in ONE
fused device dispatch (docs/SERVING.md "Standing queries").

    registry.py   Subscription state: matched-fid sets, decayed grids,
                  bounded outboxes, rate limits, lifecycle + TTL,
                  matched-set handoff snapshots
    lanes.py      lane classification + pow2 [S]-row parameter tables
                  (host side of engine/lanes.py; membership is a row
                  write, never a recompile)
    evaluator.py  delta-driven lane + fused evaluation hooked on
                  KafkaDataStore.poll (ExecutableRegistry-routed,
                  exactly-once per batch, quarantine fallback)
    manager.py    admission (tenant buckets, bounds, quarantine),
                  poll/flush driving, wire-layer glue
"""

from geomesa_tpu.subscribe.evaluator import DeltaEvaluator
from geomesa_tpu.subscribe.manager import (
    SubscribeConfig, SubscriptionManager)
from geomesa_tpu.subscribe.registry import (
    DensityWindow, Subscription, SubscriptionRegistry)

__all__ = [
    "DeltaEvaluator",
    "DensityWindow",
    "SubscribeConfig",
    "Subscription",
    "SubscriptionManager",
    "SubscriptionRegistry",
]
