"""geomesa_tpu.subscribe — standing queries over the Kafka live layer.

A client registers a long-lived predicate (CQL / BBOX / DWITHIN
geofence) or a density/heatmap window and receives incremental push
updates — enter/exit events, density folds — as Kafka batches fold in.
Every poll evaluates ALL registered standing queries in ONE fused
device dispatch (docs/SERVING.md "Standing queries").

    registry.py   Subscription state: matched-fid sets, decayed grids,
                  bounded outboxes, rate limits, lifecycle + TTL
    evaluator.py  delta-driven fused evaluation hooked on
                  KafkaDataStore.poll (ExecutableRegistry-routed,
                  exactly-once per batch, quarantine fallback)
    manager.py    admission (tenant buckets, bounds, quarantine),
                  poll/flush driving, wire-layer glue
"""

from geomesa_tpu.subscribe.evaluator import DeltaEvaluator
from geomesa_tpu.subscribe.manager import (
    SubscribeConfig, SubscriptionManager)
from geomesa_tpu.subscribe.registry import (
    DensityWindow, Subscription, SubscriptionRegistry)

__all__ = [
    "DeltaEvaluator",
    "DensityWindow",
    "SubscribeConfig",
    "Subscription",
    "SubscriptionManager",
    "SubscriptionRegistry",
]
