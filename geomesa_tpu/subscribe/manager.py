"""SubscriptionManager: admission, push and lifecycle glue.

The wire layer (serve/protocol.py `subscribe`/`unsubscribe` verbs) and
the bench loadgen talk to THIS class; the registry holds state, the
evaluator folds deltas (one fused device dispatch per poll). Admission
reuses the PR-2 serving fabric: per-tenant token buckets (the same
RateLimiter the QueryService uses — pass the service's limiter in so
queries and subscriptions draw from one budget), a bounded subscription
table, and the PR-5 poison quarantine keyed by predicate fingerprint —
a predicate that crashed evaluation out of the registry is rejected at
(re-)registration with a typed QueryRejected("quarantined") until the
TTL lapses.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable, Dict, Optional

from geomesa_tpu.subscribe.evaluator import DeltaEvaluator
from geomesa_tpu.subscribe.registry import (
    DensityWindow, Subscription, SubscriptionRegistry)
from geomesa_tpu.telemetry.trace import TRACER


@dataclasses.dataclass
class SubscribeConfig:
    max_subscriptions: int = 256     # admission bound (backpressure)
    outbox_limit: int = 1024         # per-subscription pending frames
    default_ttl_s: Optional[float] = None
    rate: Optional[float] = None     # per-subscription push frames/s
    rate_burst: float = 8.0
    # predicate quarantine (docs/ROBUSTNESS.md): strikes before a
    # crashing predicate is removed from evaluation; 0 disables
    quarantine_after: int = 3
    quarantine_ttl_s: float = 600.0
    # registration-rate tenant buckets (only used when no shared
    # limiter is passed in)
    tenant_rate: Optional[float] = None
    tenant_burst: float = 8.0
    # vmapped parametric lanes (subscribe/lanes.py): off forces every
    # predicate onto the fused-slot path — the bench's lane-vs-slot
    # comparison and parity tests flip this
    lanes: bool = True


class SubscriptionManager:
    def __init__(self, store, config: Optional[SubscribeConfig] = None,
                 limiter=None):
        self.store = store
        self.config = config or SubscribeConfig()
        self.registry = SubscriptionRegistry()
        if limiter is None:
            from geomesa_tpu.serve.scheduler import RateLimiter

            limiter = RateLimiter(self.config.tenant_rate,
                                  self.config.tenant_burst)
        self.limiter = limiter
        self.evaluator = DeltaEvaluator(
            store, self.registry,
            quarantine_after=self.config.quarantine_after,
            quarantine_ttl_s=self.config.quarantine_ttl_s,
            lanes=self.config.lanes)
        # serializes concurrent flushes (the --live-poll-ms pump thread
        # vs an explicit `poll` verb on the reader thread): without it,
        # two drains of the same outbox can interleave their writes
        # and deliver a subscription's frames out of seq order
        self._flush_lock = threading.Lock()
        # checkpoints(): last (watermark, status) handed out per
        # subscription — the seq-watermark cadence that keeps the
        # stats-probe piggyback from re-shipping unchanged snapshots
        self._checkpoint_marks: Dict[str, tuple] = {}

    # -- admission ---------------------------------------------------------

    def subscribe(
        self,
        type_name: str,
        cql: str = "INCLUDE",
        density: Optional[DensityWindow] = None,
        tenant: str = "",
        ttl_s: Optional[float] = None,
        rate: Optional[float] = None,
        outbox_limit: Optional[int] = None,
        initial_state: bool = True,
        handoff: Optional[dict] = None,
        paused: bool = False,
        ack: Optional[Callable[[Subscription], None]] = None,
    ) -> Subscription:
        """Register a standing query. Raises the serving layer's typed
        QueryRejected on admission failure (rate_limited /
        subscription_limit / quarantined / shutting_down analog), and
        ValueError for an invalid predicate — validation happens HERE,
        not at the first fold.

        `handoff` re-homes a standing query from another replica
        (docs/ROBUSTNESS.md): a Subscription.handoff_snapshot dict
        whose canonical CQL must match this registration's predicate.
        The new subscription continues the client's sequence numbers
        from the snapshot's delivered watermark and its first frame is
        a full `state` resync built from THIS replica's live snapshot,
        so the client reconciles instead of starting over. Predicate
        subscriptions only (density grids re-seed anyway).

        `paused=True` registers then immediately pauses, still inside
        the flush-excluded unit: the queued state frame stays in the
        outbox until resume (the fleet router re-homes a paused
        subscription with this — it lands paused, and the resume
        resync replaces the stale frame with current state).

        `ack` (the wire layer's subscribe response) runs under the
        flush lock, BEFORE any flusher — in particular the
        --live-poll-ms pump — can drain this subscription's outbox: the
        client always learns the subscription id before the first push
        frame that references it."""
        from geomesa_tpu.serve.scheduler import QueryRejected

        sft = self.store.get_schema(type_name)  # KeyError for unknown
        sub = Subscription(
            type_name, cql=cql, density=density, tenant=tenant,
            ttl_s=ttl_s if ttl_s is not None else self.config.default_ttl_s,
            outbox_limit=(outbox_limit if outbox_limit is not None
                          else self.config.outbox_limit),
            rate=rate if rate is not None else self.config.rate,
            rate_burst=self.config.rate_burst,
            initial_state=initial_state)
        if handoff is not None:
            if density is not None:
                raise ValueError(
                    "density subscriptions do not hand off: the grid "
                    "re-seeds from the live snapshot on re-subscribe")
            from geomesa_tpu.cql import parse_cql
            from geomesa_tpu.cql.ast import to_cql

            canon = to_cql(parse_cql(cql))
            if handoff.get("type") != type_name:
                raise ValueError(
                    f"handoff type {handoff.get('type')!r} does not "
                    f"match subscribe type {type_name!r}")
            if handoff.get("cql") != canon:
                raise ValueError(
                    f"handoff predicate {handoff.get('cql')!r} does "
                    f"not match subscribe predicate {canon!r}")
            # continue the client's numbering from the last frame the
            # old replica DELIVERED; the state resync frame queued
            # below (always — it replaces the missed tail) is the
            # next seq the client sees
            sub._seq = int(handoff.get("watermark", 0))
        if self.config.quarantine_after:
            detail = self.evaluator.quarantine.blocked(sub.fingerprint())
            if detail is not None:
                raise QueryRejected("quarantined", detail)
        self.limiter.admit(tenant)
        if density is None:
            # compile now: a bad CQL (unknown attribute, unsupported
            # op) is the CLIENT's error and must answer the subscribe
            # request, not crash the first fold
            self.evaluator._filter_for(type_name, cql, sft)
        elif density.weight_attr is not None:
            # same contract for the density weight column: a typo'd or
            # non-numeric attribute answers HERE, typed — not as a
            # KeyError from the first fold over a non-empty topic
            if density.weight_attr not in sft:
                raise ValueError(
                    f"density weight attribute {density.weight_attr!r} "
                    f"not in schema {type_name!r}")
            wtype = sft.attribute(density.weight_attr).type
            if wtype not in ("Integer", "Long", "Double", "Float"):
                raise ValueError(
                    f"density weight attribute {density.weight_attr!r} "
                    f"is {wtype}, not numeric")
        self.evaluator.watch(type_name)
        # register + initial frame + ack as one flush-excluded unit (a
        # racing pump flush waits); inside, bootstrap-then-register
        # runs under the per-type eval lock: a concurrent fold can
        # neither see the subscription baseline-less nor tear it
        with self._flush_lock:
            # bound check under the same lock as registration: checked
            # outside, two concurrent subscribes at capacity-1 both
            # pass and the table exceeds max_subscriptions
            if len(self.registry) >= self.config.max_subscriptions:
                raise QueryRejected(
                    "subscription_limit",
                    f"subscription table at capacity "
                    f"({self.config.max_subscriptions})")
            self.evaluator.admit(sub)
            if initial_state or handoff is not None:
                sub.queue_state_frame()
            if paused:
                self.registry.pause(sub.sub_id)
            if ack is not None:
                ack(sub)
        return sub

    def unsubscribe(self, sub_id: str) -> Subscription:
        return self.registry.cancel(sub_id)

    def pause(self, sub_id: str) -> Subscription:
        return self.registry.pause(sub_id)

    def resume(self, sub_id: str) -> Subscription:
        sub = self.registry.resume(sub_id)
        # re-seed NOW so the next flush (which may run before any fold)
        # pushes a `state` frame built from the live snapshot rather
        # than the pre-pause matched set / grid
        self.evaluator.resync(sub)
        return sub

    def checkpoints(self) -> Dict[str, dict]:
        """Handoff snapshots for every live PREDICATE subscription
        whose delivered watermark advanced since the last call — the
        seq-watermark cadence the fleet piggybacks on the stats probe
        (docs/ROBUSTNESS.md "Standing queries"): no new RPC, bounded
        staleness of one probe interval once the stream quiesces, and
        an unchanged subscription ships zero bytes. Density grids are
        skipped — they re-seed from the survivor's live snapshot on
        re-home, so there is nothing to checkpoint. Called on the wire
        connection's reader thread (the stats verb), same thread as
        subscribe/unsubscribe — the marks dict needs no lock."""
        out: Dict[str, dict] = {}
        live = {}
        for sub in self.registry.subs():
            if (sub.density is not None
                    or sub.status not in ("active", "paused")):
                continue
            snap = sub.handoff_snapshot()
            live[sub.sub_id] = True
            mark = self._checkpoint_marks.get(sub.sub_id)
            if mark == (snap["watermark"], snap["status"]):
                continue
            # gt: waive GT07
            # (reader-confined: the stats verb that calls this runs on
            # the connection's ONE reader thread — the same thread that
            # handles subscribe/unsubscribe — so the marks dict never
            # crosses threads; _flush_lock guards outbox drains only,
            # taking it here would stall the probe behind a flush)
            self._checkpoint_marks[sub.sub_id] = (
                snap["watermark"], snap["status"])
            out[sub.sub_id] = snap
        # prune marks of cancelled/expired subscriptions so a
        # long-lived connection's table does not grow forever
        for sid in list(self._checkpoint_marks):
            if sid not in live:
                del self._checkpoint_marks[sid]
        return out

    # -- driving -----------------------------------------------------------

    def poll_now(self) -> Dict[str, int]:
        """Poll every live topic with registered subscriptions; the
        store's fold hook pumps the evaluator, so by return every
        subscription's outbox holds this window's events. Typed broker
        errors (injected kafka.poll faults, BreakerOpen) propagate to
        the caller — the poll loop in the wire layer reports and
        retries on its own cadence."""
        out: Dict[str, int] = {}
        for name in self.registry.type_names():
            out[name] = self.store.poll(name)
        return out

    def flush(self, push: Callable[[dict], None]) -> int:
        """Drain every outbox through `push` (one dict frame per call),
        honoring per-subscription rate limits. A lagged subscription
        gets its `state` re-sync frame the moment its marker frame has
        been delivered. Returns frames pushed."""
        n = 0
        trace = TRACER.start_trace("subscribe.push")
        try:
            # ONE flusher at a time: drain order == write order, so a
            # subscription's frames always arrive in seq order even
            # when the pump thread races an explicit poll verb
            # gt: waive GT09
            # (deliberate: the push sink IS this lock's critical
            # section — see _flush_lock comment; flushers are the only
            # contenders and frame ordering is the product contract)
            with self._flush_lock:
                subs = self.registry.subs()
                parting = self.registry.take_parting()
                if trace is not None:
                    with TRACER.scope(trace):
                        with TRACER.span("subscribe.push",
                                         subs=len(subs)):
                            n = self._flush_all(subs, parting, push)
                else:
                    n = self._flush_all(subs, parting, push)
        finally:
            if trace is not None:
                from geomesa_tpu.telemetry.recorder import RECORDER

                RECORDER.record(trace.finish(status="ok", frames=n))
        if n:
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.counter("subscribe.push.frames", n)
            except Exception:
                pass
        return n

    def _flush_all(self, subs, parting, push) -> int:
        n = 0
        parting_ids = {s.sub_id for s in parting}
        pending = list(subs) + list(parting)
        for i, sub in enumerate(pending):
            if sub.status == "paused":
                continue  # a paused consumer holds its outbox
            frames = sub.drain()
            # the lagged marker (or a resume/resync) has been drained:
            # hand the client the full current state and resume
            # incremental delivery (checked-and-built atomically so a
            # racing offer cannot make the state frame outrun a queued
            # frame's seq)
            resync = sub.take_resync_frame()
            if resync is not None:
                frames.append(resync)
            try:
                for k, frame in enumerate(frames):
                    push(frame)
                    n += 1
            except BaseException:
                # a broken push sink must not lose drained-but-unpushed
                # frames or later parting subscriptions' terminal
                # frames: put both back so the next flush retries
                sub.requeue(frames[k:])
                self.registry.requeue_parting(
                    [s for s in pending[i:]
                     if s.sub_id in parting_ids])
                raise
        return n

    def close(self) -> None:
        """Cancel every live subscription AND release the store-side
        hooks (fold hook + cache listeners): a closed manager must not
        keep costing every future poll or pin its evaluator alive."""
        for sub in self.registry.subs():
            if sub.status in ("active", "paused"):
                self.registry.cancel(sub.sub_id)
        self.evaluator.detach()

    # -- introspection -----------------------------------------------------

    def stats(self) -> dict:
        out = self.registry.stats()
        out["evaluator"] = self.evaluator.stats()
        out["lanes"] = self.evaluator.lane_stats()
        out["quarantine"] = self.evaluator.quarantine.stats()
        return out
