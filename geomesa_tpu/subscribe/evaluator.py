"""Delta-driven incremental evaluation for standing queries.

The evaluation contract (docs/SERVING.md "Standing queries"):

- The Kafka layer is the only writer of live state. Every
  `KafkaDataStore.poll` folds a message window into the
  KafkaFeatureCache ATOMICALLY (offset-pinned — kafka/store.py); the
  cache's FeatureEvents for that window land in this module's per-type
  delta buffer via a non-blocking listener (lint rule GT17 enforces
  that listener bodies stay non-blocking), and the store's post-fold
  hook pumps the evaluator OUTSIDE the store lock.

- One poll = a HANDFUL of coalesced device dispatches, independent of
  how many subscriptions are registered: the window's changed rows
  stack into a single columnar delta (pow2-padded, so shapes repeat),
  lane-eligible geofences (bbox / dwithin / polygon — subscribe/
  lanes.py) evaluate as one [S]-axis-batched kernel per CLASS
  (engine/lanes.py) whose compiled program is independent of S —
  registration churn is a parameter-row write, zero recompiles within
  an [S]-bucket — and only the irregular remainder (compound CQL,
  attribute predicates, density windows) rides the FUSED kernel:
  every remaining predicate's compiled mask + f32 boundary band, plus
  every density window's cell binning, built per remainder-membership
  signature, registered with the compilecache ExecutableRegistry, and
  AOT-compiled per shape bucket. A steady subscription set therefore
  never recompiles per batch; lane-only churn never rebuilds the
  fused kernel at all.

- Exactly-once: buffered events are consumed only after a successful
  evaluation. An injected `kafka.poll` fault fails the poll BEFORE the
  fold (no events buffered); an infrastructure failure inside the
  evaluator (device transfer, injected `subscribe.eval` fault) leaves
  the buffer intact for the next poll — no missed events, and the
  diff-based state update (enter/exit = set difference against the
  previous matched set) makes re-evaluation idempotent, so no
  duplicates either.

- Exactness matches the one-shot planner: predicates evaluate on the
  same f32 device columns `to_device` builds, and rows flagged by the
  compiled filter's f32 boundary band are re-evaluated in f64 on host
  (cql/hosteval) before the matched-set diff — so the incremental
  matched set is bit-identical to a fresh planner query's fids.

- A predicate that CRASHES evaluation is struck against the faults/
  quarantine registry (keyed by predicate fingerprint, not sub id) and
  quarantined after the configured strikes — never retried forever.
  The crashing fold degrades to per-subscription evaluation so healthy
  subscriptions still get their events; a subscription that survives a
  crash re-syncs from the live snapshot on its next clean fold.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.faults import harness as _faults
from geomesa_tpu.subscribe.registry import (
    DensityWindow, Subscription, SubscriptionRegistry)
from geomesa_tpu.telemetry.recorder import RECORDER
from geomesa_tpu.telemetry.trace import TRACER
from geomesa_tpu.utils.padding import next_pow2

# evaluation boundary fault site (docs/ROBUSTNESS.md site catalog):
# fires once per fused evaluation, BEFORE any subscription state
# mutates — an injected failure must leave the delta buffer intact for
# the next poll (exactly-once), never half-apply a batch
_EVAL_SITE = _faults.site(
    "subscribe.eval", "standing-query fused delta evaluation")

_PAD_MIN = 16          # smallest delta bucket (tiny deltas share one shape)
_TABLE_PAD_MIN = 8     # smallest vocab-table bucket (string predicates)
_MAX_BUFFER = 65_536   # per-type delta buffer bound (overflow => resync)
_MAX_FILTERS = 256     # compiled-predicate cache bound (LRU-ish eviction)

_eval_ids = itertools.count(1)


def _infra_error(exc: BaseException) -> bool:
    """Infrastructure answer vs predicate crash — the serving layer's
    quarantine exemption (serve/service.py): the OSError family (even
    when classified permanent — a compaction-raced read) and transient
    failures say nothing about the PREDICATE being poisonous."""
    from geomesa_tpu.faults import classify

    return isinstance(exc, OSError) or classify(exc) == "transient"


class _TypeState:
    """Per-feature-type evaluator state. The eval lock serializes folds
    (delta windows apply in offset order — the store's poll already
    guarantees at-most-one fold per window); the buffer lock guards the
    listener-side event appends, which must stay cheap (GT17)."""

    def __init__(self, type_name: str):
        self.type_name = type_name
        self.eval_lock = threading.Lock()
        self.buf_lock = threading.Lock()
        self.buffer: List[tuple] = []   # (kind, fid, attrs-or-None)
        self.overflowed = False
        self.listening = False
        self.listener_fn = None
        # listener gate: True while the type plausibly has active
        # subscriptions. A plain bool (GIL-atomic) because the
        # listener runs per folded MESSAGE inside the store lock — a
        # registry lock + list build there would contend with every
        # subscribe/flush thread on the hottest path. Set on admit,
        # refreshed by each pump; a stale True costs one bounded
        # buffer until the next pump clears it.
        self.armed = False
        # fused-kernel cache: rebuilt when the REMAINDER membership
        # (the subscriptions actually riding the fused kernel) moves —
        # lane-side churn bumps the registry version but must never
        # rebuild the fused program, so the cache keys on the
        # remainder sub-id signature, not the version
        self.version = -1
        self.fused_sig: Optional[tuple] = None
        self.fused_name: Optional[str] = None
        self.fused_fn = None
        self.treedef = None
        self.pred_subs: List[str] = []
        self.dens_subs: List[str] = []
        # vmapped-lane membership (subscribe/lanes.py): same-shape
        # geofence classes as [S]-bucketed parameter tables; mutated
        # only under the eval lock
        self.lanes = None
        # approximate-density shared state (docs/SERVING.md
        # "Approximate answers"): ONE host-side world occupancy grid +
        # fid->cell map per type, folded from deltas with plain numpy —
        # every approx_density subscriber resamples it, so a
        # thousand-subscriber density fan-out costs ZERO device
        # dispatches per poll. Mutated only under the eval lock; the
        # per-fid last-cell map makes re-application idempotent
        # (exactly-once survives a partially applied window retry).
        self.approx_grid: Optional[np.ndarray] = None
        self.approx_cells: Dict[str, Tuple[int, int]] = {}
        self.approx_seeded = False


class DeltaEvaluator:
    """Incremental evaluator over one live store (KafkaDataStore duck
    type: `get_schema`, `cache`, `add_fold_hook`)."""

    def __init__(self, store, registry: SubscriptionRegistry,
                 quarantine=None, quarantine_after: int = 3,
                 quarantine_ttl_s: float = 600.0, lanes: bool = True):
        self.store = store
        self.registry = registry
        # vmapped parametric lanes (subscribe/lanes.py): off forces
        # every predicate onto the fused-slot path — the bench's
        # lane-vs-slot comparison and the parity tests use this
        self._lanes_enabled = lanes
        # quarantine_after=0 disables quarantine (the serve layer's
        # contract): strikes are never counted, a crashing predicate
        # just re-seeds and retries each fold
        self._quarantine_enabled = (quarantine is not None
                                    or quarantine_after > 0)
        if quarantine is None:
            from geomesa_tpu.faults import QuarantineRegistry

            quarantine = QuarantineRegistry(
                strikes=max(quarantine_after, 1), ttl_s=quarantine_ttl_s)
        self.quarantine = quarantine
        self._nonce = next(_eval_ids)
        self._types: Dict[str, _TypeState] = {}
        self._types_lock = threading.Lock()
        # compiled predicate cache, keyed by (type, cql): compile once
        # per predicate, shared across fused rebuilds
        self._filters: Dict[Tuple[str, str], object] = {}
        # serializes compile/insert/evict (subscribe-time validation on
        # the reader thread vs the pump's fused rebuild); steady-state
        # reads of live keys stay lock-free — eviction never removes a
        # key a live subscription references
        self._filters_lock = threading.Lock()
        # bootstrap-path cell-binning executables, keyed by window
        # geometry: per-instance so a closed manager's evaluator frees
        # them with it (a process-wide dict would grow one entry per
        # distinct window for the server's lifetime)
        self._cells_cache: Dict[tuple, object] = {}
        self._counters: Dict[str, int] = {}
        self._counters_lock = threading.Lock()
        store.add_fold_hook(self.pump)

    # -- counters ----------------------------------------------------------

    def _bump(self, name: str, n: int = 1) -> None:
        with self._counters_lock:
            self._counters[name] = self._counters.get(name, 0) + n

    def stats(self) -> Dict[str, int]:
        with self._counters_lock:
            out = dict(self._counters)
        for k in ("folds", "dispatches", "events", "fallbacks",
                  "resyncs", "eval_errors"):
            out.setdefault(k, 0)
        return out

    # -- wiring ------------------------------------------------------------

    def _state(self, type_name: str) -> _TypeState:
        with self._types_lock:
            st = self._types.get(type_name)
            if st is None:
                st = self._types[type_name] = _TypeState(type_name)
            return st

    def watch(self, type_name: str) -> None:
        """Attach the delta listener to the type's cache (idempotent).
        Called by the manager when the first subscription for a type
        registers; detach() removes it again — it no-ops (cheap check)
        while no subscription is active."""
        st = self._state(type_name)
        with st.buf_lock:
            if st.listening:
                return
            st.listening = True
            st.listener_fn = self._listener(st)
        self.store.cache(type_name).add_listener(st.listener_fn)

    def admit(self, sub: Subscription) -> None:
        """Bootstrap-then-register as one unit UNDER the per-type eval
        lock: a concurrent fold (the --live-poll-ms pump) can neither
        evaluate the subscription before its baseline state exists nor
        overwrite a baseline mid-diff. Events buffered while the
        bootstrap snapshot is read are re-evaluated by the next fold —
        the diff-based state update makes that idempotent."""
        st = self._state(sub.type_name)
        with st.eval_lock:
            st.armed = True  # before register: no event window is missed
            self.bootstrap(sub)
            self.registry.register(sub)

    def resync(self, sub: Subscription) -> None:
        """Eagerly re-seed a subscription from the live snapshot under
        the per-type eval lock (resume path: folds applied while the
        subscription was paused never reached its state, so the next
        flush must hand the client CURRENT state, not pre-pause state)."""
        st = self._state(sub.type_name)
        with st.eval_lock:
            if sub._resync_pending():
                self._resync(sub)

    def detach(self) -> None:
        """Release every store-side hook this evaluator installed (the
        fold hook and per-type cache listeners), so a closed manager —
        one wire connection's worth of standing queries — stops costing
        every future poll and becomes collectable."""
        try:
            self.store.remove_fold_hook(self.pump)
        except (AttributeError, ValueError):
            pass
        with self._types_lock:
            states = list(self._types.values())
        from geomesa_tpu.compilecache.registry import registry as aot

        for st in states:
            st.armed = False
            if st.fused_name is not None:
                aot.unregister(st.fused_name)
                st.fused_name = None
            with st.buf_lock:
                fn, st.listener_fn = st.listener_fn, None
                st.listening = False
                st.buffer.clear()
            if fn is not None:
                try:
                    self.store.cache(st.type_name).remove_listener(fn)
                except (KeyError, ValueError):
                    pass

    def _listener(self, st: _TypeState):
        def on_feature_event(event) -> None:
            # GT17: listener body — buffer append only, no blocking
            # calls (no I/O, no device work, no future waits); the
            # heavy lifting happens in pump(), after the store's fold
            if not st.armed:
                return
            with st.buf_lock:
                if len(st.buffer) >= _MAX_BUFFER:
                    st.buffer.clear()
                    st.overflowed = True
                st.buffer.append((event.kind, event.fid,
                                  event.attributes))

        return on_feature_event

    # -- registration-time state -------------------------------------------

    def bootstrap(self, sub: Subscription) -> None:
        """Seed a subscription's state from the CURRENT live snapshot
        (one-shot semantics), so subsequent folds are pure increments.
        Also the re-sync path after a crashed or overflowed fold."""
        sft = self.store.get_schema(sub.type_name)
        if sub.density is not None and sub.density.approx:
            # sketch-backed window: seed the SHARED per-type grid once
            # (host-side, no device work), then this sub's resample
            st = self._state(sub.type_name)
            self._seed_approx_shared(st, sft)
            self._apply_approx(st, sub, offer=False)
            return
        snap = self.store.cache(sub.type_name).snapshot()
        if sub.density is not None:
            cells = None
            if snap is not None and len(snap):
                rows, cols, inb = self._density_cells_host(
                    sub.density, sft, snap)
                w = self._weights(sub.density, snap)
                cells = (rows, cols, inb, w, _batch_fids(snap))
            # mutate under the subscription lock so a flush racing the
            # re-seed never serializes a half-built grid (same
            # discipline as _apply_density)
            with sub._lock:
                sub.grid[:] = 0.0
                sub.contrib.clear()
                if cells is not None:
                    rows, cols, inb, w, fids = cells
                    for j in range(len(fids)):
                        if inb[j]:
                            sub.grid[rows[j], cols[j]] += w[j]
                            sub.contrib[fids[j]] = (
                                int(rows[j]), int(cols[j]), float(w[j]))
            return
        compiled = self._filter_for(sub.type_name, sub.cql, sft)
        matched: set = set()
        if snap is not None and len(snap):
            from geomesa_tpu.engine.device import to_device

            padded = snap.pad_to(next_pow2(max(len(snap), _PAD_MIN)))
            # gt: waive GT09
            # (deliberate: bootstrap runs under the per-type eval lock
            # by design — the fold serialization IS the consistency
            # boundary; registration/resync cold path)
            dev = to_device(padded)
            mask = compiled.mask_refined(dev, padded)[: len(snap)]
            fids = _batch_fids(snap)
            matched = {fids[j] for j in range(len(snap)) if mask[j]}
        sub.matched = matched

    def _filter_for(self, type_name: str, cql: str, sft):
        key = (type_name, cql)
        got = self._filters.get(key)  # lock-free hot-path hit
        if got is None:
            from geomesa_tpu.cql import parse_cql
            from geomesa_tpu.cql.compile import compile_filter

            got = compile_filter(parse_cql(cql), sft)
            with self._filters_lock:
                if len(self._filters) >= _MAX_FILTERS:
                    # a connection looping subscribe/unsubscribe over
                    # distinct predicates (shifting geofences) must not
                    # grow this monotonically: evict compiled filters no
                    # live subscription references (insertion order —
                    # oldest first; an evicted-but-needed one recompiles)
                    live = {(s.type_name, s.cql)
                            for s in self.registry.subs() if s.cql}
                    for k in [k for k in self._filters if k not in live]:
                        if len(self._filters) < _MAX_FILTERS:
                            break
                        del self._filters[k]
                got = self._filters.setdefault(key, got)
        return got

    # -- density helpers ---------------------------------------------------

    @staticmethod
    def _cells_device(d: DensityWindow, x, y, valid):
        """Grid-cell binning for one density window, INSIDE the fused
        jit — the exact arithmetic of engine.density.density_grid (f32
        coords, weak-typed bbox operands), so incremental folds land in
        the same cells the one-shot density kernel would."""
        import jax.numpy as jnp

        xmin, ymin, xmax, ymax = d.bbox
        dx = (xmax - xmin) / d.width
        dy = (ymax - ymin) / d.height
        col = jnp.floor((x - xmin) / dx).astype(jnp.int32)
        row = jnp.floor((y - ymin) / dy).astype(jnp.int32)
        inb = ((col >= 0) & (col < d.width)
               & (row >= 0) & (row < d.height) & valid)
        return (jnp.clip(row, 0, d.height - 1),
                jnp.clip(col, 0, d.width - 1), inb)

    def _density_cells_host(self, d: DensityWindow, sft, batch):
        """Bootstrap-path binning: one jitted call over a snapshot
        (cold path; the per-poll folds ride the fused kernel)."""
        import jax

        from geomesa_tpu.engine.device import VALID, to_device

        padded = batch.pad_to(next_pow2(max(len(batch), _PAD_MIN)))
        # gt: waive GT09
        # (deliberate: runs under the per-type eval lock — fold
        # serialization is the point; bootstrap/fallback cold path)
        dev = to_device(padded)
        g = _geom_name(sft)
        # gt: waive GT09
        # (deliberate: same eval-lock serialization as above)
        rows, cols, inb = jax.device_get(self._cells_jit(
            d, dev[f"{g}__x"], dev[f"{g}__y"], dev[VALID]))
        n = len(batch)
        return rows[:n], cols[:n], inb[:n]

    def _cells_jit(self, d: DensityWindow, x, y, valid):
        import jax

        key = (d.bbox, d.width, d.height)
        cells_exec = self._cells_cache.get(key)
        if cells_exec is None:
            cells_exec = jax.jit(
                lambda x, y, v, _d=d: self._cells_device(_d, x, y, v))
            self._cells_cache[key] = cells_exec
        # gt: waive GT09
        # (deliberate: the per-type eval lock EXISTS to serialize fold
        # evaluation — device work is its whole body, same stance as
        # the device-cache residency uploads; cold path, snapshots only)
        return cells_exec(x, y, valid)

    def _weights(self, d: DensityWindow, batch) -> np.ndarray:
        if d.weight_attr is None:
            return np.ones(len(batch), np.float64)
        col = batch.columns[d.weight_attr]
        return np.asarray(col, np.float64)

    # -- the fused kernel --------------------------------------------------

    def _fused_for(self, st: _TypeState, sft, subs: List[Subscription],
                   version: int):
        """(Re)build the fused evaluation kernel when its MEMBERSHIP
        (the remainder subscriptions riding it) moved; otherwise
        return the cached registration. The kernel closes over
        predicate structure and density geometry; per-batch VALUES
        (vocab tables, device columns) arrive as arguments, so
        repeated shapes are AOT-registry hits. Keyed on the sub-id
        signature, NOT the registry version: sub ids are never reused,
        so signature equality implies identical membership (and
        pause/resume round-trips re-hit the cached kernel), while
        lane-side churn — which bumps the version every registration —
        never rebuilds the fused program."""
        sig = tuple(s.sub_id for s in subs)
        if st.fused_name is not None and st.fused_sig == sig:
            return st.fused_name
        if st.fused_name is not None:
            # membership moved: the stale version's kernel and its AOT
            # executables are unreachable — drop them, or subscription
            # churn grows the process-global registry forever
            from geomesa_tpu.compilecache.registry import registry as aot

            aot.unregister(st.fused_name)
        pred = [s for s in subs if s.density is None]
        # approx windows NEVER join the fused device kernel — they fold
        # host-side into the shared grid (the whole point: no per-poll
        # device dispatch for dashboard density fan-out)
        dens = [s for s in subs
                if s.density is not None and not s.density.approx]
        filters = [self._filter_for(st.type_name, s.cql, sft) for s in pred]
        windows = [s.density for s in dens]
        geom = _geom_name(sft)
        mask_fns = [f.mask_fn() for f in filters]
        band_fns = [f._band_fn for f in filters]
        cells_device = self._cells_device

        def fused(params_list, dev):
            import jax.numpy as jnp

            from geomesa_tpu.engine.device import VALID

            n = dev[VALID].shape[0]
            if mask_fns:
                masks = jnp.stack([fn(p, dev)
                                   for fn, p in zip(mask_fns, params_list)])
                bands = jnp.stack([
                    bf(p, dev) if bf is not None
                    else jnp.zeros(n, bool)
                    for bf, p in zip(band_fns, params_list)])
            else:
                masks = jnp.zeros((0, n), bool)
                bands = masks
            cells = tuple(
                cells_device(d, dev[f"{geom}__x"], dev[f"{geom}__y"],
                             dev[VALID])
                for d in windows)
            return masks, bands, cells

        st.fused_fn = fused
        st.version = version
        st.fused_sig = sig
        st.treedef = None  # re-derived at the first call
        # the version keeps the name unique across rebuilds (a
        # membership change always bumps it); equal signatures never
        # reach here, so a stale name is never re-registered
        st.fused_name = (f"subscribe.eval.{st.type_name}"
                         f".e{self._nonce}.v{version}")
        st.pred_subs = [s.sub_id for s in pred]
        st.dens_subs = [s.sub_id for s in dens]
        return st.fused_name

    def _eval_fused(self, st: _TypeState, sft, subs, version, delta, dev):
        """ONE device dispatch for every registered standing query:
        route the fused kernel through the ExecutableRegistry (AOT per
        shape bucket — zero recompiles per batch for a steady
        subscription set), then one combined device_get."""
        import jax
        from jax import tree_util

        from geomesa_tpu.compilecache.registry import registry as aot

        name = self._fused_for(st, sft, subs, version)
        pred_ids = set(st.pred_subs)
        pred = [s for s in subs if s.sub_id in pred_ids]
        params_list = []
        for s in pred:
            f = self._filters[(st.type_name, s.cql)]
            params_list.append(_pad_tables(f.params(delta)))
        leaves, treedef = tree_util.tree_flatten((params_list, dev))
        # register on the first call after a (re)build — _fused_for
        # resets treedef to None — or if the params structure shifted
        # (it cannot for a fixed version, but a re-register is safe)
        if st.treedef is None or st.treedef != treedef:
            st.treedef = treedef
            fused = st.fused_fn

            def fused_flat(*leaves, _td=treedef, _fn=fused):
                p, d = tree_util.tree_unflatten(_td, leaves)
                return _fn(p, d)

            aot.register(name, fused_flat)
        handle = aot.compile(name, *leaves)
        self._bump("dispatches")
        t0 = time.perf_counter()
        # gt: waive GT09
        # (deliberate: THE fused dispatch — one per poll — runs under
        # the per-type eval lock because fold order is the exactly-once
        # contract; contending pollers of other types take other locks)
        out = jax.device_get(handle.call(*leaves))
        try:
            from geomesa_tpu.utils.metrics import metrics

            metrics.histogram("subscribe.eval").update(
                time.perf_counter() - t0)
        except Exception:
            pass
        masks, bands, cells = out
        return pred, masks, bands, cells

    # -- pump: fold one delta window ---------------------------------------

    def pump(self, type_name: str) -> int:
        """Fold buffered FeatureEvents for `type_name` into every
        registered subscription. Called by the store's post-fold hook
        (outside the store lock) and by the manager's poll loop.
        Returns the number of events consumed; 0 when the buffer is
        empty or evaluation must be retried (buffer retained)."""
        st = self._state(type_name)
        self.registry.expire_tick()
        with st.eval_lock:
            return self._pump_locked(st)

    def _pump_locked(self, st: _TypeState) -> int:
        with st.buf_lock:
            events = list(st.buffer)
            n_ev = len(events)
            overflowed = st.overflowed
        version, subs = self.registry.active_snapshot(st.type_name)
        st.armed = bool(subs)  # refresh the listener gate
        if not subs:
            with st.buf_lock:
                del st.buffer[:n_ev]
                st.overflowed = False
            return n_ev
        if overflowed:
            # the delta buffer overflowed between pumps: incremental
            # continuity is lost — re-seed every subscription from the
            # live snapshot and tell clients via lagged/state frames.
            # Consume the buffer and clear the flag BEFORE the (slow)
            # re-seed: resetting after it would erase a SECOND overflow
            # landing mid-re-seed (its fresh events deleted, its flag
            # cleared — silent divergence). Everything cleared here is
            # covered by the bootstrap snapshots (the listener fires
            # after the cache mutation), and events landing after the
            # clear stay queued for the next pump, whose diff-based
            # application is idempotent against the fresh baseline.
            with st.buf_lock:
                st.buffer.clear()
                st.overflowed = False
            # the shared approx grid missed the overflowed window too:
            # force its next bootstrap to re-seed from the live snapshot
            st.approx_seeded = False
            for sub in subs:
                try:
                    self.bootstrap(sub)
                    with sub._lock:
                        sub.lagged = True
                except Exception as e:  # noqa: BLE001 — strike, don't spread
                    # a crashing re-seed must not escape to the store's
                    # poll (untyped error to every caller) or cost the
                    # other subscriptions their re-seed
                    self._strike(sub, e)
            self._bump("resyncs", len(subs))
            return n_ev
        if not events:
            return 0
        changed, removed, cleared = _coalesce(events)
        trace = TRACER.start_trace(
            "subscribe.eval", type=st.type_name, subs=len(subs),
            delta=len(changed) + len(removed))
        status = "ok"
        try:
            if trace is not None:
                with TRACER.scope(trace):
                    with TRACER.span("subscribe.eval", type=st.type_name,
                                     subs=len(subs)):
                        consumed = self._fold(st, subs, version, changed,
                                              removed, cleared)
            else:
                consumed = self._fold(st, subs, version, changed,
                                      removed, cleared)
        except Exception as e:  # noqa: BLE001 — taxonomy + retry contract
            # infrastructure failure (device transfer, injected
            # subscribe.eval fault): NOTHING was applied — keep the
            # buffer so the next poll retries the whole window
            # (exactly-once), surface through metrics + flight recorder
            status = "error"
            self._bump("eval_errors")
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.counter("subscribe.eval.errors")
            except Exception:
                pass
            RECORDER.note_event("subscribe", action="eval_error",
                                type=st.type_name,
                                error=f"{type(e).__name__}: {e}")
            return 0
        finally:
            if trace is not None:
                RECORDER.record(trace.finish(status=status))
        with st.buf_lock:
            del st.buffer[:n_ev]
        self._bump("folds")
        return consumed

    def _fold(self, st: _TypeState, subs, version, changed, removed,
              cleared: bool) -> int:
        sft = self.store.get_schema(st.type_name)
        _EVAL_SITE.fire()
        # an all-approx subscription set never touches the device — not
        # even the delta upload: the shared host grid is the entire
        # evaluation, so the thousand-subscriber dashboard fan-out pays
        # zero device work per poll
        needs_device = any(
            s.density is None or not s.density.approx for s in subs)
        delta, dev, fids = self._delta_batch(sft, changed,
                                             device=needs_device)
        try:
            # lane-eligible geofences first: one [S]-batched dispatch
            # per CLASS (membership reconciled as row writes), then the
            # fused kernel over only the irregular remainder — skipped
            # entirely when nothing rides it
            lane_members, remainder = self._lane_sync(st, sft, subs)
            lane_rows = self._eval_lanes(st, sft, lane_members, dev)
            fused_live = any(
                s.density is None or not s.density.approx
                for s in remainder)
            pred, masks, bands, cells = (
                self._eval_fused(st, sft, remainder, version, delta,
                                 dev)
                if (delta is not None and fused_live) else (
                    [s for s in remainder if s.density is None], None,
                    None, None))
        except Exception as e:
            if _infra_error(e):
                # infrastructure answer (device transfer, raced read,
                # injected transient), not a poisonous predicate: no
                # state was applied — propagate so _pump_locked keeps
                # the buffer and the next poll retries the window
                raise
            # a crashing fused or lane kernel: degrade to
            # per-subscription evaluation so the poisonous predicate is
            # identified and struck while healthy subscriptions still
            # fold this window
            self._bump("fallbacks")
            self._fold_fallback(st, sft, subs, delta, dev, fids,
                                changed, removed, cleared)
            return len(changed) + len(removed) + (1 if cleared else 0)
        dens = [s for s in remainder
                if s.density is not None and not s.density.approx]
        approx_dens = [s for s in subs
                       if s.density is not None and s.density.approx]
        # lane subscriptions: per-row slices of the lane masks get the
        # same f64 band refinement and strike protection as fused rows
        for _group, members in lane_members:
            for sub, _row in members:
                try:
                    if sub._resync_pending():
                        self._resync(sub)
                        continue
                    pair = lane_rows.get(sub.sub_id)
                    mask = (self._refine_mask(st, sub, pair[0], pair[1],
                                              delta, fids)
                            if pair is not None else np.zeros(0, bool))
                    self._apply_predicate(sub, fids, mask, removed,
                                          cleared)
                except Exception as e:  # noqa: BLE001 — strike
                    self._strike(sub, e)
        # the per-subscription apply phase gets the same strike
        # protection as the fallback path: a predicate that crashes
        # only HERE (host-band refinement, density weights) must be
        # struck and quarantined, not retried forever via the
        # buffer-retaining outer except — and one crash must not cost
        # the other subscriptions their window
        for i, sub in enumerate(pred):
            try:
                if sub._resync_pending():
                    self._resync(sub)
                    continue
                mask = self._refined_row(st, sub, masks, bands, i,
                                         delta, fids)
                self._apply_predicate(sub, fids, mask, removed, cleared)
            except Exception as e:  # noqa: BLE001 — strike, don't spread
                self._strike(sub, e)
        for i, sub in enumerate(dens):
            try:
                if sub._resync_pending():
                    self._resync(sub)
                    continue
                cell = None if cells is None else cells[i]
                self._apply_density(sub, delta, fids, cell, removed,
                                    cleared)
            except Exception as e:  # noqa: BLE001 — strike, don't spread
                self._strike(sub, e)
        if approx_dens:
            # sketch-backed windows: ONE shared host fold per type
            # (idempotent — per-fid last-cell map), then a per-sub
            # resample + typed approx_density frame. No device work.
            changed_any = self._fold_approx_shared(
                st, sft, delta, fids, removed, cleared)
            for sub in approx_dens:
                try:
                    if sub._resync_pending():
                        self._resync(sub)
                        continue
                    if changed_any:
                        self._apply_approx(st, sub)
                except Exception as e:  # noqa: BLE001 — strike, not spread
                    self._strike(sub, e)
        return len(changed) + len(removed) + (1 if cleared else 0)

    # -- lanes -------------------------------------------------------------

    def _lane_sync(self, st: _TypeState, sft, subs):
        """Reconcile lane membership against this fold's atomic
        registry snapshot (row writes only — subscribe/lanes.py);
        returns ([(group, [(sub, row)])], remainder). Lanes disabled
        (SubscribeConfig.lanes=False) routes everything fused."""
        if not self._lanes_enabled:
            return [], list(subs)
        if st.lanes is None:
            from geomesa_tpu.subscribe.lanes import LaneTable

            st.lanes = LaneTable()

        def spec_for(sub):
            from geomesa_tpu.subscribe.lanes import classify

            f = self._filter_for(st.type_name, sub.cql, sft)
            return classify(f.filter_ast, sft)

        return st.lanes.sync(subs, spec_for)

    def _eval_lanes(self, st: _TypeState, sft, lane_members, dev):
        """One device dispatch per lane CLASS — an [S]-batched kernel
        whose compiled program is independent of S (engine/lanes.py) —
        fetched once and sliced per member row. Returns
        {sub_id: (mask_row, band_row)} over the padded delta."""
        if dev is None or not lane_members:
            return {}
        import jax

        from geomesa_tpu.engine import lanes as lane_kernels
        from geomesa_tpu.engine.device import VALID

        g = _geom_name(sft)
        x, y, valid = dev[f"{g}__x"], dev[f"{g}__y"], dev[VALID]
        out = {}
        for group, members in lane_members:
            kern = getattr(lane_kernels, f"lane_{group.cls}")
            self._bump("dispatches")
            self._bump("lane_dispatches")
            t0 = time.perf_counter()
            with TRACER.span("subscribe.lane.eval", cls=group.cls,
                             rows=len(members), bucket=group.cap):
                # gt: waive GT09
                # (deliberate: the lane dispatch runs under the
                # per-type eval lock — fold order is the exactly-once
                # contract, same stance as the fused dispatch)
                mask, band = jax.device_get(
                    kern(group.params, group.active, x, y, valid))
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.histogram("lane.eval").update(
                    time.perf_counter() - t0)
            except Exception:
                pass  # observability must never fail the fold
            for sub, row in members:
                out[sub.sub_id] = (mask[row], band[row])
        return out

    def lane_stats(self) -> dict:
        """Lanes introspection (manager.stats `lanes` section): per-
        class row counts/capacities plus the typed `lane_ineligible`
        reasons for the currently-registered predicate set."""
        with self._types_lock:
            states = list(self._types.values())
        classes: Dict[str, dict] = {}
        ineligible: Dict[str, int] = {}
        for st in states:
            if st.lanes is None:
                continue
            s = st.lanes.stats()
            for cls, c in s["classes"].items():
                agg = classes.setdefault(cls, {"rows": 0, "capacity": 0})
                agg["rows"] += c["rows"]
                agg["capacity"] += c["capacity"]
            for why, n in s["ineligible"].items():
                ineligible[why] = ineligible.get(why, 0) + n
        return {"enabled": self._lanes_enabled, "classes": classes,
                "ineligible": ineligible}

    # -- refinement --------------------------------------------------------

    def _refined_row(self, st, sub, masks, bands, i, delta, fids):
        """One fused-slot predicate's delta mask with f32 boundary-band
        rows re-evaluated exactly in f64 on host (the planner's
        refinement discipline, applied to just the delta)."""
        if masks is None:
            return np.zeros(0, bool)
        return self._refine_mask(st, sub, masks[i], bands[i], delta,
                                 fids)

    def _refine_mask(self, st, sub, mask_row, band_row, delta, fids):
        """Shared by the fused and lane apply phases: copy the row,
        re-evaluate its band-flagged entries in f64 (cql/hosteval)."""
        n = len(fids)
        mask = np.asarray(mask_row[:n]).copy()
        band = np.asarray(band_row[:n])
        idx = np.nonzero(band)[0]
        if len(idx):
            from geomesa_tpu.cql.hosteval import eval_filter_host

            # via _filter_for, not the dict: past _MAX_FILTERS live
            # predicates the cache evicts, and an evicted-but-needed
            # filter must recompile, not strike the subscription
            sub_filter = self._filter_for(
                st.type_name, sub.cql,
                self.store.get_schema(st.type_name))
            mask[idx] = eval_filter_host(
                sub_filter.filter_ast, delta.select(idx))
        return mask

    def _apply_predicate(self, sub: Subscription, fids, mask,
                         removed, cleared: bool) -> None:
        prev = sub.matched
        new = set() if cleared else set(prev)
        for fid in removed:
            new.discard(fid)
        for j, fid in enumerate(fids):
            if mask[j]:
                new.add(fid)
            else:
                new.discard(fid)
        enters = sorted(new - prev)
        exits = sorted(prev - new)
        sub.matched = new
        if enters:
            sub.offer({"event": "enter", "fids": enters})
            self._bump("events", len(enters))
        if exits:
            sub.offer({"event": "exit", "fids": exits})
            self._bump("events", len(exits))

    def _apply_density(self, sub: Subscription, delta, fids, cell,
                       removed, cleared: bool) -> None:
        d = sub.density
        grid = sub.grid
        changed_any = False
        if cell is not None and len(fids):
            rows, cols, inb = (np.asarray(c[: len(fids)]) for c in cell)
            w = self._weights(d, delta)[: len(fids)]
        exact = d.decay is None
        # in-place grid/contrib mutation under the subscription lock:
        # a racing flush (resync_frame after a lagged window) reads
        # the grid under the same lock, so it never serializes a
        # half-applied fold
        with sub._lock:
            if cleared:
                if sub.contrib or grid.any():
                    changed_any = True
                grid[:] = 0.0
                sub.contrib.clear()
            if d.decay is not None and d.decay < 1.0:
                grid *= d.decay
                changed_any = changed_any or bool(grid.any())
            for fid in removed:
                old = sub.contrib.pop(fid, None)
                if old is not None and exact:
                    grid[old[0], old[1]] -= old[2]
                    changed_any = True
            if cell is not None and len(fids):
                for j, fid in enumerate(fids):
                    old = sub.contrib.pop(fid, None)
                    if old is not None and exact:
                        grid[old[0], old[1]] -= old[2]
                        changed_any = True
                    if inb[j]:
                        grid[rows[j], cols[j]] += w[j]
                        sub.contrib[fid] = (int(rows[j]), int(cols[j]),
                                            float(w[j]))
                        changed_any = True
        if changed_any:
            sub.offer({
                "event": "density",
                "total": float(grid.sum()),
                "cells": int(np.count_nonzero(grid)),
            })
            self._bump("events")

    # -- approximate density (shared host grid, no device) -----------------

    def _approx_bins(self) -> int:
        from geomesa_tpu.approx.sketches import DEFAULT_BINS

        return DEFAULT_BINS

    def _host_cells(self, sft, batch, n: int):
        """World-grid cells of the first `n` rows, pure numpy — THE
        shared sketch binning (approx.sketches.world_cells), so the
        subscribe tier's grid and the serve tier's partition sketches
        can never bin differently."""
        from geomesa_tpu.approx.sketches import world_cells

        col = batch.columns[_geom_name(sft)]
        return world_cells(np.asarray(col.x)[:n], np.asarray(col.y)[:n],
                           self._approx_bins())

    def _seed_approx_shared(self, st: _TypeState, sft) -> None:
        """Build the shared grid + fid->cell map from the live
        snapshot (idempotent; under the per-type eval lock)."""
        if st.approx_seeded:
            return
        b = self._approx_bins()
        grid = np.zeros((b, b), np.float64)
        cells: Dict[str, Tuple[int, int]] = {}
        snap = self.store.cache(st.type_name).snapshot()
        if snap is not None and len(snap):
            rows, cols = self._host_cells(sft, snap, len(snap))
            for j, fid in enumerate(_batch_fids(snap)):
                grid[rows[j], cols[j]] += 1.0
                cells[fid] = (int(rows[j]), int(cols[j]))
        st.approx_grid = grid
        st.approx_cells = cells
        st.approx_seeded = True

    def _fold_approx_shared(self, st: _TypeState, sft, delta, fids,
                            removed, cleared: bool) -> bool:
        """Fold one delta window into the shared grid — plain numpy,
        O(delta), IDEMPOTENT (the fid->cell map records each feature's
        last-applied cell, so re-applying a retried window lands in the
        same state). Returns whether anything moved."""
        self._seed_approx_shared(st, sft)
        grid = st.approx_grid
        cells = st.approx_cells
        changed_any = False
        if cleared:
            if cells or grid.any():
                changed_any = True
            grid[:] = 0.0
            cells.clear()
        for fid in removed:
            old = cells.pop(fid, None)
            if old is not None:
                grid[old] -= 1.0
                changed_any = True
        if delta is not None and len(fids):
            rows, cols = self._host_cells(sft, delta, len(fids))
            for j, fid in enumerate(fids):
                new = (int(rows[j]), int(cols[j]))
                old = cells.get(fid)
                if old == new:
                    continue
                if old is not None:
                    grid[old] -= 1.0
                grid[new] += 1.0
                cells[fid] = new
                changed_any = True
        return changed_any

    def _apply_approx(self, st: _TypeState, sub: Subscription,
                      offer: bool = True) -> None:
        """Resample the shared grid onto one subscription's window and
        push the typed `approx_density` frame carrying the bound."""
        from geomesa_tpu.approx.sketches import resample_bounds

        d = sub.density
        grid, bound = resample_bounds(
            st.approx_grid, None, d.bbox, d.width, d.height)
        with sub._lock:
            sub.grid = grid
        if not offer:
            return
        total = float(grid.sum())
        sub.offer({
            "event": "approx_density",
            "approx": True,
            "total": total,
            "cells": int(np.count_nonzero(grid)),
            "bound": float(bound),
            "confidence": 1.0,
            "within_tolerance": bound <= d.tolerance * max(total, 1.0),
        })
        self._bump("events")
        self._bump("approx_frames")

    # -- degraded per-subscription path ------------------------------------

    def _fold_fallback(self, st, sft, subs, delta, dev, fids,
                       changed, removed, cleared) -> None:
        """Per-subscription evaluation after a fused-kernel crash: the
        poisonous predicate is struck (and quarantined after the
        configured strikes — docs/ROBUSTNESS.md); everything healthy
        still folds this window exactly once."""
        approx_dens = [s for s in subs
                       if s.density is not None and s.density.approx]
        if approx_dens:
            # approx windows never rode the crashed fused kernel — the
            # shared host fold serves them exactly as on the clean
            # path. Only a SHARED-fold failure strikes the whole set
            # (the state is shared); per-sub resync/apply failures are
            # isolated per subscription, same as every other path.
            shared_err = None
            try:
                changed_any = self._fold_approx_shared(
                    st, sft, delta, fids, removed, cleared)
            except Exception as e:  # noqa: BLE001 — shared state failed
                shared_err = e
            for sub in approx_dens:
                try:
                    if shared_err is not None:
                        self._strike(sub, shared_err)
                    elif sub._resync_pending():
                        self._resync(sub)
                    elif changed_any:
                        self._apply_approx(st, sub)
                except Exception as e:  # noqa: BLE001 — strike, not spread
                    self._strike(sub, e)
        for sub in subs:
            if sub.density is not None and sub.density.approx:
                continue
            try:
                if sub._resync_pending():
                    self._resync(sub)
                    continue
                if sub.density is not None:
                    cell = None
                    if delta is not None and len(fids):
                        rows, cols, inb = self._density_cells_host(
                            sub.density, sft, delta)
                        cell = (rows, cols, inb)
                    self._apply_density(sub, delta, fids, cell,
                                        removed, cleared)
                else:
                    if delta is not None and len(fids):
                        f = self._filter_for(st.type_name, sub.cql, sft)
                        mask = f.mask_refined(dev, delta)[: len(fids)]
                    else:
                        mask = np.zeros(0, bool)
                    self._apply_predicate(sub, fids, mask, removed,
                                          cleared)
            except Exception as e:  # noqa: BLE001 — strike, don't spread
                self._strike(sub, e)

    def _strike(self, sub: Subscription, exc: BaseException) -> None:
        if not self._quarantine_enabled or _infra_error(exc):
            # no strike: quarantine is disabled (quarantine_after=0),
            # or — the serving layer's exemption (serve/service.py) —
            # the OSError family and transient failures are
            # infrastructure answers, not predicate crashes, and an
            # infra blip must not quarantine every standing
            # subscription. State for THIS sub may be partially
            # applied, so re-seed from the snapshot instead.
            self._bump("eval_errors")
            with sub._lock:
                sub._resync = True
            return
        self._bump("strikes")
        tripped = self.quarantine.strike(sub.fingerprint())
        with sub._lock:
            sub._resync = True  # survived strikes re-seed on next fold
        RECORDER.note_event(
            "subscribe", action="strike", subscription=sub.sub_id,
            error=f"{type(exc).__name__}: {exc}")
        if tripped:
            self.registry.quarantine(sub.sub_id)
            # quarantined subscriptions keep their state out of the
            # evaluation set but stay in the table; stamp the
            # quarantine TTL so an abandoned one is swept by
            # expire_tick instead of leaking forever
            with sub._lock:
                ttl_at = sub.clock() + self.quarantine.ttl_s
                sub.expires_at = (ttl_at if sub.expires_at is None
                                  else min(sub.expires_at, ttl_at))
            sub.offer({
                "event": "quarantined",
                "message": (f"predicate crashed evaluation "
                            f"{self.quarantine.strikes}+ times: "
                            f"{type(exc).__name__}"),
            })
            try:
                from geomesa_tpu.utils.metrics import metrics

                metrics.counter("subscribe.quarantined")
            except Exception:
                pass

    def _resync(self, sub: Subscription) -> None:
        """Re-seed a subscription that missed a fold (post-crash): the
        buffered window was consumed for the healthy set, so this sub
        rebuilds from the live snapshot and flags the client with a
        lagged/state hand-off instead of silently diverging."""
        self.bootstrap(sub)
        with sub._lock:
            sub._resync = False
            sub.lagged = True
        self._bump("resyncs")

    # -- delta construction ------------------------------------------------

    def _delta_batch(self, sft, changed: "dict[str, dict]",
                     device: bool = True):
        """Columnar delta: the window's changed rows as one pow2-padded
        FeatureBatch + DeviceBatch (f32 coords — the serving dtype).
        `device=False` (all-approx subscription sets) skips the upload
        entirely — the host fold needs only the batch and fids."""
        if not changed:
            return None, None, []
        from geomesa_tpu.core.columnar import FeatureBatch

        fids = list(changed)
        data = {a.name: [changed[f].get(a.name) for f in fids]
                for a in sft.attributes}
        batch = FeatureBatch.from_pydict(sft, data, fids=fids)
        padded = batch.pad_to(next_pow2(max(len(batch), _PAD_MIN)))
        if not device:
            return padded, None, fids
        from geomesa_tpu.engine.device import to_device

        # gt: waive GT09
        # (deliberate: delta upload under the per-type eval lock — the
        # fold serialization boundary; see module docstring)
        return padded, to_device(padded), fids


def _coalesce(events: List[tuple]):
    """Fold a window's FeatureEvents, in order, into (changed,
    removed, cleared): latest-wins per fid, a Clear supersedes
    everything before it (the cache state after the window is exactly
    post-clear changes)."""
    changed: Dict[str, dict] = {}
    removed: Dict[str, None] = {}
    cleared = False
    for kind, fid, attrs in events:
        if kind == "changed":
            changed[fid] = attrs
            removed.pop(fid, None)
        elif kind == "removed":
            changed.pop(fid, None)
            removed[fid] = None
        elif kind == "cleared":
            changed.clear()
            removed.clear()
            cleared = True
    return changed, list(removed), cleared


def _pad_tables(params: Dict[str, np.ndarray]) -> Dict[str, np.ndarray]:
    """Pow2-pad the per-batch vocab tables (string-predicate allowed
    tables) so their shapes repeat across deltas — padded entries are
    False and unreachable (dictionary codes never index past the real
    vocab)."""
    out = {}
    for k, v in params.items():
        v = np.asarray(v)
        if v.ndim == 1 and v.dtype == bool:
            target = next_pow2(max(len(v), _TABLE_PAD_MIN))
            if target > len(v):
                v = np.concatenate(
                    [v, np.zeros(target - len(v), bool)])
        out[k] = v
    return out


def _geom_name(sft) -> str:
    g = sft.default_geometry
    if g is None:
        raise ValueError(f"feature type {sft.name!r} has no geometry")
    return g.name


def _batch_fids(batch) -> List[str]:
    if batch.fids is None:
        return [str(i) for i in range(len(batch))]
    return [str(f) for f in batch.fids.decode()]
