"""Subscription registry: standing queries over the Kafka live layer.

Parity role: the GeoMesa Kafka layer's KafkaFeatureEventSource consumers
plus `geomesa-process` analytics run continuously [upstream, unverified]
— a client registers a long-lived predicate (CQL / BBOX / DWITHIN
geofence) or a density/heatmap window and receives incremental push
updates as Kafka batches fold in, instead of re-issuing one-shot
queries.

This module is the STATE side of the subsystem (docs/SERVING.md
"Standing queries"): `Subscription` objects carry the standing query,
its per-subscription state (the matched-fid set that gives geofence
enter/exit semantics; the grid + per-fid contribution map that gives
incremental density), a bounded outbox of pending event frames, a
per-subscription push rate limit, and lifecycle (active / paused /
cancelled / expired / quarantined, TTL expiry). `SubscriptionRegistry`
is the thread-safe directory the evaluator reads; every membership or
lifecycle change bumps a per-type VERSION so the evaluator's fused
device kernel is rebuilt exactly when the subscription set changes —
never per batch (subscribe/evaluator.py).

Slow consumers (docs/SERVING.md "Backpressure and lagged
subscriptions"): an outbox past its bound flips the subscription into
lagged mode — pending events are dropped for a single typed
`subscription_lagged` frame, incremental delivery is suspended, and the
next successful flush re-syncs the client with a full `state` frame
before incremental frames resume. Memory stays bounded; the client is
TOLD it missed events instead of silently losing them.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Set, Tuple

import numpy as np

# subscription lifecycle states
STATUSES = ("active", "paused", "cancelled", "expired", "quarantined")

_ids = itertools.count(1)


def _next_id() -> str:
    return f"sub-{next(_ids)}"


@dataclasses.dataclass
class DensityWindow:
    """A standing density/heatmap window: the DensityScan envelope +
    grid shape, folded incrementally (engine/density.py binning)."""

    bbox: Tuple[float, float, float, float]
    width: int
    height: int
    weight_attr: Optional[str] = None
    # fading-heatmap mode: grid *= decay per folded batch, no per-fid
    # subtraction (the exact incremental contract — and the parity test
    # — applies only when decay is None)
    decay: Optional[float] = None
    # approximate mode (docs/SERVING.md "Approximate answers"): a
    # tolerance turns this into a SKETCH-BACKED window — per poll the
    # evaluator folds the delta into one shared host-side occupancy
    # grid per type (NO device dispatch, however many subscribers) and
    # pushes typed `approx_density` frames carrying the resample bound.
    # Incompatible with weight_attr/decay (per-subscription semantics a
    # shared grid cannot carry) — validated at subscribe time.
    tolerance: Optional[float] = None

    def __post_init__(self):
        if self.width < 1 or self.height < 1:
            raise ValueError("density window needs width/height >= 1")
        x0, y0, x1, y1 = self.bbox
        if not (x1 > x0 and y1 > y0):
            raise ValueError(f"degenerate density bbox {self.bbox}")
        if self.decay is not None and not 0.0 < self.decay <= 1.0:
            raise ValueError("decay must be in (0, 1]")
        if self.tolerance is not None:
            if self.tolerance <= 0.0:
                raise ValueError("density tolerance must be > 0")
            if self.weight_attr is not None or self.decay is not None:
                raise ValueError(
                    "approximate density (tolerance) does not support "
                    "weight_attr or decay — the shared sketch grid is "
                    "unweighted and exact-incremental")

    @property
    def approx(self) -> bool:
        return self.tolerance is not None


class Subscription:
    """One standing query. State transitions and outbox appends are
    guarded by the instance lock; the evaluator mutates matched/grid
    state only from its own serialized fold path."""

    def __init__(
        self,
        type_name: str,
        cql: str = "INCLUDE",
        density: Optional[DensityWindow] = None,
        tenant: str = "",
        sub_id: Optional[str] = None,
        ttl_s: Optional[float] = None,
        outbox_limit: int = 1024,
        rate: Optional[float] = None,
        rate_burst: float = 8.0,
        initial_state: bool = True,
        clock: Callable[[], float] = time.monotonic,
    ):
        if outbox_limit < 2:
            # the lagged frame itself needs a slot after overflow clears
            raise ValueError("outbox_limit must be >= 2")
        self.sub_id = sub_id or _next_id()
        self.type_name = type_name
        self.cql = cql
        self.density = density
        self.tenant = tenant
        self.clock = clock
        self.registered_at = clock()
        self.expires_at = (clock() + ttl_s) if ttl_s else None
        self.outbox_limit = outbox_limit
        self.initial_state = initial_state
        self.status = "active"
        self.lagged = False
        # set by the evaluator after a crashed fold: the next clean
        # fold re-seeds state from the live snapshot (lagged hand-off)
        self._resync = False
        # per-subscription push rate limit (frames/s): reuses the serve
        # scheduler's TokenBucket; None = unlimited
        self._bucket = None
        if rate is not None:
            from geomesa_tpu.serve.scheduler import TokenBucket

            self._bucket = TokenBucket(rate, rate_burst)
        self._lock = threading.Lock()
        self._outbox: "deque[dict]" = deque()
        self._seq = 0
        # evaluator-owned incremental state (mutated only under the
        # evaluator's per-type fold serialization):
        self.matched: Set[str] = set()
        self.grid: Optional[np.ndarray] = None
        # fid -> (row, col, weight): the contribution to subtract when
        # the feature moves or leaves (exact incremental density)
        self.contrib: Dict[str, Tuple[int, int, float]] = {}
        if density is not None:
            self.grid = np.zeros((density.height, density.width),
                                 np.float64)
        # counters (introspection / bench): events offered, frames
        # drained, overflows
        self.events_offered = 0
        self.overflows = 0

    # -- identity ----------------------------------------------------------

    @property
    def mode(self) -> str:
        if self.density is None:
            return "predicate"
        return "approx_density" if self.density.approx else "density"

    def fingerprint(self) -> tuple:
        """Quarantine key: the predicate identity, NOT the sub id — a
        crashing predicate must stay blocked when re-registered under a
        fresh id (same stance as serve's coalescing fingerprint)."""
        if self.density is not None:
            d = self.density
            return ("subscribe", self.type_name, "density", d.bbox,
                    d.width, d.height, d.weight_attr, d.tolerance)
        return ("subscribe", self.type_name, "predicate", self.cql)

    # -- lifecycle ---------------------------------------------------------

    @property
    def live(self) -> bool:
        return self.status == "active"

    def expired(self, now: Optional[float] = None) -> bool:
        if self.expires_at is None:
            return False
        return (now if now is not None else self.clock()) >= self.expires_at

    def touch(self, ttl_s: Optional[float]) -> None:
        """Extend the TTL (client keep-alive)."""
        if ttl_s:
            self.expires_at = self.clock() + ttl_s

    # -- outbox ------------------------------------------------------------

    def offer(self, event: dict) -> bool:
        """Queue one event frame for push. Returns False when the
        subscription is lagged (event dropped by contract — a `state`
        re-sync frame replaces the missed window at the next flush).
        Overflow flips lagged mode: the queue is cleared down to one
        typed `subscription_lagged` frame so memory never grows past
        the bound."""
        terminal = event.get("event") in ("expired", "quarantined")
        with self._lock:
            self.events_offered += 1
            if self.lagged and not terminal:
                # lagged drops INCREMENTAL events (the state re-sync
                # replaces them) — but a terminal frame is the last
                # thing the client will ever hear; dropping it would
                # leave them waiting forever on a dead subscription
                return False
            if not terminal and len(self._outbox) >= self.outbox_limit:
                self.overflows += 1
                self.lagged = True
                dropped = len(self._outbox)
                self._outbox.clear()
                self._seq += 1
                self._outbox.append({
                    "event": "subscription_lagged",
                    "subscription": self.sub_id,
                    "seq": self._seq,
                    "dropped": dropped + 1,
                    "message": ("outbox overflow: incremental events "
                                "dropped; a state re-sync frame follows"),
                })
                self._note_lagged()
                return False
            self._seq += 1
            event = dict(event)
            event.setdefault("subscription", self.sub_id)
            event["seq"] = self._seq
            self._outbox.append(event)
            return True

    def _note_lagged(self) -> None:
        # under self._lock: cheap bookkeeping only (GT17 discipline —
        # the recorder append is a dict+deque, never I/O)
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER
            from geomesa_tpu.utils.metrics import metrics

            metrics.counter("subscribe.lagged")
            RECORDER.note_event("subscribe", action="lagged",
                                subscription=self.sub_id,
                                tenant=self.tenant)
        except Exception:
            pass  # observability must never fail the fold

    def drain(self, limit: Optional[int] = None) -> List[dict]:
        """Pop queued frames for push, honoring the per-subscription
        rate limit (frames stay queued when the bucket is empty —
        backpressure into the bounded outbox, which is what eventually
        trips lagged mode for a chronically slow consumer). Draining
        the lagged marker frame arms a one-shot `state` re-sync: the
        flusher appends it and clears lagged mode."""
        out: List[dict] = []
        with self._lock:
            while self._outbox:
                if limit is not None and len(out) >= limit:
                    break
                if self._bucket is not None and not self._bucket.try_acquire():
                    break
                out.append(self._outbox.popleft())
        return out

    def resync_frame(self) -> dict:
        """The latest-state-only frame that ends a lagged window: the
        full matched set (or density total), after which incremental
        delivery resumes."""
        with self._lock:
            return self._resync_frame_locked()

    def queue_state_frame(self) -> None:
        """Queue the registration-time `state` frame: built AND
        enqueued under one lock so its seq is stamped exactly once
        (routing it through offer() would re-stamp, and the client's
        first frame would arrive seq=2 — a phantom gap under the
        monotonic-seq contract)."""
        with self._lock:
            self._outbox.append(self._resync_frame_locked())

    def take_resync_frame(self) -> Optional[dict]:
        """The lagged hand-off, checked-and-built atomically: returns
        the state frame only while still lagged with a drained outbox.
        A fold's offer() landing between the flusher's drain and this
        call forfeits the hand-off for the cycle (the next flush
        retries) — otherwise the state frame would outrun the queued
        increment's seq and the client would see non-monotonic
        sequence numbers."""
        with self._lock:
            if not (self.lagged and not self._outbox and self.live):
                return None
            return self._resync_frame_locked()

    def _resync_frame_locked(self) -> dict:
        self._seq += 1
        self.lagged = False
        # state reads stay under the lock: the evaluator mutates
        # the grid in place under the same lock, so a flush racing
        # a fold never serializes a half-applied grid
        frame = {"event": "state", "subscription": self.sub_id,
                 "seq": self._seq}
        if self.density is not None:
            frame["shape"] = [self.density.height, self.density.width]
            frame["total"] = (float(self.grid.sum())
                              if self.grid is not None else 0.0)
        else:
            frame["fids"] = sorted(self.matched)
        return frame

    def handoff_snapshot(self) -> dict:
        """Serializable failover hand-off (docs/ROBUSTNESS.md): the
        canonical predicate, the matched-fid baseline, and the seq /
        delivered-watermark pair. A fleet router re-homes the standing
        query onto a survivor by re-subscribing WITH this snapshot
        (manager.subscribe `handoff=`): the acceptor seeds its sequence
        counter from the watermark and answers with a full `state`
        resync frame, so the client reconciles instead of starting
        over. Predicate subscriptions only — a density grid's float
        state is replica-local by design and re-seeds from the live
        snapshot anyway."""
        if self.density is not None:
            raise ValueError(
                "density subscriptions do not hand off: the grid "
                "re-seeds from the live snapshot on re-subscribe")
        from geomesa_tpu.cql import parse_cql
        from geomesa_tpu.cql.ast import to_cql

        with self._lock:
            return {
                "type": self.type_name,
                # canonical form: the acceptor validates predicate
                # identity by string equality, not parse-tree walks
                "cql": to_cql(parse_cql(self.cql)),
                "matched": sorted(self.matched),
                "seq": self._seq,
                # last DELIVERED seq: frames still queued were never
                # pushed, so the acceptor's state frame re-covers them
                "watermark": self._seq - len(self._outbox),
                # a re-homed paused subscription must LAND paused (the
                # fleet router reads this; the acceptor ignores it)
                "status": self.status,
            }

    def requeue(self, frames: List[dict]) -> None:
        """Put back frames a failed flush drained but could not push
        (front of the queue, original order, seq already stamped) — a
        broken push sink must not silently lose delivered-to-nobody
        frames."""
        if not frames:
            return
        with self._lock:
            self._outbox.extendleft(reversed(frames))

    def outbox_depth(self) -> int:
        with self._lock:
            return len(self._outbox)

    def _resync_pending(self) -> bool:
        with self._lock:
            return self._resync

    def stats(self) -> dict:
        with self._lock:
            return {
                "id": self.sub_id,
                "type": self.type_name,
                "mode": self.mode,
                "tenant": self.tenant,
                "status": self.status,
                "lagged": self.lagged,
                "matched": len(self.matched),
                "outbox": len(self._outbox),
                "events_offered": self.events_offered,
                "overflows": self.overflows,
            }


class SubscriptionRegistry:
    """Thread-safe directory of subscriptions, grouped by feature type.

    The per-type `version` is the evaluator's cache key for the fused
    device kernel: it moves only on membership/lifecycle changes, so a
    steady subscription set never recompiles across folded batches."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: Dict[str, Subscription] = {}
        self._by_type: Dict[str, List[str]] = {}
        self._versions: Dict[str, int] = {}
        # transitioned-out subscriptions (cancelled/expired) whose
        # final frames still need one last flush (manager.take_parting)
        self._parting: List[Subscription] = []

    # -- membership --------------------------------------------------------

    def register(self, sub: Subscription) -> Subscription:
        with self._lock:
            if sub.sub_id in self._subs:
                raise ValueError(f"duplicate subscription id {sub.sub_id!r}")
            self._subs[sub.sub_id] = sub
            self._by_type.setdefault(sub.type_name, []).append(sub.sub_id)
            self._versions[sub.type_name] = (
                self._versions.get(sub.type_name, 0) + 1)
        self._export_active()
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER

            RECORDER.note_event("subscribe", action="register",
                                subscription=sub.sub_id,
                                type=sub.type_name, mode=sub.mode,
                                tenant=sub.tenant)
        except Exception:
            pass
        return sub

    def get(self, sub_id: str) -> Subscription:
        with self._lock:
            return self._subs[sub_id]

    def maybe(self, sub_id: str) -> Optional[Subscription]:
        with self._lock:
            return self._subs.get(sub_id)

    def __len__(self) -> int:
        with self._lock:
            return sum(1 for s in self._subs.values()
                       if s.status in ("active", "paused"))

    def type_names(self) -> List[str]:
        with self._lock:
            return sorted(n for n, ids in self._by_type.items() if ids)

    def active_for(self, type_name: str) -> List[Subscription]:
        """Evaluation set: ACTIVE subscriptions of one type, in
        registration order (stable — the fused kernel's lane order)."""
        return self.active_snapshot(type_name)[1]

    def active_snapshot(
        self, type_name: str
    ) -> Tuple[int, List[Subscription]]:
        """(version, active subscriptions) read ATOMICALLY under the
        registry lock: every membership/lifecycle change bumps the
        version, so equal versions imply identical membership — the
        invariant the evaluator's fused-kernel cache keys on. Reading
        the two separately would let a registration land between the
        reads and stamp a stale subscription list into the new
        version's cached kernel."""
        with self._lock:
            ids = self._by_type.get(type_name, ())
            return (self._versions.get(type_name, 0),
                    [self._subs[i] for i in ids
                     if self._subs[i].status == "active"])

    def version(self, type_name: str) -> int:
        with self._lock:
            return self._versions.get(type_name, 0)

    # -- lifecycle ---------------------------------------------------------

    def _transition(self, sub_id: str, status: str,
                    final_frame: Optional[dict] = None) -> Subscription:
        assert status in STATUSES
        removing = False
        with self._lock:
            sub = self._subs[sub_id]
            if sub.status == status:
                return sub
            sub.status = status
            self._versions[sub.type_name] = (
                self._versions.get(sub.type_name, 0) + 1)
            removing = status in ("cancelled", "expired")
            if removing:
                ids = self._by_type.get(sub.type_name)
                if ids and sub_id in ids:
                    ids.remove(sub_id)
                del self._subs[sub_id]
                if final_frame is None:
                    self._parting.append(sub)
        if removing and final_frame is not None:
            # terminal frame FIRST, take_parting() visibility second:
            # once the subscription is in _parting a concurrent flush
            # can pop-and-drain it, and a frame offered after that
            # drain lands in an outbox nothing will ever flush again —
            # the client waits forever on a dead subscription. In the
            # gap (removed from _subs, not yet parting) a flush simply
            # doesn't see the sub; delivery waits for the next flush.
            sub.offer(final_frame)
            with self._lock:
                self._parting.append(sub)
        self._export_active()
        try:
            from geomesa_tpu.telemetry.recorder import RECORDER

            RECORDER.note_event("subscribe", action=status,
                                subscription=sub_id,
                                type=sub.type_name, tenant=sub.tenant)
        except Exception:
            pass
        return sub

    def pause(self, sub_id: str) -> Subscription:
        return self._transition(sub_id, "paused")

    def resume(self, sub_id: str) -> Subscription:
        with self._lock:
            sub = self._subs[sub_id]
            if sub.status != "paused":
                raise ValueError(
                    f"cannot resume {sub_id!r} from {sub.status!r}")
        # a resumed subscription missed every batch folded while paused
        # (the evaluator may even have disarmed and dropped the buffered
        # window): its matched set / grid is stale, so it must re-seed
        # from the live snapshot — not just re-announce its old state.
        # Mark the re-seed BEFORE going active so a fold that interleaves
        # with the caller's eager resync (manager.resume) re-seeds
        # instead of diffing against the stale baseline.
        with sub._lock:
            sub._resync = True
        return self._transition(sub_id, "active")

    def cancel(self, sub_id: str) -> Subscription:
        return self._transition(sub_id, "cancelled")

    def quarantine(self, sub_id: str) -> Subscription:
        return self._transition(sub_id, "quarantined")

    def expire_tick(self, now: Optional[float] = None) -> List[Subscription]:
        """TTL sweep: returns the subscriptions expired by this tick,
        already transitioned with their final `expired` frame queued
        (queueing it here, not in the caller, keeps the frame ahead of
        take_parting() visibility — see _transition). Runs before
        every fold (subscribe/evaluator.py).
        Quarantined subscriptions are swept too — the evaluator stamps
        them with the quarantine TTL on trip, so an abandoned poisoned
        subscription is eventually removed instead of being pinned and
        re-scanned by every flush forever."""
        with self._lock:
            stale = [s.sub_id for s in self._subs.values()
                     if s.status in ("active", "paused", "quarantined")
                     and s.expired(now)]
        out = []
        for sid in stale:
            # two concurrent pumps (--live-poll-ms + a reader-thread
            # poll verb) can both collect the same expired id; the
            # loser's _transition finds it already removed — the
            # winner's tick owns the parting frame (same TOCTOU
            # discipline as manager.unsubscribe)
            try:
                out.append(self._transition(
                    sid, "expired", final_frame={"event": "expired"}))
            except KeyError:
                pass
        return out

    def subs(self) -> List[Subscription]:
        """Every registered subscription (any status), registration
        order — the flush iteration set."""
        with self._lock:
            return list(self._subs.values())

    def take_parting(self) -> List[Subscription]:
        """Pop the transitioned-out subscriptions whose final frames
        (`expired`, `quarantined`) still need delivery."""
        with self._lock:
            out, self._parting = self._parting, []
            return out

    def requeue_parting(self, subs: List[Subscription]) -> None:
        """Put back parting subscriptions a failed flush popped but
        never delivered terminal frames for (next flush retries)."""
        if not subs:
            return
        with self._lock:
            self._parting = list(subs) + self._parting

    # -- introspection -----------------------------------------------------

    def _export_active(self) -> None:
        """`subscribe.active{tenant}` gauge refresh on every membership
        change (docs/OBSERVABILITY.md metrics reference)."""
        with self._lock:
            per_tenant: Dict[str, int] = {}
            for s in self._subs.values():
                if s.status == "active":
                    per_tenant[s.tenant or "-"] = (
                        per_tenant.get(s.tenant or "-", 0) + 1)
        try:
            from geomesa_tpu.utils.metrics import metrics

            metrics.gauge("subscribe.active", float(sum(per_tenant.values())))
            for tenant, n in per_tenant.items():
                metrics.gauge("subscribe.active.by_tenant", float(n),
                              tenant=tenant)
        except Exception:
            pass

    def stats(self) -> dict:
        with self._lock:
            subs = list(self._subs.values())
            types = {n: len(ids) for n, ids in self._by_type.items() if ids}
        by_status: Dict[str, int] = {}
        lagged = 0
        for s in subs:
            by_status[s.status] = by_status.get(s.status, 0) + 1
            if s.lagged:
                lagged += 1
        return {
            "subscriptions": len(subs),
            "by_status": by_status,
            # latest-state-only mode count (outbox overflow): the
            # `gmtpu top` subscriptions line reads this straight off
            # /debug/stats
            "lagged": lagged,
            "types": types,
        }
