"""Lane classification and membership for standing geofences.

The host side of the vmapped parametric lanes (engine/lanes.py):
classify a parsed CQL predicate into a geofence CLASS (bbox, dwithin,
polygon) whose parameters fit one row of a per-class [S, P] table, or
return a typed ineligibility reason and leave the subscription on the
fused-slot path. Membership is a device-shape contract, not a kernel
contract: tables are padded to pow2 [S]-buckets (polygon edge tables
additionally to pow2 E-buckets) with an `active` mask column, so
register/cancel/pause are a parameter-array ROW write — the compiled
lane program only changes when a bucket grows, asserted zero-recompile
via JitTracker in the subscribe tests.

Eligibility (docs/SERVING.md "Standing queries" carries the table):

- ``bbox``    — a bare BBOX on the default Point geometry.
- ``dwithin`` — DWITHIN against a single-point literal (BEYOND and
  segment/multi-point literals keep the fused path: they compile to
  different arithmetic).
- ``polygon`` — INTERSECTS/WITHIN against an area literal (polygon /
  multipolygon / geometry collection edge tables).

Anything else — compound filters, attribute predicates, negations,
extended-geometry data — is `lane_ineligible` with the reason on
stats, and evaluates exactly as before on the fused path.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.cql import ast
from geomesa_tpu.cql.compile import f32_ulp_band
from geomesa_tpu.utils.padding import next_pow2

LANE_CLASSES = ("bbox", "dwithin", "polygon")

_ROW_MIN = 8     # smallest [S]-bucket (row capacities are pow2)
_EDGE_MIN = 8    # smallest polygon E-bucket
# degenerate pad-edge coordinate: far enough that no crossing
# condition or band term can fire for real (lon, lat) points
_FAR = np.float32(1.0e30)

# params-row widths per class (bbox: 4 extents + 4 band half-widths)
_WIDTHS = {"bbox": 8, "dwithin": 3}


class LaneSpec:
    """One classified predicate: its class and parameter row."""

    __slots__ = ("cls", "params", "edges")

    def __init__(self, cls: str, params: Optional[np.ndarray] = None,
                 edges: Optional[np.ndarray] = None):
        self.cls = cls
        self.params = params   # [P] f32 (bbox / dwithin)
        self.edges = edges     # [4, E] f32 (polygon)


def classify(f, sft) -> Tuple[Optional[LaneSpec], str]:
    """(spec, "") for a lane-eligible filter AST, (None, reason)
    otherwise. The reasons are the typed `lane_ineligible` vocabulary
    surfaced on evaluator stats."""
    if isinstance(f, (ast.And, ast.Or, ast.Not)):
        return None, "compound"
    if isinstance(f, ast.SpatialPredicate):
        ok, why = _default_point_geom(f, sft)
        if not ok:
            return None, why
        if f.op == "BBOX":
            x0, y0, x1, y1 = f.geometry.bbox
            prm = np.array(
                [x0, x1, y0, y1,
                 f32_ulp_band(x0), f32_ulp_band(x1),
                 f32_ulp_band(y0), f32_ulp_band(y1)], np.float32)
            return LaneSpec("bbox", params=prm), ""
        if f.op in ("INTERSECTS", "WITHIN"):
            g = f.geometry
            if g.kind in ("Point", "MultiPoint",
                          "LineString", "MultiLineString"):
                return None, "non_area_literal"
            from geomesa_tpu.engine.pip import polygon_edges

            x1e, y1e, x2e, y2e = polygon_edges(g)
            if len(x1e) == 0:
                return None, "empty_geometry"
            # f64 -> f32 by np cast: the same round-to-nearest the
            # one-shot path's jnp.asarray applies with x64 disabled
            edges = np.stack([x1e, y1e, x2e, y2e]).astype(np.float32)
            return LaneSpec("polygon", edges=edges), ""
        return None, "spatial_op"
    if isinstance(f, ast.DistancePredicate):
        ok, why = _default_point_geom(f, sft)
        if not ok:
            return None, why
        if f.op != "DWITHIN":
            return None, "negated"
        g = f.geometry
        if (g.kind not in ("Point", "MultiPoint")
                or sum(len(r) for r in g.rings) != 1):
            return None, "segment_literal"
        px, py = g.point
        prm = np.array([px, py, float(f.distance_m)], np.float32)
        return LaneSpec("dwithin", params=prm), ""
    return None, "non_spatial"


def _default_point_geom(f, sft) -> Tuple[bool, str]:
    g = sft.default_geometry
    if g is None or f.prop.name != g.name:
        return False, "non_default_geometry"
    if g.type != "Point":
        # extended-geometry data compiles through engine.geometry CSR
        # kernels — a different arithmetic the lane cannot reproduce
        return False, "extended_geometry"
    return True, ""


class LaneGroup:
    """One lane's parameter table: pow2-capacity rows + active mask.

    Mutated only under the evaluator's per-type eval lock (the fold
    serialization boundary), so row assignment needs no lock of its
    own. Rows are recycled through a free list; capacity doubles
    through `next_pow2` when full — the only event that changes the
    lane kernel's [S] shape, and therefore the only compile.
    """

    def __init__(self, cls: str, ebucket: int = 0):
        self.cls = cls
        self.ebucket = ebucket           # polygon only: padded E
        cap = next_pow2(_ROW_MIN)
        self.cap = cap
        self.params = self._alloc(cap)
        self.active = np.zeros(cap, bool)
        self.rows: Dict[str, int] = {}   # sub_id -> row
        self.free: List[int] = []
        self._used = 0

    def _alloc(self, cap: int) -> np.ndarray:
        if self.cls == "polygon":
            return np.full((cap, 4, self.ebucket), _FAR, np.float32)
        return np.zeros((cap, _WIDTHS[self.cls]), np.float32)

    def assign(self, sub_id: str, spec: LaneSpec) -> int:
        """Write one geofence into a free row (growing the bucket when
        full) and activate it. The steady-state cost of registration."""
        t0 = time.perf_counter()
        if self.free:
            # gt: waive GT12
            # (caller-holds-lock: LaneGroup/LaneTable are owned by the
            # evaluator's per-type _TypeState and mutate only inside
            # the fold, under that type's eval lock — the fold
            # serialization boundary; a per-table lock would re-lock
            # the same critical section per poll)
            row = self.free.pop()
        else:
            if self._used >= self.cap:
                self._grow()
            row = self._used
            # gt: waive GT12
            # (same: guarded by the owning type's eval lock)
            self._used += 1
        if self.cls == "polygon":
            # gt: waive GT12
            # (same: guarded by the owning type's eval lock)
            self.params[row] = _FAR
            self.params[row, :, : spec.edges.shape[1]] = spec.edges
        else:
            # gt: waive GT12
            # (same: guarded by the owning type's eval lock)
            self.params[row] = spec.params
        # gt: waive GT12
        # (same: guarded by the owning type's eval lock)
        self.active[row] = True
        # gt: waive GT12
        # (same: guarded by the owning type's eval lock)
        self.rows[sub_id] = row
        try:
            from geomesa_tpu.utils.metrics import metrics

            metrics.histogram("lane.param_write").update(
                time.perf_counter() - t0)
        except Exception:
            pass  # observability must never fail registration
        return row

    def release(self, sub_id: str) -> None:
        # gt: waive GT12
        # (caller-holds-lock: see assign() — eval-lock confined)
        row = self.rows.pop(sub_id, None)
        if row is None:
            return
        # gt: waive GT12
        # (same: guarded by the owning type's eval lock)
        self.active[row] = False
        # gt: waive GT12
        # (same: guarded by the owning type's eval lock)
        self.free.append(row)

    def _grow(self) -> None:
        cap = next_pow2(self.cap + 1)
        params = self._alloc(cap)
        params[: self.cap] = self.params
        active = np.zeros(cap, bool)
        active[: self.cap] = self.active
        # gt: waive GT12
        # (caller-holds-lock: see assign() — eval-lock confined)
        self.cap, self.params, self.active = cap, params, active

    def occupancy(self) -> int:
        return len(self.rows)


class LaneTable:
    """Per-feature-type lane membership: the diff between the current
    active subscription set and the assigned rows, applied as row
    writes. Owned by the evaluator's _TypeState; every method runs
    under the per-type eval lock."""

    def __init__(self):
        # group key: ("bbox",) / ("dwithin",) / ("polygon", E-bucket)
        self.groups: Dict[tuple, LaneGroup] = {}
        self.assigned: Dict[str, tuple] = {}  # sub_id -> group key
        self.reasons: Dict[str, str] = {}     # sub_id -> ineligible why

    def sync(self, subs, spec_for: Callable) -> Tuple[list, list]:
        """Reconcile membership with one atomic registry snapshot.

        Returns (lanes, remainder): `lanes` is [(group, [(sub, row)])]
        for every group with members in `subs`; `remainder` is every
        subscription staying on the fused path (densities + ineligible
        predicates), in registration order. Newly seen predicates are
        classified once and cached by sub_id; subscriptions gone from
        the active set release their rows (a row write — pause/cancel
        never rebuilds anything)."""
        members: Dict[tuple, list] = {}
        remainder = []
        seen = {sub.sub_id for sub in subs if sub.density is None}
        # release rows of subscriptions gone from the active set BEFORE
        # assigning newcomers: a cancel+register cycle at full capacity
        # must recycle the cancelled row, not grow the bucket (growth
        # is the only lane recompile — JitTracker-asserted)
        for sid in [s for s in self.assigned if s not in seen]:
            # gt: waive GT12
            # (caller-holds-lock: LaneTable is owned by the
            # evaluator's per-type _TypeState; sync/_assign run only
            # inside the fold, under that type's eval lock)
            self.groups[self.assigned.pop(sid)].release(sid)
        for sid in [s for s in self.reasons if s not in seen]:
            # gt: waive GT12
            # (same: guarded by the owning type's eval lock)
            del self.reasons[sid]
        for sub in subs:
            if sub.density is not None:
                remainder.append(sub)
                continue
            sid = sub.sub_id
            key = self.assigned.get(sid)
            if key is None and sid not in self.reasons:
                spec, reason = spec_for(sub)
                if spec is None:
                    # gt: waive GT12
                    # (same: guarded by the owning type's eval lock)
                    self.reasons[sid] = reason
                else:
                    key = self._assign(sid, spec)
            if key is None:
                remainder.append(sub)
                continue
            members.setdefault(key, []).append(
                (sub, self.groups[key].rows[sid]))
        self._export_gauges()
        return ([(self.groups[k], members[k])
                 for k in sorted(members)], remainder)

    def _assign(self, sub_id: str, spec: LaneSpec) -> tuple:
        if spec.cls == "polygon":
            eb = next_pow2(max(spec.edges.shape[1], _EDGE_MIN))
            key = ("polygon", eb)
        else:
            key = (spec.cls,)
        group = self.groups.get(key)
        if group is None:
            # gt: waive GT12
            # (caller-holds-lock: see sync() — eval-lock confined)
            group = self.groups[key] = LaneGroup(
                spec.cls, ebucket=key[1] if spec.cls == "polygon" else 0)
        group.assign(sub_id, spec)
        # gt: waive GT12
        # (same: guarded by the owning type's eval lock)
        self.assigned[sub_id] = key
        return key

    def _export_gauges(self) -> None:
        try:
            from geomesa_tpu.utils.metrics import metrics

            per_cls: Dict[str, int] = {}
            for g in self.groups.values():
                per_cls[g.cls] = per_cls.get(g.cls, 0) + g.occupancy()
            for cls in LANE_CLASSES:
                metrics.gauge("subscribe.lanes", float(per_cls.get(cls, 0)),
                              **{"class": cls})
        except Exception:
            pass  # observability must never fail the fold

    def stats(self) -> dict:
        classes: Dict[str, dict] = {}
        for key, g in sorted(self.groups.items()):
            c = classes.setdefault(g.cls, {"rows": 0, "capacity": 0})
            c["rows"] += g.occupancy()
            c["capacity"] += g.cap
        ineligible: Dict[str, int] = {}
        for why in self.reasons.values():
            ineligible[why] = ineligible.get(why, 0) + 1
        return {"classes": classes, "ineligible": ineligible}
