"""Device cache manager: HBM residency with a persistent manifest.

Parity: SURVEY.md §5.4's checkpoint/resume obligation — the reference's
"checkpointing" is FS partition->file manifests + Kafka offsets; the TPU
analog is a manifest of *device residency*: which partition files are
resident in HBM, under which layout version, so a restarted server rebuilds
identical device state deterministically. Also covers the Kafka-layer
snapshot-refresh design (SURVEY.md C12 TPU note): `refresh()` is the
double-buffered snapshot swap — a new padded batch is built while the old
one keeps serving, then the reference flips.

Layout notes:
- partitions are cached independently (pruning stays effective: a query
  touching 3 of 300 partitions pulls 3 cache entries);
- each entry is padded to the next pow2 so jit cache keys stabilize across
  refreshes (same policy as the planner's scan path);
- LAYOUT_VERSION participates in the manifest: a layout change invalidates
  stale residency on load instead of serving mis-shaped arrays.
"""

from __future__ import annotations

import dataclasses
import functools
import json
import os
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.store.fs import FileSystemStorage
from geomesa_tpu.utils.padding import next_pow2 as _next_pow2

LAYOUT_VERSION = 1
MANIFEST = ".device_cache.json"


def _locked(fn):
    """Serialize a DeviceCacheManager method on the instance RLock —
    ensure/refresh/invalidate/superbatch are compound read-modify-write
    sequences that tear under concurrent queries without it."""

    @functools.wraps(fn)
    def wrapper(self, *args, **kwargs):
        with self._lock:
            return fn(self, *args, **kwargs)

    return wrapper


@dataclasses.dataclass
class CacheEntry:
    """One resident partition (host columnar copy; device residency lives
    in the concatenated superbatch — see `superbatch()`)."""

    files: List[str]  # source files (residency provenance)
    count: int  # valid rows
    padded: int  # padded device length (pow2)
    batch: FeatureBatch  # host copy (padded)
    dev: Optional[dict] = None  # per-partition device segment (flat stores)


@dataclasses.dataclass
class SuperBatch:
    """All resident partitions as ONE device batch + a partition-id row
    column. Execution masks pruned-out partitions by lane (allowed[pid])
    instead of dispatching per-partition kernels: at ~100ms per device
    round trip on remote-tunnel platforms and ~1ms per kernel launch, one
    dense pass over every resident row beats dozens of tiny dispatches —
    partition pruning still governs what gets LOADED into HBM."""

    batch: FeatureBatch          # host concat (padded segments)
    dev: dict                    # DeviceBatch of the concat
    pids: object                 # device i32 [N] partition id per row
    ids: Dict[str, int]          # partition name -> id
    version: int
    # mesh residency tier (docs/SERVING.md "Sharded serving"): when the
    # cache carries a serving mesh, `dev` arrays are NamedSharding-placed
    # over it (feature axis sharded, CSR/replicated keys replicated) and
    # the layout is the SERIAL layout plus trailing invalid padding to a
    # multiple of the mesh size — so global row indices (and therefore
    # kNN results) are bit-identical to the single-chip path. `owners`
    # records per-chip tile ownership: which shards hold each
    # partition's rows — the shard-affinity signal admission and the
    # planner's dispatch route consume.
    mesh: object = None                       # jax.sharding.Mesh | None
    shard_rows: int = 0                       # rows per shard (mesh only)
    owners: Dict[str, tuple] = dataclasses.field(default_factory=dict)

    def shards_for(self, partitions) -> tuple:
        """Sorted shard ids owning any of `partitions`' rows (empty
        tuple when the cache is single-chip or nothing matches)."""
        out: set = set()
        for name in partitions:
            out.update(self.owners.get(name, ()))
        return tuple(sorted(out))

    # Round-3: residency changes no longer re-upload unchanged segments
    # for FLAT stores (point geometry + numeric/date/dict columns): each
    # partition keeps its own device segment, dictionary columns are
    # re-encoded against a store-level grow-only vocab at load time (so
    # device codes stay comparable across partitions), and the superbatch
    # is a DEVICE-side concat of segments. Non-point geometry (CSR ring
    # tables need offset rewrites on concat) falls back to the round-1
    # full host-concat + re-upload. Host RAM still holds per-partition
    # copies for the double-buffered reload path.


class DeviceCacheManager:
    """Keeps partitions of a FileSystemStorage resident on device."""

    def __init__(self, storage: FileSystemStorage, coord_dtype=None,
                 mesh=None):
        self.storage = storage
        self.coord_dtype = coord_dtype
        # serving mesh (docs/SERVING.md "Sharded serving"): when set,
        # superbatch() builds the mesh-resident tier — one
        # NamedSharding upload per residency change (per manifest
        # snapshot, never per query) with per-chip row-range ownership.
        # Extended-geometry stores stay single-chip: their CSR ring
        # tables index per-feature arrays, which row sharding would
        # misalign.
        self.mesh = mesh
        # reentrant: compound ops (refresh -> ensure, resume -> _load)
        # re-enter; guards every mutation/compound read so concurrent
        # queries (the serve dispatch thread) never observe a half-swapped
        # superbatch or race an invalidating writer
        self._lock = threading.RLock()
        self._entries: Dict[str, CacheEntry] = {}
        self._super: Optional[SuperBatch] = None
        self._version = 0
        self._applied_mversion = -1  # storage commit version last applied
        # store-level grow-only vocabularies (per dict column) so device
        # code segments from different partitions remain comparable
        self._vocab: Dict[str, list] = {}
        self.upload_count = 0  # partitions transferred host->device
        # host->device transfer accounting (ROADMAP item 4 foundation):
        # rows that actually crossed the tunnel. The incremental mesh
        # GROWTH path appends only the delta tile, so these counters
        # must NOT scale with resident size on append — regression-
        # asserted in tests/test_device_cache.py
        self.upload_rows = 0
        # last mesh superbatch layout, kept for the delta-append path:
        # (mesh, names tuple, {name: (padded, files tuple)}, concat row
        # count BEFORE mesh padding, dev dict, padded_total)
        self._mesh_prev = None
        self._flat = all(
            (not a.is_geometry) or a.type == "Point"
            for a in storage.sft.attributes
        )

    # -- mesh residency (docs/SERVING.md "Sharded serving") ----------------

    def _mesh_active(self) -> bool:
        return self.mesh is not None and self._flat

    @_locked
    def serving_mesh(self):
        """The mesh live dispatch will actually take: the installed
        mesh when the mesh residency tier is active (flat store), else
        None. The pipeline keys its staging placement on THIS — not on
        `ServeConfig.mesh` — so a store the tier cannot shard
        (extended geometry, or no device cache at all) stages
        single-device buffers for the single-chip kernel it will
        actually run."""
        return self.mesh if self._mesh_active() else None

    @_locked
    def set_mesh(self, mesh) -> None:
        """Install (or clear) the serving mesh. Residency is rebuilt on
        the next superbatch(): entries keep their host copies; stale
        single-device segments are dropped so the sharded upload does
        not double HBM. No-op when the mesh is unchanged — by VALUE:
        every QueryService construction resolves a fresh Mesh object
        over the same devices (serve_mesh), and dropping residency on
        an identical placement would re-upload the whole store through
        the tunnel for nothing."""
        if mesh is self.mesh or (
                mesh is not None and self.mesh is not None
                and mesh == self.mesh):
            return
        self.mesh = mesh
        if self._mesh_active():
            for e in self._entries.values():
                e.dev = None
        self._super = None
        self._mesh_prev = None  # layout-invalidating: full re-tier
        self._version += 1
        # flight-recorder lifecycle event (docs/OBSERVABILITY.md): a
        # re-tier drops residency and re-uploads on the next
        # superbatch — a crash dump that shows one right before a
        # latency cliff explains a multi-chip incident by itself
        from geomesa_tpu.telemetry.recorder import RECORDER

        RECORDER.note_event(
            "mesh", action="retier",
            shape=(list(int(s) for s in mesh.devices.shape)
                   if mesh is not None else None),
            entries=len(self._entries))

    @_locked
    def shards_for(self, partitions) -> tuple:
        """Shard-affinity lookup: the sorted shard ids owning the named
        partitions' rows under the CURRENT mesh superbatch. PEEK-only —
        a cold or stale cache answers () instead of paying a residency
        build on the caller's (admission) thread; the planner's mesh
        dispatch reads ownership off the superbatch it just ensured."""
        if not self._mesh_active() or self._super is None:
            return ()
        return self._super.shards_for(partitions)

    # -- residency ---------------------------------------------------------

    def _partition_files(self, name: str,
                         manifest: Optional[dict] = None) -> List[str]:
        src = manifest if manifest is not None else self.storage.manifest
        return sorted(e["file"] for e in src.get(name, []))

    def _shared_vocab_recode(self, batch: FeatureBatch) -> FeatureBatch:
        """Re-encode dict columns against the store-level vocabularies
        (append-only merge) so per-partition device code segments are
        directly concatenable."""
        from geomesa_tpu.core.columnar import DictColumn

        cols = dict(batch.columns)
        changed = False
        for name, col in batch.columns.items():
            if not isinstance(col, DictColumn):
                continue
            vocab = self._vocab.setdefault(name, [])
            lookup = {v: i for i, v in enumerate(vocab)}
            remap = np.empty(len(col.vocab), np.int32)
            for i, v in enumerate(col.vocab):
                if v not in lookup:
                    lookup[v] = len(vocab)
                    vocab.append(v)
                remap[i] = lookup[v]
            codes = np.where(col.codes >= 0, remap[np.maximum(col.codes, 0)], -1)
            cols[name] = DictColumn(codes.astype(np.int32), vocab)
            changed = True
        if not changed:
            return batch
        return FeatureBatch(batch.sft, cols, batch.fids, batch.valid)

    def _load_partition(self, name: str,
                        manifest: Optional[dict] = None,
                        ) -> Optional[CacheEntry]:
        batches = list(self.storage.scan_partitions([name],
                                                    manifest=manifest))
        if not batches:
            return None
        batch = FeatureBatch.concat(batches)
        n = len(batch)
        padded = batch.pad_to(_next_pow2(n))
        dev = None
        if self._flat and self._mesh_active():
            # mesh tier: no per-partition single-device segments — the
            # sharded superbatch is ONE NamedSharding upload of the host
            # concat, so uploading each partition here would double HBM.
            # The shared-vocab recode still runs so host/device code
            # spaces stay comparable across refreshes.
            padded = self._shared_vocab_recode(padded)
        elif self._flat:
            from geomesa_tpu.engine.device import to_device

            padded = self._shared_vocab_recode(padded)
            kw = {"coord_dtype": self.coord_dtype} if self.coord_dtype else {}
            # gt: waive GT09
            # (deliberate: the upload IS the guarded residency swap;
            # queries blocked here would otherwise read a half-registered
            # partition — double-buffer under the lock)
            dev = to_device(padded, **kw)
            self.upload_count += 1
            self.upload_rows += len(padded)
        return CacheEntry(
            files=self._partition_files(name, manifest),
            count=n,
            padded=len(padded),
            batch=padded,
            dev=dev,
        )

    @_locked
    def ensure(self, partitions: Optional[List[str]] = None,
               manifest: Optional[dict] = None) -> List[str]:
        """Make the named partitions (default: all) resident; returns the
        list actually (re)loaded. Already-resident, unchanged partitions are
        untouched — the double-buffer: a changed partition's new entry is
        fully built before the old one is dropped. `manifest` pins the
        whole ensure to one committed write version (the planner passes
        its plan-time snapshot so pruning and residency agree — without
        it, a concurrent batch-atomic write could be half-visible:
        reloaded files in old partitions, missing new partitions)."""
        mv = getattr(manifest, "version", None)
        if manifest is None or (mv is not None
                                and mv < self._applied_mversion):
            # a STALE plan snapshot (another query already applied a
            # newer commit) must not roll residency backward / thrash
            # re-uploads: take a fresh snapshot instead — it is at least
            # as new as anything applied
            manifest = self.storage.manifest_snapshot()
            mv = getattr(manifest, "version", None)
        if mv is not None:
            self._applied_mversion = max(self._applied_mversion, mv)
        names = partitions if partitions is not None else sorted(manifest)
        loaded = []
        for name in names:
            files = self._partition_files(name, manifest)
            cur = self._entries.get(name)
            if cur is not None and cur.files == files:
                continue
            entry = self._load_partition(name, manifest)
            changed = True
            if entry is None:
                # only a real removal changes residency — a partition that
                # can never load must not invalidate the superbatch on
                # every query
                changed = self._entries.pop(name, None) is not None
            else:
                self._entries[name] = entry  # atomic reference flip
            if changed:
                loaded.append(name)
        if loaded:
            self._super = None  # residency changed: superbatch stale
            self._version += 1
        return loaded

    @_locked
    def refresh(self) -> List[str]:
        """Re-sync with the storage manifest: load new/changed partitions,
        drop removed ones. Returns changed partition names."""
        manifest = self.storage.manifest_snapshot()
        dropped = [n for n in self._entries if n not in manifest]
        for n in dropped:
            del self._entries[n]
        if dropped:
            self._super = None
            self._version += 1
        return self.ensure(manifest=manifest) + dropped

    @_locked
    def invalidate(self, partition: Optional[str] = None) -> None:
        if partition is None:
            self._entries.clear()
        else:
            self._entries.pop(partition, None)
        self._super = None
        # a forced invalidation must actually free device state: the
        # delta-append path would otherwise keep the dropped rows alive
        self._mesh_prev = None
        self._version += 1

    @_locked
    def get(self, partition: str) -> Optional[CacheEntry]:
        return self._entries.get(partition)

    @_locked
    def superbatch_peek(self) -> Optional[SuperBatch]:
        """The CURRENT superbatch if one is built, else None — no
        residency work, no rebuild. The ring serve loop's per-window
        freshness gate (docs/SERVING.md "Persistent serve loop") must
        stay a lock acquire + identity compare, never an upload."""
        return self._super

    @_locked
    def superbatch(self) -> Optional[SuperBatch]:
        """The concatenated device view of every resident partition (None
        when nothing is resident). Built lazily and re-uploaded only when
        residency changes — the double-buffered snapshot idea at store
        granularity."""
        if self._super is not None:
            return self._super
        if not self._entries:
            return None
        import jax.numpy as jnp
        import numpy as np

        from geomesa_tpu.engine.device import to_device

        names = sorted(self._entries)
        entries = [self._entries[n] for n in names]
        batch = FeatureBatch.concat([e.batch for e in entries])
        pids_host = np.concatenate([
            np.full(e.padded, i, np.int32) for i, e in enumerate(entries)
        ])
        if self._mesh_active():
            return self._mesh_superbatch(names, entries, batch, pids_host)
        if self._flat and all(e.dev is not None for e in entries):
            # incremental path: DEVICE-side concat of the per-partition
            # segments — changed partitions were re-uploaded at load; the
            # unchanged ones never cross the host boundary again. The
            # shared-vocab recode (load time) makes dict-code segments
            # directly comparable; host `batch` concat re-encodes too but
            # the ORDER of first-appearance matches the grow-only vocab,
            # so host and device code spaces agree (asserted in tests).
            keys = entries[0].dev.keys()
            dev = {
                k: jnp.concatenate([e.dev[k] for e in entries])
                for k in keys
            }
        else:
            kw = {"coord_dtype": self.coord_dtype} if self.coord_dtype else {}
            # gt: waive GT09
            # (deliberate: full re-upload path of the superbatch rebuild;
            # the lock is what makes the swap atomic for concurrent
            # queries — see class docstring)
            dev = to_device(batch, **kw)
            self.upload_count += 1
            self.upload_rows += len(batch)
        self._super = SuperBatch(
            batch=batch,
            dev=dev,
            pids=jnp.asarray(pids_host),
            ids={n: i for i, n in enumerate(names)},
            version=self._version,
        )
        return self._super

    def _mesh_superbatch(self, names, entries, batch, pids_host):
        """Mesh-resident tier: the SERIAL layout (partitions in sorted
        order, each pow2-padded) plus trailing invalid padding to a
        multiple of the mesh size, uploaded ONCE via NamedSharding
        placement (`parallel.mesh.shard_device_batch` — no per-device
        device_put loops, the GT18 contract). Keeping the serial row
        layout is what makes sharded kNN indices bit-identical to the
        single-chip path; ownership is the row-range → shard map.

        Growth-phase cost (ROADMAP item 4 foundation): a residency
        GROWTH — new partitions appended at the end of the sorted
        layout, every already-resident entry byte-identical — uploads
        ONLY the delta tile (the new rows + fresh mesh padding) and
        reassembles the sharded arrays device-side from the previous
        superbatch's buffers, so `upload_rows` does not scale with
        resident size on append (regression-asserted in
        tests/test_device_cache.py). Everything else — a changed or
        removed partition, a name sorting into the middle, a mesh
        change — is layout-invalidating and takes the full host-concat
        re-upload (prior row ownership is stale there anyway). Old rows
        re-placed from device buffers are bit-identical to a fresh
        upload: the host copies are unchanged and the dict vocab is
        grow-only, so previously-uploaded codes never re-encode."""
        import jax
        import jax.numpy as jnp
        from jax.sharding import NamedSharding, PartitionSpec as P

        from geomesa_tpu.parallel.mesh import SHARD_AXIS

        d = int(self.mesh.devices.size)
        total = len(batch)
        padded_total = ((total + d - 1) // d) * d
        if padded_total > total:
            batch = batch.pad_to(padded_total)
            pids_host = np.concatenate([
                pids_host,
                # trailing pad rows carry the last pid; their validity
                # mask is False, so they are inert in every kernel
                np.full(padded_total - total, pids_host[-1], np.int32),
            ])
        # the GT09 waivers below are deliberate: the sharded upload IS
        # the guarded residency swap — the same device-work-under-the-
        # instance-lock contract the single-chip _load path carries
        from geomesa_tpu.engine.device import to_device

        kw = {"coord_dtype": self.coord_dtype} if self.coord_dtype else {}
        # flat stores carry only [N]-leading arrays, so ONE row-sharded
        # NamedSharding placement covers the whole batch — host rows go
        # straight to their owning chip, no single-device staging hop
        row = NamedSharding(self.mesh, P(SHARD_AXIS))
        prev = self._mesh_growth_prev(names)
        if prev is not None:
            # delta-append: host→device transfer covers ONLY the rows
            # past the previous concat (new partitions + the new mesh
            # padding); the old rows re-place from the previous device
            # buffers over ICI/device copies, never the tunnel
            old_concat = prev["concat_rows"]
            tail = batch.select(np.arange(old_concat, len(batch)))
            tail_dev = to_device(tail, **kw)  # gt: waive GT09
            self.upload_count += 1
            self.upload_rows += len(tail)
            dev = {
                # gt: waive GT09
                # (device-side reassembly under the residency lock —
                # same guarded-swap contract as the uploads above)
                k: jax.device_put(jnp.concatenate(
                    [prev["dev"][k][:old_concat], tail_dev[k]]), row)
                for k in tail_dev
            }
            pids = jax.device_put(jnp.concatenate(  # gt: waive GT09
                [prev["pids"][:old_concat],
                 jnp.asarray(pids_host[old_concat:])]), row)
        else:
            dev = to_device(batch, device=row, **kw)  # gt: waive GT09
            self.upload_count += 1
            self.upload_rows += len(batch)
            pids = jax.device_put(  # gt: waive GT09
                jnp.asarray(pids_host), row)
        shard_rows = padded_total // d
        owners: Dict[str, tuple] = {}
        off = 0
        for name, e in zip(names, entries):
            lo, hi = off, off + e.padded
            owners[name] = tuple(
                range(lo // shard_rows,
                      min((hi - 1) // shard_rows + 1, d)))
            off = hi
        self._super = SuperBatch(
            batch=batch,
            dev=dev,
            pids=pids,
            ids={n: i for i, n in enumerate(names)},
            version=self._version,
            mesh=self.mesh,
            shard_rows=shard_rows,
            owners=owners,
        )
        self._mesh_prev = {
            "mesh": self.mesh,
            "names": tuple(names),
            "meta": {n: (e.padded, tuple(e.files))
                     for n, e in zip(names, entries)},
            "concat_rows": total,
            "dev": dev,
            "pids": pids,
        }
        return self._super

    def _mesh_growth_prev(self, names) -> Optional[dict]:
        """The previous mesh layout IF the pending rebuild is a pure
        GROWTH against it: same mesh, the old name sequence is a strict
        prefix of the new sorted one (appends only — a name sorting
        into the middle shifts every later partition's rows), and every
        previously-resident entry is byte-identical (same padded length
        and file list). Anything else returns None → full re-upload."""
        prev = self._mesh_prev
        if prev is None or prev["mesh"] is not self.mesh:
            return None
        pn = prev["names"]
        if len(names) <= len(pn) or tuple(names[: len(pn)]) != pn:
            return None
        for name in pn:
            e = self._entries.get(name)
            meta = prev["meta"][name]
            if e is None or e.padded != meta[0] \
                    or tuple(e.files) != meta[1]:
                return None
        return prev

    @_locked
    def resident(self) -> List[str]:
        return sorted(self._entries)

    @_locked
    def stats(self) -> dict:
        return {
            "partitions": len(self._entries),
            "rows": sum(e.count for e in self._entries.values()),
            "padded_rows": sum(e.padded for e in self._entries.values()),
            "uploads": self.upload_count,
            "upload_rows": self.upload_rows,
            "layout_version": LAYOUT_VERSION,
        }

    # -- manifest persistence (restart determinism) ------------------------

    @property
    def manifest_path(self) -> str:
        return os.path.join(self.storage.root, MANIFEST)

    @_locked
    def save_manifest(self) -> None:
        from geomesa_tpu.parallel.distributed import is_coordinator

        if not is_coordinator():
            # multi-host: residency is globally consistent (every host
            # computes the same superbatch layout), so the manifests
            # would be byte-identical — one writer is the contract
            # anyway (GT27)
            return
        doc = {
            "layout_version": LAYOUT_VERSION,
            "coord_dtype": str(np.dtype(self.coord_dtype).name)
            if self.coord_dtype
            else None,
            "partitions": {
                name: {"files": e.files, "count": e.count, "padded": e.padded}
                for name, e in self._entries.items()
            },
        }
        tmp = self.manifest_path + ".tmp"
        # gt: waive GT09
        # (deliberate: manifest persistence under the lock keeps the
        # snapshot consistent with residency; the file swap is atomic)
        with open(tmp, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
        os.replace(tmp, self.manifest_path)

    @_locked
    def resume(self) -> Tuple[List[str], List[str]]:
        """Rebuild device state from the saved manifest: reload every
        partition it names whose files still match; report (restored,
        stale). Stale = layout drift or file-list drift — reloaded fresh
        via ensure() by the caller if wanted."""
        if not os.path.exists(self.manifest_path):
            return [], []
        # gt: waive GT09
        # (deliberate: restart-time rebuild — determinism of the restored
        # device state depends on the lock excluding queries)
        with open(self.manifest_path) as f:
            doc = json.load(f)
        restored, stale = [], []
        if doc.get("layout_version") != LAYOUT_VERSION:
            return [], sorted(doc.get("partitions", {}))
        snap = self.storage.manifest_snapshot()
        for name, meta in sorted(doc.get("partitions", {}).items()):
            if self._partition_files(name, snap) != meta["files"]:
                stale.append(name)
                continue
            entry = self._load_partition(name, snap)
            if entry is None:
                stale.append(name)
                continue
            assert entry.padded == meta["padded"], (
                f"non-deterministic rebuild for {name}: "
                f"{entry.padded} != {meta['padded']}"
            )
            self._entries[name] = entry
            restored.append(name)
        if restored:
            self._super = None  # residency changed: superbatch stale
            self._version += 1
        return restored, stale
