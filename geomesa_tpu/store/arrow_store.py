"""ArrowDataStore: a read-oriented DataStore over Arrow IPC files.

Parity: geomesa-arrow's ArrowDataStore (read an Arrow IPC stream as a
GeoTools DataStore — SURVEY.md:341 [upstream, unverified]). The IPC files
are the ones this framework itself writes (`core.arrow_io.write_ipc`, the
CLI's arrow export), carrying the SFT in schema metadata, so an exported
query result is immediately re-queryable: export -> hand the file around ->
open as a store. Writes go through `add_features` + `flush` (append
batches, rewrite the stream), matching upstream's file-granularity write
model.

Queries ride the STANDARD QueryPlanner over a duck-typed single-partition
storage (the same adapter pattern as kafka's MemoryStorage), so the full
surface — hints, interceptors, audit, visibility, count shortcuts,
consistent empty-result kinds — comes for free. The C11 "local fallback
separation" lesson again: the compute layer does not care that the storage
layer is a single file.
"""

from __future__ import annotations

import os
from typing import Dict, Iterator, List, Optional, Sequence

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.plan.datastore import FeatureSource
from geomesa_tpu.plan.planner import QueryPlanner


class _IpcStorage:
    """Duck-typed single-partition storage over one in-memory batch."""

    def __init__(self, sft: SimpleFeatureType, batch: FeatureBatch, root: str):
        self.sft = sft
        self.batch = batch
        # stats.json is never written for an IPC file; point the stats
        # manager somewhere that does not exist
        self.root = root + ".nostats"

    @property
    def count(self) -> int:
        return len(self.batch)

    def partitions(self) -> List[str]:
        return ["ipc"]

    def prune_partitions(self, bbox: BBox, interval: Interval) -> List[str]:
        return ["ipc"] if len(self.batch) else []

    def scan(
        self,
        bbox: Optional[BBox] = None,
        interval: Optional[Interval] = None,
        columns: Optional[Sequence[str]] = None,
    ) -> Iterator[FeatureBatch]:
        if len(self.batch):
            yield self.batch  # covering superset; residual mask follows


class ArrowFeatureSource(FeatureSource):
    def __init__(self, path: str):
        from geomesa_tpu.core.arrow_io import read_ipc

        self.path = path
        batches = read_ipc(path)
        if not batches:
            raise ValueError(f"empty arrow stream: {path}")
        batch = (
            FeatureBatch.concat(batches) if len(batches) > 1 else batches[0]
        )
        storage = _IpcStorage(batch.sft, batch, path)
        super().__init__(storage, QueryPlanner(storage))
        self._pending: List[FeatureBatch] = []

    def __len__(self) -> int:
        return len(self.storage.batch)

    # -- writes (file-granularity append) ----------------------------------

    def write(self, batch: FeatureBatch) -> None:
        self.add_features(batch)
        self.flush()

    def add_features(self, batch: FeatureBatch) -> None:
        if batch.sft.to_spec() != self.sft.to_spec():
            raise ValueError("schema mismatch on arrow append")
        self._pending.append(batch)

    def flush(self) -> None:
        """Rewrite the stream with appended batches (IPC streams are not
        appendable in place; upstream's writer also rewrites)."""
        from geomesa_tpu.core.arrow_io import write_ipc

        if not self._pending:
            return
        self.storage.batch = FeatureBatch.concat(
            [self.storage.batch] + self._pending
        )
        self._pending = []
        tmp = self.path + ".tmp"
        write_ipc(tmp, [self.storage.batch])
        # gt: waive GT27
        # (single-writer store by contract: the Arrow IPC rewrite is
        # the ingest path, which runs before a store is served; multi-
        # host feeding uses the FS store with per-host disjoint
        # partitions via process_partitions())
        os.replace(tmp, self.path)


class ArrowDataStore:
    """Catalog over a directory of `.arrow` IPC files (or one file). Each
    file is one feature type, named by the SFT in its metadata."""

    def __init__(self, path: str):
        self.path = path
        self._sources: Dict[str, ArrowFeatureSource] = {}
        if os.path.isdir(path):
            files = [
                os.path.join(path, fn)
                for fn in sorted(os.listdir(path))
                if fn.endswith(".arrow")
            ]
        else:
            files = [path]
        for fp in files:
            src = ArrowFeatureSource(fp)
            self._sources[src.sft.name] = src

    def get_feature_source(self, name: Optional[str] = None) -> ArrowFeatureSource:
        if name is None:
            if len(self._sources) != 1:
                raise ValueError("name required: store has multiple types")
            return next(iter(self._sources.values()))
        return self._sources[name]

    def get_schema(self, name: str) -> SimpleFeatureType:
        return self._sources[name].sft

    def get_type_names(self) -> List[str]:
        return sorted(self._sources)
