"""Filesystem (Parquet) storage: partitioned writes, pruned + pushed-down reads.

Parity: geomesa-fs-storage-parquet SimpleFeatureParquetWriter + FilterConverter
(CQL -> Parquet predicate pushdown) and geomesa-fs-datastore's
query = prune partitions -> read files w/ pushdown -> residual pipeline
[upstream, unverified].

Layout on disk:

    <root>/metadata.json            sft spec + scheme config + manifest
    <root>/<partition>/<uuid>.parquet

Parquet schema is the flat columnar mapping of core.arrow_io (point geometry
as x/y float64 columns named <attr>__x/__y so min/max row-group statistics
prune on bbox; extended geometries as WKT plus <attr>__bbox_* bound columns).
Partition pruning consumes the covering sets from store.partition; pruned
names match partitions by exact name or path-prefix (composite wildcards).
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import uuid
from typing import Dict, Iterator, List, Optional, Sequence, Set

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc
import pyarrow.parquet as pq

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import parse_wkt, to_wkt
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.faults import BREAKERS, RetryPolicy, retry_call
from geomesa_tpu.faults import harness as _faults
from geomesa_tpu.store.partition import PartitionScheme, scheme_from_config

METADATA = "metadata.json"
FID = "__fid__"

# fault-injection sites + retry policy for the storage boundary
# (docs/ROBUSTNESS.md). Reads and partition-file writes retry transient
# I/O against the "storage" breaker. The manifest commit is DELIBERATELY
# non-retryable: it runs under the manifest lock (sleeping there stalls
# every reader/writer) and the tmp+os.replace swap is already
# all-or-nothing — a failed commit leaves the previous manifest intact,
# never a torn one (.gmtpu-waivers documents this contract).
_READ_SITE = _faults.site(
    "fs.read_partition", "partition data file read (parquet/orc)")
_WRITE_SITE = _faults.site(
    "fs.write_partition", "partition data file write (staging)")
_MANIFEST_SITE = _faults.site(
    "fs.write_manifest", "metadata.json manifest commit (atomic swap)")
_STORAGE_RETRY = RetryPolicy(max_attempts=4, base_ms=5.0, cap_ms=250.0)


class ManifestSnapshot(Dict[str, List[dict]]):
    """A plain partition->entries dict plus the commit version it was
    taken at (monotonic per storage instance). Every dict consumer works
    unchanged; version-aware consumers use `.version` to refuse applying
    an older snapshot over newer state."""

    version: int = 0


def _batch_to_table(batch: FeatureBatch) -> pa.Table:
    arrays: Dict[str, pa.Array] = {}
    for a in batch.sft.attributes:
        col = batch.columns[a.name]
        if isinstance(col, GeometryColumn):
            if col.is_point:
                arrays[f"{a.name}__x"] = pa.array(col.x, pa.float64())
                arrays[f"{a.name}__y"] = pa.array(col.y, pa.float64())
            else:
                arrays[a.name] = pa.array(
                    [to_wkt(col.geometry(i)) for i in range(len(col))]
                )
                bb = col.bbox
                arrays[f"{a.name}__xmin"] = pa.array(bb[:, 0], pa.float64())
                arrays[f"{a.name}__ymin"] = pa.array(bb[:, 1], pa.float64())
                arrays[f"{a.name}__xmax"] = pa.array(bb[:, 2], pa.float64())
                arrays[f"{a.name}__ymax"] = pa.array(bb[:, 3], pa.float64())
        elif isinstance(col, DictColumn):
            codes = np.asarray(col.codes, np.int64)
            arrays[a.name] = pa.DictionaryArray.from_arrays(
                pa.array(codes, pa.int32(), mask=codes < 0),
                pa.array(col.vocab, pa.string()),
            )
        elif a.type == "Bytes":
            arrays[a.name] = pa.array(list(col), pa.binary())
        elif a.is_temporal:
            arrays[a.name] = pa.array(np.asarray(col, np.int64), pa.int64())
        else:
            arrays[a.name] = pa.array(col)
    if batch.fids is not None:
        codes = np.asarray(batch.fids.codes, np.int64)
        arrays[FID] = pa.DictionaryArray.from_arrays(
            pa.array(codes, pa.int32(), mask=codes < 0),
            pa.array(batch.fids.vocab, pa.string()),
        )
    return pa.table(arrays)


def _table_to_batch(t: pa.Table, sft: SimpleFeatureType) -> FeatureBatch:
    # projection support: narrow the SFT to the attributes present
    present = [
        a
        for a in sft.attributes
        if (a.name in t.schema.names)
        or (a.is_geometry and a.type == "Point" and f"{a.name}__x" in t.schema.names)
    ]
    if len(present) != len(sft.attributes):
        sft = SimpleFeatureType(sft.name, present, sft.user_data)
    cols: Dict[str, object] = {}
    for a in sft.attributes:
        if a.is_geometry:
            if a.type == "Point":
                x = t.column(f"{a.name}__x").to_numpy()
                y = t.column(f"{a.name}__y").to_numpy()
                cols[a.name] = GeometryColumn.from_points(x, y)
            else:
                geoms = [parse_wkt(w) for w in t.column(a.name).to_pylist()]
                cols[a.name] = GeometryColumn.from_geometries(geoms)
        elif a.type in ("String", "UUID"):
            col = t.column(a.name)
            arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
            if pa.types.is_dictionary(arr.type):
                codes = arr.indices.to_numpy(zero_copy_only=False)
                if codes.dtype.kind == "f":
                    codes = np.where(np.isnan(codes), -1, codes)
                cols[a.name] = DictColumn(codes.astype(np.int32), arr.dictionary.to_pylist())
            else:
                cols[a.name] = DictColumn.encode(arr.to_pylist())
        elif a.type == "Bytes":
            cols[a.name] = np.array(t.column(a.name).to_pylist(), dtype=object)
        else:
            cols[a.name] = t.column(a.name).to_numpy()
    fids = None
    if FID in t.schema.names:
        col = t.column(FID)
        arr = col.combine_chunks() if isinstance(col, pa.ChunkedArray) else col
        if pa.types.is_dictionary(arr.type):
            codes = arr.indices.to_numpy(zero_copy_only=False)
            if codes.dtype.kind == "f":
                codes = np.where(np.isnan(codes), -1, codes)
            fids = DictColumn(codes.astype(np.int32), arr.dictionary.to_pylist())
        else:
            fids = DictColumn.encode(arr.to_pylist())
    return FeatureBatch(sft, cols, fids)


class FileSystemStorage:
    """A partitioned Parquet (or ORC) feature store."""

    def __init__(
        self,
        root: str,
        sft: SimpleFeatureType,
        scheme: PartitionScheme,
        encoding: str = "parquet",
    ):
        if encoding not in ("parquet", "orc"):
            raise ValueError(f"unknown encoding {encoding!r}")
        self.root = root
        self.sft = sft
        self.scheme = scheme
        self.encoding = encoding
        # manifest: partition -> list of {"file", "count"}
        self.manifest: Dict[str, List[dict]] = {}
        # serve made writer-vs-scan concurrency the normal mode: without
        # this, _save_metadata can crash iterating the manifest mid-append
        # ("dictionary changed size") and readers see torn entry lists.
        # Data files are immutable once written, so only manifest state
        # needs the lock — file I/O stays outside it. The version bumps
        # on every committed mutation so consumers can order snapshots
        # (DeviceCacheManager refuses to roll residency backward).
        self._lock = threading.Lock()
        self._mversion = 0

    # -- lifecycle ---------------------------------------------------------

    @classmethod
    def create(
        cls,
        root: str,
        sft: SimpleFeatureType,
        scheme: PartitionScheme,
        encoding: str = "parquet",
    ) -> "FileSystemStorage":
        os.makedirs(root, exist_ok=True)
        if os.path.exists(os.path.join(root, METADATA)):
            raise FileExistsError(f"storage already exists at {root}")
        store = cls(root, sft, scheme, encoding)
        store._save_metadata()
        return store

    @classmethod
    def load(cls, root: str) -> "FileSystemStorage":
        with open(os.path.join(root, METADATA)) as f:
            meta = json.load(f)
        sft = SimpleFeatureType.from_spec(meta["name"], meta["spec"])
        store = cls(
            root,
            sft,
            scheme_from_config(meta["scheme"]),
            meta.get("encoding", "parquet"),
        )
        store.manifest = meta.get("manifest", {})
        return store

    def _save_metadata(self):
        """Persist metadata + manifest. Callers on the mutation paths
        hold self._lock so the json serialization sees one consistent
        manifest (a concurrent append would otherwise blow up the dict
        iteration); `create` runs before the store is shared."""
        from geomesa_tpu.parallel.distributed import is_coordinator

        if not is_coordinator():
            # multi-host runtimes READ the FS store (each host feeds
            # from its process_partitions slice); mutation is single-
            # writer before serving. The gate keeps a non-coordinator
            # host from clobbering the shared manifest with its
            # partial view of the partition set (GT27)
            return
        meta = {
            "version": 1,
            "name": self.sft.name,
            "spec": self.sft.to_spec(),
            "scheme": self.scheme.to_config(),
            "encoding": self.encoding,
            "manifest": self.manifest,
        }
        tmp = os.path.join(self.root, METADATA + ".tmp")
        # injection point for the chaos harness: a failure HERE (before
        # or during the tmp write) must leave the previous manifest
        # untouched — the no-torn-manifest invariant gmtpu chaos checks
        _MANIFEST_SITE.fire()
        # gt: waive GT09
        # (deliberate: persisting under the manifest lock is the point —
        # the snapshot must not move while it serializes; the final
        # os.replace swap is atomic for readers of the file)
        with open(tmp, "w") as f:
            json.dump(meta, f, indent=1)
        os.replace(tmp, os.path.join(self.root, METADATA))

    @property
    def count(self) -> int:
        with self._lock:
            return sum(f["count"]
                       for files in self.manifest.values() for f in files)

    # -- write -------------------------------------------------------------

    def write(self, batch: FeatureBatch) -> None:
        """Partition the batch by the scheme and append one parquet file per
        touched partition. Writes are idempotent at file granularity (fresh
        uuids), matching the reference's append model."""
        if batch.valid is not None and not batch.valid.all():
            batch = batch.select(batch.valid)
        names = np.asarray(self.scheme.partitions_for(batch))
        # stage every partition file FIRST (outside the lock), then
        # commit the whole batch to the manifest in ONE lock acquisition:
        # a concurrent reader snapshot sees all of this write or none of
        # it, so counts only ever move at batch boundaries (the serve
        # torn-read contract, tests/test_serve_concurrency.py)
        staged = []
        for name in np.unique(names):
            sub = batch.select(names == name)
            pdir = os.path.join(self.root, name)
            os.makedirs(pdir, exist_ok=True)
            fname = f"{uuid.uuid4().hex}.{self.encoding}"
            # retryable: the file is not in the manifest yet, so a
            # partial write from a failed attempt is an invisible
            # orphan the successful attempt simply overwrites
            retry_call(
                self._write_data_file, sub, os.path.join(pdir, fname),
                policy=_STORAGE_RETRY, label="storage",
                breaker=BREAKERS.get("storage"))
            staged.append((str(name), fname, len(sub)))
        with self._lock:
            for name, fname, count in staged:
                self.manifest.setdefault(name, []).append(
                    {"file": fname, "count": count}
                )
            try:
                self._save_metadata()
            except BaseException:
                # the durable commit failed: ROLL BACK the in-memory
                # append so memory never runs ahead of disk — otherwise
                # this "failed" batch would keep serving from memory, a
                # client retry would duplicate every row, and the next
                # unrelated write would silently commit it. We hold the
                # lock for the whole append+save, so our entries are
                # still the tail of each partition list; the staged
                # files become unreferenced orphans (harmless).
                for name, fname, count in staged:
                    entries = self.manifest.get(name, [])
                    if entries and entries[-1].get("file") == fname:
                        entries.pop()
                    if not entries:
                        self.manifest.pop(name, None)
                raise
            self._mversion += 1

    def compact(self, partition: Optional[str] = None) -> int:
        """Merge each touched partition's files into one (the FS store's
        compact command). Returns how many files were removed."""
        with self._lock:
            targets = [partition] if partition is not None \
                else list(self.manifest)
        removed = 0
        for name in targets:
            with self._lock:
                entries = list(self.manifest.get(name, []))
            if len(entries) <= 1:
                continue
            tables = []
            for entry in entries:
                path = os.path.join(self.root, name, entry["file"])
                tables.append(self._read_file(path, None, None))
            merged = pa.concat_tables(tables, promote_options="permissive")
            count = sum(e["count"] for e in entries)
            fname = f"{uuid.uuid4().hex}.{self.encoding}"
            out = os.path.join(self.root, name, fname)
            retry_call(self._write_table, merged, out,
                       policy=_STORAGE_RETRY, label="storage",
                       breaker=BREAKERS.get("storage"))
            # crash-safety ordering: write merged file, point the manifest
            # at it, persist — only then delete the old files. A crash
            # leaves either the old manifest (old files intact) or the new
            # one (merged file intact); never a manifest of missing files.
            with self._lock:
                # writes only APPEND, so the snapshot is a prefix of the
                # live list: keep any entry a concurrent write() added
                # since (wholesale replace would orphan its file/rows)
                prev = self.manifest.get(name)
                tail = self.manifest.get(name, [])[len(entries):]
                self.manifest[name] = [{"file": fname,
                                        "count": count}] + tail
                try:
                    self._save_metadata()
                except BaseException:
                    # memory must never run ahead of the durable
                    # manifest (same rollback as write/delete): restore
                    # the live pre-compact list — the merged file
                    # becomes an unreferenced orphan, the old files
                    # stay live and are NOT removed below
                    if prev is not None:
                        self.manifest[name] = prev
                    else:  # pragma: no cover - entries implied a list
                        self.manifest.pop(name, None)
                    raise
                self._mversion += 1
            for entry in entries:
                os.remove(os.path.join(self.root, name, entry["file"]))
                removed += 1
        return removed

    def delete_features(self, cql: "str | object") -> int:
        """Delete features matching an ECQL filter (geomesa-tools
        delete-features; upstream writes deletion mutations — here each
        touched file is rewritten without the matching rows). Exact f64
        host evaluation; crash-safety ordering as in compact (new file +
        manifest first, removals last). Returns rows deleted."""
        from geomesa_tpu.cql import ast, parse_cql
        from geomesa_tpu.cql.hosteval import eval_filter_host

        f = parse_cql(cql) if isinstance(cql, str) else cql
        if isinstance(f, ast.Include):
            # delete-all: clear every partition (schema stays). Same
            # crash-safety ordering as below: persist the emptied
            # manifest FIRST, remove files last — a crash then leaves
            # either the old manifest (files intact) or the new one
            # (orphaned files, harmless), never references to missing
            # files.
            total = self.count
            with self._lock:
                paths = [
                    os.path.join(self.root, name, entry["file"])
                    for name, entries in self.manifest.items()
                    for entry in entries
                ]
                prev = self.manifest
                self.manifest = {}
                try:
                    self._save_metadata()
                except BaseException:
                    # memory must never run ahead of the durable
                    # manifest (same invariant as write()'s rollback)
                    self.manifest = prev
                    raise
                self._mversion += 1
            for p in paths:
                os.remove(p)
            return total
        deleted = 0
        with self._lock:
            names = list(self.manifest)
        for name in names:
            new_entries = []
            removals = []
            changed = False
            with self._lock:
                entries = list(self.manifest.get(name, []))
            for entry in entries:
                path = os.path.join(self.root, name, entry["file"])
                batch = _table_to_batch(
                    self._read_file(path, None, None), self.sft)
                hit = eval_filter_host(f, batch)
                nh = int(hit.sum())
                if nh == 0:
                    new_entries.append(entry)
                    continue
                changed = True
                deleted += nh
                removals.append(entry["file"])
                keep = batch.select(~hit)
                if len(keep):
                    fname = f"{uuid.uuid4().hex}.{self.encoding}"
                    out = os.path.join(self.root, name, fname)
                    retry_call(self._write_data_file, keep, out,
                               policy=_STORAGE_RETRY, label="storage",
                               breaker=BREAKERS.get("storage"))
                    new_entries.append({"file": fname, "count": len(keep)})
            if changed:
                with self._lock:
                    # preserve entries a concurrent write() appended
                    # after our snapshot (appends-only: snapshot is a
                    # prefix of the live list)
                    prev = self.manifest.get(name)
                    tail = self.manifest.get(name, [])[len(entries):]
                    if new_entries or tail:
                        self.manifest[name] = new_entries + tail
                    else:
                        del self.manifest[name]
                    try:
                        self._save_metadata()
                    except BaseException:
                        # roll back: a failed durable commit must not
                        # leave the deletion visible in memory (phantom
                        # deletes that a restart would resurrect)
                        if prev is not None:
                            self.manifest[name] = prev
                        else:
                            self.manifest.pop(name, None)
                        raise
                    self._mversion += 1
                for fname in removals:
                    os.remove(os.path.join(self.root, name, fname))
        return deleted

    def age_off(self, older_than_ms: int, dtg_attr: "str | None" = None) -> int:
        """Delete features whose dtg is strictly before `older_than_ms`
        (the FS analog of the KV store's age-off; upstream: the age-off
        iterators/filters). Returns rows deleted."""
        from geomesa_tpu.cql import ast

        d = (self.sft.attribute(dtg_attr) if dtg_attr
             else self.sft.default_dtg)
        if d is None:
            raise ValueError("age_off needs a dtg attribute")
        return self.delete_features(
            ast.TemporalPredicate(
                "BEFORE", ast.Property(d.name), int(older_than_ms), None)
        )

    # -- read --------------------------------------------------------------

    def manifest_snapshot(self) -> "ManifestSnapshot":
        """One consistent view of partition -> entry list, taken in a
        single lock acquisition, stamped with the commit version.
        Queries that enumerate partitions and then read their files must
        do BOTH against the same snapshot, or a concurrent batch-atomic
        write tears across the two reads (new rows visible in old
        partitions, new partitions missing)."""
        with self._lock:
            snap = ManifestSnapshot(
                (name, list(entries))
                for name, entries in self.manifest.items())
            snap.version = self._mversion
            return snap

    def manifest_version(self) -> int:
        """The current committed write version (monotonic per
        instance) without copying the manifest — the serve result
        cache's peek-time key component (geomesa_tpu.approx.cache)."""
        with self._lock:
            return self._mversion

    def partitions(self) -> List[str]:
        with self._lock:
            return sorted(self.manifest)

    def prune_partitions(self, bbox: BBox, interval: Interval,
                         manifest: Optional[Dict[str, List[dict]]] = None,
                         ) -> List[str]:
        names = (sorted(manifest) if manifest is not None
                 else self.partitions())
        pruned = self.scheme.prune(bbox, interval)
        if pruned is None:
            return names
        out = []
        for name in names:
            for p in pruned:
                if name == p or name.startswith(p + "/") or p == "":
                    out.append(name)
                    break
        return sorted(out)

    def _pushdown_expr(self, bbox: BBox, interval: Interval):
        """Build a pyarrow filter expression from the covering bounds —
        the FilterConverter analog (row-group statistics do the pruning)."""
        g = self.sft.default_geometry
        d = self.sft.default_dtg
        expr = None

        def AND(a, b):
            return b if a is None else (a if b is None else a & b)

        if g is not None and not bbox.is_whole_world:
            if g.type == "Point":
                e = (
                    (pc.field(f"{g.name}__x") >= bbox.xmin)
                    & (pc.field(f"{g.name}__x") <= bbox.xmax)
                    & (pc.field(f"{g.name}__y") >= bbox.ymin)
                    & (pc.field(f"{g.name}__y") <= bbox.ymax)
                )
            else:
                e = (
                    (pc.field(f"{g.name}__xmin") <= bbox.xmax)
                    & (pc.field(f"{g.name}__xmax") >= bbox.xmin)
                    & (pc.field(f"{g.name}__ymin") <= bbox.ymax)
                    & (pc.field(f"{g.name}__ymax") >= bbox.ymin)
                )
            expr = AND(expr, e)
        if d is not None and not interval.is_unbounded:
            if interval.start is not None:
                expr = AND(expr, pc.field(d.name) >= int(interval.start))
            if interval.end is not None:
                expr = AND(expr, pc.field(d.name) <= int(interval.end))
        return expr

    def scan(
        self,
        bbox: Optional[BBox] = None,
        interval: Optional[Interval] = None,
        columns: Optional[Sequence[str]] = None,
    ) -> Iterator[FeatureBatch]:
        """Yield batches from pruned partitions with parquet pushdown.

        The result is a *covering* superset: exact predicate evaluation is
        the engine's job (residual mask), same as the reference's split.
        """
        bbox = bbox if bbox is not None else BBox(-180.0, -90.0, 180.0, 90.0)
        interval = interval if interval is not None else Interval(None, None)
        expr = self._pushdown_expr(bbox, interval)
        phys_cols = None
        if columns is not None:
            phys_cols = []
            for c in columns:
                a = self.sft.attribute(c)
                if a.is_geometry and a.type == "Point":
                    phys_cols += [f"{c}__x", f"{c}__y"]
                elif a.is_geometry:
                    phys_cols += [c, f"{c}__xmin", f"{c}__ymin", f"{c}__xmax", f"{c}__ymax"]
                else:
                    phys_cols.append(c)
        # one snapshot for BOTH pruning and entry reads: a batch-atomic
        # concurrent write is either fully visible or not at all
        snap = self.manifest_snapshot()
        for name in self.prune_partitions(bbox, interval, manifest=snap):
            for entry in snap.get(name, []):
                path = os.path.join(self.root, name, entry["file"])
                cols = phys_cols
                if phys_cols is not None:
                    # include fids only when the file actually has them
                    schema_names = self._file_schema_names(path)
                    cols = phys_cols + ([FID] if FID in schema_names else [])
                # geomesa.scan.batch.size bounds per-yield rows so one huge
                # file cannot force an oversized host allocation — and the
                # parquet path STREAMS row groups (pads.Scanner.to_batches)
                # so consumers can overlap decode with device compute (the
                # cold-path pipeline; the whole file is never materialized)
                from geomesa_tpu.utils.config import SystemProperties

                target = int(SystemProperties.SCAN_BATCH_SIZE.get())
                for t in self._stream_file(path, expr, cols, target):
                    if len(t):
                        yield _table_to_batch(t, self.sft)

    def scan_partitions(
        self,
        names: Sequence[str],
        manifest: Optional[Dict[str, List[dict]]] = None,
    ) -> Iterator[FeatureBatch]:
        """Yield every row (all columns) of the named partitions, no
        pushdown — the device-cache residency read (store.cache and the
        export jobs load whole partitions). Passing a `manifest`
        snapshot pins the read to one committed write version."""
        snap = manifest if manifest is not None else self.manifest_snapshot()
        for name in names:
            for entry in snap.get(name, []):
                path = os.path.join(self.root, name, entry["file"])
                t = self._read_file(path, None, None)
                if len(t):
                    yield _table_to_batch(t, self.sft)

    def _write_data_file(self, sub: FeatureBatch, path: str) -> None:
        """Encode + write one partition data file (the staged half of a
        batch-atomic write). A distinct method so the retry fabric can
        re-attempt the WHOLE encode+write as one idempotent unit."""
        self._write_table(_batch_to_table(sub), path)

    def _write_table(self, table: pa.Table, path: str) -> None:
        _WRITE_SITE.fire()
        if self.encoding == "orc":
            from pyarrow import orc

            orc.write_table(self._decode_dictionaries(table), path,
                            compression="zstd")
        else:
            pq.write_table(table, path, compression="zstd",
                           row_group_size=64 * 1024)

    @staticmethod
    def _decode_dictionaries(table: pa.Table) -> pa.Table:
        """ORC has no dictionary type: cast dict columns to their value
        type (the read path re-encodes into DictColumn)."""
        fields = []
        arrays = []
        for field in table.schema:
            col = table.column(field.name)
            if pa.types.is_dictionary(field.type):
                col = col.cast(field.type.value_type)
                field = pa.field(field.name, field.type.value_type)
            fields.append(field)
            arrays.append(col)
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))

    def _file_schema_names(self, path: str) -> List[str]:
        if self.encoding == "orc":
            from pyarrow import orc

            return orc.ORCFile(path).schema.names
        return pq.read_schema(path).names

    def _read_file(self, path: str, expr, cols):
        """Read one data file with predicate + column pushdown. Parquet uses
        row-group statistics natively; ORC goes through pyarrow.dataset for
        stripe-level filtering (the geomesa-fs-storage-orc analog).
        Transient read failures retry against the storage breaker —
        data files are immutable once committed, so a re-read is
        trivially idempotent."""
        return retry_call(
            self._read_file_once, path, expr, cols,
            policy=_STORAGE_RETRY, label="storage",
            breaker=BREAKERS.get("storage"))

    def _read_file_once(self, path: str, expr, cols):
        _READ_SITE.fire()
        if self.encoding == "orc":
            import pyarrow.dataset as pads

            dataset = pads.dataset(path, format="orc")
            return dataset.to_table(filter=expr, columns=cols)
        return pq.read_table(path, filters=expr, columns=cols)

    def _stream_file(self, path: str, expr, cols, target: int):
        """Yield ~target-row pyarrow Tables from one file incrementally.
        Parquet decodes row-group-wise with predicate+column pushdown;
        ORC falls back to a whole-file read chunked afterwards. Only the
        dataset/scanner OPEN retries: a failure mid-stream surfaces
        typed instead of replaying already-yielded rows (documented
        non-retryable case, docs/ROBUSTNESS.md)."""
        if self.encoding == "orc":
            t = self._read_file(path, expr, cols)
            for off in range(0, max(len(t), 1), target):
                yield t.slice(off, target)
            return
        import pyarrow as pa

        def _open():
            import pyarrow.dataset as pads

            _READ_SITE.fire()
            return pads.dataset(path, format="parquet").scanner(
                filter=expr, columns=cols, batch_size=target
            )

        scanner = retry_call(
            _open, policy=_STORAGE_RETRY, label="storage",
            breaker=BREAKERS.get("storage"))
        pending = []
        rows = 0
        for rb in scanner.to_batches():
            while rb.num_rows:
                take = min(rb.num_rows, target - rows)
                pending.append(rb.slice(0, take))
                rb = rb.slice(take)
                rows += take
                if rows >= target:  # hard per-yield bound (SCAN_BATCH_SIZE)
                    yield pa.Table.from_batches(pending)
                    pending, rows = [], 0
        if pending:
            yield pa.Table.from_batches(pending)

    def read_all(self) -> Optional[FeatureBatch]:
        batches = list(self.scan())
        return FeatureBatch.concat(batches) if batches else None
