"""Partition schemes: feature -> partition path; query bounds -> partition set.

Parity: geomesa-fs-storage-common partition schemes (DateTimeScheme,
Z2Scheme/XZ2Scheme, attribute scheme, composite hierarchies) and their
partition-pruning contract (filter -> covered partition list) [upstream,
unverified].

A scheme assigns every feature a partition name (a relative path segment);
`prune` maps extracted query bounds (BBox + Interval) to the set of partition
names that may contain matches — a covering set, possibly `None` meaning
"cannot prune, scan all".
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Sequence, Set

import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.cql.extract import BBox, Interval
from geomesa_tpu.curve.z2 import Z2SFC
from geomesa_tpu.curve.xz import XZ2SFC


class PartitionScheme:
    def partitions_for(self, batch: FeatureBatch) -> List[str]:
        """Partition name per feature (len == len(batch))."""
        raise NotImplementedError

    def prune(self, bbox: BBox, interval: Interval) -> Optional[Set[str]]:
        """Covering partition set for the bounds, or None (= all)."""
        raise NotImplementedError

    def to_config(self) -> dict:
        raise NotImplementedError


_DT_PATTERNS: Dict[str, str] = {
    # upstream uses Java DateTimeFormatter patterns; keep the same surface
    "yyyy": "%Y",
    "yyyy/MM": "%Y/%m",
    "yyyy/MM/dd": "%Y/%m/%d",
    "yyyy/MM/dd/HH": "%Y/%m/%d/%H",
    "yyyy/DDD": "%Y/%j",
}

_STEP = {
    "yyyy": "Y",
    "yyyy/MM": "M",
    "yyyy/MM/dd": "D",
    "yyyy/MM/dd/HH": "h",
    "yyyy/DDD": "D",
}


@dataclasses.dataclass
class DateTimeScheme(PartitionScheme):
    """Time-bucketed directories, e.g. 2020/06/01 (pattern yyyy/MM/dd)."""

    pattern: str = "yyyy/MM/dd"
    dtg_attr: str = "dtg"

    def __post_init__(self):
        if self.pattern not in _DT_PATTERNS:
            raise ValueError(
                f"unsupported datetime pattern {self.pattern!r}; "
                f"one of {sorted(_DT_PATTERNS)}"
            )

    def _format(self, millis: np.ndarray) -> List[str]:
        import datetime as _dt

        fmt = _DT_PATTERNS[self.pattern]
        return [
            _dt.datetime.fromtimestamp(int(m) / 1000, _dt.timezone.utc).strftime(fmt)
            for m in np.asarray(millis, np.int64)
        ]

    def partitions_for(self, batch: FeatureBatch) -> List[str]:
        return self._format(batch.columns[self.dtg_attr])

    def prune(self, bbox: BBox, interval: Interval) -> Optional[Set[str]]:
        if interval.start is None or interval.end is None:
            return None
        step = _STEP[self.pattern]
        t0 = np.datetime64(int(interval.start), "ms").astype(f"datetime64[{step}]")
        t1 = np.datetime64(int(interval.end), "ms").astype(f"datetime64[{step}]")
        bins = np.arange(t0, t1 + np.timedelta64(1, step))
        millis = bins.astype("datetime64[ms]").astype(np.int64)
        return set(self._format(millis))

    def to_config(self):
        return {"scheme": "datetime", "pattern": self.pattern, "dtg": self.dtg_attr}


@dataclasses.dataclass
class Z2Scheme(PartitionScheme):
    """Z2-prefix directories: the top `bits` bits per dimension of the Z2
    curve, e.g. z2/0213 for bits=2 (4^2 cells). Points only."""

    bits: int = 4
    geom_attr: str = "geom"

    def __post_init__(self):
        self._sfc = Z2SFC(self.bits)
        self._digits = max(1, (2 * self.bits + 3) // 4)

    def _name(self, z: np.ndarray) -> List[str]:
        return [f"z2/{int(v):0{self._digits}x}" for v in np.asarray(z).ravel()]

    def partitions_for(self, batch: FeatureBatch) -> List[str]:
        col = batch.columns[self.geom_attr]
        assert isinstance(col, GeometryColumn)
        z = self._sfc.index(col.x, col.y)
        return self._name(z)

    def prune(self, bbox: BBox, interval: Interval) -> Optional[Set[str]]:
        if bbox.is_whole_world:
            return None
        out: Set[str] = set()
        for r in self._sfc.ranges(bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax,
                                  max_ranges=4 ** self.bits):
            for z in range(r.lower, r.upper + 1):
                out.add(f"z2/{z:0{self._digits}x}")
        return out

    def to_config(self):
        return {"scheme": "z2", "bits": self.bits, "geom": self.geom_attr}


@dataclasses.dataclass
class XZ2Scheme(PartitionScheme):
    """XZ2 sequence-code directories for extended geometries."""

    g: int = 4
    geom_attr: str = "geom"

    def __post_init__(self):
        self._sfc = XZ2SFC(self.g)

    def partitions_for(self, batch: FeatureBatch) -> List[str]:
        col = batch.columns[self.geom_attr]
        assert isinstance(col, GeometryColumn)
        out = []
        if col.is_point:
            for x, y in zip(col.x, col.y):
                out.append(f"xz2/{self._sfc.index(x, y, x, y)}")
        else:
            for i in range(len(col)):
                x0, y0, x1, y1 = col.bbox[i]
                out.append(f"xz2/{self._sfc.index(x0, y0, x1, y1)}")
        return out

    def prune(self, bbox: BBox, interval: Interval) -> Optional[Set[str]]:
        if bbox.is_whole_world:
            return None
        out: Set[str] = set()
        from geomesa_tpu.utils.config import SystemProperties

        budget = int(SystemProperties.SCAN_RANGES_TARGET.get())
        for r in self._sfc.ranges(bbox.xmin, bbox.ymin, bbox.xmax, bbox.ymax,
                                  max_ranges=budget):
            for c in range(r.lower, r.upper + 1):
                out.add(f"xz2/{c}")
        return out

    def to_config(self):
        return {"scheme": "xz2", "g": self.g, "geom": self.geom_attr}


@dataclasses.dataclass
class AttributeScheme(PartitionScheme):
    """One directory per attribute value (dictionary columns only)."""

    attr: str = "type"

    def partitions_for(self, batch: FeatureBatch) -> List[str]:
        col = batch.columns[self.attr]
        assert isinstance(col, DictColumn)
        return [v if v is not None else "__null__" for v in col.decode()]

    def prune(self, bbox: BBox, interval: Interval) -> Optional[Set[str]]:
        return None  # attribute bounds don't flow through BBox/Interval (yet)

    def to_config(self):
        return {"scheme": "attribute", "attr": self.attr}


@dataclasses.dataclass
class CompositeScheme(PartitionScheme):
    """Hierarchical composition: parent/child paths (upstream: composite
    schemes like datetime,z2)."""

    schemes: Sequence[PartitionScheme] = ()

    def partitions_for(self, batch: FeatureBatch) -> List[str]:
        parts = [s.partitions_for(batch) for s in self.schemes]
        return ["/".join(p) for p in zip(*parts)]

    def prune(self, bbox: BBox, interval: Interval) -> Optional[Set[str]]:
        pruned = [s.prune(bbox, interval) for s in self.schemes]
        if all(p is None for p in pruned):
            return None
        # cartesian product of per-level sets; None level = wildcard, which
        # we cannot enumerate, so fall back to prefix filtering by the
        # first non-None levels only
        out: Set[str] = {""}
        for p in pruned:
            if p is None:
                # wildcard: signal prefix-match semantics via trailing '/'
                return {prefix for prefix in out}
            out = {
                (f"{prefix}/{name}" if prefix else name)
                for prefix in out
                for name in p
            }
        return out

    def to_config(self):
        return {"scheme": "composite",
                "schemes": [s.to_config() for s in self.schemes]}


def scheme_from_config(cfg: dict) -> PartitionScheme:
    kind = cfg["scheme"]
    if kind == "datetime":
        return DateTimeScheme(cfg.get("pattern", "yyyy/MM/dd"), cfg.get("dtg", "dtg"))
    if kind == "z2":
        return Z2Scheme(cfg.get("bits", 4), cfg.get("geom", "geom"))
    if kind == "xz2":
        return XZ2Scheme(cfg.get("g", 4), cfg.get("geom", "geom"))
    if kind == "attribute":
        return AttributeScheme(cfg.get("attr", "type"))
    if kind == "composite":
        return CompositeScheme([scheme_from_config(s) for s in cfg["schemes"]])
    raise ValueError(f"unknown partition scheme {kind!r}")
