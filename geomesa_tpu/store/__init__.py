"""Storage: filesystem (Parquet) datastore, partition schemes, device cache.

Parity: geomesa-fs (geomesa-fs-storage-api / -common / -parquet /
-datastore) [upstream, unverified] — the store behind BASELINE config #1.
"""

from geomesa_tpu.store.partition import (
    AttributeScheme,
    CompositeScheme,
    DateTimeScheme,
    PartitionScheme,
    XZ2Scheme,
    Z2Scheme,
    scheme_from_config,
)
from geomesa_tpu.store.fs import FileSystemStorage

__all__ = [
    "PartitionScheme",
    "DateTimeScheme",
    "Z2Scheme",
    "XZ2Scheme",
    "AttributeScheme",
    "CompositeScheme",
    "scheme_from_config",
    "FileSystemStorage",
]
