"""Storage: filesystem (Parquet) datastore, partition schemes, device cache.

Parity: geomesa-fs (geomesa-fs-storage-api / -common / -parquet /
-datastore) [upstream, unverified] — the store behind BASELINE config #1.
"""

from geomesa_tpu.store.partition import (
    AttributeScheme,
    CompositeScheme,
    DateTimeScheme,
    PartitionScheme,
    XZ2Scheme,
    Z2Scheme,
    scheme_from_config,
)
from geomesa_tpu.store.fs import FileSystemStorage


def __getattr__(name):
    # lazy: arrow_store rides the QueryPlanner, whose module imports
    # store.fs — importing it eagerly here would close an import cycle
    if name in ("ArrowDataStore", "ArrowFeatureSource"):
        from geomesa_tpu.store import arrow_store

        return getattr(arrow_store, name)
    raise AttributeError(name)

__all__ = [
    "ArrowDataStore",
    "ArrowFeatureSource",
    "PartitionScheme",
    "DateTimeScheme",
    "Z2Scheme",
    "XZ2Scheme",
    "AttributeScheme",
    "CompositeScheme",
    "scheme_from_config",
    "FileSystemStorage",
]
