"""Vmapped parametric geofence lane kernels.

One kernel per geofence CLASS, batched over an [S]-axis parameter
table — the evaluation half of ROADMAP item 3 (the transport half is
PR 13's PushMux). The fused standing-query kernel spends one slot per
predicate, so its trace/compile cost and its rebuild-on-churn cost are
O(S) in registered geofences; a lane evaluates every same-class
geofence as ONE [S, N] broadcast whose compiled program is independent
of S — registration churn is a parameter-ROW write, never a retrace.

Bit-identity contract (the subscribe parity tests pin it): each lane
reproduces cql/compile.py's per-predicate arithmetic exactly —
identical f32 elementwise ops in identical order, so a lane row equals
the one-shot compiled filter's mask for the same predicate. Bands
mirror the compiled filter's f32 ambiguity bands (bbox edge ulp bands,
polygon BAND_EPS terms; dwithin compiles with NO band) so the
evaluator's f64 host refinement patches exactly the same rows.

Layout notes: parameters ride [S, P] f32 tables (rows = geofences),
padded to pow2 [S]-buckets with an `active` mask column — inactive and
never-assigned rows compute garbage that the mask AND discards. The
[S, N] broadcast is pure elementwise work that XLA tiles onto the VPU;
polygon lanes inline the dense crossing-number formula over an
[S, 4, E] edge table (pad edges are degenerate points at a far-away
coordinate: zero crossings, zero band) instead of calling
pip.points_in_polygon under vmap, which could route into the Pallas
streamed-tile kernel whose block shapes assume a flat [N].

Module-level jits only: this module is in compilecache ENGINE_MODULES,
so the ExecutableRegistry default sweep registers each lane as
``lanes.lane_<class>`` (AOT-keyed by the ([S]-bucket, N-bucket) shape
signature — `gmtpu warmup --check` covers lanes) and the JitTracker
recompile counters see every lane call.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from geomesa_tpu.engine.geodesy import haversine_m
from geomesa_tpu.engine.pip import BAND_EPS

# the evaluator dispatches these by class name (`lane_{cls}` getattr,
# which is what lets the JitTracker's module-attribute wrap intercept
# lane calls) — the export list is the static record of that surface
__all__ = ["lane_bbox", "lane_dwithin", "lane_polygon"]


@jax.jit
def lane_bbox(prm, active, x, y, valid):
    """BBOX lane: [S, 8] params vs [N] points -> (mask, band) [S, N].

    Row layout: (x0, x1, y0, y1, ex0, ex1, ey0, ey1) — the bbox
    extents plus cql.compile.f32_ulp_band half-widths per edge. Mask
    and band are the compiled bbox predicate's exact f32 arithmetic,
    ANDed with the row's active flag and the batch validity column
    (the compiled filter's top-level `& dev[VALID]`).
    """
    X = x[None, :]
    Y = y[None, :]
    x0, x1 = prm[:, 0:1], prm[:, 1:2]
    y0, y1 = prm[:, 2:3], prm[:, 3:4]
    mask = (X >= x0) & (X <= x1) & (Y >= y0) & (Y <= y1)
    band = (
        (jnp.abs(X - x0) <= prm[:, 4:5]) | (jnp.abs(X - x1) <= prm[:, 5:6])
        | (jnp.abs(Y - y0) <= prm[:, 6:7]) | (jnp.abs(Y - y1) <= prm[:, 7:8])
    )
    live = active[:, None] & valid[None, :]
    return mask & live, band & live


@jax.jit
def lane_dwithin(prm, active, x, y, valid):
    """DWITHIN lane: [S, 3] (lon, lat, meters) vs [N] points.

    The compiled single-point DWITHIN is `haversine_m(x, y, px, py)
    <= d` with NO ambiguity band (bands come only from bbox/polygon
    predicates), so the lane's band is all-False — parity with the
    one-shot path is pure f32 mask equality.
    """
    m = haversine_m(x[None, :], y[None, :],
                    prm[:, 0:1], prm[:, 1:2]) <= prm[:, 2:3]
    live = active[:, None] & valid[None, :]
    mask = m & live
    return mask, jnp.zeros_like(mask)


@jax.jit
def lane_polygon(edges, active, x, y, valid):
    """Polygon lane: [S, 4, E] edge tables vs [N] points.

    Inlines pip.points_in_polygon's dense crossing-number formula and
    points_in_polygon_band's flag terms with an extra [S] axis. Pad
    edges (rows shorter than the E-bucket, and unassigned rows) are
    degenerate points at a far-away coordinate: their crossing
    condition is identically False and both band terms miss, so
    padding changes neither the integer crossing sum nor the band.
    """
    px = x[None, :, None]                 # [1, N, 1]
    py = y[None, :, None]
    x1 = edges[:, 0][:, None, :]          # [S, 1, E]
    y1 = edges[:, 1][:, None, :]
    x2 = edges[:, 2][:, None, :]
    y2 = edges[:, 3][:, None, :]
    cond = (y1 <= py) != (y2 <= py)
    t = (py - y1) / jnp.where(y2 == y1, 1.0, y2 - y1)
    xc = x1 + t * (x2 - x1)
    crossings = jnp.sum(cond & (xc > px), axis=2)
    mask = (crossings % 2) == 1
    eps = BAND_EPS
    near_flat = (
        (jnp.abs(py - y1) <= eps)
        & (jnp.abs(py - y2) <= eps)
        & (px >= jnp.minimum(x1, x2) - eps)
        & (px <= jnp.maximum(x1, x2) + eps)
    )
    err = eps * (
        1.0 + jnp.abs(x2 - x1) / jnp.maximum(jnp.abs(y2 - y1), eps)
    )
    near_cross = cond & (jnp.abs(xc - px) <= err)
    band = jnp.any(near_flat | near_cross, axis=2)
    live = active[:, None] & valid[None, :]
    return mask & live, band & live
