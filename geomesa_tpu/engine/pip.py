"""Point-in-polygon kernels.

Parity role: the geometry-predicate evaluation that the reference delegates
to JTS prepared geometries inside FilterTransformIterator / CqlTransformFilter
(geomesa-filter FastFilterFactory's prepared-geometry optimization) [upstream,
unverified]. TPU-first design: the polygon is decomposed host-side into an
edge table (all rings concatenated — even-odd rule makes holes free), and the
device kernel is a dense (N points x E edges) crossing-number count that XLA
tiles onto the VPU. For big polygon sets, engine.pip_join provides the
CSR/bucketed variant.

Boundary semantics: crossing-number with half-open edge rule — points exactly
on a horizontal-crossing boundary may fall either way at f32 resolution
(documented divergence; the reference inherits JTS's exact predicates).
`points_in_polygon_np` is the NumPy f64 oracle with identical edge rule.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.core.wkt import Geometry


def polygon_edges(geom: Geometry) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Host-side: all ring edges of a geometry as (x1,y1,x2,y2).

    Rings of polygon kinds are closed if not explicitly closed; line kinds
    keep open paths (a closing edge would fabricate a phantom segment).
    Even-odd counting over the concatenated edge table handles holes and
    multi-parts without any per-ring bookkeeping.
    """
    close = "Polygon" in geom.kind or geom.kind in ("Geometry", "GeometryCollection")
    x1s, y1s, x2s, y2s = [], [], [], []
    for ring in geom.rings:
        r = np.asarray(ring, np.float64)
        if len(r) < 2:
            continue
        if close and not np.array_equal(r[0], r[-1]):
            r = np.concatenate([r, r[:1]], axis=0)
        x1s.append(r[:-1, 0])
        y1s.append(r[:-1, 1])
        x2s.append(r[1:, 0])
        y2s.append(r[1:, 1])
    if not x1s:
        z = np.zeros(0, np.float64)
        return z, z, z, z
    return (
        np.concatenate(x1s),
        np.concatenate(y1s),
        np.concatenate(x2s),
        np.concatenate(y2s),
    )


def points_in_polygon(px, py, x1, y1, x2, y2):
    """Crossing-number test: [N] points vs [E] edges -> bool [N].

    Edge rule: an edge crosses the upward ray from p iff exactly one endpoint
    is strictly above p's y (half-open: y1 <= py < y2 or y2 <= py < y1), and
    the edge's x at py is strictly right of px. Even crossings = outside.

    On TPU with enough work, dispatches to the Pallas streamed-tile kernel
    (engine.pip_pallas) — O(N+E) HBM traffic vs this dense path's O(N·E).
    """
    from geomesa_tpu.engine.pip_pallas import (
        points_in_polygon_pallas,
        use_pallas_pip,
    )

    if use_pallas_pip(px.shape[0], x1.shape[0]):
        return points_in_polygon_pallas(px, py, x1, y1, x2, y2)
    px = px[:, None]
    py = py[:, None]
    cond = (y1[None, :] <= py) != (y2[None, :] <= py)
    # x coordinate where the edge crosses the horizontal line at py
    t = (py - y1[None, :]) / jnp.where(
        y2[None, :] == y1[None, :], 1.0, y2[None, :] - y1[None, :]
    )
    xc = x1[None, :] + t * (x2[None, :] - x1[None, :])
    crossings = jnp.sum(cond & (xc > px), axis=1)
    return (crossings % 2) == 1


# f32 boundary ambiguity band, degrees. Must dominate (a) the f64->f32
# coordinate cast error (ulp(180) ~ 2.1e-5) and (b) the crossing-x
# arithmetic error, which the band test scales per edge by its slope
# (nearly-horizontal edges amplify t = (py-y1)/(y2-y1)); edges flatter
# than the band are caught by the endpoint-proximity term instead.
BAND_EPS = 1e-4


def points_in_polygon_band(px, py, x1, y1, x2, y2, eps: float = BAND_EPS):
    """Boundary-ambiguity flags: True where the f32 crossing test may
    disagree with f64 (SURVEY.md:824-827 robustness plan). Flag rule per
    edge (see pip_sparse._crossing_and_band for the proof): a crossing
    whose x lands within the slope-amplified error of px, or a
    near-horizontal edge (both endpoint ys within eps of py) whose
    eps-inflated bbox contains the point — the only case where the two
    span comparisons can flip independently. A general endpoint-y strip
    is NOT needed: vertex comparisons are bit-consistent across a closed
    ring's incident edges in any precision, so parity survives rounding
    away from the boundary. Callers re-evaluate flagged rows on host in
    f64 (cql.hosteval) — see CompiledFilter.mask_refined."""
    from geomesa_tpu.engine.pip_pallas import (
        points_in_polygon_band_pallas,
        use_pallas_pip,
    )

    if use_pallas_pip(px.shape[0], x1.shape[0]):
        return points_in_polygon_band_pallas(px, py, x1, y1, x2, y2, eps=eps)
    px = px[:, None]
    py = py[:, None]
    # band terms match pip_sparse._crossing_and_band (see its proof):
    # edge-crossing proximity + the near-horizontal-edge bbox; a general
    # endpoint-y strip is unnecessary (vertex comparisons are consistent
    # across a closed ring's incident edges in any precision)
    near_flat = (
        (jnp.abs(py - y1[None, :]) <= eps)
        & (jnp.abs(py - y2[None, :]) <= eps)
        & (px >= jnp.minimum(x1, x2)[None, :] - eps)
        & (px <= jnp.maximum(x1, x2)[None, :] + eps)
    )
    cond = (y1[None, :] <= py) != (y2[None, :] <= py)
    dy = jnp.where(y2 == y1, 1.0, y2 - y1)[None, :]
    t = (py - y1[None, :]) / dy
    xc = x1[None, :] + t * (x2[None, :] - x1[None, :])
    err = eps * (
        1.0
        + jnp.abs(x2 - x1)[None, :] / jnp.maximum(jnp.abs(y2 - y1), eps)[None, :]
    )
    near_cross = cond & (jnp.abs(xc - px) <= err)
    return jnp.any(near_flat | near_cross, axis=1)


def points_in_polygon_np(px, py, geom: Geometry) -> np.ndarray:
    """NumPy f64 oracle with the identical edge rule."""
    x1, y1, x2, y2 = polygon_edges(geom)
    px = np.asarray(px, np.float64)[:, None]
    py = np.asarray(py, np.float64)[:, None]
    cond = (y1[None, :] <= py) != (y2[None, :] <= py)
    t = (py - y1[None, :]) / np.where(y2 == y1, 1.0, y2 - y1)[None, :]
    xc = x1[None, :] + t * (x2[None, :] - x1[None, :])
    crossings = np.sum(cond & (xc > px), axis=1)
    return (crossings % 2) == 1
