"""Device-resident feature batches.

The host FeatureBatch (NumPy + vocab) maps onto a flat dict of device arrays
— a pytree that jitted kernels take as an argument. Naming convention:

  <attr>            numeric / dict-code (int32) / temporal (int64 millis)
  <attr>__x/__y     point coordinates (coord_dtype, default float32)
  <attr>__bbox      [N,4] per-feature envelopes (extended geometries)
  <attr>__verts     [V,2] CSR vertex buffer (extended geometries)
  <attr>__rings     [R+1] ring offsets        <attr>__featr  [N+1] feature->rings
  __valid__         bool validity mask (padding-aware)

Dtype policy (SURVEY.md §7 design stance): f64 on host; f32 coordinates on
device by default (adequate for ~1 m predicate resolution; kernels that need
tighter tolerance, e.g. kNN refinement, upcast selectively). Epoch-millis
stay int64 — int64 compare/add on TPU lowers to cheap s32 pairs, unlike f64
matmuls. geomesa_tpu enables jax x64 so int64 survives; all kernel dtypes
are explicit, so nothing else silently widens.
"""

from __future__ import annotations

import os
from typing import Dict

import jax

if os.environ.get("GEOMESA_TPU_ENABLE_X64", "1") == "1":
    jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn

DeviceBatch = Dict[str, jax.Array]

VALID = "__valid__"


def to_device(
    batch: FeatureBatch,
    coord_dtype=jnp.float32,
    device=None,
) -> DeviceBatch:
    """Transfer a FeatureBatch to device arrays (see module docstring)."""
    out: Dict[str, jax.Array] = {}
    put = lambda a: jax.device_put(a, device)
    for attr in batch.sft.attributes:
        col = batch.columns[attr.name]
        if isinstance(col, GeometryColumn):
            out[f"{attr.name}__x"] = put(jnp.asarray(col.x, coord_dtype))
            out[f"{attr.name}__y"] = put(jnp.asarray(col.y, coord_dtype))
            if not col.is_point:
                out[f"{attr.name}__bbox"] = put(jnp.asarray(col.bbox, coord_dtype))
                out[f"{attr.name}__verts"] = put(jnp.asarray(col.vertices, coord_dtype))
                out[f"{attr.name}__rings"] = put(jnp.asarray(col.ring_offsets, jnp.int32))
                out[f"{attr.name}__featr"] = put(jnp.asarray(col.feature_rings, jnp.int32))
                vfeat, edges, efeat = _csr_tables(col)
                out[f"{attr.name}__vfeat"] = put(jnp.asarray(vfeat, jnp.int32))
                out[f"{attr.name}__ex1"] = put(jnp.asarray(edges[0], coord_dtype))
                out[f"{attr.name}__ey1"] = put(jnp.asarray(edges[1], coord_dtype))
                out[f"{attr.name}__ex2"] = put(jnp.asarray(edges[2], coord_dtype))
                out[f"{attr.name}__ey2"] = put(jnp.asarray(edges[3], coord_dtype))
                out[f"{attr.name}__efeat"] = put(jnp.asarray(efeat, jnp.int32))
        elif isinstance(col, DictColumn):
            out[attr.name] = put(jnp.asarray(col.codes, jnp.int32))
        elif col.dtype == object:
            continue  # Bytes columns stay host-side
        elif attr.is_temporal:
            out[attr.name] = put(jnp.asarray(col, jnp.int64))
        else:
            out[attr.name] = put(jnp.asarray(col))
    valid = (
        batch.valid
        if batch.valid is not None
        else np.ones(len(batch), dtype=bool)
    )
    out[VALID] = put(jnp.asarray(valid))
    return out


def _csr_tables(col: GeometryColumn):
    """Host-side: per-vertex feature ids and the ring edge table.

    Rings are closed into edges for polygon kinds; line kinds keep open
    paths. Edge table is (x1, y1, x2, y2) with a parallel feature-id array —
    the layout the extended-geometry predicate kernels segment-reduce over.
    """
    n = len(col)
    is_poly = "Polygon" in col.kind or col.kind in ("Geometry", "GeometryCollection")
    vfeat = np.zeros(len(col.vertices), dtype=np.int32)
    x1s, y1s, x2s, y2s, efeat = [], [], [], [], []
    for i in range(n):
        r0, r1 = int(col.feature_rings[i]), int(col.feature_rings[i + 1])
        for r in range(r0, r1):
            v0, v1 = int(col.ring_offsets[r]), int(col.ring_offsets[r + 1])
            vfeat[v0:v1] = i
            ring = col.vertices[v0:v1]
            if len(ring) < 2:
                continue
            closed = is_poly and not np.array_equal(ring[0], ring[-1])
            pts = np.concatenate([ring, ring[:1]], axis=0) if closed else ring
            x1s.append(pts[:-1, 0])
            y1s.append(pts[:-1, 1])
            x2s.append(pts[1:, 0])
            y2s.append(pts[1:, 1])
            efeat.append(np.full(len(pts) - 1, i, dtype=np.int32))
    if x1s:
        edges = tuple(
            np.concatenate(a) for a in (x1s, y1s, x2s, y2s)
        )
        ef = np.concatenate(efeat)
    else:
        z = np.zeros(0, np.float64)
        edges = (z, z, z, z)
        ef = np.zeros(0, np.int32)
    return vfeat, edges, ef
