"""Device-resident feature batches.

The host FeatureBatch (NumPy + vocab) maps onto a flat dict of device arrays
— a pytree that jitted kernels take as an argument. Naming convention:

  <attr>            numeric / dict-code (int32) / temporal (int64 millis)
  <attr>__x/__y     point coordinates (coord_dtype, default float32)
  <attr>__bbox      [N,4] per-feature envelopes (extended geometries)
  <attr>__verts     [V,2] CSR vertex buffer (extended geometries)
  <attr>__rings     [R+1] ring offsets        <attr>__featr  [N+1] feature->rings
  __valid__         bool validity mask (padding-aware)

Dtype policy (SURVEY.md §7 design stance): f64 on host; f32 coordinates on
device by default (adequate for ~1 m predicate resolution; kernels that need
tighter tolerance, e.g. kNN refinement, upcast selectively). Epoch-millis
stay int64 — int64 compare/add on TPU lowers to cheap s32 pairs, unlike f64
matmuls. geomesa_tpu enables jax x64 so int64 survives; all kernel dtypes
are explicit, so nothing else silently widens.
"""

from __future__ import annotations

import os
import threading
from typing import Dict

import jax

# gt: waive GT25
# (the env-conditioned x64 switch IS per-process divergence bait on a
# pod — a host with a different GEOMESA_TPU_ENABLE_X64 compiles
# different programs and deadlocks the first collective. The static
# finding is real; the mitigation is runtime, where statics can't see
# it: parallel.distributed.assert_uniform_runtime() folds this knob
# into a cross-process fingerprint check right after
# jax.distributed.initialize, so divergence dies loudly at startup
# instead of hanging a pod)
if os.environ.get("GEOMESA_TPU_ENABLE_X64", "1") == "1":
    jax.config.update("jax_enable_x64", True)

import jax.numpy as jnp
import numpy as np

from geomesa_tpu.core.columnar import DictColumn, FeatureBatch, GeometryColumn
from geomesa_tpu.faults import BREAKERS, RetryPolicy, retry_call
from geomesa_tpu.faults import harness as _faults
from geomesa_tpu.telemetry.trace import TRACER

DeviceBatch = Dict[str, jax.Array]

VALID = "__valid__"

# host->device transfers are the remote-tunnel boundary: a dropped
# tunnel surfaces as an I/O-ish error worth a couple of fast retries;
# RESOURCE_EXHAUSTED (OOM) is NOT retried here — the same transfer would
# fail identically, so it propagates for the serve layer's bucket-halving
# + host-eval fallback (faults/fallback.py). The backoff is deliberately
# TINY (worst case ~37ms of sleep total): some callers — the
# DeviceCacheManager residency swaps — invoke to_device under their
# instance lock (the GT09-waived double-buffer uploads), and while the
# multi-second upload itself is the accepted cost there, the retry
# fabric must not add meaningful lock-held sleep on top of it.
_TRANSFER_SITE = _faults.site(
    "device.transfer", "host->device batch transfer (engine.device)")
_DEVICE_RETRY = RetryPolicy(max_attempts=3, base_ms=2.0, cap_ms=25.0)


def to_device(
    batch: FeatureBatch,
    coord_dtype=jnp.float32,
    device=None,
) -> DeviceBatch:
    """Transfer a FeatureBatch to device arrays (see module docstring).
    Runs under the recovery fabric: transient transfer failures retry
    with backoff against the "device" circuit breaker; OOM propagates
    typed (see _TRANSFER_SITE note above)."""
    with TRACER.span("device.transfer", rows=len(batch)):
        return retry_call(
            _to_device_impl, batch, coord_dtype, device,
            policy=_DEVICE_RETRY, label="device",
            breaker=BREAKERS.get("device"))


def _to_device_impl(
    batch: FeatureBatch,
    coord_dtype=jnp.float32,
    device=None,
) -> DeviceBatch:
    _TRANSFER_SITE.fire()
    out: Dict[str, jax.Array] = {}
    put = lambda a: jax.device_put(a, device)
    for attr in batch.sft.attributes:
        col = batch.columns[attr.name]
        if isinstance(col, GeometryColumn):
            out[f"{attr.name}__x"] = put(jnp.asarray(col.x, coord_dtype))
            out[f"{attr.name}__y"] = put(jnp.asarray(col.y, coord_dtype))
            if not col.is_point:
                out[f"{attr.name}__bbox"] = put(jnp.asarray(col.bbox, coord_dtype))
                out[f"{attr.name}__verts"] = put(jnp.asarray(col.vertices, coord_dtype))
                out[f"{attr.name}__rings"] = put(jnp.asarray(col.ring_offsets, jnp.int32))
                out[f"{attr.name}__featr"] = put(jnp.asarray(col.feature_rings, jnp.int32))
                et = col.edge_table()
                out[f"{attr.name}__vfeat"] = put(jnp.asarray(et.vfeat, jnp.int32))
                out[f"{attr.name}__ex1"] = put(jnp.asarray(et.x1, coord_dtype))
                out[f"{attr.name}__ey1"] = put(jnp.asarray(et.y1, coord_dtype))
                out[f"{attr.name}__ex2"] = put(jnp.asarray(et.x2, coord_dtype))
                out[f"{attr.name}__ey2"] = put(jnp.asarray(et.y2, coord_dtype))
                out[f"{attr.name}__efeat"] = put(jnp.asarray(et.efeat, jnp.int32))
        elif isinstance(col, DictColumn):
            out[attr.name] = put(jnp.asarray(col.codes, jnp.int32))
        elif col.dtype == object:
            continue  # Bytes columns stay host-side
        elif attr.is_temporal:
            out[attr.name] = put(jnp.asarray(col, jnp.int64))
        else:
            out[attr.name] = put(jnp.asarray(col))
    valid = (
        batch.valid
        if batch.valid is not None
        else np.ones(len(batch), dtype=bool)
    )
    out[VALID] = put(jnp.asarray(valid))
    return out


# edge tables are built by GeometryColumn.edge_table() (vectorized,
# memoized, ring-orientation-normalized for polygon kinds) — see
# core.columnar.EdgeTable.


# -- double-buffered query staging (serve pipeline) -------------------------


class QueryStager:
    """Double-buffered host→device staging slots for the serve
    pipeline's query streams (docs/SERVING.md "Pipelined dispatch").

    Each pipelined window stages its (padded, f32) stacked query points
    through `stage()` before the kernel launch, so the transfer overlaps
    the PREVIOUS window's kernel instead of serializing in front of this
    window's. Per (kernel, bucket) key the stager keeps `depth` slots
    rotated per window; the slot reference is what bounds live staging
    HBM to `depth` buffers per key and — under the registry's serve
    donation tier — guarantees the pair handed to window N is never the
    pair window N+1 is transferring into (a donated buffer is consumed
    by its window's program; the rotation means the stager re-offers
    that slot only after the depth-bounded pipeline has synced the
    window that consumed it).

    The persistent serve loop (serve/ringloop.py) reuses this exact
    discipline generalized to depth R: its ring of donated slot buffers
    IS a QueryStager at `depth=R`, so the slot handed to window N is
    never the slot window N+1 is transferring into as long as R bounds
    the windows in flight (docs/SERVING.md "Persistent serve loop").

    The dtype discipline matches the serial path exactly
    (`jnp.asarray(np.asarray(qx), jnp.float32)`): host f64 → f32 cast on
    host, then device_put — so pipelined results are bit-identical.
    Transfers run under the same recovery fabric as `to_device`
    (device.transfer fault site, tiny-backoff retries, device breaker).
    Thread-safe, though the serve pipeline calls it from the single
    dispatch thread."""

    # bound on distinct (kernel, bucket) keys: beyond it the
    # least-recently-staged key is evicted so a long-lived multi-tenant
    # service never pins more than MAX_KEYS * depth stale device pairs
    # (an evicted key's buffers free once its in-flight windows sync —
    # the kernels hold their own references)
    MAX_KEYS = 64

    def __init__(self, depth: int = 2):
        if depth < 2:
            raise ValueError("stager depth must be >= 2 (double buffer)")
        self.depth = depth
        self._lock = threading.Lock()
        # key -> [seq, slot0, slot1, ...]; slot = (qx_dev, qy_dev).
        # Insertion-ordered; stage() re-inserts on touch, so iteration
        # order is least-recently-staged first (the eviction order)
        self._slots: Dict[object, list] = {}
        self._staged_total = 0

    def stage(self, key, qx, qy, device=None):
        """Transfer one window's stacked query points; returns the
        device (qx, qy) pair. `qx`/`qy` are host arrays (the caller
        keeps them — the OOM ladder re-stages from host)."""
        qx32 = np.asarray(qx, np.float32)
        qy32 = np.asarray(qy, np.float32)

        def _put():
            _TRANSFER_SITE.fire()
            return (jax.device_put(jnp.asarray(qx32), device),
                    jax.device_put(jnp.asarray(qy32), device))

        from geomesa_tpu.utils.metrics import note_device_op

        note_device_op()
        with TRACER.span("device.transfer", rows=int(qx32.shape[0]),
                         staged=True):
            pair = retry_call(
                _put, policy=_DEVICE_RETRY, label="device",
                breaker=BREAKERS.get("device"))
        with self._lock:
            slot = self._slots.pop(key, None)
            if slot is None:
                slot = [0] + [None] * self.depth
                while len(self._slots) >= self.MAX_KEYS:
                    # least-recently-staged key goes first
                    self._slots.pop(next(iter(self._slots)))
            self._slots[key] = slot  # re-insert = LRU touch
            seq = slot[0]
            slot[1 + seq % self.depth] = pair
            slot[0] = seq + 1
            self._staged_total += 1
        return pair

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"keys": len(self._slots),
                    "staged": self._staged_total}


# -- batch-identity device cache --------------------------------------------
# Repeat analytics over one materialized batch (the KNN process's steady
# state, the SQL engine's table scans) must not re-upload coordinates per
# call — the remote-tunnel host->device path is the dominant cost at scale.
# Keyed by object identity + dtype; evicted when the batch is collected.
# (FeatureBatch is an eq=True dataclass, hence unhashable — id() keying
# with a weakref.finalize eviction hook instead of a WeakKeyDictionary.)
_BATCH_CACHE: Dict[int, Dict[str, DeviceBatch]] = {}


def to_device_cached(
    batch: FeatureBatch, coord_dtype=jnp.float32, device=None
) -> DeviceBatch:
    """`to_device` memoized on the batch OBJECT (not value): safe because
    FeatureBatch columns are treated as immutable throughout the engine
    (every mutation path builds a new batch via select/concat/pad_to)."""
    import weakref

    key = id(batch)
    slot = _BATCH_CACHE.get(key)
    if slot is None:
        slot = _BATCH_CACHE[key] = {}
        weakref.finalize(batch, _BATCH_CACHE.pop, key, None)
    dkey = f"{jnp.dtype(coord_dtype)}|{device}"
    if dkey not in slot:
        slot[dkey] = to_device(batch, coord_dtype=coord_dtype, device=device)
    return slot[dkey]
