"""BIN record encoding: minimal binary results for massive dot-map rendering.

Parity: geomesa-index-api BinAggregatingScan / Accumulo BinAggregatingIterator
[upstream, unverified]: 16-byte records (trackId-hash:int32, dtg-seconds:int32,
lat:float32, lon:float32), +8 bytes (label:int64) for the labeled variant.
Wire layout is little-endian here (documented divergence: the JVM reference
writes big-endian); `decode_bin` is the matching reader.

Device side packs the four lanes as an [N, 4] int32 matrix (floats bitcast),
which transfers once and serializes host-side with .tobytes().
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bin_pack(
    track_code: jax.Array,  # int32 (dictionary code or hash)
    dtg_ms: jax.Array,  # int64 epoch millis
    lat: jax.Array,
    lon: jax.Array,
    label: Optional[jax.Array] = None,  # int lane -> 24-byte labeled records
) -> jax.Array:
    """[N,4] int32 (16B records) or [N,6] with a label (24B: label as two
    little-endian int32 lanes, low word first)."""
    lanes = [
        track_code.astype(jnp.int32),
        (dtg_ms // 1000).astype(jnp.int32),
        jax.lax.bitcast_convert_type(lat.astype(jnp.float32), jnp.int32),
        jax.lax.bitcast_convert_type(lon.astype(jnp.float32), jnp.int32),
    ]
    if label is not None:
        l64 = label.astype(jnp.int64)
        lanes.append((l64 & 0xFFFFFFFF).astype(jnp.int32))
        lanes.append((l64 >> 32).astype(jnp.int32))
    return jnp.stack(lanes, axis=1)


def encode_bin(packed: jax.Array, select: Optional[np.ndarray] = None) -> bytes:
    """Host-side: [N,4|6] int32 -> 16/24-byte-per-record LE buffer."""
    arr = np.asarray(packed, dtype="<i4")
    if select is not None:
        arr = arr[select]
    return arr.tobytes()


def decode_bin(buf: bytes, labeled: bool = False) -> np.ndarray:
    """bytes -> structured array (track, dtg_s, lat, lon[, label])."""
    lanes = 6 if labeled else 4
    raw = np.frombuffer(buf, dtype="<i4").reshape(-1, lanes)
    fields = [("track", "<i4"), ("dtg_s", "<i4"), ("lat", "<f4"), ("lon", "<f4")]
    if labeled:
        fields.append(("label", "<i8"))
    out = np.empty(len(raw), dtype=fields)
    out["track"] = raw[:, 0]
    out["dtg_s"] = raw[:, 1]
    out["lat"] = raw[:, 2].view("<f4")
    out["lon"] = raw[:, 3].view("<f4")
    if labeled:
        out["label"] = (
            raw[:, 4].astype(np.int64) & 0xFFFFFFFF
        ) | (raw[:, 5].astype(np.int64) << 32)
    return out
