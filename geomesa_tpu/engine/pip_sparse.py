"""Sparse pair-list point-in-polygon-LAYER: the config-2 spatial join.

Parity role: `Within()` over an OSM-admin-style polygon LAYER x point
events (BASELINE.json config 2; upstream: geomesa's Z2/XZ2 index scan +
JTS prepared-geometry per candidate — SURVEY.md §3.2). The reference
prunes candidates per polygon through the key-value index; the TPU-native
equivalent prunes (point-tile x edge-tile) PAIRS on the host from the
store's Z-order and lets a scalar-prefetched Pallas kernel stream only
the surviving pairs.

Geometry of the pruning (why skipping whole polygons is exact): the
crossing-number ray runs to +x. A CLOSED ring never containing the point
crosses the ray an even number of times, so parity is unchanged if every
edge of that ring is dropped TOGETHER. Hence:
  - polygons whose bbox misses the point tile's bbox are dropped whole;
  - for polygons kept, an edge TILE is dropped only when it provably adds
    zero crossings for every point in the tile (no y-overlap, or entirely
    left of the tile) — this never splits a ring's parity.
To keep "whole polygon" well-defined at tile granularity, the edge table
pads each polygon to a multiple of EDGE_TILE with degenerate edges
(y1 == y2 == BIG: never cross, never flag).

Union semantics: the layer's total crossing parity equals point-in-union
for DISJOINT polygons (admin boundaries; containment count <= 1). Holes
are interior rings in the same table (parity cancels). Overlapping
polygons would need per-polygon parity — documented non-goal here.

f32 boundary: a companion band kernel (same pair list) flags points whose
result is ambiguous at f32 resolution; callers re-evaluate flagged points
exactly in f64 on the host (cql.hosteval pattern). The refinement uses
the SAME pair list, so its candidate set is identical.
"""

from __future__ import annotations

import functools
import threading
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import enable_x64 as _enable_x64
import numpy as np

POINT_TILE = 512
# 512-edge tiles: per-program cost is DMA-latency-bound (~25 us whether
# the fetch is 128 or 512 edges — measured: pair and grouped kernels both
# ~11-14 s over 409k programs at 128), so bigger tiles cut program count
# 4x for ~18% polygon-padding overhead
EDGE_TILE = 512
BIG = 1e9  # degenerate-edge y (never crosses, never near a real point)


class PairList(NamedTuple):
    """Host-built sparse join structure (all numpy)."""

    pair_pt: np.ndarray     # [M] point-tile id per pair (sorted)
    pair_et: np.ndarray     # [M] edge-tile id per pair
    first: np.ndarray       # [M] 1 where a new point tile starts
    covered: np.ndarray     # [n_ptiles] bool: tile appears in >=1 pair
    n_ptiles: int
    n_etiles: int


def _group_ids(ids: np.ndarray):
    """(unique_ids, counts, order): group ANY int id array (sparse,
    large, unsorted — the public contract; a bincount here would
    allocate O(max id) and reject negatives, round-4 review) with an
    O(n) run-length fast path for already-sorted input (every generator
    and the columnar edge table emit sorted ids). `order` sorts ids
    grouped (slice(None) when already sorted)."""
    ids = np.asarray(ids, np.int64)
    if bool((np.diff(ids) >= 0).all()):
        order = slice(None)
        s = ids
    else:
        order = np.argsort(ids, kind="stable")
        s = ids[order]
    if not len(s):
        return s, np.zeros(0, np.int64), order
    starts = np.concatenate([[0], np.nonzero(np.diff(s))[0] + 1])
    counts = np.diff(np.concatenate([starts, [len(s)]]))
    return s[starts], counts, order


def pad_polygon_edges(
    x1, y1, x2, y2, poly_of_edge
) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Pad the concatenated oriented edge table so each polygon occupies
    whole EDGE_TILE tiles (degenerate BIG edges fill the tail). Returns
    (x1, y1, x2, y2, poly_of_tile [n_etiles] — ORIGINAL polygon ids).

    Fully vectorized: the round-3 bench measured the per-polygon python
    loop at ~100 s over 10k polygons x 1.5M edges (each iteration scanned
    the whole edge table); this is one (skippable) sort + one scatter."""
    poly_of_edge = np.asarray(poly_of_edge, np.int64)
    pids, counts, order = _group_ids(poly_of_edge)
    padded_counts = -(-counts // EDGE_TILE) * EDGE_TILE
    total = int(padded_counts.sum())
    starts = np.concatenate([[0], np.cumsum(padded_counts)[:-1]])
    # destination of each (pid-sorted) edge = its polygon's padded start
    # + rank within the polygon
    src_starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    rank = np.arange(len(poly_of_edge)) - np.repeat(src_starts, counts)
    dest = np.repeat(starts, counts) + rank
    outs = []
    for arr, fill in zip((x1, y1, x2, y2), (0.0, BIG, 0.0, BIG)):
        # x slots of degenerate edges are logically dead (the y-based
        # crossing test gates them out) but MUST hold finite values:
        # uninitialized garbage flowed into the f64 refine arithmetic and
        # the f32 upload, raising overflow warnings (round-4 review)
        buf = np.full(total, fill, np.float64)
        buf[dest] = np.asarray(arr, np.float64)[order]
        outs.append(buf)
    tiles_per = padded_counts // EDGE_TILE
    poly_of_tile = np.repeat(pids, tiles_per)
    return (*outs, poly_of_tile)


def _cumsum0(counts):
    return np.concatenate([[0], np.cumsum(counts)[:-1]])


def _expand_ranges(starts, counts):
    """[sum(counts)] indices: for each i, starts[i] .. starts[i]+counts[i]."""
    total = int(counts.sum())
    rank = np.arange(total) - np.repeat(_cumsum0(counts), counts)
    return np.repeat(starts, counts) + rank


def build_pairs(
    ptile_bbox: np.ndarray,   # [T, 4] xmin,ymin,xmax,ymax per point tile
    etile_bbox: np.ndarray,   # [E, 4] per edge tile (degenerates excluded)
    poly_of_tile: np.ndarray,  # [E] owning polygon per edge tile
    poly_bbox: np.ndarray,    # [P, 4]
    margin: float = 1e-3,
) -> PairList:
    """Bbox-prune (point tile x edge tile) pairs, polygon-atomically.

    Pair (T, et) survives iff bbox(poly(et)) intersects bbox(T) (expanded
    by `margin` for the f32 band) AND et y-overlaps T AND et is not
    entirely LEFT of T (the +x crossing ray can never reach a tile whose
    ex1 < px0; right-side tiles must be kept — the ray points at them.
    Round 3 had this mirrored; rings spanning >1 edge tile lost
    crossings). Sorted by point tile for revisited-output accumulation.

    Fully vectorized (round 4): the per-polygon python loop measured
    3.9 s at 10k polygons — most of the config-2 end-to-end time. Now:
    tiles and polygons expand into bucket-grid (cell, id) pairs, a CSR
    over cells joins them into (polygon, tile) candidates, and the
    per-pair prunes are flat boolean masks."""
    T = ptile_bbox.shape[0]
    E = etile_bbox.shape[0]
    P = poly_bbox.shape[0]
    px0, py0, px1, py1 = (ptile_bbox[:, i] for i in range(4))

    empty = PairList(np.zeros(0, np.int32), np.zeros(0, np.int32),
                     np.ones(0, np.int32), np.zeros(T, bool), T, E)
    if T == 0 or E == 0 or P == 0:
        return empty

    # ---- bucket grid CSR: cell -> point tiles (tiles register in every
    # cell their bbox touches; Z-ordered tiles overwhelmingly span one)
    G = 128
    gx0 = np.clip(((px0 + 180) / 360 * G).astype(np.int64), 0, G - 1)
    gx1 = np.clip(((px1 + 180) / 360 * G).astype(np.int64), 0, G - 1)
    gy0 = np.clip(((py0 + 90) / 180 * G).astype(np.int64), 0, G - 1)
    gy1 = np.clip(((py1 + 90) / 180 * G).astype(np.int64), 0, G - 1)
    w = gx1 - gx0 + 1
    h = gy1 - gy0 + 1
    reps = w * h
    tid = np.repeat(np.arange(T), reps)
    rank = np.arange(int(reps.sum())) - np.repeat(_cumsum0(reps), reps)
    wrep = np.repeat(w, reps)
    cell = ((np.repeat(gx0, reps) + rank % wrep) * G
            + np.repeat(gy0, reps) + rank // wrep)
    order = np.argsort(cell, kind="stable")
    cell_s, tile_s = cell[order], tid[order]
    cell_lo = np.searchsorted(cell_s, np.arange(G * G))
    cell_hi = np.searchsorted(cell_s, np.arange(G * G) + 1)

    # ---- polygons -> covered cells (both ends clamped INTO the grid so
    # out-of-domain bboxes still query the edge cells — round-3 review)
    bx0, by0, bx1, by1 = (poly_bbox[:, i] for i in range(4))
    cx_lo = np.minimum(
        np.maximum(((bx0 - margin + 180) / 360 * G).astype(np.int64), 0),
        G - 1)
    cx_hi = np.maximum(
        np.minimum(((bx1 + margin + 180) / 360 * G).astype(np.int64), G - 1),
        0)
    cy_lo = np.minimum(
        np.maximum(((by0 - margin + 90) / 180 * G).astype(np.int64), 0),
        G - 1)
    cy_hi = np.maximum(
        np.minimum(((by1 + margin + 90) / 180 * G).astype(np.int64), G - 1),
        0)
    pw = cx_hi - cx_lo + 1
    ph = cy_hi - cy_lo + 1
    preps = pw * ph
    pid_c = np.repeat(np.arange(P), preps)
    prank = np.arange(int(preps.sum())) - np.repeat(_cumsum0(preps), preps)
    pwrep = np.repeat(pw, preps)
    pcell = ((np.repeat(cx_lo, preps) + prank % pwrep) * G
             + np.repeat(cy_lo, preps) + prank // pwrep)

    # ---- CSR join: (polygon, cell) -> candidate (polygon, tile)
    cnt = cell_hi[pcell] - cell_lo[pcell]
    if cnt.sum() == 0:
        return empty
    cand_poly = np.repeat(pid_c, cnt)
    cand_tile = tile_s[_expand_ranges(cell_lo[pcell], cnt)]
    # dedupe (a tile can reach one polygon through several cells)
    key = np.unique(cand_poly.astype(np.int64) * T + cand_tile)
    cand_poly = (key // T).astype(np.int64)
    cand_tile = (key % T).astype(np.int64)

    # ---- polygon-bbox x tile-bbox filter
    hit = (
        (px1[cand_tile] >= bx0[cand_poly] - margin)
        & (px0[cand_tile] <= bx1[cand_poly] + margin)
        & (py1[cand_tile] >= by0[cand_poly] - margin)
        & (py0[cand_tile] <= by1[cand_poly] + margin)
    )
    cand_poly, cand_tile = cand_poly[hit], cand_tile[hit]
    if not len(cand_poly):
        return empty

    # ---- expand each surviving (polygon, tile) over the polygon's edge
    # tiles (contiguous in poly_of_tile by construction: pad_polygon_edges
    # emits pid-sorted tiles)
    et_lo = np.searchsorted(poly_of_tile, cand_poly, side="left")
    et_hi = np.searchsorted(poly_of_tile, cand_poly, side="right")
    ecnt = et_hi - et_lo
    pair_pt = np.repeat(cand_tile, ecnt)
    pair_et = _expand_ranges(et_lo, ecnt)

    # ---- per-pair y-overlap + not-entirely-left prune (degenerate-only
    # tiles carry +-inf bboxes and fail the y test)
    ex1b = etile_bbox[pair_et, 2]
    ey0b = etile_bbox[pair_et, 1]
    ey1b = etile_bbox[pair_et, 3]
    keep = (
        (py1[pair_pt] >= ey0b - margin) & (py0[pair_pt] <= ey1b + margin)
        & (px0[pair_pt] <= ex1b + margin)
    )
    pt = pair_pt[keep]
    et = pair_et[keep]

    order = np.argsort(pt, kind="stable")
    pt, et = pt[order], et[order]
    first = np.ones(len(pt), np.int32)
    first[1:] = (pt[1:] != pt[:-1]).astype(np.int32)
    covered = np.zeros(T, bool)
    covered[pt] = True
    return PairList(pt.astype(np.int32), et.astype(np.int32), first,
                    covered, T, E)


def _crossing_and_band(px, py, x1, y1, x2, y2, eps: float):
    """Shared predicate math for the PIP kernel bodies: returns
    (crossing bool [E, P], band-flag bool [E, P]).

    Why the flag needs NO general endpoint-y strip (round 5; the old
    `|py - y_end| <= eps` term flagged 23% of config-2 points — a
    horizontal strip across the whole tile per endpoint — and made the
    host f64 refine the first-query bottleneck): f32 evaluation computes
    the EXACT even-odd parity of a perturbed polygon. Each vertex
    comparison `(V.y <= py)` is computed bit-identically by both edges
    incident to V (rings are closed; both store the same f32 V), so a
    rounding flip moves V to the other side of the ray CONSISTENTLY —
    pass-through vertices still count once, extrema 0 or 2. Parity of
    the perturbed polygon differs from the true one only for points
    within the perturbation distance of the BOUNDARY, which two cheap
    local tests cover exactly:
      1. `cond & |xc - px| <= err` — horizontal proximity to the edge's
         ray crossing, with `err` inflated by the slope so y-rounding of
         a shallow edge (dxc = slope * dy) stays inside the band;
      2. `near_flat` — an edge whose BOTH endpoint ys are within eps of
         py can have its two comparisons flip independently (the
         vertex-consistency argument couples comparisons across edges,
         not within one); that edge is then near-horizontal at py, so
         the affected points lie inside its eps-inflated bbox — flag
         exactly those, not the whole strip.
    Points outside both bands provably match the f64 oracle; flagged
    points are re-evaluated in f64 by _refine_band_f64."""
    cond = (y1 <= py) != (y2 <= py)
    # dtype-pinned literal: a bare 1.0 traces as weak f64 when the
    # interpret-mode kernel trace is deferred past the enable_x64(False)
    # window, and the while-loop lowering rejects the f64/f32 mix
    t = (py - y1) / jnp.where(y2 == y1, jnp.ones((), y1.dtype), y2 - y1)
    xc = x1 + t * (x2 - x1)
    err = eps * (1.0 + jnp.abs(x2 - x1)
                 / jnp.maximum(jnp.abs(y2 - y1), eps))
    near_flat = (
        (jnp.abs(py - y1) <= eps) & (jnp.abs(py - y2) <= eps)
        & (px >= jnp.minimum(x1, x2) - eps)
        & (px <= jnp.maximum(x1, x2) + eps)
    )
    return cond & (xc > px), near_flat | (cond & (jnp.abs(xc - px) <= err))


def _sparse_kernel(pt_ref, et_ref, px_ref, py_ref,
                   x1_ref, y1_ref, x2_ref, y2_ref, out_ref):
    import jax.experimental.pallas as pl

    m = pl.program_id(0)
    # first-visit detection from the pt scalars themselves (a dedicated
    # flags array would blow the 1 MB SMEM prefetch budget at ~100k pairs)
    prev = pt_ref[jnp.maximum(m - 1, 0)]

    @pl.when((m == 0) | (pt_ref[m] != prev))
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    px = px_ref[0]
    py = py_ref[0]
    # edges arrive lane-major ([1, EDGE_TILE]: a [E, 128, 1] layout pads
    # the 1-wide lane dim 128x -> 7 GB/array at 15M edge slots) and are
    # transposed onto sublanes in VMEM for the [E, P] broadcast
    x1 = x1_ref[0].reshape(EDGE_TILE, 1)
    y1 = y1_ref[0].reshape(EDGE_TILE, 1)
    x2 = x2_ref[0].reshape(EDGE_TILE, 1)
    y2 = y2_ref[0].reshape(EDGE_TILE, 1)
    crossing, _ = _crossing_and_band(px, py, x1, y1, x2, y2, 1e-4)
    partial = jnp.sum(crossing.astype(jnp.int32), axis=0)
    out_ref[...] += partial.reshape(out_ref.shape)


def _sparse_band_kernel(pt_ref, et_ref, px_ref, py_ref,
                        x1_ref, y1_ref, x2_ref, y2_ref, out_ref, *,
                        eps: float):
    import jax.experimental.pallas as pl

    m = pl.program_id(0)
    prev = pt_ref[jnp.maximum(m - 1, 0)]

    @pl.when((m == 0) | (pt_ref[m] != prev))
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    px = px_ref[0]
    py = py_ref[0]
    x1 = x1_ref[0].reshape(EDGE_TILE, 1)
    y1 = y1_ref[0].reshape(EDGE_TILE, 1)
    x2 = x2_ref[0].reshape(EDGE_TILE, 1)
    y2 = y2_ref[0].reshape(EDGE_TILE, 1)
    _, flag = _crossing_and_band(px, py, x1, y1, x2, y2, eps)
    out_ref[...] += jnp.sum(flag.astype(jnp.int32), axis=0).reshape(
        out_ref.shape)


def _make_multi_kernel(e_per: int, eps: float):
    """Grid (tiles, cap/e_per): program (i, j) folds E_PER edge tiles
    into point tile i's accumulators in ONE program. Each edge tile is a
    SEPARATE scalar-indexed operand, so Mosaic issues their DMAs
    concurrently. Measured on the config-2 layer (v5e, round 4):
    e_per=2 is the sweet spot (0.55 s vs 1.49 s at e_per=1); 4/8 regress
    (~1.1-1.2 s — wider programs starve the double-buffering). The
    decisive round-4 fix was pow2 capacity BUCKETS in the caller, not
    e_per: two coarse classes let one dense tile inflate cap for
    thousands of rows and the pallas call count dominated (6 s)."""

    def _kernel(etab_ref, px_ref, py_ref, *refs):
        import jax.experimental.pallas as pl

        out_ref, band_ref = refs[-2], refs[-1]
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            out_ref[...] = jnp.zeros_like(out_ref)
            band_ref[...] = jnp.zeros_like(band_ref)

        px = px_ref[0]
        py = py_ref[0]
        for e in range(e_per):
            x1 = refs[4 * e][0].reshape(EDGE_TILE, 1)
            y1 = refs[4 * e + 1][0].reshape(EDGE_TILE, 1)
            x2 = refs[4 * e + 2][0].reshape(EDGE_TILE, 1)
            y2 = refs[4 * e + 3][0].reshape(EDGE_TILE, 1)
            crossing, flag = _crossing_and_band(px, py, x1, y1, x2, y2, eps)
            out_ref[...] += jnp.sum(
                crossing.astype(jnp.int32), axis=0).reshape(out_ref.shape)
            band_ref[...] += jnp.sum(
                flag.astype(jnp.int32), axis=0).reshape(band_ref.shape)

    return _kernel


@functools.partial(
    jax.jit,
    static_argnames=("cap", "n_etiles", "eps", "interpret", "e_per"),
)
def _pip_grouped_call(
    px_cov, py_cov, x1, y1, x2, y2, etab,
    cap: int, n_etiles: int, eps: float, interpret: bool, e_per: int = 2,
):
    """One capacity class: [Tc] gathered point tiles x up to `cap` edge
    tiles each (etab [Tc, cap] i32; entries == n_etiles hit the appended
    all-degenerate dummy tile — the caller appends it ONCE per query).
    cap must be a multiple of e_per (callers pad etab with the dummy).
    Returns (counts [Tc, POINT_TILE], band [Tc, POINT_TILE])."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e_per = min(e_per, cap)
    assert cap % e_per == 0, (cap, e_per)
    dt = jnp.float32
    tc = px_cov.shape[0]
    pxp = px_cov.astype(dt).reshape(tc, 1, POINT_TILE)
    pyp = py_cov.astype(dt).reshape(tc, 1, POINT_TILE)
    e1 = x1.astype(dt).reshape(-1, 1, EDGE_TILE)
    f1 = y1.astype(dt).reshape(-1, 1, EDGE_TILE)
    e2 = x2.astype(dt).reshape(-1, 1, EDGE_TILE)
    f2 = y2.astype(dt).reshape(-1, 1, EDGE_TILE)

    point_block = pl.BlockSpec((1, 1, POINT_TILE), lambda i, j, et: (i, 0, 0))

    def edge_block(e):
        return pl.BlockSpec(
            (1, 1, EDGE_TILE),
            lambda i, j, et, e=e: (et[i, j * e_per + e], 0, 0),
        )

    out_block = pl.BlockSpec((1, 1, POINT_TILE), lambda i, j, et: (i, 0, 0))
    out_shape = jax.ShapeDtypeStruct((tc, 1, POINT_TILE), jnp.int32)

    edge_specs = []
    edge_args = []
    for e in range(e_per):
        edge_specs.extend([edge_block(e)] * 4)
        edge_args.extend([e1, f1, e2, f2])

    with _enable_x64(False):
        counts, band = pl.pallas_call(
            _make_multi_kernel(e_per, eps),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(tc, cap // e_per),
                in_specs=[point_block, point_block] + edge_specs,
                out_specs=(out_block, out_block),
            ),
            out_shape=(out_shape, out_shape),
            interpret=interpret,
        )(etab, pxp, pyp, *edge_args)
    return counts.reshape(tc, POINT_TILE), band.reshape(tc, POINT_TILE)


# SMEM budget: etab is the only prefetched scalar array (4 B/slot); the
# runtime DOUBLE-BUFFERS prefetched operands and row-pads narrow rows,
# so the effective budget is ~2^15 padded slots (256 KB resident)
MAX_ETAB_SLOTS = 1 << 15


def _pow2_caps(counts: np.ndarray) -> np.ndarray:
    """pow2 capacity bucket per tile row (floor 4). Shared by the union
    and assignment drivers: a coarse two-class scheme let one dense tile
    inflate cap for thousands of rows, and the collapsed rows-per-call
    made pallas dispatch count dominate (measured 6 s on the config-2
    layer; bucketing brings total calls to ~total_slots/MAX_ETAB_SLOTS)."""
    return np.maximum(
        2 ** np.ceil(np.log2(np.maximum(counts, 1))).astype(np.int64), 4)


def pip_layer_grouped(
    px, py, x1, y1, x2, y2, pair_pt, pair_et,
    n_ptiles: int = 0, n_etiles: int = 0, eps: float = 1e-4,
    interpret: bool = False, e_per: int = 2,
):
    """Grouped-by-point-tile execution of the pair list (the fast path;
    same result contract as pip_layer_sparse but returns DEVICE arrays).
    Tiles are bucketed into two capacity classes (tunnel dispatches cost
    ~110 ms each, so call count matters more than padding waste); per-call
    results stay on device and scatter into the full outputs — the first
    grouped implementation's per-call host fetches dominated its wall
    time through the 0.05 GB/s tunnel."""
    import jax.numpy as _jnp

    pt_np = np.asarray(pair_pt, np.int64)
    et_np = np.asarray(pair_et, np.int64)
    if not len(pt_np):
        z = _jnp.zeros(n_ptiles * POINT_TILE, _jnp.int32)
        return z, z
    tiles, counts = np.unique(pt_np, return_counts=True)
    starts = np.concatenate([[0], np.cumsum(counts)[:-1]])
    pxt = _jnp.asarray(px).reshape(n_ptiles, POINT_TILE)
    pyt = _jnp.asarray(py).reshape(n_ptiles, POINT_TILE)
    out_c = _jnp.zeros((n_ptiles, POINT_TILE), _jnp.int32)
    out_b = _jnp.zeros((n_ptiles, POINT_TILE), _jnp.int32)
    # dummy all-BIG edge tile appended ONCE per query (id n_etiles)
    dt32 = _jnp.float32
    ax1 = _jnp.concatenate([_jnp.asarray(x1, dt32),
                            _jnp.zeros(EDGE_TILE, dt32)])
    ay1 = _jnp.concatenate([_jnp.asarray(y1, dt32),
                            _jnp.full(EDGE_TILE, BIG, dt32)])
    ax2 = _jnp.concatenate([_jnp.asarray(x2, dt32),
                            _jnp.zeros(EDGE_TILE, dt32)])
    ay2 = _jnp.concatenate([_jnp.asarray(y2, dt32),
                            _jnp.full(EDGE_TILE, BIG, dt32)])

    from geomesa_tpu.utils.padding import next_pow2 as _np2

    caps_of = _pow2_caps(counts)
    for cap_c in np.unique(caps_of):
        sel = np.nonzero(caps_of == cap_c)[0]
        cap_c = int(cap_c)
        # vectorized etab fill (repeat/rank scatter, same idiom as
        # pad_polygon_edges — a per-row python loop sat in the timed path)
        etab = np.full((len(sel), cap_c), n_etiles, np.int32)
        cnt_s = counts[sel]
        row_of = np.repeat(np.arange(len(sel)), cnt_s)
        col_of = (np.arange(cnt_s.sum())
                  - np.repeat(np.concatenate([[0], np.cumsum(cnt_s)[:-1]]),
                              cnt_s))
        etab[row_of, col_of] = et_np[
            np.repeat(starts[sel], cnt_s) + col_of]
        ptids = tiles[sel]
        # a single row wider than the SMEM budget splits by COLUMN chunks
        # that accumulate (+=) into the same tiles — counts and band
        # flags are both additive across edge-tile subsets
        for k0 in range(0, cap_c, MAX_ETAB_SLOTS):
            sub = etab[:, k0: k0 + MAX_ETAB_SLOTS]
            cap_k = sub.shape[1]
            per_call = max(1, MAX_ETAB_SLOTS // max(cap_k, 32))
            for c0 in range(0, len(sel), per_call):
                c1 = min(c0 + per_call, len(sel))
                ids = ptids[c0:c1]
                tab = np.ascontiguousarray(sub[c0:c1])
                # pow2 tile-count bucket: padding rows reuse a real tile
                # id with an ALL-DUMMY etab row, contributing exact zeros
                # through the scatter-add
                tc_pad = max(_np2(len(ids)), 8) - len(ids)
                if tc_pad:
                    ids = np.concatenate(
                        [ids, np.full(tc_pad, ids[0], ids.dtype)])
                    tab = np.concatenate([
                        tab,
                        np.full((tc_pad, cap_k), n_etiles, np.int32),
                    ])
                jid = _jnp.asarray(ids)
                # per-layer tiling: point/edge tile counts are fixed
                # by the loaded polygon layer (chunks pow2-padded
                # above) — compiles track layer loads, not traffic
                # gt: waive GT28
                cc, bb = _pip_grouped_call(
                    _jnp.take(pxt, jid, axis=0),
                    _jnp.take(pyt, jid, axis=0),
                    ax1, ay1, ax2, ay2,
                    _jnp.asarray(tab),
                    cap=cap_k, n_etiles=n_etiles, eps=eps,
                    interpret=interpret, e_per=e_per,
                )
                out_c = out_c.at[jid].add(cc)
                out_b = out_b.at[jid].add(bb)
    return out_c.reshape(-1), out_b.reshape(-1)


def _make_assign_kernel(e_per: int, eps: float):
    """Per-POLYGON parity (the relation-join kernel): like the union
    kernel, but a running per-point crossing accumulator FLUSHES at each
    polygon boundary (pinfo slot < 0), adding parity * (pid+1) into the
    assignment and parity into the containment count. For a disjoint
    layer, assignment-1 is exactly the containing polygon id (or -1).
    Requires each row's pairs grouped contiguously by polygon — the
    pair list is built that way (build_pairs expands polygon-major)."""

    def _kernel(etab_ref, pinfo_ref, px_ref, py_ref, *refs):
        import jax.experimental.pallas as pl

        assign_ref, count_ref, band_ref, cur_ref = refs[-4:]
        i = pl.program_id(0)
        j = pl.program_id(1)

        @pl.when(j == 0)
        def _init():
            assign_ref[...] = jnp.zeros_like(assign_ref)
            count_ref[...] = jnp.zeros_like(count_ref)
            band_ref[...] = jnp.zeros_like(band_ref)
            cur_ref[...] = jnp.zeros_like(cur_ref)

        px = px_ref[0]
        py = py_ref[0]
        for e in range(e_per):
            x1 = refs[4 * e][0].reshape(EDGE_TILE, 1)
            y1 = refs[4 * e + 1][0].reshape(EDGE_TILE, 1)
            x2 = refs[4 * e + 2][0].reshape(EDGE_TILE, 1)
            y2 = refs[4 * e + 3][0].reshape(EDGE_TILE, 1)
            crossing, flag = _crossing_and_band(px, py, x1, y1, x2, y2, eps)
            cur_ref[...] += jnp.sum(
                crossing.astype(jnp.int32), axis=0).reshape(cur_ref.shape)
            band_ref[...] += jnp.sum(
                flag.astype(jnp.int32), axis=0).reshape(band_ref.shape)
            info = pinfo_ref[i, j * e_per + e]

            @pl.when(info < 0)
            def _flush(info=info):
                parity = cur_ref[...] & 1
                assign_ref[...] += parity * (-info)
                count_ref[...] += parity
                cur_ref[...] = jnp.zeros_like(cur_ref)

    return _kernel


@functools.partial(
    jax.jit,
    static_argnames=("cap", "n_etiles", "eps", "interpret", "e_per"),
)
def _pip_assign_call(
    px_cov, py_cov, x1, y1, x2, y2, etab, pinfo,
    cap: int, n_etiles: int, eps: float, interpret: bool, e_per: int = 2,
):
    """Assignment-mode capacity class (see _make_assign_kernel). Returns
    (assign, count, band) each [Tc, POINT_TILE] i32. `pinfo[i, j]` is
    pid+1 of the pair's polygon, NEGATED on the last slot of that
    polygon's run in row i, 0 for dummy padding."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    e_per = min(e_per, cap)
    assert cap % e_per == 0, (cap, e_per)
    dt = jnp.float32
    tc = px_cov.shape[0]
    pxp = px_cov.astype(dt).reshape(tc, 1, POINT_TILE)
    pyp = py_cov.astype(dt).reshape(tc, 1, POINT_TILE)
    e1 = x1.astype(dt).reshape(-1, 1, EDGE_TILE)
    f1 = y1.astype(dt).reshape(-1, 1, EDGE_TILE)
    e2 = x2.astype(dt).reshape(-1, 1, EDGE_TILE)
    f2 = y2.astype(dt).reshape(-1, 1, EDGE_TILE)

    point_block = pl.BlockSpec(
        (1, 1, POINT_TILE), lambda i, j, et, pi: (i, 0, 0))

    def edge_block(e):
        return pl.BlockSpec(
            (1, 1, EDGE_TILE),
            lambda i, j, et, pi, e=e: (et[i, j * e_per + e], 0, 0),
        )

    out_block = pl.BlockSpec(
        (1, 1, POINT_TILE), lambda i, j, et, pi: (i, 0, 0))
    out_shape = jax.ShapeDtypeStruct((tc, 1, POINT_TILE), jnp.int32)

    edge_specs = []
    edge_args = []
    for e in range(e_per):
        edge_specs.extend([edge_block(e)] * 4)
        edge_args.extend([e1, f1, e2, f2])

    with _enable_x64(False):
        assign, count, band, _cur = pl.pallas_call(
            _make_assign_kernel(e_per, eps),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(tc, cap // e_per),
                in_specs=[point_block, point_block] + edge_specs,
                out_specs=(out_block, out_block, out_block, out_block),
            ),
            out_shape=(out_shape,) * 4,
            interpret=interpret,
        )(etab, pinfo, pxp, pyp, *edge_args)
    return (assign.reshape(tc, POINT_TILE), count.reshape(tc, POINT_TILE),
            band.reshape(tc, POINT_TILE))


def pip_layer_assign(
    px_np: np.ndarray,
    py_np: np.ndarray,
    x1: np.ndarray,
    y1: np.ndarray,
    x2: np.ndarray,
    y2: np.ndarray,
    poly_of_edge: np.ndarray,
    eps: float = 1e-4,
    interpret: bool = False,
    refine_f64: bool = True,
    prep: "LayerPrep | None" = None,
    poly_of_tile: "np.ndarray | None" = None,
) -> Tuple[np.ndarray, np.ndarray, dict]:
    """Point -> polygon ASSIGNMENT over the layer (the relation-join /
    JoinProcess result shape, SURVEY.md:382-383, 415): returns
    (poly_id [N] int32 — containing polygon id, -1 outside every polygon,
    count [N] int32 — how many polygons contain the point (==1 for
    disjoint layers; >1 reveals overlap, where poly_id is a sum and NOT
    a valid id), info dict). Band-flagged points are re-evaluated in f64
    per candidate polygon on the host (exact assignment)."""
    n = len(px_np)
    if prep is None:
        prep = prepare_layer(px_np, py_np, x1, y1, x2, y2, poly_of_edge)
    pl_ = prep.pairs
    n_ptiles, n_etiles = prep.n_ptiles, prep.n_etiles
    if len(pl_.pair_pt) == 0:
        return (np.full(n, -1, np.int32), np.zeros(n, np.int32),
                {"pairs": 0, "refined": 0})

    import jax.numpy as _jnp
    from geomesa_tpu.utils.padding import next_pow2 as _np2

    # polygon RANKS per edge tile + rank->id mapping (see
    # _poly_of_tile_from) — callers holding one (pip_layer_join) pass it
    if poly_of_tile is None:
        poly_of_tile, poly_uids = _poly_of_tile_from(prep, poly_of_edge)
    else:
        poly_of_tile, poly_uids = poly_of_tile

    pt_np = np.asarray(pl_.pair_pt, np.int64)
    et_np = np.asarray(pl_.pair_et, np.int64)
    pid_np = poly_of_tile[et_np]
    # group each row's pairs by polygon (they are already polygon-major
    # from build_pairs; a stable (pt, pid) sort makes it unconditional)
    order = np.lexsort((pid_np, pt_np))
    pt_np, et_np, pid_np = pt_np[order], et_np[order], pid_np[order]
    # flush marker: last slot of each (tile, polygon) run
    last = np.ones(len(pt_np), bool)
    last[:-1] = (pt_np[1:] != pt_np[:-1]) | (pid_np[1:] != pid_np[:-1])
    pinfo_val = np.where(last, -(pid_np + 1), pid_np + 1).astype(np.int32)

    tiles, counts = np.unique(pt_np, return_counts=True)
    starts = _cumsum0(counts)
    pxt = _jnp.asarray(prep.pxp).reshape(n_ptiles, POINT_TILE)
    pyt = _jnp.asarray(prep.pyp).reshape(n_ptiles, POINT_TILE)
    out_a = np.zeros((n_ptiles, POINT_TILE), np.int32)
    out_n = np.zeros((n_ptiles, POINT_TILE), np.int32)
    out_b = np.zeros((n_ptiles, POINT_TILE), np.int32)
    dt32 = _jnp.float32
    ax1 = _jnp.concatenate([_jnp.asarray(prep.ex1, dt32),
                            _jnp.zeros(EDGE_TILE, dt32)])
    ay1 = _jnp.concatenate([_jnp.asarray(prep.ey1, dt32),
                            _jnp.full(EDGE_TILE, BIG, dt32)])
    ax2 = _jnp.concatenate([_jnp.asarray(prep.ex2, dt32),
                            _jnp.zeros(EDGE_TILE, dt32)])
    ay2 = _jnp.concatenate([_jnp.asarray(prep.ey2, dt32),
                            _jnp.full(EDGE_TILE, BIG, dt32)])

    host_rows = []
    caps_of = _pow2_caps(counts)
    for cap_c in np.unique(caps_of):
        sel = np.nonzero(caps_of == cap_c)[0]
        cap_c = int(cap_c)
        if cap_c > MAX_ETAB_SLOTS // 2:
            # assignment cannot split a row across calls (the running
            # parity would be lost between them): rows this dense are
            # evaluated exactly on the host instead. Half the union
            # budget: this kernel prefetches TWO scalar arrays
            # (etab + pinfo), and SMEM overflowed by 1.2K at the 10k-
            # polygon SQL-join scale when budgeted for one.
            host_rows.extend(tiles[sel].tolist())
            continue
        etab = np.full((len(sel), cap_c), n_etiles, np.int32)
        pinf = np.zeros((len(sel), cap_c), np.int32)
        cnt_s = counts[sel]
        row_of = np.repeat(np.arange(len(sel)), cnt_s)
        col_of = (np.arange(cnt_s.sum()) - np.repeat(_cumsum0(cnt_s), cnt_s))
        src = np.repeat(starts[sel], cnt_s) + col_of
        etab[row_of, col_of] = et_np[src]
        pinf[row_of, col_of] = pinfo_val[src]
        ptids = tiles[sel]
        # half the union kernel's SMEM budget: etab AND pinfo prefetch
        per_call = max(1, (MAX_ETAB_SLOTS // 2) // max(cap_c, 32))
        for c0 in range(0, len(sel), per_call):
            c1 = min(c0 + per_call, len(sel))
            ids = ptids[c0:c1]
            tab = np.ascontiguousarray(etab[c0:c1])
            pin = np.ascontiguousarray(pinf[c0:c1])
            tc_pad = max(_np2(len(ids)), 8) - len(ids)
            if tc_pad:
                ids = np.concatenate([ids, np.full(tc_pad, ids[0], ids.dtype)])
                tab = np.concatenate(
                    [tab, np.full((tc_pad, cap_c), n_etiles, np.int32)])
                pin = np.concatenate(
                    [pin, np.zeros((tc_pad, cap_c), np.int32)])
            jid = _jnp.asarray(ids)
            # cap_c is pow2-bucketed: one trace per bucket, bounded;
            # tile extents are per-layer constants (see grouped path)
            # gt: waive GT28
            aa, nn, bb = _pip_assign_call(  # gt: waive GT01
                _jnp.take(pxt, jid, axis=0), _jnp.take(pyt, jid, axis=0),
                ax1, ay1, ax2, ay2,
                _jnp.asarray(tab), _jnp.asarray(pin),
                cap=cap_c, n_etiles=n_etiles, eps=eps, interpret=interpret,
            )
            la = len(ptids[c0:c1])
            out_a[ptids[c0:c1]] = np.asarray(aa)[:la]
            out_n[ptids[c0:c1]] = np.asarray(nn)[:la]
            out_b[ptids[c0:c1]] = np.asarray(bb)[:la]

    out_a[~pl_.covered] = 0
    out_n[~pl_.covered] = 0
    out_b[~pl_.covered] = 0
    assign = out_a.reshape(-1)[:n]
    count = out_n.reshape(-1)[:n]
    band = out_b.reshape(-1)[:n]
    poly_id = np.where(count == 1, assign - 1, -1).astype(np.int32)

    # host-exact rows: band-flagged points (skippable via refine_f64) +
    # tiles too dense for one call (NEVER skippable — the kernel computed
    # nothing for them, so skipping would silently report every point of
    # the tile as outside; round-4 review)
    refine_idx = np.nonzero(band > 0)[0] if refine_f64 else (
        np.zeros(0, np.int64))
    if host_rows:
        hr = np.concatenate([
            np.arange(t * POINT_TILE, min((t + 1) * POINT_TILE, n))
            for t in host_rows
        ])
        refine_idx = np.unique(np.concatenate([refine_idx, hr]))
    refined = 0
    if len(refine_idx):
        poly_id, count = _refine_assign_f64(
            refine_idx, poly_id, count, px_np, py_np, prep, poly_of_tile)
        refined = len(refine_idx)
    # map dense kernel ranks back to the caller's original polygon ids
    out_ids = np.full(n, -1, np.int64)
    valid_a = poly_id >= 0
    out_ids[valid_a] = poly_uids[poly_id[valid_a]]
    return out_ids, count, {
        "pairs": int(len(pl_.pair_pt)), "refined": refined,
        "host_rows": len(host_rows),
        "flagged": int((band > 0).sum()),
    }


def _poly_of_tile_from(prep: "LayerPrep", poly_of_edge):
    """(rank_of_tile [n_etiles], unique_ids [P]): per-edge-tile polygon
    RANKS (dense 0..P-1 — the i32 kernel encoding and every internal
    group key use ranks, so sparse/large ids neither overflow nor size
    arrays) plus the rank -> original-id mapping for outputs."""
    pids, counts, _ = _group_ids(np.asarray(poly_of_edge, np.int64))
    tiles_per = -(-counts // EDGE_TILE)
    return np.repeat(np.arange(len(pids)), tiles_per), pids


def pip_layer_join(
    px_np: np.ndarray,
    py_np: np.ndarray,
    x1: np.ndarray,
    y1: np.ndarray,
    x2: np.ndarray,
    y2: np.ndarray,
    poly_of_edge: np.ndarray,
    eps: float = 1e-4,
    interpret: bool = False,
    prep: "LayerPrep | None" = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Full spatial-join pair emission: returns (point_rows [M],
    polygon_ids [M]) — one row per (point, containing polygon) pair,
    INCLUDING multiplicity for overlapping layers (points contained in
    k polygons emit k pairs, enumerated exactly on the host from the
    pair list's candidates). The SQL engine's ON st_contains path."""
    if prep is None:
        prep = prepare_layer(px_np, py_np, x1, y1, x2, y2, poly_of_edge)
    groups = _poly_of_tile_from(prep, poly_of_edge)
    poly_id, count, _info = pip_layer_assign(
        px_np, py_np, x1, y1, x2, y2, poly_of_edge,
        eps=eps, interpret=interpret, prep=prep,
        poly_of_tile=groups,
    )
    single = np.nonzero(count == 1)[0]
    pt_rows = [single]
    polys = [poly_id[single].astype(np.int64)]
    multi = np.nonzero(count > 1)[0]
    if len(multi):
        mp, mrank = _multi_assign_f64(multi, px_np, py_np, prep,
                                      groups[0])
        pt_rows.append(mp)
        polys.append(groups[1][mrank])  # ranks -> original ids
    return np.concatenate(pt_rows), np.concatenate(polys)


def _multi_assign_f64(idx, px_np, py_np, prep, poly_of_tile):
    """Exact f64 enumeration of EVERY containing polygon for the given
    points (the overlap path of pip_layer_join)."""
    pl_ = prep.pairs
    ex1, ey1, ex2, ey2 = prep.ex1, prep.ey1, prep.ex2, prep.ey2
    csr_tiles, csr_starts = _tile_pair_csr(pl_)
    out_pt = []
    out_poly = []
    by_tile: dict = {}
    for i in idx:
        by_tile.setdefault(i // POINT_TILE, []).append(i)
    for ptid, pts in by_tile.items():
        ets = _ets_of_tile(pl_, csr_tiles, csr_starts, int(ptid))
        if not len(ets):
            continue
        pids = poly_of_tile[ets]
        ii = np.asarray(pts)
        pxi = px_np[ii][:, None]
        pyi = py_np[ii][:, None]
        for pid in np.unique(pids):
            sl = np.concatenate([
                np.arange(e * EDGE_TILE, (e + 1) * EDGE_TILE)
                for e in ets[pids == pid]
            ])
            a1, b1 = ex1[sl], ey1[sl]
            a2, b2 = ex2[sl], ey2[sl]
            condx = (b1[None] <= pyi) != (b2[None] <= pyi)
            tt = (pyi - b1[None]) / np.where(b2 == b1, 1.0, b2 - b1)[None]
            xc = a1[None] + tt * (a2 - a1)[None]
            inside = (np.sum(condx & (xc > pxi), 1) % 2) == 1
            hit = ii[inside]
            out_pt.append(hit)
            out_poly.append(np.full(len(hit), pid, np.int64))
    if not out_pt:
        return np.zeros(0, np.int64), np.zeros(0, np.int64)
    return np.concatenate(out_pt), np.concatenate(out_poly)


def _refine_assign_f64(idx, poly_id, count, px_np, py_np, prep,
                       poly_of_tile):
    """Exact f64 per-polygon parity for the given point indices, over the
    pair list's candidate polygons of each point's tile."""
    pl_ = prep.pairs
    ex1, ey1, ex2, ey2 = prep.ex1, prep.ey1, prep.ex2, prep.ey2
    csr_tiles, csr_starts = _tile_pair_csr(pl_)
    by_tile: dict = {}
    for i in idx:
        by_tile.setdefault(i // POINT_TILE, []).append(i)
    poly_id = poly_id.copy()
    count = count.copy()
    for ptid, pts in by_tile.items():
        ets = _ets_of_tile(pl_, csr_tiles, csr_starts, int(ptid))
        ii = np.asarray(pts)
        if not len(ets):
            poly_id[ii] = -1
            count[ii] = 0
            continue
        pids = poly_of_tile[ets]
        pxi = px_np[ii][:, None]
        pyi = py_np[ii][:, None]
        acc_id = np.full(len(ii), -1, np.int64)
        acc_n = np.zeros(len(ii), np.int64)
        for pid in np.unique(pids):
            sl = np.concatenate([
                np.arange(e * EDGE_TILE, (e + 1) * EDGE_TILE)
                for e in ets[pids == pid]
            ])
            a1, b1 = ex1[sl], ey1[sl]
            a2, b2 = ex2[sl], ey2[sl]
            condx = (b1[None] <= pyi) != (b2[None] <= pyi)
            tt = (pyi - b1[None]) / np.where(b2 == b1, 1.0, b2 - b1)[None]
            xc = a1[None] + tt * (a2 - a1)[None]
            inside = (np.sum(condx & (xc > pxi), 1) % 2) == 1
            acc_id = np.where(inside, pid, acc_id)
            acc_n += inside
        poly_id[ii] = np.where(acc_n == 1, acc_id, -1)
        count[ii] = acc_n
    return poly_id, count


@functools.partial(
    jax.jit, static_argnames=("n_ptiles", "n_etiles", "eps", "interpret")
)
def _pip_sparse_call(
    px, py, x1, y1, x2, y2, pair_pt, pair_et,
    n_ptiles: int, n_etiles: int, eps: float, interpret: bool,
):
    """One pallas invocation over one (pow2-padded) pair chunk. The out
    array carries ONE EXTRA scratch tile (index n_ptiles) that padding
    pairs target, so real tiles are never corrupted."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    dt = jnp.float32
    # one extra SCRATCH point tile (index n_ptiles): capacity-padding
    # pairs target it for both input fetch AND output, so padded programs
    # never address out-of-bounds blocks (round-3 review finding)
    pxp = jnp.concatenate(
        [px.astype(dt), jnp.full(POINT_TILE, 1e8, dt)]
    ).reshape(-1, 1, POINT_TILE)
    pyp = jnp.concatenate(
        [py.astype(dt), jnp.full(POINT_TILE, 1e8, dt)]
    ).reshape(-1, 1, POINT_TILE)
    e1 = x1.astype(dt).reshape(-1, 1, EDGE_TILE)
    f1 = y1.astype(dt).reshape(-1, 1, EDGE_TILE)
    e2 = x2.astype(dt).reshape(-1, 1, EDGE_TILE)
    f2 = y2.astype(dt).reshape(-1, 1, EDGE_TILE)
    M = pair_pt.shape[0]

    point_block = pl.BlockSpec(
        (1, 1, POINT_TILE), lambda m, pt, et: (pt[m], 0, 0)
    )
    edge_block = pl.BlockSpec(
        (1, 1, EDGE_TILE), lambda m, pt, et: (et[m], 0, 0)
    )
    out_block = pl.BlockSpec(
        (1, 1, POINT_TILE), lambda m, pt, et: (pt[m], 0, 0)
    )
    out_shape = jax.ShapeDtypeStruct(
        (n_ptiles + 1, 1, POINT_TILE), jnp.int32
    )

    with _enable_x64(False):
        counts = pl.pallas_call(
            _sparse_kernel,
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(M,),
                in_specs=[point_block, point_block,
                          edge_block, edge_block, edge_block, edge_block],
                out_specs=out_block,
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(pair_pt, pair_et, pxp, pyp, e1, f1, e2, f2)
        band = pl.pallas_call(
            functools.partial(_sparse_band_kernel, eps=eps),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(M,),
                in_specs=[point_block, point_block,
                          edge_block, edge_block, edge_block, edge_block],
                out_specs=out_block,
            ),
            out_shape=out_shape,
            interpret=interpret,
        )(pair_pt, pair_et, pxp, pyp, e1, f1, e2, f2)
    return counts, band


# at ~8 B of SMEM per pair (two i32 scalars), the TPU's ~1 MB scalar-
# prefetch budget caps a single call near 128k pairs; chunks split at
# point-tile boundaries so every tile's accumulation stays in one call
MAX_PAIRS_PER_CALL = 1 << 16


def chunk_pairs(pair_pt, pair_et, cap=MAX_PAIRS_PER_CALL):
    """Split the (pt-sorted) pair list into chunks of <= cap pairs,
    PREFERRING tile boundaries. A single tile denser than cap is split
    mid-tile — the caller ACCUMULATES (+=) rather than assigns for tiles
    it has already seen, and the kernel's first-visit zeroing only fires
    on each chunk's first pair of a tile, so partial counts add exactly
    (crossing counts and band flags are both additive)."""
    M = len(pair_pt)
    chunks = []
    start = 0
    while start < M:
        end = min(start + cap, M)
        if end < M:
            # back off to the last tile boundary if one exists
            back = end
            while back > start and pair_pt[back] == pair_pt[back - 1]:
                back -= 1
            if back > start:
                end = back
        chunks.append((start, end))
        start = end
    return chunks


def pip_layer_sparse(
    px: jax.Array,          # [n_ptiles * POINT_TILE] padded, tile-ordered
    py: jax.Array,
    x1: jax.Array,          # [n_etiles * EDGE_TILE] polygon-padded
    y1: jax.Array,
    x2: jax.Array,
    y2: jax.Array,
    pair_pt,                # [M] int32, sorted by point tile
    pair_et,                # [M] int32
    n_ptiles: int = 0,
    n_etiles: int = 0,
    eps: float = 1e-4,
    interpret: bool = False,
    max_pairs_per_call: int = MAX_PAIRS_PER_CALL,
) -> Tuple[jax.Array, jax.Array]:
    """Sparse-pair crossing counts + boundary-band flags.

    Returns (counts int32 [n_ptiles*POINT_TILE], band int32 same shape).
    Tiles never named in pair_pt hold GARBAGE — mask with PairList.covered
    (they are provably outside every polygon bbox => count 0, band 0).
    Internally chunked: each pallas call takes <= MAX_PAIRS_PER_CALL
    pairs (SMEM scalar-prefetch budget), split at tile boundaries."""
    from geomesa_tpu.utils.padding import next_pow2

    pt_np = np.asarray(pair_pt, np.int32)
    et_np = np.asarray(pair_et, np.int32)
    out_c = np.zeros((n_ptiles, POINT_TILE), np.int32)
    out_b = np.zeros((n_ptiles, POINT_TILE), np.int32)
    seen: set = set()
    for s0, s1 in chunk_pairs(pt_np, et_np, cap=max_pairs_per_call):
        seg_pt = pt_np[s0:s1]
        seg_et = et_np[s0:s1]
        cap = max(next_pow2(len(seg_pt)), 256)
        pad = cap - len(seg_pt)
        if pad:
            seg_pt = np.concatenate(
                [seg_pt, np.full(pad, n_ptiles, np.int32)])
            seg_et = np.concatenate([seg_et, np.zeros(pad, np.int32)])
        counts, band = _pip_sparse_call(
            px, py, x1, y1, x2, y2,
            jnp.asarray(seg_pt), jnp.asarray(seg_et),
            n_ptiles=n_ptiles, n_etiles=n_etiles, eps=eps,
            interpret=interpret,
        )
        cc = np.asarray(counts).reshape(n_ptiles + 1, POINT_TILE)
        bb = np.asarray(band).reshape(n_ptiles + 1, POINT_TILE)
        for t in np.unique(pt_np[s0:s1]):
            if t in seen:  # tile split across chunks: partials ADD
                out_c[t] += cc[t]
                out_b[t] += bb[t]
            else:
                out_c[t] = cc[t]
                out_b[t] = bb[t]
                seen.add(int(t))
    return out_c.reshape(-1), out_b.reshape(-1)


def _tile_pair_csr(pl_: "PairList"):
    """CSR view of the (pt-sorted) pair list: (tiles [K], starts [K+1])
    so tile tiles[i]'s edge tiles are pair_et[starts[i]:starts[i+1]].
    O(K) from the precomputed `first` markers — the refine paths used to
    rebuild a python dict by looping the ENTIRE pair list (round-4
    review: seconds of host time at config-2 scale)."""
    pt = np.asarray(pl_.pair_pt, np.int64)
    s = np.nonzero(np.asarray(pl_.first))[0]
    return pt[s], np.concatenate([s, [len(pt)]])


def _ets_of_tile(pl_, tiles, starts, ptid: int) -> np.ndarray:
    k = int(np.searchsorted(tiles, ptid))
    if k >= len(tiles) or tiles[k] != ptid:
        return np.zeros(0, np.int64)
    return np.asarray(pl_.pair_et[starts[k]: starts[k + 1]], np.int64)


class LayerPrep(NamedTuple):
    """Everything the sparse kernels need, host-built once per layer
    (the prepared-geometry/index analog; reused by bench.py so the bench
    and the engine can never desynchronize)."""

    pxp: np.ndarray
    pyp: np.ndarray
    ex1: np.ndarray
    ey1: np.ndarray
    ex2: np.ndarray
    ey2: np.ndarray
    pairs: PairList
    n_ptiles: int
    n_etiles: int


def prepare_layer(
    px_np, py_np, x1, y1, x2, y2, poly_of_edge, margin: float = 1e-3
) -> LayerPrep:
    """Z-tile the points, polygon-pad the edges, bbox-prune pairs."""
    n = len(px_np)
    npad = (-n) % POINT_TILE
    pxp = np.concatenate([px_np, np.full(npad, 1e8)])
    pyp = np.concatenate([py_np, np.full(npad, 1e8)])
    n_ptiles = len(pxp) // POINT_TILE
    tx = pxp.reshape(n_ptiles, POINT_TILE)
    ty = pyp.reshape(n_ptiles, POINT_TILE)
    ptile_bbox = np.stack(
        [tx.min(1), ty.min(1), tx.max(1), ty.max(1)], 1
    )
    # padded tail tile bbox is at 1e8: never intersects a polygon

    ex1, ey1, ex2, ey2, poly_of_tile = pad_polygon_edges(
        x1, y1, x2, y2, poly_of_edge
    )
    n_etiles = len(ex1) // EDGE_TILE
    tiles = lambda a: a.reshape(n_etiles, EDGE_TILE)  # noqa: E731
    real = tiles(ey1) < BIG / 2  # degenerate edges excluded from bboxes

    def _bb(a, lo):
        v = np.where(real, tiles(a), np.inf if lo else -np.inf)
        return v.min(1) if lo else v.max(1)

    etile_bbox = np.stack([
        _bb(np.minimum(ex1, ex2), True), _bb(np.minimum(ey1, ey2), True),
        _bb(np.maximum(ex1, ex2), False), _bb(np.maximum(ey1, ey2), False),
    ], 1)
    # per-polygon bboxes via reduceat over pid-sorted edges (the naive
    # per-polygon masking re-scanned the edge table 10k times). Both the
    # bbox table and build_pairs work in DENSE RANK space (0..P-1), so
    # sparse/large polygon ids never size an array (round-4 review)
    poe = np.asarray(poly_of_edge, np.int64)
    pids, counts, order = _group_ids(poe)
    bounds = np.concatenate([[0], np.cumsum(counts)[:-1]])
    exmin = np.minimum(x1, x2)[order]
    eymin = np.minimum(y1, y2)[order]
    exmax = np.maximum(x1, x2)[order]
    eymax = np.maximum(y1, y2)[order]
    poly_bbox = np.stack([
        np.minimum.reduceat(exmin, bounds),
        np.minimum.reduceat(eymin, bounds),
        np.maximum.reduceat(exmax, bounds),
        np.maximum.reduceat(eymax, bounds),
    ], 1)
    pot_rank = np.searchsorted(pids, poly_of_tile)
    pairs = build_pairs(
        ptile_bbox, etile_bbox, pot_rank, poly_bbox, margin=margin
    )
    return LayerPrep(pxp, pyp, ex1, ey1, ex2, ey2, pairs,
                     n_ptiles, n_etiles)


def _refine_band_f64(px_np, py_np, ex1, ey1, ex2, ey2, pl_, inside, flagged):
    """Exact f64 re-evaluation of band-flagged points over the SAME pair
    candidate set, vectorized per point tile ([pts-in-tile, E] ops).
    Mutates `inside` in place; returns the refined count. Shared by the
    single-device and mesh-sharded drivers."""
    refined = 0
    csr_tiles, csr_starts = _tile_pair_csr(pl_)
    by_tile: dict = {}
    for i in flagged:
        by_tile.setdefault(i // POINT_TILE, []).append(i)
    for ptid, idxs in by_tile.items():
        ets = _ets_of_tile(pl_, csr_tiles, csr_starts, ptid)
        ii = np.asarray(idxs)
        if not len(ets):
            inside[ii] = False
            continue
        sl = np.concatenate(
            [np.arange(e * EDGE_TILE, (e + 1) * EDGE_TILE) for e in ets]
        )
        a1, b1 = ex1[sl], ey1[sl]
        a2, b2 = ex2[sl], ey2[sl]
        pxi = px_np[ii][:, None]
        pyi = py_np[ii][:, None]
        condx = (b1[None, :] <= pyi) != (b2[None, :] <= pyi)
        tt = (pyi - b1[None, :]) / np.where(b2 == b1, 1.0, b2 - b1)[None, :]
        xc = a1[None, :] + tt * (a2 - a1)[None, :]
        inside[ii] = (np.sum(condx & (xc > pxi), axis=1) % 2) == 1
        refined += len(ii)
    return refined


# --- LayerPrep persistence (round 5, VERDICT r4 task 5) ---------------------
# The pair list is (point-batch x layer)-intrinsic state, exactly like the
# reference's prepared-geometry cache (SURVEY.md:184-186): content-addressed
# on the input arrays, persisted as one .npz, with a small in-process LRU in
# front. At the 10k-polygon config-2 shape the host build costs ~5 s; a
# cache hit loads in ~0.1 s, so the FIRST query of a new process stops being
# host-bound.

_PREP_MEM_CACHE: "dict[str, LayerPrep]" = {}
_PREP_MEM_MAX = 4
# bytes cap so one-shot joins over big batches cannot pin multi-GB padded
# copies for the process lifetime (review finding); the entry just built
# is always admitted — eviction only sheds OLDER entries
_PREP_MEM_MAX_BYTES = 512 << 20
# created eagerly at import: the old lazy `if _PREP_LOCK is None:
# _PREP_LOCK = Lock()` double-check was itself the race it guarded
# against — two warm-up threads could mint two locks (GT12)
_PREP_LOCK = threading.Lock()


def _prep_lock():
    return _PREP_LOCK


def _prep_nbytes(prep: LayerPrep) -> int:
    return sum(a.nbytes for a in prep[:6]) + sum(
        a.nbytes for a in prep.pairs[:4])


def _prep_cache_put(key: str, prep: LayerPrep) -> None:
    with _prep_lock():
        _PREP_MEM_CACHE[key] = prep
        while len(_PREP_MEM_CACHE) > 1 and (
            len(_PREP_MEM_CACHE) > _PREP_MEM_MAX
            or sum(map(_prep_nbytes, _PREP_MEM_CACHE.values()))
            > _PREP_MEM_MAX_BYTES
        ):
            oldest = next(iter(_PREP_MEM_CACHE))
            if oldest == key:  # never evict the entry just inserted
                break
            _PREP_MEM_CACHE.pop(oldest)


def layer_prep_key(px_np, py_np, x1, y1, x2, y2, poly_of_edge,
                   margin: float = 1e-3) -> str:
    """Content fingerprint of (point batch, polygon layer, tiling
    constants). sha1 over the raw bytes: ~100 ms at 4M points — 50x
    cheaper than the build it saves."""
    import hashlib

    h = hashlib.sha1()
    for a in (px_np, py_np, x1, y1, x2, y2, poly_of_edge):
        a = np.ascontiguousarray(a)
        h.update(str(a.dtype).encode())
        h.update(str(a.shape).encode())
        h.update(a.tobytes())
    h.update(f"m{margin};pt{POINT_TILE};et{EDGE_TILE};v1".encode())
    return h.hexdigest()


def save_layer_prep(prep: LayerPrep, path: str) -> None:
    import os

    tmp = path + f".tmp{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            np.savez(
                f,
                pxp=prep.pxp, pyp=prep.pyp,
                ex1=prep.ex1, ey1=prep.ey1, ex2=prep.ex2, ey2=prep.ey2,
                pair_pt=prep.pairs.pair_pt, pair_et=prep.pairs.pair_et,
                first=prep.pairs.first, covered=prep.pairs.covered,
                scalars=np.asarray(
                    [prep.n_ptiles, prep.n_etiles,
                     prep.pairs.n_ptiles, prep.pairs.n_etiles], np.int64),
            )
        os.replace(tmp, path)
    except BaseException:
        # never leave a partial multi-hundred-MB tmp behind (ENOSPC would
        # otherwise worsen the very pressure that caused the failure)
        try:
            os.remove(tmp)
        except OSError:
            pass
        raise


def load_layer_prep(path: str) -> LayerPrep:
    with np.load(path, allow_pickle=False) as z:
        sc = z["scalars"]
        return LayerPrep(
            z["pxp"], z["pyp"], z["ex1"], z["ey1"], z["ex2"], z["ey2"],
            PairList(z["pair_pt"], z["pair_et"], z["first"], z["covered"],
                     int(sc[2]), int(sc[3])),
            int(sc[0]), int(sc[1]),
        )


def prepare_layer_cached(
    px_np, py_np, x1, y1, x2, y2, poly_of_edge,
    margin: float = 1e-3, cache_dir: "str | None" = None,
    key: "str | None" = None,
) -> LayerPrep:
    """prepare_layer behind a content-addressed cache: in-process LRU
    first, then `cache_dir` (or the geomesa.spatial.prep.cache.dir system
    property; empty = memory only) on disk. A corrupt/unreadable disk
    entry falls through to a rebuild. `key` may carry a precomputed
    layer_prep_key to skip re-hashing the inputs."""
    import os

    from geomesa_tpu.utils.config import SystemProperties

    if key is None:
        key = layer_prep_key(
            px_np, py_np, x1, y1, x2, y2, poly_of_edge, margin)
    with _prep_lock():
        hit = _PREP_MEM_CACHE.get(key)
        if hit is not None:
            # true LRU: refresh recency (eviction pops insertion order)
            _PREP_MEM_CACHE.pop(key)
            _PREP_MEM_CACHE[key] = hit
    if hit is not None:
        return hit
    if cache_dir is None:
        cache_dir = str(SystemProperties.SPATIAL_PREP_CACHE_DIR.get()) or None
    path = os.path.join(cache_dir, f"layerprep_{key}.npz") if cache_dir else None
    prep = None
    if path and os.path.exists(path):
        try:
            prep = load_layer_prep(path)
        except Exception:
            prep = None
    if prep is None:
        prep = prepare_layer(px_np, py_np, x1, y1, x2, y2, poly_of_edge,
                             margin=margin)
        if path:
            try:
                os.makedirs(cache_dir, exist_ok=True)
                save_layer_prep(prep, path)
            except OSError:
                pass
    _prep_cache_put(key, prep)
    return prep


def prepare_layer_async(
    px_np, py_np, x1, y1, x2, y2, poly_of_edge,
    margin: float = 1e-3, cache_dir: "str | None" = None,
    key: "str | None" = None,
):
    """Kick the (cached) prep build onto a worker thread so the caller can
    overlap it with device work that does not need pairs — point upload
    and kernel warm-up (VERDICT r4 task 5's overlap half). Returns a
    0-arg callable that joins and yields the LayerPrep. The build is pure
    numpy, so the thread releases the GIL for the big vector ops."""
    import threading

    out: dict = {}

    def work():
        try:
            out["prep"] = prepare_layer_cached(
                px_np, py_np, x1, y1, x2, y2, poly_of_edge,
                margin=margin, cache_dir=cache_dir, key=key)
        except BaseException as e:  # re-raise on join
            out["err"] = e

    t = threading.Thread(target=work, daemon=True)
    t.start()

    def result() -> LayerPrep:
        t.join()
        if "err" in out:
            raise out["err"]
        return out["prep"]

    return result


def pip_layer(
    px_np: np.ndarray,
    py_np: np.ndarray,
    x1: np.ndarray,
    y1: np.ndarray,
    x2: np.ndarray,
    y2: np.ndarray,
    poly_of_edge: np.ndarray,
    eps: float = 1e-4,
    interpret: bool = False,
    refine_f64: bool = True,
    prep: "LayerPrep | None" = None,
    points_device=None,
):
    """End-to-end host orchestration: prepare_layer + sparse kernels +
    f64 band refinement.

    Returns (inside bool [N], info dict). Points are assumed Z/store-
    ordered (tile bboxes are only tight then); correctness holds for any
    order. `points_device` optionally supplies the PADDED point arrays
    already device-resident (uploaded concurrently with an async prep
    build — the overlap path); the host refine still reads px_np/py_np."""
    n = len(px_np)
    if prep is None:
        prep = prepare_layer(px_np, py_np, x1, y1, x2, y2, poly_of_edge)
    pxp, pyp = prep.pxp, prep.pyp
    ex1, ey1, ex2, ey2 = prep.ex1, prep.ey1, prep.ex2, prep.ey2
    n_ptiles, n_etiles = prep.n_ptiles, prep.n_etiles
    pl_ = prep.pairs

    if len(pl_.pair_pt) == 0:
        # same info keys as the normal return: callers index 'flagged'
        # and 'refine_s' unconditionally
        return np.zeros(n, bool), {"pairs": 0, "refined": 0,
                                   "n_ptiles": n_ptiles,
                                   "n_etiles": n_etiles,
                                   "flagged": 0, "refine_s": 0.0}

    if points_device is not None:
        pxp, pyp = points_device  # padded, already device-resident
    counts, band = pip_layer_grouped(
        pxp, pyp,
        jnp.asarray(ex1), jnp.asarray(ey1),
        jnp.asarray(ex2), jnp.asarray(ey2),
        pl_.pair_pt, pl_.pair_et,
        n_ptiles=n_ptiles, n_etiles=n_etiles, eps=eps,
        interpret=interpret,
    )
    counts = np.array(counts).reshape(n_ptiles, POINT_TILE)
    band_np = np.array(band).reshape(n_ptiles, POINT_TILE)
    counts[~pl_.covered] = 0
    band_np[~pl_.covered] = 0
    inside = (counts.reshape(-1)[:n] % 2) == 1
    flagged = np.nonzero(band_np.reshape(-1)[:n] > 0)[0]

    refined = 0
    refine_s = 0.0
    if refine_f64 and len(flagged):
        import time as _time

        _t0 = _time.perf_counter()
        refined = _refine_band_f64(
            px_np, py_np, ex1, ey1, ex2, ey2, pl_, inside, flagged)
        refine_s = _time.perf_counter() - _t0
    return inside, {
        "pairs": int(len(pl_.pair_pt)), "refined": refined,
        "n_ptiles": n_ptiles, "n_etiles": n_etiles,
        "flagged": int(len(flagged)), "refine_s": round(refine_s, 3),
    }


def pip_layer_sharded(
    mesh,
    px_np: np.ndarray,
    py_np: np.ndarray,
    x1: np.ndarray,
    y1: np.ndarray,
    x2: np.ndarray,
    y2: np.ndarray,
    poly_of_edge: np.ndarray,
    eps: float = 1e-4,
    interpret: bool = False,
    refine_f64: bool = True,
):
    """Config-2 spatial join over a device mesh (round 5, VERDICT task 4).

    Point tiles are sharded across the mesh; the padded edge table rides
    REPLICATED (polygon layers are MBs against GB point sets — the same
    asymmetry the reference exploits by broadcasting the small join side).
    One shard_map Pallas pass at a single global capacity class (pow2 of
    the max per-tile pair count; the single-chip driver's per-tile
    bucketing matters for 10k-polygon skew, not at mesh-dryrun shapes),
    then the SAME host-side parity finish + f64 band refinement as
    pip_layer. Returns (inside bool [N], info dict)."""
    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.parallel.mesh import SHARD_AXIS
    from geomesa_tpu.utils.jaxcompat import shard_map

    n = len(px_np)
    prep = prepare_layer(px_np, py_np, x1, y1, x2, y2, poly_of_edge)
    pl_ = prep.pairs
    ex1, ey1, ex2, ey2 = prep.ex1, prep.ey1, prep.ex2, prep.ey2
    n_etiles = prep.n_etiles
    if len(pl_.pair_pt) == 0:
        # same info keys as the normal return below
        return np.zeros(n, bool), {
            "pairs": 0, "refined": 0, "n_ptiles": prep.n_ptiles,
            "n_etiles": n_etiles, "flagged": 0, "cap": 0,
            "shards": int(np.prod(mesh.devices.shape)),
        }

    D = int(np.prod(mesh.devices.shape))
    nt = prep.n_ptiles
    tpd = -(-nt // D)
    ntp = tpd * D

    pt_np = np.asarray(pl_.pair_pt, np.int64)
    et_np = np.asarray(pl_.pair_et, np.int64)
    counts_t = np.bincount(pt_np, minlength=ntp)
    cap = int(_pow2_caps(np.asarray([counts_t.max()]))[0])
    if cap > MAX_ETAB_SLOTS:
        raise ValueError(
            f"per-tile pair count {counts_t.max()} exceeds the SMEM etab "
            f"budget ({MAX_ETAB_SLOTS}); shard a smaller layer or use the "
            "single-chip pip_layer driver (it chunks by column)"
        )
    etab = np.full((ntp, cap), n_etiles, np.int32)
    order = np.argsort(pt_np, kind="stable")
    starts = np.concatenate([[0], np.cumsum(counts_t)[:-1]])
    col = np.arange(len(order)) - starts[pt_np[order]]
    etab[pt_np[order], col] = et_np[order]

    pad_pts = ntp * POINT_TILE - len(prep.pxp)
    pxp = np.concatenate([prep.pxp, np.full(pad_pts, 1e8)])
    pyp = np.concatenate([prep.pyp, np.full(pad_pts, 1e8)])

    dt32 = jnp.float32
    ax1 = jnp.concatenate([jnp.asarray(ex1, dt32), jnp.zeros(EDGE_TILE, dt32)])
    ay1 = jnp.concatenate([jnp.asarray(ey1, dt32),
                           jnp.full(EDGE_TILE, BIG, dt32)])
    ax2 = jnp.concatenate([jnp.asarray(ex2, dt32), jnp.zeros(EDGE_TILE, dt32)])
    ay2 = jnp.concatenate([jnp.asarray(ey2, dt32),
                           jnp.full(EDGE_TILE, BIG, dt32)])

    def shard_fn(pxl, pyl, etabl, a1, b1, a2, b2):
        return _pip_grouped_call(
            pxl.reshape(tpd, POINT_TILE), pyl.reshape(tpd, POINT_TILE),
            a1, b1, a2, b2, etabl,
            cap=cap, n_etiles=n_etiles, eps=eps, interpret=interpret,
        )

    f = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                  P(), P(), P(), P()),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,  # pallas outputs carry no vma (knn_scan idiom)
    )
    counts, band = f(
        jnp.asarray(pxp, dt32), jnp.asarray(pyp, dt32), jnp.asarray(etab),
        ax1, ay1, ax2, ay2,
    )

    counts = np.array(counts).reshape(ntp, POINT_TILE)[:nt]
    band_np = np.array(band).reshape(ntp, POINT_TILE)[:nt]
    counts[~pl_.covered] = 0
    band_np[~pl_.covered] = 0
    inside = (counts.reshape(-1)[:n] % 2) == 1
    flagged = np.nonzero(band_np.reshape(-1)[:n] > 0)[0]
    refined = 0
    if refine_f64 and len(flagged):
        refined = _refine_band_f64(
            px_np, py_np, ex1, ey1, ex2, ey2, pl_, inside, flagged)
    return inside, {
        "pairs": int(len(pt_np)), "refined": refined,
        "n_ptiles": nt, "n_etiles": n_etiles,
        "flagged": int(len(flagged)), "cap": cap, "shards": D,
    }
