"""Masked statistical reductions — the device side of StatsScan.

Parity: geomesa-index-api StatsScan + the Stat sketch evaluation hot path
(geomesa-utils stats) [upstream, unverified]. Each function is a pure masked
reduction over device columns producing small arrays that merge across shards
with psum/min/max — the collective analog of the reference's mergeable
sketches streaming from tablet servers. Host-side mergeable sketch *objects*
(Stat DSL, serialization) live in geomesa_tpu.stats; these kernels feed them.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from geomesa_tpu.parallel.mesh import SHARD_AXIS


@jax.jit
def masked_count(mask: jax.Array) -> jax.Array:
    return jnp.sum(mask.astype(jnp.int64))


@jax.jit
def masked_minmax(v: jax.Array, mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    big = jnp.asarray(jnp.inf, jnp.float64)
    vf = v.astype(jnp.float64)
    return (
        jnp.min(jnp.where(mask, vf, big)),
        jnp.max(jnp.where(mask, vf, -big)),
    )


@jax.jit
def masked_moments(v: jax.Array, mask: jax.Array):
    """(count, sum, sum-of-squares) in f64 — exact merge across shards by
    adding components (DescriptiveStats parity)."""
    vf = jnp.where(mask, v.astype(jnp.float64), 0.0)
    return (
        jnp.sum(mask.astype(jnp.int64)),
        jnp.sum(vf),
        jnp.sum(vf * vf),
    )


@functools.partial(jax.jit, static_argnames=("bins",))
def masked_histogram(
    v: jax.Array, mask: jax.Array, lo: float, hi: float, bins: int
) -> jax.Array:
    """Fixed-width binned histogram (Histogram stat parity). Values outside
    [lo, hi] clamp into the end bins, as the reference's Histogram does."""
    vf = v.astype(jnp.float32)
    idx = jnp.floor((vf - lo) / ((hi - lo) / bins)).astype(jnp.int32)
    idx = jnp.clip(idx, 0, bins - 1)
    w = mask.astype(jnp.int32)
    return jnp.zeros(bins, jnp.int32).at[idx].add(w)


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def masked_value_counts(codes: jax.Array, mask: jax.Array, vocab_size: int) -> jax.Array:
    """Per-dictionary-code counts (Frequency/TopK/Enumeration parity feed).
    Null codes (-1) and codes beyond the vocab are dropped."""
    valid = mask & (codes >= 0) & (codes < vocab_size)
    idx = jnp.clip(codes, 0, max(vocab_size - 1, 0))
    w = valid.astype(jnp.int32)
    return jnp.zeros(max(vocab_size, 1), jnp.int32).at[idx].add(w)


# -- grouped (segment) reductions: the device side of SQL GROUP BY ----------
# Parity: upstream runs GROUP BY aggregation in Spark after the relation
# scan (SURVEY.md:381-383 GeoMesaRelation); here the grouped reduction IS a
# device kernel — one masked segment reduction per aggregate, mergeable
# across shards by the same add/min/max laws the sketches use.


@functools.partial(jax.jit, static_argnames=("num_groups",))
def grouped_count(gids: jax.Array, mask: jax.Array, num_groups: int) -> jax.Array:
    return jax.ops.segment_sum(
        mask.astype(jnp.int64), gids, num_segments=num_groups
    )


@functools.partial(jax.jit, static_argnames=("num_groups",))
def grouped_sum(
    v: jax.Array, gids: jax.Array, mask: jax.Array, num_groups: int
) -> jax.Array:
    vf = jnp.where(mask, v.astype(jnp.float64), 0.0)
    return jax.ops.segment_sum(vf, gids, num_segments=num_groups)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def grouped_min(
    v: jax.Array, gids: jax.Array, mask: jax.Array, num_groups: int
) -> jax.Array:
    vf = jnp.where(mask, v.astype(jnp.float64), jnp.inf)
    return jax.ops.segment_min(vf, gids, num_segments=num_groups)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def grouped_max(
    v: jax.Array, gids: jax.Array, mask: jax.Array, num_groups: int
) -> jax.Array:
    vf = jnp.where(mask, v.astype(jnp.float64), -jnp.inf)
    return jax.ops.segment_max(vf, gids, num_segments=num_groups)


@functools.partial(jax.jit, static_argnames=("n_time_bins", "bins_per_dim"))
def z3_histogram(
    x: jax.Array,
    y: jax.Array,
    t_bin: jax.Array,
    mask: jax.Array,
    n_time_bins: int,
    bins_per_dim: int = 16,
) -> jax.Array:
    """Coarse (time-bin, x-cell, y-cell) occupancy counts (Z3Histogram
    parity): the planner's selectivity estimator for spatio-temporal cost."""
    cx = jnp.clip(
        jnp.floor((x + 180.0) / 360.0 * bins_per_dim).astype(jnp.int32),
        0,
        bins_per_dim - 1,
    )
    cy = jnp.clip(
        jnp.floor((y + 90.0) / 180.0 * bins_per_dim).astype(jnp.int32),
        0,
        bins_per_dim - 1,
    )
    tb = jnp.clip(t_bin, 0, n_time_bins - 1)
    flat = (tb * bins_per_dim + cy) * bins_per_dim + cx
    w = mask.astype(jnp.int32)
    out = jnp.zeros(n_time_bins * bins_per_dim * bins_per_dim, jnp.int32)
    return out.at[flat].add(w).reshape(n_time_bins, bins_per_dim, bins_per_dim)


def stats_sharded(mesh: Mesh, fn, *arrays):
    """Run a masked reduction per shard and psum-merge the results.

    `fn(*local_arrays)` must return a pytree of summable partials (counts,
    sums, histograms). For min/max use the component trick (negate) or
    dedicated lax collectives in a custom fn.
    """

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=tuple(P(SHARD_AXIS) for _ in arrays),
        out_specs=P(),
    )
    def run(*local):
        return jax.tree.map(lambda t: jax.lax.psum(t, SHARD_AXIS), fn(*local))

    return run(*arrays)
