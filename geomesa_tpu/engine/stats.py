"""Masked statistical reductions — the device side of StatsScan.

Parity: geomesa-index-api StatsScan + the Stat sketch evaluation hot path
(geomesa-utils stats) [upstream, unverified]. Each function is a pure masked
reduction over device columns producing small arrays that merge across shards
with psum/min/max — the collective analog of the reference's mergeable
sketches streaming from tablet servers. Host-side mergeable sketch *objects*
(Stat DSL, serialization) live in geomesa_tpu.stats; these kernels feed them.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map
from jax.sharding import Mesh, PartitionSpec as P

from geomesa_tpu.parallel.mesh import SHARD_AXIS


@jax.jit
def masked_count(mask: jax.Array) -> jax.Array:
    return jnp.sum(mask.astype(jnp.int64))


@jax.jit
def masked_minmax(v: jax.Array, mask: jax.Array) -> Tuple[jax.Array, jax.Array]:
    big = jnp.asarray(jnp.inf, jnp.float64)  # gt: f64-refine
    vf = v.astype(jnp.float64)  # gt: f64-refine
    return (
        jnp.min(jnp.where(mask, vf, big)),
        jnp.max(jnp.where(mask, vf, -big)),
    )


@jax.jit
def masked_moments(v: jax.Array, mask: jax.Array):
    """(count, sum, sum-of-squares) in f64 — exact merge across shards by
    adding components (DescriptiveStats parity)."""
    vf = jnp.where(mask, v.astype(jnp.float64), 0.0)  # gt: f64-refine
    return (
        jnp.sum(mask.astype(jnp.int64)),
        jnp.sum(vf),
        jnp.sum(vf * vf),
    )


@functools.partial(jax.jit, static_argnames=("bins",))
def masked_histogram(
    v: jax.Array, mask: jax.Array, lo: float, hi: float, bins: int
) -> jax.Array:
    """Fixed-width binned histogram (Histogram stat parity). Values outside
    [lo, hi] clamp into the end bins, as the reference's Histogram does."""
    vf = v.astype(jnp.float32)
    idx = jnp.floor((vf - lo) / ((hi - lo) / bins)).astype(jnp.int32)
    idx = jnp.clip(idx, 0, bins - 1)
    w = mask.astype(jnp.int32)
    return jnp.zeros(bins, jnp.int32).at[idx].add(w)


@functools.partial(jax.jit, static_argnames=("vocab_size",))
def masked_value_counts(codes: jax.Array, mask: jax.Array, vocab_size: int) -> jax.Array:
    """Per-dictionary-code counts (Frequency/TopK/Enumeration parity feed).
    Null codes (-1) and codes beyond the vocab are dropped."""
    valid = mask & (codes >= 0) & (codes < vocab_size)
    idx = jnp.clip(codes, 0, max(vocab_size - 1, 0))
    w = valid.astype(jnp.int32)
    return jnp.zeros(max(vocab_size, 1), jnp.int32).at[idx].add(w)


# -- device-side sketch observation (HLL registers, CMS rows) ----------------
# Parity: upstream's StatsScan evaluates the Stat sketches INSIDE the
# tablet-server iterator (SURVEY.md:266-274); round 2 still hashed on the
# host (~3.9s for a 67M HLL observation). These kernels run the identical
# FNV/fmix64 hash + fold pipeline on device and emit the tiny mergeable
# state (4 KB of registers / a [depth, width] table) for the host sketch
# objects to fold in — bit-identical to stats.sketches._hash64's numeric
# fast path, so device- and host-observed sketches merge losslessly.

# The hash family is PURE 32-bit (2x murmur32 fmix over the value's
# 32-bit halves, floats canonicalized via their f32 bit pattern) because
# the TPU x64 rewriter has no lowering for 64-bit bitcasts — mirrored
# bit-for-bit by stats.sketches._hash64_numeric (HASH_VERSION v2).

_M32_1 = 0x85EBCA6B
_M32_2 = 0xC2B2AE35


def _fmix32_dev(h: jax.Array) -> jax.Array:
    h = h ^ (h >> jnp.uint32(16))
    h = h * jnp.uint32(_M32_1)
    h = h ^ (h >> jnp.uint32(13))
    h = h * jnp.uint32(_M32_2)
    h = h ^ (h >> jnp.uint32(16))
    return h


def _halves_u32_dev(v: jax.Array):
    """(lo, hi) u32 halves — mirrors stats.sketches._halves_u32."""
    if v.dtype.kind == "f":
        lo = jax.lax.bitcast_convert_type(
            v.astype(jnp.float32), jnp.uint32
        )
        return lo, jnp.zeros_like(lo)
    iv = v.astype(jnp.int64)
    lo = (iv & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    hi = ((iv >> jnp.int64(32)) & jnp.int64(0xFFFFFFFF)).astype(jnp.uint32)
    return lo, hi


def _hash_pair_dev(v: jax.Array, seed: int):
    s1 = jnp.uint32((seed * 0x9E3779B9 + 0x165667B1) & 0xFFFFFFFF)
    s2 = jnp.uint32((seed * 0x85EBCA77 + 0x27D4EB2F) & 0xFFFFFFFF)
    lo, hi = _halves_u32_dev(v)
    h1 = _fmix32_dev(lo ^ _fmix32_dev(hi ^ s1))
    h2 = _fmix32_dev(h1 ^ hi ^ s2)
    return h1, h2


def _bit_length_u32_dev(x: jax.Array) -> jax.Array:
    """bit_length of u32 (0 -> 0) via the f32 exponent field — matches
    the host's float-conversion rounding (round-to-nearest on both
    sides), so ranks agree bit-for-bit."""
    f = x.astype(jnp.float32)
    exp = (
        (jax.lax.bitcast_convert_type(f, jnp.uint32) >> jnp.uint32(23))
        .astype(jnp.int32) & 0xFF
    )
    return jnp.where(x > 0, exp - 126, 0)


@functools.partial(jax.jit, static_argnames=("p",))
def hll_registers(v: jax.Array, mask: jax.Array, p: int = 12) -> jax.Array:
    """Masked HyperLogLog register fold on device -> [2^p] int32 ranks.

    Same index/rank rules as stats.sketches.Cardinality._observe_chunk
    over the v2 numeric hash: idx = top p bits of h1; rank = 1-based
    first-1-bit of the remaining 64-p bits of (h1, h2). Fold with
    Cardinality.observe_registers — registers agree bit-for-bit with a
    host observation of the same values, so max-merge is lossless."""
    m = 1 << p
    h1, h2 = _hash_pair_dev(v, 0)
    idx = (h1 >> jnp.uint32(32 - p)).astype(jnp.int32)
    # rest (as the host sees it): the u64 (h1<<32|h2) shifted left by p
    rest_hi = (h1 << jnp.uint32(p)) | (h2 >> jnp.uint32(32 - p))
    rest_lo = h2 << jnp.uint32(p)
    bl_hi = _bit_length_u32_dev(rest_hi)
    bl_lo = _bit_length_u32_dev(rest_lo)
    rank = jnp.where(
        rest_hi > 0,
        65 - (bl_hi + 32),
        jnp.where(rest_lo > 0, 65 - bl_lo, 64 - p + 1),
    )
    rank = jnp.where(mask, rank, 0).astype(jnp.int32)
    return jnp.zeros(m, jnp.int32).at[idx].max(rank, mode="drop")


@functools.partial(jax.jit, static_argnames=("width", "depth"))
def cms_table(
    v: jax.Array, mask: jax.Array, width: int = 1024, depth: int = 4
) -> jax.Array:
    """Masked Count-Min observation on device -> [depth, width] int32.

    NUMERIC-KEYED: rows hash the value's canonical pattern (seed d+1),
    the same v2 family as Frequency._cols on numeric input — fold with
    Frequency.observe_table (numeric_keys sketches only; the flag is
    enforced there and in merge/from_json). The column index matches the
    host's (h1*2^32 + h2) % width via modular arithmetic in i64."""
    w = jnp.where(mask, 1, 0).astype(jnp.int32)
    rows = []
    for d in range(depth):
        h1, h2 = _hash_pair_dev(v, d + 1)
        two32_mod = (1 << 32) % width
        col = (
            (h1.astype(jnp.int64) % width) * two32_mod
            + h2.astype(jnp.int64)
        ) % width
        rows.append(
            jnp.zeros(width, jnp.int32).at[col.astype(jnp.int32)].add(w)
        )
    return jnp.stack(rows)


# -- grouped (segment) reductions: the device side of SQL GROUP BY ----------
# Parity: upstream runs GROUP BY aggregation in Spark after the relation
# scan (SURVEY.md:381-383 GeoMesaRelation); here the grouped reduction IS a
# device kernel — one masked segment reduction per aggregate, mergeable
# across shards by the same add/min/max laws the sketches use.


@functools.partial(jax.jit, static_argnames=("num_groups",))
def grouped_count(gids: jax.Array, mask: jax.Array, num_groups: int) -> jax.Array:
    return jax.ops.segment_sum(
        mask.astype(jnp.int64), gids, num_segments=num_groups
    )


@functools.partial(jax.jit, static_argnames=("num_groups",))
def grouped_sum(
    v: jax.Array, gids: jax.Array, mask: jax.Array, num_groups: int
) -> jax.Array:
    vf = jnp.where(mask, v.astype(jnp.float64), 0.0)  # gt: f64-refine
    return jax.ops.segment_sum(vf, gids, num_segments=num_groups)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def grouped_min(
    v: jax.Array, gids: jax.Array, mask: jax.Array, num_groups: int
) -> jax.Array:
    vf = jnp.where(mask, v.astype(jnp.float64), jnp.inf)  # gt: f64-refine
    return jax.ops.segment_min(vf, gids, num_segments=num_groups)


@functools.partial(jax.jit, static_argnames=("num_groups",))
def grouped_max(
    v: jax.Array, gids: jax.Array, mask: jax.Array, num_groups: int
) -> jax.Array:
    vf = jnp.where(mask, v.astype(jnp.float64), -jnp.inf)  # gt: f64-refine
    return jax.ops.segment_max(vf, gids, num_segments=num_groups)


@functools.partial(jax.jit, static_argnames=("n_time_bins", "bins_per_dim"))
def z3_histogram(
    x: jax.Array,
    y: jax.Array,
    t_bin: jax.Array,
    mask: jax.Array,
    n_time_bins: int,
    bins_per_dim: int = 16,
) -> jax.Array:
    """Coarse (time-bin, x-cell, y-cell) occupancy counts (Z3Histogram
    parity): the planner's selectivity estimator for spatio-temporal cost."""
    cx = jnp.clip(
        jnp.floor((x + 180.0) / 360.0 * bins_per_dim).astype(jnp.int32),
        0,
        bins_per_dim - 1,
    )
    cy = jnp.clip(
        jnp.floor((y + 90.0) / 180.0 * bins_per_dim).astype(jnp.int32),
        0,
        bins_per_dim - 1,
    )
    tb = jnp.clip(t_bin, 0, n_time_bins - 1)
    flat = (tb * bins_per_dim + cy) * bins_per_dim + cx
    w = mask.astype(jnp.int32)
    out = jnp.zeros(n_time_bins * bins_per_dim * bins_per_dim, jnp.int32)
    return out.at[flat].add(w).reshape(n_time_bins, bins_per_dim, bins_per_dim)


def stats_sharded(mesh: Mesh, fn, *arrays):
    """Run a masked reduction per shard and psum-merge the results.

    `fn(*local_arrays)` must return a pytree of summable partials (counts,
    sums, histograms). For min/max use the component trick (negate) or
    dedicated lax collectives in a custom fn.
    """

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=tuple(P(SHARD_AXIS) for _ in arrays),
        out_specs=P(),
    )
    def run(*local):
        return jax.tree.map(lambda t: jax.lax.psum(t, SHARD_AXIS), fn(*local))

    return run(*arrays)
