"""Fused scan kNN: Pallas chord-key block-minima + deferred block refine.

Parity role: the server-side scan half of KNearestNeighborSearchProcess
(geomesa-process knn/) — the reference streams index-scan hits through a
per-tablet iterator and merges client-side; here ONE fused device pass
scans the whole candidate batch (SURVEY.md §5.7 feature-set scaling).

Why these kernels exist (measured on v5e, 67M points, 256 queries):
the XLA path (`knn_compact`) pays three separate HBM regimes —
  1. flat `lax.top_k` stream compaction over 67M lanes   ~180 ms
  2. element gather of 4.2M matched rows                  ~90 ms
  3. `knn_mxu`'s scan, whose [Q, data_tile] ranking-key
     matmul output round-trips HBM every fold step       ~20 ms/4.2M
                                                         (~320 ms at 67M)
The dense kernel (`knn_fullscan`) replaces all three with the
flash-attention access pattern: stream coordinate tiles through VMEM,
compute the centered chord ranking key (MXU matmul, K=4) IN VMEM, reduce
each BLK-lane block to its minimum, and emit only the [Q, N/BLK] minima:

  minima = pallas_scan(x, y, maskf)             # one HBM pass, fused
  blocks = two-level top-m over minima          # m winning blocks/query
  refine = exact haversine over m*BLK gathered  # block-granular gather —
           lanes -> top-k                       # measured as fast as a
                                                # contiguous copy

Its wall is the MXU OUTPUT RATE, not HBM: [Q=256] x [N=67M] keys at ~128
results/cycle is ~134 M cycles (~140 ms @ 0.94 GHz) no matter how the
reduction is tuned (measured 122 ms with the VPU reduction overlapped).
Brute force is therefore Q-bound, which is what the SPARSE kernel
(`knn_sparse_scan`) attacks: a scalar-prefetched list of match-bearing
data tiles drives the BlockSpec index maps, so unselected tiles never
leave HBM and the MXU bound scales with sum(selected tiles) instead of N.
On store-ordered (Z-sorted) batches a bbox predicate touches ~selectivity
fraction of tiles; on randomly-ordered batches it degrades to the dense
cost plus one cheap pass (every tile holds a match).

Exactness (both kernels): identical argument to knn_mxu's deferred block
selection — if a true top-m element's block were unpicked, the m picked
blocks each hold an element with key <= it, so its rank exceeds m >= k
(m_blocks >= k is REQUIRED and checked at trace time). The final k always
comes from exact haversine over the gathered candidates, and the
guarantee is noise-independent: it needs only a per-row-monotonic ranking
key, which any f32 rounding of chord^2 still is within each block's min.

The ranking key is the centered augmented form (knn_mxu's derivation):
  key(q, d) = |d-c|^2 - 2 (q-c).(d-c) + (1-mask) * 1e9
monotonic in chord^2 within a query row; c = the query set's mean unit
vector, so f32 resolution scales with distance-from-centroid.

Mosaic constraints that shaped the code (each cost a compile attempt):
64-bit anything is rejected -> trace under jax.enable_x64(False); output
block lane dims must be >=128 or the full array -> DATA_TILE/BLK = 128;
dynamic (fori_loop-indexed) sub-128-lane vector stores don't legalize ->
the chunk sweep is a PYTHON loop (static store offsets), and >8 unrolled
bodies send Mosaic compile time past 10 minutes -> DATA_TILE/CHUNK = 4.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import enable_x64 as _enable_x64
from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map
import numpy as np

from geomesa_tpu.engine.geodesy import haversine_m
from geomesa_tpu.engine.knn import _topk_smallest, _twolevel_smallest, _unit3

BLK = 128  # minima granularity: one minimum per BLK data lanes
DATA_TILE = 16384  # lanes per pallas program (output block [Q, 128])
CHUNK = 4096  # key-matrix chunk inside the kernel ([Q, CHUNK] in VMEM)
PENALTY = 1e9  # additive key for masked rows (|key| <= 12 for real rows)


def _chunk_body(aug_q, cx, cy, cz, x_ref, y_ref, m_ref, out_ref, s: int,
                chunk: int, blk: int, extra: float = 0.0):
    """One static chunk: unit vectors + MXU key + blk-lane minima."""
    q = aug_q.shape[0]
    sl = slice(s * chunk, (s + 1) * chunk)
    rlon = jnp.radians(x_ref[0, sl])  # [chunk]
    rlat = jnp.radians(y_ref[0, sl])
    cl = jnp.cos(rlat)
    dx = cl * jnp.cos(rlon) - cx
    dy = cl * jnp.sin(rlon) - cy
    dz = jnp.sin(rlat) - cz
    nd = dx * dx + dy * dy + dz * dz
    ndm = nd + (1.0 - m_ref[0, sl]) * PENALTY + extra  # [chunk]

    # [Q, 4] x [4, chunk] on the MXU: key = ndm - 2 (q-c).(d-c)
    aug_d = jnp.stack([dx, dy, dz, ndm])  # [4, chunk]
    key = jnp.dot(
        aug_q, aug_d,
        preferred_element_type=jnp.float32,
        precision=jax.lax.Precision.HIGHEST,
    )  # [Q, chunk]
    nb = chunk // blk
    out_ref[:, s * nb: (s + 1) * nb] = key.reshape(q, nb, blk).min(axis=-1)


def _make_kernel(data_tile: int, chunk: int, blk: int):
    def _scan_kernel(aug_q_ref, c_ref, x_ref, y_ref, m_ref, out_ref):
        aug_q = aug_q_ref[...]
        cx = c_ref[0, 0]
        cy = c_ref[0, 1]
        cz = c_ref[0, 2]
        # the [Q, data_tile] key matrix would blow VMEM, so the tile is
        # swept in chunk-lane slices (static Python loop — see module
        # docstring for why not fori_loop)
        for s in range(data_tile // chunk):
            _chunk_body(aug_q, cx, cy, cz, x_ref, y_ref, m_ref, out_ref,
                        s, chunk, blk)

    return _scan_kernel


def chord_blockmin(
    qx: jax.Array,
    qy: jax.Array,
    x: jax.Array,
    y: jax.Array,
    maskf: jax.Array,
    blk: int = BLK,
    data_tile: int = DATA_TILE,
    chunk: int = CHUNK,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """One fused pass: [Q] queries x [N] points -> ([Q, N/blk] block
    minima of the centered chord ranking key, [3] centroid). N must be a
    multiple of data_tile; maskf is the predicate mask as f32 0/1."""
    from jax.experimental import pallas as pl

    n = x.shape[0]
    q = qx.shape[0]
    assert n % data_tile == 0, (n, data_tile)
    chunk = min(chunk, data_tile)
    assert data_tile % chunk == 0 and chunk % blk == 0, (
        data_tile, chunk, blk)
    qu = _unit3(qx, qy)  # [Q, 3]
    c = qu.mean(axis=0)  # [3]
    qc = qu - c
    aug_q = jnp.concatenate([-2.0 * qc, jnp.ones((q, 1), jnp.float32)], 1)
    carr = jnp.zeros((1, 128), jnp.float32).at[0, :3].set(c)

    grid = (n // data_tile,)
    out_lanes = data_tile // blk
    # Mosaic rejects 64-bit types; trace with x64 off so index-map and
    # in-kernel literals stay i32/f32 under the repo's global x64 mode
    with _enable_x64(False):
        minima = pl.pallas_call(
            _make_kernel(data_tile, chunk, blk),
            grid=grid,
            in_specs=[
                pl.BlockSpec((q, 4), lambda j: (0, 0)),
                pl.BlockSpec((1, 128), lambda j: (0, 0)),
                pl.BlockSpec((1, data_tile), lambda j: (0, j)),
                pl.BlockSpec((1, data_tile), lambda j: (0, j)),
                pl.BlockSpec((1, data_tile), lambda j: (0, j)),
            ],
            out_specs=pl.BlockSpec((q, out_lanes), lambda j: (0, j)),
            out_shape=jax.ShapeDtypeStruct((q, n // blk), jnp.float32),
            interpret=interpret,
        )(aug_q, carr, x.reshape(1, n), y.reshape(1, n), maskf.reshape(1, n))
    return minima, c


def _make_sparse_kernel(data_tile: int, chunk: int, blk: int):
    """Program p processes the data tile named by the scalar-prefetched
    `ids` array; programs past `nsel` (capacity padding) emit PENALTY
    without touching the MXU."""

    def _kernel(ids_ref, nsel_ref, aug_q_ref, c_ref, x_ref, y_ref, m_ref,
                out_ref):
        from jax.experimental import pallas as pl

        p = pl.program_id(0)

        @pl.when(p < nsel_ref[0])
        def _live():
            aug_q = aug_q_ref[...]
            cx = c_ref[0, 0]
            cy = c_ref[0, 1]
            cz = c_ref[0, 2]
            for s in range(data_tile // chunk):
                _chunk_body(aug_q, cx, cy, cz, x_ref, y_ref, m_ref,
                            out_ref, s, chunk, blk)

        @pl.when(p >= nsel_ref[0])
        def _dead():
            out_ref[...] = jnp.full_like(out_ref, PENALTY)

    return _kernel


def chord_blockmin_sparse(
    qx: jax.Array,
    qy: jax.Array,
    x: jax.Array,
    y: jax.Array,
    maskf: jax.Array,
    tile_ids: jax.Array,
    n_sel: jax.Array,
    blk: int = BLK,
    data_tile: int = DATA_TILE,
    chunk: int = CHUNK,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Sparse block-minima: only the data tiles named by `tile_ids` are
    scanned. tile_ids is a static-capacity [C] int32 array (entries past
    `n_sel` are ignored — their minima come out as +PENALTY). Returns
    ([Q, C * data_tile/blk] minima over the SELECTED tiles in tile_ids
    order, [3] centroid)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = x.shape[0]
    q = qx.shape[0]
    assert n % data_tile == 0, (n, data_tile)
    chunk = min(chunk, data_tile)
    cap = tile_ids.shape[0]
    qu = _unit3(qx, qy)
    c = qu.mean(axis=0)
    qc = qu - c
    aug_q = jnp.concatenate([-2.0 * qc, jnp.ones((q, 1), jnp.float32)], 1)
    carr = jnp.zeros((1, 128), jnp.float32).at[0, :3].set(c)
    out_lanes = data_tile // blk

    with _enable_x64(False):
        grid_spec = pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,  # tile_ids, n_sel
            grid=(cap,),
            in_specs=[
                pl.BlockSpec((q, 4), lambda p, ids, ns: (0, 0)),
                pl.BlockSpec((1, 128), lambda p, ids, ns: (0, 0)),
                pl.BlockSpec((1, data_tile), lambda p, ids, ns: (0, ids[p])),
                pl.BlockSpec((1, data_tile), lambda p, ids, ns: (0, ids[p])),
                pl.BlockSpec((1, data_tile), lambda p, ids, ns: (0, ids[p])),
            ],
            out_specs=pl.BlockSpec(
                (q, out_lanes), lambda p, ids, ns: (0, p)
            ),
        )
        minima = pl.pallas_call(
            _make_sparse_kernel(data_tile, chunk, blk),
            grid_spec=grid_spec,
            out_shape=jax.ShapeDtypeStruct((q, cap * out_lanes), jnp.float32),
            interpret=interpret,
        )(
            tile_ids.astype(jnp.int32),
            jnp.asarray(n_sel, jnp.int32).reshape(1),
            aug_q, carr,
            x.reshape(1, n), y.reshape(1, n), maskf.reshape(1, n),
        )
    return minima, c


def _refine(qx, qy, xf, yf, maskf, orig_blk, n, k, blk, blk_ok=None):
    """Exact haversine over the selected blocks' lanes -> top-k.
    Block-granular gather: rows of blk contiguous lanes (measured as fast
    as a contiguous copy; element gathers are ~50x slower). `blk_ok`
    [Q, mb] masks out selected blocks that are capacity-padding artifacts
    (sparse scan: dead slots alias data tile 0 and would otherwise
    DUPLICATE tile-0 lanes in the pool)."""
    q = qx.shape[0]
    mb = orig_blk.shape[1]
    nb = xf.shape[0] // blk
    xb = xf.reshape(nb, blk)
    yb = yf.reshape(nb, blk)
    vb = maskf.reshape(nb, blk) > 0.5
    gx = jnp.take(xb, orig_blk, axis=0).reshape(q, mb * blk)
    gy = jnp.take(yb, orig_blk, axis=0).reshape(q, mb * blk)
    gv = jnp.take(vb, orig_blk, axis=0).reshape(q, mb * blk)
    if blk_ok is not None:
        gv = gv & jnp.repeat(blk_ok, blk, axis=1)
    lane = (orig_blk[:, :, None] * blk + jnp.arange(blk, dtype=jnp.int32)
            ).reshape(q, mb * blk)

    d = haversine_m(
        qx[:, None].astype(jnp.float32), qy[:, None].astype(jnp.float32),
        gx, gy,
    )
    d = jnp.where(gv & (lane < n), d, jnp.float32(jnp.inf))
    fd, sel = _topk_smallest(d, k)
    fi = jnp.minimum(jnp.take_along_axis(lane, sel, axis=1), n - 1)
    return fd, fi


@functools.partial(
    jax.jit,
    static_argnames=("k", "m_blocks", "blk", "data_tile", "interpret"),
)
def knn_fullscan(
    qx: jax.Array,
    qy: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    k: int,
    m_blocks: int = 64,
    blk: int = BLK,
    data_tile: int = DATA_TILE,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN over the masked batch in one fused dense scan (no
    compaction, no capacity, no host round trip). Same contract as `knn`:
    returns (dists [Q, k] meters, indices [Q, k] into the original
    arrays). m_blocks >= k required (see module docstring); N is padded
    to data_tile internally (padded lanes masked out)."""
    n = x.shape[0]
    q = qx.shape[0]
    if k > m_blocks:  # trace-time contract: exactness needs m >= k
        raise ValueError(
            f"k={k} exceeds m_blocks={m_blocks}: the deferred block "
            "selection only guarantees the top-m_blocks elements"
        )
    pad = (-n) % data_tile
    xf = jnp.pad(x.astype(jnp.float32), (0, pad))
    yf = jnp.pad(y.astype(jnp.float32), (0, pad))
    maskf = jnp.pad(mask.astype(jnp.float32), (0, pad))
    npad = n + pad

    minima, _ = chord_blockmin(
        qx, qy, xf, yf, maskf,
        blk=blk, data_tile=data_tile, interpret=interpret,
    )
    mb = min(m_blocks, npad // blk)
    _, blkid = _twolevel_smallest(minima, mb)  # [Q, mb]
    return _refine(qx, qy, xf, yf, maskf, blkid, n, k, blk)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "m_blocks", "blk", "data_tile", "tile_capacity", "interpret"
    ),
)
def knn_sparse_scan(
    qx: jax.Array,
    qy: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    k: int,
    tile_capacity: int,
    m_blocks: int = 64,
    blk: int = BLK,
    data_tile: int = DATA_TILE,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Exact kNN over the masked batch scanning ONLY data tiles that hold
    at least one match. Same contract as `knn` plus an overflow flag:
    (dists [Q, k], indices [Q, k], overflow bool scalar).

    The win is proportional to match clustering: on store-ordered
    (Z-sorted) batches a bbox predicate selects a contiguous ~selectivity
    fraction of tiles; on randomly-ordered batches nearly every tile has
    a match and this degrades to the dense kernel plus one cheap pass.
    `tile_capacity` is the static bound on selected tiles (callers bucket
    it pow2 from the planner's selectivity estimate — overshoot is cheap,
    dead programs skip the MXU); if more tiles match, `overflow` is True,
    the top-k silently ignored the highest-id matching tiles, and the
    caller MUST fall back (knn_fullscan). m_blocks >= k required."""
    n = x.shape[0]
    if k > m_blocks:
        raise ValueError(
            f"k={k} exceeds m_blocks={m_blocks}: the deferred block "
            "selection only guarantees the top-m_blocks elements"
        )
    pad = (-n) % data_tile
    xf = jnp.pad(x.astype(jnp.float32), (0, pad))
    yf = jnp.pad(y.astype(jnp.float32), (0, pad))
    maskf = jnp.pad(mask.astype(jnp.float32), (0, pad))
    npad = n + pad
    ntiles = npad // data_tile
    tile_capacity = min(tile_capacity, ntiles)

    # matching tiles (ascending ids), static capacity
    tmatch = maskf.reshape(ntiles, data_tile).max(axis=1) > 0.0
    n_sel = jnp.sum(tmatch.astype(jnp.int32))
    overflow = n_sel > tile_capacity
    picked = jax.lax.top_k(
        jnp.where(tmatch, -jnp.arange(ntiles, dtype=jnp.int32),
                  -(1 << 30)),
        tile_capacity,
    )[0]
    tile_ids = jnp.where(picked > -(1 << 30), -picked, 0)

    minima, _ = chord_blockmin_sparse(
        qx, qy, xf, yf, maskf, tile_ids, n_sel,
        blk=blk, data_tile=data_tile, interpret=interpret,
    )
    bpt = data_tile // blk  # blocks per tile
    mb = min(m_blocks, minima.shape[1])
    vals, selblk = _twolevel_smallest(minima, mb)  # [Q, mb] minima space
    # minima-space block -> original block id. Dead capacity-padding
    # programs emit exactly PENALTY and alias data tile 0 — a selected
    # block is real only if its minimum is below the mask penalty (real
    # matched blocks carry keys <= 12; all-masked and dead blocks >= 1e9)
    blk_ok = vals < jnp.float32(PENALTY / 2)
    orig_blk = jnp.take(tile_ids, selblk // bpt) * bpt + selblk % bpt
    fd, fi = _refine(qx, qy, xf, yf, maskf, orig_blk, n, k, blk,
                     blk_ok=blk_ok)
    return fd, fi, overflow


# f32 scan-ranking error budget (round 5, VERDICT r4 task 10): the fused
# scan ranks by f32 haversine (d = 2R asin(sqrt(a))) over f32-rounded
# coordinates. |d_f32 - d_f64(original coords)| at true distance d:
#   - coordinate rounding: one lat/lon ulp at |coord|<=360 is 2^-24*256 ~
#     1.5e-5 deg ~ 1.7 m of ground shift per endpoint -> ~4 m absolute;
#   - f32 arithmetic in `a`: ~relative error REL_A in a, AMPLIFIED by
#     dd/da = 2R/sin(d/R) — near the antipode sin(d/R) -> 0 and the
#     error reaches km scale (review finding: empirically ~3.9 km at
#     100 km short of the antipode; a flat 4 m + 1e-5*d model falsely
#     certified there). err_m(d) models exactly that amplification:
#     2R*REL_A*sin^2(d/2R)/sin(d/R), which reduces to (REL_A/2)*d for
#     small d and covers the measured antipodal blowup with ~4x margin.
KNN_F32_ABS_M = 4.0
KNN_F32_REL_A = 1e-5  # ~160 ulps of `a` — deliberately loose
_R_EARTH_M = 6_371_000.0


def knn_f32_err_m(d):
    """Upper bound on |f32 scan distance - f64 true distance| at true
    distance d meters (see the model above). Monotone increasing on
    [0, pi*R), which the certificate in knn_exact_refine relies on."""
    d = np.asarray(d, np.float64)
    half = d / (2.0 * _R_EARTH_M)
    s = np.sin(np.clip(2.0 * half, 0.0, np.pi))
    amp = np.where(
        s > 1e-9,
        2.0 * _R_EARTH_M * KNN_F32_REL_A * np.sin(half) ** 2 / s,
        np.inf,  # at/after the antipode nothing is certifiable
    )
    return KNN_F32_ABS_M + amp


def knn_exact_refine(qx_np, qy_np, x_np, y_np, fd, fi, k):
    """Band-refine at the k-th boundary: f64 re-ranking of the k' > k
    candidates a kernel returned, with a certificate that the TRUE top-k
    (by f64 haversine over the ORIGINAL f64 coordinates) lies inside the
    candidate set.

    Args: query/data coords as f64 numpy; fd/fi [Q, k'] f32 distances +
    indices from any scan kernel run with k' = k + pad. Returns
    (d64 [Q, k] sorted, idx [Q, k], certified [Q] bool).

    Certificate: a row NOT returned has f32 distance >= L := the largest
    returned f32 distance. A missed row with true distance D <= B (the
    refined k-th distance, exact f64) would need its f32 distance pushed
    from <= B + err(B) up to >= L (err monotone increasing), so
    L > B + err_m(B) proves no true top-k member was missed. The bound
    decertifies antipodal boundaries by construction — err_m blows up
    exactly where f32 haversine does. Uncertified rows need a caller
    fallback (wider pad or full rescan)."""
    from geomesa_tpu.engine.geodesy import haversine_m_np

    fd = np.asarray(fd)
    fi = np.asarray(fi)
    Q, kp = fd.shape
    assert kp >= k
    d64 = np.empty((Q, kp))
    for i in range(Q):
        d64[i] = np.where(
            np.isfinite(fd[i]),
            haversine_m_np(qx_np[i], qy_np[i], x_np[fi[i]], y_np[fi[i]]),
            np.inf,
        )
    order = np.argsort(d64, axis=1, kind="stable")[:, :k]
    dists = np.take_along_axis(d64, order, axis=1)
    idx = np.take_along_axis(fi, order, axis=1)
    # an inf anywhere in fd means fewer than k' matches exist, so nothing
    # was cut off: L=inf certifies those rows through the same comparison
    L = np.where(np.isfinite(fd).all(1), fd.max(1), np.inf)
    B = dists[:, -1]
    with np.errstate(invalid="ignore"):
        certified = (L > B + knn_f32_err_m(B)) | ~np.isfinite(B)
    return dists, idx, certified


# -- ring-loop kernel variants (docs/SERVING.md "Persistent serve loop") ----
# The persistent serve loop dispatches ONE long-lived executable per
# (kernel, bucket, dtype, mesh_shape) and feeds it query slots from a
# fixed ring of staging buffers. These raw (un-jitted) callables are the
# forms the ExecutableRegistry's ring tier compiles for it: argnums 0/1
# (the slot's qx/qy) are the ONLY per-window inputs — the feature-set
# arguments (x, y, mask) are pre-bound device references the ring
# program re-passes unchanged every window, so XLA sees a stable
# parameter layout and (with donation, non-CPU) reuses the slot HBM
# across windows. The math is knn_sparse_scan / knn_fullscan_tiled
# exactly — a distinct callable only so the ring registration can carry
# its own donation contract without re-keying the base kernels.


def knn_ring_scan(qx, qy, x, y, mask, k, tile_capacity, m_blocks,
                  interpret):
    """Slot-parameterized sparse scan for the ring tier (see above).
    Same contract as `knn_sparse_scan`: (dists, idx, overflow)."""
    return knn_sparse_scan(
        qx, qy, x, y, mask, k=k, tile_capacity=tile_capacity,
        m_blocks=m_blocks, interpret=interpret)


def knn_ring_fullscan(qx, qy, x, y, mask, k, m_blocks, interpret):
    """Slot-parameterized dense scan for the ring tier (see above).
    Same contract as `knn_fullscan_tiled`: (dists, idx)."""
    return knn_fullscan_tiled(
        qx, qy, x, y, mask, k=k, m_blocks=m_blocks, interpret=interpret)


def default_interpret() -> bool:
    """Pallas interpret mode when the default device is CPU (Mosaic
    kernels lower only on TPU) — used by product paths that run the same
    code in CI (virtual CPU devices) and on hardware."""
    return jax.devices()[0].platform == "cpu"


@functools.partial(jax.jit, static_argnames=("data_tile",))
def count_match_tiles(mask: jax.Array, data_tile: int = DATA_TILE):
    """Device count of match-bearing data tiles (the planner's capacity
    calibration input — one i32 scalar crosses the tunnel, not the mask)."""
    n = mask.shape[0]
    pad = (-n) % data_tile
    mf = jnp.pad(mask.astype(jnp.int32), (0, pad))
    return jnp.sum(
        (mf.reshape(-1, data_tile).max(axis=1) > 0).astype(jnp.int32)
    )


def capacity_bucket(tiles_hit: int, slack: float = 1.25,
                    floor: int = 64) -> int:
    """pow2 capacity bucket from a tiles-hit measurement/estimate: slack
    absorbs drift between calibration and the live query (overshoot is
    cheap — dead capacity programs skip the MXU), pow2 keeps the pallas
    jit cache stable across queries."""
    need = max(int(tiles_hit * slack), 1)
    return max(floor, 1 << int(np.ceil(np.log2(need))))


def knn_sparse_launch(
    qx: jax.Array,
    qy: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    k: int,
    tile_capacity: "int | None" = None,
    m_blocks: int = 64,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array, int]:
    """Async half of `knn_sparse_auto`: calibrate capacity if the caller
    has no estimate (one device scalar fetch — the only sync here), then
    DISPATCH the sparse scan and return device-resident
    (dists, idx, overflow, tile_capacity) without reading anything back.
    JAX's async dispatch means the kernel executes while the caller's
    host thread moves on — the serve pipeline launches window N+1's
    transfer behind this. `knn_sparse_finish` completes the contract."""
    if tile_capacity is None:
        tile_capacity = capacity_bucket(int(np.asarray(
            count_match_tiles(mask))))
    fd, fi, ov = knn_sparse_scan(
        qx, qy, x, y, mask, k=k, tile_capacity=tile_capacity,
        m_blocks=m_blocks, interpret=interpret,
    )
    return fd, fi, ov, tile_capacity


def knn_sparse_finish(
    fd, fi, ov,
    qx: jax.Array,
    qy: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    k: int,
    tile_capacity: int,
    m_blocks: int = 64,
    interpret: bool = False,
    extra=(),
) -> tuple:
    """Sync half: ONE transfer for results + overflow flag (+ any
    `extra` device values riding the same fetch — the serve path's fused
    count scalar), falling back to the dense fullscan on overflow
    exactly like `knn_sparse_auto`. Returns
    (dists np, idx np, capacity_used, extra_host tuple)."""
    # ONE transfer: fetching ov alone first would serialize a second
    # tunnel round trip (~110 ms on the remote platform) before the
    # caller's own result fetch
    fd, fi, ov, *extra_host = jax.device_get((fd, fi, ov) + tuple(extra))
    if bool(ov):
        fd, fi = jax.device_get(knn_fullscan(
            qx, qy, x, y, mask, k=k, m_blocks=m_blocks,
            interpret=interpret))
        return fd, fi, -1, tuple(extra_host)
    return fd, fi, tile_capacity, tuple(extra_host)


def knn_sparse_auto(
    qx: jax.Array,
    qy: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    k: int,
    tile_capacity: "int | None" = None,
    m_blocks: int = 64,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, int]:
    """The framework-facing sparse kNN: calibrate capacity if the caller
    has no estimate (one device scalar fetch), run the sparse scan, and
    on overflow fall back to the dense fullscan (documented contract of
    `knn_sparse_scan`). Returns (dists, idx, capacity_used) with dists/
    idx as HOST numpy arrays (results and the overflow flag come back in
    one transfer). Callers cache capacity_used across queries and only
    pay calibration again after an overflow (capacity_used == -1 signals
    the fallback ran, so the next query recalibrates). Composed from the
    launch/finish halves so the serial path and the serve pipeline run
    byte-identical kernel sequences."""
    fd, fi, ov, tile_capacity = knn_sparse_launch(
        qx, qy, x, y, mask, k=k, tile_capacity=tile_capacity,
        m_blocks=m_blocks, interpret=interpret,
    )
    fd, fi, cap, _ = knn_sparse_finish(
        fd, fi, ov, qx, qy, x, y, mask, k=k, tile_capacity=tile_capacity,
        m_blocks=m_blocks, interpret=interpret,
    )
    return fd, fi, cap


def knn_sparse_sharded(
    mesh,
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    tile_capacity: int,
    m_blocks: int = 64,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """`knn_sparse_scan` under the data-sharded all_gather merge (same
    shape as `knn.knn_compact_sharded`): each shard scans only its own
    match-bearing tiles (static per-shard `tile_capacity`), per-shard
    top-ks merge exactly. Returns (dists [Q,k], global indices [Q,k],
    overflow — True if ANY shard overflowed its tile capacity, in which
    case the caller MUST fall back to a dense sharded scan)."""
    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.engine.knn import _topk_smallest
    from geomesa_tpu.parallel.mesh import SHARD_AXIS

    d_count = mesh.devices.size
    shard_n = dx.shape[0] // d_count

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False,  # post-gather re-top-k replicated (see knn_sharded)
    )
    def run(qx, qy, dx, dy, mask):
        fd, fi, ov = knn_sparse_scan(
            qx, qy, dx, dy, mask, k=k, tile_capacity=tile_capacity,
            m_blocks=m_blocks, interpret=interpret,
        )
        shard = jax.lax.axis_index(SHARD_AXIS)
        gidx = fi + shard * shard_n
        all_d = jax.lax.all_gather(fd, SHARD_AXIS)
        all_i = jax.lax.all_gather(gidx, SHARD_AXIS)
        pool_d = jnp.moveaxis(all_d, 0, 1).reshape(fd.shape[0], -1)
        pool_i = jnp.moveaxis(all_i, 0, 1).reshape(fd.shape[0], -1)
        md, mi = _topk_smallest(pool_d, k)
        gi = jnp.take_along_axis(pool_i, mi, axis=1)
        ov_any = jnp.any(jax.lax.all_gather(ov, SHARD_AXIS))
        return md, gi, ov_any

    return run(qx, qy, dx, dy, mask)


def shard_match_tiles(mask: jax.Array, n_shards: int,
                      data_tile: int = DATA_TILE) -> jax.Array:
    """MAX over shards of the per-shard match-bearing tile count — the
    serve mesh path's capacity calibration input (one i32 scalar crosses
    the tunnel, exactly like `count_match_tiles` on the serial path).
    Each shard pads its rows to `data_tile` independently inside
    `knn_sparse_scan`, so the per-shard tiling here mirrors that."""
    n = mask.shape[0]
    s = n // n_shards
    pad = (-s) % data_tile
    m = mask.astype(jnp.int32).reshape(n_shards, s)
    if pad:
        m = jnp.pad(m, ((0, 0), (0, pad)))
    per_shard = jnp.sum(
        (m.reshape(n_shards, -1, data_tile).max(axis=2) > 0)
        .astype(jnp.int32), axis=1)
    return jnp.max(per_shard)


def _shard_merge_topk(fd, fi, shard_n: int, k: int):
    """The mesh-serving merge epilogue, shared by the sparse program
    and its fullscan overflow fallback (a divergence here would break
    the bit-identity contract exactly on the rarely-taken overflow
    path): local indices lift to global (`local + shard * shard_n` —
    the mesh superbatch keeps the serial layout, so the global index
    IS the serial index), every shard's top-k pools via all_gather,
    and one re-top-k picks the global k-smallest."""
    import jax

    from geomesa_tpu.engine.knn import _topk_smallest
    from geomesa_tpu.parallel.mesh import SHARD_AXIS

    shard = jax.lax.axis_index(SHARD_AXIS)
    gidx = fi + shard * shard_n
    all_d = jax.lax.all_gather(fd, SHARD_AXIS)
    all_i = jax.lax.all_gather(gidx, SHARD_AXIS)
    pool_d = jnp.moveaxis(all_d, 0, 1).reshape(fd.shape[0], -1)
    pool_i = jnp.moveaxis(all_i, 0, 1).reshape(fd.shape[0], -1)
    md, mi = _topk_smallest(pool_d, k)
    gi = jnp.take_along_axis(pool_i, mi, axis=1)
    return md, gi


def make_knn_serve_sharded(mesh):
    """Build the mesh-serving kNN program for `mesh` (docs/SERVING.md
    "Sharded serving"): ONE shard_map program in which every chip runs
    `knn_sparse_scan` over its own resident rows, per-shard top-ks merge
    via all_gather + re-top-k, the overflow flags OR-reduce, and (when
    `want_count` is set) the cross-kind fused COUNT psum-reduces over
    ICI — the paper's "batched JAX reductions with psum over ICI"
    shape. Global indices are `local + shard * shard_rows`, which under
    the mesh superbatch's serial-layout contract makes results
    bit-identical to the single-chip kernel (tests/test_mesh_serve.py).

    Returns a plain callable suitable for ExecutableRegistry
    registration (`registry.mesh_variant`); statics are keyword-only so
    the AOT key covers (bucket, dtype, k, capacity, mesh shape)."""
    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.parallel.mesh import SHARD_AXIS

    d_count = int(mesh.devices.size)

    def run(qx, qy, x, y, mask, k, tile_capacity, m_blocks,
            want_count, interpret):
        shard_n = x.shape[0] // d_count

        @functools.partial(
            _shard_map,
            mesh=mesh,
            in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS),
                      P(SHARD_AXIS)),
            out_specs=((P(), P(), P(), P()) if want_count
                       else (P(), P(), P())),
            check_vma=False,  # post-gather re-top-k replicated
        )
        def body(qx, qy, lx, ly, lm):
            fd, fi, ov = knn_sparse_scan(
                qx, qy, lx, ly, lm, k=k, tile_capacity=tile_capacity,
                m_blocks=m_blocks, interpret=interpret,
            )
            md, gi = _shard_merge_topk(fd, fi, shard_n, k)
            ov_any = jnp.any(jax.lax.all_gather(ov, SHARD_AXIS))
            if want_count:
                cnt = jax.lax.psum(
                    jnp.sum(lm, dtype=jnp.int64), SHARD_AXIS)
                return md, gi, ov_any, cnt
            return md, gi, ov_any

        return body(qx, qy, x, y, mask)

    return run


def make_knn_fullscan_sharded(mesh):
    """Dense mesh fallback for `make_knn_serve_sharded`'s overflow
    contract: each chip runs the exact `knn_fullscan` over its rows;
    the merge is identical. Per-pair distances are the same f32
    haversine the serial fallback computes, so the overflow path stays
    bit-identical too."""
    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.parallel.mesh import SHARD_AXIS

    d_count = int(mesh.devices.size)

    def run(qx, qy, x, y, mask, k, m_blocks, interpret):
        shard_n = x.shape[0] // d_count

        @functools.partial(
            _shard_map,
            mesh=mesh,
            in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS),
                      P(SHARD_AXIS)),
            out_specs=(P(), P()),
            check_vma=False,
        )
        def body(qx, qy, lx, ly, lm):
            fd, fi = knn_fullscan(
                qx, qy, lx, ly, lm, k=k, m_blocks=m_blocks,
                interpret=interpret,
            )
            return _shard_merge_topk(fd, fi, shard_n, k)

        return body(qx, qy, x, y, mask)

    return run


def knn_fullscan_tiled(
    qx: jax.Array,
    qy: jax.Array,
    x: jax.Array,
    y: jax.Array,
    mask: jax.Array,
    k: int,
    m_blocks: int = 64,
    query_tile: int = 256,
    interpret: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """knn_fullscan for arbitrary Q: queries processed in centroid-centered
    tiles of `query_tile` (each tile re-scans the batch — the scan is one
    HBM pass, so wall time scales with ceil(Q/query_tile))."""
    q = qx.shape[0]
    if q <= query_tile:
        return knn_fullscan(qx, qy, x, y, mask, k=k, m_blocks=m_blocks,
                            interpret=interpret)
    pad = (-q) % query_tile
    qxp = jnp.pad(qx, (0, pad), mode="edge")
    qyp = jnp.pad(qy, (0, pad), mode="edge")

    def tile(args):
        tx, ty = args
        return knn_fullscan(tx, ty, x, y, mask, k=k, m_blocks=m_blocks,
                            interpret=interpret)

    fd, fi = jax.lax.map(
        tile, (qxp.reshape(-1, query_tile), qyp.reshape(-1, query_tile))
    )
    return fd.reshape(-1, k)[:q], fi.reshape(-1, k)[:q]
