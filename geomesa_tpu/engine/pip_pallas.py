"""Pallas TPU kernel: tiled crossing-number point-in-polygon.

Parity role: same predicate as engine.pip.points_in_polygon (the JTS
prepared-geometry intersects analog — SURVEY.md C4/§7 "hardest kernel",
baseline config 2). TPU-first design: the dense lax implementation
materializes the [N, E] crossing matrix in HBM; this kernel streams fixed
[POINT_TILE, EDGE_TILE] blocks through VMEM with a revisited int32
accumulator block, so HBM traffic is O(N + E) instead of O(N·E) and the
VPU stays saturated on elementwise compare/FMA work.

Grid: (point_tiles, edge_tiles), edge axis minor — each point block's
accumulator is initialized at edge step 0 and folded until the last step
(standard Pallas revisited-output accumulation; the sequential TPU grid
guarantees ordering). Padding edges are degenerate (all zeros) and can
never satisfy the half-open crossing rule; padded points are sliced off.

Layout (Mosaic tiling): points ride the LANE axis as [1, POINT_TILE]
blocks and edges ride the SUBLANE axis as [EDGE_TILE, 1] blocks, so the
[EDGE_TILE, POINT_TILE] crossing matrix is a native VPU broadcast
(no relayout) and the per-point count is a sublane-axis reduction. Block
shapes obey the TPU lowering rule (last two dims divisible by (8, 128) or
equal to the array dims: the 1-sized dims equal the array's).

f32 note: edge-crossing comparisons at f32 resolution can flip for points
within ~1e-7 deg of a boundary (documented divergence from the f64 oracle,
same caveat as the lax path)."""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import enable_x64 as _enable_x64
import numpy as np

POINT_TILE = 512
EDGE_TILE = 512


def _pip_kernel(px_ref, py_ref, x1_ref, y1_ref, x2_ref, y2_ref, out_ref):
    import jax.experimental.pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    px = px_ref[0]  # [1, P] — points in lanes
    py = py_ref[0]
    x1 = x1_ref[0]  # [E, 1] — edges in sublanes
    y1 = y1_ref[0]
    x2 = x2_ref[0]
    y2 = y2_ref[0]

    # half-open rule: exactly one endpoint strictly above py
    cond = (y1 <= py) != (y2 <= py)          # [E, P] native broadcast
    # dtype-pinned literal: a bare 1.0 traces as weak f64 when the
    # interpret-mode kernel trace runs under the process-wide x64 mode
    # (the enable_x64(False) window only covers the outer trace entry)
    t = (py - y1) / jnp.where(y2 == y1, jnp.ones((), y1.dtype), y2 - y1)
    xc = x1 + t * (x2 - x1)
    partial = jnp.sum((cond & (xc > px)).astype(jnp.int32), axis=0)  # [P]
    out_ref[...] += partial.reshape(out_ref.shape)


@functools.partial(jax.jit, static_argnames=("interpret",))
def points_in_polygon_pallas(px, py, x1, y1, x2, y2, interpret: bool = False):
    """Crossing-number test [N] points vs [E] edges -> bool [N] (Pallas)."""
    import jax.experimental.pallas as pl

    n = px.shape[0]
    e = x1.shape[0]
    if e == 0:
        return jnp.zeros((n,), bool)
    npad = (-n) % POINT_TILE
    epad = (-e) % EDGE_TILE
    # kernel is f32-only (Mosaic rejects 64-bit operands); f64 callers accept
    # the documented boundary-resolution caveat above
    dt = jnp.float32
    # points: [gp, 1, POINT_TILE] (lane axis); edges: [ge, EDGE_TILE, 1]
    # (sublane axis)
    pxp = jnp.pad(px.astype(dt), (0, npad)).reshape(-1, 1, POINT_TILE)
    pyp = jnp.pad(py.astype(dt), (0, npad)).reshape(-1, 1, POINT_TILE)
    # degenerate zero edges never cross (y1 == y2 fails the half-open rule)
    e1 = jnp.pad(x1.astype(dt), (0, epad)).reshape(-1, EDGE_TILE, 1)
    f1 = jnp.pad(y1.astype(dt), (0, epad)).reshape(-1, EDGE_TILE, 1)
    e2 = jnp.pad(x2.astype(dt), (0, epad)).reshape(-1, EDGE_TILE, 1)
    f2 = jnp.pad(y2.astype(dt), (0, epad)).reshape(-1, EDGE_TILE, 1)

    gp, ge = pxp.shape[0], e1.shape[0]
    point_block = pl.BlockSpec((1, 1, POINT_TILE), lambda i, j: (i, 0, 0))
    edge_block = pl.BlockSpec((1, EDGE_TILE, 1), lambda i, j: (j, 0, 0))

    # Mosaic rejects 64-bit types; trace the kernel with x64 off so index-map
    # and in-kernel literals stay i32/f32 even when the host runs x64 mode.
    with _enable_x64(False):
        counts = pl.pallas_call(
            _pip_kernel,
            grid=(gp, ge),
            in_specs=[point_block, point_block,
                      edge_block, edge_block, edge_block, edge_block],
            out_specs=pl.BlockSpec((1, 1, POINT_TILE), lambda i, j: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((gp, 1, POINT_TILE), jnp.int32),
            interpret=interpret,
        )(pxp, pyp, e1, f1, e2, f2)
    return (counts.reshape(-1)[:n] % 2) == 1


def _pip_band_kernel(
    px_ref, py_ref, x1_ref, y1_ref, x2_ref, y2_ref, out_ref, *, eps: float
):
    """Boundary-ambiguity flags, same streaming-tile shape as _pip_kernel
    (see engine.pip.points_in_polygon_band for the flag rule)."""
    import jax.experimental.pallas as pl

    j = pl.program_id(1)

    @pl.when(j == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    px = px_ref[0]
    py = py_ref[0]
    x1 = x1_ref[0]
    y1 = y1_ref[0]
    x2 = x2_ref[0]
    y2 = y2_ref[0]

    # band terms match pip_sparse._crossing_and_band (see its proof)
    near_flat = ((jnp.abs(py - y1) <= eps) & (jnp.abs(py - y2) <= eps)
                 & (px >= jnp.minimum(x1, x2) - eps)
                 & (px <= jnp.maximum(x1, x2) + eps))
    cond = (y1 <= py) != (y2 <= py)
    # dtype-pinned literal: a bare 1.0 traces as weak f64 when the
    # interpret-mode kernel trace runs under the process-wide x64 mode
    # (the enable_x64(False) window only covers the outer trace entry)
    t = (py - y1) / jnp.where(y2 == y1, jnp.ones((), y1.dtype), y2 - y1)
    xc = x1 + t * (x2 - x1)
    err = eps * (1.0 + jnp.abs(x2 - x1) / jnp.maximum(jnp.abs(y2 - y1), eps))
    flag = jnp.sum((near_flat | (cond & (jnp.abs(xc - px) <= err))).astype(jnp.int32), axis=0)
    out_ref[...] += flag.reshape(out_ref.shape)


@functools.partial(jax.jit, static_argnames=("eps", "interpret"))
def points_in_polygon_band_pallas(
    px, py, x1, y1, x2, y2, eps: float = 1e-4, interpret: bool = False
):
    """Streaming-tile boundary-band flags -> bool [N] (Pallas)."""
    import jax.experimental.pallas as pl

    n = px.shape[0]
    e = x1.shape[0]
    if e == 0:
        return jnp.zeros((n,), bool)
    npad = (-n) % POINT_TILE
    epad = (-e) % EDGE_TILE
    dt = jnp.float32
    pxp = jnp.pad(px.astype(dt), (0, npad)).reshape(-1, 1, POINT_TILE)
    pyp = jnp.pad(py.astype(dt), (0, npad), constant_values=1e9).reshape(
        -1, 1, POINT_TILE
    )
    # padding edges sit at y=1e9 so they are never near a real point's y
    # (zero-padded edges would flag every point with |py| <= eps)
    e1 = jnp.pad(x1.astype(dt), (0, epad)).reshape(-1, EDGE_TILE, 1)
    f1 = jnp.pad(y1.astype(dt), (0, epad), constant_values=1e9).reshape(
        -1, EDGE_TILE, 1
    )
    e2 = jnp.pad(x2.astype(dt), (0, epad)).reshape(-1, EDGE_TILE, 1)
    f2 = jnp.pad(y2.astype(dt), (0, epad), constant_values=1e9).reshape(
        -1, EDGE_TILE, 1
    )

    gp, ge = pxp.shape[0], e1.shape[0]
    point_block = pl.BlockSpec((1, 1, POINT_TILE), lambda i, j: (i, 0, 0))
    edge_block = pl.BlockSpec((1, EDGE_TILE, 1), lambda i, j: (j, 0, 0))

    with _enable_x64(False):
        counts = pl.pallas_call(
            functools.partial(_pip_band_kernel, eps=float(eps)),
            grid=(gp, ge),
            in_specs=[point_block, point_block,
                      edge_block, edge_block, edge_block, edge_block],
            out_specs=pl.BlockSpec((1, 1, POINT_TILE), lambda i, j: (i, 0, 0)),
            out_shape=jax.ShapeDtypeStruct((gp, 1, POINT_TILE), jnp.int32),
            interpret=interpret,
        )(pxp, pyp, e1, f1, e2, f2)
    return counts.reshape(-1)[:n] > 0


# threshold below which the dense lax path wins (kernel launch + padding
# overhead dominates when the [N, E] block fits comfortably anyway)
_MIN_WORK = 1 << 22


def use_pallas_pip(n: int, e: int) -> bool:
    return jax.default_backend() == "tpu" and n * max(e, 1) >= _MIN_WORK


def points_in_polygon_np_edges(px, py, x1, y1, x2, y2) -> np.ndarray:
    """NumPy f64 oracle over an explicit edge table (same edge rule)."""
    px = np.asarray(px, np.float64)[:, None]
    py = np.asarray(py, np.float64)[:, None]
    x1 = np.asarray(x1, np.float64)[None, :]
    y1 = np.asarray(y1, np.float64)[None, :]
    x2 = np.asarray(x2, np.float64)[None, :]
    y2 = np.asarray(y2, np.float64)[None, :]
    cond = (y1 <= py) != (y2 <= py)
    t = (py - y1) / np.where(y2 == y1, 1.0, y2 - y1)
    xc = x1 + t * (x2 - x1)
    return (np.sum(cond & (xc > px), axis=1) % 2) == 1
