"""Extended-geometry (CSR) spatial predicates against a literal geometry.

Parity role: JTS geometry predicates as evaluated server-side by the
reference's residual filters over non-point indexed data (XZ indices demand
strict residual filtering — SURVEY.md C7) [upstream, unverified].

TPU-first formulation: everything is dense edge/vertex tables with
segment-reductions keyed by feature id — no per-feature control flow:

  INTERSECTS(feature, L) = any feature vertex in L
                         | any L vertex inside feature
                         | any (feature edge x L edge) proper crossing
  WITHIN(feature, L)     = all feature vertices in L
                         & no proper edge crossings
                         & no L vertex strictly inside feature
  CONTAINS(feature, L)   = the mirror image of WITHIN
  DISJOINT               = ~INTERSECTS; BBOX = envelope overlap test

Exact for valid simple polygons/lines up to boundary-touch cases, which sit
on the half-open crossing rule like the point kernel (documented tolerance).
OVERLAPS/CROSSES/TOUCHES are principled approximations from the same
primitives (noted inline) — the reference gets these from full DE-9IM.
"""

from __future__ import annotations

from typing import Callable

import jax
import jax.numpy as jnp
import numpy as np

from geomesa_tpu.core.wkt import Geometry
from geomesa_tpu.engine.device import VALID
from geomesa_tpu.engine.pip import points_in_polygon, polygon_edges
from geomesa_tpu.cql import ast


def _literal_arrays(g: Geometry):
    x1, y1, x2, y2 = polygon_edges(g)
    verts = (
        np.concatenate(g.rings, axis=0) if g.rings else np.zeros((0, 2))
    )
    return (
        tuple(jnp.asarray(a) for a in (x1, y1, x2, y2)),
        jnp.asarray(verts[:, 0]),
        jnp.asarray(verts[:, 1]),
    )


def _cross(ox, oy, ax, ay, bx, by):
    return (ax - ox) * (by - oy) - (ay - oy) * (bx - ox)


def _any_by_feature(values: jax.Array, feat: jax.Array, n: int) -> jax.Array:
    """OR-reduce a per-edge/vertex bool array into per-feature bools."""
    return (
        jax.ops.segment_sum(values.astype(jnp.int32), feat, num_segments=n) > 0
    )


def _feature_masks(f, name: str, data_is_poly: bool = True):
    """Build (params, dev) -> mask for SpatialPredicate on CSR data.

    `data_is_poly`: whether the data features are areal (ray-crossing parity
    against their edge tables is meaningful). Open polylines/multipoints have
    no interior, so "literal vertex inside feature" is identically False.
    """
    op = f.op
    g = f.geometry
    lit_edges, lvx, lvy = _literal_arrays(g)
    x0, y0, x1b, y1b = g.bbox
    poly_literal = g.kind in ("Polygon", "MultiPolygon")

    def parts(dev):
        n = dev[f"{name}__x"].shape[0]
        vx = dev[f"{name}__verts"][:, 0]
        vy = dev[f"{name}__verts"][:, 1]
        vfeat = dev[f"{name}__vfeat"]
        ex1, ey1 = dev[f"{name}__ex1"], dev[f"{name}__ey1"]
        ex2, ey2 = dev[f"{name}__ex2"], dev[f"{name}__ey2"]
        efeat = dev[f"{name}__efeat"]
        return n, vx, vy, vfeat, ex1, ey1, ex2, ey2, efeat

    def vertex_in_literal_any(dev):
        n, vx, vy, vfeat, *_ = parts(dev)
        if not poly_literal:
            return jnp.zeros(n, bool)
        vin = points_in_polygon(vx, vy, *lit_edges)
        return _any_by_feature(vin, vfeat, n)

    def vertex_in_literal_all(dev):
        n, vx, vy, vfeat, *_ = parts(dev)
        if not poly_literal:
            return jnp.zeros(n, bool)
        vout = ~points_in_polygon(vx, vy, *lit_edges)
        has_out = _any_by_feature(vout, vfeat, n)
        counts = jax.ops.segment_sum(jnp.ones_like(vfeat), vfeat, num_segments=n)
        return ~has_out & (counts > 0)

    def literal_vertex_in_feature(dev):
        """[N] : does any literal vertex fall inside the data feature?
        Per-feature crossing-number via segment-sum over the edge table."""
        n, _, _, _, ex1, ey1, ex2, ey2, efeat = parts(dev)
        if lvx.shape[0] == 0 or not data_is_poly:
            return jnp.zeros(n, bool)
        py = lvy[None, :]
        px = lvx[None, :]
        cond = (ey1[:, None] <= py) != (ey2[:, None] <= py)
        t = (py - ey1[:, None]) / jnp.where(
            ey2[:, None] == ey1[:, None], 1.0, ey2[:, None] - ey1[:, None]
        )
        xc = ex1[:, None] + t * (ex2[:, None] - ex1[:, None])
        crossing = (cond & (xc > px)).astype(jnp.int32)  # [E, L]
        counts = jax.ops.segment_sum(crossing, efeat, num_segments=n)  # [N, L]
        inside = (counts % 2) == 1
        return jnp.any(inside, axis=1)

    def edge_crossings(dev):
        """[N] : any proper data-edge x literal-edge crossing."""
        n, _, _, _, ex1, ey1, ex2, ey2, efeat = parts(dev)
        lx1, ly1, lx2, ly2 = lit_edges
        if lx1.shape[0] == 0:
            return jnp.zeros(n, bool)
        d1 = _cross(lx1[None, :], ly1[None, :], lx2[None, :], ly2[None, :], ex1[:, None], ey1[:, None])
        d2 = _cross(lx1[None, :], ly1[None, :], lx2[None, :], ly2[None, :], ex2[:, None], ey2[:, None])
        d3 = _cross(ex1[:, None], ey1[:, None], ex2[:, None], ey2[:, None], lx1[None, :], ly1[None, :])
        d4 = _cross(ex1[:, None], ey1[:, None], ex2[:, None], ey2[:, None], lx2[None, :], ly2[None, :])
        proper = ((d1 > 0) != (d2 > 0)) & ((d3 > 0) != (d4 > 0))  # [E, L]
        return _any_by_feature(jnp.any(proper, axis=1), efeat, n)

    def bbox_overlap(dev):
        bb = dev[f"{name}__bbox"]
        return (
            (bb[:, 0] <= x1b) & (bb[:, 2] >= x0) & (bb[:, 1] <= y1b) & (bb[:, 3] >= y0)
        )

    def intersects(dev):
        return bbox_overlap(dev) & (
            vertex_in_literal_any(dev)
            | literal_vertex_in_feature(dev)
            | edge_crossings(dev)
        )

    def within(dev):
        return (
            vertex_in_literal_all(dev)
            & ~edge_crossings(dev)
            & ~literal_vertex_in_feature(dev)
        )

    def contains(dev):
        n, vx, vy, vfeat, *_ = parts(dev)
        if lvx.shape[0] == 0:
            return jnp.zeros(n, bool)
        all_lit_in = literal_all_in_feature(dev)
        if poly_literal:
            no_data_vertex_in_lit = ~_any_by_feature(
                points_in_polygon(vx, vy, *lit_edges), vfeat, n
            )
        else:
            no_data_vertex_in_lit = jnp.ones(n, bool)
        return all_lit_in & ~edge_crossings(dev) & no_data_vertex_in_lit

    def literal_all_in_feature(dev):
        n, _, _, _, ex1, ey1, ex2, ey2, efeat = parts(dev)
        if not data_is_poly:
            return jnp.zeros(n, bool)
        py = lvy[None, :]
        px = lvx[None, :]
        cond = (ey1[:, None] <= py) != (ey2[:, None] <= py)
        t = (py - ey1[:, None]) / jnp.where(
            ey2[:, None] == ey1[:, None], 1.0, ey2[:, None] - ey1[:, None]
        )
        xc = ex1[:, None] + t * (ex2[:, None] - ex1[:, None])
        crossing = (cond & (xc > px)).astype(jnp.int32)
        counts = jax.ops.segment_sum(crossing, efeat, num_segments=n)
        inside = (counts % 2) == 1  # [N, L]
        return jnp.all(inside, axis=1)

    if op == "BBOX":
        return lambda params, dev: bbox_overlap(dev)
    if op == "INTERSECTS":
        return lambda params, dev: intersects(dev)
    if op == "DISJOINT":
        return lambda params, dev: ~intersects(dev)
    if op == "WITHIN":
        return lambda params, dev: within(dev)
    if op == "CONTAINS":
        return lambda params, dev: contains(dev)
    if op == "EQUALS":
        # approximation: mutual containment
        return lambda params, dev: within(dev) & contains(dev)
    if op == "OVERLAPS":
        # approximation: interiors intersect, neither contains the other
        return lambda params, dev: intersects(dev) & ~within(dev) & ~contains(dev)
    if op == "CROSSES":
        # line/polygon crossing: edge crossings, or part-in/part-out
        def crosses(params, dev):
            n, vx, vy, vfeat, *_ = parts(dev)
            some_in = vertex_in_literal_any(dev)
            all_in = vertex_in_literal_all(dev)
            return edge_crossings(dev) | (some_in & ~all_in)
        return crosses
    if op == "TOUCHES":
        # approximation: boundaries meet but interiors don't overlap =
        # bbox overlap & ~(any vertex strictly inside either way) & edges meet
        def touches(params, dev):
            return (
                bbox_overlap(dev)
                & ~vertex_in_literal_any(dev)
                & ~literal_vertex_in_feature(dev)
                & edge_crossings(dev)
            )
        return touches
    raise NotImplementedError(f"extended spatial op {op}")


def compile_extended_spatial(f, name: str, attr_type: str = "Polygon") -> Callable:
    """Entry point used by cql.compile for non-Point geometry attributes."""
    data_is_poly = "Polygon" in attr_type or attr_type in (
        "Geometry",
        "GeometryCollection",
    )
    if isinstance(f, ast.DistancePredicate):
        return _distance_mask(f, name, data_is_poly)
    return _feature_masks(f, name, data_is_poly)


def _distance_mask(f, name: str, data_is_poly: bool = True):
    from geomesa_tpu.engine.geodesy import point_to_segments_m

    lit_edges, lvx, lvy = _literal_arrays(f.geometry)
    lx1, ly1, lx2, ly2 = lit_edges
    if lx1.shape[0] == 0:
        if lvx.shape[0] == 0:  # EMPTY literal: nothing is within any distance
            return lambda params, dev: jnp.zeros_like(dev[VALID])
        lx1 = lx2 = lvx
        ly1 = ly2 = lvy
    d = float(f.distance_m)
    intersect_fn = _feature_masks(
        ast.SpatialPredicate("INTERSECTS", f.prop, f.geometry), name, data_is_poly
    )

    def dwithin(params, dev):
        n = dev[f"{name}__x"].shape[0]
        vx = dev[f"{name}__verts"][:, 0]
        vy = dev[f"{name}__verts"][:, 1]
        vfeat = dev[f"{name}__vfeat"]
        vd = point_to_segments_m(vx, vy, lx1, ly1, lx2, ly2)
        near = (
            jax.ops.segment_sum((vd <= d).astype(jnp.int32), vfeat, num_segments=n)
            > 0
        )
        # near via any vertex, or actually intersecting (distance 0)
        return near | intersect_fn(params, dev)

    if f.op == "BEYOND":
        return lambda params, dev: ~dwithin(params, dev)
    return dwithin
