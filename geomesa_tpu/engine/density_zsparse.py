"""Z-locality density: the store-order-aware heatmap kernel.

Parity role: DensityScan / DensityProcess (SURVEY.md §3.5) at the
north-star scale — config 4's 512x512 heatmap over 10s of millions of
points. The round-2 kernels pay per-point costs that dwarf the HBM
roofline: XLA scatter-add serializes (~1 cycle/point), and the dense MXU
one-hot formulation (`density.density_grid_mxu`) materializes [T, H] and
[T, W] one-hots through HBM (~137 GB at 67M points / 512^2 — measured
0.65 s, vs a ~2 ms read-the-data bound).

The insight (same as the sparse kNN scan): index scans emit rows in
STORE ORDER — the Z curve — so consecutive points are spatially local,
and a 16384-point data tile touches only a narrow band of density cells.
In MORTON order over the density grid those cells are near-contiguous:
measured on the config-4 shapes, a tile's (max - min) Morton-cell span
is ~64-256 out of 262144. That turns the histogram into

  per tile:  local = morton_cell(point) - tile_base     (in [0, CAP))
             counts[local] += w                          (VMEM one-hot)
  finally:   scatter per-tile count rows into the Morton-flat grid,
             permute Morton -> raster once (static per W,H)

The per-tile one-hot is [chunk, CAP] with CAP ~128-1024 instead of
[chunk, H] + [chunk, W] with H = W = 512, and it never leaves VMEM.
Cost: ~0.3-0.5 VPU cycles/point — an HBM-bound kernel.

Exactness: identical contract to `density_grid` (same binning, same
mask/out-of-bounds exclusion). Weighted sums run the one-hot matmul in
f32 (HIGHEST); counts are exact, weighted grids agree with the scatter
path to f32 summation-order noise. Tiles whose span exceeds CAP (Z-curve
quadrant seams, sparse regions) and tiles with no matching points are
EXCLUDED from the kernel: empty tiles are pruned outright (the VERDICT
r3 tile-pruning item), overflow tiles are evaluated by the caller on the
dense path over block-gathered points (`density_zsparse` handles both).

Mosaic notes (same constraints as knn_scan.py): i32 bit-twiddling only
(Morton interleave in 32-bit), trace under enable_x64(False), static
chunk loop (4 bodies), output lanes >= 128.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

BBox = Tuple[float, float, float, float]

# kernel geometry bounded by scoped VMEM (~16 MB): the in-kernel one-hot
# is [CHUNK, cap] f32, so CHUNK x MAX_CAP x 4 B must stay well under the
# limit (the first hardware run allocated 64 MB at 4096x4096 and the
# compile OOMed). Smaller data tiles also shrink per-tile Morton spans,
# keeping more tiles on the sparse path at the smaller cap.
DATA_TILE = 4096
CHUNK = 2048
MAX_CAP = 1024  # beyond this span the dense path is cheaper anyway


def _interleave16(v):
    """Spread the low 16 bits of each lane to even bit positions."""
    v = v & 0xFFFF
    v = (v | (v << 8)) & 0x00FF00FF
    v = (v | (v << 4)) & 0x0F0F0F0F
    v = (v | (v << 2)) & 0x33333333
    v = (v | (v << 1)) & 0x55555555
    return v


def _morton_cells(col, row):
    """Morton (Z) cell id from grid col/row (i32, grids up to 2^15)."""
    return _interleave16(col) | (_interleave16(row) << 1)


@functools.lru_cache(maxsize=8)
def _raster_of_morton(width: int, height: int) -> np.ndarray:
    """[n_morton] i32: raster index (row*W+col) per Morton cell id, for
    the final permutation. Static per grid shape."""
    side = 1 << int(np.ceil(np.log2(max(width, height, 2))))
    cc, rr = np.meshgrid(np.arange(side), np.arange(side), indexing="xy")

    def spread(v):
        v = v.astype(np.uint32)
        v = (v | (v << 8)) & np.uint32(0x00FF00FF)
        v = (v | (v << 4)) & np.uint32(0x0F0F0F0F)
        v = (v | (v << 2)) & np.uint32(0x33333333)
        v = (v | (v << 1)) & np.uint32(0x55555555)
        return v

    z = spread(cc) | (spread(rr) << np.uint32(1))
    out = np.full(side * side, width * height, np.int32)  # sink for pads
    inb = (cc < width) & (rr < height)
    out[z[inb]] = (rr[inb] * width + cc[inb]).astype(np.int32)
    return out


def _bin_cells(x, y, mask, bbox: BBox, width: int, height: int):
    """Shared binning math: (morton cell i32, in-bounds-and-masked)."""
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    col = jnp.floor((x - xmin) / dx).astype(jnp.int32)
    row = jnp.floor((y - ymin) / dy).astype(jnp.int32)
    inb = (col >= 0) & (col < width) & (row >= 0) & (row < height) & mask
    col = jnp.clip(col, 0, width - 1)
    row = jnp.clip(row, 0, height - 1)
    return _morton_cells(col, row), inb


class DensityCalib(NamedTuple):
    """Host-side plan from one calibration pass (cacheable across
    queries, like the sparse kNN tile capacity)."""

    tile_ids: np.ndarray   # [S] tiles the sparse kernel scans
    tile_base: np.ndarray  # [S] morton base cell per tile
    cap: int               # local one-hot width (pow2)
    dense_ids: np.ndarray  # tiles overflowing cap -> dense fallback
    n_tiles: int


@functools.partial(
    jax.jit, static_argnames=("bbox", "width", "height", "data_tile")
)
def _tile_ranges(x, y, mask, bbox: BBox, width: int, height: int,
                 data_tile: int):
    n = x.shape[0]
    pad = (-n) % data_tile
    xp = jnp.pad(x.astype(jnp.float32), (0, pad))
    yp = jnp.pad(y.astype(jnp.float32), (0, pad))
    mp = jnp.pad(mask, (0, pad))
    zc, ok = _bin_cells(xp, yp, mp, bbox, width, height)
    nt = zc.shape[0] // data_tile
    zt = zc.reshape(nt, data_tile)
    okt = ok.reshape(nt, data_tile)
    big = jnp.int32(1 << 30)
    zmin = jnp.where(okt, zt, big).min(axis=1)
    zmax = jnp.where(okt, zt, -1).max(axis=1)
    return zmin, zmax


def calibrate_density(
    x, y, mask, bbox: BBox, width: int, height: int,
    data_tile: int = DATA_TILE, slack: float = 2.0,
) -> DensityCalib:
    """One device pass + one small ([n_tiles] x2 i32) fetch: per-tile
    Morton cell ranges under the CURRENT mask. cap is a pow2 bucket of
    the median span x slack — covering most tiles keeps the one-hot
    narrow; the tail goes to the dense fallback list."""
    zmin, zmax = _tile_ranges(x, y, mask, bbox, width, height, data_tile)
    zmin = np.asarray(zmin)
    zmax = np.asarray(zmax)
    nt = len(zmin)
    has = zmax >= 0  # tile bears >= 1 matching point; others pruned
    ids = np.nonzero(has)[0]
    if len(ids) == 0:
        return DensityCalib(
            np.zeros(0, np.int32), np.zeros(0, np.int32), 128,
            np.zeros(0, np.int32), nt,
        )
    span = zmax[ids] - zmin[ids] + 1
    cap = int(min(MAX_CAP, max(
        128, 1 << int(np.ceil(np.log2(max(np.median(span) * slack, 2))))
    )))
    fits = span <= cap
    return DensityCalib(
        ids[fits].astype(np.int32),
        zmin[ids][fits].astype(np.int32),
        cap,
        ids[~fits].astype(np.int32),
        nt,
    )


def _make_kernel(data_tile: int, chunk: int, cap: int, bbox: BBox,
                 width: int, height: int):
    def _kernel(ids_ref, base_ref, x_ref, y_ref, w_ref, m_ref, out_ref):
        from jax.experimental import pallas as pl

        p = pl.program_id(0)
        base = base_ref[p]
        iota = jax.lax.broadcasted_iota(jnp.int32, (1, cap), 1)
        acc = jnp.zeros((1, cap), jnp.float32)
        for s in range(data_tile // chunk):
            sl = slice(s * chunk, (s + 1) * chunk)
            zc, ok = _bin_cells(
                x_ref[0, sl], y_ref[0, sl], m_ref[0, sl] > 0.5,
                bbox, width, height,
            )
            local = jnp.clip(zc - base, 0, cap - 1)
            lw = jnp.where(
                ok & (zc >= base) & (zc < base + cap),
                w_ref[0, sl], 0.0,
            ).reshape(1, chunk)
            onehot = (
                local.reshape(chunk, 1) == iota
            ).astype(jnp.float32)
            acc = acc + jax.lax.dot_general(
                lw, onehot, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32,
                precision=jax.lax.Precision.HIGHEST,
            )
        out_ref[...] = acc.reshape(out_ref.shape)

    return _kernel


@functools.partial(
    jax.jit,
    static_argnames=(
        "cap", "bbox", "width", "height", "data_tile", "chunk", "interpret"
    ),
)
def _zsparse_call(
    x, y, w, maskf, tile_ids, tile_base,
    cap: int, bbox: BBox, width: int, height: int,
    data_tile: int, chunk: int, interpret: bool,
):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = x.shape[0]
    s = tile_ids.shape[0]
    xr = x.astype(jnp.float32).reshape(1, n)
    yr = y.astype(jnp.float32).reshape(1, n)
    wr = w.astype(jnp.float32).reshape(1, n)
    mr = maskf.reshape(1, n)

    data_block = pl.BlockSpec(
        (1, data_tile), lambda p, ids, base: (0, ids[p])
    )
    # out rows live in a 3-D [S, 1, cap] array with (1, 1, cap) blocks:
    # Mosaic requires the last two block dims divisible by (8, 128) OR
    # equal to the array dims — a 2-D (1, cap) block over [S, cap] fails
    # that check (caught on hardware; interpret mode never sees Mosaic)
    with jax.enable_x64(False):
        counts = pl.pallas_call(
            _make_kernel(data_tile, chunk, cap, bbox, width, height),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=2,
                grid=(s,),
                in_specs=[data_block] * 4,
                out_specs=pl.BlockSpec(
                    (1, 1, cap), lambda p, ids, base: (p, 0, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct((s, 1, cap), jnp.float32),
            interpret=interpret,
        )(tile_ids.astype(jnp.int32), tile_base.astype(jnp.int32),
          xr, yr, wr, mr)
    return counts.reshape(s, cap)


@functools.partial(
    jax.jit,
    static_argnames=("cap", "width", "height"),
)
def _fold_counts(counts, tile_base, raster_of_z, cap: int, width: int,
                 height: int):
    """Scatter per-tile count rows into the Morton-flat grid, then
    permute Morton -> raster (one static scatter each)."""
    n_morton = raster_of_z.shape[0]
    idx = tile_base[:, None] + jnp.arange(cap, dtype=jnp.int32)[None, :]
    flat_z = jnp.zeros(n_morton + cap, jnp.float32)
    flat_z = flat_z.at[idx.reshape(-1)].add(counts.reshape(-1))
    # raster_of_z routes Morton pads (cells outside WxH) to a sink slot
    grid = jnp.zeros(width * height + 1, jnp.float32)
    grid = grid.at[raster_of_z].add(flat_z[:n_morton])
    return grid[: width * height].reshape(height, width)


@functools.partial(
    jax.jit, static_argnames=("bbox", "width", "height")
)
def _expected_mass(x, y, w, mask, bbox: BBox, width: int, height: int):
    _, ok = _bin_cells(x, y, mask, bbox, width, height)
    return jnp.sum(jnp.where(ok, w.astype(jnp.float64), 0.0))


def density_zsparse(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
    calib: Optional[DensityCalib] = None,
    data_tile: int = DATA_TILE,
    interpret: bool = False,
    check_stale: bool = True,
) -> Tuple[jax.Array, DensityCalib]:
    """Store-order density grid (see module docstring). Returns
    ([height, width] f32 grid, calib) — pass `calib` back in on repeat
    queries over the same batch+filter to skip the calibration fetch.
    Exact contract of `density.density_grid` for any input order; the
    sparse win requires store (Z) order, the fallback keeps it correct
    otherwise.

    A REUSED calib is validated (`check_stale`): unlike the kNN tile
    capacity, a stale density plan is a silent correctness failure (a
    point in a tile pruned under the OLD mask, or outside a tile's
    cached cell band, would vanish from the grid), so the grid's total
    mass is checked against the mask's expected mass and a mismatch
    triggers automatic recalibration. Callers looping the IDENTICAL
    query (mask unchanged) may pass check_stale=False to skip the extra
    device reduction + fetch."""
    from geomesa_tpu.engine.density import density_grid_mxu

    reused_calib = calib is not None
    n = x.shape[0]
    pad = (-n) % data_tile
    xp = jnp.pad(x.astype(jnp.float32), (0, pad))
    yp = jnp.pad(y.astype(jnp.float32), (0, pad))
    wp = jnp.pad(weights.astype(jnp.float32), (0, pad))
    mp = jnp.pad(mask, (0, pad))
    if calib is None:
        calib = calibrate_density(
            xp, yp, mp, bbox, width, height, data_tile=data_tile
        )

    grid = jnp.zeros((height, width), jnp.float32)
    if len(calib.tile_ids):
        raster = jnp.asarray(_raster_of_morton(width, height))
        # chunk the tile list so one call's output stays ~4 MB: XLA may
        # place a pallas output in VMEM, and a full [S, 1, cap] count
        # array blew the 16 MB scoped-vmem limit at bench scale (caught
        # on hardware: S=3074, cap=4096 -> 50 MB)
        maxs = max(256, (1 << 20) // max(calib.cap, 1))
        S = len(calib.tile_ids)
        for c0 in range(0, S, maxs):
            c1 = min(c0 + maxs, S)
            ids_c = calib.tile_ids[c0:c1]
            base_c = calib.tile_base[c0:c1]
            pad_c = maxs - len(ids_c) if S > maxs else 0
            if pad_c:  # stable shapes across chunks (one compile)
                ids_c = np.concatenate(
                    [ids_c, np.full(pad_c, ids_c[0], ids_c.dtype)])
                base_c = np.concatenate(
                    [base_c, np.full(pad_c, 1 << 29, base_c.dtype)])
                # padding rows re-scan a real tile with an impossible
                # base: every local index clips out, contributing zeros
            counts = _zsparse_call(
                xp, yp, wp, mp.astype(jnp.float32),
                jnp.asarray(ids_c), jnp.asarray(base_c),
                cap=calib.cap, bbox=tuple(bbox), width=width,
                height=height,
                data_tile=data_tile, chunk=min(CHUNK, data_tile),
                interpret=interpret,
            )
            grid = grid + _fold_counts(
                counts, jnp.asarray(base_c), raster,
                cap=calib.cap, width=width, height=height,
            )
    if len(calib.dense_ids):
        # overflow tiles (Z seams / sparse regions): block-gather their
        # points (contiguous 16k rows — fast) and run the dense MXU path
        ids = jnp.asarray(calib.dense_ids)
        gx = jnp.take(xp.reshape(-1, data_tile), ids, axis=0).reshape(-1)
        gy = jnp.take(yp.reshape(-1, data_tile), ids, axis=0).reshape(-1)
        gw = jnp.take(wp.reshape(-1, data_tile), ids, axis=0).reshape(-1)
        gm = jnp.take(mp.reshape(-1, data_tile), ids, axis=0).reshape(-1)
        grid = grid + density_grid_mxu(
            gx, gy, gw, gm, tuple(bbox), width, height,
            point_tile=min(8192, max(len(calib.dense_ids) * data_tile, 128)),
        )
    if reused_calib and check_stale:
        expected = float(_expected_mass(
            xp, yp, wp, mp, tuple(bbox), width, height))
        got = float(np.asarray(grid, np.float64).sum())
        if not np.isclose(got, expected, rtol=1e-5, atol=1e-3):
            # the cached plan no longer covers this mask: recalibrate
            return density_zsparse(
                x, y, weights, mask, bbox, width, height, calib=None,
                data_tile=data_tile, interpret=interpret,
            )
    return grid, calib
