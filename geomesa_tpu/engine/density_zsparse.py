"""Cell-dictionary density: the store-order-aware heatmap kernel.

Parity role: DensityScan / DensityProcess (SURVEY.md §3.5) at the
north-star scale — config 4's 512x512 heatmap over 10s of millions of
points. The round-2 kernels pay per-point costs that dwarf the HBM
roofline: XLA scatter-add serializes (~1 cycle/point), and the dense MXU
one-hot formulation (`density.density_grid_mxu`) builds [T, H] + [T, W]
one-hots (~3 VPU cycles/point at 512^2 — measured 0.45-0.65 s at 67M).

The insight (same family as the sparse kNN scan): index scans emit rows
in STORE ORDER — the Z curve — so consecutive points are spatially
local, and a 4096-point data tile touches only a HANDFUL of distinct
density cells (~16-64 at config-4 shapes; uniform 67M over 512^2 is
~256 points per cell). Each tile gets a DICTIONARY of its distinct cell
ids, built on device (sort + dedupe, one calibration pass), and the
kernel one-hots points against that narrow dictionary:

  per tile:  match[i, j] = (cell(point_i) == dict[j])     [chunk, capd]
             counts[j] += sum_i match[i, j] * w_i          (VMEM)
  finally:   grid.at[dict].add(counts)                     (one scatter)

capd is the pow2 bucket of the median distinct-cell count (~64), so the
per-point cost is ~capd/1024 lanes * ~3 ops ~ 0.2 VPU cycles — an
HBM-bound kernel. A span-based variant (round-4 first cut) used
base+offset locality instead; measured Morton spans of store tiles run
512-1024 (alignment + world-vs-grid curve mismatch), making its one-hot
as wide as the dense kernel's — the dictionary restores the ~10x.

Exactness: identical contract to `density_grid` (same binning, same
mask/out-of-bounds exclusion). Counts are exact; weighted sums agree
with the scatter path to f32 summation-order noise. Tiles with more
distinct cells than capd and tiles with no matching points are EXCLUDED
from the kernel: empty tiles are pruned outright (the VERDICT r3
tile-pruning item), overflow tiles go to the caller's EXACT scatter
fallback (the bf16 hi/lo MXU fallback of the first cut failed the
weighted cells-parity gate on hardware).

Mosaic notes: the dictionary rides as a (1, 1, capd) VMEM operand
(block == array dims satisfies the lane rule at any capd); out blocks
use the same 3-D idiom; scoped VMEM bounds chunk x capd.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import enable_x64 as _enable_x64
import numpy as np

BBox = Tuple[float, float, float, float]

DATA_TILE = 4096
CHUNK = 1024  # hardware sweep (round 5): 144 ms vs 185 ms at 2048/4096
MAX_CAPD = 512   # beyond this many distinct cells the scatter path wins
BIGCELL = 1 << 30


def _bin_cells(x, y, mask, bbox: BBox, width: int, height: int):
    """Shared binning math: (raster cell id row*W+col i32, in-bounds)."""
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    col = jnp.floor((x - xmin) / dx).astype(jnp.int32)
    row = jnp.floor((y - ymin) / dy).astype(jnp.int32)
    inb = (col >= 0) & (col < width) & (row >= 0) & (row < height) & mask
    # i32-pinned clip bounds: bare Python ints trace as weak i64 when
    # the interpret-mode kernel trace is deferred past the
    # enable_x64(False) window, and the while-loop lowering rejects it
    col = jnp.clip(col, jnp.int32(0), jnp.int32(width - 1))
    row = jnp.clip(row, jnp.int32(0), jnp.int32(height - 1))
    return row * width + col, inb


class DensityCalib(NamedTuple):
    """Plan from one calibration pass (cacheable across queries, like
    the sparse kNN tile capacity). `dicts` is a DEVICE array."""

    tile_ids: np.ndarray   # [S] tiles the sparse kernel scans
    dicts: object          # [S, capd] i32 device: distinct cells (-1 pad)
    capd: int              # dictionary width (pow2)
    dense_ids: np.ndarray  # tiles with > capd distinct cells -> fallback
    n_tiles: int


@functools.partial(
    jax.jit, static_argnames=("bbox", "width", "height", "data_tile")
)
def _tile_sorted_cells(x, y, mask, bbox: BBox, width: int, height: int,
                       data_tile: int):
    """Per-tile sorted cell ids (+BIGCELL for masked/out rows), first-
    occurrence flags, and distinct counts."""
    n = x.shape[0]
    pad = (-n) % data_tile
    xp = jnp.pad(x.astype(jnp.float32), (0, pad))
    yp = jnp.pad(y.astype(jnp.float32), (0, pad))
    mp = jnp.pad(mask, (0, pad))
    cells, ok = _bin_cells(xp, yp, mp, bbox, width, height)
    nt = cells.shape[0] // data_tile
    zt = jnp.where(ok, cells, BIGCELL).reshape(nt, data_tile)
    s = jnp.sort(zt, axis=1)
    live = s < BIGCELL
    first = jnp.concatenate(
        [live[:, :1],
         (s[:, 1:] != s[:, :-1]) & live[:, 1:]], axis=1)
    return s, first, jnp.sum(first.astype(jnp.int32), axis=1)


@functools.partial(jax.jit, static_argnames=("capd",))
def _tile_dicts(s, first, capd: int):
    """[nt, capd] distinct-cell dictionaries (-1 pads): re-sort with
    duplicates pushed to BIGCELL, take the first capd slots."""
    t = jnp.where(first, s, BIGCELL)
    t2 = jnp.sort(t, axis=1)[:, :capd]
    return jnp.where(t2 >= BIGCELL, -1, t2).astype(jnp.int32)


def calibrate_density(
    x, y, mask, bbox: BBox, width: int, height: int,
    data_tile: int = DATA_TILE, slack: float = 2.0,
) -> DensityCalib:
    """One device sort pass + one small ([n_tiles] i32) fetch: per-tile
    distinct-cell dictionaries under the CURRENT mask. capd is a pow2
    bucket of the median distinct count x slack."""
    s, first, distinct = _tile_sorted_cells(
        x, y, mask, bbox, width, height, data_tile)
    dn = np.asarray(distinct)
    # calibration-plan shapes: the tile list is sized once per
    # (batch, filter) calibration and reused via the returned calib,
    # so compiles track plan builds, not traffic
    # gt: waive GT28
    nt = len(dn)
    ids = np.nonzero(dn > 0)[0]
    if len(ids) == 0:
        return DensityCalib(
            np.zeros(0, np.int32), jnp.zeros((0, 8), jnp.int32), 8,
            np.zeros(0, np.int32), nt,
        )
    capd = int(min(MAX_CAPD, max(
        8, 1 << int(np.ceil(np.log2(max(
            float(np.median(dn[ids])) * slack, 2.0))))
    )))
    fits = dn[ids] <= capd
    sel = ids[fits].astype(np.int32)
    dicts = jnp.take(_tile_dicts(s, first, capd), jnp.asarray(sel), axis=0)
    return DensityCalib(
        sel, dicts, capd, ids[~fits].astype(np.int32), nt,
    )


def _make_kernel(data_tile: int, chunk: int, capd: int, bbox: BBox,
                 width: int, height: int, tpp: int):
    """tpp data tiles folded per program (each a separate scalar-indexed
    operand triple, the pip-kernel e_per idiom): at bench scale the
    one-tile-per-program grid paid ~16k program launches of fixed
    overhead (~33 ms) against ~6 ms of VPU work — tiles-per-program
    amortizes it tpp-fold. The filter mask arrives pre-folded into the
    weights (masked-out rows carry w=0), saving one operand array per
    tile and a full HBM pass over the mask."""

    def _kernel(ids_ref, dict_ref, *refs):
        out_ref = refs[-1]
        rows = []
        for e in range(tpp):
            x_ref, y_ref, w_ref = refs[3 * e: 3 * e + 3]
            drow = dict_ref[0, e, :].reshape(1, capd)
            acc = jnp.zeros((1, capd), jnp.float32)
            for s in range(data_tile // chunk):
                sl = slice(s * chunk, (s + 1) * chunk)
                cells, ok = _bin_cells(
                    x_ref[0, sl], y_ref[0, sl], True,
                    bbox, width, height,
                )
                # out-of-bounds zeroing folds into the f32 weights, NOT
                # a bool reshape: Mosaic rejects minor-dim insertion on i1.
                # f32-pinned zeros: bare 0.0 traces as weak f64 when the
                # interpret-mode kernel trace runs under global x64 mode
                zero = jnp.zeros((), jnp.float32)
                lw = jnp.where(ok, w_ref[0, sl], zero).reshape(chunk, 1)
                match = cells.reshape(chunk, 1) == drow
                acc = acc + jnp.sum(
                    jnp.where(match, lw, zero), axis=0,
                ).reshape(1, capd)
            rows.append(acc)
        out_ref[...] = jnp.concatenate(rows, axis=0).reshape(out_ref.shape)

    return _kernel


TILES_PER_PROGRAM = 4


@functools.partial(
    jax.jit,
    static_argnames=(
        "capd", "bbox", "width", "height", "data_tile", "chunk",
        "interpret", "tpp",
    ),
)
def _zsparse_call(
    x, y, lw, tile_ids, dicts,
    capd: int, bbox: BBox, width: int, height: int,
    data_tile: int, chunk: int, interpret: bool,
    tpp: int = TILES_PER_PROGRAM,
):
    """`lw` carries the mask pre-folded (w where mask else 0). VMEM
    budget at tpp=4, capd<=512: 12 data blocks x 128 KB (sublane-padded)
    x 2 (double-buffer) + the padded out stack block — comfortably
    inside the 16 MB scoped limit (tpp=8 with a separate mask operand
    measured 30.6 MB and failed to compile)."""
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n = x.shape[0]
    s0 = tile_ids.shape[0]
    # per-program VMEM scales with tpp * capd (data blocks + the
    # [chunk, capd] match transients): capd=512 at tpp=4 measured 16.35M
    # scoped and failed to compile — shrink tpp as the dictionary widens
    tpp = max(1, min(tpp, (64 * TILES_PER_PROGRAM) // max(capd, 64)))
    tpp = min(tpp, s0)
    pad = (-s0) % tpp
    if pad:
        # pad rows scan tile 0 against an all(-1) dictionary: nothing
        # matches, zeros fold into the sink slot
        tile_ids = jnp.concatenate(
            [tile_ids, jnp.zeros(pad, tile_ids.dtype)])
        dicts = jnp.concatenate(
            [dicts, jnp.full((pad, capd), -1, dicts.dtype)])
    s = s0 + pad
    xr = x.astype(jnp.float32).reshape(1, n)
    yr = y.astype(jnp.float32).reshape(1, n)
    wr = lw.astype(jnp.float32).reshape(1, n)
    dr = dicts.reshape(s // tpp, tpp, capd)

    def data_block(e):
        return pl.BlockSpec(
            (1, data_tile), lambda p, ids, e=e: (0, ids[p * tpp + e]))

    dict_block = pl.BlockSpec((1, tpp, capd), lambda p, ids: (p, 0, 0))
    data_specs = []
    data_args = []
    for e in range(tpp):
        data_specs.extend([data_block(e)] * 3)
        data_args.extend([xr, yr, wr])
    with _enable_x64(False):
        counts = pl.pallas_call(
            _make_kernel(data_tile, chunk, capd, bbox, width, height, tpp),
            grid_spec=pltpu.PrefetchScalarGridSpec(
                num_scalar_prefetch=1,
                grid=(s // tpp,),
                in_specs=[dict_block] + data_specs,
                out_specs=pl.BlockSpec(
                    (1, tpp, capd), lambda p, ids: (p, 0, 0)),
            ),
            out_shape=jax.ShapeDtypeStruct((s // tpp, tpp, capd),
                                           jnp.float32),
            interpret=interpret,
        )(tile_ids.astype(jnp.int32), dr, *data_args)
    return counts.reshape(s, capd)[:s0]


@functools.partial(jax.jit, static_argnames=("width", "height"))
def _fold_counts(counts, dicts, width: int, height: int):
    """Scatter per-tile count rows into the raster grid via their cell
    dictionaries (-1 pads route to a sink slot)."""
    sink = width * height
    idx = jnp.where(dicts < 0, sink, dicts)
    grid = jnp.zeros(sink + 1, jnp.float32)
    grid = grid.at[idx.reshape(-1)].add(counts.reshape(-1))
    return grid[:sink].reshape(height, width)


@functools.partial(
    jax.jit, static_argnames=("bbox", "width", "height")
)
def _expected_mass(x, y, w, mask, bbox: BBox, width: int, height: int):
    _, ok = _bin_cells(x, y, mask, bbox, width, height)
    # deliberate f64 accumulation: the mass check is the recall oracle
    # accumulation-only upcast: summing f32 weights in f64 bounds the
    # reduction error of the oracle itself; no claim is made about
    # pre-cast precision, so the exactness-leak rule does not apply
    # gt: waive GT29
    return jnp.sum(jnp.where(ok, w.astype(jnp.float64), 0.0))  # gt: f64-refine


def density_zsparse(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
    calib: Optional[DensityCalib] = None,
    data_tile: int = DATA_TILE,
    interpret: bool = False,
    check_stale: bool = True,
    stale_exact: bool = False,
) -> Tuple[jax.Array, DensityCalib]:
    """Store-order density grid (see module docstring). Returns
    ([height, width] f32 grid, calib) — pass `calib` back in on repeat
    queries over the same batch+filter to skip the calibration pass.
    Exact contract of `density.density_grid` for any input order; the
    sparse win requires store (Z) order, the fallback keeps it correct
    otherwise.

    A REUSED calib is validated (`check_stale`): unlike the kNN tile
    capacity, a stale density plan is a silent correctness failure (a
    point in a tile pruned under the OLD mask, or whose cell is missing
    from the tile's cached dictionary, would vanish from the grid), so
    the grid's total mass is checked against the mask's expected mass
    and a mismatch triggers automatic recalibration. Callers looping
    the IDENTICAL query (mask unchanged) may pass check_stale=False to
    skip the extra device reduction + fetch.

    With `stale_exact` (unweighted grids: cell values are small-integer
    counts, exact in f32), the mass check runs at atol=0.5 — ONE dropped
    point triggers recalibration. The default relative tolerance only
    bounds f32 summation noise for WEIGHTED grids; a sub-noise deficit
    (a handful of points against tens of millions) can pass it, so
    callers caching calibs across queries must key the cache on the
    FILTER as well as the arrays (see plan.runner._zsparse_grid)."""
    from geomesa_tpu.engine.density import density_grid

    reused_calib = calib is not None
    n = x.shape[0]
    pad = (-n) % data_tile
    xp = jnp.pad(x.astype(jnp.float32), (0, pad))
    yp = jnp.pad(y.astype(jnp.float32), (0, pad))
    wp = jnp.pad(weights.astype(jnp.float32), (0, pad))
    mp = jnp.pad(mask, (0, pad))
    if calib is None:
        calib = calibrate_density(
            xp, yp, mp, bbox, width, height, data_tile=data_tile
        )

    grid = jnp.zeros((height, width), jnp.float32)
    lwp = jnp.where(mp, wp, 0.0)  # mask pre-folded (one fused pass)
    if len(calib.tile_ids):
        # chunk the tile list so one call's output + dictionary operand
        # stay small (XLA may place a pallas output in VMEM; a full
        # [S, 1, cap] array blew the 16 MB scoped limit at bench scale)
        maxs = max(256, (1 << 19) // max(calib.capd, 1))
        S = len(calib.tile_ids)
        for c0 in range(0, S, maxs):
            c1 = min(c0 + maxs, S)
            ids_c = calib.tile_ids[c0:c1]
            dict_c = calib.dicts[c0:c1]
            # chunk pad: every chunk is padded up to the fixed `maxs`,
            # so the kernel sees one stable shape per calib plan (the
            # len() only sizes the pad amount)
            # gt: waive GT28
            pad_c = maxs - len(ids_c) if S > maxs else 0
            if pad_c:  # stable shapes across chunks (one compile)
                ids_c = np.concatenate(
                    [ids_c, np.full(pad_c, ids_c[0], ids_c.dtype)])
                dict_c = jnp.concatenate([
                    dict_c,
                    jnp.full((pad_c, calib.capd), -1, jnp.int32),
                ])
                # padding rows re-scan a real tile against an all-pad
                # dictionary: nothing matches, zeros fold into the sink
            counts = _zsparse_call(
                xp, yp, lwp,
                jnp.asarray(ids_c), jnp.asarray(dict_c),
                capd=calib.capd, bbox=tuple(bbox), width=width,
                height=height,
                data_tile=data_tile, chunk=min(CHUNK, data_tile),
                interpret=interpret,
            )
            grid = grid + _fold_counts(
                counts, dict_c, width=width, height=height)
    if len(calib.dense_ids):
        # overflow tiles (unsorted input / cell-dense regions): block-
        # gather their points and take the EXACT scatter path (the bf16
        # hi/lo MXU fallback failed the weighted cells-parity gate)
        ids = jnp.asarray(calib.dense_ids)
        gx = jnp.take(xp.reshape(-1, data_tile), ids, axis=0).reshape(-1)
        gy = jnp.take(yp.reshape(-1, data_tile), ids, axis=0).reshape(-1)
        gw = jnp.take(wp.reshape(-1, data_tile), ids, axis=0).reshape(-1)
        gm = jnp.take(mp.reshape(-1, data_tile), ids, axis=0).reshape(-1)
        grid = grid + density_grid(gx, gy, gw, gm, tuple(bbox),
                                   width, height)
    if reused_calib and check_stale:
        expected = float(_expected_mass(
            xp, yp, wp, mp, tuple(bbox), width, height))
        # accumulation-only upcast: the f32 grid is summed in f64 so
        # the mass comparison is not noise-limited; it feeds a
        # tolerance check, not an exact-f64 answer
        # gt: waive GT29
        got = float(np.asarray(grid, np.float64).sum())
        rtol, atol = (0.0, 0.5) if stale_exact else (1e-5, 1e-3)
        if not np.isclose(got, expected, rtol=rtol, atol=atol):
            # the cached plan no longer covers this mask: recalibrate
            return density_zsparse(
                x, y, weights, mask, bbox, width, height, calib=None,
                data_tile=data_tile, interpret=interpret,
            )
    return grid, calib


def density_zsparse_sharded(
    mesh,
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
    data_tile: int = DATA_TILE,
    interpret: bool = False,
):
    """Data-parallel cell-dictionary density over a device mesh.

    One GLOBAL calibration pass (per-tile dictionaries are a property of
    the row layout, not of the shard cut), partitioned by shard — rows
    are split contiguously and the shard size is a tile multiple, so a
    data tile never crosses a shard boundary. Each shard runs the same
    Pallas kernel over its local tiles (lists padded to a common length
    with all(-1) dictionaries — pad rows match nothing and fold zeros),
    overflow tiles take the exact per-shard scatter fallback, and the
    per-shard grids merge with one psum — the C25 reduction-tree shape
    (SURVEY.md:318-329) on XLA collectives.

    Returns the REPLICATED [height, width] grid (same contract as
    density_sharded)."""
    import jax.lax as lax
    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.utils.jaxcompat import shard_map

    from geomesa_tpu.engine.density import density_grid
    from geomesa_tpu.parallel.mesh import SHARD_AXIS

    D = int(np.prod(mesh.devices.shape))
    n = int(x.shape[0])
    per = n // D
    if n % D or per % data_tile:
        raise ValueError(
            f"n={n} must split into {D} shards of data_tile={data_tile} "
            "multiples (pad the batch; the planner's pow2 padding does)"
        )
    calib = calibrate_density(
        x, y, mask, bbox, width, height, data_tile=data_tile)
    tpd = per // data_tile

    def _partition(global_ids, payload=None, fill=0):
        """[n_sel] global tile ids -> ([D, S] local ids, [D, S] valid,
        optionally [D, S, ...] payload) padded to the max shard count."""
        shard_of = global_ids // tpd
        counts = np.bincount(shard_of, minlength=D)
        S = max(int(counts.max()), 1)
        ids = np.full((D, S), fill, np.int32)
        valid = np.zeros((D, S), bool)
        pay = None
        if payload is not None:
            pay = np.full((D, S) + payload.shape[1:], -1, payload.dtype)
        for d in range(D):
            sel = np.nonzero(shard_of == d)[0]
            ids[d, : len(sel)] = global_ids[sel] - d * tpd
            valid[d, : len(sel)] = True
            if payload is not None:
                pay[d, : len(sel)] = payload[sel]
        return ids, valid, pay

    sp_ids, _, sp_dicts = _partition(
        calib.tile_ids.astype(np.int64), np.asarray(calib.dicts))
    have_dense = len(calib.dense_ids) > 0
    if have_dense:
        dn_ids, dn_valid, _ = _partition(calib.dense_ids.astype(np.int64))
    else:
        dn_ids = np.zeros((D, 1), np.int32)
        dn_valid = np.zeros((D, 1), bool)

    capd = calib.capd
    bbox = tuple(bbox)

    def shard_fn(xl, yl, wl, ml, idsl, dictsl, didl, dvall):
        # sharded [D, ...] operands arrive with a leading length-1 dim
        idsl = idsl.reshape(-1)
        dictsl = dictsl.reshape(-1, capd)
        didl = didl.reshape(-1)
        dvall = dvall.reshape(-1)
        lwl = jnp.where(ml, wl, 0.0)  # mask pre-folded (driver idiom)
        # chunk the tile list exactly like the single-device driver: a
        # full [S, 1, capd] pallas output may land in VMEM and blew the
        # 16 MB scoped limit at bench scale (review finding — the mesh
        # path must survive the scale it exists for)
        S = int(idsl.shape[0])
        maxs = max(256, (1 << 19) // max(capd, 1))
        grid = jnp.zeros((height, width), jnp.float32)
        for c0 in range(0, S, maxs):
            c1 = min(c0 + maxs, S)
            counts = _zsparse_call(
                xl, yl, lwl, idsl[c0:c1], dictsl[c0:c1],
                capd=capd, bbox=bbox, width=width, height=height,
                data_tile=data_tile, chunk=min(CHUNK, data_tile),
                interpret=interpret,
            )
            grid = grid + _fold_counts(
                counts, dictsl[c0:c1], width=width, height=height)
        if have_dense:
            gx = jnp.take(xl.reshape(tpd, data_tile), didl, axis=0)
            gy = jnp.take(yl.reshape(tpd, data_tile), didl, axis=0)
            gw = jnp.take(wl.reshape(tpd, data_tile), didl, axis=0)
            gm = jnp.take(ml.reshape(tpd, data_tile), didl, axis=0)
            gm = gm & dvall[:, None]
            grid = grid + density_grid(
                gx.reshape(-1), gy.reshape(-1), gw.reshape(-1),
                gm.reshape(-1), bbox, width, height,
            )
        return lax.psum(grid, SHARD_AXIS)

    f = shard_map(
        shard_fn,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
        ),
        out_specs=P(),
        check_vma=False,  # pallas output vma; psum replicates (knn idiom)
    )
    return f(
        x.astype(jnp.float32), y.astype(jnp.float32),
        weights.astype(jnp.float32), mask,
        jnp.asarray(sp_ids), jnp.asarray(sp_dicts),
        jnp.asarray(dn_ids), jnp.asarray(dn_valid),
    )
