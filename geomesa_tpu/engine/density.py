"""Density (heatmap) kernels.

Parity: geomesa-index-api DensityScan + geomesa-process DensityProcess
[upstream, unverified]: rasterize matching features into a width x height
weight grid over a query envelope; per-shard partial grids merge by summation.
The reference runs this per tablet server and sums sparse grids client-side;
here it is one masked scatter-add per shard and one psum over ICI
(SURVEY.md §3.5: "the whole server+client merge in two ops").

Weights: uniform 1, a numeric attribute column, or any precomputed array.
Points outside the envelope never contribute (mask AND bounds check), and the
kernel-radius spread (DensityProcess radiusPixels) is applied as a separable
box/gaussian blur on the final grid host-side or via conv on device.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from geomesa_tpu.parallel.mesh import SHARD_AXIS

BBox = Tuple[float, float, float, float]


@functools.partial(jax.jit, static_argnames=("width", "height", "bbox"))
def density_grid(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
) -> jax.Array:
    """Masked scatter-add of points into a [height, width] f32 grid.

    Grid cell (row, col) covers
      lon in [xmin + col*dx, xmin + (col+1)*dx), lat analogously, row 0 at
    ymin (south) — callers flip for image rendering.
    """
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    col = jnp.floor((x - xmin) / dx).astype(jnp.int32)
    row = jnp.floor((y - ymin) / dy).astype(jnp.int32)
    inb = (col >= 0) & (col < width) & (row >= 0) & (row < height) & mask
    # clip so the scatter index is always in range; weight 0 where not inb
    col = jnp.clip(col, 0, width - 1)
    row = jnp.clip(row, 0, height - 1)
    w = jnp.where(inb, weights.astype(jnp.float32), 0.0)
    flat = jnp.zeros(height * width, jnp.float32)
    flat = flat.at[row * width + col].add(w)
    return flat.reshape(height, width)


def density_sharded(
    mesh: Mesh,
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
) -> jax.Array:
    """Sharded density: per-shard scatter + psum merge. Returns the full
    [height, width] grid, replicated."""

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(),
    )
    def run(x, y, w, m):
        g = density_grid(x, y, w, m, bbox, width, height)
        return jax.lax.psum(g, SHARD_AXIS)

    return run(x, y, weights, mask)


@functools.partial(jax.jit, static_argnames=("radius_pixels",))
def gaussian_blur(grid: jax.Array, radius_pixels: int) -> jax.Array:
    """Separable gaussian spread (DensityProcess radiusPixels analog)."""
    if radius_pixels <= 0:
        return grid
    sigma = jnp.float32(max(radius_pixels / 2.0, 0.5))
    r = radius_pixels
    xs = jnp.arange(-r, r + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (xs / sigma) ** 2)
    k = k / k.sum()
    # separable conv via vmap over rows then cols
    conv1 = lambda v: jnp.convolve(v, k, mode="same")
    blurred = jax.vmap(conv1)(grid)
    blurred = jax.vmap(conv1)(blurred.T).T
    return blurred
