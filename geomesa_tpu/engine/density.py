"""Density (heatmap) kernels.

Parity: geomesa-index-api DensityScan + geomesa-process DensityProcess
[upstream, unverified]: rasterize matching features into a width x height
weight grid over a query envelope; per-shard partial grids merge by summation.
The reference runs this per tablet server and sums sparse grids client-side;
here it is one masked scatter-add per shard and one psum over ICI
(SURVEY.md §3.5: "the whole server+client merge in two ops").

Weights: uniform 1, a numeric attribute column, or any precomputed array.
Points outside the envelope never contribute (mask AND bounds check), and the
kernel-radius spread (DensityProcess radiusPixels) is applied as a separable
box/gaussian blur on the final grid host-side or via conv on device.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map
from jax.sharding import Mesh, PartitionSpec as P

from geomesa_tpu.parallel.mesh import SHARD_AXIS

BBox = Tuple[float, float, float, float]


@functools.partial(jax.jit, static_argnames=("width", "height", "bbox"))
def density_grid(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
) -> jax.Array:
    """Masked scatter-add of points into a [height, width] f32 grid.

    Grid cell (row, col) covers
      lon in [xmin + col*dx, xmin + (col+1)*dx), lat analogously, row 0 at
    ymin (south) — callers flip for image rendering.
    """
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    col = jnp.floor((x - xmin) / dx).astype(jnp.int32)
    row = jnp.floor((y - ymin) / dy).astype(jnp.int32)
    inb = (col >= 0) & (col < width) & (row >= 0) & (row < height) & mask
    # clip so the scatter index is always in range; weight 0 where not inb
    col = jnp.clip(col, 0, width - 1)
    row = jnp.clip(row, 0, height - 1)
    w = jnp.where(inb, weights.astype(jnp.float32), 0.0)
    flat = jnp.zeros(height * width, jnp.float32)
    flat = flat.at[row * width + col].add(w)
    return flat.reshape(height, width)


@functools.partial(
    jax.jit, static_argnames=("width", "height", "bbox", "point_tile")
)
def density_grid_mxu(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
    point_tile: int = 8192,
) -> jax.Array:
    """Density via the MXU: per-tile one-hot matmuls instead of scatter.

    XLA's scatter-add serializes on TPU (~106ms for 4M points at 512x512,
    HBM bound is ~2ms). Reformulated: for a tile of T points,

        grid += onehot_rows[T, H]^T  @  (onehot_cols[T, W] * w[:, None])

    — an outer-product accumulation the systolic array does at matmul rate.
    One-hot entries are exactly representable in bf16; weights are split
    into bf16 hi + lo parts folded into the COLUMN one-hots of a doubled
    tile, so each product is an exact bf16 multiply and the f32 MXU
    accumulator sees w_hi + w_lo ≈ f32(w) per point. The two-term split
    recovers ~16 of f32's 24 mantissa bits (~2^-16 relative error per
    weight); unweighted counts are exact. Callers needing full f32 weight
    fidelity use the scatter path.

    Out-of-envelope or masked points get row index -1: their one-hot row is
    all zero, so they contribute nothing (same exclusion rule as
    `density_grid`).
    """
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    n = x.shape[0]
    pad = (-n) % point_tile
    xp = jnp.pad(x, (0, pad))
    yp = jnp.pad(y, (0, pad))
    wp = jnp.pad(weights.astype(jnp.float32), (0, pad))
    mp = jnp.pad(mask, (0, pad))

    col = jnp.floor((xp - xmin) / dx).astype(jnp.int32)
    row = jnp.floor((yp - ymin) / dy).astype(jnp.int32)
    inb = (col >= 0) & (col < width) & (row >= 0) & (row < height) & mp
    row = jnp.where(inb, row, -1)  # -1 -> all-zero one-hot row
    col = jnp.where(inb, col, 0)

    w_hi = wp.astype(jnp.bfloat16)
    w_lo = (wp - w_hi.astype(jnp.float32)).astype(jnp.bfloat16)

    iota_h = jnp.arange(height, dtype=jnp.int32)
    iota_w = jnp.arange(width, dtype=jnp.int32)

    def tile(grid, args):
        r, c, hi, lo = args
        rows = (r[:, None] == iota_h[None, :]).astype(jnp.bfloat16)
        cols = (c[:, None] == iota_w[None, :]).astype(jnp.bfloat16)
        # doubled tile: [2T, H] rows against hi- and lo-weighted cols
        rows2 = jnp.concatenate([rows, rows], axis=0)
        cols2 = jnp.concatenate(
            [cols * hi[:, None], cols * lo[:, None]], axis=0
        )
        grid = grid + jax.lax.dot_general(
            rows2, cols2, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        return grid, None

    init = jnp.zeros((height, width), jnp.float32)
    grid, _ = jax.lax.scan(
        tile,
        init,
        (
            row.reshape(-1, point_tile),
            col.reshape(-1, point_tile),
            w_hi.reshape(-1, point_tile),
            w_lo.reshape(-1, point_tile),
        ),
    )
    return grid


# one-hot tiles get memory-heavy past this grid edge ([T, 4096] bf16 = 64MB)
_MXU_MAX_EDGE = 4096
_MXU_MIN_POINTS = 1 << 17


def density_grid_auto(
    x, y, weights, mask, bbox, width, height, exact_weights: bool = False
) -> jax.Array:
    """Backend dispatch: the matmul formulation on TPU at scale, the
    scatter path elsewhere (CPU scatter is fine, and small batches don't
    amortize the one-hot construction). `exact_weights` pins the f32
    scatter path (the MXU bf16 hi/lo split carries ~2^-16 relative weight
    error); surfaced as the `density_exact_weights` query hint."""
    if (
        not exact_weights
        and jax.default_backend() == "tpu"
        and x.shape[0] >= _MXU_MIN_POINTS
        and max(width, height) <= _MXU_MAX_EDGE
    ):
        return density_grid_mxu(x, y, weights, mask, bbox, width, height)
    return density_grid(x, y, weights, mask, bbox, width, height)


def density_sharded(
    mesh: Mesh,
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
) -> jax.Array:
    """Sharded density: per-shard scatter + psum merge. Returns the full
    [height, width] grid, replicated."""

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=P(),
    )
    def run(x, y, w, m):
        g = density_grid(x, y, w, m, bbox, width, height)
        return jax.lax.psum(g, SHARD_AXIS)

    return run(x, y, weights, mask)


def make_density_sharded(mesh: Mesh):
    """Registry-compatible builder of the sharded density program
    (docs/SERVING.md "Sharded serving"): per-shard scatter-add + one
    psum over ICI, with bbox/width/height as static arguments so the
    serve path AOT-compiles one executable per (grid, bucket,
    mesh_shape) key instead of retracing the eager `density_sharded`
    closure on every query."""

    def run(x, y, weights, mask, bbox, width, height):
        @functools.partial(
            _shard_map,
            mesh=mesh,
            in_specs=(P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
                      P(SHARD_AXIS)),
            out_specs=P(),
        )
        def body(x, y, w, m):
            g = density_grid(x, y, w, m, bbox, width, height)
            return jax.lax.psum(g, SHARD_AXIS)

        return body(x, y, weights, mask)

    return run


def density_grid_slotted(
    x: jax.Array,
    y: jax.Array,
    weights: jax.Array,
    mask: jax.Array,
    bbox_slot: jax.Array,
    width: int,
    height: int,
) -> jax.Array:
    """Slot-parameterized `density_grid`: the query envelope is a
    DEVICE [4] f32 array (xmin, ymin, xmax, ymax) — a ring-slot input —
    instead of a static trace constant, so one long-lived executable
    per (grid shape, bucket) can serve every envelope without a new
    compile per window. GROUNDWORK for a density ring tier
    (docs/SERVING.md "Persistent serve loop" — today's ring dispatches
    kNN windows only; nothing registers this kernel yet).
    Bit-compatibility caveat a future caller MUST gate on: cell edges
    derive from f32 envelope arithmetic here versus the static path's
    python f64-then-f32 folding, so results match the static kernel
    only when the envelope round-trips f32 exactly (the common
    tile-aligned case) — that parity is what
    tests/test_ringloop.py::TestDensitySlotParity pins. Raw (un-jitted)
    on purpose: the ExecutableRegistry's ring tier owns its
    jit/donation wrapping."""
    xmin = bbox_slot[0]
    ymin = bbox_slot[1]
    xmax = bbox_slot[2]
    ymax = bbox_slot[3]
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    col = jnp.floor((x - xmin) / dx).astype(jnp.int32)
    row = jnp.floor((y - ymin) / dy).astype(jnp.int32)
    inb = (col >= 0) & (col < width) & (row >= 0) & (row < height) & mask
    col = jnp.clip(col, 0, width - 1)
    row = jnp.clip(row, 0, height - 1)
    w = jnp.where(inb, weights.astype(jnp.float32), 0.0)
    flat = jnp.zeros(height * width, jnp.float32)
    flat = flat.at[row * width + col].add(w)
    return flat.reshape(height, width)


@functools.partial(jax.jit, static_argnames=("radius_pixels",))
def gaussian_blur(grid: jax.Array, radius_pixels: int) -> jax.Array:
    """Separable gaussian spread (DensityProcess radiusPixels analog)."""
    if radius_pixels <= 0:
        return grid
    sigma = jnp.float32(max(radius_pixels / 2.0, 0.5))
    r = radius_pixels
    xs = jnp.arange(-r, r + 1, dtype=jnp.float32)
    k = jnp.exp(-0.5 * (xs / sigma) ** 2)
    k = k / k.sum()
    # separable conv via vmap over rows then cols
    conv1 = lambda v: jnp.convolve(v, k, mode="same")
    blurred = jax.vmap(conv1)(grid)
    blurred = jax.vmap(conv1)(blurred.T).T
    return blurred
