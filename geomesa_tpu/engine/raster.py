"""Density rasterization for extended geometries (lines / polygons).

Parity: geomesa-index-api DensityScan rasterizes non-point geometries into
the weight grid (SURVEY.md:258-259, C8) [upstream, unverified] — round 1
binned only a representative point per feature; these kernels close that
gap with TPU-first formulations (no per-feature control flow, static
shapes, one scatter + one cumsum instead of per-geometry rasterizer
loops):

- **Lines** (`line_density`): EXACT length-proportional apportioning. A
  feature's weight is distributed over cells proportional to the planar
  length of its path inside each cell, normalized by the feature's total
  planar length. Per segment, the cell-boundary crossings are parametric
  t-values forming two arithmetic sequences (vertical/horizontal grid
  lines); sorting the fixed-size t-array and scattering midpoint cells
  with dt-weights rasterizes every segment in one vectorized pass.
  Segments are Liang-Barsky-clipped to the envelope first so the static
  crossing budget k is bounded by the grid diagonal, not the data extent.

- **Polygons** (`polygon_density`): cell-center coverage — a cell receives
  the feature's full weight iff its center lies inside the polygon
  (holes excluded). Instead of per-polygon parity tests, the kernel
  exploits winding numbers over the ORIENTED flat edge table
  (core.columnar.EdgeTable guarantees shells CCW / holes CW): for a cell
  center p, sum over ALL edges of signed ray crossings s·w equals
  Σ_f w_f·winding_f(p) = Σ_f w_f·inside_f(p) — per-feature grouping
  disappears. Per edge and spanned grid row, the crossing column is
  scattered once into an [H, W+1] accumulator; a reversed exclusive
  row-cumsum then materializes "all cells left of the crossing" — total
  work O(E·rows_spanned + H·W) instead of O(E·H·W).

- **MultiPoint** (via `density_grid_geometry`): every vertex scatters the
  feature's full weight (each constituent point is an observation).

Self-intersecting polygons have winding ≠ parity and are out of contract
(the reference's JTS would reject them as invalid).

Static sizing (`k`) comes from host-side NumPy over the host edge table —
geometry is static per superbatch, so jit cache keys are stable across
queries at a fixed grid/bbox.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map
import numpy as np

BBox = Tuple[float, float, float, float]

_DEF_TILE_BUDGET = 1 << 22  # elements per [seg_tile, k] tile block


def _seg_tile(k: int) -> int:
    t = _DEF_TILE_BUDGET // max(k, 1)
    t = 1 << (int(t).bit_length() - 1)
    return int(min(max(t, 256), 8192))


def _clip_np(x1, y1, x2, y2, bbox):
    """Host Liang-Barsky: clipped (t0, t1, ok) per segment (f64 NumPy)."""
    xmin, ymin, xmax, ymax = bbox
    ddx, ddy = x2 - x1, y2 - y1
    t0 = np.zeros_like(x1)
    t1 = np.ones_like(x1)
    ok = np.ones(len(x1), dtype=bool)
    for p, q in (
        (-ddx, x1 - xmin),
        (ddx, xmax - x1),
        (-ddy, y1 - ymin),
        (ddy, ymax - y1),
    ):
        r = q / np.where(p == 0, 1.0, p)
        t0 = np.where(p < 0, np.maximum(t0, r), t0)
        t1 = np.where(p > 0, np.minimum(t1, r), t1)
        ok &= ~((p == 0) & (q < 0))
    ok &= t0 <= t1
    return t0, t1, ok


def line_crossing_bounds(
    x1, y1, x2, y2, bbox: BBox, width: int, height: int
) -> Tuple[int, int]:
    """Host: max vertical/horizontal grid-line crossings of any clipped
    segment — the static (kx, ky) budget for `line_density`."""
    if len(x1) == 0:
        return 1, 1
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    t0, t1, ok = _clip_np(x1, y1, x2, y2, bbox)
    ddx, ddy = x2 - x1, y2 - y1
    xa, xb = x1 + t0 * ddx, x1 + t1 * ddx
    ya, yb = y1 + t0 * ddy, y1 + t1 * ddy
    nx = np.floor((np.maximum(xa, xb) - xmin) / dx) - np.floor(
        (np.minimum(xa, xb) - xmin) / dx
    )
    ny = np.floor((np.maximum(ya, yb) - ymin) / dy) - np.floor(
        (np.minimum(ya, yb) - ymin) / dy
    )
    nx = np.where(ok, nx, 0)
    ny = np.where(ok, ny, 0)
    return int(max(nx.max(), 1)), int(max(ny.max(), 1))


def polygon_rowspan_bound(y1, y2, bbox: BBox, height: int) -> int:
    """Host: max grid rows spanned by any edge (clipped to the envelope) —
    the static k budget for `polygon_density`."""
    if len(y1) == 0:
        return 1
    _, ymin, _, ymax = bbox
    dy = (ymax - ymin) / height
    ylow = np.minimum(y1, y2)
    yhigh = np.maximum(y1, y2)
    rlo = np.maximum(np.ceil((ylow - ymin) / dy - 0.5), 0.0)
    rhi = np.minimum(np.ceil((yhigh - ymin) / dy - 0.5), float(height))
    return int(max((rhi - rlo).max(), 1))


@functools.partial(
    jax.jit,
    static_argnames=("bbox", "width", "height", "kx", "ky", "seg_tile"),
)
def line_density(
    x1: jax.Array,
    y1: jax.Array,
    x2: jax.Array,
    y2: jax.Array,
    wseg: jax.Array,
    segmask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
    kx: int,
    ky: int,
    seg_tile: int = 2048,
) -> jax.Array:
    """Exact length-proportional line rasterization -> [height, width] f32.

    `wseg` is the per-segment weight DENSITY factor: contribution of a
    t-interval dt inside one cell is wseg * dt, so callers pass
    w_feature * seg_len / total_feature_len for the documented semantics.
    """
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    f32 = jnp.float32
    n = x1.shape[0]
    pad = (-n) % seg_tile
    arrs = [
        jnp.pad(a.astype(f32), (0, pad)).reshape(-1, seg_tile)
        for a in (x1, y1, x2, y2, wseg)
    ]
    mp = jnp.pad(segmask, (0, pad)).reshape(-1, seg_tile)

    jx = jnp.arange(kx, dtype=f32)
    jy = jnp.arange(ky, dtype=f32)

    def tile(grid, args):
        ax1, ay1, ax2, ay2, w, m = args
        ddx = ax2 - ax1
        ddy = ay2 - ay1
        # Liang-Barsky clip to the envelope
        t0 = jnp.zeros_like(ax1)
        t1 = jnp.ones_like(ax1)
        ok = m
        for p, q in (
            (-ddx, ax1 - xmin),
            (ddx, xmax - ax1),
            (-ddy, ay1 - ymin),
            (ddy, ymax - ay1),
        ):
            r = q / jnp.where(p == 0, 1.0, p)
            t0 = jnp.where(p < 0, jnp.maximum(t0, r), t0)
            t1 = jnp.where(p > 0, jnp.minimum(t1, r), t1)
            ok = ok & ~((p == 0) & (q < 0))
        ok = ok & (t0 <= t1)
        t1c = jnp.maximum(t1, t0)

        # crossing t-values with vertical / horizontal grid lines: two
        # arithmetic sequences over the CLIPPED coordinate span, each t
        # computed against the ORIGINAL segment parameterization; invalid
        # slots park at t1 (zero-length intervals contribute nothing)
        def crossings(lo, hi, orig, delta, start, step, jj):
            i_first = jnp.floor((lo - start) / step) + 1.0
            cnt = jnp.floor((hi - start) / step) - i_first + 1.0
            line = start + (i_first[:, None] + jj[None, :]) * step
            t = (line - orig[:, None]) / jnp.where(delta == 0, 1.0, delta)[
                :, None
            ]
            return jnp.where(jj[None, :] < cnt[:, None], t, t1c[:, None])

        xa = ax1 + t0 * ddx
        xb = ax1 + t1c * ddx
        ya = ay1 + t0 * ddy
        yb = ay1 + t1c * ddy
        tx = crossings(
            jnp.minimum(xa, xb), jnp.maximum(xa, xb), ax1, ddx, xmin, dx, jx
        )
        ty = crossings(
            jnp.minimum(ya, yb), jnp.maximum(ya, yb), ay1, ddy, ymin, dy, jy
        )
        ts = jnp.concatenate(
            [t0[:, None], t1c[:, None], tx, ty], axis=1
        )  # [T, kx+ky+2]
        ts = jnp.clip(ts, t0[:, None], t1c[:, None])
        ts = jnp.sort(ts, axis=1)
        dt = jnp.diff(ts, axis=1)
        tm = (ts[:, 1:] + ts[:, :-1]) * 0.5
        xm = ax1[:, None] + tm * ddx[:, None]
        ym = ay1[:, None] + tm * ddy[:, None]
        colc = jnp.floor((xm - xmin) / dx).astype(jnp.int32)
        rowc = jnp.floor((ym - ymin) / dy).astype(jnp.int32)
        inb = (
            (colc >= 0)
            & (colc < width)
            & (rowc >= 0)
            & (rowc < height)
            & ok[:, None]
            & (dt > 0)
        )
        wv = jnp.where(inb, w[:, None] * dt, 0.0)
        idx = jnp.where(inb, rowc * width + colc, 0)
        grid = grid.at[idx.reshape(-1)].add(wv.reshape(-1))
        return grid, None

    init = jnp.zeros(height * width, f32)
    grid, _ = jax.lax.scan(tile, init, tuple(arrs) + (mp,))
    return grid.reshape(height, width)


@functools.partial(
    jax.jit, static_argnames=("bbox", "width", "height", "k", "seg_tile")
)
def polygon_density(
    x1: jax.Array,
    y1: jax.Array,
    x2: jax.Array,
    y2: jax.Array,
    wedge: jax.Array,
    edgemask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
    k: int,
    seg_tile: int = 2048,
) -> jax.Array:
    """Cell-center polygon coverage -> [height, width] f32 grid.

    Requires the oriented edge table (shells CCW, holes CW); `wedge` is the
    owning feature's weight replicated per edge.
    """
    return jnp.maximum(
        _polygon_density_signed(
            x1, y1, x2, y2, wedge, edgemask, bbox, width, height, k,
            seg_tile,
        ),
        0.0,
    )


def _polygon_density_signed(
    x1, y1, x2, y2, wedge, edgemask, bbox: BBox,
    width: int, height: int, k: int, seg_tile: int = 2048,
) -> jax.Array:
    """Signed (pre-clamp) winding grid — linear in the edge set."""
    xmin, ymin, xmax, ymax = bbox
    dx = (xmax - xmin) / width
    dy = (ymax - ymin) / height
    f32 = jnp.float32
    n = x1.shape[0]
    pad = (-n) % seg_tile
    arrs = [
        jnp.pad(a.astype(f32), (0, pad)).reshape(-1, seg_tile)
        for a in (x1, y1, x2, y2, wedge)
    ]
    mp = jnp.pad(edgemask, (0, pad)).reshape(-1, seg_tile)
    jj = jnp.arange(k, dtype=f32)

    def tile(acc, args):
        ax1, ay1, ax2, ay2, w, m = args
        ddy = ay2 - ay1
        s = jnp.where(ddy > 0, 1.0, -1.0)
        ylow = jnp.minimum(ay1, ay2)
        yhigh = jnp.maximum(ay1, ay2)
        rlo = jnp.maximum(jnp.ceil((ylow - ymin) / dy - 0.5), 0.0)
        rhi = jnp.minimum(
            jnp.ceil((yhigh - ymin) / dy - 0.5), float(height)
        )
        r = rlo[:, None] + jj[None, :]
        valid = (
            (jj[None, :] < (rhi - rlo)[:, None])
            & m[:, None]
            & (ddy != 0)[:, None]
        )
        py = ymin + (r + 0.5) * dy
        t = (py - ay1[:, None]) / jnp.where(ddy == 0, 1.0, ddy)[:, None]
        xc = ax1[:, None] + t * (ax2 - ax1)[:, None]
        # cells with center strictly left of the crossing receive the
        # signed weight: scatter at the crossing column, prefix later
        cmax = jnp.ceil((xc - xmin) / dx - 0.5)
        valid = valid & (cmax >= 1)
        colp = jnp.minimum(cmax, float(width)).astype(jnp.int32)
        rowp = r.astype(jnp.int32)
        wv = jnp.where(valid, (s * w)[:, None], 0.0)
        idx = jnp.where(valid, rowp * (width + 1) + colp, 0)
        acc = acc.at[idx.reshape(-1)].add(wv.reshape(-1))
        return acc, None

    # derive the init from the inputs so it inherits their varying-
    # mesh-axes tag (lax.scan carry typing under shard_map — same trick
    # as engine.knn)
    vzero = jnp.sum(x1[:1].astype(f32) * 0)
    init = jnp.zeros(height * (width + 1), f32) + vzero
    acc, _ = jax.lax.scan(tile, init, tuple(arrs) + (mp,))
    a = acc.reshape(height, width + 1)
    rev = jnp.cumsum(a[:, ::-1], axis=1)[:, ::-1]
    # f32 boundary band (same caveat as engine.pip_pallas): a cell center
    # within ~1e-6 relative of an edge crossing can see one signed
    # contribution flip sides, leaving a spurious ±w residue in that cell.
    # Clamp keeps the grid non-negative; the affected weight mass is
    # bounded by the band width (tested against the f64 oracle as a
    # mismatch-mass fraction, not bitwise).
    # The PRE-clamp grid is linear in the edge set (scatter + cumsum are
    # both linear), which is what lets polygon_density_sharded psum
    # per-shard signed grids and clamp ONCE at the end (polygon_density
    # itself applies the clamp).
    return rev[:, 1:]


def polygon_density_sharded(
    mesh,
    x1: jax.Array,
    y1: jax.Array,
    x2: jax.Array,
    y2: jax.Array,
    wedge: jax.Array,
    edgemask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
    k: int,
    seg_tile: int = 2048,
) -> jax.Array:
    """polygon_density with the oriented EDGE table sharded over the mesh:
    per-shard signed winding grids psum-merge exactly (the signed grid is
    linear in edges; edges of one polygon may land on different shards),
    clamped once after the merge. Returns the full grid, replicated."""
    import functools as _ft

    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.parallel.mesh import SHARD_AXIS

    @_ft.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(SHARD_AXIS),) * 6,
        out_specs=P(),
    )
    def run(a, b, c, d, w, m):
        # per-shard signed grid = polygon_density minus its final clamp:
        # recompute via the public kernel on the shard, minus clamping --
        # the clamp is idempotent on the true grid but NOT linear, so it
        # must not run before the psum. We get the signed grid by running
        # the kernel body with clamping disabled.
        g = _polygon_density_signed(
            a, b, c, d, w, m, bbox, width, height, k, seg_tile
        )
        return jnp.maximum(jax.lax.psum(g, SHARD_AXIS), 0.0)

    return run(x1, y1, x2, y2, wedge, edgemask)


def _pow2(n: int) -> int:
    return 1 << max(int(n) - 1, 0).bit_length()


def density_grid_geometry(
    geom_col,
    dev: dict,
    name: str,
    weights: jax.Array,
    mask: jax.Array,
    bbox: BBox,
    width: int,
    height: int,
) -> jax.Array:
    """Dispatch density rasterization by geometry kind.

    `geom_col` is the HOST GeometryColumn (static sizing source), `dev` the
    device batch carrying the matching CSR/edge arrays, `weights`/`mask`
    per-FEATURE device arrays. Static k budgets are rounded to pow2 so jit
    caches stay warm across small data changes.

    Mixed "Geometry" columns split per base kind (feature_kinds codes) and
    sum the three sub-grids — running everything through the polygon kernel
    would cancel line/point winding contributions to zero.
    """
    kind = geom_col.kind
    if kind in ("Geometry", "GeometryCollection"):
        return _density_mixed(
            geom_col, name, weights, mask, bbox, width, height
        )
    efeat = dev[f"{name}__efeat"]
    ex1, ey1 = dev[f"{name}__ex1"], dev[f"{name}__ey1"]
    ex2, ey2 = dev[f"{name}__ex2"], dev[f"{name}__ey2"]
    et = geom_col.edge_table()
    if "Point" in kind:  # MultiPoint: every vertex scatters full weight
        from geomesa_tpu.engine.density import density_grid

        vfeat = dev[f"{name}__vfeat"]
        verts = dev[f"{name}__verts"]
        return density_grid(
            verts[:, 0],
            verts[:, 1],
            weights[vfeat],
            mask[vfeat],
            bbox,
            width,
            height,
        )
    if "LineString" in kind:
        kx, ky = line_crossing_bounds(
            et.x1, et.y1, et.x2, et.y2, bbox, width, height
        )
        # +1 margin: the host bound is f64, the kernel counts in f32 — a
        # rounding flip at a cell boundary may admit one extra crossing
        kx, ky = _pow2(kx + 1), _pow2(ky + 1)
        seg_len = jnp.hypot(ex2 - ex1, ey2 - ey1)
        # per-batch geometry extents: the feature/segment counts are
        # fixed by the loaded batch (warmed at ingest), not by the
        # request — the rasterizer compiles once per dataset load
        total = jax.ops.segment_sum(
            seg_len, efeat, num_segments=len(geom_col)  # gt: waive GT28
        )
        wseg = (
            weights[efeat]
            * seg_len
            / jnp.where(total == 0, 1.0, total)[efeat]
        )
        return line_density(
            ex1, ey1, ex2, ey2, wseg, mask[efeat],
            bbox, width, height, kx, ky,
            seg_tile=_seg_tile(kx + ky + 2),
        )
    k = _pow2(polygon_rowspan_bound(et.y1, et.y2, bbox, height) + 1)
    return polygon_density(
        ex1, ey1, ex2, ey2, weights[efeat], mask[efeat],
        bbox, width, height, k, seg_tile=_seg_tile(k),
    )


def _density_mixed(
    geom_col, name: str, weights, mask, bbox: BBox, width: int, height: int
):
    """Mixed-kind density: split the host column per base kind (codes
    0-5 -> code % 3), upload each subset's CSR/edge arrays ad hoc, and sum
    the sub-grids. GeometryCollection features (code 6) have no single
    base kind and degrade to representative-point binning — a documented
    approximation, never a silent zero. Mixed layers are rare and small
    relative to the bench paths, so the per-subset host round trip is
    acceptable; homogeneous columns never come through here.
    """
    import dataclasses

    codes = geom_col.feature_kinds
    from geomesa_tpu.engine.density import density_grid

    if codes is None:
        # no per-feature info (e.g. a column built before round 2 and
        # deserialized from a cache): every feature degrades to its
        # representative point rather than silently cancelling to zero
        return density_grid(
            jnp.asarray(geom_col.x, jnp.float32),
            jnp.asarray(geom_col.y, jnp.float32),
            weights,
            mask,
            bbox,
            width,
            height,
        )
    grid = jnp.zeros((height, width), jnp.float32)
    coll = np.nonzero(codes == 6)[0]
    if len(coll):
        jc = jnp.asarray(coll)
        grid = grid + density_grid(
            jnp.asarray(geom_col.x[coll], jnp.float32),
            jnp.asarray(geom_col.y[coll], jnp.float32),
            jnp.take(weights, jc),
            jnp.take(mask, jc),
            bbox,
            width,
            height,
        )
    base = codes % 3
    for code, sub_kind in ((0, "MultiPoint"), (1, "MultiLineString"), (2, "MultiPolygon")):
        idx = np.nonzero((base == code) & (codes != 6))[0]
        if not len(idx):
            continue
        sub = dataclasses.replace(geom_col.take(idx), kind=sub_kind, feature_kinds=None)
        et = sub.edge_table()
        sub_dev = {
            f"{name}__efeat": jnp.asarray(et.efeat, jnp.int32),
            f"{name}__ex1": jnp.asarray(et.x1, jnp.float32),
            f"{name}__ey1": jnp.asarray(et.y1, jnp.float32),
            f"{name}__ex2": jnp.asarray(et.x2, jnp.float32),
            f"{name}__ey2": jnp.asarray(et.y2, jnp.float32),
            f"{name}__vfeat": jnp.asarray(et.vfeat, jnp.int32),
            f"{name}__verts": jnp.asarray(sub.vertices, jnp.float32),
        }
        jidx = jnp.asarray(idx)
        grid = grid + density_grid_geometry(
            sub,
            sub_dev,
            name,
            jnp.take(weights, jidx),
            jnp.take(mask, jidx),
            bbox,
            width,
            height,
        )
    return grid
