"""Extended-geometry predicate kernels over per-shard CSR tiles.

Parity role: the JTS prepared-geometry predicate evaluation the reference
applies to line/polygon features [upstream, unverified], restated in the
engine's mask-kernel idiom. The residency tier (store.cache._extended_tiles)
hands each chip an offset-rewritten CSR slice of the store's vertex/ring/edge
buffers — [D, vp, 2] vertices, [D, ep] edge tables, pow2-padded per bucket —
and the kernels here evaluate INTERSECTS / DWITHIN-style predicates per
feature with pure segment reductions (no host loop per geometry; that
antipattern is what analysis rule GT28 guards against).

Exactness contract (same shape as the kNN band corrections): the device scan
runs in f32 and ALSO emits a conservative ambiguity band — rows whose
decision could flip under f32 coordinate rounding (boundary-proximate PiP,
near-degenerate orientation tests, distances within meters of the
threshold). Callers re-decide banded rows on host in f64 against the
ORIGINAL geometry via cql.hosteval — the f64 oracle itself — so the final
mask is bit-identical to `eval_filter_host` on every route.

Semantics mirror cql.hosteval._geom_predicate_np / _eval_distance exactly:
  intersects = bbox_overlap AND (any feature vertex in literal OR any
               literal vertex in feature OR any proper edge crossing)
  dwithin    = (min feature-vertex -> literal-segment planar distance <= d)
               OR intersects
with the identical half-open crossing-number edge rule (engine.pip) and the
identical deg_m/coslat planar projection (111_194.9 m per degree).
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from geomesa_tpu.engine.pip import (
    BAND_EPS,
    points_in_polygon,
    points_in_polygon_band,
    polygon_edges,
)
from geomesa_tpu.parallel.mesh import SHARD_AXIS
from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map

# must equal cql.hosteval._dist_to_segment_arrays_np's constant
DEG_M = 111_194.9

# distance band (meters): dominates the f64->f32 coordinate cast (~2.5 m
# at |lon| <= 180) with a relative term for long-haul thresholds
DIST_BAND_M = 10.0
DIST_BAND_REL = 1e-3

# orientation-test band: |cross| below this coordinate-scaled epsilon may
# flip sign under f32 rounding (3e-5 deg ~ 2x the f32 ulp at 180)
ORIENT_EPS = 3.0e-5


def _cross(ox, oy, px, py, qx, qy):
    return (px - ox) * (qy - oy) - (py - oy) * (qx - ox)


def _cross_eps(ox, oy, px, py, qx, qy):
    return ORIENT_EPS * (
        jnp.abs(px - ox) + jnp.abs(py - oy)
        + jnp.abs(qx - ox) + jnp.abs(qy - oy)
    ) + 1e-12


@functools.partial(
    jax.jit,
    static_argnames=("n_rows", "poly_lit", "poly_a", "want_dist"),
)
def extended_predicate_tile(
    vx, vy, vfeat,
    ex1, ey1, ex2, ey2, efeat,
    bbox,
    lx1, ly1, lx2, ly2,
    lvx, lvy,
    lit_bbox,
    dist_m,
    *,
    n_rows: int,
    poly_lit: bool,
    poly_a: bool,
    want_dist: bool,
) -> Tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """One shard's predicate scan: feature CSR tile vs one literal.

    vx/vy [vp] + vfeat [vp] (pad id = n_rows), edge table [ep] + efeat
    (pad id = n_rows), bbox [n_rows, 4]; literal edges [L], literal
    vertices [Lv], lit_bbox [4] (xmin, ymin, xmax, ymax). Returns
    (bbox_overlap, intersects, band_intersects, dwithin_or_intersects,
    band_dwithin), each bool [n_rows]. Pad rows (NaN bbox) fail every
    comparison; pad vertex/edge slots bucket into segment n_rows and
    are sliced off."""
    ns = n_rows + 1
    eps = jnp.asarray(BAND_EPS, vx.dtype)
    zrows = jnp.zeros((n_rows,), bool)

    ov = (
        (bbox[:, 0] <= lit_bbox[2]) & (bbox[:, 2] >= lit_bbox[0])
        & (bbox[:, 1] <= lit_bbox[3]) & (bbox[:, 3] >= lit_bbox[1])
    )
    bbox_band = (
        (jnp.abs(bbox[:, 0] - lit_bbox[2]) <= eps)
        | (jnp.abs(bbox[:, 2] - lit_bbox[0]) <= eps)
        | (jnp.abs(bbox[:, 1] - lit_bbox[3]) <= eps)
        | (jnp.abs(bbox[:, 3] - lit_bbox[1]) <= eps)
    )

    # feature vertices inside the literal (only meaningful for polygonal
    # literals — hosteval returns all-False otherwise)
    if poly_lit and lx1.shape[0]:
        in_v = points_in_polygon(vx, vy, lx1, ly1, lx2, ly2)
        bd_v = points_in_polygon_band(vx, vy, lx1, ly1, lx2, ly2)
        a_in = jax.ops.segment_max(
            in_v.astype(jnp.int32), vfeat, num_segments=ns)[:n_rows] > 0
        a_band = jax.ops.segment_max(
            bd_v.astype(jnp.int32), vfeat, num_segments=ns)[:n_rows] > 0
    else:
        a_in, a_band = zrows, zrows

    # literal vertices inside the feature: crossing-number counted per
    # feature by a segment_sum over the edge table (identical edge rule
    # to engine.pip, bucketed instead of dense)
    if poly_a and lvx.shape[0] and ex1.shape[0]:
        py = lvy[None, :]
        y1, y2 = ey1[:, None], ey2[:, None]
        x1, x2 = ex1[:, None], ex2[:, None]
        cond = (y1 <= py) != (y2 <= py)
        t = (py - y1) / jnp.where(y2 == y1, 1.0, y2 - y1)
        xc = x1 + t * (x2 - x1)
        contrib = (cond & (xc > lvx[None, :])).astype(jnp.int32)
        cnt = jax.ops.segment_sum(
            contrib, efeat, num_segments=ns)[:n_rows]
        lit_in = jnp.any((cnt % 2) == 1, axis=1)
        near_flat = (
            (jnp.abs(py - y1) <= eps) & (jnp.abs(py - y2) <= eps)
            & (lvx[None, :] >= jnp.minimum(x1, x2) - eps)
            & (lvx[None, :] <= jnp.maximum(x1, x2) + eps)
        )
        err = eps * (
            1.0 + jnp.abs(x2 - x1)
            / jnp.maximum(jnp.abs(y2 - y1), eps)
        )
        near_cross = cond & (jnp.abs(xc - lvx[None, :]) <= err)
        lit_band = jax.ops.segment_max(
            jnp.any(near_flat | near_cross, axis=1).astype(jnp.int32),
            efeat, num_segments=ns)[:n_rows] > 0
    else:
        lit_in, lit_band = zrows, zrows

    # proper edge crossings (strict orientation signs, collinear = no
    # crossing — exactly _segments_cross); any |d| inside its epsilon
    # means the f32 sign is untrustworthy -> band
    if lx1.shape[0] and ex1.shape[0]:
        a1x, a1y = ex1[:, None], ey1[:, None]
        a2x, a2y = ex2[:, None], ey2[:, None]
        b1x, b1y = lx1[None, :], ly1[None, :]
        b2x, b2y = lx2[None, :], ly2[None, :]
        d1 = _cross(b1x, b1y, b2x, b2y, a1x, a1y)
        d2 = _cross(b1x, b1y, b2x, b2y, a2x, a2y)
        d3 = _cross(a1x, a1y, a2x, a2y, b1x, b1y)
        d4 = _cross(a1x, a1y, a2x, a2y, b2x, b2y)
        crossing = ((d1 > 0) != (d2 > 0)) & ((d3 > 0) != (d4 > 0))
        near = (
            (jnp.abs(d1) <= _cross_eps(b1x, b1y, b2x, b2y, a1x, a1y))
            | (jnp.abs(d2) <= _cross_eps(b1x, b1y, b2x, b2y, a2x, a2y))
            | (jnp.abs(d3) <= _cross_eps(a1x, a1y, a2x, a2y, b1x, b1y))
            | (jnp.abs(d4) <= _cross_eps(a1x, a1y, a2x, a2y, b2x, b2y))
        )
        cr = jax.ops.segment_max(
            jnp.any(crossing, axis=1).astype(jnp.int32),
            efeat, num_segments=ns)[:n_rows] > 0
        cr_band = jax.ops.segment_max(
            jnp.any(near, axis=1).astype(jnp.int32),
            efeat, num_segments=ns)[:n_rows] > 0
    else:
        cr, cr_band = zrows, zrows

    its = ov & (a_in | lit_in | cr)
    # a robustly-disjoint bbox cannot flip regardless of component bands
    band_its = bbox_band | (ov & (a_band | lit_band | cr_band))

    if want_dist:
        # min feature-vertex -> literal-segment distance, the hosteval
        # planar projection verbatim (deg_m * coslat per POINT latitude)
        coslat = jnp.cos(jnp.radians(vy))[:, None]
        ax = (lx1[None, :] - vx[:, None]) * DEG_M * coslat
        ay = (ly1[None, :] - vy[:, None]) * DEG_M
        bx = (lx2[None, :] - vx[:, None]) * DEG_M * coslat
        by = (ly2[None, :] - vy[:, None]) * DEG_M
        dx, dy = bx - ax, by - ay
        L2 = jnp.maximum(dx * dx + dy * dy, 1e-12)
        tt = jnp.clip(-(ax * dx + ay * dy) / L2, 0.0, 1.0)
        cx, cy = ax + tt * dx, ay + tt * dy
        dmin_v = jnp.sqrt(jnp.min(cx * cx + cy * cy, axis=1))
        big = jnp.asarray(np.finfo(np.float32).max, dmin_v.dtype)
        dmin = jax.ops.segment_min(
            jnp.where(vfeat < n_rows, dmin_v, big),
            vfeat, num_segments=ns)[:n_rows]
        dw = (dmin <= dist_m) | its
        dband = jnp.asarray(
            DIST_BAND_M, dmin.dtype) + DIST_BAND_REL * dist_m
        band_dw = (jnp.abs(dmin - dist_m) <= dband) | band_its
    else:
        dw, band_dw = zrows, zrows

    return ov, its, band_its, dw, band_dw


def make_extended_sharded(
    mesh: Mesh,
    *,
    n_rows: int,
    poly_lit: bool,
    poly_a: bool,
    want_dist: bool,
    want_count: bool = False,
):
    """shard_map variant: each chip scans ITS CSR tile (leading-axis
    slice of the [D, ...] tile stacks) against the replicated literal;
    outputs stay row-sharded like the store. With `want_count` the
    dispatch also returns the psum'd fused count of f32-intersecting
    valid rows (pre-band-refinement — callers use it only when the band
    comes back empty)."""

    data = tuple(P(SHARD_AXIS) for _ in range(10))  # tiles + bbox + valid
    lit = tuple(P() for _ in range(8))              # literal + dist

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=data + lit,
        out_specs=(
            (P(SHARD_AXIS),) * 5 + ((P(),) if want_count else ())
        ),
        check_vma=False,
    )
    def run(verts, vfeat, ex1, ey1, ex2, ey2, efeat, bbox, valid,
            pids, lx1, ly1, lx2, ly2, lvx, lvy, lit_bbox, dist_m):
        res = extended_predicate_tile(
            verts[0, :, 0], verts[0, :, 1], vfeat[0],
            ex1[0], ey1[0], ex2[0], ey2[0], efeat[0],
            bbox,
            lx1, ly1, lx2, ly2, lvx, lvy, lit_bbox, dist_m,
            n_rows=n_rows, poly_lit=poly_lit, poly_a=poly_a,
            want_dist=want_dist,
        )
        if not want_count:
            return res
        hit = (res[3] if want_dist else res[1]) & valid & (pids >= 0)
        count = jax.lax.psum(
            jnp.sum(hit, dtype=jnp.int64), SHARD_AXIS)
        return res + (count,)

    return run


# -- host orchestration ------------------------------------------------------


_SUPPORTED_SPATIAL = ("BBOX", "INTERSECTS", "DISJOINT")
_SUPPORTED_DISTANCE = ("DWITHIN", "BEYOND")
_POLY_KINDS = ("Polygon", "MultiPolygon")


def _poly_vertices_np(g) -> np.ndarray:
    return (
        np.concatenate(g.rings, axis=0).astype(np.float64)
        if g.rings else np.zeros((0, 2))
    )


def _literal_arrays(g):
    """Literal geometry -> the exact arrays hosteval's formulas see:
    ring edges (degenerate vertex segments for point-cloud literals,
    mirroring _dist_to_segments_np), vertices, bbox."""
    x1, y1, x2, y2 = polygon_edges(g)
    if len(x1) == 0:
        pts = _poly_vertices_np(g)
        x1 = x2 = pts[:, 0]
        y1 = y2 = pts[:, 1]
    pts = _poly_vertices_np(g)
    return (
        np.asarray(x1, np.float64), np.asarray(y1, np.float64),
        np.asarray(x2, np.float64), np.asarray(y2, np.float64),
        pts[:, 0], pts[:, 1],
        np.asarray(g.bbox, np.float64),
    )


def tile_predicate(f, sb):
    """Single extended spatial/distance predicate, evaluated on the
    mesh's CSR tiles -> exact host bool [N] (f32 scan + f64 band
    refinement via cql.hosteval, so bit-identical to eval_filter_host).
    Returns None when `f` is not a supported single-predicate shape or
    the superbatch carries no tile for its attribute — callers fall
    back to full host evaluation."""
    from geomesa_tpu.cql import ast
    from geomesa_tpu.cql.hosteval import eval_filter_host

    if isinstance(f, ast.SpatialPredicate):
        if f.op not in _SUPPORTED_SPATIAL:
            return None
        want_dist, dist = False, 0.0
    elif isinstance(f, ast.DistancePredicate):
        if f.op not in _SUPPORTED_DISTANCE:
            return None
        want_dist, dist = True, float(f.distance_m)
    else:
        return None
    name = f.prop.name
    if f"{name}__verts" not in getattr(sb, "tiles", {}):
        return None
    col = sb.batch.columns.get(name)
    if col is None or col.is_point or col.feature_kinds is not None:
        # mixed-kind collections need per-feature poly_a: host path
        return None
    g = f.geometry
    d = int(sb.mesh.devices.size)
    n = len(sb.batch)
    n_rows = n // d
    lx1, ly1, lx2, ly2, lvx, lvy, lbb = _literal_arrays(g)
    run = make_extended_sharded(
        sb.mesh,
        n_rows=n_rows,
        poly_lit=g.kind in _POLY_KINDS,
        poly_a=col.kind in _POLY_KINDS,
        want_dist=want_dist,
    )
    t = sb.tiles
    f32 = np.float32
    ov, its, band_its, dw, band_dw = run(
        t[f"{name}__verts"], t[f"{name}__vfeat"],
        t[f"{name}__ex1"], t[f"{name}__ey1"],
        t[f"{name}__ex2"], t[f"{name}__ey2"], t[f"{name}__efeat"],
        sb.dev[f"{name}__bbox"], sb.dev["__valid__"], sb.pids,
        jnp.asarray(lx1, f32), jnp.asarray(ly1, f32),
        jnp.asarray(lx2, f32), jnp.asarray(ly2, f32),
        jnp.asarray(lvx, f32), jnp.asarray(lvy, f32),
        jnp.asarray(lbb, f32), jnp.asarray(dist, f32),
    )
    ov, its, band_its, dw, band_dw = jax.device_get(
        (ov, its, band_its, dw, band_dw))
    if isinstance(f, ast.SpatialPredicate):
        if f.op == "BBOX":
            base, band = ov, band_its
        else:
            base = ~its if f.op == "DISJOINT" else its
            band = band_its
    else:
        base = ~dw if f.op == "BEYOND" else dw
        band = band_dw
    valid = (
        sb.batch.valid if sb.batch.valid is not None
        else np.ones(n, bool)
    )
    mask = np.asarray(base) & valid
    rows = np.nonzero(np.asarray(band) & valid)[0]
    if len(rows):
        # f64 re-decision against the ORIGINAL geometry — hosteval IS
        # the oracle, so banded rows land bit-identical by construction
        mask[rows] = eval_filter_host(f, sb.batch.select(rows))
    return mask


def host_exact_mask(f, sb) -> np.ndarray:
    """Exact (f64-oracle-identical) filter mask for an extended-store
    mesh superbatch, validity folded: the tile kernels when `f` is a
    single supported predicate, full host f64 evaluation otherwise.
    The planner memoizes the row-sharded device copy per (filter,
    superbatch), so either path costs once per manifest snapshot."""
    from geomesa_tpu.cql.hosteval import eval_filter_host

    m = tile_predicate(f, sb)
    if m is None:
        m = eval_filter_host(f, sb.batch)
    return m
