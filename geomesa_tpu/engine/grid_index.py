"""Device-side grid index: O(N) build, per-query candidate pruning for kNN.

Parity role: the reference's KNN process avoids scanning the world by
windowed index queries (KNearestNeighborSearchProcess's estimated-radius
window + geometric expansion — SURVEY.md §3.4); its exactness comes from
re-querying until the window provably contains the true neighbors. This is
the TPU-native equivalent: a batch-resident spatial index built ON DEVICE
(one sort), then per-query candidate gathering from a fixed cell
neighborhood with a per-query EXACTNESS CERTIFICATE, and a fallback scan
for the (rare) queries the certificate cannot prove.

Index build (O(N log N) sort, amortized over all queries against a batch):
  cell(p) = (floor((lon+180)/360*G), floor((lat+90)/180*G)) on a G x G
  lon/lat grid; points argsorted by where(mask, cell_id, G*G) so masked
  rows sink to the tail; per-cell [start, end) offsets by searchsorted.

Query (static shapes): each query gathers the (2R+1)^2 cell neighborhood
around its own cell, S candidate slots per cell (cells larger than S set an
overflow flag), computes exact haversine over the gathered candidates, and
takes top-k.

Certificate (sphere-safe): every point OUTSIDE the searched square differs
from the query by >= dlat degrees latitude or >= dlon degrees longitude
(to the square's nearer unsearched edge). Lower bounds on its distance:
  lat:  d >= R * dlat_rad                      (meridian arc)
  lon:  d >= R * asin(sin(dlon_rad) * cos(lat_q))   (distance to the
        meridian great circle every path must cross; valid dlon <= 90deg)
The result is exact iff kth_dist <= min(edge bounds), no gathered cell
overflowed, fewer than k candidates never happened, and no clipped grid
edge hides wraparound neighbors (lon edges; lat edges are true poles).
Flagged queries are re-run by the caller on an exact full-scan path
(`knn`/`knn_mxu`) — the moral equivalent of the reference's window
expansion loop, except the common case needs no second round trip.
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map

from geomesa_tpu.engine.geodesy import EARTH_RADIUS_M, haversine_m
from geomesa_tpu.utils.padding import next_pow2

INF = jnp.float32(jnp.inf)


def auto_grid_params(match_count: int,
                     per_cell_target: int = 16) -> Tuple[int, int]:
    """(g, cell_slots) sized to the matched-point count: grid edge chosen
    so the GLOBAL-mean per-cell occupancy is ~per_cell_target, with slot
    capacity 16x that — geo workloads concentrate matches (a predicate
    bbox covering ~1/10 of the grid means dense-region occupancy ~10x the
    global mean), and slots must absorb that skew or dense cells overflow
    and every query near them pays the exact fallback on top of the wasted
    sort. (Correctness never depends on these numbers — overflow only
    flags queries for fallback.)

    Too-coarse grids overflow everywhere; too-fine grids make the
    (2R+1)^2 neighborhood too sparse to hold k candidates (the 'short'
    flag forces fallback). Both degenerate silently to full scans, so
    sizing matters for speed. Calibrated on TPU v5e at 67M points / 3.1M
    matches in a 120x50deg window: g=512, slots=256 certifies all queries.
    """
    import math

    g = 1 << max(
        6, min(11, int(math.sqrt(max(match_count, 1) / per_cell_target)
                       ).bit_length())
    )
    return g, 16 * per_cell_target


class GridIndex(NamedTuple):
    """Batch-resident spatial index (all device arrays)."""

    sx: jax.Array       # [N] lon, sorted by cell
    sy: jax.Array       # [N] lat, sorted by cell
    sidx: jax.Array     # [N] original row of each sorted point (int32)
    starts: jax.Array   # [G*G + 1] cell -> first sorted row
    counts: jax.Array   # [G*G] matched points per cell
    g: int              # grid edge (static)


@functools.partial(jax.jit, static_argnames=("g",))
def build_grid_index(x: jax.Array, y: jax.Array, mask: jax.Array,
                     g: int = 128) -> GridIndex:
    """Sort the batch by grid cell (masked rows last). One device sort +
    three gathers; reusable across every query against this batch."""
    n = x.shape[0]
    cx = jnp.clip(jnp.floor((x + 180.0) / 360.0 * g).astype(jnp.int32), 0, g - 1)
    cy = jnp.clip(jnp.floor((y + 90.0) / 180.0 * g).astype(jnp.int32), 0, g - 1)
    cell = cy * g + cx
    key = jnp.where(mask, cell, g * g)  # masked -> sentinel tail bucket
    # variadic sort carries the payload columns through the sort network:
    # argsort + three post-hoc random gathers measured ~13x slower on TPU
    # (random 67M-element gathers dominate; the sort itself is ~0.4s)
    skey, sx, sy, sidx = jax.lax.sort(
        (key, x, y, jnp.arange(n, dtype=jnp.int32)), num_keys=1
    )
    starts = jnp.searchsorted(skey, jnp.arange(g * g + 1, dtype=jnp.int32))
    counts = jnp.diff(starts)
    return GridIndex(
        sx=sx,
        sy=sy,
        sidx=sidx,
        starts=starts.astype(jnp.int32),
        counts=counts.astype(jnp.int32),
        g=g,
    )


@functools.partial(
    jax.jit, static_argnames=("k", "ring_radius", "cell_slots")
)
def knn_grid(
    qx: jax.Array,
    qy: jax.Array,
    index: GridIndex,
    k: int,
    ring_radius: int = 2,
    cell_slots: int = 256,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Exact-or-flagged kNN from the grid index.

    Returns (dists [Q,k], original indices [Q,k], uncertain [Q] bool).
    `uncertain=True` means the certificate could not PROVE exactness
    (k-th neighbor too far for the searched square, an overflowing cell in
    range, or a clipped lon edge) — the caller re-runs those queries on a
    full-scan path. Distances/indices for uncertain queries are still the
    best found among gathered candidates.
    """
    gq = index.g
    R = ring_radius
    S = cell_slots
    ncell = (2 * R + 1) ** 2

    qcx = jnp.clip(
        jnp.floor((qx + 180.0) / 360.0 * gq).astype(jnp.int32), 0, gq - 1
    )
    qcy = jnp.clip(
        jnp.floor((qy + 90.0) / 180.0 * gq).astype(jnp.int32), 0, gq - 1
    )

    offs = jnp.arange(-R, R + 1, dtype=jnp.int32)
    ox = jnp.tile(offs, 2 * R + 1)                      # [ncell]
    oy = jnp.repeat(offs, 2 * R + 1)                    # [ncell]

    def one_query(cqx, cqy, qlon, qlat):
        ccx = cqx + ox
        ccy = cqy + oy
        inside = (ccx >= 0) & (ccx < gq) & (ccy >= 0) & (ccy < gq)
        cells = jnp.where(inside, ccy * gq + ccx, 0)
        base = jnp.take(index.starts, cells)            # [ncell]
        cnt = jnp.where(inside, jnp.take(index.counts, cells), 0)
        overflow = jnp.any(cnt > S)
        # lon-edge clipping hides antimeridian neighbors; lat edges are
        # real poles (nothing beyond), so only lon clipping taints
        clipped_lon = jnp.any(((ccx < 0) | (ccx >= gq)))

        lanes = base[:, None] + jnp.arange(S, dtype=jnp.int32)[None, :]
        valid = jnp.arange(S, dtype=jnp.int32)[None, :] < jnp.minimum(cnt, S)[:, None]
        lanes = jnp.clip(lanes.reshape(-1), 0, index.sx.shape[0] - 1)
        px = jnp.take(index.sx, lanes)
        py = jnp.take(index.sy, lanes)
        pidx = jnp.take(index.sidx, lanes)
        d = haversine_m(qlon, qlat, px, py)
        d = jnp.where(valid.reshape(-1), d, INF)
        neg, sel = jax.lax.top_k(-d, k)
        kd = -neg
        ki = jnp.take(pidx, sel)

        # certificate: margins to the square's outer edges, in degrees
        cw = 360.0 / gq
        ch = 180.0 / gq
        west = qlon - (-180.0 + (cqx - R).astype(jnp.float32) * cw)
        east = (-180.0 + (cqx + R + 1).astype(jnp.float32) * cw) - qlon
        south = qlat - (-90.0 + (cqy - R).astype(jnp.float32) * ch)
        north = (-90.0 + (cqy + R + 1).astype(jnp.float32) * ch) - qlat
        deg = jnp.float32(jnp.pi / 180.0)
        lat_bound = jnp.minimum(south, north) * deg * EARTH_RADIUS_M
        dlon = jnp.clip(jnp.minimum(west, east), 0.0, 90.0) * deg
        lon_bound = EARTH_RADIUS_M * jnp.arcsin(
            jnp.sin(dlon) * jnp.cos(qlat * deg)
        )
        d_out = jnp.minimum(lat_bound, lon_bound)
        short = ~jnp.isfinite(kd[k - 1])  # fewer than k candidates gathered
        # f32 safety margin: a rounding-level false "certified" would break
        # exactness silently, so demand a 1m + 1e-6-relative gap
        guard = kd[k - 1] + jnp.maximum(1.0, 1e-6 * kd[k - 1])
        uncertain = (guard > d_out) | overflow | clipped_lon | short
        return kd, ki, uncertain

    return jax.vmap(one_query)(qcx, qcy, qx, qy)


def knn_indexed_sharded(
    mesh,
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    g: int = 128,
    ring_radius: int = 2,
    cell_slots: int = 256,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Grid-index kNN with data sharded over the mesh axis.

    Each device sorts ITS shard into a local grid index (the sort
    parallelizes perfectly — no cross-device data movement), runs the
    certified neighborhood search for the replicated queries, and the
    per-shard top-ks merge by all_gather + re-top-k (C25's reduction-tree
    shape, same argument as knn_sharded: the global top-k is a subset of
    the union of exact per-shard top-ks).

    A query is globally uncertain if ANY shard's certificate failed for it
    (an or-reduce over the gathered flags); callers re-run flagged queries
    on an exact sharded scan (`knn_sharded`). Returns
    (dists [Q,k], global indices [Q,k], uncertain [Q]) replicated.
    """
    import functools

    from jax.sharding import PartitionSpec as P

    from geomesa_tpu.engine.knn import _topk_smallest
    from geomesa_tpu.parallel.mesh import SHARD_AXIS

    d_count = mesh.devices.size
    shard_n = dx.shape[0] // d_count

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(), P(), P()),
        # post-gather merge computes identical values on every device
        check_vma=False,
    )
    def run(qx, qy, dxs, dys, ms):
        index = build_grid_index(dxs, dys, ms, g=g)
        kd, ki, unc = knn_grid(
            qx, qy, index, k=k, ring_radius=ring_radius,
            cell_slots=cell_slots,
        )
        shard = jax.lax.axis_index(SHARD_AXIS)
        gi = ki + shard * shard_n
        all_d = jax.lax.all_gather(kd, SHARD_AXIS)   # [D, Q, k]
        all_i = jax.lax.all_gather(gi, SHARD_AXIS)
        all_u = jax.lax.all_gather(unc, SHARD_AXIS)  # [D, Q]
        pool_d = jnp.moveaxis(all_d, 0, 1).reshape(kd.shape[0], -1)
        pool_i = jnp.moveaxis(all_i, 0, 1).reshape(kd.shape[0], -1)
        md, sel = _topk_smallest(pool_d, k)
        return md, jnp.take_along_axis(pool_i, sel, axis=1), jnp.any(all_u, 0)

    return run(qx, qy, dx, dy, mask)


def knn_indexed(
    qx, qy, dx, dy, mask, k: int,
    g: int = 128, ring_radius: int = 2, cell_slots: int = 256,
    index: GridIndex | None = None,
):
    """Grid-index kNN with exact fallback: certificate-failed queries are
    re-run on the exact full-scan haversine path. Host round trip: one
    bool-vector fetch to decide whether a fallback is needed at all.

    Pass a prebuilt `index` to amortize the build across query rounds
    (the device-cache analog of the reference keeping its index tables).
    """
    import numpy as np

    from geomesa_tpu.engine.knn import knn

    if index is None:
        index = build_grid_index(dx, dy, mask, g=g)
    kd, ki, uncertain = knn_grid(
        qx, qy, index, k=k, ring_radius=ring_radius, cell_slots=cell_slots
    )
    flags = np.asarray(uncertain)
    if not flags.any():
        return kd, ki
    rows = np.nonzero(flags)[0]
    # pow2-bucket the fallback set: the uncertain-query count varies per
    # round, and both the gathered query extent and the tile parameter
    # shape the exact-path executable — raw counts would compile one per
    # distinct count. Padded slots re-run rows[0]; their results are
    # dropped by the slice before the scatter-back.
    nb = next_pow2(max(len(rows), 1))
    rpad = np.concatenate(
        [rows, np.full(nb - len(rows), rows[0], rows.dtype)])
    fd, fi = knn(
        jnp.take(qx, jnp.asarray(rpad)), jnp.take(qy, jnp.asarray(rpad)),
        dx, dy, mask, k=k,
        query_tile=max(1, min(1024, nb)),
    )
    kd = jnp.asarray(kd).at[jnp.asarray(rows)].set(fd[: len(rows)])
    ki = jnp.asarray(ki).at[jnp.asarray(rows)].set(fi[: len(rows)])
    return kd, ki
