"""Tube-select kernel: spatio-temporal corridor join.

Parity: geomesa-process TubeSelectProcess (tube/) [upstream, unverified]:
"find features near this track in space AND time". The reference builds tube
segments (buffered geometries + time intervals) host-side via TubeBuilder
variants (NoGapFill / LineGapFill / InterpolatedGapFill) and issues one
spatial+temporal query per segment. TPU-first shape: the tube is a compact
array of (lon, lat, time, radius_m, half_window_ms) samples; the kernel is a
single masked (N data x T tube-samples) haversine + time-window test, tiled
over T — every data point is matched against the whole corridor in one fused
pass instead of S sequential store queries.

Gap-filling lives host-side in process/tube.py (same division of labor as the
reference); this kernel only sees the sampled tube.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from geomesa_tpu.engine.geodesy import haversine_m
from geomesa_tpu.parallel.mesh import SHARD_AXIS


@functools.partial(jax.jit, static_argnames=("tube_tile", "data_tile"))
def tube_select(
    x: jax.Array,
    y: jax.Array,
    t: jax.Array,
    mask: jax.Array,
    tube_x: jax.Array,
    tube_y: jax.Array,
    tube_t: jax.Array,
    radius_m: jax.Array,
    half_window_ms: jax.Array,
    tube_tile: int = 2048,
    data_tile: int = 8192,
) -> jax.Array:
    """bool [N]: data point matches if within radius AND time window of ANY
    tube sample. Tube arrays are [T]; radius/window may be scalar or [T].

    Tiled over BOTH axes: the [data_tile, tube_tile] hit block is the only
    pairwise intermediate, so HBM stays O(N + T) regardless of problem size
    (a flat [N, T] broadcast at N=4M, T=2k would materialize ~32 GB).
    """
    T = tube_x.shape[0]
    n = x.shape[0]
    if T == 0:
        return jnp.zeros((n,), bool)
    radius_m = jnp.broadcast_to(jnp.asarray(radius_m, jnp.float32), (T,))
    half_window_ms = jnp.broadcast_to(
        jnp.asarray(half_window_ms, jnp.int64), (T,)
    )
    # pad the tube axis only to the lane quantum (128), not a full tile —
    # short tubes (the common case) shouldn't pay 8x padding waste
    tube_tile = min(tube_tile, (T + 127) // 128 * 128)
    tpad = (-T) % tube_tile
    tx = jnp.pad(tube_x, (0, tpad))
    ty = jnp.pad(tube_y, (0, tpad))
    tt = jnp.pad(tube_t, (0, tpad))
    tr = jnp.pad(radius_m, (0, tpad), constant_values=-1.0)  # pad never matches
    tw = jnp.pad(half_window_ms, (0, tpad))
    tube = (
        tx.reshape(-1, tube_tile),
        ty.reshape(-1, tube_tile),
        tt.reshape(-1, tube_tile),
        tr.reshape(-1, tube_tile),
        tw.reshape(-1, tube_tile),
    )

    data_tile = min(data_tile, max(n, 1))
    npad = (-n) % data_tile
    xd = jnp.pad(x, (0, npad)).reshape(-1, data_tile)
    yd = jnp.pad(y, (0, npad)).reshape(-1, data_tile)
    td = jnp.pad(t, (0, npad)).reshape(-1, data_tile)

    def data_block(_, args):
        xi, yi, ti = args

        def tube_block(carry, targs):
            txi, tyi, tti, tri, twi = targs
            d = haversine_m(xi[:, None], yi[:, None], txi[None, :], tyi[None, :])
            dt = jnp.abs(ti[:, None] - tti[None, :])
            hit = (d <= tri[None, :]) & (dt <= twi[None, :])
            return carry | jnp.any(hit, axis=1), None

        init = jnp.zeros_like(xi, dtype=bool)
        out, _ = jax.lax.scan(tube_block, init, tube)
        return None, out

    _, hits = jax.lax.scan(data_block, None, (xd, yd, td))
    return hits.reshape(-1)[:n] & mask


def tube_select_sharded(
    mesh: Mesh,
    x, y, t, mask,
    tube_x, tube_y, tube_t, radius_m, half_window_ms,
    tube_tile: int = 2048,
):
    """Data sharded over the mesh; the tube (small) is replicated. The result
    mask stays sharded like the data — no collective needed (pure map)."""

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(), P(),
        ),
        out_specs=P(SHARD_AXIS),
    )
    def run(x, y, t, m, tx, ty, tt, tr, tw):
        return tube_select(x, y, t, m, tx, ty, tt, tr, tw, tube_tile=tube_tile)

    return run(
        x, y, t, mask,
        tube_x, tube_y, tube_t,
        jnp.broadcast_to(jnp.asarray(radius_m, jnp.float32), tube_x.shape),
        jnp.broadcast_to(jnp.asarray(half_window_ms, jnp.int64), tube_x.shape),
    )
