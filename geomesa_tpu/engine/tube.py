"""Tube-select kernel: spatio-temporal corridor join.

Parity: geomesa-process TubeSelectProcess (tube/) [upstream, unverified]:
"find features near this track in space AND time". The reference builds tube
segments (buffered geometries + time intervals) host-side via TubeBuilder
variants (NoGapFill / LineGapFill / InterpolatedGapFill) and issues one
spatial+temporal query per segment. TPU-first shape: the tube is a compact
array of (lon, lat, time, radius_m, half_window_ms) samples; the kernel is a
single masked (N data x T tube-samples) haversine + time-window test, tiled
over T — every data point is matched against the whole corridor in one fused
pass instead of S sequential store queries.

Gap-filling lives host-side in process/tube.py (same division of labor as the
reference); this kernel only sees the sampled tube.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from geomesa_tpu.parallel.mesh import SHARD_AXIS


@functools.partial(jax.jit, static_argnames=("tube_tile", "data_tile"))
def tube_select(
    x: jax.Array,
    y: jax.Array,
    t: jax.Array,
    mask: jax.Array,
    tube_x: jax.Array,
    tube_y: jax.Array,
    tube_t: jax.Array,
    radius_m: jax.Array,
    half_window_ms: jax.Array,
    tube_tile: int = 2048,
    data_tile: int = 8192,
) -> jax.Array:
    """bool [N]: data point matches if within radius AND time window of ANY
    tube sample. Tube arrays are [T]; radius/window may be scalar or [T].

    Tiled over BOTH axes: the [data_tile, tube_tile] hit block is the only
    pairwise intermediate, so HBM stays O(N + T) regardless of problem size
    (a flat [N, T] broadcast at N=4M, T=2k would materialize ~32 GB).

    The pairwise test is a CHORD-SQUARED compare (round 4): d <= r on
    the sphere iff |u_point - u_tube|^2 <= (2 sin(r/2R))^2 — identical
    to the haversine compare in exact arithmetic, but the per-pair work
    is 8 elementwise flops instead of transcendental-heavy haversine
    (per-pair sin/cos/asin on the VPU). The DIFFERENCE form is
    essential: the dot-product form (dot >= cos(r/R)) cancels
    catastrophically in f32 — cos(r/R) rounds to exactly 1.0f below
    r ~ 2.2 km, silently dropping true matches (round-4 review,
    reproduced at 500 m radius); differences of unit-vector components
    keep ~1% relative accuracy at any radius, the same ~1 m floor as
    f32 coordinates themselves. Unit vectors and thresholds are
    precomputed once per point/sample in the INPUT dtype, so f64 inputs
    (the process path, CPU tests) stay f64-exact.
    """
    from geomesa_tpu.engine.geodesy import EARTH_RADIUS_M

    T = tube_x.shape[0]
    n = x.shape[0]
    if T == 0:
        return jnp.zeros((n,), bool)
    radius_m = jnp.broadcast_to(
        jnp.asarray(radius_m, x.dtype), (T,))
    half_window_ms = jnp.broadcast_to(
        jnp.asarray(half_window_ms, jnp.int64), (T,)
    )
    # pad the tube axis only to the lane quantum (128), not a full tile —
    # short tubes (the common case) shouldn't pay 8x padding waste
    tube_tile = min(tube_tile, (T + 127) // 128 * 128)
    tpad = (-T) % tube_tile
    tx = jnp.pad(tube_x, (0, tpad))
    ty = jnp.pad(tube_y, (0, tpad))
    tt = jnp.pad(tube_t, (0, tpad))
    tr = jnp.pad(radius_m, (0, tpad), constant_values=-1.0)
    tw = jnp.pad(half_window_ms, (0, tpad))

    def unit3(lon, lat):
        rlon = jnp.radians(lon)
        rlat = jnp.radians(lat)
        cl = jnp.cos(rlat)
        return jnp.stack(
            [cl * jnp.cos(rlon), cl * jnp.sin(rlon), jnp.sin(rlat)], -1)

    tu = unit3(tx, ty)                      # [Tp, 3]
    # pad samples (r < 0) get threshold -1: chord^2 >= 0 never matches
    half = jnp.sin(tr / (2.0 * EARTH_RADIUS_M))
    thresh = jnp.where(tr < 0, -1.0, 4.0 * half * half)
    tube = (
        tu.reshape(-1, tube_tile, 3),
        thresh.reshape(-1, tube_tile),
        tt.reshape(-1, tube_tile),
        tw.reshape(-1, tube_tile),
    )

    data_tile = min(data_tile, max(n, 1))
    npad = (-n) % data_tile
    xd = jnp.pad(x, (0, npad)).reshape(-1, data_tile)
    yd = jnp.pad(y, (0, npad)).reshape(-1, data_tile)
    td = jnp.pad(t, (0, npad)).reshape(-1, data_tile)

    def data_block(_, args):
        xi, yi, ti = args
        ui = unit3(xi, yi)                  # [data_tile, 3]

        def tube_block(carry, targs):
            tui, thi, tti, twi = targs
            dx = ui[:, None, 0] - tui[None, :, 0]
            dy = ui[:, None, 1] - tui[None, :, 1]
            dz = ui[:, None, 2] - tui[None, :, 2]
            chord_sq = dx * dx + dy * dy + dz * dz
            dt = jnp.abs(ti[:, None] - tti[None, :])
            hit = (chord_sq <= thi[None, :]) & (dt <= twi[None, :])
            return carry | jnp.any(hit, axis=1), None

        init = jnp.zeros_like(xi, dtype=bool)
        out, _ = jax.lax.scan(tube_block, init, tube)
        return None, out

    _, hits = jax.lax.scan(data_block, None, (xd, yd, td))
    return hits.reshape(-1)[:n] & mask


# tube samples per pruning segment: a long track's segment boxes must
# stay LOCAL or the prune is vacuous — at SEG=128 a 256-sample diagonal
# corridor became 2 region-sized boxes covering ~half the data (measured
# round 4: tile_capacity overflowed to ALL tiles, 4.6x; at SEG=16 the
# boxes hug the corridor). The [n_tiles, K] overlap test stays trivial.
SEG = 16


@functools.partial(
    jax.jit, static_argnames=("data_tile", "tile_capacity")
)
def _tube_pruned_call(
    x, y, t, mask,
    tube_x, tube_y, tube_t, radius_m, half_window_ms,
    margin_lon, margin_lat,
    data_tile: int, tile_capacity: int,
):
    n = x.shape[0]
    pad = (-n) % data_tile
    big = 3.0e8  # dtype-preserving: the process path runs f64 coords
    xp = jnp.pad(x, (0, pad), constant_values=big)
    yp = jnp.pad(y, (0, pad), constant_values=big)
    tp = jnp.pad(t, (0, pad))
    mp = jnp.pad(mask, (0, pad))
    nt = xp.shape[0] // data_tile

    # per-data-tile envelopes over ALL rows (filter-independent — the
    # mask still applies inside the kernel; conservative is exact). On
    # store-ordered batches these are tight, which is the whole win.
    xt = xp.reshape(nt, data_tile)
    yt = yp.reshape(nt, data_tile)
    tt_ = tp.reshape(nt, data_tile)
    txmin, txmax = xt.min(1), jnp.where(xt >= big, -big, xt).max(1)
    tymin, tymax = yt.min(1), jnp.where(yt >= big, -big, yt).max(1)
    ttmin, ttmax = tt_.min(1), tt_.max(1)

    # tube segment envelopes ([K] boxes of SEG samples) expanded by the
    # geodesic margins + time window: a long track's global bbox would
    # cover everything; per-segment boxes track the corridor
    T = tube_x.shape[0]
    spad = (-T) % SEG
    sx = jnp.pad(tube_x, (0, spad), constant_values=big)
    sy = jnp.pad(tube_y, (0, spad), constant_values=big)
    st = jnp.pad(tube_t, (0, spad))
    sw = jnp.pad(
        jnp.broadcast_to(jnp.asarray(half_window_ms, jnp.int64), (T,)),
        (0, spad), constant_values=-1,
    )
    K = sx.shape[0] // SEG
    sxs = sx.reshape(K, SEG)
    sys_ = sy.reshape(K, SEG)
    sts = st.reshape(K, SEG)
    sws = sw.reshape(K, SEG)
    live = sxs < big / 2
    inf64 = jnp.int64(1) << 60
    sxmin = jnp.where(live, sxs, big).min(1) - margin_lon
    sxmax = jnp.where(live, sxs, -big).max(1) + margin_lon
    symin = jnp.where(live, sys_, big).min(1) - margin_lat
    symax = jnp.where(live, sys_, -big).max(1) + margin_lat
    wmax = sws.max(1)
    stmin = jnp.where(live, sts, inf64).min(1) - wmax
    stmax = jnp.where(live, sts, -inf64).max(1) + wmax

    # longitude wraps: a corridor reaching past +-180 must also match
    # tiles on the far side, so the x-overlap test additionally checks
    # the +-360-shifted segment boxes (data lons live in [-180, 180];
    # the extra tests are vacuous for interior corridors)
    x_overlap = (
        ((txmax[:, None] >= sxmin[None, :]) & (txmin[:, None] <= sxmax[None, :]))
        | ((txmax[:, None] >= sxmin[None, :] + 360.0)
           & (txmin[:, None] <= sxmax[None, :] + 360.0))
        | ((txmax[:, None] >= sxmin[None, :] - 360.0)
           & (txmin[:, None] <= sxmax[None, :] - 360.0))
    )
    hit = (
        x_overlap
        & (tymax[:, None] >= symin[None, :]) & (tymin[:, None] <= symax[None, :])
        & (ttmax[:, None] >= stmin[None, :]) & (ttmin[:, None] <= stmax[None, :])
    ).any(axis=1)

    n_sel = jnp.sum(hit.astype(jnp.int32))
    cap = min(tile_capacity, nt)
    overflow = n_sel > cap
    picked = jax.lax.top_k(
        jnp.where(hit, -jnp.arange(nt, dtype=jnp.int32), -(1 << 30)), cap
    )[0]
    live_slot = picked > -(1 << 30)
    ids = jnp.where(live_slot, -picked, 0)

    gx = jnp.take(xt, ids, axis=0).reshape(-1)
    gy = jnp.take(yt, ids, axis=0).reshape(-1)
    gt = jnp.take(tt_, ids, axis=0).reshape(-1)
    gm = (
        jnp.take(mp.reshape(nt, data_tile), ids, axis=0)
        & live_slot[:, None]
    ).reshape(-1)
    hits_sel = tube_select(
        gx, gy, gt, gm, tube_x, tube_y, tube_t, radius_m, half_window_ms,
        data_tile=data_tile,
    )
    out = jnp.zeros((nt, data_tile), bool)
    out = out.at[ids].max(hits_sel.reshape(cap, data_tile))
    return out.reshape(-1)[:n] & mask, overflow


def tube_margins(tube_y, radius_m) -> Tuple[float, float]:
    """Conservative degree margins covering a `radius_m` geodesic reach:
    1 deg latitude >= 110574 m everywhere; longitude degrees shrink by
    cos(lat), evaluated at the highest latitude the corridor can reach."""
    rmax = float(np.max(np.asarray(radius_m)))
    margin_lat = rmax / 110574.0 * 1.01
    lat_max = float(np.max(np.abs(np.asarray(tube_y))))
    # a corridor whose reach includes a pole spans EVERY longitude (a
    # hard 89.5-deg clamp under-margined polar corridors and silently
    # dropped true matches — round-4 review, reproduced at 89.8N)
    pole_dist_m = max(90.0 - lat_max, 0.0) * 110574.0
    if rmax * 1.01 >= pole_dist_m:
        return 360.0, float(margin_lat)
    lat_reach = lat_max + margin_lat  # provably < 90 here
    margin_lon = min(
        360.0,
        rmax / (111320.0 * np.cos(np.radians(lat_reach))) * 1.01,
    )
    return float(margin_lon), float(margin_lat)


def tube_select_pruned(
    x, y, t, mask,
    tube_x, tube_y, tube_t, radius_m, half_window_ms,
    data_tile: int = 8192,
    tile_capacity: "int | None" = None,
) -> Tuple[jax.Array, "int"]:
    """`tube_select` scanning only data tiles whose envelope intersects
    the corridor's per-segment reach (bbox + time window) — the VERDICT
    r3 tile-pruning pass for config 5. Exact for any input order (pruned
    tiles provably cannot match); the win requires store (Z) order where
    tile envelopes are tight.

    Returns (bool [N] hits, capacity_used). tile_capacity=None
    calibrates with one scalar fetch; on overflow the dense kernel runs
    instead and capacity_used = -1 (callers drop their cached value, as
    with knn_sparse_auto)."""
    margin_lon, margin_lat = tube_margins(tube_y, radius_m)
    T = tube_x.shape[0]
    radius_b = jnp.broadcast_to(jnp.asarray(radius_m, jnp.float32), (T,))
    window_b = jnp.broadcast_to(jnp.asarray(half_window_ms, jnp.int64), (T,))
    if tile_capacity is None:
        hits, ov = _tube_pruned_call(
            x, y, t, mask, tube_x, tube_y, tube_t, radius_b, window_b,
            margin_lon, margin_lat, data_tile=data_tile,
            tile_capacity=max(
                64, -(-x.shape[0] // data_tile) // 4
            ),
        )
        if not bool(np.asarray(ov)):
            return hits, max(64, -(-x.shape[0] // data_tile) // 4)
        tile_capacity = -(-x.shape[0] // data_tile)  # all tiles
    hits, ov = _tube_pruned_call(
        x, y, t, mask, tube_x, tube_y, tube_t, radius_b, window_b,
        margin_lon, margin_lat, data_tile=data_tile,
        tile_capacity=tile_capacity,
    )
    if bool(np.asarray(ov)):
        return (
            tube_select(x, y, t, mask, tube_x, tube_y, tube_t,
                        radius_b, window_b, data_tile=data_tile),
            -1,
        )
    return hits, tile_capacity


def tube_select_pruned_sharded(
    mesh: Mesh,
    x, y, t, mask,
    tube_x, tube_y, tube_t, radius_m, half_window_ms,
    data_tile: int = 8192,
    tile_capacity: int = 64,
) -> Tuple[jax.Array, jax.Array]:
    """Tile-pruned tube select with data sharded over the mesh (tube
    replicated, result sharded like the data — pure map, plus one tiny
    all_gather for the overflow flag). Returns (hits sharded [N],
    overflow — True if ANY shard exceeded tile_capacity; callers MUST
    then fall back to tube_select_sharded)."""
    T = tube_x.shape[0]
    margin_lon, margin_lat = tube_margins(np.asarray(tube_y), radius_m)
    radius_b = jnp.broadcast_to(jnp.asarray(radius_m, jnp.float32), (T,))
    window_b = jnp.broadcast_to(jnp.asarray(half_window_ms, jnp.int64), (T,))

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(), P(),
        ),
        out_specs=(P(SHARD_AXIS), P()),
        check_vma=False,  # ov_any is replicated by construction
    )
    def run(x, y, t, m, tx, ty, tt, tr, tw):
        hits, ov = _tube_pruned_call(
            x, y, t, m, tx, ty, tt, tr, tw, margin_lon, margin_lat,
            data_tile=data_tile, tile_capacity=tile_capacity,
        )
        return hits, jnp.any(jax.lax.all_gather(ov, SHARD_AXIS))

    return run(x, y, t, mask, tube_x, tube_y, tube_t, radius_b, window_b)


def tube_select_sharded(
    mesh: Mesh,
    x, y, t, mask,
    tube_x, tube_y, tube_t, radius_m, half_window_ms,
    tube_tile: int = 2048,
):
    """Data sharded over the mesh; the tube (small) is replicated. The result
    mask stays sharded like the data — no collective needed (pure map)."""

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
            P(), P(), P(), P(), P(),
        ),
        out_specs=P(SHARD_AXIS),
    )
    def run(x, y, t, m, tx, ty, tt, tr, tw):
        return tube_select(x, y, t, m, tx, ty, tt, tr, tw, tube_tile=tube_tile)

    return run(
        x, y, t, mask,
        tube_x, tube_y, tube_t,
        jnp.broadcast_to(jnp.asarray(radius_m, jnp.float32), tube_x.shape),
        jnp.broadcast_to(jnp.asarray(half_window_ms, jnp.int64), tube_x.shape),
    )
