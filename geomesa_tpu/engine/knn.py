"""k-nearest-neighbor kernels: tiled brute-force haversine + sharded merges.

Parity: geomesa-process KNearestNeighborSearchProcess (knn/) [upstream,
unverified]. The reference's windowed expand-and-requery search exists to
avoid scanning the world from a key-value store; on TPU the economics invert —
a dense tiled all-pairs haversine over the (index-pruned) candidate batch is
exact by construction, so there is no radius iteration and no recall risk.
Recall@k parity is therefore structural: every kernel here is brute-force
over whatever candidates it is given.

Three execution shapes (SURVEY.md §5.7's "ring-topk replaces ring-attention"):

- `knn`          — single device, queries tiled through VMEM via lax.map.
- `knn_sharded`  — data sharded over the mesh axis; per-shard local top-k,
                   then all_gather(k·D candidates) + re-top-k. One collective,
                   exact. The merge is the TPU analog of the reference's
                   client-side fan-in of per-tablet results (C25).
- `knn_ring`     — queries AND data sharded; data shards rotate by ppermute
                   around the ring while each device folds the visiting shard
                   into its running top-k. O(D) steps, constant memory: the
                   long-context/feature-set-scaling shape.

Distances are f32 by default (~meter-scale resolution at Earth radius);
ties at f32 resolution can reorder equidistant neighbors vs an f64 oracle —
recall tests treat within-tolerance distance ties as equivalent.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from geomesa_tpu.engine.geodesy import haversine_m
from geomesa_tpu.parallel.mesh import SHARD_AXIS

INF = jnp.float32(jnp.inf)


def _topk_smallest(d: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """top-k smallest along the last axis -> (dists, indices).

    If fewer than k candidates exist (tiny shard, k > rows), the result is
    padded with +inf distances so downstream merges stay shape-stable.
    """
    kk = min(k, d.shape[-1])
    neg, idx = jax.lax.top_k(-d, kk)
    if kk < k:
        pad = [(0, 0)] * (d.ndim - 1) + [(0, k - kk)]
        neg = jnp.pad(neg, pad, constant_values=-jnp.inf)
        idx = jnp.pad(idx, pad)
    return -neg, idx


@functools.partial(jax.jit, static_argnames=("k", "query_tile", "data_tile"))
def knn(
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    query_tile: int = 1024,
    data_tile: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN: [Q] query points vs [N] masked data points.

    Returns (dists [Q,k] meters, indices [Q,k] into the data arrays).
    Invalid/masked data points get +inf distance (index still in range).

    Both axes are tiled: queries via lax.map, data via a lax.scan that folds
    each [query_tile, data_tile] distance block into a running top-k — peak
    memory is O(query_tile · data_tile), never O(Q · N), so GDELT-scale N
    streams through HBM instead of materializing a multi-GB block. Folding
    per-tile top-ks is exact (the global top-k is a subset of the union of
    tile top-ks — the same argument as the cross-shard merge below).
    """
    q = qx.shape[0]
    n = dx.shape[0]
    if data_tile is None:
        # cap the distance block at ~64M lanes (256MB f32)
        data_tile = max(k, min(n, (1 << 26) // max(query_tile, 1)))
    pad = (-q) % query_tile
    qxp = jnp.pad(qx, (0, pad))
    qyp = jnp.pad(qy, (0, pad))
    tiles_x = qxp.reshape(-1, query_tile)
    tiles_y = qyp.reshape(-1, query_tile)

    dpad = (-n) % data_tile
    dxp = jnp.pad(dx, (0, dpad)).reshape(-1, data_tile)
    dyp = jnp.pad(dy, (0, dpad)).reshape(-1, data_tile)
    mp = jnp.pad(mask, (0, dpad)).reshape(-1, data_tile)
    n_dtiles = dxp.shape[0]
    dist_dtype = jnp.promote_types(jnp.promote_types(qx.dtype, dx.dtype), jnp.float32)

    def tile(args):
        tx, ty = args

        def fold(carry, xs):
            bd, bi = carry
            dxt, dyt, mt, base = xs
            d = haversine_m(tx[:, None], ty[:, None], dxt[None, :], dyt[None, :])
            d = jnp.where(mt[None, :], d, INF)
            ld, li = _topk_smallest(d, k)
            # clamp padded-lane indices into range — their distances are
            # +inf so they never displace real neighbors, but the contract
            # is "index still in range" even for unfilled slots
            gi = jnp.minimum((li + base).astype(jnp.int32), n - 1)
            pool_d = jnp.concatenate([bd, ld], axis=1)
            pool_i = jnp.concatenate([bi, gi], axis=1)
            nd, sel = _topk_smallest(pool_d, k)
            ni = jnp.take_along_axis(pool_i, sel, axis=1)
            return (nd, ni), None

        # derive the init from the inputs so it inherits their varying-
        # mesh-axes tag — a plain constant init breaks lax.scan's carry
        # typing when knn runs inside a shard_map (ring/sharded callers)
        vzero = jnp.sum(dx[:1] * 0).astype(dist_dtype) + jnp.sum(tx[:1] * 0).astype(dist_dtype)
        init = (
            jnp.full((query_tile, k), jnp.inf, dist_dtype) + vzero,
            jnp.zeros((query_tile, k), jnp.int32) + vzero.astype(jnp.int32),
        )
        bases = (jnp.arange(n_dtiles) * data_tile).astype(jnp.int32)
        (bd, bi), _ = jax.lax.scan(fold, init, (dxp, dyp, mp, bases))
        return bd, bi

    dists, idx = jax.lax.map(tile, (tiles_x, tiles_y))
    return (
        dists.reshape(-1, k)[:q],
        idx.reshape(-1, k)[:q],
    )


def knn_sharded(
    mesh: Mesh,
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    query_tile: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN with data sharded over the mesh: local top-k + all_gather
    merge. Returns (dists [Q,k], global indices [Q,k]).

    Exactness: each shard's local top-k is exact over its rows; the true
    global top-k is a subset of the union of per-shard top-ks, so the merged
    re-top-k is exact — the same argument as the reference's per-tablet
    aggregation + client merge, with psum-free O(D·Q·k) gather traffic.
    """
    d_count = mesh.devices.size
    shard_n = dx.shape[0] // d_count

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(), P()),
        # post-gather re-top-k computes identical values on every device;
        # JAX's varying-mesh-axes check can't infer that, so assert it
        check_vma=False,
    )
    def run(qx, qy, dx, dy, mask):
        dists, idx = knn(qx, qy, dx, dy, mask, k=k, query_tile=query_tile)
        shard = jax.lax.axis_index(SHARD_AXIS)
        gidx = idx + shard * shard_n
        # [D, Q, k] candidate pools on every device
        all_d = jax.lax.all_gather(dists, SHARD_AXIS)
        all_i = jax.lax.all_gather(gidx, SHARD_AXIS)
        pool_d = jnp.moveaxis(all_d, 0, 1).reshape(dists.shape[0], -1)
        pool_i = jnp.moveaxis(all_i, 0, 1).reshape(dists.shape[0], -1)
        md, mi = _topk_smallest(pool_d, k)
        return md, jnp.take_along_axis(pool_i, mi, axis=1)

    return run(qx, qy, dx, dy, mask)


def knn_ring(
    mesh: Mesh,
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    query_tile: int = 1024,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN with BOTH queries and data sharded: ring top-k.

    Each device owns a query shard and a data shard; data shards rotate
    around the ring (ppermute) for D steps while every device folds the
    visiting shard into its running top-k. Communication is the data shard
    itself (the ring-attention access pattern), never the QxN distances.
    Returns (dists, global indices) sharded like the queries.
    """
    d_count = mesh.devices.size
    shard_n = dx.shape[0] // d_count

    @functools.partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
        ),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
    )
    def run(qx, qy, dx, dy, mask):
        me = jax.lax.axis_index(SHARD_AXIS)
        perm = [(i, (i + 1) % d_count) for i in range(d_count)]

        def step(i, carry):
            best_d, best_i, dx, dy, mask = carry
            owner = (me - i) % d_count  # whose shard is visiting
            ld, li = knn(qx, qy, dx, dy, mask, k=k, query_tile=query_tile)
            gi = (li + owner * shard_n).astype(jnp.int32)
            pool_d = jnp.concatenate([best_d, ld], axis=1)
            pool_i = jnp.concatenate([best_i, gi], axis=1)
            nd, sel = _topk_smallest(pool_d, k)
            ni = jnp.take_along_axis(pool_i, sel, axis=1)
            dx, dy, mask = (
                jax.lax.ppermute(a, SHARD_AXIS, perm) for a in (dx, dy, mask)
            )
            return nd, ni, dx, dy, mask

        q = qx.shape[0]
        dist_dtype = jnp.promote_types(jnp.promote_types(qx.dtype, dx.dtype), jnp.float32)
        # mark the init carry as device-varying (it becomes so after step 1)
        best_d = jax.lax.pcast(
            jnp.full((q, k), jnp.inf, dist_dtype), SHARD_AXIS, to="varying"
        )
        best_i = jax.lax.pcast(jnp.zeros((q, k), jnp.int32), SHARD_AXIS, to="varying")
        best_d, best_i, *_ = jax.lax.fori_loop(
            0, d_count, step, (best_d, best_i, dx, dy, mask)
        )
        return best_d, best_i

    return run(qx, qy, dx, dy, mask)
