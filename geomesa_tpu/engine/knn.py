"""k-nearest-neighbor kernels: tiled brute-force haversine + sharded merges.

Parity: geomesa-process KNearestNeighborSearchProcess (knn/) [upstream,
unverified]. The reference's windowed expand-and-requery search exists to
avoid scanning the world from a key-value store; on TPU the economics invert —
a dense tiled all-pairs haversine over the (index-pruned) candidate batch is
exact by construction, so there is no radius iteration and no recall risk.
Recall@k parity is therefore structural: every kernel here is brute-force
over whatever candidates it is given.

Three execution shapes (SURVEY.md §5.7's "ring-topk replaces ring-attention"):

- `knn`          — single device, queries tiled through VMEM via lax.map.
- `knn_sharded`  — data sharded over the mesh axis; per-shard local top-k,
                   then all_gather(k·D candidates) + re-top-k. One collective,
                   exact. The merge is the TPU analog of the reference's
                   client-side fan-in of per-tablet results (C25).
- `knn_ring`     — queries AND data sharded; data shards rotate by ppermute
                   around the ring while each device folds the visiting shard
                   into its running top-k. O(D) steps, constant memory: the
                   long-context/feature-set-scaling shape.

Distances are f32 by default (~meter-scale resolution at Earth radius);
ties at f32 resolution can reorder equidistant neighbors vs an f64 oracle —
recall tests treat within-tolerance distance ties as equivalent.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from geomesa_tpu.utils.jaxcompat import pcast as _pcast
from geomesa_tpu.utils.jaxcompat import shard_map as _shard_map
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from geomesa_tpu.engine.geodesy import haversine_m
from geomesa_tpu.parallel.mesh import SHARD_AXIS

INF = jnp.float32(jnp.inf)


def _topk_smallest(d: jax.Array, k: int) -> Tuple[jax.Array, jax.Array]:
    """top-k smallest along the last axis -> (dists, indices).

    If fewer than k candidates exist (tiny shard, k > rows), the result is
    padded with +inf distances so downstream merges stay shape-stable.
    """
    kk = min(k, d.shape[-1])
    neg, idx = jax.lax.top_k(-d, kk)
    if kk < k:
        pad = [(0, 0)] * (d.ndim - 1) + [(0, k - kk)]
        neg = jnp.pad(neg, pad, constant_values=-jnp.inf)
        idx = jnp.pad(idx, pad)
    return -neg, idx


def _twolevel_smallest(
    d: jax.Array, m: int, block: int = 128
) -> Tuple[jax.Array, jax.Array]:
    """Exact top-m smallest over the last axis via two-level block selection.

    Level 1 takes per-`block` minima and picks the m blocks with the
    smallest minima; level 2 takes the exact top-m over those m·block
    gathered elements. Exactness: if a block holding a true top-m element e
    were NOT picked, then m picked blocks each have a minimum <= e, i.e. m
    elements <= e, so e has rank > m — contradiction. (Ties may swap
    equal-valued candidates, exactly as lax.top_k itself may.)

    Why: lax.top_k over a million-lane axis is a full sort and dominates the
    streamed kNN fold (~4.6x the matmul cost measured on v5e); the block-min
    reduction is a cheap VPU pass over the same data, and the tail top-k
    runs on m·block lanes instead of N.
    """
    n = d.shape[-1]
    nb = n // block
    if nb * block != n or nb < m or n <= 4 * m:
        return _topk_smallest(d, m)
    lead = d.shape[:-1]
    blk = d.reshape(*lead, nb, block)
    bmin = blk.min(axis=-1)
    _, bidx = jax.lax.top_k(-bmin, m)  # [..., m] winning blocks
    g = jnp.take_along_axis(blk, bidx[..., None], axis=-2)
    vals, within = _topk_smallest(g.reshape(*lead, m * block), m)
    blk_of = jnp.take_along_axis(bidx, within // block, axis=-1)
    return vals, blk_of * block + (within % block)


@functools.partial(jax.jit, static_argnames=("k", "query_tile", "data_tile"))
def knn(
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    query_tile: int = 1024,
    data_tile: Optional[int] = None,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN: [Q] query points vs [N] masked data points.

    Returns (dists [Q,k] meters, indices [Q,k] into the data arrays).
    Invalid/masked data points get +inf distance (index still in range).

    Both axes are tiled: queries via lax.map, data via a lax.scan that folds
    each [query_tile, data_tile] distance block into a running top-k — peak
    memory is O(query_tile · data_tile), never O(Q · N), so GDELT-scale N
    streams through HBM instead of materializing a multi-GB block. Folding
    per-tile top-ks is exact (the global top-k is a subset of the union of
    tile top-ks — the same argument as the cross-shard merge below).
    """
    q = qx.shape[0]
    n = dx.shape[0]
    if data_tile is None:
        # cap the distance block at ~128M lanes (512MB f32): with two-level
        # selection the fold is bandwidth-bound, and fewer/larger blocks
        # measurably beat smaller ones (v5e sweep: 2^21 lanes/row ~ -20%)
        data_tile = max(k, min(n, (1 << 27) // max(query_tile, 1)))
    pad = (-q) % query_tile
    qxp = jnp.pad(qx, (0, pad))
    qyp = jnp.pad(qy, (0, pad))
    tiles_x = qxp.reshape(-1, query_tile)
    tiles_y = qyp.reshape(-1, query_tile)

    dpad = (-n) % data_tile
    dxp = jnp.pad(dx, (0, dpad)).reshape(-1, data_tile)
    dyp = jnp.pad(dy, (0, dpad)).reshape(-1, data_tile)
    mp = jnp.pad(mask, (0, dpad)).reshape(-1, data_tile)
    n_dtiles = dxp.shape[0]
    dist_dtype = jnp.promote_types(jnp.promote_types(qx.dtype, dx.dtype), jnp.float32)

    def tile(args):
        tx, ty = args

        def fold(carry, xs):
            bd, bi = carry
            dxt, dyt, mt, base = xs
            d = haversine_m(tx[:, None], ty[:, None], dxt[None, :], dyt[None, :])
            d = jnp.where(mt[None, :], d, INF)
            ld, li = _twolevel_smallest(d, k)
            # clamp padded-lane indices into range — their distances are
            # +inf so they never displace real neighbors, but the contract
            # is "index still in range" even for unfilled slots
            gi = jnp.minimum((li + base).astype(jnp.int32), n - 1)
            pool_d = jnp.concatenate([bd, ld], axis=1)
            pool_i = jnp.concatenate([bi, gi], axis=1)
            nd, sel = _topk_smallest(pool_d, k)
            ni = jnp.take_along_axis(pool_i, sel, axis=1)
            return (nd, ni), None

        # derive the init from the inputs so it inherits their varying-
        # mesh-axes tag — a plain constant init breaks lax.scan's carry
        # typing when knn runs inside a shard_map (ring/sharded callers)
        vzero = jnp.sum(dx[:1] * 0).astype(dist_dtype) + jnp.sum(tx[:1] * 0).astype(dist_dtype)
        init = (
            jnp.full((query_tile, k), jnp.inf, dist_dtype) + vzero,
            jnp.zeros((query_tile, k), jnp.int32) + vzero.astype(jnp.int32),
        )
        bases = (jnp.arange(n_dtiles) * data_tile).astype(jnp.int32)
        (bd, bi), _ = jax.lax.scan(fold, init, (dxp, dyp, mp, bases))
        return bd, bi

    dists, idx = jax.lax.map(tile, (tiles_x, tiles_y))
    return (
        dists.reshape(-1, k)[:q],
        idx.reshape(-1, k)[:q],
    )


def _unit3(lon: jax.Array, lat: jax.Array) -> jax.Array:
    """[N] lon/lat degrees -> [N,3] unit vectors on the sphere (f32)."""
    rlon = jnp.radians(lon.astype(jnp.float32))
    rlat = jnp.radians(lat.astype(jnp.float32))
    cl = jnp.cos(rlat)
    return jnp.stack([cl * jnp.cos(rlon), cl * jnp.sin(rlon), jnp.sin(rlat)], -1)


def _morton16(lon: jax.Array, lat: jax.Array) -> jax.Array:
    """Z-order key from 16-bit-quantized lon/lat (device-side, jit-safe)."""
    qx = jnp.clip(((lon + 180.0) / 360.0 * 65535.0), 0, 65535).astype(jnp.uint32)
    qy = jnp.clip(((lat + 90.0) / 180.0 * 65535.0), 0, 65535).astype(jnp.uint32)

    def spread(v):
        v = (v | (v << 8)) & jnp.uint32(0x00FF00FF)
        v = (v | (v << 4)) & jnp.uint32(0x0F0F0F0F)
        v = (v | (v << 2)) & jnp.uint32(0x33333333)
        v = (v | (v << 1)) & jnp.uint32(0x55555555)
        return v

    return spread(qx) | (spread(qy) << 1)


@functools.partial(
    jax.jit,
    static_argnames=(
        "k", "query_tile", "data_tile", "margin", "with_flags", "presorted"
    ),
)
def knn_mxu(
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    query_tile: int = 64,
    data_tile: Optional[int] = None,
    margin: Optional[int] = None,
    with_flags: bool = False,
    presorted: bool = False,
):
    """kNN via the MXU: centered chord-distance matmul + exact refine.

    Same contract as `knn`. The great-circle distance is monotonic in the
    3D chord distance, so top-k by smallest chord^2 equals top-k by
    smallest haversine. With points as unit vectors, chord^2 = 2 - 2 q.d
    cancels catastrophically in f32 for nearby points (every dot rounds to
    1.0 inside a ~3 km cluster). Instead both sides are translated by the
    query tile's centroid c and

        chord^2 = |q-c|^2 + |d-c|^2 - 2 (q-c).(d-c)

    — translation-invariant and exact in infinite precision, while every
    operand now scales with distance-from-centroid, so f32 resolution is
    relative to the local spread rather than to 1.0. The cross term is a
    [Q,3]x[3,N] matmul on the MXU (~3 MACs/pair at systolic-array rate vs
    ~20 VPU transcendental ops/pair for direct haversine); the norms are
    cheap elementwise VPU work.

    Accuracy model (documented, tested): the f32 rounding noise in chord^2
    is ~6e-8 * r^2 for r = the query TILE's radius in radians. Queries are
    therefore Z-order-sorted internally so each tile of `query_tile`
    (default 64) consecutive queries is as spatially compact as the query
    distribution allows, the candidate pool keeps a top-M margin
    (M = max(4k, 64)) per query, and the final k come from EXACT haversine
    over those M gathered candidates. A true neighbor can only be lost when
    MORE than M-k data points sit inside the noise band around the k-th
    distance — i.e. a meters-dense data cluster queried from a tile whose
    other queries are 100s of km away (the sorted-order tile that straddles
    a cluster boundary). For guaranteed exactness, `with_flags=True` also
    returns a per-query bool that is True whenever the noise bound CANNOT
    prove the result exact: the refined pool's chord^2 span is compared
    against 2B for B = a conservative multiple of eps*r_tile^2. Callers
    (the KNN process does this) re-run flagged queries on the exact
    haversine path — typically none, or only the handful in boundary tiles.

    Small query sets (Q < 128) fall back to the exact haversine path: with
    so few MXU rows the kernel is HBM-bandwidth-bound either way, so the
    matmul buys nothing and tile compactness cannot be established.
    """
    q = qx.shape[0]
    n = dx.shape[0]
    if q < 128:
        fd, fi = knn(qx, qy, dx, dy, mask, k=k,
                     query_tile=min(query_tile, max(q, 1)), data_tile=data_tile)
        return (fd, fi, jnp.zeros(q, bool)) if with_flags else (fd, fi)
    m = margin if margin is not None else max(4 * k, 64)
    m = min(m, n) if n else m
    if data_tile is None:
        data_tile = max(m, min(n, (1 << 27) // max(query_tile, 1)))
    # block-minima layout needs whole 128-lane blocks per data tile
    data_tile = -(-data_tile // 128) * 128

    # compact tiles: process queries in Z-order, un-permute at the end.
    # presorted=True lets loop callers (knn_ring) sort once outside.
    if presorted:
        inv = None
    else:
        order = jnp.argsort(_morton16(qx, qy))
        inv = jnp.argsort(order)
        qx = jnp.take(qx, order)
        qy = jnp.take(qy, order)

    pad = (-q) % query_tile
    # edge-pad so padded lanes don't drag the tile centroid off-cluster
    qxp = jnp.pad(qx, (0, pad), mode="edge") if q else jnp.pad(qx, (0, pad))
    qyp = jnp.pad(qy, (0, pad), mode="edge") if q else jnp.pad(qy, (0, pad))
    qu = _unit3(qxp, qyp)
    tiles_q = qu.reshape(-1, query_tile, 3)

    dpad = (-n) % data_tile
    du = _unit3(jnp.pad(dx, (0, dpad)), jnp.pad(dy, (0, dpad)))
    dut = du.reshape(-1, data_tile, 3)
    mp = jnp.pad(mask, (0, dpad)).reshape(-1, data_tile)
    n_dtiles = dut.shape[0]
    BIG = jnp.float32(8.0)  # > max chord^2 (4.0)

    # deferred block selection: the scan emits only per-128-lane block
    # minima (which XLA fuses into the matmul epilogue — the [Q, N] chord^2
    # matrix never reaches HBM), the m winning blocks per query are picked
    # ONCE over the accumulated minima, and chord^2 is recomputed for just
    # those m·128 lanes. This replaces a per-scan-step top-k + pool merge
    # that cost ~3.5x the fused pass at GDELT scale. Exactness is the
    # two-level argument: if a true top-m element's block were unpicked, m
    # picked blocks each hold an element <= it, so its rank exceeds m.
    BLK = 128
    nb_tile = data_tile // BLK
    du_flat = du  # [n_padded, 3]
    mp_flat = jnp.pad(mask, (0, dpad))

    def tile(tq):
        c = tq.mean(axis=0)
        tqc = tq - c
        nq = jnp.sum(tqc * tqc, axis=-1)  # [query_tile]
        r2_tile = jnp.max(nq)  # squared tile radius, for the noise bound
        # augmented queries [tqc | 1]: one matmul emits the entire per-pair
        # ranking key nd - 2 q.d (chord^2 minus the per-query constant nq,
        # which cannot change ranks within a query row), so the VPU's only
        # [Q, N] work is the block-min compare
        aug_q = jnp.concatenate(
            [tqc, jnp.ones((query_tile, 1), tqc.dtype)], axis=1
        )

        def fold(_, xs):
            dt, mt = xs
            dtc = dt - c
            nd = jnp.sum(dtc * dtc, axis=-1)  # [data_tile]
            # masked rows carry a huge additive term instead of a [Q, N]
            # where(): 1e9 dwarfs any real key (|nd - 2 q.d| <= 12)
            ndm = jnp.where(mt, nd, jnp.float32(1e9))
            aug_d = jnp.concatenate([-2.0 * dtc, ndm[:, None]], axis=1)
            key = jax.lax.dot_general(
                aug_q, aug_d, (((1,), (1,)), ((), ())),
                precision=jax.lax.Precision.HIGHEST,
            )  # [query_tile, data_tile] = nd - 2 q.d (+1e9 where masked)
            bmin = key.reshape(query_tile, nb_tile, BLK).min(axis=-1)
            return None, bmin

        _, minima = jax.lax.scan(fold, None, (dut, mp))
        # [n_dtiles, query_tile, nb_tile] -> [query_tile, total_blocks]
        minima = minima.transpose(1, 0, 2).reshape(query_tile, -1)
        mb = min(m, minima.shape[-1])
        _, blk_ids = _twolevel_smallest(minima, mb)  # [query_tile, mb]

        # recompute chord^2 for the winning blocks only (same centered
        # arithmetic, so the noise model and certificate are unchanged)
        lane = (blk_ids[:, :, None] * BLK
                + jnp.arange(BLK, dtype=jnp.int32)).reshape(query_tile, -1)
        gd = jnp.take(du_flat, lane, axis=0)  # [query_tile, mb*BLK, 3]
        gm = jnp.take(mp_flat, lane)
        gdc = gd - c
        nd_g = jnp.sum(gdc * gdc, axis=-1)
        s_g = jnp.einsum("qd,qjd->qj", tqc, gdc,
                         precision=jax.lax.Precision.HIGHEST)
        chord2_g = nq[:, None] + nd_g - 2.0 * s_g
        chord2_g = jnp.where(gm, chord2_g, BIG)
        bs, within = _topk_smallest(chord2_g, m)
        bi = jnp.minimum(
            jnp.take_along_axis(lane, within, axis=1).astype(jnp.int32), n - 1
        )
        return bs, bi, jnp.broadcast_to(r2_tile, (tq.shape[0],))

    chord2, cidx, r2 = jax.lax.map(tile, tiles_q)
    chord2 = chord2.reshape(-1, m)[:q]
    cidx = cidx.reshape(-1, m)[:q]
    r2 = r2.reshape(-1)[:q]

    # exact refine: haversine over the gathered M candidates per query
    cx = jnp.take(dx, cidx)
    cy = jnp.take(dy, cidx)
    dist_dtype = jnp.promote_types(jnp.promote_types(qx.dtype, dx.dtype), jnp.float32)
    d = haversine_m(
        qx[:, None].astype(dist_dtype), qy[:, None].astype(dist_dtype),
        cx.astype(dist_dtype), cy.astype(dist_dtype),
    )
    # masked/unfilled slots carry chord2 == BIG (8.0); legitimate points can
    # reach chord2 == 4.0 exactly at a query's antipode, so the cut must sit
    # strictly between 4+noise and BIG or antipodal neighbors read as masked
    d = jnp.where(chord2 >= 6.0, INF, d)
    fd, sel = _topk_smallest(d, k)
    fi = jnp.take_along_axis(cidx, sel, axis=1)
    fd_out = fd if inv is None else jnp.take(fd, inv, axis=0)
    fi_out = fi if inv is None else jnp.take(fi, inv, axis=0)
    if not with_flags:
        return fd_out, fi_out

    # exactness certificate: an excluded point's true chord^2 exceeds the
    # pool's selection threshold minus the rounding-noise bound B; if the
    # exact k-th..M-th chord^2 span is wider than 2B, no excluded point can
    # beat the k-th neighbor and the result is provably exact.
    from geomesa_tpu.engine.geodesy import EARTH_RADIUS_M

    EPS = jnp.float32(6e-8)  # f32 ulp at ~1 (matmul/norm rounding)
    KAPPA = jnp.float32(8.0)  # roundings of magnitude <= eps * r^2 each
    ETA = jnp.float32(1.3e-7)  # unit-vector f32 quantization (per point)
    finite = jnp.isfinite(d)
    has_unfilled = jnp.any(~finite, axis=1)  # pool held every candidate
    d_M = jnp.max(jnp.where(finite, d, -jnp.inf), axis=1)
    chord_k = 2.0 * jnp.sin(fd[:, -1] / (2.0 * EARTH_RADIUS_M))
    chord_M = 2.0 * jnp.sin(jnp.where(jnp.isfinite(d_M), d_M, 0.0)
                            / (2.0 * EARTH_RADIUS_M))
    B = KAPPA * EPS * r2 + 8.0 * ETA * chord_k
    uncertain = (
        ~has_unfilled
        & (chord_M * chord_M - chord_k * chord_k < 2.0 * B)
    )
    if inv is not None:
        uncertain = jnp.take(uncertain, inv, axis=0)
    return fd_out, fi_out, uncertain


@functools.partial(
    jax.jit, static_argnames=("k", "capacity", "impl", "query_tile")
)
def knn_compact(
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    capacity: int,
    impl: str = "mxu",
    query_tile: int = 64,
) -> Tuple[jax.Array, jax.Array]:
    """kNN over the mask's matches only: device-side candidate compaction.

    At GDELT-scale selectivity (a few % of the scanned batch matches the
    predicate) the dominant cost of `knn`/`knn_mxu` is streaming [Q, N]
    distance blocks through HBM for rows the mask rejects anyway. This
    gathers the matching rows into a dense [capacity] candidate array first
    (one `nonzero` pass — the columnar analog of the reference emitting
    index-scan hits before running KNN on them), then runs the kNN kernel on
    the compacted set: distance traffic drops from O(Q·N) to O(Q·count).

    `capacity` must be a static bound >= the match count (callers bucket it
    to the next power of two to stabilize jit cache keys); validity of each
    compacted slot is derived on device from a sentinel, so no count needs
    to cross from the host. Returned indices refer to the ORIGINAL arrays.

    Returns (dists [Q,k], indices [Q,k], overflow scalar bool): `overflow`
    is True iff the match count exceeded `capacity`, in which case the
    result silently dropped the lowest-index matches — callers MUST check
    it and fall back to the full-scan kernel (the round-1 advisor flagged
    the unchecked contract).
    """
    # top_k-based stream compaction: jnp.nonzero(size=...) lowers ~26x
    # slower on TPU (measured 6.3s vs 0.26s at 67M); top_k over
    # where(mask, iota, -1) yields the matched indices (descending order —
    # irrelevant for kNN) at sort-free selection cost
    n = dx.shape[0]
    if n >= (1 << 31):
        # the int32 index iota below wraps past 2^31 rows; callers shard /
        # tile batches far below this (trace-time check, n is static)
        raise ValueError("knn_compact supports n < 2^31 rows per batch")
    capacity = min(capacity, n)  # lax.top_k requires k <= lane count
    overflow = jnp.sum(mask, dtype=jnp.int32) > capacity
    picked = jax.lax.top_k(
        jnp.where(mask, jnp.arange(n, dtype=jnp.int32), -1), capacity
    )[0]
    idx = jnp.maximum(picked, 0)
    valid = picked >= 0
    cx = jnp.take(dx, idx)
    cy = jnp.take(dy, idx)
    if impl == "mxu":
        fd, fi = knn_mxu(qx, qy, cx, cy, valid, k=k, query_tile=query_tile)
    else:
        fd, fi = knn(qx, qy, cx, cy, valid, k=k)
    return fd, jnp.take(idx, fi), overflow


def knn_sharded(
    mesh: Mesh,
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    query_tile: int = 1024,
    impl: str = "haversine",
    debug_check: bool = False,
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN with data sharded over the mesh: local top-k + all_gather
    merge. Returns (dists [Q,k], global indices [Q,k]).

    Exactness: each shard's local top-k is exact over its rows; the true
    global top-k is a subset of the union of per-shard top-ks, so the merged
    re-top-k is exact — the same argument as the reference's per-tablet
    aggregation + client merge, with psum-free O(D·Q·k) gather traffic.

    impl: "haversine" (VPU, bit-exact — the merge argument above holds
    unconditionally) or "mxu" (`knn_mxu` without its exactness certificate:
    the local top-k inherits knn_mxu's f32 noise model, so cluster-boundary
    query tiles can mis-rank meters-scale near-ties; use the KNN process or
    impl="haversine" where guaranteed exactness is required).

    debug_check: the out_specs below declare the post-gather re-top-k
    replicated (check_vma=False silences JAX's varying-mesh-axes check,
    which cannot infer it). With debug_check=True the kernel additionally
    all_gathers the FINAL result and asserts on host that every device
    computed bitwise-identical values — pinning the unchecked invariant
    (round-1 review) at the cost of one extra [D, Q, k] gather.
    """
    if impl == "mxu":
        def local(*a, **kw):
            kw["query_tile"] = min(kw.pop("query_tile", 64), 64)
            return knn_mxu(*a, **kw)
    else:
        local = knn
    d_count = mesh.devices.size
    shard_n = dx.shape[0] // d_count

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(), P(), P()) if debug_check else (P(), P()),
        # post-gather re-top-k computes identical values on every device;
        # JAX's varying-mesh-axes check can't infer that, so assert it
        # (debug_check=True verifies the claim at run time)
        check_vma=False,
    )
    def run(qx, qy, dx, dy, mask):
        dists, idx = local(qx, qy, dx, dy, mask, k=k, query_tile=query_tile)
        shard = jax.lax.axis_index(SHARD_AXIS)
        gidx = idx + shard * shard_n
        # [D, Q, k] candidate pools on every device
        all_d = jax.lax.all_gather(dists, SHARD_AXIS)
        all_i = jax.lax.all_gather(gidx, SHARD_AXIS)
        pool_d = jnp.moveaxis(all_d, 0, 1).reshape(dists.shape[0], -1)
        pool_i = jnp.moveaxis(all_i, 0, 1).reshape(dists.shape[0], -1)
        md, mi = _topk_smallest(pool_d, k)
        gi = jnp.take_along_axis(pool_i, mi, axis=1)
        if debug_check:
            # gather every device's final answer and count positions that
            # differ from device 0's — must be 0 when the replication
            # claim holds. Equality (not subtraction): results are
            # +inf-padded when valid matches < k, and inf - inf = NaN
            # would flag agreement as divergence
            gd = jax.lax.all_gather(md, SHARD_AXIS)
            gg = jax.lax.all_gather(gi, SHARD_AXIS)
            div = jnp.sum((gd != gd[0:1]).astype(jnp.int32)) + jnp.sum(
                (gg != gg[0:1]).astype(jnp.int32)
            )
            return md, gi, div
        return md, gi

    if debug_check:
        md, gi, div = run(qx, qy, dx, dy, mask)
        if float(div) != 0.0:
            raise AssertionError(
                "knn_sharded replication invariant violated: devices "
                f"disagree on the merged top-k (divergence {float(div)})"
            )
        return md, gi
    return run(qx, qy, dx, dy, mask)


def knn_compact_sharded(
    mesh: Mesh,
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    capacity: int,
    query_tile: int = 64,
) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """knn_compact under the data-sharded merge: each shard compacts its
    own matches (static per-shard `capacity`) and runs the MXU kNN over
    them; the per-shard top-ks merge via all_gather exactly as
    `knn_sharded`. Returns (dists [Q,k], global indices [Q,k],
    overflow bool — True if ANY shard's matches exceeded capacity, in
    which case callers MUST fall back to the full sharded scan)."""
    d_count = mesh.devices.size
    shard_n = dx.shape[0] // d_count

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(P(), P(), P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS)),
        out_specs=(P(), P(), P()),
        check_vma=False,  # post-gather re-top-k replicated (see knn_sharded)
    )
    def run(qx, qy, dx, dy, mask):
        fd, fi, ov = knn_compact(
            qx, qy, dx, dy, mask, k=k, capacity=capacity,
            query_tile=query_tile,
        )
        shard = jax.lax.axis_index(SHARD_AXIS)
        gidx = fi + shard * shard_n
        all_d = jax.lax.all_gather(fd, SHARD_AXIS)
        all_i = jax.lax.all_gather(gidx, SHARD_AXIS)
        pool_d = jnp.moveaxis(all_d, 0, 1).reshape(fd.shape[0], -1)
        pool_i = jnp.moveaxis(all_i, 0, 1).reshape(fd.shape[0], -1)
        md, mi = _topk_smallest(pool_d, k)
        gi = jnp.take_along_axis(pool_i, mi, axis=1)
        ov_any = jnp.any(jax.lax.all_gather(ov, SHARD_AXIS))
        return md, gi, ov_any

    return run(qx, qy, dx, dy, mask)


def knn_ring(
    mesh: Mesh,
    qx: jax.Array,
    qy: jax.Array,
    dx: jax.Array,
    dy: jax.Array,
    mask: jax.Array,
    k: int,
    query_tile: int = 1024,
    impl: str = "haversine",
) -> Tuple[jax.Array, jax.Array]:
    """Exact kNN with BOTH queries and data sharded: ring top-k.

    Each device owns a query shard and a data shard; data shards rotate
    around the ring (ppermute) for D steps while every device folds the
    visiting shard into its running top-k. Communication is the data shard
    itself (the ring-attention access pattern), never the QxN distances.
    Returns (dists, global indices) sharded like the queries.

    impl: "haversine" (bit-exact) or "mxu" (knn_mxu's f32 noise model, no
    certificate — see knn_sharded). For mxu the Z-order query sort is
    hoisted out of the ring loop (queries never change between steps).
    """
    use_mxu = impl == "mxu"
    d_count = mesh.devices.size
    shard_n = dx.shape[0] // d_count

    @functools.partial(
        _shard_map,
        mesh=mesh,
        in_specs=(
            P(SHARD_AXIS), P(SHARD_AXIS),
            P(SHARD_AXIS), P(SHARD_AXIS), P(SHARD_AXIS),
        ),
        out_specs=(P(SHARD_AXIS), P(SHARD_AXIS)),
        check_vma=False,  # fori_loop carry turns varying after step 1;
        # the 0.4.x shard_map path relies on this (pcast shims to a
        # no-op there — see jaxcompat.pcast)
    )
    def run(qx, qy, dx, dy, mask):
        me = jax.lax.axis_index(SHARD_AXIS)
        perm = [(i, (i + 1) % d_count) for i in range(d_count)]

        if use_mxu:
            order = jnp.argsort(_morton16(qx, qy))
            inv = jnp.argsort(order)
            qx = jnp.take(qx, order)
            qy = jnp.take(qy, order)

            def local(qx, qy, dx, dy, mask, k, query_tile):
                return knn_mxu(qx, qy, dx, dy, mask, k=k,
                               query_tile=min(query_tile, 64), presorted=True)
        else:
            local = knn

        def step(i, carry):
            best_d, best_i, dx, dy, mask = carry
            owner = (me - i) % d_count  # whose shard is visiting
            ld, li = local(qx, qy, dx, dy, mask, k=k, query_tile=query_tile)
            gi = (li + owner * shard_n).astype(jnp.int32)
            pool_d = jnp.concatenate([best_d, ld], axis=1)
            pool_i = jnp.concatenate([best_i, gi], axis=1)
            nd, sel = _topk_smallest(pool_d, k)
            ni = jnp.take_along_axis(pool_i, sel, axis=1)
            dx, dy, mask = (
                jax.lax.ppermute(a, SHARD_AXIS, perm) for a in (dx, dy, mask)
            )
            return nd, ni, dx, dy, mask

        q = qx.shape[0]
        dist_dtype = jnp.promote_types(jnp.promote_types(qx.dtype, dx.dtype), jnp.float32)
        # mark the init carry as device-varying (it becomes so after step 1)
        best_d = _pcast(
            jnp.full((q, k), jnp.inf, dist_dtype), SHARD_AXIS, to="varying"
        )
        best_i = _pcast(jnp.zeros((q, k), jnp.int32), SHARD_AXIS, to="varying")
        best_d, best_i, *_ = jax.lax.fori_loop(
            0, d_count, step, (best_d, best_i, dx, dy, mask)
        )
        if use_mxu:
            best_d = jnp.take(best_d, inv, axis=0)
            best_i = jnp.take(best_i, inv, axis=0)
        return best_d, best_i

    return run(qx, qy, dx, dy, mask)
