"""Geodetic distance kernels.

Parity: the geodesic-distance role of GeoTools' GeodeticCalculator in the
reference's KNN process (treated as haversine per BASELINE.json's config 3)
[upstream, unverified]. Haversine on the WGS84 mean sphere — vectorized,
MXU/VPU-friendly (pure elementwise trig; fuses into surrounding kernels).
"""

from __future__ import annotations

import jax.numpy as jnp
import numpy as np

EARTH_RADIUS_M = 6_371_008.8  # IUGG mean radius


def haversine_m(lon1, lat1, lon2, lat2, dtype=None):
    """Great-circle distance in meters. Broadcasts over inputs.

    Uses the numerically-stable haversine form; for sub-meter stability at
    tiny separations compute in f32 with f64 refinement upstream if needed.
    """
    if dtype is not None:
        lon1, lat1, lon2, lat2 = (jnp.asarray(a, dtype) for a in (lon1, lat1, lon2, lat2))
    rlon1, rlat1, rlon2, rlat2 = (jnp.radians(a) for a in (lon1, lat1, lon2, lat2))
    dlat = rlat2 - rlat1
    dlon = rlon2 - rlon1
    a = (
        jnp.sin(dlat / 2) ** 2
        + jnp.cos(rlat1) * jnp.cos(rlat2) * jnp.sin(dlon / 2) ** 2
    )
    return 2.0 * EARTH_RADIUS_M * jnp.arcsin(jnp.sqrt(jnp.clip(a, 0.0, 1.0)))


def haversine_m_np(lon1, lat1, lon2, lat2):
    """NumPy reference implementation (the test oracle's distance)."""
    rlon1, rlat1, rlon2, rlat2 = (
        np.radians(np.asarray(a, np.float64)) for a in (lon1, lat1, lon2, lat2)
    )
    dlat = rlat2 - rlat1
    dlon = rlon2 - rlon1
    a = (
        np.sin(dlat / 2) ** 2
        + np.cos(rlat1) * np.cos(rlat2) * np.sin(dlon / 2) ** 2
    )
    return 2.0 * EARTH_RADIUS_M * np.arcsin(np.sqrt(np.clip(a, 0.0, 1.0)))


def point_to_segments_m(px, py, sx1, sy1, sx2, sy2):
    """Approximate min distance (meters) from points to a set of segments.

    Equirectangular local projection around each point's latitude: exact
    enough for DWITHIN-style predicates at sub-percent error for segment
    spans << Earth radius (documented divergence from the reference's
    geodesic calculator; the error is conservative-tested in parity suites).

    px, py: [N]; s*: [S]. Returns [N] min over segments.
    """
    deg_m_lat = 111_194.9  # pi * R / 180
    coslat = jnp.cos(jnp.radians(py))[:, None]
    # project: meters relative to each point
    ax = (sx1[None, :] - px[:, None]) * deg_m_lat * coslat
    ay = (sy1[None, :] - py[:, None]) * deg_m_lat
    bx = (sx2[None, :] - px[:, None]) * deg_m_lat * coslat
    by = (sy2[None, :] - py[:, None]) * deg_m_lat
    dx = bx - ax
    dy = by - ay
    seg_len2 = dx * dx + dy * dy
    t = jnp.clip(-(ax * dx + ay * dy) / jnp.maximum(seg_len2, 1e-12), 0.0, 1.0)
    cx = ax + t * dx
    cy = ay + t * dy
    d2 = cx * cx + cy * cy
    return jnp.sqrt(jnp.min(d2, axis=1))
