"""TPU execution engine: device batches and the kernel suite.

This package is the TPU-native replacement for the reference's server-side
scan path (geomesa-index-api iterators + geomesa-accumulo/-hbase distributed
runtimes) and the compute cores of geomesa-process [upstream, unverified]:
residual CQL evaluation = compiled predicate masks; DensityScan = masked
scatter-add; StatsScan = masked reductions; KNN/TubeSelect = tiled distance
kernels; cross-device merge = XLA collectives over the "shard" mesh axis.
"""

from geomesa_tpu.engine.device import DeviceBatch, to_device

__all__ = ["DeviceBatch", "to_device"]
