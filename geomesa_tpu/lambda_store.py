"""Lambda store: transient live tier + persistent tier, merged on read.

Parity: geomesa-lambda LambdaDataStore [upstream, unverified]: recent writes
live in Kafka + an in-memory cache (transient tier) and are asynchronously
persisted after an age threshold to a backing persistent store; queries
merge both tiers with the transient feature winning on feature-id collision.

Here: transient = KafkaDataStore (in-process broker), persistent = the
partitioned Parquet DataStore. `persist()` is the explicit tick the
reference runs on a scheduled executor (upstream: OffsetManager-coordinated
expiry); call it from a host timer.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set

import numpy as np

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.kafka.store import InProcessBroker, KafkaDataStore
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query
from geomesa_tpu.plan.planner import QueryResult


class LambdaDataStore:
    def __init__(
        self,
        catalog: str,
        persist_after_ms: int = 60_000,
        broker: Optional[InProcessBroker] = None,
        mesh=None,
    ):
        self.persistent = DataStore(catalog, mesh=mesh)
        self.transient = KafkaDataStore(broker=broker, mesh=mesh)
        self.persist_after_ms = persist_after_ms
        self._created: Set[str] = set()

    # -- schema ------------------------------------------------------------

    def create_schema(self, sft: SimpleFeatureType, scheme=None) -> None:
        self.persistent.create_schema(sft, scheme)
        self.transient.create_schema(sft)
        self._created.add(sft.name)

    def get_type_names(self) -> List[str]:
        return sorted(set(self.persistent.get_type_names()) | set(self._created))

    def get_schema(self, name: str) -> SimpleFeatureType:
        return self.persistent.get_schema(name)

    # -- writes (transient tier) ------------------------------------------

    def write(self, name: str, batch: FeatureBatch) -> None:
        self.transient.write(name, batch)

    def delete(self, name: str, fid: str) -> None:
        self.transient.delete(name, fid)

    # -- persistence tick --------------------------------------------------

    def persist(self, name: str, now: Optional[float] = None) -> int:
        """Move features older than persist_after_ms into the persistent
        store; returns how many were persisted."""
        self.transient.poll(name)
        cache = self.transient.cache(name)
        now = now if now is not None else time.time()
        cutoff = now - self.persist_after_ms / 1000.0
        snap = cache.snapshot()
        if snap is None:
            return 0
        with cache._lock:
            old = [fid for fid, ts in cache._stamps.items() if ts < cutoff]
        if not old:
            return 0
        fids = snap.fids.decode() if snap.fids is not None else []
        idx = [i for i, f in enumerate(fids) if f in set(old)]
        if not idx:
            return 0
        moving = snap.select(np.asarray(idx))
        self.persistent.get_feature_source(name).write(moving)
        for fid in old:
            self.transient.delete(name, fid)
        self.transient.poll(name)
        return len(idx)

    # -- merged reads ------------------------------------------------------

    def get_features(self, query: "Query | str") -> QueryResult:
        """Query both tiers; merge feature results with transient-wins
        dedupe by fid.

        Aggregation hints (density/stats/bin/arrow) run over the MERGED
        deduped rows (round-3; previously unsupported): both tiers are
        fetched as features with the same filter, deduped transient-wins,
        and the standard hint dispatcher (plan.runner.aggregate) runs on
        the merged batch — semantics identical to aggregating a single
        store holding the merged view. Trade: the merged rows come back
        to the host before aggregation (no per-tier partial aggregation;
        the transient tier is small by design, so the persistent tier's
        feature fetch dominates either way)."""
        if isinstance(query, str):
            name = self.get_type_names()[0] if "(" not in query else None
            raise TypeError("pass a Query(type_name, cql) to LambdaDataStore")
        if query.hints is not None and (
            query.hints.is_density or query.hints.is_stats
            or query.hints.is_bin or query.hints.is_arrow
        ):
            import dataclasses as _dc

            # strip ONLY the aggregation-kind fields: auths/sampling/etc
            # must survive into the tier fetches (a fresh QueryHints()
            # would fold visibility with EMPTY auths and hide rows the
            # caller is authorized to see — round-3 review finding)
            plain = _dc.replace(query, hints=_dc.replace(
                query.hints,
                density_bbox=None, density_width=None,
                density_height=None, density_weight=None,
                bin_track=None, bin_label=None,
                stats_string=None, arrow_encode=False,
            ))
            merged = self.get_features(plain)
            mb = merged.features
            sft = self.get_schema(query.type_name)
            if mb is None or not len(mb):
                from geomesa_tpu.core.columnar import FeatureBatch as _FB

                mb = _FB.from_pydict(
                    sft, {a.name: [] for a in sft.attributes}
                )
            from geomesa_tpu.engine.device import to_device
            from geomesa_tpu.plan.runner import aggregate

            dev = to_device(mb)
            return aggregate(
                sft, mb, dev, np.ones(len(mb), bool), query,
                fold_visibility=False,  # folded by each tier's fetch
            )
        p = self.persistent.get_feature_source(query.type_name).get_features(query)
        t = self.transient.get_feature_source(query.type_name).get_features(query)
        if p.kind != "features":
            raise NotImplementedError(
                "aggregation hints over the merged lambda view are not "
                "supported; query a single tier"
            )
        return _merge_features(t, p)

    def get_count(self, query: "Query | str") -> int:
        r = self.get_features(query)
        return len(r.features) if r.features is not None else 0


def _merge_features(transient: QueryResult, persistent: QueryResult) -> QueryResult:
    tb = transient.features
    pb = persistent.features
    if tb is None or len(tb) == 0:
        return persistent
    if pb is None or len(pb) == 0:
        return transient
    tfids = set(tb.fids.decode()) if tb.fids is not None else set()
    if pb.fids is not None and tfids:
        keep = np.asarray([f not in tfids for f in pb.fids.decode()])
        pb = pb.select(np.nonzero(keep)[0])
    merged = FeatureBatch.concat([tb, pb]) if len(pb) else tb
    return QueryResult("features", features=merged, count=len(merged))
