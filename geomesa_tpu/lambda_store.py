"""Lambda store: transient live tier + persistent tier, merged on read.

Parity: geomesa-lambda LambdaDataStore [upstream, unverified]: recent writes
live in Kafka + an in-memory cache (transient tier) and are asynchronously
persisted after an age threshold to a backing persistent store; queries
merge both tiers with the transient feature winning on feature-id collision.

Here: transient = KafkaDataStore (in-process broker), persistent = the
partitioned Parquet DataStore. `persist()` is the explicit tick the
reference runs on a scheduled executor (upstream: OffsetManager-coordinated
expiry); call it from a host timer.
"""

from __future__ import annotations

import time
from typing import Dict, List, Optional, Set

import numpy as np

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.kafka.store import InProcessBroker, KafkaDataStore
from geomesa_tpu.plan.datastore import DataStore
from geomesa_tpu.plan.query import Query
from geomesa_tpu.plan.planner import QueryResult


class LambdaDataStore:
    def __init__(
        self,
        catalog: str,
        persist_after_ms: int = 60_000,
        broker: Optional[InProcessBroker] = None,
        mesh=None,
    ):
        self.persistent = DataStore(catalog, mesh=mesh)
        self.transient = KafkaDataStore(broker=broker, mesh=mesh)
        self.persist_after_ms = persist_after_ms
        self._created: Set[str] = set()

    # -- schema ------------------------------------------------------------

    def create_schema(self, sft: SimpleFeatureType, scheme=None) -> None:
        self.persistent.create_schema(sft, scheme)
        self.transient.create_schema(sft)
        self._created.add(sft.name)

    def get_type_names(self) -> List[str]:
        return sorted(set(self.persistent.get_type_names()) | set(self._created))

    def get_schema(self, name: str) -> SimpleFeatureType:
        return self.persistent.get_schema(name)

    # -- writes (transient tier) ------------------------------------------

    def write(self, name: str, batch: FeatureBatch) -> None:
        self.transient.write(name, batch)

    def delete(self, name: str, fid: str) -> None:
        self.transient.delete(name, fid)

    # -- persistence tick --------------------------------------------------

    def persist(self, name: str, now: Optional[float] = None) -> int:
        """Move features older than persist_after_ms into the persistent
        store; returns how many were persisted."""
        self.transient.poll(name)
        cache = self.transient.cache(name)
        now = now if now is not None else time.time()
        cutoff = now - self.persist_after_ms / 1000.0
        snap = cache.snapshot()
        if snap is None:
            return 0
        with cache._lock:
            old = [fid for fid, ts in cache._stamps.items() if ts < cutoff]
        if not old:
            return 0
        fids = snap.fids.decode() if snap.fids is not None else []
        idx = [i for i, f in enumerate(fids) if f in set(old)]
        if not idx:
            return 0
        moving = snap.select(np.asarray(idx))
        self.persistent.get_feature_source(name).write(moving)
        for fid in old:
            self.transient.delete(name, fid)
        self.transient.poll(name)
        return len(idx)

    # -- merged reads ------------------------------------------------------

    def get_features(self, query: "Query | str") -> QueryResult:
        """Query both tiers; merge feature results with transient-wins
        dedupe by fid. Aggregations (density/stats) run per tier and are
        NOT merged here — run them post-persist or on one tier."""
        if isinstance(query, str):
            name = self.get_type_names()[0] if "(" not in query else None
            raise TypeError("pass a Query(type_name, cql) to LambdaDataStore")
        p = self.persistent.get_feature_source(query.type_name).get_features(query)
        t = self.transient.get_feature_source(query.type_name).get_features(query)
        if p.kind != "features":
            raise NotImplementedError(
                "aggregation hints over the merged lambda view are not "
                "supported; query a single tier"
            )
        return _merge_features(t, p)

    def get_count(self, query: "Query | str") -> int:
        r = self.get_features(query)
        return len(r.features) if r.features is not None else 0


def _merge_features(transient: QueryResult, persistent: QueryResult) -> QueryResult:
    tb = transient.features
    pb = persistent.features
    if tb is None or len(tb) == 0:
        return persistent
    if pb is None or len(pb) == 0:
        return transient
    tfids = set(tb.fids.decode()) if tb.fids is not None else set()
    if pb.fids is not None and tfids:
        keep = np.asarray([f not in tfids for f in pb.fids.decode()])
        pb = pb.select(np.nonzero(keep)[0])
    merged = FeatureBatch.concat([tb, pb]) if len(pb) else tb
    return QueryResult("features", features=merged, count=len(merged))
