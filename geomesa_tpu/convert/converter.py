"""SimpleFeatureConverter implementations: delimited text and JSON.

Parity: geomesa-convert-text / geomesa-convert-json [upstream, unverified].
Config shape (the TypeSafe-Config structure as a plain dict):

    {
      "type": "delimited-text",        # or "json"
      "format": "CSV",                 # CSV | TSV (delimited-text)
      "options": {"skip-lines": 1, "error-mode": "skip-bad-records"},
      "id-field": "md5($2)",           # transform expr for the feature id
      "fields": [
        {"name": "eventId", "transform": "$1::int"},
        {"name": "geom", "transform": "point($40, $39)"},
      ],
    }

$0 is the whole record; $N is the 1-based source column (upstream
convention). For JSON, fields use "path" ($.a.b) plus optional transform
over $0 (= the extracted path value).

Validation parity: records whose geometry/dtg fail to materialize are
dropped ("skip-bad-records", the default) or raise ("raise-errors").
"""

from __future__ import annotations

import csv
import dataclasses
import io
import json
from typing import Dict, Iterable, List, Optional, Sequence

import numpy as np

from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import Geometry
from geomesa_tpu.convert.transforms import EvalContext, compile_expression


@dataclasses.dataclass
class _Field:
    name: str
    transform: Optional[object]  # compiled expr
    path: Optional[List[str]] = None  # json path segments


class _BaseConverter:
    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.sft = sft
        self.config = config
        self.error_mode = config.get("options", {}).get(
            "error-mode", "skip-bad-records"
        )
        self.id_field = (
            compile_expression(config["id-field"]) if config.get("id-field") else None
        )
        self.fields: List[_Field] = []
        for f in config.get("fields", []):
            self.fields.append(
                _Field(
                    f["name"],
                    compile_expression(f["transform"]) if f.get("transform") else None,
                    _json_path(f["path"]) if f.get("path") else None,
                )
            )
        self.failed = 0

    def _records(self, source) -> Iterable[EvalContext]:
        raise NotImplementedError

    def _field_value(self, ctx: EvalContext, f: _Field):
        if f.transform is not None:
            return f.transform(ctx)
        return ctx.named.get(f.name)

    def convert(self, source) -> FeatureBatch:
        """Parse a source (file path / file obj / string) into a batch."""
        data: Dict[str, list] = {a.name: [] for a in self.sft.attributes}
        fids: List[str] = []
        self.failed = 0
        for ctx in self._records(source):
            try:
                row = {}
                for f in self.fields:
                    row[f.name] = self._field_value(ctx, f)
                    ctx.named[f.name] = row[f.name]
                fid = str(self.id_field(ctx)) if self.id_field else f"f{ctx.line_no}"
                # validate the whole row BEFORE any append so a skipped
                # record never leaves columns misaligned
                for a in self.sft.attributes:
                    v = row.get(a.name)
                    if a.is_geometry and v is None:
                        raise ValueError(f"no geometry for {a.name}")
                    if a.is_temporal and v is None:
                        raise ValueError(f"no date for {a.name}")
                for a in self.sft.attributes:
                    data[a.name].append(row.get(a.name))
                fids.append(fid)
            except Exception:
                if self.error_mode == "raise-errors":
                    raise
                self.failed += 1
        from geomesa_tpu.utils.metrics import metrics

        metrics.counter("convert.success", len(fids))
        metrics.counter("convert.failure", self.failed)
        return self._to_batch(data, fids)

    def _to_batch(self, data, fids) -> FeatureBatch:
        cols = {}
        for a in self.sft.attributes:
            vals = data[a.name]
            if a.is_geometry:
                if vals and isinstance(vals[0], tuple):
                    arr = np.asarray(vals, np.float64)
                    cols[a.name] = arr
                else:
                    cols[a.name] = vals  # Geometry objects / WKT
            else:
                cols[a.name] = vals
        return FeatureBatch.from_pydict(self.sft, cols, fids=fids)


class DelimitedTextConverter(_BaseConverter):
    def _records(self, source):
        fh, close = _open(source)
        try:
            delim = "\t" if self.config.get("format", "CSV").upper() == "TSV" else ","
            skip = int(self.config.get("options", {}).get("skip-lines", 0))
            reader = csv.reader(fh, delimiter=delim)
            for i, rec in enumerate(reader):
                if i < skip:
                    continue
                raw = delim.join(rec)
                # $0 = full record, $N = 1-based column (upstream convention)
                yield EvalContext([raw] + rec, {}, line_no=i, raw=raw)
        finally:
            if close:
                fh.close()


class JsonConverter(_BaseConverter):
    def _records(self, source):
        fh, close = _open(source)
        try:
            for i, line in enumerate(fh):
                line = line.strip()
                if not line:
                    continue
                obj = json.loads(line)
                named = {}
                for f in self.fields:
                    if f.path is not None:
                        named[f.name] = _extract(obj, f.path)
                yield EvalContext([obj], named, line_no=i, raw=line)
        finally:
            if close:
                fh.close()

    def _field_value(self, ctx: EvalContext, f: _Field):
        # transforms run over the extracted path value, exposed as $0; a
        # missing path stays None so e.g. withDefault($0, ...) sees null
        # rather than the whole record
        v = ctx.named.get(f.name)
        if f.transform is not None:
            sub = EvalContext([v], ctx.named, ctx.line_no, ctx.raw)
            v = f.transform(sub)
        return v


def _open(source):
    if hasattr(source, "read"):
        return source, False
    if isinstance(source, str) and "\n" in source:
        return io.StringIO(source), True  # inline data
    # anything else is a path; a missing file must fail loudly, never be
    # silently parsed as inline data
    return open(source, "r"), True


def _json_path(path: str) -> List[str]:
    if path.startswith("$."):
        path = path[2:]
    elif path.startswith("$"):
        path = path[1:]
    return [p for p in path.split(".") if p]


def _extract(obj, path: List[str]):
    cur = obj
    for p in path:
        if isinstance(cur, dict):
            cur = cur.get(p)
        elif isinstance(cur, list) and p.isdigit():
            cur = cur[int(p)] if int(p) < len(cur) else None
        else:
            return None
        if cur is None:
            return None
    return cur


def converter_from_config(sft: SimpleFeatureType, config: dict):
    kind = config.get("type", "delimited-text")
    if kind == "delimited-text":
        return DelimitedTextConverter(sft, config)
    if kind == "json":
        return JsonConverter(sft, config)
    if kind in ("fixed-width", "xml", "shp", "avro", "parquet", "jdbc"):
        from geomesa_tpu.convert import formats

        cls = {
            "fixed-width": formats.FixedWidthConverter,
            "xml": formats.XmlConverter,
            "shp": formats.ShapefileConverter,
            "avro": formats.AvroConverter,
            "parquet": formats.ParquetConverter,
            "jdbc": formats.JdbcConverter,
        }[kind]
        return cls(sft, config)
    raise ValueError(f"unknown converter type {kind!r}")
