"""Converter framework: config-driven ingest.

Parity: geomesa-convert (SimpleFeatureConverter SPI v2, o.l.g.convert2)
[upstream, unverified]: TypeSafe-Config-defined field extraction plus a
transform expression DSL ($1, dateParse(...), point($lon,$lat), md5(...),
uuid(), casts) over delimited text / JSON sources; predefined well-known
schemas (GDELT, AIS, NYC taxi) as geomesa-tools ships.
"""

from geomesa_tpu.convert.transforms import compile_expression, EvalContext
from geomesa_tpu.convert.converter import (
    DelimitedTextConverter,
    JsonConverter,
    converter_from_config,
)
from geomesa_tpu.convert import schemas

__all__ = [
    "compile_expression",
    "EvalContext",
    "DelimitedTextConverter",
    "JsonConverter",
    "converter_from_config",
    "schemas",
]
