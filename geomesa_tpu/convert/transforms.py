"""Transform expression DSL.

Parity: o.l.g.convert2 Transformers [upstream, unverified]. Grammar:

  expr     := cast | call | ref | literal
  cast     := expr '::' type          (int, long, double, float, string, boolean)
  ref      := '$' digits | '$' name   (source column by position or name)
  call     := name '(' [expr (',' expr)*] ')'
  literal  := 'single-quoted' | number

Functions (the commonly-used upstream set): concat, trim, strip, lowercase,
uppercase, substring, replace, regexReplace, length, md5, murmurHash3, uuid,
point, geometry (WKT parse), dateParse (Java-style patterns), isoDate,
isoDateTime, secsToDate, millisToDate, toInt/toLong/toDouble/toFloat/
toString/toBoolean, stringToInt..., withDefault, require, lineNo.

Evaluation is row-wise over an EvalContext (ingest is a host-side path; the
device sees only the resulting columnar batch).
"""

from __future__ import annotations

import dataclasses
import hashlib
import re
import uuid as _uuid
from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

Value = object


@dataclasses.dataclass
class EvalContext:
    """One source record: positional fields ($0 = whole line upstream;
    kept here as the raw record string) + named fields + line number."""

    positional: Sequence[Value]
    named: Dict[str, Value]
    line_no: int = 0
    raw: str = ""


_TOKEN = re.compile(
    r"""\s*(?:
      (?P<dollar>\$[A-Za-z0-9_.]+)
    | (?P<number>-?\d+\.\d*|-?\d+)
    | (?P<string>'(?:[^']|'')*')
    | (?P<name>[A-Za-z_][A-Za-z0-9_]*)
    | (?P<cast>::)
    | (?P<punct>[(),])
    )""",
    re.VERBOSE,
)


def _tokenize(s: str):
    out, pos = [], 0
    while pos < len(s):
        m = _TOKEN.match(s, pos)
        if not m:
            raise ValueError(f"transform parse error at {s[pos:pos+15]!r}")
        pos = m.end()
        for kind in ("dollar", "number", "string", "name", "cast", "punct"):
            if m.group(kind) is not None:
                out.append((kind, m.group(kind)))
                break
    return out


# longer tokens first (MMM before MM, EEE before any E handling) so prefix
# tokens can't corrupt them; quoted literals go through placeholders so a
# later token rule can never rewrite their contents
_JAVA_TO_STRPTIME = [
    ("'T'", "\x01"), ("'Z'", "\x02"),
    ("yyyy", "%Y"), ("EEE", "%a"), ("MMM", "%b"), ("MM", "%m"),
    ("dd", "%d"), ("HH", "%H"), ("mm", "%M"), ("ss", "%S"),
    ("SSS", "%f"), ("DDD", "%j"), ("Z", "%z"),
    ("\x01", "T"), ("\x02", "Z"),
]


def _java_pattern(p: str) -> str:
    for a, b in _JAVA_TO_STRPTIME:
        p = p.replace(a, b)
    return p


_EN_MONTHS = {
    "Jan": "01", "Feb": "02", "Mar": "03", "Apr": "04", "May": "05",
    "Jun": "06", "Jul": "07", "Aug": "08", "Sep": "09", "Oct": "10",
    "Nov": "11", "Dec": "12",
}
_EN_DAYS = ("Mon", "Tue", "Wed", "Thu", "Fri", "Sat", "Sun")


def _parse_date(pattern: str, v: str) -> int:
    import datetime as dt
    import re as _re

    fmt = _java_pattern(pattern)
    s = str(v).strip()
    # EEE/MMM name tokens are defined as ENGLISH in the Java patterns these
    # configs come from, but strptime's %a/%b follow LC_TIME — normalize to
    # numerics/removal so parsing is locale-independent
    if "%b" in fmt:
        for name, num in _EN_MONTHS.items():
            if name in s:
                s = s.replace(name, num, 1)
                break
        fmt = fmt.replace("%b", "%m")
    if "%a" in fmt:
        s = _re.sub(r"(?:%s)\s*" % "|".join(_EN_DAYS), "", s, count=1)
        fmt = _re.sub(r"%a\s*", "", fmt, count=1)
    d = dt.datetime.strptime(s, fmt)
    if d.tzinfo is None:
        d = d.replace(tzinfo=dt.timezone.utc)
    return int(d.timestamp() * 1000)


def _iso_millis(v: str) -> int:
    s = str(v).strip()
    if s.endswith("Z"):
        s = s[:-1]
    return int(np.datetime64(s, "ms").astype(np.int64))


def _num(v) -> float:
    if isinstance(v, str):
        v = v.strip()
        if v == "":
            raise ValueError("empty numeric field")
    return float(v)


_FUNCTIONS: Dict[str, Callable] = {
    "concat": lambda ctx, *a: "".join(str(x) for x in a),
    "trim": lambda ctx, v: str(v).strip(),
    "strip": lambda ctx, v, chars=None: str(v).strip(chars),
    "lowercase": lambda ctx, v: str(v).lower(),
    "uppercase": lambda ctx, v: str(v).upper(),
    "substring": lambda ctx, v, a, b: str(v)[int(a): int(b)],
    "replace": lambda ctx, v, a, b: str(v).replace(str(a), str(b)),
    "regexReplace": lambda ctx, rx, rep, v: re.sub(str(rx), str(rep), str(v)),
    "length": lambda ctx, v: len(str(v)),
    "md5": lambda ctx, v: hashlib.md5(str(v).encode()).hexdigest(),
    "murmurHash3": lambda ctx, v: int.from_bytes(
        hashlib.blake2b(str(v).encode(), digest_size=4).digest(), "big"
    ),
    "uuid": lambda ctx: str(_uuid.uuid4()),
    "point": lambda ctx, x, y: (float(_num(x)), float(_num(y))),
    "geometry": lambda ctx, v: _parse_geom(v),
    "dateParse": lambda ctx, pattern, v: _parse_date(pattern, v),
    "date": lambda ctx, pattern, v: _parse_date(pattern, v),
    "isoDate": lambda ctx, v: _iso_millis(v),
    "isoDateTime": lambda ctx, v: _iso_millis(v),
    "secsToDate": lambda ctx, v: int(_num(v) * 1000),
    "millisToDate": lambda ctx, v: int(_num(v)),
    "toInt": lambda ctx, v, default=None: _safe(int, _num, v, default),
    "toLong": lambda ctx, v, default=None: _safe(int, _num, v, default),
    "toDouble": lambda ctx, v, default=None: _safe(float, _num, v, default),
    "toFloat": lambda ctx, v, default=None: _safe(float, _num, v, default),
    "toString": lambda ctx, v: str(v),
    "toBoolean": lambda ctx, v: str(v).strip().lower() in ("true", "1", "t", "yes"),
    "stringToInt": lambda ctx, v, default=None: _safe(int, _num, v, default),
    "stringToDouble": lambda ctx, v, default=None: _safe(float, _num, v, default),
    "withDefault": lambda ctx, v, default: default if v in (None, "") else v,
    "require": lambda ctx, v: _require(v),
    "lineNo": lambda ctx: ctx.line_no,
}


def _safe(outer, inner, v, default):
    try:
        return outer(inner(v))
    except (ValueError, TypeError):
        if default is None:
            raise
        return default


def _require(v):
    if v in (None, ""):
        raise ValueError("required field is empty")
    return v


def _parse_geom(v):
    from geomesa_tpu.core.wkt import parse_wkt

    return parse_wkt(str(v))


_CASTS = {
    "int": lambda v: int(_num(v)),
    "long": lambda v: int(_num(v)),
    "integer": lambda v: int(_num(v)),
    "double": lambda v: float(_num(v)),
    "float": lambda v: float(_num(v)),
    "string": str,
    "boolean": lambda v: str(v).strip().lower() in ("true", "1", "t", "yes"),
}


class _Parser:
    def __init__(self, tokens):
        self.tokens = tokens
        self.pos = 0

    def peek(self):
        return self.tokens[self.pos] if self.pos < len(self.tokens) else (None, None)

    def next(self):
        t = self.peek()
        self.pos += 1
        return t

    def expression(self):
        node = self.primary()
        while self.peek()[0] == "cast":
            self.next()
            kind, text = self.next()
            if kind != "name" or text.lower() not in _CASTS:
                raise ValueError(f"unknown cast type {text!r}")
            node = ("cast", text.lower(), node)
        return node

    def primary(self):
        kind, text = self.next()
        if kind == "dollar":
            key = text[1:]
            return ("ref", int(key)) if key.isdigit() else ("refname", key)
        if kind == "number":
            return ("lit", float(text) if "." in text else int(text))
        if kind == "string":
            return ("lit", text[1:-1].replace("''", "'"))
        if kind == "name":
            nkind, ntext = self.peek()
            if ntext == "(":
                self.next()
                args = []
                if self.peek()[1] != ")":
                    args.append(self.expression())
                    while self.peek()[1] == ",":
                        self.next()
                        args.append(self.expression())
                if self.next()[1] != ")":
                    raise ValueError("transform parse error: expected ')'")
                if text not in _FUNCTIONS:
                    raise ValueError(f"unknown transform function {text!r}")
                return ("call", text, args)
            return ("lit", text)  # bareword literal
        raise ValueError(f"transform parse error at {text!r}")


def compile_expression(expr: str) -> Callable[[EvalContext], Value]:
    """Compile a transform expression into ctx -> value."""
    tokens = _tokenize(expr)
    parser = _Parser(tokens)
    tree = parser.expression()
    if parser.pos != len(tokens):
        raise ValueError(f"trailing input in transform {expr!r}")

    def ev(node, ctx: EvalContext):
        tag = node[0]
        if tag == "lit":
            return node[1]
        if tag == "ref":
            i = node[1]
            return ctx.positional[i] if i < len(ctx.positional) else None
        if tag == "refname":
            return ctx.named.get(node[1])
        if tag == "cast":
            return _CASTS[node[1]](ev(node[2], ctx))
        if tag == "call":
            args = [ev(a, ctx) for a in node[2]]
            return _FUNCTIONS[node[1]](ctx, *args)
        raise AssertionError(node)

    return lambda ctx: ev(tree, ctx)
