"""Additional converter source formats: fixed-width, XML, shapefile, Avro.

Parity: geomesa-convert-fixedwidth / geomesa-convert-xml /
geomesa-convert-shp / geomesa-convert-avro [upstream, unverified].

- Fixed-width: fields declare (start, width) column slices; transforms see
  the slice as $0 and the whole line as $line.
- XML: one feature per element matched by `feature-path` (a simple
  tag/tag/tag path, no full XPath); fields use `path` relative to the
  feature element — child tag names, `@attr` attribute refs, and `tag/@attr`.
- Shapefile: a from-scratch reader of the public ESRI .shp/.dbf binary
  layout (point / polyline / polygon shapes); attributes come from the
  sibling .dbf (dBASE III) file.
- Avro: gated — the environment ships no Avro library; construction raises
  with a clear message (SURVEY.md: stub or gate missing deps).
"""

from __future__ import annotations

import dataclasses
import io
import os
import struct
import xml.etree.ElementTree as ET
from typing import Dict, Iterator, List, Optional

import numpy as np

from geomesa_tpu.convert.converter import _BaseConverter, _Field, _open
from geomesa_tpu.convert.transforms import EvalContext, compile_expression
from geomesa_tpu.core.columnar import FeatureBatch
from geomesa_tpu.core.sft import SimpleFeatureType
from geomesa_tpu.core.wkt import Geometry


class FixedWidthConverter(_BaseConverter):
    """Config fields add "start" and "width" (0-based character slices):

        {"type": "fixed-width",
         "fields": [{"name": "lat", "start": 0, "width": 6,
                     "transform": "$0::double"}, ...]}
    """

    def __init__(self, sft: SimpleFeatureType, config: dict):
        super().__init__(sft, config)
        self._slices = {}
        for f in config.get("fields", []):
            if "start" in f:
                self._slices[f["name"]] = (int(f["start"]), int(f["width"]))

    def _records(self, source):
        fh, close = _open(source)
        try:
            skip = int(self.config.get("options", {}).get("skip-lines", 0))
            for i, line in enumerate(fh):
                if i < skip:
                    continue
                line = line.rstrip("\n")
                if not line:
                    continue
                named = {}
                for name, (start, width) in self._slices.items():
                    named[name] = line[start : start + width].strip() or None
                yield EvalContext([line], named, line_no=i, raw=line)
        finally:
            if close:
                fh.close()

    def _field_value(self, ctx: EvalContext, f: _Field):
        v = ctx.named.get(f.name)
        if f.transform is not None:
            sub = EvalContext([v], ctx.named, ctx.line_no, ctx.raw)
            return f.transform(sub)
        return v


class XmlConverter(_BaseConverter):
    """Element-per-feature XML:

        {"type": "xml", "feature-path": "doc/row",
         "fields": [{"name": "name", "path": "props/name"},
                    {"name": "id", "path": "@id"}, ...]}
    """

    def __init__(self, sft: SimpleFeatureType, config: dict):
        super().__init__(sft, config)
        self.feature_path = config.get("feature-path", "")
        self._paths = {
            f["name"]: f["path"] for f in config.get("fields", []) if f.get("path")
        }

    def _records(self, source):
        fh, close = _open(source)
        try:
            root = ET.parse(fh).getroot()
        finally:
            if close:
                fh.close()
        parts = [p for p in self.feature_path.split("/") if p]
        # the root element itself may be the first path segment
        if parts and root.tag == parts[0]:
            parts = parts[1:]
        elements = root.iterfind("/".join(parts)) if parts else [root]
        for i, el in enumerate(elements):
            named = {
                name: _xml_extract(el, path) for name, path in self._paths.items()
            }
            yield EvalContext([el], named, line_no=i, raw=ET.tostring(el, "unicode"))

    def _field_value(self, ctx: EvalContext, f: _Field):
        v = ctx.named.get(f.name)
        if f.transform is not None:
            sub = EvalContext([v], ctx.named, ctx.line_no, ctx.raw)
            return f.transform(sub)
        return v


def _xml_extract(el: ET.Element, path: str) -> Optional[str]:
    if path.startswith("@"):
        return el.get(path[1:])
    if "/@" in path:
        sub, attr = path.rsplit("/@", 1)
        child = el.find(sub)
        return child.get(attr) if child is not None else None
    child = el.find(path)
    if child is None:
        return None
    return (child.text or "").strip() or None


# ---------------------------------------------------------------------------
# shapefile


_SHP_POINT = 1
_SHP_POLYLINE = 3
_SHP_POLYGON = 5


@dataclasses.dataclass
class ShapefileRecord:
    geometry: Geometry
    attributes: Dict[str, object]


def read_shapefile(path: str) -> Iterator[ShapefileRecord]:
    """Stream (geometry, attributes) from an ESRI shapefile pair
    (.shp + optional .dbf). Supports Point, PolyLine, Polygon."""
    base, _ = os.path.splitext(path)
    with open(base + ".shp", "rb") as f:
        shp = f.read()
    code, = struct.unpack(">i", shp[0:4])
    if code != 9994:
        raise ValueError(f"not a shapefile (magic {code})")
    dbf_rows = _read_dbf(base + ".dbf") if os.path.exists(base + ".dbf") else None
    off = 100
    i = 0
    while off + 8 <= len(shp):
        _, length_words = struct.unpack(">ii", shp[off : off + 8])
        content = shp[off + 8 : off + 8 + length_words * 2]
        off += 8 + length_words * 2
        if len(content) < 4:
            break
        (shape_type,) = struct.unpack("<i", content[0:4])
        geom = _parse_shape(shape_type, content)
        attrs = (
            dbf_rows[i]
            if dbf_rows is not None and i < len(dbf_rows) and dbf_rows[i] is not None
            else {}
        )
        if geom is not None:
            yield ShapefileRecord(geom, attrs)
        i += 1


def _parse_shape(shape_type: int, content: bytes) -> Optional[Geometry]:
    if shape_type == 0:  # null shape
        return None
    if shape_type == _SHP_POINT:
        x, y = struct.unpack_from("<dd", content, 4)
        return Geometry("Point", [np.array([[x, y]], np.float64)])
    if shape_type in (_SHP_POLYLINE, _SHP_POLYGON):
        num_parts, num_points = struct.unpack_from("<ii", content, 36)
        parts = list(struct.unpack_from(f"<{num_parts}i", content, 44))
        pts_off = 44 + 4 * num_parts
        flat = np.frombuffer(
            content, dtype="<f8", count=num_points * 2, offset=pts_off
        ).reshape(-1, 2)
        rings: List[np.ndarray] = []
        bounds = parts + [num_points]
        for p in range(num_parts):
            rings.append(np.array(flat[bounds[p] : bounds[p + 1]], np.float64))
        kind = "Polygon" if shape_type == _SHP_POLYGON else "LineString"
        if num_parts > 1 and shape_type == _SHP_POLYLINE:
            kind = "MultiLineString"
        return Geometry(kind, rings)
    raise NotImplementedError(f"shapefile shape type {shape_type}")


def _read_dbf(path: str) -> List[Dict[str, object]]:
    """dBASE III attribute table."""
    with open(path, "rb") as f:
        data = f.read()
    n_records, header_len, record_len = struct.unpack_from("<IHH", data, 4)
    fields = []
    off = 32
    while off < header_len - 1 and data[off] != 0x0D:
        raw_name = data[off : off + 11].split(b"\x00")[0].decode("ascii")
        ftype = chr(data[off + 11])
        flen = data[off + 16]
        fdec = data[off + 17]
        fields.append((raw_name, ftype, flen, fdec))
        off += 32
    rows = []
    off = header_len
    for _ in range(n_records):
        if off + record_len > len(data):
            break
        rec = data[off : off + record_len]
        off += record_len
        if rec[0:1] == b"*":  # deleted: keep a placeholder so .shp record
            rows.append(None)  # ordinals stay aligned with dbf ordinals
            continue
        row: Dict[str, object] = {}
        pos = 1
        for name, ftype, flen, fdec in fields:
            raw = rec[pos : pos + flen].decode("latin-1").strip()
            pos += flen
            if raw == "":
                row[name] = None
            elif ftype == "N":
                row[name] = float(raw) if fdec or "." in raw else int(raw)
            elif ftype == "F":
                row[name] = float(raw)
            elif ftype == "L":
                row[name] = raw.upper() in ("T", "Y")
            else:
                row[name] = raw
        rows.append(row)
    return rows


class ShapefileConverter:
    """Converter facade over read_shapefile: maps dbf columns (and the
    shape geometry) onto SFT attributes, with optional transforms taking
    the dbf value as $0."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        self.sft = sft
        self.config = config
        self.fields = {
            f["name"]: f for f in config.get("fields", [])
        }
        self.failed = 0

    def convert(self, path: str) -> FeatureBatch:
        data: Dict[str, list] = {a.name: [] for a in self.sft.attributes}
        fids: List[str] = []
        self.failed = 0
        geom_attr = self.sft.default_geometry
        for i, rec in enumerate(read_shapefile(path)):
            try:
                row: Dict[str, object] = {}
                for a in self.sft.attributes:
                    if geom_attr is not None and a.name == geom_attr.name:
                        row[a.name] = rec.geometry
                        continue
                    spec = self.fields.get(a.name, {})
                    src = spec.get("attribute", a.name)
                    v = rec.attributes.get(src)
                    if spec.get("transform"):
                        expr = compile_expression(spec["transform"])
                        v = expr(EvalContext([v], dict(rec.attributes), i, ""))
                    row[a.name] = v
                for a in self.sft.attributes:
                    if a.is_geometry and row.get(a.name) is None:
                        raise ValueError("no geometry")
                for a in self.sft.attributes:
                    data[a.name].append(row.get(a.name))
                fids.append(f"f{i}")
            except Exception:
                self.failed += 1
        return FeatureBatch.from_pydict(self.sft, data, fids=fids)


class AvroConverter:
    """Gated: no Avro library ships in this environment."""

    def __init__(self, sft: SimpleFeatureType, config: dict):
        raise ImportError(
            "Avro ingest requires an avro library (fastavro or avro-python3), "
            "which is not available in this environment; convert to "
            "JSON/Parquet first or install a provider"
        )


# ---------------------------------------------------------------------------
# shapefile writing (export parity: the CLI's SHP export format)


def write_shapefile(path: str, batch: FeatureBatch) -> None:
    """Write points (+ dbf attributes) — the minimal export counterpart."""
    geom = batch.geometry
    if geom is None or not geom.is_point:
        raise NotImplementedError("shapefile export supports point layers")
    base, _ = os.path.splitext(path)
    n = len(batch)
    # .shp
    rec_len_words = (8 + 20) // 2  # header + point content, in 16-bit words
    file_words = (100 + n * (8 + 20)) // 2
    with open(base + ".shp", "wb") as f:
        _shp_header(f, file_words, geom)
        for i in range(n):
            f.write(struct.pack(">ii", i + 1, 10))
            f.write(struct.pack("<idd", _SHP_POINT, float(geom.x[i]), float(geom.y[i])))
    # .shx
    with open(base + ".shx", "wb") as f:
        _shp_header(f, (100 + n * 8) // 2, geom)
        for i in range(n):
            f.write(struct.pack(">ii", (100 + i * 28) // 2, 10))
    # .dbf
    _write_dbf(base + ".dbf", batch)


def _shp_header(f, file_words: int, geom) -> None:
    f.write(struct.pack(">i", 9994))
    f.write(b"\x00" * 20)
    f.write(struct.pack(">i", file_words))
    f.write(struct.pack("<ii", 1000, _SHP_POINT))
    xmin, ymin = float(np.min(geom.x)), float(np.min(geom.y))
    xmax, ymax = float(np.max(geom.x)), float(np.max(geom.y))
    f.write(struct.pack("<dddd", xmin, ymin, xmax, ymax))
    f.write(struct.pack("<dddd", 0, 0, 0, 0))


def _write_dbf(path: str, batch: FeatureBatch) -> None:
    from geomesa_tpu.core.columnar import DictColumn

    cols = []
    for a in batch.sft.attributes:
        if a.is_geometry:
            continue
        col = batch.columns[a.name]
        if isinstance(col, DictColumn):
            vals = ["" if v is None else str(v) for v in col.decode()]
            width = max(1, min(254, max((len(v) for v in vals), default=1)))
            cols.append((a.name[:10], "C", width, 0, vals))
        else:
            arr = np.asarray(col)
            dec = 6 if arr.dtype.kind == "f" else 0
            if dec:
                # render first, size the field after: fixed-point when it
                # fits the 32-char N cap AND preserves the value; else
                # %.10g (≤17 chars, always fits; dbf readers parse either)
                def fmt(x: float) -> str:
                    s = f"{x:.6f}"
                    if len(s) > 32 or (
                        x != 0.0 and abs(float(s) - x) > 1e-9 * abs(x)
                    ):
                        s = f"{x:.10g}"
                    return s

                vals = [fmt(float(v)) for v in arr.tolist()]
            else:
                vals = [str(v) for v in arr.tolist()]
            width = max(1, min(32, max((len(v) for v in vals), default=1)))
            cols.append((a.name[:10], "N", width, dec, vals))
    n = len(batch)
    record_len = 1 + sum(w for _, _, w, _, _ in cols)
    header_len = 32 + 32 * len(cols) + 1
    with open(path, "wb") as f:
        f.write(struct.pack("<BBBBIHH", 3, 95, 7, 26, n, header_len, record_len))
        f.write(b"\x00" * 20)
        for name, ftype, width, dec, _ in cols:
            f.write(name.encode("ascii").ljust(11, b"\x00"))
            f.write(ftype.encode("ascii"))
            f.write(b"\x00" * 4)
            f.write(struct.pack("<BB", width, dec))
            f.write(b"\x00" * 14)
        f.write(b"\x0d")
        for i in range(n):
            f.write(b" ")
            for _, ftype, width, _, vals in cols:
                v = vals[i][:width]
                f.write(v.rjust(width).encode("latin-1") if ftype == "N"
                        else v.ljust(width).encode("latin-1"))
        f.write(b"\x1a")


class ParquetConverter(_BaseConverter):
    """Parquet input (upstream: geomesa-convert parquet input [L],
    SURVEY.md:431-432). Fields address source columns by `path` (column
    name) or positionally ($1..$N in schema order); transforms apply on
    top as usual. Reads row-group batches columnar-side and only then
    iterates rows, so the per-record Python work is dict assembly, not
    parquet decoding."""

    def _records(self, source):
        import pyarrow.parquet as papq

        pf = papq.ParquetFile(source)
        names = pf.schema_arrow.names
        line = 0
        for rb in pf.iter_batches():
            cols = [c.to_pylist() for c in rb.columns]
            for i in range(rb.num_rows):
                line += 1
                row = {n: cols[j][i] for j, n in enumerate(names)}
                yield EvalContext(
                    positional=[row] + [cols[j][i] for j in range(len(names))],
                    named=dict(row),
                    line_no=line,
                )

    def _field_value(self, ctx, f):
        return _columnar_field_value(self, ctx, f)


class JdbcConverter(_BaseConverter):
    """JDBC-style input over a SQL database (upstream: geomesa-convert
    JDBC [L]). The config carries the query; the SOURCE is a DB-API
    connection or a SQLite path (the zero-dependency stand-in for the
    reference's JDBC URL). Columns address by name (`path`) or position.

        {"type": "jdbc", "query": "SELECT id, lon, lat FROM obs", ...}
    """

    def _records(self, source):
        import sqlite3

        close = False
        if isinstance(source, (str, bytes)):
            conn = sqlite3.connect(source)
            close = True
        else:
            conn = source
        try:
            cur = conn.cursor()
            cur.execute(self.config["query"])
            names = [d[0] for d in cur.description]
            for line, rec in enumerate(cur, 1):
                row = dict(zip(names, rec))
                yield EvalContext(
                    positional=[row] + list(rec),
                    named=row,
                    line_no=line,
                )
        finally:
            if close:
                conn.close()

    def _field_value(self, ctx, f):
        return _columnar_field_value(self, ctx, f)


def _columnar_field_value(conv: _BaseConverter, ctx: EvalContext, f: _Field):
    """Shared by the columnar-source converters (parquet/jdbc): `path`
    addresses a source column by name (nested struct/list segments use the
    same _extract rules as the JSON converter); transforms see $0 = that
    value."""
    from geomesa_tpu.convert.converter import _extract

    if f.path is not None:
        v = _extract(ctx.named, f.path)
        if f.transform is not None:
            return f.transform(EvalContext([v], dict(ctx.named), ctx.line_no))
        return v
    return _BaseConverter._field_value(conv, ctx, f)
