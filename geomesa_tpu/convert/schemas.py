"""Predefined well-known schemas and converter configs.

Parity: the GDELT / AIS / NYC-taxi converter definitions shipped in
geomesa-tools resources [upstream, unverified] — the benchmark datasets'
attribute schemas (BASELINE configs 1-5), reduced to the benchmark-relevant
columns. Column positions follow the public file formats:

- GDELT 1.0 events TSV (57 cols): GlobalEventID, day, actor/event codes,
  GoldsteinScale, NumMentions, ActionGeo lat/lon.
- AIS NMEA-decoded CSV (MarineCadastre layout): MMSI, BaseDateTime, LAT,
  LON, SOG, COG, Heading, VesselName.
- NYC TLC yellow-taxi CSV: pickup datetime + pickup lon/lat.
"""

from __future__ import annotations

from geomesa_tpu.core.sft import SimpleFeatureType

GDELT_SPEC = (
    "GlobalEventID:String,EventCode:String,Actor1Name:String,Actor2Name:String,"
    "GoldsteinScale:Double,NumMentions:Integer,dtg:Date,*geom:Point:srid=4326"
)

GDELT_SFT = SimpleFeatureType.from_spec("gdelt", GDELT_SPEC)

# GDELT 1.0: $1=GlobalEventID $2=Day(yyyyMMdd) $7=Actor1Name $17=Actor2Name
# $27=EventCode $31=GoldsteinScale $32=NumMentions
# $54=ActionGeo_Lat $55=ActionGeo_Long  (1-based positions into the TSV;
# $40/$41 are Actor1Geo_Lat/Long — the event's *actor* location, not the
# action location the schema promises)
GDELT_CONVERTER = {
    "type": "delimited-text",
    "format": "TSV",
    "id-field": "$1",
    "fields": [
        {"name": "GlobalEventID", "transform": "$1::string"},
        {"name": "EventCode", "transform": "$27::string"},
        {"name": "Actor1Name", "transform": "withDefault($7, 'UNKNOWN')"},
        {"name": "Actor2Name", "transform": "withDefault($17, 'UNKNOWN')"},
        {"name": "GoldsteinScale", "transform": "toDouble($31, 0.0)"},
        {"name": "NumMentions", "transform": "toInt($32, 0)"},
        {"name": "dtg", "transform": "dateParse('yyyyMMdd', $2)"},
        {"name": "geom", "transform": "point($55, $54)"},
    ],
}

AIS_SPEC = (
    "MMSI:String,VesselName:String,SOG:Double,COG:Double,Heading:Double,"
    "dtg:Date,*geom:Point:srid=4326"
)

AIS_SFT = SimpleFeatureType.from_spec("ais", AIS_SPEC)

# MarineCadastre: $1=MMSI $2=BaseDateTime(ISO) $3=LAT $4=LON $5=SOG $6=COG
# $7=Heading $8=VesselName
AIS_CONVERTER = {
    "type": "delimited-text",
    "format": "CSV",
    "options": {"skip-lines": 1},
    "id-field": "concat($1, '-', $2)",
    "fields": [
        {"name": "MMSI", "transform": "$1::string"},
        {"name": "VesselName", "transform": "withDefault($8, '')"},
        {"name": "SOG", "transform": "toDouble($5, 0.0)"},
        {"name": "COG", "transform": "toDouble($6, 0.0)"},
        {"name": "Heading", "transform": "toDouble($7, 0.0)"},
        {"name": "dtg", "transform": "isoDateTime($2)"},
        {"name": "geom", "transform": "point($4, $3)"},
    ],
}

NYC_TAXI_SPEC = (
    "vendor:String,passengers:Integer,distance:Double,fare:Double,"
    "dtg:Date,*geom:Point:srid=4326"
)

NYC_TAXI_SFT = SimpleFeatureType.from_spec("nyctaxi", NYC_TAXI_SPEC)

# Classic yellow-taxi layout: $1=vendor $2=pickup_datetime $4=passenger_count
# $5=trip_distance $6=pickup_longitude $7=pickup_latitude $13=fare_amount
NYC_TAXI_CONVERTER = {
    "type": "delimited-text",
    "format": "CSV",
    "options": {"skip-lines": 1},
    "id-field": "uuid()",
    "fields": [
        {"name": "vendor", "transform": "$1::string"},
        {"name": "passengers", "transform": "toInt($4, 1)"},
        {"name": "distance", "transform": "toDouble($5, 0.0)"},
        {"name": "fare", "transform": "toDouble($13, 0.0)"},
        {"name": "dtg", "transform": "dateParse('yyyy-MM-dd HH:mm:ss', $2)"},
        {"name": "geom", "transform": "point($6, $7)"},
    ],
}

OSM_SPEC = (
    "osm_id:String,user:String,version:Integer,tags:String,"
    "dtg:Date,*geom:Point:srid=4326"
)

OSM_SFT = SimpleFeatureType.from_spec("osm", OSM_SPEC)

# OSM nodes flattened to CSV (osmconvert --csv layout):
# $1=id $2=lon $3=lat $4=user $5=version $6=timestamp(ISO) $7=tags
OSM_CONVERTER = {
    "type": "delimited-text",
    "format": "CSV",
    "id-field": "$1",
    "fields": [
        {"name": "osm_id", "transform": "$1::string"},
        {"name": "user", "transform": "withDefault($4, '')"},
        {"name": "version", "transform": "toInt($5, 1)"},
        {"name": "tags", "transform": "withDefault($7, '')"},
        {"name": "dtg", "transform": "isoDateTime($6)"},
        {"name": "geom", "transform": "point($2, $3)"},
    ],
}

TWITTER_SPEC = (
    "tweet_id:String,user_name:String,text:String,"
    "dtg:Date,*geom:Point:srid=4326"
)

TWITTER_SFT = SimpleFeatureType.from_spec("twitter", TWITTER_SPEC)

# Twitter API v1.1 statuses (one JSON object per line); geo-tagged tweets
# carry GeoJSON [lon, lat] in coordinates.coordinates
TWITTER_CONVERTER = {
    "type": "json",
    "id-field": "$tweet_id",
    "fields": [
        {"name": "tweet_id", "path": "$.id_str"},
        {"name": "user_name", "path": "$.user.screen_name",
         "transform": "withDefault($0, '')"},
        {"name": "text", "path": "$.text",
         "transform": "withDefault($0, '')"},
        {"name": "dtg", "path": "$.created_at",
         "transform": "dateParse('EEE MMM dd HH:mm:ss Z yyyy', $0)"},
        {"name": "lon", "path": "$.coordinates.coordinates.0"},
        {"name": "lat", "path": "$.coordinates.coordinates.1"},
        {"name": "geom", "transform": "point($lon, $lat)"},
    ],
}

WELL_KNOWN = {
    "gdelt": (GDELT_SFT, GDELT_CONVERTER),
    "ais": (AIS_SFT, AIS_CONVERTER),
    "nyctaxi": (NYC_TAXI_SFT, NYC_TAXI_CONVERTER),
    "osm": (OSM_SFT, OSM_CONVERTER),
    "twitter": (TWITTER_SFT, TWITTER_CONVERTER),
}
