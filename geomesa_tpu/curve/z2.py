"""Z2 space-filling curve: (lon, lat) -> 62-bit Morton key.

Parity: org.locationtech.geomesa.curve.Z2SFC (geomesa-z3) [upstream,
unverified]: 31 bits per dimension, lon/lat normalized over the full WGS84
envelope. Used for the point index without time and for Z2 partition schemes.
"""

from __future__ import annotations

from typing import List, Sequence, Tuple

import numpy as np

from geomesa_tpu.curve.normalized import NormalizedLat, NormalizedLon
from geomesa_tpu.curve.zorder import MAX_BITS_2D, deinterleave2, interleave2
from geomesa_tpu.curve.zranges import IndexRange, zranges


class Z2SFC:
    def __init__(self, bits: int = MAX_BITS_2D):
        assert 1 <= bits <= MAX_BITS_2D
        self.bits = bits
        self.lon = NormalizedLon(bits)
        self.lat = NormalizedLat(bits)

    def index(self, lon, lat) -> np.ndarray:
        """Vectorized (lon, lat) -> z value (int64)."""
        return interleave2(self.lon.normalize(lon), self.lat.normalize(lat))

    def invert(self, z) -> Tuple[np.ndarray, np.ndarray]:
        """z -> (lon, lat) cell centers."""
        x, y = deinterleave2(z)
        return self.lon.denormalize(x), self.lat.denormalize(y)

    def ranges(
        self,
        xmin: float,
        ymin: float,
        xmax: float,
        ymax: float,
        max_ranges: int = 2000,
    ) -> List[IndexRange]:
        """Covering z-ranges for a lon/lat box."""
        return zranges(
            (int(self.lon.normalize(xmin)), int(self.lat.normalize(ymin))),
            (int(self.lon.normalize(xmax)), int(self.lat.normalize(ymax))),
            self.bits,
            max_ranges=max_ranges,
        )
