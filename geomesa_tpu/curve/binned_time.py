"""Time binning: epoch time -> (period bin, offset within period).

Parity: org.locationtech.geomesa.curve.BinnedTime / TimePeriod (geomesa-z3)
[upstream, unverified]. The Z3/XZ3 indices bin time into fixed periods
(day/week/month/year; week is the Z3 default) so that the time dimension of
the curve stays bounded; a query interval maps to one (bin, offset-range) per
touched period.

Divergence from upstream noted explicitly: offsets here are uniformly
*seconds* as float64 for all periods (upstream mixes millis/seconds/minutes by
period); bins are int32 counts since the 1970-01-01 epoch. Month bins are
calendar months (year*12+month); month/year offsets are seconds from the start
of the calendar period, normalized against the period's maximum length.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Tuple

import numpy as np

_DAY_S = 86400.0
_WEEK_S = 7 * 86400.0
# Max period lengths (for dimension normalization): longest month = 31 days,
# longest (leap) year = 366 days.
_MONTH_MAX_S = 31 * 86400.0
_YEAR_MAX_S = 366 * 86400.0
_EPOCH_DOW_OFFSET_DAYS = 4  # 1970-01-01 was a Thursday; ISO weeks start Monday


class TimePeriod(enum.Enum):
    DAY = "day"
    WEEK = "week"
    MONTH = "month"
    YEAR = "year"

    @classmethod
    def parse(cls, s: "str | TimePeriod") -> "TimePeriod":
        if isinstance(s, TimePeriod):
            return s
        return cls(s.lower())


@dataclasses.dataclass(frozen=True)
class BinnedTime:
    bin: int
    offset_seconds: float


def max_offset_seconds(period: TimePeriod) -> float:
    return {
        TimePeriod.DAY: _DAY_S,
        TimePeriod.WEEK: _WEEK_S,
        TimePeriod.MONTH: _MONTH_MAX_S,
        TimePeriod.YEAR: _YEAR_MAX_S,
    }[period]


def to_binned_time(epoch_millis, period: TimePeriod) -> Tuple[np.ndarray, np.ndarray]:
    """Vectorized: epoch millis -> (bin int32 array, offset-seconds f64 array)."""
    ms = np.asarray(epoch_millis, dtype=np.int64)
    secs = ms.astype(np.float64) / 1000.0
    if period is TimePeriod.DAY:
        bins = np.floor_divide(ms, np.int64(86400_000))
        offs = secs - bins.astype(np.float64) * _DAY_S
    elif period is TimePeriod.WEEK:
        days = np.floor_divide(ms, np.int64(86400_000)) + _EPOCH_DOW_OFFSET_DAYS
        bins = np.floor_divide(days, 7)
        week_start_ms = (bins * 7 - _EPOCH_DOW_OFFSET_DAYS) * np.int64(86400_000)
        offs = (ms - week_start_ms).astype(np.float64) / 1000.0
    else:
        dt = ms.astype("datetime64[ms]")
        months = dt.astype("datetime64[M]")
        years = dt.astype("datetime64[Y]")
        if period is TimePeriod.MONTH:
            bins = months.astype(np.int64)  # months since 1970-01
            offs = (ms - months.astype("datetime64[ms]").astype(np.int64)).astype(
                np.float64
            ) / 1000.0
        else:
            bins = years.astype(np.int64)  # years since 1970
            offs = (ms - years.astype("datetime64[ms]").astype(np.int64)).astype(
                np.float64
            ) / 1000.0
    return bins.astype(np.int32), offs


def bin_to_epoch_millis(bin_index: int, period: TimePeriod) -> int:
    """Start of a period bin, as epoch millis."""
    if period is TimePeriod.DAY:
        return int(bin_index) * 86400_000
    if period is TimePeriod.WEEK:
        return (int(bin_index) * 7 - _EPOCH_DOW_OFFSET_DAYS) * 86400_000
    if period is TimePeriod.MONTH:
        return int(np.datetime64(int(bin_index), "M").astype("datetime64[ms]").astype(np.int64))
    return int(np.datetime64(int(bin_index), "Y").astype("datetime64[ms]").astype(np.int64))


def bins_for_interval(start_millis: int, end_millis: int, period: TimePeriod):
    """All (bin, offset_lo_s, offset_hi_s) triples covering [start, end]."""
    out = []
    b0, o0 = to_binned_time(np.int64(start_millis), period)
    b1, o1 = to_binned_time(np.int64(end_millis), period)
    b0, b1 = int(b0), int(b1)
    for b in range(b0, b1 + 1):
        lo = float(o0) if b == b0 else 0.0
        hi = float(o1) if b == b1 else max_offset_seconds(period)
        out.append((b, lo, hi))
    return out
