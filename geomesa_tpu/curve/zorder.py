"""Vectorized Morton (Z-order) bit interleaving for 2 and 3 dimensions.

Parity: the bit-manipulation core of org.locationtech.sfcurve (Z2 / Z3 classes)
[upstream, unverified], re-derived from the standard magic-number spreading
technique. All functions are NumPy-vectorized over uint64 arrays.

Z2 interleaves two 31-bit values into a 62-bit key (xyxy... with x in the
even/least-significant position). Z3 interleaves three 21-bit values into a
63-bit key.
"""

from __future__ import annotations

import numpy as np

MAX_BITS_2D = 31
MAX_BITS_3D = 21

_U = np.uint64  # noqa: N816 — terse alias used heavily below


def _split2(x: np.ndarray) -> np.ndarray:
    """Spread the low 32 bits of x so bit i lands at position 2*i."""
    x = x.astype(np.uint64) & _U(0x00000000FFFFFFFF)
    x = (x | (x << _U(16))) & _U(0x0000FFFF0000FFFF)
    x = (x | (x << _U(8))) & _U(0x00FF00FF00FF00FF)
    x = (x | (x << _U(4))) & _U(0x0F0F0F0F0F0F0F0F)
    x = (x | (x << _U(2))) & _U(0x3333333333333333)
    x = (x | (x << _U(1))) & _U(0x5555555555555555)
    return x


def _combine2(x: np.ndarray) -> np.ndarray:
    """Inverse of _split2: gather every 2nd bit down to the low 32 bits."""
    x = x.astype(np.uint64) & _U(0x5555555555555555)
    x = (x | (x >> _U(1))) & _U(0x3333333333333333)
    x = (x | (x >> _U(2))) & _U(0x0F0F0F0F0F0F0F0F)
    x = (x | (x >> _U(4))) & _U(0x00FF00FF00FF00FF)
    x = (x | (x >> _U(8))) & _U(0x0000FFFF0000FFFF)
    x = (x | (x >> _U(16))) & _U(0x00000000FFFFFFFF)
    return x


def _split3(x: np.ndarray) -> np.ndarray:
    """Spread the low 21 bits of x so bit i lands at position 3*i."""
    x = x.astype(np.uint64) & _U(0x1FFFFF)
    x = (x | (x << _U(32))) & _U(0x001F00000000FFFF)
    x = (x | (x << _U(16))) & _U(0x001F0000FF0000FF)
    x = (x | (x << _U(8))) & _U(0x100F00F00F00F00F)
    x = (x | (x << _U(4))) & _U(0x10C30C30C30C30C3)
    x = (x | (x << _U(2))) & _U(0x1249249249249249)
    return x


def _combine3(x: np.ndarray) -> np.ndarray:
    """Inverse of _split3."""
    x = x.astype(np.uint64) & _U(0x1249249249249249)
    x = (x | (x >> _U(2))) & _U(0x10C30C30C30C30C3)
    x = (x | (x >> _U(4))) & _U(0x100F00F00F00F00F)
    x = (x | (x >> _U(8))) & _U(0x001F0000FF0000FF)
    x = (x | (x >> _U(16))) & _U(0x001F00000000FFFF)
    x = (x | (x >> _U(32))) & _U(0x00000000001FFFFF)
    return x


def interleave2(x, y) -> np.ndarray:
    """Morton-interleave two <=31-bit integer arrays; x gets the even bits."""
    x = np.asarray(x).astype(np.uint64)
    y = np.asarray(y).astype(np.uint64)
    return (_split2(x) | (_split2(y) << _U(1))).astype(np.int64)


def deinterleave2(z):
    z = np.asarray(z).astype(np.uint64)
    return (
        _combine2(z).astype(np.int64),
        _combine2(z >> _U(1)).astype(np.int64),
    )


def interleave3(x, y, t) -> np.ndarray:
    """Morton-interleave three <=21-bit integer arrays; x gets bits 0,3,6..."""
    x = np.asarray(x).astype(np.uint64)
    y = np.asarray(y).astype(np.uint64)
    t = np.asarray(t).astype(np.uint64)
    return (_split3(x) | (_split3(y) << _U(1)) | (_split3(t) << _U(2))).astype(np.int64)


def deinterleave3(z):
    z = np.asarray(z).astype(np.uint64)
    return (
        _combine3(z).astype(np.int64),
        _combine3(z >> _U(1)).astype(np.int64),
        _combine3(z >> _U(2)).astype(np.int64),
    )
