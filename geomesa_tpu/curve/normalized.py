"""Dimension normalization: continuous coordinates <-> integer grid cells.

Parity: org.locationtech.geomesa.curve.NormalizedDimension (geomesa-z3)
[upstream, unverified]. A dimension with `bits` precision maps [min, max] onto
[0, 2**bits - 1]; denormalization returns the *center* of the cell, matching
upstream semantics (SemiNormalizedDimension uses cell centers so that
round-tripping stays within half a cell width).
"""

from __future__ import annotations

import dataclasses

import numpy as np


@dataclasses.dataclass(frozen=True)
class NormalizedDimension:
    min: float
    max: float
    bits: int

    @property
    def precision(self) -> int:
        return 1 << self.bits

    @property
    def max_index(self) -> int:
        return self.precision - 1

    @property
    def extent(self) -> float:
        return self.max - self.min

    def normalize(self, value):
        """Map coordinate(s) to integer cell index, clipped to the valid range.

        Accepts scalars or arrays; returns int64.
        """
        v = np.asarray(value, dtype=np.float64)
        scaled = np.floor((v - self.min) / self.extent * self.precision)
        return np.clip(scaled, 0, self.max_index).astype(np.int64)

    def denormalize(self, index):
        """Map integer cell index(es) back to the cell-center coordinate."""
        i = np.asarray(index, dtype=np.float64)
        return self.min + (i + 0.5) * (self.extent / self.precision)


def NormalizedLon(bits: int) -> NormalizedDimension:
    return NormalizedDimension(-180.0, 180.0, bits)


def NormalizedLat(bits: int) -> NormalizedDimension:
    return NormalizedDimension(-90.0, 90.0, bits)


def NormalizedTime(max_seconds: float, bits: int) -> NormalizedDimension:
    return NormalizedDimension(0.0, float(max_seconds), bits)
