"""Z3 space-filling curve: (lon, lat, time-offset) -> 63-bit Morton key.

Parity: org.locationtech.geomesa.curve.Z3SFC (geomesa-z3) [upstream,
unverified]: 21 bits per dimension; the time dimension is the offset within a
BinnedTime period (week by default), normalized over the period's maximum
length. A full Z3 index key in the reference is
[shard][2-byte epoch bin][8-byte z3][feature id]; here the (bin, z3) pair is
the logical key and shard/id belong to the storage layer.
"""

from __future__ import annotations

from typing import Dict, List, Tuple

import numpy as np

from geomesa_tpu.curve.binned_time import (
    TimePeriod,
    bins_for_interval,
    max_offset_seconds,
    to_binned_time,
)
from geomesa_tpu.curve.normalized import (
    NormalizedLat,
    NormalizedLon,
    NormalizedTime,
)
from geomesa_tpu.curve.zorder import MAX_BITS_3D, deinterleave3, interleave3
from geomesa_tpu.curve.zranges import IndexRange, zranges


class Z3SFC:
    def __init__(self, period: "str | TimePeriod" = TimePeriod.WEEK, bits: int = MAX_BITS_3D):
        assert 1 <= bits <= MAX_BITS_3D
        self.bits = bits
        self.period = TimePeriod.parse(period)
        self.lon = NormalizedLon(bits)
        self.lat = NormalizedLat(bits)
        self.time = NormalizedTime(max_offset_seconds(self.period), bits)

    def index(self, lon, lat, epoch_millis) -> Tuple[np.ndarray, np.ndarray]:
        """Vectorized (lon, lat, dtg-millis) -> (bin int32, z3 int64)."""
        bins, offs = to_binned_time(epoch_millis, self.period)
        z = interleave3(
            self.lon.normalize(lon),
            self.lat.normalize(lat),
            self.time.normalize(offs),
        )
        return bins, z

    def invert(self, z) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
        """z3 -> (lon, lat, offset-seconds) cell centers."""
        x, y, t = deinterleave3(z)
        return self.lon.denormalize(x), self.lat.denormalize(y), self.time.denormalize(t)

    def ranges(
        self,
        xmin: float,
        ymin: float,
        xmax: float,
        ymax: float,
        t_start_millis: int,
        t_end_millis: int,
        max_ranges: int = 2000,
    ) -> Dict[int, List[IndexRange]]:
        """Covering z3-ranges per epoch bin for a lon/lat/time box."""
        nx = (int(self.lon.normalize(xmin)), int(self.lon.normalize(xmax)))
        ny = (int(self.lat.normalize(ymin)), int(self.lat.normalize(ymax)))
        out: Dict[int, List[IndexRange]] = {}
        bins = bins_for_interval(t_start_millis, t_end_millis, self.period)
        budget = max(1, max_ranges // max(1, len(bins)))
        for b, lo, hi in bins:
            nt = (int(self.time.normalize(lo)), int(self.time.normalize(hi)))
            out[b] = zranges(
                (nx[0], ny[0], nt[0]),
                (nx[1], ny[1], nt[1]),
                self.bits,
                max_ranges=budget,
            )
        return out
