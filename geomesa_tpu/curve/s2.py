"""S2-style cube-face space-filling curve.

Parity role: the reference's S2 index variant (geomesa-index-api s2/s3
keyspaces backed by the sidx S2 library — SURVEY.md:241-242 [L], deferred
in rounds 1-2, built here). Design follows Google S2's projection chain:

  lon/lat -> unit vector -> cube FACE (max-|axis|) -> face (u, v) by
  central projection -> quadratic (s, t) reprojection (S2's area-
  equalizing transform: cell areas vary ~2.1x instead of the raw cube
  projection's ~5.2x) -> discrete (si, ti) at `level`.

Intra-face ordering is Morton/Z (NOT S2's Hilbert): the locality
properties the planner needs (contiguous ranges cover contiguous regions)
hold for either order, the repo already has exact Z BIGMIN-style range
machinery, and Hilbert buys ~10-20% fewer ranges at equal budget — noted
trade. Cell ids are therefore NOT interoperable with Google S2 ids; this
is an S2-STYLE keyspace, not an S2 binding (none is possible: zero-dep
environment).

Why a cube-face curve at all (vs Z2): no polar singularity — Z2 cells
degenerate in area toward the poles (lon compression), while cube faces
bound the distortion, so high-latitude workloads (AIS!) get uniform
per-cell selectivity and ~constant-size covering ranges.

Covering construction: BFS quadtree refinement over (face, s, t) cells.
Each cell's lon/lat bounds come from its corners with conservative
handling of the two non-monotone cases (pole-containing cells on the top/
bottom faces; antimeridian-spanning cells) plus a curvature pad — the
covering tests assert the union of ranges contains every in-box point's
cell id over randomized boxes (the same guarantee contract as zranges).
"""

from __future__ import annotations

from typing import List, Tuple

import numpy as np

from geomesa_tpu.curve.zranges import IndexRange, _merge
from geomesa_tpu.curve.zorder import deinterleave2, interleave2

MAX_LEVEL = 30


def _uv_to_st(u):
    """S2 quadratic projection, vectorized. Both np.where branches are
    evaluated for every lane, so each sqrt argument is clamped at 0 —
    the unclamped form emitted RuntimeWarning NaNs on the unselected
    branch (u outside [-1/3, 1/3] in exactly one of them)."""
    u = np.asarray(u, np.float64)
    return np.where(
        u >= 0, 0.5 * np.sqrt(np.maximum(1.0 + 3.0 * u, 0.0)),
        1.0 - 0.5 * np.sqrt(np.maximum(1.0 - 3.0 * u, 0.0)),
    )


def _st_to_uv(s):
    s = np.asarray(s, np.float64)
    return np.where(
        s >= 0.5, (1.0 / 3.0) * (4.0 * s * s - 1.0),
        (1.0 / 3.0) * (1.0 - 4.0 * (1.0 - s) * (1.0 - s)),
    )


# face frames: normal N, tangents E1/E2 (u = p.E1/p.N, v = p.E2/p.N).
# Any orthogonal frame per face works — index/invert just must agree;
# these differ from Google S2's frames (ids are not interoperable anyway).
_N = np.array([[1, 0, 0], [0, 1, 0], [0, 0, 1],
               [-1, 0, 0], [0, -1, 0], [0, 0, -1]], np.float64)
_E1 = np.array([[0, 1, 0], [-1, 0, 0], [-1, 0, 0],
                [0, -1, 0], [1, 0, 0], [1, 0, 0]], np.float64)
_E2 = np.array([[0, 0, 1], [0, 0, 1], [0, -1, 0],
                [0, 0, 1], [0, 0, 1], [0, 1, 0]], np.float64)


def lonlat_to_face_st(lon, lat):
    """Vectorized (lon, lat) degrees -> (face [0..5], s, t)."""
    rlon = np.radians(np.asarray(lon, np.float64))
    rlat = np.radians(np.asarray(lat, np.float64))
    p = np.stack([np.cos(rlat) * np.cos(rlon),
                  np.cos(rlat) * np.sin(rlon),
                  np.sin(rlat)], -1)  # [..., 3]
    dots = p @ _N.T  # [..., 6]
    face = np.argmax(dots, axis=-1).astype(np.int64)
    denom = np.take_along_axis(dots, face[..., None], axis=-1)[..., 0]
    u = np.einsum("...k,...k->...", p, _E1[face]) / denom
    v = np.einsum("...k,...k->...", p, _E2[face]) / denom
    return face, _uv_to_st(u), _uv_to_st(v)


def face_st_to_lonlat(face, s, t):
    """Vectorized (face, s, t) -> (lon, lat) degrees."""
    face = np.asarray(face, np.int64)
    u = _st_to_uv(np.asarray(s, np.float64))
    v = _st_to_uv(np.asarray(t, np.float64))
    p = _N[face] + u[..., None] * _E1[face] + v[..., None] * _E2[face]
    lon = np.degrees(np.arctan2(p[..., 1], p[..., 0]))
    lat = np.degrees(np.arctan2(p[..., 2], np.hypot(p[..., 0], p[..., 1])))
    return lon, lat


class S2SFC:
    """Cube-face curve at a fixed level: cellid = face * 4^level + Z(si, ti)."""

    def __init__(self, level: int = 15):
        assert 1 <= level <= MAX_LEVEL
        self.level = level
        self.dim = 1 << level  # cells per face edge

    def index(self, lon, lat) -> np.ndarray:
        face, s, t = lonlat_to_face_st(lon, lat)
        si = np.clip((s * self.dim).astype(np.int64), 0, self.dim - 1)
        ti = np.clip((t * self.dim).astype(np.int64), 0, self.dim - 1)
        z = interleave2(si.astype(np.uint64), ti.astype(np.uint64))
        return face * (1 << (2 * self.level)) + np.asarray(z, np.int64)

    def invert(self, cellid) -> Tuple[np.ndarray, np.ndarray]:
        cellid = np.asarray(cellid, np.int64)
        per_face = 1 << (2 * self.level)
        face = cellid // per_face
        si, ti = deinterleave2(np.asarray(cellid % per_face, np.uint64))
        s = (np.asarray(si, np.float64) + 0.5) / self.dim
        t = (np.asarray(ti, np.float64) + 0.5) / self.dim
        return face_st_to_lonlat(face, s, t)

    # -- covering ------------------------------------------------------------

    def _cell_lonlat_bounds(self, face, s0, t0, s1, t1):
        """Conservative lon/lat bbox of one (face, st-rect) cell."""
        corners_s = np.array([s0, s1, s0, s1, (s0 + s1) / 2])
        corners_t = np.array([t0, t0, t1, t1, (t0 + t1) / 2])
        lon, lat = face_st_to_lonlat(
            np.full(5, face), corners_s, corners_t
        )
        lat_lo, lat_hi = float(lat.min()), float(lat.max())
        lon_lo, lon_hi = float(lon.min()), float(lon.max())
        # pole-containing cells: lat extreme is interior, lon spans all
        if face in (2, 5) and s0 <= 0.5 <= s1 and t0 <= 0.5 <= t1:
            if face == 2:
                lat_hi = 90.0
            else:
                lat_lo = -90.0
            lon_lo, lon_hi = -180.0, 180.0
        # antimeridian-spanning cells: corner-lon spread is meaningless
        if lon_hi - lon_lo > 180.0:
            lon_lo, lon_hi = -180.0, 180.0
        # curvature pad: cell edges bow relative to the corner hull
        pad = 0.55 * max(s1 - s0, t1 - t0) * 90.0 * 0.5 + 1e-9
        return (lon_lo - pad, max(lat_lo - pad, -90.0),
                lon_hi + pad, min(lat_hi + pad, 90.0))

    def ranges(
        self, xmin: float, ymin: float, xmax: float, ymax: float,
        max_ranges: int = 512,
    ) -> List[IndexRange]:
        """Covering cellid ranges for a lon/lat box (BFS refinement)."""

        def intersects(b):
            lo_x, lo_y, hi_x, hi_y = b
            return not (hi_x < xmin or lo_x > xmax
                        or hi_y < ymin or lo_y > ymax)

        def contained(b):
            lo_x, lo_y, hi_x, hi_y = b
            return (lo_x >= xmin and hi_x <= xmax
                    and lo_y >= ymin and hi_y <= ymax)

        out: List[IndexRange] = []
        frontier = [(f, 0, 0.0, 0.0, 1.0, 1.0) for f in range(6)]
        L = self.level
        per_face = 1 << (2 * L)

        def emit(face, lvl, s0, t0, is_contained):
            si = int(s0 * self.dim)
            ti = int(t0 * self.dim)
            z = int(interleave2(
                np.asarray([si], np.uint64), np.asarray([ti], np.uint64)
            )[0])
            span = 1 << (2 * (L - lvl))
            # align the prefix: the cell's id block starts at the z of its
            # lowest corner rounded down to the block
            lo = face * per_face + (z // span) * span
            out.append(IndexRange(lo, lo + span - 1, is_contained))

        while frontier:
            face, lvl, s0, t0, s1, t1 = frontier.pop(0)
            b = self._cell_lonlat_bounds(face, s0, t0, s1, t1)
            if not intersects(b):
                continue
            if contained(b):
                emit(face, lvl, s0, t0, True)
                continue
            if lvl >= L or len(out) + len(frontier) >= max_ranges:
                emit(face, lvl, s0, t0, False)
                continue
            sm = (s0 + s1) / 2
            tm = (t0 + t1) / 2
            frontier.extend([
                (face, lvl + 1, s0, t0, sm, tm),
                (face, lvl + 1, sm, t0, s1, tm),
                (face, lvl + 1, s0, tm, sm, t1),
                (face, lvl + 1, sm, tm, s1, t1),
            ])
        return _merge(out)
