"""XZ-ordering: index non-point geometries by enlarged quadtree/octree cells.

Parity: org.locationtech.geomesa.curve.XZ2SFC / XZ3SFC (geomesa-z3) [upstream,
unverified], implementing the XZ-ordering scheme (Boehm, Klump, Kriegel:
"XZ-Ordering: A Space-Filling Curve for Objects with Spatial Extension"): a
geometry's bounding box is assigned to the smallest quadtree cell whose
*enlarged* region (the cell doubled in each dimension, anchored at the cell's
lower corner) contains the box. Each cell has a contiguous "sequence code" so
that a cell and all of its descendants form one contiguous key range —
queries enumerate cells whose enlarged region intersects the query window.
Matches are a superset: residual filtering downstream is mandatory (same
contract as the reference's XZ indices).

XZ3 adds a time dimension with BinnedTime periods, producing per-bin codes.
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple

import numpy as np

from geomesa_tpu.curve.binned_time import (
    TimePeriod,
    bins_for_interval,
    max_offset_seconds,
    to_binned_time,
)
from geomesa_tpu.curve.zranges import IndexRange, _merge


class _XZSFC:
    """Shared XZ logic for arbitrary dimension count (2 or 3)."""

    def __init__(self, g: int, dim_bounds: Sequence[Tuple[float, float]]):
        self.g = g
        self.dims = len(dim_bounds)
        self.fanout = 1 << self.dims
        self.bounds = list(dim_bounds)
        # subtree_size[l] = number of sequence codes in a subtree rooted at
        # level l (inclusive of the root cell, down to level g).
        self.subtree = [
            (self.fanout ** (g - l + 1) - 1) // (self.fanout - 1) for l in range(g + 2)
        ]

    def _normalize(self, values: Sequence[float]) -> List[float]:
        out = []
        for v, (lo, hi) in zip(values, self.bounds):
            out.append(min(max((v - lo) / (hi - lo), 0.0), 1.0))
        return out

    def _sequence_code(self, mins: Sequence[float], length: int) -> int:
        """Code of the level-`length` cell containing the normalized point."""
        cs = 0
        cell_min = [0.0] * self.dims
        cell_w = 1.0
        for level in range(length):
            half = cell_w / 2.0
            quad = 0
            for d in range(self.dims):
                if mins[d] >= cell_min[d] + half:
                    quad |= 1 << d
                    cell_min[d] += half
            cs += 1 + quad * self.subtree[level + 1]
            cell_w = half
        return cs

    def index_box(self, mins: Sequence[float], maxs: Sequence[float]) -> int:
        """Sequence code for a (raw-coordinate) bounding box."""
        nmin = self._normalize(mins)
        nmax = self._normalize(maxs)
        # Width of the box in normalized space determines the max level at
        # which an enlarged (doubled) cell can still contain it.
        w = max(nmax[d] - nmin[d] for d in range(self.dims))
        if w <= 0.0:
            length = self.g
        else:
            length = min(self.g, int(np.floor(-np.log2(w))) + 1)

        def fits(l: int) -> bool:
            if l <= 0:
                return True
            cw = 0.5**l
            for d in range(self.dims):
                if nmax[d] > (np.floor(nmin[d] / cw) * cw) + 2 * cw:
                    return False
            return True

        while length > 0 and not fits(length):
            length -= 1
        return self._sequence_code(nmin, length)

    def ranges_box(
        self,
        mins: Sequence[float],
        maxs: Sequence[float],
        max_ranges: int = 2000,
    ) -> List[IndexRange]:
        """Sequence-code ranges whose cells may hold geometries intersecting
        the query box."""
        qmin = self._normalize(mins)
        qmax = self._normalize(maxs)

        ranges: List[IndexRange] = []
        # Frontier entries: (level, cell_min coords, cell width, sequence code).
        frontier = [(0, tuple(0.0 for _ in range(self.dims)), 1.0, 0)]
        # The root "cell" here is a virtual super-root: treat level 0 as the
        # whole space with code 0 covering everything; start from its children
        # semantics by processing it like any cell.
        while frontier:
            level, cmin, cw, code = frontier.pop()
            # Enlarged region: cell doubled in each dimension.
            disjoint = False
            contained = True
            for d in range(self.dims):
                e_lo, e_hi = cmin[d], cmin[d] + 2 * cw
                if e_lo > qmax[d] or e_hi < qmin[d]:
                    disjoint = True
                    break
                if e_lo < qmin[d] or e_hi > qmax[d]:
                    contained = False
            if disjoint:
                continue
            if contained:
                # Query window contains the whole enlarged cell: the cell and
                # every descendant match unconditionally.
                ranges.append(IndexRange(code, code + self.subtree[level] - 1, True))
                continue
            # Possible match at this cell; recurse into children if any.
            ranges.append(IndexRange(code, code, False))
            if level < self.g and len(ranges) + len(frontier) < max_ranges:
                half = cw / 2.0
                for quad in range(self.fanout):
                    child_min = tuple(
                        cmin[d] + (half if (quad >> d) & 1 else 0.0)
                        for d in range(self.dims)
                    )
                    child_code = code + 1 + quad * self.subtree[level + 1]
                    frontier.append((level + 1, child_min, half, child_code))
            elif level < self.g:
                # Budget exhausted: cover the whole remaining subtree.
                ranges.append(
                    IndexRange(code, code + self.subtree[level] - 1, False)
                )
        return _merge(ranges)


class XZ2SFC(_XZSFC):
    """XZ ordering over (lon, lat). Default resolution g=12 as upstream."""

    def __init__(self, g: int = 12):
        super().__init__(g, [(-180.0, 180.0), (-90.0, 90.0)])

    def index(self, xmin: float, ymin: float, xmax: float, ymax: float) -> int:
        return self.index_box((xmin, ymin), (xmax, ymax))

    def ranges(self, xmin, ymin, xmax, ymax, max_ranges: int = 2000):
        return self.ranges_box((xmin, ymin), (xmax, ymax), max_ranges)


class XZ3SFC(_XZSFC):
    """XZ ordering over (lon, lat, binned-time-offset)."""

    def __init__(self, period: "str | TimePeriod" = TimePeriod.WEEK, g: int = 12):
        self.period = TimePeriod.parse(period)
        self._max_offset = max_offset_seconds(self.period)
        super().__init__(
            g, [(-180.0, 180.0), (-90.0, 90.0), (0.0, self._max_offset)]
        )

    def index(
        self,
        xmin: float,
        ymin: float,
        xmax: float,
        ymax: float,
        t_start_millis: int,
        t_end_millis: int,
    ) -> Tuple[int, int]:
        """Returns (time bin, sequence code). A geometry whose time extent
        spans multiple bins is binned by its start (reference behavior:
        XZ3 uses the start of the interval [upstream, unverified])."""
        b, off0 = to_binned_time(np.int64(t_start_millis), self.period)
        _, off1 = to_binned_time(np.int64(t_end_millis), self.period)
        b = int(b)
        off1 = float(off1) if int(_) == b else self._max_offset
        return b, self.index_box(
            (xmin, ymin, float(off0)), (xmax, ymax, off1)
        )

    def ranges(
        self,
        xmin,
        ymin,
        xmax,
        ymax,
        t_start_millis: int,
        t_end_millis: int,
        max_ranges: int = 2000,
    ) -> Dict[int, List[IndexRange]]:
        out: Dict[int, List[IndexRange]] = {}
        bins = bins_for_interval(t_start_millis, t_end_millis, self.period)
        budget = max(1, max_ranges // max(1, len(bins)))
        for b, lo, hi in bins:
            out[b] = self.ranges_box((xmin, ymin, lo), (xmax, ymax, hi), budget)
        return out
