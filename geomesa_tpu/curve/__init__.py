"""Space-filling curves: Z2/Z3 (points) and XZ2/XZ3 (extended geometries).

Semantic parity with the reference's `geomesa-z3` module
(org.locationtech.geomesa.curve: Z2SFC, Z3SFC, XZ2SFC, XZ3SFC, BinnedTime,
NormalizedDimension [upstream, unverified]) and the external
org.locationtech.sfcurve range-decomposition library, re-implemented from
scratch as vectorized NumPy (host-side: used for partition pruning and index
parity, not device execution).
"""

from geomesa_tpu.curve.normalized import NormalizedDimension, NormalizedLon, NormalizedLat
from geomesa_tpu.curve.zorder import interleave2, interleave3, deinterleave2, deinterleave3
from geomesa_tpu.curve.z2 import Z2SFC
from geomesa_tpu.curve.z3 import Z3SFC
from geomesa_tpu.curve.binned_time import BinnedTime, TimePeriod
from geomesa_tpu.curve.zranges import zranges, IndexRange
from geomesa_tpu.curve.xz import XZ2SFC, XZ3SFC

__all__ = [
    "NormalizedDimension", "NormalizedLon", "NormalizedLat",
    "interleave2", "interleave3", "deinterleave2", "deinterleave3",
    "Z2SFC", "Z3SFC", "BinnedTime", "TimePeriod",
    "zranges", "IndexRange", "XZ2SFC", "XZ3SFC",
]
