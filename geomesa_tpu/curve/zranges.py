"""Z-order range decomposition: query box -> covering set of key ranges.

Parity: org.locationtech.sfcurve ZRange/zranges (the external dependency the
reference's geomesa-z3 uses for BIGMIN-style range splitting) [upstream,
unverified]. Re-implemented as a budgeted breadth-first quadtree/octree
refinement over z-prefix cells, which produces the same *covering* guarantee:
the union of returned ranges is a superset of the query box's cells, and every
range endpoint pair is a contiguous z interval. False positives inside ranges
are removed downstream by the residual predicate mask (the TPU analog of the
reference's Z3Iterator server-side mask check).

The refinement budget (`max_ranges`) mirrors the reference's
`geomesa.scan.ranges.target` system property semantics: more ranges = tighter
covering = fewer false positives, at higher planning cost.
"""

from __future__ import annotations

import dataclasses
from typing import List, Sequence


@dataclasses.dataclass(frozen=True)
class IndexRange:
    """A contiguous inclusive z-value interval [lower, upper]."""

    lower: int
    upper: int
    contained: bool = False  # True if every z in range is inside the query box

    def __iter__(self):
        yield self.lower
        yield self.upper


def _merge(ranges: List[IndexRange]) -> List[IndexRange]:
    """Sort and coalesce adjacent/overlapping ranges."""
    if not ranges:
        return []
    ranges = sorted(ranges, key=lambda r: r.lower)
    out = [ranges[0]]
    for r in ranges[1:]:
        last = out[-1]
        if r.lower <= last.upper + 1:
            out[-1] = IndexRange(
                last.lower, max(last.upper, r.upper), last.contained and r.contained
            )
        else:
            out.append(r)
    return out


def zranges(
    mins: Sequence[int],
    maxs: Sequence[int],
    bits_per_dim: int,
    max_ranges: int = 2000,
) -> List[IndexRange]:
    """Decompose an axis-aligned box of normalized cells into z-ranges.

    Args:
      mins/maxs: inclusive per-dimension cell bounds (ints in [0, 2**bits)).
      bits_per_dim: curve precision per dimension (31 for Z2, 21 for Z3).
      max_ranges: refinement budget; the result may be coarser (more false
        positives) but never misses a cell in the box.

    Returns a sorted, merged list of IndexRange.
    """
    dims = len(mins)
    assert dims == len(maxs) and dims in (2, 3)
    fanout = 1 << dims

    # A cell is (level, prefix) where prefix is the z-value of its first cell.
    # At `level`, each dimension is refined to `level` bits; the cell spans
    # z values [prefix, prefix + 2**(dims*(bits_per_dim-level)) - 1] and
    # per-dim coordinates [dim_prefix << shift, ((dim_prefix+1) << shift) - 1].
    mins = [int(m) for m in mins]
    maxs = [int(m) for m in maxs]

    def cell_relation(level: int, dim_prefixes: Sequence[int]) -> int:
        """2 = cell inside box, 1 = overlaps, 0 = disjoint."""
        shift = bits_per_dim - level
        inside = True
        for d in range(dims):
            lo = dim_prefixes[d] << shift
            hi = ((dim_prefixes[d] + 1) << shift) - 1
            if hi < mins[d] or lo > maxs[d]:
                return 0
            if lo < mins[d] or hi > maxs[d]:
                inside = False
        return 2 if inside else 1

    def cell_range(level: int, dim_prefixes: Sequence[int], contained: bool) -> IndexRange:
        shift = bits_per_dim - level
        if dims == 2:
            from geomesa_tpu.curve.zorder import interleave2

            z = int(interleave2(dim_prefixes[0], dim_prefixes[1]))
        else:
            from geomesa_tpu.curve.zorder import interleave3

            z = int(interleave3(dim_prefixes[0], dim_prefixes[1], dim_prefixes[2]))
        # z of the prefix at full resolution: shift the interleaved prefix up.
        lower = z << (dims * shift)
        upper = lower + (1 << (dims * shift)) - 1
        return IndexRange(lower, upper, contained)

    # Budgeted BFS: refine partially-overlapping cells while within budget.
    contained: List[IndexRange] = []
    frontier = [(0, tuple(0 for _ in range(dims)))]  # root cell
    level = 0
    while frontier and level < bits_per_dim:
        if len(contained) + len(frontier) * fanout > max_ranges:
            break
        level += 1
        next_frontier = []
        for _, prefixes in frontier:
            for child in range(fanout):
                # child bit d selects the upper half of dimension d
                child_prefixes = tuple(
                    (prefixes[d] << 1) | ((child >> d) & 1) for d in range(dims)
                )
                rel = cell_relation(level, child_prefixes)
                if rel == 0:
                    continue
                if rel == 2:
                    contained.append(cell_range(level, child_prefixes, True))
                else:
                    next_frontier.append((level, child_prefixes))
        frontier = next_frontier

    # Remaining frontier cells become (overestimating) ranges.
    ranges = contained + [cell_range(lvl, p, False) for lvl, p in frontier]
    return _merge(ranges)
